"""Attribute the MoE routing overhead per phase (VERDICT r4 item 6).

`routing_overhead_share` (moe_bench) lumps everything that is not the
expert FFN matmuls. This script times each routing phase of one layer
at the rung shape on the real chip — fwd and fwd+bwd — so the 27%% r4
share is attributed before it is attacked:

  route        _route: f32 router matmul + softmax/argmax + cumsum slots
  table        the (E, C) scatter building the slot table
  dispatch     _gather_dispatch: (T, D) -> (E, C, D)
  ffn          _expert_ffn on dispatched slots (the useful work)
  combine      gate-weight + _scatter_combine back to (T, D)

Run: ``PYTHONPATH=. python benchmarks/moe_route_attrib.py``
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main(T=8 * 2048, D=1024, F=4096, E=4, cf=1.25, reps=30):
    import jax
    import jax.numpy as jnp

    from mpistragglers_jl_tpu.models import moe as M

    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    x = jax.device_put(
        jnp.asarray(rng.standard_normal((T, D)), jnp.bfloat16), dev
    )
    mp = jax.device_put(
        M.init_moe_layer(rng, D, F, E, 8, jnp.bfloat16), dev
    )
    C = M._capacity(T, E, cf)

    tiny = jax.device_put(np.ones((8,), np.float32), dev)
    fence = jax.jit(jnp.sum)
    float(fence(tiny))
    rtt = min(
        (lambda t0: (float(fence(tiny)), time.perf_counter() - t0)[1])(
            time.perf_counter()
        )
        for _ in range(5)
    )

    def timed(f, *args, grad=False):
        # the tunnel's block_until_ready is optimistic (returns at
        # enqueue) — the ONLY honest fence is a scalar D2H fetch that
        # data-depends on the output (verify-skill gotcha); rtt is
        # subtracted once per chain
        if grad:
            g = jax.jit(jax.grad(lambda *a: jnp.sum(
                jax.tree.leaves(f(*a))[0].astype(jnp.float32))))
        else:
            g = jax.jit(f)

        def scalar(o):
            return float(
                jnp.sum(jax.tree.leaves(o)[0].astype(jnp.float32))
            )

        out = g(*args)
        scalar(out)
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(reps):
                out = g(*args)
            scalar(out)
            dt = (time.perf_counter() - t0 - rtt) / reps
            best = dt if best is None else min(best, dt)
        return best * 1e3

    phases = {}

    phases["route_fwd"] = timed(lambda x: M._route(x, mp["wg"]), x)

    def table_fn(x):
        return M.switch_route_indices(x, mp["wg"], C)

    phases["route+table_fwd"] = timed(table_fn, x)

    table, expert, gate, aux = jax.jit(table_fn)(x)

    phases["dispatch_fwd"] = timed(
        lambda x: M._gather_dispatch(x, table), x
    )
    xe = jax.jit(lambda x: M._gather_dispatch(x, table))(x)
    phases["ffn_fwd"] = timed(lambda xe: M._expert_ffn(xe, mp), xe)
    ye = jax.jit(lambda xe: M._expert_ffn(xe, mp))(xe)

    gate_pad = jnp.concatenate([gate, jnp.zeros((1,), gate.dtype)])
    g = gate_pad[table].astype(x.dtype)

    phases["combine_fwd"] = timed(
        lambda ye: M._scatter_combine(ye * g[..., None], table, T), ye
    )

    def whole(x):
        y, aux = M.moe_ffn_dense(x.reshape(1, T, D), mp, cf)
        return y

    phases["layer_fwd"] = timed(whole, x)
    phases["layer_fwd_bwd"] = timed(whole, x, grad=True)

    def dense_mlp(x):
        lp = {
            "w1": mp["we1"][0], "b1": mp["be1"][0],
            "w2": mp["we2"][0], "b2": mp["be2"][0],
        }
        from mpistragglers_jl_tpu.models.transformer import _mlp

        return _mlp(x.reshape(1, T, D), lp)

    phases["dense_mlp_fwd"] = timed(dense_mlp, x)
    phases["dense_mlp_fwd_bwd"] = timed(dense_mlp, x, grad=True)

    out = {
        "shape": f"T={T} D={D} F={F} E={E} C={C}",
        "fence_rtt_ms": round(rtt * 1e3, 2),
        **{k: round(v, 3) for k, v in phases.items()},
    }
    print(json.dumps(out, indent=1))
    return out


if __name__ == "__main__":
    main()
