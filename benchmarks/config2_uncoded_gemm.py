"""BASELINE config 2: uncoded distributed GEMM 4096^2, nwait=n.

Thin wrapper over the repo-root bench module's secondary metric.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import bench_uncoded_gemm

if __name__ == "__main__":
    print(json.dumps(bench_uncoded_gemm()))
