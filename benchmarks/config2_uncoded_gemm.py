"""BASELINE config 2: uncoded distributed GEMM, ``nwait = n``.

CLI front-end over the repo-root bench's measurement (one JSON line per
size), parameterized so the amortization story is reproducible at any
rung — the 4096³/DEFAULT point is dispatch-bound by construction and
only a sweep shows where compute takes over (docs/PERF.md "Config 2
closed"):

.. code-block:: console

    python benchmarks/config2_uncoded_gemm.py                 # default 4096
    python benchmarks/config2_uncoded_gemm.py --size 8192 --workers 8
    python benchmarks/config2_uncoded_gemm.py --size 2048 4096 8192
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import bench_uncoded_gemm


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--size", type=int, nargs="+", default=[4096],
        help="square GEMM size(s); one JSON line per size",
    )
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument(
        "--epochs", type=int, default=40,
        help="pipelined epochs per chain (min of 3 chains)",
    )
    args = ap.parse_args(argv)
    for m in args.size:
        print(json.dumps(bench_uncoded_gemm(
            m=m, k=m, n=m, n_workers=args.workers, epochs=args.epochs,
        )))


if __name__ == "__main__":
    main()
