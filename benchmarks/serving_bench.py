"""Continuous-batching serving throughput: aggregate decode tokens/s
at S concurrent requests vs S=1 (VERDICT r4 next-#1).

The economics being priced: a B=1 decode step is weight-read-bound —
every step streams the full parameter bytes from HBM to emit ONE token
(docs/PERF.md round 4), so every cache-side win is capped. The
scheduler's batched step streams the same weights once for S tokens;
until KV-cache reads (S x W window rows) rival the weight bytes,
aggregate throughput scales near-linearly with S. This rung measures
that scaling on the real chip through the actual scheduler tick
(admission excluded — steady-state decode is the claim; admission cost
is bounded per tick by one prefill chunk and measured separately).

Methodology: each tick is one device scan of ``n_inner`` steps for all
S slots plus one host fetch of the (S, n_inner) token block — on the
tunneled bench chip that fetch is a ~120 ms fixed round trip
(BASELINE.md), so the measured fence RTT is subtracted per tick, the
same correction every decode rung applies (transformer_train_bench).
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

__all__ = ["bench_serving", "bench_paged_vs_slot"]


def bench_serving(
    *,
    slot_counts: tuple[int, ...] = (1, 4, 8),
    prompt_len: int = 512,
    window: int = 1024,
    n_inner: int = 64,
    ticks: int = 6,
    chains: int = 3,
    d_model: int = 1024,
    n_layers: int = 8,
    n_heads: int = 8,
    n_kv_heads: int | None = 2,
    d_ff: int = 4096,
    vocab: int = 32768,
) -> dict:
    import jax
    import jax.numpy as jnp

    from benchmarks.transformer_train_bench import _fence_rtt, _timed
    from mpistragglers_jl_tpu.models.serving import ServingScheduler
    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=n_heads,
        n_kv_heads=n_kv_heads, n_layers=n_layers, d_ff=d_ff,
        attn="ulysses", attn_impl="flash", dtype=jnp.bfloat16,
        attn_window=window,
    )
    params = init_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    rtt = _fence_rtt(jax.devices()[0])

    rungs = {}
    compile_s = 0.0
    # int8 sub-rungs at the largest S: at B=1 the int8 cache LOSES
    # (weight-read-bound, docs/PERF.md) — but at S slots the cache
    # reads are S x W rows while the weight read stays constant, so
    # batching is where quantization's byte model has real leverage.
    # TWO int8 variants make the decode-path claim driver-verifiable:
    # the AUTO routing (S >= KERNEL_MIN_BATCH routes the batched
    # Pallas ring kernel inside the tick) and the forced einsum-dequant
    # path — their ratio IS the kernel's in-scan win/loss, measured
    # through the real scheduler every run.
    from mpistragglers_jl_tpu.models.decode import use_decode_kernel

    Smax = max(slot_counts)
    variants = [(S, False, None) for S in slot_counts]
    variants.append((Smax, True, None))    # AUTO: kernel at S >= 4
    variants.append((Smax, True, False))   # forced einsum dequant
    for S, q8, forced in variants:
        use_decode_kernel(forced)
        try:
            sched = ServingScheduler(
                params, cfg, slots=S, n_inner=n_inner,
                prompt_chunk=prompt_len, max_prompt=prompt_len,
                quantize_kv=q8,
            )
        finally:
            use_decode_kernel(None)  # routing snapshots at construction
        for _ in range(S):
            # budget sized so no request retires mid-measurement: every
            # tick decodes all S rows (steady state, no admission)
            sched.submit(
                rng.integers(0, vocab, prompt_len, dtype=np.int32),
                max_new=n_inner * (ticks + 2) * (chains + 2),
            )
        t0 = time.perf_counter()
        sched.step()  # admit all S + first decode tick (compiles)
        compile_s += time.perf_counter() - t0
        best = None
        for _ in range(chains):
            dt = _timed(lambda: [sched.step() for _ in range(ticks)])
            dt -= rtt * ticks  # one (S, n_inner) token fetch per tick
            best = dt if best is None else min(best, dt)
        tokens = S * n_inner * ticks
        per_tok_ms = best / tokens * 1e3
        name = f"S{S}" + (
            ("_int8_einsum" if forced is False else "_int8") if q8
            else ""
        )
        rungs[name] = {
            "aggregate_tokens_per_s": round(tokens / best, 1),
            "ms_per_token_aggregate": round(per_tok_ms, 4),
            "ms_per_step": round(best / (n_inner * ticks) * 1e3, 3),
        }
        if q8:
            # record what the tick actually ran — a "kernel win" row
            # with kernelized: false would be self-refuting
            rungs[name]["kernelized"] = bool(sched.use_kernel)

    base_n = 1 if 1 in slot_counts else min(slot_counts)
    base = rungs[f"S{base_n}"]["aggregate_tokens_per_s"]
    for S in slot_counts:
        r = rungs[f"S{S}"]
        r[f"vs_S{base_n}"] = round(
            r["aggregate_tokens_per_s"] / base, 2
        )
    for q8name in (f"S{Smax}_int8", f"S{Smax}_int8_einsum"):
        rungs[q8name]["vs_bf16"] = round(
            rungs[q8name]["aggregate_tokens_per_s"]
            / rungs[f"S{Smax}"]["aggregate_tokens_per_s"], 2
        )
    # the tentpole ratio: batched kernel tick vs the einsum dequant
    # tick, same slots, same int8 cache
    rungs[f"S{Smax}_int8"]["vs_int8_einsum"] = round(
        rungs[f"S{Smax}_int8"]["aggregate_tokens_per_s"]
        / rungs[f"S{Smax}_int8_einsum"]["aggregate_tokens_per_s"], 2
    )
    return {
        "metric": "serving-continuous-batching",
        "prompt_len": prompt_len,
        "attn_window": window,
        "n_inner": n_inner,
        "ticks": ticks,
        "chains_min_of": chains,
        "fence_rtt_s": round(rtt, 4),
        "compile_s": round(compile_s, 1),
        **rungs,
    }


def bench_paged_vs_slot(
    *,
    d_model: int = 256,
    n_layers: int = 2,
    n_heads: int = 8,
    n_kv_heads: int = 2,
    d_ff: int = 1024,
    vocab: int = 8192,
    window: int = 512,
    page_tokens: int = 64,
    slot_ref: int = 8,
    sys_len: int = 256,
    user_len: int = 16,
    n_submit: int = 80,
    decode_slots: int = 8,
    n_inner: int = 32,
    ticks: int = 4,
    chains: int = 2,
) -> dict:
    """Round-11 capacity rung: at a FIXED cache byte budget — the
    slot-ring arena of ``slot_ref`` slots — how many concurrent
    requests does the paged cache admit? Two scenarios: unique
    prompts (the right-sized-residency win alone) and a shared
    ``sys_len``-token system prompt (plus prefix sharing, the
    multi-tenant case), with the prefill skip COUNTER-verified through
    ``PagePool.share_hits``, not inferred from timing. The byte model:
    a slot-ring request costs ``W`` rows of residency regardless of
    length; a paged request costs ``ceil(min(W, Tp + max_new +
    n_inner) / P)`` pages minus the shared prefix (docs/PERF.md).

    The decode leg prices the indirection: aggregate steady-state
    decode tokens/s at ``decode_slots`` slots, slot ring vs paged
    (einsum gather fallback — the kernel path's win is the int8 rung's
    claim), same config, same fence-RTT correction as
    :func:`bench_serving`. The acceptance gate is a <= 5% regression.
    """
    import jax

    from benchmarks.transformer_train_bench import _fence_rtt, _timed
    from mpistragglers_jl_tpu.models.serving import ServingScheduler
    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    import jax.numpy as jnp

    cfg = TransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=n_heads,
        n_kv_heads=n_kv_heads, n_layers=n_layers, d_ff=d_ff,
        attn="ulysses", attn_impl="flash", dtype=jnp.bfloat16,
        attn_window=window,
    )
    params = init_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    P = page_tokens
    max_pages = window // P
    budget_pages = slot_ref * max_pages  # byte-equal to the slot arena
    kv_bytes = 2 * n_layers * cfg.kv_heads * cfg.head_dim * 2  # k+v bf16
    max_new = 16
    sys_prompt = rng.integers(0, vocab, sys_len, dtype=np.int32)

    def prompts(shared: bool):
        out = []
        for _ in range(n_submit):
            user = rng.integers(0, vocab, user_len, dtype=np.int32)
            head = sys_prompt if shared else rng.integers(
                0, vocab, sys_len, dtype=np.int32
            )
            out.append(np.concatenate([head, user]))
        return out

    def capacity(shared: bool) -> tuple[int, int]:
        sched = ServingScheduler(
            params, cfg, slots=min(n_submit, budget_pages),
            n_inner=4, prompt_chunk=sys_len + user_len,
            max_prompt=sys_len + user_len, page_tokens=P,
            cache_pages=budget_pages + 1,
        )
        for p in prompts(shared):
            sched.submit(p, max_new=max_new)
        sched.step()  # one admission wave against a fresh pool
        return sched.active, sched.pool.share_hits

    t0 = time.perf_counter()
    cap_unique, _ = capacity(shared=False)
    cap_shared, share_hits = capacity(shared=True)

    # decode-throughput leg: slot ring vs paged gather, same slots
    rtt = _fence_rtt(jax.devices()[0])
    tok_s = {}
    for paged in (False, True):
        kw = dict(page_tokens=P) if paged else {}
        sched = ServingScheduler(
            params, cfg, slots=decode_slots, n_inner=n_inner,
            prompt_chunk=sys_len, max_prompt=sys_len, **kw,
        )
        for _ in range(decode_slots):
            sched.submit(
                rng.integers(0, vocab, sys_len, dtype=np.int32),
                max_new=n_inner * (ticks + 2) * (chains + 2),
            )
        sched.step()  # admit + first tick (compiles)
        best = None
        for _ in range(chains):
            dt = _timed(lambda: [sched.step() for _ in range(ticks)])
            dt -= rtt * ticks
            best = dt if best is None else min(best, dt)
        tok_s["paged" if paged else "slot"] = (
            decode_slots * n_inner * ticks / best
        )

    return {
        "metric": "serving-paged-capacity",
        "page_tokens": P,
        "byte_budget_mb": round(
            budget_pages * P * kv_bytes / 2 ** 20, 2
        ),
        "prompt_len": sys_len + user_len,
        "max_new": max_new,
        "slot_capacity": slot_ref,
        "paged_capacity": cap_unique,
        "paged_capacity_shared": cap_shared,
        "capacity_x": round(cap_unique / slot_ref, 2),
        "capacity_x_shared": round(cap_shared / slot_ref, 2),
        "prefill_pages_skipped": int(share_hits),
        "prefill_skip_verified": bool(share_hits > 0),
        "slot_tok_s": round(tok_s["slot"], 1),
        "paged_tok_s": round(tok_s["paged"], 1),
        "paged_vs_slot_tok_s": round(tok_s["paged"] / tok_s["slot"], 3),
        "elapsed_s": round(time.perf_counter() - t0, 1),
    }


if __name__ == "__main__":
    import json

    print(json.dumps({
        "serving": bench_serving(),
        "paged_vs_slot": bench_paged_vs_slot(),
    }))
