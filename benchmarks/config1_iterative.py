"""BASELINE config 1: iterative 4-worker pool, nwait=3, float64 reduce.

The reference's ``examples/iterative_example.jl`` shape: a coordinator
broadcasts a dense vector each epoch, workers transform it with
deterministic injected delays (replacing the reference's
``sleep(rand())``, examples/iterative_example.jl:74), and the
coordinator reduces the ``nwait=3`` freshest responses. ``vs_baseline``
is the straggler-mitigation factor: the same loop forced to ``nwait=4``
(bulk-synchronous, pays the slowest worker every epoch) over the
fastest-3 loop.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpistragglers_jl_tpu import AsyncPool, LocalBackend, asyncmap, waitall

N_WORKERS = 4
DIM = 4096
EPOCHS = 30
# worker 3 is the persistent straggler
DELAYS = [0.01, 0.02, 0.03, 0.20]


def run(nwait: int) -> float:
    backend = LocalBackend(
        lambda i, x, e: x * (i + 1),
        N_WORKERS,
        delay_fn=lambda i, e: DELAYS[i],
    )
    pool = AsyncPool(N_WORKERS)
    x = np.linspace(0.0, 1.0, DIM)  # float64, like the reference tests
    recvbuf = np.zeros(N_WORKERS * DIM)
    t0 = time.perf_counter()
    for _ in range(EPOCHS):
        repochs = asyncmap(pool, x, backend, recvbuf, nwait=nwait)
        fresh = repochs == pool.epoch
        # reduce over fresh chunks only (coordinator-side combine)
        chunks = recvbuf.reshape(N_WORKERS, DIM)
        x = chunks[fresh].mean(axis=0) / (np.flatnonzero(fresh) + 1).mean()
    dt = (time.perf_counter() - t0) / EPOCHS
    waitall(pool, backend)
    backend.shutdown()
    return dt


if __name__ == "__main__":
    t_fast = run(nwait=3)
    t_all = run(nwait=N_WORKERS)
    print(json.dumps({
        "metric": "iterative-pool-4w-nwait3-epoch-wallclock",
        "value": round(t_fast, 4),
        "unit": "s",
        "vs_baseline": round(t_all / t_fast, 2),
        "nwait_all_epoch_s": round(t_all, 4),
        "epochs": EPOCHS,
        "injected_delays_s": DELAYS,
    }))
