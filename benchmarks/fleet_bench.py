"""Round-18 elastic-fleet rung: the closed control loop, priced.

One leg, sim-only (unscaled in bench.py — virtual-time bookkeeping
does not track the matmul rate): a compressed diurnal day with a **3x
rate swing** (amplitude 0.5: peak/trough = 1.5/0.5) over an 8-replica
fleet, driven twice —

* **elastic**: a :class:`~mpistragglers_jl_tpu.fleet.FleetController`
  under a :class:`~mpistragglers_jl_tpu.fleet.ControllerSupervisor`
  autoscales between 2 and 8 replicas against hysteresis bands,
  re-derives (outer rate, inner nwait) via ``sweep_hierarchical`` and
  the router policy via ``sweep_router_policy`` on every accepted
  resize (the ``agree`` flags land in the rung detail), checkpoints
  through the (5, 3)-coded channel, and survives one mid-day
  ``CoordinatorKill`` — the standby adopts from the last checkpoint;
* **static**: the same arrivals on the peak-provisioned 8-replica
  fleet, no controller.

Headline scalars (bench.py compact line, format in
benchmarks/README.md round-18 note):

* ``fleet_chip_time_x`` — static peak-provisioned chip-seconds /
  elastic chip-seconds; FAILS below the 1.2x acceptance floor;
* ``fleet_failover_drops`` — dropped requests across the killed
  elastic day; FAILS unless exactly 0 (the zero-drop failover
  contract).

Both elastic days (same seed) must agree on the workload digest AND
the decision records — the bit-identity witness the sim plane pins.
"""

from __future__ import annotations

import os
import tempfile
import time

import numpy as np

_N_FLEET = 8
_SLOTS, _NI, _TICK = 2, 4, 0.25
_PLEN, _CHUNK, _MNEW = 64, 64, 16
_PERIOD = 3600.0
_PEAK_UTIL = 0.675


def _capacity():
    from mpistragglers_jl_tpu.fleet import replica_capacity_rps

    return replica_capacity_rps(
        slots=_SLOTS, n_inner=_NI, tick_s=_TICK, prompt_len=_PLEN,
        prompt_chunk=_CHUNK, max_new=_MNEW,
    )


def _fitted_model(seed=5):
    from mpistragglers_jl_tpu.utils.straggle import PoolLatencyModel

    model = PoolLatencyModel(_NI, seed=0)
    rng = np.random.default_rng(seed)
    for _ in range(40):
        for w in range(_NI):
            model.observe(
                w, 0.01 * (1 + 0.3 * w) * float(rng.lognormal(0, 0.3))
            )
    return model


def _day(n, seed, *, elastic, kill_at=None, ckpt_dir=None):
    from mpistragglers_jl_tpu.fleet import (
        ControllerSupervisor,
        FleetCheckpointer,
        FleetController,
    )
    from mpistragglers_jl_tpu.models.router import RequestRouter
    from mpistragglers_jl_tpu.sim import (
        CoordinatorKill,
        SimReplica,
        VirtualClock,
        diurnal_arrivals,
        lognormal_ticks,
        run_router_day,
    )

    cap = _capacity()
    clock = VirtualClock()
    reps = [
        SimReplica(
            clock, slots=_SLOTS, n_inner=_NI, prompt_chunk=_CHUNK,
            tick_s=lognormal_ticks(_TICK, 0.2, seed=1009 + i),
        )
        for i in range(_N_FLEET)
    ]
    router = RequestRouter(reps, policy="least_loaded", clock=clock)
    peak = _N_FLEET * cap * _PEAK_UTIL
    mean_rate = peak / 1.5  # amplitude 0.5 -> the 3x swing
    sup = None
    events = []
    if elastic:
        ck = FleetCheckpointer(ckpt_dir, n=5, k=3)
        model = _fitted_model()

        def mk():
            return FleetController(
                router, clock=clock, capacity_rps=cap,
                min_replicas=2, max_replicas=_N_FLEET,
                high=0.75, low=0.45, target_util=0.55,
                decision_interval_s=30.0,
                dwell_s=30.0, cooldown_s=60.0, rate_tau_s=120.0,
                checkpointer=ck, checkpoint_every_s=150.0,
                recode=dict(
                    model=model, n_inner=_NI,
                    candidates=[(1.0, 2), (1.0, 3), (0.75, 3)],
                    inner_floor=2, epochs=12,
                ),
                policy_sweep=dict(
                    requests=250, slots=_SLOTS, n_inner=_NI,
                    tick_s=_TICK, prompt_len=_PLEN,
                    prompt_chunk=_CHUNK, max_new=_MNEW, seed=11,
                ),
                decision_budget=100,
            )

        sup = ControllerSupervisor(mk, clock=clock, takeover_s=60.0)
        if kill_at is not None:
            events.append(CoordinatorKill(kill_at))
    report = run_router_day(
        router,
        diurnal_arrivals(
            mean_rate, n=n, period=_PERIOD, amplitude=0.5, seed=seed,
            prompt_len=_PLEN, max_new=_MNEW,
        ),
        controller=sup,
        events=events,
    )
    return report, sup


def bench_fleet_rung(requests: int | None = None):
    """The driver rung ``fleet``: elastic-vs-static chip time under
    the 3x swing + one coordinator kill, with the bit-identity
    witness over the killed day."""
    cap = _capacity()
    mean_rate = _N_FLEET * cap * _PEAK_UTIL / 1.5
    if requests is None:
        requests = int(os.environ.get(
            "FLEET_BENCH_REQUESTS", str(int(mean_rate * _PERIOD * 0.97))
        ))
    # the kill lands at ~45% of the ACTUAL arrival span (an overridden
    # request count shortens the day; a kill past the last arrival
    # would leave the standby nothing to adopt into)
    kill_at = 0.45 * requests / mean_rate
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as d1:
        e1, s1 = _day(
            requests, 13, elastic=True, kill_at=kill_at, ckpt_dir=d1,
        )
        dec1 = [dd.to_dict() for dd in s1.decisions]
        elastic_chip = s1.chip_seconds(e1.virtual_s)
    with tempfile.TemporaryDirectory() as d2:
        e2, s2 = _day(
            requests, 13, elastic=True, kill_at=kill_at, ckpt_dir=d2,
        )
        if e1.digest() != e2.digest():
            raise AssertionError(
                f"elastic day not bit-identical: {e1.digest()} != "
                f"{e2.digest()}"
            )
        if dec1 != [dd.to_dict() for dd in s2.decisions]:
            raise AssertionError(
                "decision records diverged across two replays of the "
                "same seed"
            )
    if e1.dropped != 0:
        raise AssertionError(
            f"fleet_failover_drops {e1.dropped} != 0: the kill dropped "
            "requests (the zero-drop failover contract)"
        )
    if e1.n_failovers != 1:
        raise AssertionError(
            f"expected exactly one coordinator takeover, saw "
            f"{e1.n_failovers}"
        )
    if e1.n_resizes < 2:
        raise AssertionError(
            f"the 3x swing moved the fleet only {e1.n_resizes} times "
            "— the controller never closed the loop"
        )
    # the kill-free elastic day attributes the killed day's TTFT
    # tail: the coordinator dying at the steepest ramp costs TAIL
    # (the dead+re-ramp window under-provisions), never drops or chips
    with tempfile.TemporaryDirectory() as d3:
        nokill, _ = _day(requests, 13, elastic=True, ckpt_dir=d3)
    static, _ = _day(requests, 13, elastic=False)
    if static.dropped:
        raise AssertionError(f"{static.dropped} static-day drops")
    static_chip = _N_FLEET * static.virtual_s
    chip_x = static_chip / elastic_chip
    if chip_x < 1.2:
        raise AssertionError(
            f"fleet_chip_time_x {chip_x:.2f} below the 1.2x "
            f"acceptance floor (elastic {elastic_chip:.0f} vs static "
            f"{static_chip:.0f} chip-seconds)"
        )
    recodes = [
        dd["recode"] for dd in dec1 if dd.get("recode") is not None
    ]
    return {
        "requests": int(requests),
        "virtual_day_s": round(e1.virtual_s, 1),
        "fleet_chip_time_x": round(chip_x, 2),
        "fleet_failover_drops": int(e1.dropped),
        "elastic_chip_s": round(elastic_chip, 1),
        "static_chip_s": round(static_chip, 1),
        "resizes": int(e1.n_resizes),
        "failovers": int(e1.n_failovers),
        "recode_agree": [bool(rc["agree"]) for rc in recodes
                         if rc["agree"] is not None],
        "recode_pairs": [list(rc["pair"]) for rc in recodes],
        "p99_ttft_ms": round(e1.p99_ttft() * 1e3, 2),
        "p99_ttft_nokill_ms": round(nokill.p99_ttft() * 1e3, 2),
        "static_p99_ttft_ms": round(static.p99_ttft() * 1e3, 2),
        "digest": e1.digest(),
        "wall_s": round(time.perf_counter() - t0, 2),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(bench_fleet_rung(), indent=2, default=str))
