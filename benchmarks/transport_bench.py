"""Transport microbenchmark: native C++ backend vs Python-pipe backend.

Measures the coordinator-side cost of the pool's hot path (dispatch ->
waitany -> harvest) with trivial worker compute, isolating the transport
(the reference's libmpi role, SURVEY component C8):

* round-trip latency: tiny payload, one worker, nwait=1 epochs
* throughput: 4 MiB payloads broadcast to 4 workers, nwait=4

Prints one JSON line per configuration.

Run:  python benchmarks/transport_bench.py [epochs]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mpistragglers_jl_tpu import AsyncPool, ProcessBackend, asyncmap, waitall


def _echo_small(i, payload, epoch):
    return payload


def _sum_large(i, payload, epoch):
    # touch the whole payload (forces full deserialization + a pass)
    return np.array([float(payload.sum())])


def bench_backend(make_backend, name, epochs=200):
    out = []
    # --- round-trip latency: 8-byte payload, 1 worker ---
    backend = make_backend(_echo_small, 1)
    try:
        pool = AsyncPool(1)
        payload = np.zeros(1)
        asyncmap(pool, payload, backend, nwait=1)  # warmup
        t0 = time.perf_counter()
        for _ in range(epochs):
            asyncmap(pool, payload, backend, nwait=1)
        dt = time.perf_counter() - t0
        out.append({
            "metric": f"transport-roundtrip-{name}",
            "value": round(dt / epochs * 1e6, 1),
            "unit": "us/epoch",
        })
        waitall(pool, backend)
    finally:
        backend.shutdown()

    # --- throughput: 4 MiB payload to 4 workers, full gather ---
    n, mb = 4, 4
    backend = make_backend(_sum_large, n)
    try:
        pool = AsyncPool(n)
        payload = np.ones(mb * 1024 * 1024 // 8)  # 4 MiB of float64
        asyncmap(pool, payload, backend, nwait=n)  # warmup
        reps = max(epochs // 10, 5)
        t0 = time.perf_counter()
        for _ in range(reps):
            asyncmap(pool, payload, backend, nwait=n)
        dt = time.perf_counter() - t0
        # each epoch ships the payload to all n workers
        gbps = (mb / 1024) * n * reps / dt
        out.append({
            "metric": f"transport-broadcast-{name}",
            "value": round(gbps, 2),
            "unit": "GiB/s",
            "payload_mib": mb,
            "n_workers": n,
        })
        waitall(pool, backend)
    finally:
        backend.shutdown()
    return out


def main():
    epochs = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    results = bench_backend(
        lambda fn, n: ProcessBackend(fn, n), "pipes", epochs
    )
    try:
        from mpistragglers_jl_tpu.backends.native import NativeProcessBackend
        from mpistragglers_jl_tpu.native import transport

        transport.load_lib()
    except Exception as e:  # genuinely no toolchain; runtime errors raise
        print(f"[native transport unavailable: {e}]", file=sys.stderr)
    else:
        results += bench_backend(
            lambda fn, n: NativeProcessBackend(fn, n), "native", epochs
        )
        results += bench_backend(
            lambda fn, n: NativeProcessBackend(
                fn, n, address="tcp://127.0.0.1:0"
            ),
            "native-tcp", epochs,
        )
    for r in results:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
