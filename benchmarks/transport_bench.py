"""Transport microbenchmark: native C++ backend vs Python-pipe backend.

Measures the coordinator-side cost of the pool's hot path (dispatch ->
waitany -> harvest) with trivial worker compute, isolating the transport
(the reference's libmpi role, SURVEY component C8):

* round-trip latency: tiny payload, one worker, nwait=1 epochs
* throughput: 4 MiB payloads broadcast to 4 workers, nwait=4

Prints one JSON line per configuration.

Run:  python benchmarks/transport_bench.py [epochs]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mpistragglers_jl_tpu import AsyncPool, ProcessBackend, asyncmap, waitall


def _echo_small(i, payload, epoch):
    return payload


def _sum_large(i, payload, epoch):
    # touch the whole payload (forces full deserialization + a pass)
    return np.array([float(payload.sum())])


def bench_backend(make_backend, name, epochs=200):
    out = []
    # --- round-trip latency: 8-byte payload, 1 worker ---
    backend = make_backend(_echo_small, 1)
    try:
        pool = AsyncPool(1)
        payload = np.zeros(1)
        asyncmap(pool, payload, backend, nwait=1)  # warmup
        t0 = time.perf_counter()
        for _ in range(epochs):
            asyncmap(pool, payload, backend, nwait=1)
        dt = time.perf_counter() - t0
        out.append({
            "metric": f"transport-roundtrip-{name}",
            "value": round(dt / epochs * 1e6, 1),
            "unit": "us/epoch",
        })
        waitall(pool, backend)
    finally:
        backend.shutdown()

    # --- throughput: 4 MiB payload to 4 workers, full gather ---
    n, mb = 4, 4
    backend = make_backend(_sum_large, n)
    try:
        pool = AsyncPool(n)
        payload = np.ones(mb * 1024 * 1024 // 8)  # 4 MiB of float64
        asyncmap(pool, payload, backend, nwait=n)  # warmup
        reps = max(epochs // 10, 5)
        t0 = time.perf_counter()
        for _ in range(reps):
            asyncmap(pool, payload, backend, nwait=n)
        dt = time.perf_counter() - t0
        # each epoch ships the payload to all n workers
        gbps = (mb / 1024) * n * reps / dt
        out.append({
            "metric": f"transport-broadcast-{name}",
            "value": round(gbps, 2),
            "unit": "GiB/s",
            "payload_mib": mb,
            "n_workers": n,
        })
        waitall(pool, backend)
    finally:
        backend.shutdown()
    return out


def _echo_payload(i, payload, epoch):
    # the transport rung's worker: return the payload itself, so the
    # result leg carries exactly the dispatch leg's bytes (round-trip
    # identity is asserted) and per-epoch wall is transport, not compute
    return payload


def bench_transport_rung(n=8, ladder=((1 << 16, 24), (1 << 20, 12),
                                      (16 << 20, 4))):
    """Round-12 driver rung: per-epoch coordinator dispatch+harvest
    overhead (µs) and effective two-way GB/s for the three host
    transports at ``n`` workers across a payload ladder —

    * ``pipe``     — ProcessBackend, classic in-band pickling
      (``shm_rings=False``);
    * ``socket``   — NativeProcessBackend with every shared-memory path
      off (``zero_copy=False``): two-buffer socket frames both ways;
    * ``shm_ring`` — NativeProcessBackend default: persistent broadcast
      arena + per-worker result rings, bytes never cross the sockets.

    Workers echo the payload, so each epoch moves ``2 * n * size``
    bytes coordinator<->workers and the harvested results are asserted
    byte-identical to the dispatch. The acceptance claim (ISSUE 7): at
    >= 1 MiB, shm_ring per-epoch overhead improves >= 2x over the
    socket/pipe baseline. Compact-line digest documented in
    benchmarks/README.md (round-12 note)."""
    from mpistragglers_jl_tpu import AsyncPool, asyncmap, waitall

    def measure(backend, size, epochs):
        pool = AsyncPool(n)
        rng = np.random.default_rng(size)
        payload = rng.integers(
            0, 255, size, dtype=np.uint8
        ).view(np.float32)
        for _ in range(2):  # warmup: arena/ring creation + fd passes
            asyncmap(pool, payload, backend, nwait=n)
        for r in range(n):  # byte-exactness of the zero-copy round trip
            got = np.asarray(pool.results[r])
            assert got.dtype == payload.dtype and np.array_equal(
                got.view(np.uint8), payload.view(np.uint8)
            ), f"transport round-trip mismatch (worker {r})"
            # random bytes as f32 include NaNs, so compare RAW bytes —
            # exactly the claim (no float canonicalization in transit)
        t0 = time.perf_counter()
        for _ in range(epochs):
            asyncmap(pool, payload, backend, nwait=n)
        wall = time.perf_counter() - t0
        waitall(pool, backend)
        us = wall / epochs * 1e6
        gbps = 2.0 * n * payload.nbytes * epochs / wall / 1e9
        return round(us, 1), round(gbps, 2)

    configs = [("pipe", None), ("socket", None), ("shm_ring", None)]
    native_err = None
    try:
        from mpistragglers_jl_tpu.backends.native import (
            NativeProcessBackend,
        )
        from mpistragglers_jl_tpu.native import transport

        transport.load_lib()
    except Exception as e:  # no toolchain: pipe numbers still print
        native_err = f"{type(e).__name__}: {e}"

    from mpistragglers_jl_tpu import ProcessBackend

    out = {"n_workers": n, "sizes": [s for s, _ in ladder]}
    for name, _ in configs:
        if name != "pipe" and native_err is not None:
            out[name] = {"error": f"native transport: {native_err}"}
            continue
        if name == "pipe":
            backend = ProcessBackend(_echo_payload, n, shm_rings=False)
        elif name == "socket":
            backend = NativeProcessBackend(
                _echo_payload, n, zero_copy=False
            )
        else:
            backend = NativeProcessBackend(_echo_payload, n)
        try:
            per = {}
            for size, epochs in ladder:
                us, gbps = measure(backend, size, epochs)
                per[size] = {"us_per_epoch": us, "gbps": gbps}
            out[name] = per
            if name == "shm_ring":
                s = backend._coord.stats
                out["zero_copy_bytes"] = s["arena_bytes"] + s["ring_bytes"]
                out["ring_full_stalls"] = (
                    s["arena_stalls"] + s["ring_stalls"]
                )
                out["pinned_slots_peak"] = s["pinned_peak"]
        finally:
            backend.shutdown()
    mb = 1 << 20
    if "error" not in out.get("shm_ring", {"error": 1}):
        shm_us = out["shm_ring"][mb]["us_per_epoch"]
        out["shm_vs_socket_x_1mb"] = round(
            out["socket"][mb]["us_per_epoch"] / shm_us, 2
        )
        out["shm_vs_pipe_x_1mb"] = round(
            out["pipe"][mb]["us_per_epoch"] / shm_us, 2
        )
        big = max(s for s, _ in ladder)
        out["shm_vs_socket_x_16mb"] = round(
            out["socket"][big]["us_per_epoch"]
            / out["shm_ring"][big]["us_per_epoch"], 2
        )
        out["digest"] = (
            f"x{out['shm_vs_socket_x_1mb']:.1f}sock"
            f"/x{out['shm_vs_pipe_x_1mb']:.1f}pipe@1MB"
            f"/{out['shm_ring'][big]['gbps']:.1f}GB/s@16MB"
        )
    return out


def main():
    epochs = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    results = bench_backend(
        lambda fn, n: ProcessBackend(fn, n), "pipes", epochs
    )
    try:
        from mpistragglers_jl_tpu.backends.native import NativeProcessBackend
        from mpistragglers_jl_tpu.native import transport

        transport.load_lib()
    except Exception as e:  # genuinely no toolchain; runtime errors raise
        print(f"[native transport unavailable: {e}]", file=sys.stderr)
    else:
        results += bench_backend(
            lambda fn, n: NativeProcessBackend(fn, n), "native", epochs
        )
        results += bench_backend(
            lambda fn, n: NativeProcessBackend(
                fn, n, address="tcp://127.0.0.1:0"
            ),
            "native-tcp", epochs,
        )
    for r in results:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
