"""BASELINE config 3: (n=8, k=6) MDS-coded GEMM 8192^2, nwait=6.

This is the headline metric; thin wrapper over the repo-root bench.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import bench_coded_gemm

if __name__ == "__main__":
    print(json.dumps(bench_coded_gemm()))
