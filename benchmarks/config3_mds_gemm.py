"""BASELINE config 3: (n, k) MDS-coded GEMM — the headline metric.

CLI front-end over the repo-root bench's measurement, parameterized
over problem size and code rate so redundancy/wall-clock trade-offs
are reproducible without editing the driver contract (`bench.py`
pins the official 8192³ (8, 6) point):

.. code-block:: console

    python benchmarks/config3_mds_gemm.py                   # 8192^3 (8,6)
    python benchmarks/config3_mds_gemm.py --n 16 --k 12     # v5e-16 shape
    python benchmarks/config3_mds_gemm.py --size 4096 --epochs 20
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import bench_coded_gemm


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--size", type=int, default=8192,
                    help="square GEMM size")
    ap.add_argument("--n", type=int, default=8, help="coded workers")
    ap.add_argument("--k", type=int, default=6,
                    help="shards needed to decode (nwait)")
    ap.add_argument("--epochs", type=int, default=7,
                    help="pipelined epochs per chain (min of 3 chains)")
    args = ap.parse_args(argv)
    if not 0 < args.k <= args.n:
        ap.error(f"need 0 < k <= n, got k={args.k} n={args.n}")
    print(json.dumps(bench_coded_gemm(
        m=args.size, kdim=args.size, ncols=args.size,
        n=args.n, k=args.k, epochs=args.epochs,
    )))


if __name__ == "__main__":
    main()
