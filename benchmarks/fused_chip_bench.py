"""Fused pool↔mesh epoch on the REAL chip (VERDICT r3 weak #4).

`benchmarks/fused_bench.py` grounds the fused path's host-orchestration
cost on the 8-device virtual CPU mesh; this bench runs the SAME
(n=8, k=6) coded workload on the real chip's 1-device mesh via the
round-4 folded-pool layout (`PoolMeshCodedGemm(n_workers=8)` on a
1-device mesh: all eight workers' blocks live in the chip's HBM, the
adopter stacks each device group on-device, the masked combine is one
compiled program) and compares it against the unfused
`ops/coded_gemm.CodedGemm` device-0 gather+solve under the tunnel's
real enqueue/fence economics.

Methodology (docs/PERF.md): EPOCHS epochs chained back-to-back with ONE
scalar fence over the final decoded output, measured fence RTT
subtracted — per-epoch fencing on this tunnel times the ~110 ms RPC,
not the framework. The `assemble` host cost (group stack enqueue +
`make_array_from_single_device_arrays` metadata) is additionally timed
per call, host-side, since it is a pure dispatch-side cost.

Run: ``PYTHONPATH=. python benchmarks/fused_chip_bench.py``
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

M, D, NCOLS = 1536, 512, 512
N, K = 8, 6
EPOCHS = 10


def bench_fused_chip(epochs: int = EPOCHS) -> dict:
    from mpistragglers_jl_tpu.parallel import PoolMeshCodedGemm, make_mesh
    from mpistragglers_jl_tpu.pool import AsyncPool, waitall

    rng = np.random.default_rng(0)
    A = rng.standard_normal((M, D)).astype(np.float32)
    B = rng.standard_normal((D, NCOLS)).astype(np.float32)
    dev = jax.devices()[0]

    tiny = jax.device_put(np.ones((8,), np.float32), dev)
    tiny_fence = jax.jit(jnp.sum)
    float(tiny_fence(tiny))
    rtt = min(
        (lambda t0: (float(tiny_fence(tiny)), time.perf_counter() - t0)[1])(
            time.perf_counter()
        )
        for _ in range(5)
    )

    fence = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))

    from mpistragglers_jl_tpu.ops import CodedGemm
    from mpistragglers_jl_tpu.pool import asyncmap

    mesh = make_mesh(1, devices=[dev])
    # batch=True: one stacked map program for the whole folded group +
    # zero-copy adoption of its result — the fully fused epoch.
    # batch_arrival="enqueue" on BOTH paths: "ready" arrival waits a
    # full tunnel round trip (~100 ms) per epoch before decode dispatch
    # and times the link, not the framework (docs/PERF.md methodology;
    # production chips have ~us fences and "ready" is the default).
    fg = PoolMeshCodedGemm(
        A, mesh, K, n_workers=N, dtype=np.float32, batch=True,
        batch_arrival="enqueue",
    )
    pool_f = AsyncPool(N)
    decoded = fg.epoch(pool_f, B)  # warmup/compile
    float(fence(decoded))
    waitall(pool_f, fg.backend)

    cg = CodedGemm(A, N, K, devices=[dev], batch=True,
                   batch_arrival="enqueue")
    pool_u = AsyncPool(N)
    asyncmap(pool_u, B, cg.backend, nwait=cg.nwait)
    Cd = cg.result_device(pool_u)
    float(fence(Cd))
    waitall(pool_u, cg.backend)

    # ALTERNATING chains: the tunnel's throughput drifts minute-to-
    # minute by more than the fused/unfused difference, so each rep
    # times both paths back-to-back and the min-over-reps compares
    # like-for-like conditions
    reps = 3
    fused_s = unfused_s = None
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(epochs):
            decoded = fg.epoch(pool_f, B)
            waitall(pool_f, fg.backend)
        float(fence(decoded))
        dt = (time.perf_counter() - t0 - rtt) / epochs
        fused_s = dt if fused_s is None else min(fused_s, dt)

        t0 = time.perf_counter()
        for _ in range(epochs):
            asyncmap(pool_u, B, cg.backend, nwait=cg.nwait)
            Cd = cg.result_device(pool_u)
            waitall(pool_u, cg.backend)
        float(fence(Cd))
        dt = (time.perf_counter() - t0 - rtt) / epochs
        unfused_s = dt if unfused_s is None else min(unfused_s, dt)

    # assemble cost alone (host dispatch side), per call
    ref = pool_f.results[int(np.flatnonzero(pool_f.repochs > 0)[0])]
    t0 = time.perf_counter()
    for _ in range(20):
        fg._adopter.assemble(pool_f, ref.shape, ref.dtype)
    assemble_ms = (time.perf_counter() - t0) / 20 * 1e3

    C = fg.full(decoded)
    err_f = float(np.max(np.abs(C - A @ B)) / np.max(np.abs(A @ B)))
    err_u = float(
        np.max(np.abs(np.asarray(Cd) - A @ B)) / np.max(np.abs(A @ B))
    )
    fg.shutdown()
    cg.backend.shutdown()

    # the library's own measured auto-selection (VERDICT r4 item 4):
    # on one device the paths sit inside the noise band, so
    # select_coded_gemm probes THIS session and the rung records the
    # decision it made
    from mpistragglers_jl_tpu.parallel import select_coded_gemm

    sel = select_coded_gemm(
        A, mesh, K, B, n_workers=N, dtype=np.float32, batch=True,
        batch_arrival="enqueue",
    )
    selection = sel.selection
    sel.shutdown()

    return {
        "auto_selection": selection,
        "metric": "fused-pool-mesh-real-chip",
        "shape": f"(n={N},k={K}) coded {M}x{D} @ {D}x{NCOLS} f32",
        "device": str(dev),
        "epochs": epochs,
        "chains_min_of": reps,
        "fence_rtt_s": round(rtt, 4),
        "fused_epoch_ms": round(fused_s * 1e3, 2),
        "assemble_ms_per_call": round(assemble_ms, 3),
        "unfused_device0_epoch_ms": round(unfused_s * 1e3, 2),
        "fused_vs_unfused": round(fused_s / unfused_s, 3),
        "fused_decode_rel_err": err_f,
        "unfused_decode_rel_err": err_u,
    }


if __name__ == "__main__":
    print(json.dumps(bench_fused_chip(), indent=1))
