"""Round-17 driver rung: device-resident coordination dispatch overhead.

The claim under measurement (ROADMAP item 4, the Amdahl item): with
transport zero-copy and the decode batched, the interpreter IS the
per-epoch cost — every host-loop epoch pays 2 + 3W host touches
(dispatch, arrival bookkeeping, decode trigger), while a fused K-epoch
window pays 2 per window (stage + harvest), 2/K per epoch amortized.

The ladder runs the SAME (n=8, k=6) MDS-coded workload over the same
per-epoch payload stream both ways on this box:

* **host loop** — 1k epochs of the real ``asyncmap`` over an
  ``XLADeviceBackend`` (dispatcher threads, mailbox completions) plus
  the per-epoch ``result_device`` decode — the before;
* **fused** — the identical 1k epochs through
  :func:`~mpistragglers_jl_tpu.pool.asyncmap_fused` windows at
  K in {1, 8, 64}: per-epoch arrival masks, fastest-k selection and
  the MDS solve inside one compiled program per window, per-epoch
  decode products harvested at the window edge.

Both sides run a zero injected-delay schedule (pure dispatch-overhead
measurement; straggler semantics are pinned bit-identically by
tests/test_device_coord.py, not timed here) and per-epoch DISTINCT
payloads, so neither side can hoist the epoch compute out of its loop.
Decode identity vs numpy is asserted on the final window.

``devcoord_harvest_k`` is the K that :func:`~mpistragglers_jl_tpu.sim.
sweep_harvest_k` recommends when priced with THIS box's measured host
costs (host_epoch_s from the host loop, host_harvest_s from the
ladder) on a representative seeded-lognormal virtual fleet;
``devcoord_overhead_x`` is the measured host/fused wall ratio at that
K and the rung FAILS below the >= 3 acceptance floor.

Standalone: ``python -m benchmarks.device_coord_bench`` (or with
``DEVCOORD_BENCH_EPOCHS=200`` for a quick pass) prints one JSON line.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def bench_device_coord_rung(epochs: int | None = None, n=8, k=6):
    from mpistragglers_jl_tpu import (
        AsyncPool,
        asyncmap,
        asyncmap_fused,
        waitall,
    )
    from mpistragglers_jl_tpu.ops.coded_gemm import CodedGemm
    from mpistragglers_jl_tpu.sim import sweep_harvest_k
    from mpistragglers_jl_tpu.utils import faults

    if epochs is None:
        epochs = int(os.environ.get("DEVCOORD_BENCH_EPOCHS", "1000"))
    ladder = [kk for kk in (1, 8, 64) if kk <= epochs]
    rng = np.random.default_rng(17)
    A = rng.standard_normal((k * 4, 32)).astype(np.float32)
    # per-epoch DISTINCT payloads: neither loop may hoist the compute
    Bs = rng.standard_normal((epochs, 32, 8)).astype(np.float32)

    out: dict = {"epochs": epochs, "n": n, "k": k}

    # -- host loop: the before -------------------------------------------
    cg = CodedGemm(A, n, k)
    try:
        pool = AsyncPool(n)
        asyncmap(pool, Bs[0], cg.backend, nwait=k)  # warmup compiles
        cg.result_device(pool)
        waitall(pool, cg.backend)
        t0 = time.perf_counter()
        for e in range(epochs):
            asyncmap(pool, Bs[e], cg.backend, nwait=k)
            dec = cg.result_device(pool)
        dec.block_until_ready()
        waitall(pool, cg.backend)
        host_s = time.perf_counter() - t0
        out["host_loop_s"] = round(host_s, 3)
        out["host_ms_per_epoch"] = round(host_s / epochs * 1e3, 4)

        # -- fused ladder: the after -------------------------------------
        rungs: dict = {}
        for K in ladder:
            coord = cg.coordinator()  # zero-delay schedule
            fpool = AsyncPool(n)
            # warmup: compile the K-window program off the clock
            asyncmap_fused(fpool, Bs[:K], coord, epochs=K)
            coord.reset()
            fpool = AsyncPool(n)
            windows = epochs // K
            t0 = time.perf_counter()
            for w in range(windows):
                hist = asyncmap_fused(
                    fpool, Bs[w * K : (w + 1) * K], coord, epochs=K
                )
            fused_s = time.perf_counter() - t0
            covered = windows * K
            rungs[str(K)] = {
                "fused_s": round(fused_s, 3),
                "ms_per_epoch": round(fused_s / covered * 1e3, 4),
                "harvest_ms": round(fused_s / windows * 1e3, 3),
                "windows": windows,
                "overhead_x_vs_host": round(
                    (host_s / epochs) / (fused_s / covered), 2
                ),
            }
            assert hist.shape == (K, n)
        out["ladder"] = rungs
        # decode identity on the final window's last epoch (the
        # coordinator must still be DOING the coordination, not a
        # degenerate no-op)
        last = np.asarray(coord.last_decoded)[-1]
        ref = A.astype(np.float64) @ Bs[covered - 1].astype(np.float64)
        err = float(np.max(np.abs(last - ref)) / np.max(np.abs(ref)))
        out["decode_rel_err"] = err
        if err > 1e-3:
            raise RuntimeError(
                f"fused window decode diverged: rel err {err:.2e}"
            )
    finally:
        cg.backend.shutdown()

    # -- the swept K: the sim twin priced with THIS box's measured
    # host costs on a representative straggling fleet ---------------------
    best_harvest_s = min(
        r["harvest_ms"] for r in rungs.values()
    ) / 1e3
    sweep = sweep_harvest_k(
        faults.seeded_lognormal(0.02, 0.6, seed=4),
        n_workers=n, nwait=k, epochs=min(epochs, 256),
        k_values=tuple(ladder),
        host_epoch_s=host_s / epochs,
        host_harvest_s=best_harvest_s,
    )
    swept_k = int(sweep["best"])
    out["sweep"] = {
        "best_k": swept_k,
        "host_loop_epochs_per_s": round(
            sweep["host_loop_epochs_per_s"], 1
        ),
        "best_epochs_per_s": round(
            sweep["best_entry"]["epochs_per_s"], 1
        ),
        "staleness_s": round(
            sweep["best_entry"]["staleness_s"], 4
        ),
    }
    out["devcoord_harvest_k"] = swept_k
    out["devcoord_overhead_x"] = rungs[str(swept_k)][
        "overhead_x_vs_host"
    ]
    if out["devcoord_overhead_x"] < 3.0:
        raise RuntimeError(
            f"devcoord_overhead_x {out['devcoord_overhead_x']} below "
            "the 3x acceptance floor at the swept K="
            f"{swept_k} (host {out['host_ms_per_epoch']} ms/epoch vs "
            f"fused {rungs[str(swept_k)]['ms_per_epoch']} ms/epoch)"
        )
    return out


if __name__ == "__main__":
    print(json.dumps(bench_device_coord_rung(), default=str))
