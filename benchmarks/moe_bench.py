"""On-chip MoE rung: the flagship with a top-1-routed expert FFN.

VERDICT r3 weak #2: MoE was correctness-tested on the virtual mesh but
never *timed* anywhere. This bench trains a 134M-activated-class MoE
(the flagship shape with every layer's MLP replaced by
``n_experts`` Switch experts of the same d_ff, all resident on the
single chip — the ep=1 fold) and reports, against the DENSE flagship
measured in the same session:

* ``tokens_per_s`` and per-step time, chained + RTT-subtracted
  (docs/PERF.md methodology);
* ``routing_overhead_share`` — (moe_step - dense_step)/moe_step, the
  router + gather-dispatch + scatter-combine share of the step (at
  ep=1 the all_to_all is a no-op, so this isolates the single-chip
  routing machinery the a2a would ride on);
* ``drop_rate`` — measured fraction of tokens dropped at the bench's
  capacity factor (computed from the routing table on the training
  batch, on-device);
* loss sanity — the MoE loss decreases and its aux load-balance loss
  is finite and near 1 (perfect balance) at init.

MFU is reported against ACTIVATED matmul FLOPs (each token runs one
expert of the same d_ff as the dense MLP, so activated FLOPs equal the
dense rung's — the standard MoE accounting).
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["bench_moe_train"]


def _timed(thunk) -> float:
    t0 = time.perf_counter()
    thunk()
    return time.perf_counter() - t0


def bench_moe_train(
    *,
    batch: int = 8,
    seq: int = 2048,
    d_model: int = 1024,
    n_layers: int = 8,
    n_heads: int = 8,
    d_ff: int = 4096,
    vocab: int = 32768,
    n_experts: int = 4,
    capacity_factor: float = 1.25,
    steps: int = 4,
    chains: int = 2,
    dense_baseline: bool = True,
) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mpistragglers_jl_tpu.models.moe import (
        _capacity,
        switch_route_indices,
    )
    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        make_train_step,
        shard_params,
    )

    dev = jax.devices()[0]
    rng = np.random.default_rng(0)

    def make(n_experts_):
        cfg = TransformerConfig(
            vocab=vocab, d_model=d_model, n_heads=n_heads,
            n_layers=n_layers, d_ff=d_ff, attn="ulysses",
            attn_impl="flash", dtype=jnp.bfloat16,
            n_experts=n_experts_, capacity_factor=capacity_factor,
            moe_aux_coef=0.01 if n_experts_ else 0.0,
        )
        axes = ("dp", "ep", "sp", "tp") if n_experts_ else ("dp", "sp", "tp")
        shape = (1,) * len(axes)
        mesh = Mesh(np.asarray([dev]).reshape(shape), axes)
        params = shard_params(init_params(cfg, seed=0), cfg, mesh)
        dspec = NamedSharding(
            mesh, P(("dp", "ep"), "sp") if n_experts_ else P("dp", "sp")
        )
        data = rng.integers(0, vocab, (batch, seq + 1), dtype=np.int32)
        toks = jax.device_put(data, dspec)
        step = make_train_step(cfg, mesh, lr=1e-3, donate=True)
        return cfg, params, step, toks[:, :-1], toks[:, 1:]

    # fence RTT
    tiny = jax.device_put(np.ones((8,), np.float32), dev)
    tiny_fence = jax.jit(jnp.sum)
    float(tiny_fence(tiny))
    rtt = min(_timed(lambda: float(tiny_fence(tiny))) for _ in range(5))

    class _Side:
        """One model's measurement state (chains are ALTERNATED between
        sides so the routing share compares like-minute conditions —
        the same drift discipline as the flagship's interleaved MFU
        ceiling)."""

        def __init__(self, cfg, params, step, inp, tgt):
            self.cfg, self.params, self.step = cfg, params, step
            self.inp, self.tgt = inp, tgt
            t0 = time.perf_counter()
            self.params, loss0 = step(self.params, inp, tgt)
            self.loss0 = float(loss0)
            self.compile_s = time.perf_counter() - t0
            self.best = None
            self.loss = self.loss0

        def chain(self):
            t0 = time.perf_counter()
            for _ in range(steps):
                self.params, loss = self.step(self.params, self.inp,
                                              self.tgt)
            self.loss = float(loss)
            dt = (time.perf_counter() - t0 - rtt) / steps
            self.best = dt if self.best is None else min(self.best, dt)

    cfg_m, params_m, step_m, inp_m, tgt_m = make(n_experts)
    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(params_m)
    )
    moe = _Side(cfg_m, params_m, step_m, inp_m, tgt_m)
    dense = _Side(*make(0)) if dense_baseline else None
    for _ in range(chains):
        moe.chain()
        if dense is not None:
            dense.chain()
    moe_s, l0, l1 = moe.best, moe.loss0, moe.loss
    compile_s, params_m = moe.compile_s, moe.params

    # >= 10-step loss TRAJECTORY with a noise-calibrated assertion
    # (VERDICT r4 item 6: a 3-step loss_decreased with a 3e-4 margin is
    # noise-level). Losses stay on device until one fetch; noise is the
    # median |second difference| — deviation from the local linear
    # trend — so the drop is measured against the trajectory's own
    # jitter, not an arbitrary epsilon.
    traj_steps = 12
    traj = []
    for _ in range(traj_steps):
        params_m, li = step_m(params_m, inp_m, tgt_m)
        traj.append(li)
    traj = [float(v) for v in np.asarray(jnp.stack(traj))]
    drop = traj[0] - traj[-1]
    second = np.abs(np.diff(traj, n=2))
    noise = float(np.median(second)) if second.size else 0.0
    traj_ok = bool(drop > 5 * max(noise, 1e-9))

    # measured drop rate at this capacity factor: route the actual
    # training batch through layer 0's (trained) router on-device
    E = n_experts
    T = batch * seq
    C = _capacity(T, E, capacity_factor)

    C_half = _capacity(T, E, 0.5)

    @jax.jit
    def drops(params, toks):
        x = params["emb"][toks].reshape(T, d_model)
        wg = params["layers"][0]["wg"]
        table, _, _, aux = switch_route_indices(x, wg, C)
        routed = (table < T).sum()
        # under-capacity probe: the SAME batch/router at cf=0.5 — a
        # balanced router must then drop ~half its tokens, so this
        # shows the measured drop machinery firing (a near-init router
        # at the rung's generous cf legitimately reads 0.0 — round-4
        # PERF note)
        table_h, _, _, _ = switch_route_indices(x, wg, C_half)
        routed_h = (table_h < T).sum()
        return 1.0 - routed / T, 1.0 - routed_h / T, aux

    drop_rate, drop_rate_cf_half, aux0 = drops(params_m, inp_m)

    out = {
        "metric": "moe-train-step",
        "value": round(moe_s, 4),
        "unit": "s",
        "tokens_per_s": round(batch * seq / moe_s, 1),
        "params_m": round(n_params / 1e6, 1),
        "n_experts": n_experts,
        "capacity_factor": capacity_factor,
        "capacity_per_expert": C,
        "drop_rate": round(float(drop_rate), 4),
        "drop_rate_at_cf_0.5": round(float(drop_rate_cf_half), 4),
        "aux_loss": round(float(aux0), 3),
        "loss_first": round(l0, 4),
        "loss_last": round(l1, 4),
        "loss_decreased": bool(l1 < l0),
        "trajectory_steps": traj_steps,
        "trajectory_first": round(traj[0], 4),
        "trajectory_last": round(traj[-1], 4),
        "trajectory_drop": round(drop, 5),
        "trajectory_noise_med2nd": round(noise, 6),
        "trajectory_drop_over_noise": round(
            drop / max(noise, 1e-9), 1
        ),
        "trajectory_ok": traj_ok,
        "compile_s": round(compile_s, 1),
        "batch": batch,
        "seq": seq,
        "fence_rtt_s": round(rtt, 4),
        "steps_pipelined": steps,
        "chains_min_of": chains,
    }
    if dense is not None:
        dense_s = dense.best
        out["dense_step_s"] = round(dense_s, 4)
        out["dense_tokens_per_s"] = round(batch * seq / dense_s, 1)
        out["routing_overhead_share"] = round((moe_s - dense_s) / moe_s, 3)
        out["dense_loss_first"] = round(dense.loss0, 4)
        out["chains_alternated"] = True
    return out


if __name__ == "__main__":
    import json
    import os
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    kw = {}
    if "--quick" in sys.argv:
        kw = dict(steps=2, chains=1, n_layers=2)
    print(json.dumps(bench_moe_train(**kw), indent=1))
