"""Fused pool↔mesh decode vs unfused device-0 gather: measured overhead.

VERDICT r2 weak #6: the fused path (parallel/fused.py) claimed
zero-copy / no-device-0-hotspot with no number attached. This bench
measures both paths per epoch on the 8-device virtual CPU mesh (the
only place an 8-device mesh exists in this environment) and splits the
fused epoch into its phases:

* ``asyncmap`` — the pool map step (same on both paths);
* ``assemble`` — `_ShardAdopter.assemble`: adopting the 8 device-
  resident shards into ONE sharded global array
  (``jax.make_array_from_single_device_arrays`` — metadata only, no
  copy; this is the number that proves "zero-copy");
* ``combine`` — the masked psum_scatter decode (one sharded program,
  decode collective rides the mesh interconnect);
* unfused ``result_device`` — `ops/coded_gemm.CodedGemm`: device_put
  of the k winners onto device 0 + the k×k solve there (the hotspot
  the fused path removes).

Interpretation notes for the PERF table (docs/PERF.md):

* on the virtual CPU mesh the COLLECTIVE cost is host-emulated and the
  per-device HBM hotspot does not exist, so the comparison grounds the
  *host-side orchestration* overhead (adopt + launch vs gather) and
  the structural claim, not TPU rates;
* the single-chip dispatch-side costs (enqueue ~0.6-0.9 ms/epoch,
  fence ~110 ms) are measured on real hardware by bench.py's config-2
  breakdown and apply to both paths identically — the fused path adds
  `assemble` (measured ~sub-ms here) and removes the k device-to-
  device copies.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python benchmarks/fused_bench.py
(forces the CPU platform itself, like tests/conftest.py).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FLAG = "--xla_force_host_platform_device_count=8"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _FLAG).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from mpistragglers_jl_tpu import AsyncPool, asyncmap, waitall
from mpistragglers_jl_tpu.ops import CodedGemm
from mpistragglers_jl_tpu.parallel import PoolMeshCodedGemm, make_mesh

M, D, NCOLS = 1536, 512, 512
N, K = 8, 6
EPOCHS = 20


def bench_fused() -> dict:
    rng = np.random.default_rng(0)
    A = rng.standard_normal((M, D)).astype(np.float32)
    B = rng.standard_normal((D, NCOLS)).astype(np.float32)

    mesh = make_mesh(N)
    fg = PoolMeshCodedGemm(A, mesh, K)
    pool = AsyncPool(N)
    decoded = fg.epoch(pool, B)  # warmup: compiles map + combine
    jax.block_until_ready(decoded)
    waitall(pool, fg.backend)

    t_async = t_assemble = t_decode = 0.0
    for _ in range(EPOCHS):
        t0 = time.perf_counter()
        asyncmap(pool, B, fg.backend, nwait=fg.nwait)
        t1 = time.perf_counter()
        # assemble timed standalone for the breakdown (decode_from_pool
        # repeats it internally; its cost is counted once, inside
        # decode_ms, for the total)
        ref = pool.results[int(pool.fresh_indices()[0])]
        fg._adopter.assemble(pool, ref.shape, ref.dtype)
        t2 = time.perf_counter()
        # steady state: same arrival pattern -> decode weights cached
        decoded = fg.decode_from_pool(pool)
        jax.block_until_ready(decoded)
        t3 = time.perf_counter()
        t_async += t1 - t0
        t_assemble += t2 - t1
        t_decode += t3 - t2
        waitall(pool, fg.backend)
    C = fg.full(decoded)
    err = float(np.max(np.abs(C - A @ B))) / float(np.max(np.abs(A @ B)))
    fg.shutdown()
    return {
        "asyncmap_ms": round(t_async / EPOCHS * 1e3, 3),
        "assemble_ms": round(t_assemble / EPOCHS * 1e3, 3),
        "decode_ms_incl_assemble": round(t_decode / EPOCHS * 1e3, 3),
        "total_ms": round((t_async + t_decode) / EPOCHS * 1e3, 3),
        "decode_rel_err": err,
    }


def bench_unfused() -> dict:
    rng = np.random.default_rng(0)
    A = rng.standard_normal((M, D)).astype(np.float32)
    B = rng.standard_normal((D, NCOLS)).astype(np.float32)

    cg = CodedGemm(A, N, K, devices=jax.devices()[:N])
    pool = AsyncPool(N)
    asyncmap(pool, B, cg.backend, nwait=cg.nwait)  # warmup
    jax.block_until_ready(cg.result_device(pool))
    waitall(pool, cg.backend)

    t_async = t_decode = 0.0
    for _ in range(EPOCHS):
        t0 = time.perf_counter()
        asyncmap(pool, B, cg.backend, nwait=cg.nwait)
        t1 = time.perf_counter()
        C = cg.result_device(pool)  # gathers k winners onto device 0
        jax.block_until_ready(C)
        t2 = time.perf_counter()
        t_async += t1 - t0
        t_decode += t2 - t1
        waitall(pool, cg.backend)
    err = float(np.max(np.abs(np.asarray(C) - A @ B))) / float(
        np.max(np.abs(A @ B))
    )
    cg.backend.shutdown()
    return {
        "asyncmap_ms": round(t_async / EPOCHS * 1e3, 3),
        "gather_decode_ms": round(t_decode / EPOCHS * 1e3, 3),
        "total_ms": round((t_async + t_decode) / EPOCHS * 1e3, 3),
        "decode_rel_err": err,
    }


if __name__ == "__main__":
    fused = bench_fused()
    unfused = bench_unfused()
    print(json.dumps({
        "metric": "fused-vs-unfused-decode",
        "mesh": "8 virtual CPU devices (see module docstring caveats)",
        "shape": f"(n={N},k={K}) coded {M}x{D} @ {D}x{NCOLS} f32",
        "epochs": EPOCHS,
        "fused": fused,
        "unfused_device0": unfused,
    }))
