"""Adaptive vs fixed nwait under a drifting straggler pattern.

The reference hard-codes ``nwait`` everywhere (test/kmap2.jl:32 etc.);
this measures what that costs when the straggler MOVES. Workload: n=8
thread workers, 5 ms base latency; the straggler (75 ms) rotates to a
different worker every 20 epochs. Policies:

* ``full gather``   — nwait = 8 (pays the straggler every epoch)
* ``fixed k=6``     — the right constant for this fault pattern, if you
                      somehow knew it in advance
* ``adaptive``      — AdaptiveNwait with kmin=4, learning online

Metric: mean epoch wall-clock per policy over 100 epochs (+ fresh
results per epoch, since waiting for fewer buys time but less data).
Prints one JSON line per policy. CPU-only (threads), deterministic.

Run:  python benchmarks/adaptive_nwait_bench.py [epochs]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mpistragglers_jl_tpu import AsyncPool, LocalBackend, asyncmap, waitall
from mpistragglers_jl_tpu.utils import AdaptiveNwait

N = 8
BASE_S = 0.005
STRAGGLE_S = 0.075
ROTATE_EVERY = 20


class RotatingStraggler:
    """The straggler moves to worker (epoch // ROTATE_EVERY) % N."""

    def __call__(self, worker: int, epoch: int) -> float:
        hot = (epoch // ROTATE_EVERY) % N
        return STRAGGLE_S if worker == hot else BASE_S


def run_policy(name: str, epochs: int):
    backend = LocalBackend(
        lambda i, p, e: p + i, N, delay_fn=RotatingStraggler()
    )
    ctl = (
        AdaptiveNwait(N, kmin=4, min_samples=2, refit_every=5, seed=0)
        if name == "adaptive"
        else None
    )
    fixed = (
        None if ctl is not None
        else {"full-gather": N, "fixed-k6": 6}[name]  # unknown: fail fast
    )
    try:
        pool = AsyncPool(N)
        walls, fresh_counts = [], []
        # the straggler rotation keys off pool.epoch (advanced inside
        # asyncmap), not a loop counter
        for _ in range(epochs):
            nwait = ctl.nwait if ctl is not None else fixed
            t0 = time.perf_counter()
            asyncmap(pool, np.zeros(1), backend, nwait=nwait)
            walls.append(time.perf_counter() - t0)
            fresh_counts.append(int(pool.fresh_indices().size))
            if ctl is not None:
                ctl.observe(pool)
        waitall(pool, backend)
        return {
            "metric": f"adaptive-nwait-{name}",
            "value": round(float(np.mean(walls)) * 1e3, 2) if walls else None,
            "unit": "ms/epoch",
            "fresh_mean": (
                round(float(np.mean(fresh_counts)), 2) if fresh_counts else None
            ),
            "epochs": epochs,
            # the controller's state AFTER its last observe/refit
            "final_nwait": ctl.nwait if ctl is not None else fixed,
        }
    finally:
        backend.shutdown()


def main():
    epochs = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    for name in ("full-gather", "fixed-k6", "adaptive"):
        print(json.dumps(run_policy(name, epochs)))


if __name__ == "__main__":
    main()
