"""Adaptive vs fixed nwait under a drifting straggler pattern.

The reference hard-codes ``nwait`` everywhere (test/kmap2.jl:32 etc.);
this measures what that costs when the straggler MOVES. Workload: n=8
thread workers, 5 ms base latency; the straggler (75 ms) rotates to a
different worker every 20 epochs. Policies:

* ``full gather``   — nwait = 8 (pays the straggler every epoch)
* ``fixed k=6``     — the right constant for this fault pattern, if you
                      somehow knew it in advance
* ``adaptive``      — AdaptiveNwait with kmin=4, learning online

Metric: mean epoch wall-clock per policy over 100 epochs (+ fresh
results per epoch, since waiting for fewer buys time but less data).
Prints one JSON line per policy. CPU-only (threads), deterministic.

Run:  python benchmarks/adaptive_nwait_bench.py [epochs]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mpistragglers_jl_tpu import AsyncPool, LocalBackend, asyncmap, waitall
from mpistragglers_jl_tpu.utils import AdaptiveNwait

N = 8
BASE_S = 0.005
STRAGGLE_S = 0.075
ROTATE_EVERY = 20


class RotatingStraggler:
    """The straggler moves to worker (epoch // rotate_every) % n."""

    def __init__(self, n: int = N, slow: float = STRAGGLE_S,
                 base: float = BASE_S, rotate_every: int = ROTATE_EVERY):
        self.n, self.slow, self.base = n, slow, base
        self.rotate_every = rotate_every

    def __call__(self, worker: int, epoch: int) -> float:
        hot = (epoch // self.rotate_every) % self.n
        return self.slow if worker == hot else self.base


def _echo(i, payload, epoch):
    return payload


def record_drifting_trace(path, epochs: int, n: int = N,
                          delay_fn=None) -> None:
    """Record one drifting-straggler trace (EpochTracer JSONL) that
    ``utils.faults.from_trace`` replays identically for every policy —
    the record -> replay loop as the A/B's controlled variable."""
    from mpistragglers_jl_tpu.utils import EpochTracer

    tracer = EpochTracer()
    backend = LocalBackend(
        _echo, n, delay_fn=delay_fn or RotatingStraggler(n)
    )
    try:
        pool = AsyncPool(n)
        for _ in range(epochs):
            asyncmap(pool, np.zeros(1), backend, nwait=n, tracer=tracer)
        waitall(pool, backend)
        tracer.dump_jsonl(path)
    finally:
        backend.shutdown()


def replay_policy(path, *, adaptive: bool, epochs: int, n: int = N,
                  kmin: int | None = None):
    """Replay the recorded trace under one nwait policy (thread
    workers). Returns (mean_ms, mean_fresh, final_nwait)."""
    from mpistragglers_jl_tpu.utils.faults import from_trace

    backend = LocalBackend(_echo, n, delay_fn=from_trace(path))
    ctl = AdaptiveNwait(
        n, kmin=n - 2 if kmin is None else kmin,
        min_samples=2, refit_every=5, seed=0,
    ) if adaptive else None
    try:
        pool = AsyncPool(n)
        walls, fresh = [], []
        for _ in range(epochs):
            nwait = ctl.nwait if ctl else n
            t0 = time.perf_counter()
            asyncmap(pool, np.zeros(1), backend, nwait=nwait)
            walls.append(time.perf_counter() - t0)
            fresh.append(int(pool.fresh_indices().size))
            if ctl:
                ctl.observe(pool)
        waitall(pool, backend)
        return (
            float(np.mean(walls)) * 1e3,
            float(np.mean(fresh)),
            ctl.nwait if ctl else n,
        )
    finally:
        backend.shutdown()


def run_policy(name: str, epochs: int):
    backend = LocalBackend(
        lambda i, p, e: p + i, N, delay_fn=RotatingStraggler()
    )
    ctl = (
        AdaptiveNwait(N, kmin=4, min_samples=2, refit_every=5, seed=0)
        if name == "adaptive"
        else None
    )
    fixed = (
        None if ctl is not None
        else {"full-gather": N, "fixed-k6": 6}[name]  # unknown: fail fast
    )
    try:
        pool = AsyncPool(N)
        walls, fresh_counts = [], []
        # the straggler rotation keys off pool.epoch (advanced inside
        # asyncmap), not a loop counter
        for _ in range(epochs):
            nwait = ctl.nwait if ctl is not None else fixed
            t0 = time.perf_counter()
            asyncmap(pool, np.zeros(1), backend, nwait=nwait)
            walls.append(time.perf_counter() - t0)
            fresh_counts.append(int(pool.fresh_indices().size))
            if ctl is not None:
                ctl.observe(pool)
        waitall(pool, backend)
        return {
            "metric": f"adaptive-nwait-{name}",
            "value": round(float(np.mean(walls)) * 1e3, 2) if walls else None,
            "unit": "ms/epoch",
            "fresh_mean": (
                round(float(np.mean(fresh_counts)), 2) if fresh_counts else None
            ),
            "epochs": epochs,
            # the controller's state AFTER its last observe/refit
            "final_nwait": ctl.nwait if ctl is not None else fixed,
        }
    finally:
        backend.shutdown()


def run_coded_sgd_policy(adaptive: bool, trace_path, epochs: int = 60):
    """BASELINE config 5 driven by the decision layer: gradient-coded
    SGD (s=2 redundancy) under a drifting straggler TRACE, adaptive vs
    the full-gather posture. The trace is recorded once (rotating
    straggler over thread workers) and replayed via
    ``utils.faults.from_trace`` so both policies face the identical
    latency pattern (VERDICT round 1 item 10)."""
    from mpistragglers_jl_tpu.models import CodedSGD
    from mpistragglers_jl_tpu.utils.faults import from_trace

    n, s_red = 8, 2
    path = trace_path
    rng = np.random.default_rng(0)
    X = rng.standard_normal((4096, 64)).astype(np.float32)
    w_true = rng.standard_normal(64)
    y = (X @ w_true > 0).astype(np.float32)
    sgd = CodedSGD(X, y, n, s_red, delay_fn=from_trace(path))
    try:
        ctl = AdaptiveNwait(
            n, kmin=n - s_red, min_samples=2, refit_every=5, seed=0
        ) if adaptive else None
        pool = AsyncPool(n)
        import jax.numpy as jnp

        w = jnp.zeros(64, dtype=jnp.float32)
        walls = []
        for _ in range(epochs):
            t0 = time.perf_counter()
            w = sgd.step(
                pool, w, 0.5, nwait=(ctl.nwait if ctl else n)
            )
            walls.append(time.perf_counter() - t0)
            if ctl:
                ctl.observe(pool)
        waitall(pool, sgd.backend)
        Xe, ye = sgd.eval_data()
        loss = float(sgd.model.loss(w, Xe, ye))
        return {
            "metric": "adaptive-nwait-codedsgd-"
            + ("adaptive" if adaptive else "full-gather"),
            "value": round(float(np.mean(walls)) * 1e3, 2),
            "unit": "ms/step",
            "final_loss": round(loss, 5),
            "final_nwait": ctl.nwait if ctl else n,
            "epochs": epochs,
        }
    finally:
        sgd.backend.shutdown()


def main():
    import tempfile
    import uuid

    epochs = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    for name in ("full-gather", "fixed-k6", "adaptive"):
        print(json.dumps(run_policy(name, epochs)))
    # config 5 under the decision layer: ONE recorded trace, replayed
    # identically for both policies. The straggler is slowed to 0.6 s so
    # it dominates the device path's fixed per-step dispatch cost (the
    # tunneled bench chip pays ~0.1-0.2 s/step regardless of policy).
    sgd_epochs = min(epochs, 60)
    path = os.path.join(
        tempfile.gettempdir(), f"adpt-trace-{uuid.uuid4().hex[:8]}.jsonl"
    )
    record_drifting_trace(
        path, sgd_epochs, delay_fn=RotatingStraggler(slow=0.6)
    )
    try:
        for adaptive in (False, True):
            print(json.dumps(
                run_coded_sgd_policy(adaptive, path, sgd_epochs)
            ))
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


if __name__ == "__main__":
    main()
