"""Round-15 router rung: the serving-tier router plane, sim and live.

Two halves, mirroring how the router is meant to be operated:

* **sim** (:func:`bench_router_rung`, unscaled like the ``sim`` rung —
  virtual-time bookkeeping does not track the matmul rate): a
  1M-request diurnal day over 8 straggling ``SimReplica`` schedulers
  through the REAL :class:`~mpistragglers_jl_tpu.models.router.
  RequestRouter` on a ``VirtualClock`` — replay throughput in
  requests/s and events/s, a bit-identity witness (a 50k-request slice
  run twice must produce one digest), and the policy headline: the
  ``sweep_router_policy`` point at 0.8 load with a 1.8x straggling
  replica, reporting the swept winner's p99-TTFT edge over round_robin
  (``router_p99_x``, the compact-line scalar; acceptance floor 1.15).
* **live** (:func:`bench_router_live_rung`, budget-guarded): four REAL
  ``ServingScheduler`` replicas (one artificially stalled per tick —
  the straggling-replica scenario) under a paced open-loop arrival
  stream at ~0.8 utilization, round_robin vs least_loaded p99 TTFT on
  the wall clock, a mid-run replica kill/recover leg asserting ZERO
  dropped requests, and the router's own bookkeeping share of the
  stepping wall (the <= 5% tick-budget gate).

Compact-line scalars (bench.py): ``router_p99_x`` (sim sweep,
round_robin p99 / winner p99) and ``router_sim_Mreq_s`` (million
requests replayed per wall second). Format documented in
benchmarks/README.md (round-15 note).
"""

from __future__ import annotations

import os
import time

import numpy as np


def _fleet(clock, n=8, slots=16, n_inner=32, tick_s=0.025, sigma=0.2,
           straggler=None):
    from mpistragglers_jl_tpu.sim import SimReplica, lognormal_ticks

    mult = straggler or {}
    return [
        SimReplica(
            clock, slots=slots, n_inner=n_inner, prompt_chunk=128,
            tick_s=lognormal_ticks(tick_s * mult.get(i, 1.0), sigma,
                                   seed=60 + i),
        )
        for i in range(n)
    ]


def _day(requests, *, n=8, slots=16, seed=4):
    from mpistragglers_jl_tpu.models.router import RequestRouter
    from mpistragglers_jl_tpu.sim import (
        VirtualClock,
        diurnal_arrivals,
        run_router_day,
    )

    clock = VirtualClock()
    fleet = _fleet(clock, n=n, slots=slots)
    router = RequestRouter(fleet, policy="least_loaded", clock=clock)
    cap = n * slots / (2 * 0.025)  # 2 ticks per request at mean tick
    report = run_router_day(
        router,
        diurnal_arrivals(0.7 * cap, n=requests, period=86_400.0,
                         amplitude=0.8, seed=seed, prompt_len=128,
                         max_new=32),
    )
    ticks = sum(r.tick_count for r in fleet)
    return report, ticks


def bench_router_rung(requests: int | None = None):
    """The sim half (driver rung ``router``): 1M-request diurnal
    replay + determinism witness + the swept policy headline."""
    if requests is None:
        requests = int(os.environ.get("ROUTER_BENCH_REQUESTS",
                                      "1000000"))
    # -- determinism witness: a 50k slice, twice, one digest ------------
    slice_n = min(50_000, requests)
    d1, _ = _day(slice_n, seed=11)
    d2, _ = _day(slice_n, seed=11)
    if d1.digest() != d2.digest():
        raise AssertionError(
            f"sim day not bit-identical: {d1.digest()} != {d2.digest()}"
        )
    # -- the 1M-request diurnal day -------------------------------------
    t0 = time.perf_counter()
    report, ticks = _day(requests)
    wall = time.perf_counter() - t0
    if report.dropped:
        raise AssertionError(f"{report.dropped} requests dropped")
    events = requests + ticks  # arrivals + scheduler ticks replayed
    # -- policy headline: the sweep point the ROADMAP asks for ----------
    from mpistragglers_jl_tpu.sim import sweep_router_policy

    sweep = sweep_router_policy(
        requests=3000, load=0.8, straggler={0: 1.8}, tick_sigma=0.25,
        seed=4,
        policies=("round_robin", "least_loaded", "prefix_affinity"),
    )
    p99x = sweep["p99_vs_round_robin"]
    return {
        "sim_requests": requests,
        "sim_wall_s": round(wall, 2),
        "req_per_s": round(requests / wall),
        "events_per_s": round(events / wall),
        "virtual_s": round(report.virtual_s, 1),
        "p99_ttft_ms": round(report.p99_ttft() * 1e3, 2),
        "digest": (
            f"{requests/1e6:g}M/{requests/wall/1e3:.0f}kreq/s"
            f"/x{p99x:.2f}"
        ),
        "deterministic": True,
        "replay_digest": d1.digest(),
        "sweep_best": sweep["best"],
        "sweep_p99_ms": {
            e["policy"]: round(e["p99_ttft_s"] * 1e3, 2)
            for e in sweep["entries"]
        },
        # compact-line scalars (benchmarks/README.md round-15 note)
        "router_p99_x": round(p99x, 2),
        "router_sim_Mreq_s": round(requests / wall / 1e6, 3),
    }


class _TimedReplica:
    """Forwarding proxy that clocks the scheduler's own step() wall, so
    the live rung can separate router bookkeeping from scheduler ticks
    (the <= 5% budget is on the ROUTER'S share)."""

    def __init__(self, inner):
        self.inner = inner
        self.step_s = 0.0

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def step(self):
        t0 = time.perf_counter()
        out = self.inner.step()
        self.step_s += time.perf_counter() - t0
        return out


def _live_fleet(params, cfg, stall_s):
    from mpistragglers_jl_tpu.models.serving import ServingScheduler

    class Stalled(ServingScheduler):
        """A replica rate-limited to one tick per ``stall_s`` of wall
        clock — slow WITHOUT blocking the shared step loop (a sleeping
        straggler would serialize every replica behind it, which is
        exactly what independent scheduler processes do not do; the
        gate models the slow box, not a slow loop)."""

        _last_gate = 0.0

        def step(self):
            now = time.perf_counter()
            if now - self._last_gate < stall_s:
                return []
            self._last_gate = now
            return super().step()

    mk = lambda cls: cls(params, cfg, slots=4, n_inner=4,  # noqa: E731
                         prompt_chunk=32, max_prompt=64)
    return [
        _TimedReplica(mk(Stalled if i == 3 else ServingScheduler))
        for i in range(4)
    ]


def _drive_live(router, prompts, max_new, inter_arrival_s,
                kill_at=None, recover_at=None, min_work_s=0.0):
    """Open-loop pacing on the wall clock: request i is due at
    ``t0 + i * inter_arrival_s`` and EVERY due request is submitted
    before the next step (no sleeps, and the pacing survives slow
    iterations — a single-threaded loop must not let tick time dilute
    the offered load); optionally mark a replica down/up at given
    request indices (the kill/recover leg)."""
    rrs = []
    t0 = time.perf_counter()
    # overhead accounting: only iterations where a scheduler actually
    # ticked count toward the router-vs-tick share — iterations that
    # spin on a rate-gated straggler are loop artifacts, not
    # per-request router cost (the <= 5% budget is bookkeeping per
    # unit of TICK work)
    step_work = 0.0
    sched_work = 0.0
    sched_prev = 0.0
    i = 0
    while i < len(prompts) or router.in_flight:
        now = time.perf_counter() - t0
        while i < len(prompts) and now >= i * inter_arrival_s:
            if kill_at is not None and i == kill_at:
                router.mark_down(3)
            if recover_at is not None and i == recover_at:
                router.mark_up(3)
            rrs.append(router.submit(prompts[i], max_new))
            i += 1
        s0 = time.perf_counter()
        router.step()
        dt = time.perf_counter() - s0
        sched_now = sum(r.step_s for r in router.replicas)
        if sched_now - sched_prev > min_work_s:
            # a real tick ran (the threshold screens out iterations
            # whose only "work" was a rate-gate check, microseconds)
            step_work += dt
            sched_work += sched_now - sched_prev
        sched_prev = sched_now
    return rrs, step_work, sched_work


def bench_router_live_rung(requests: int = 40):
    """The live half (driver key ``router.live``, budget-guarded):
    real schedulers, real wall clock — the p99 margin, the kill leg,
    and the router-overhead share."""
    from mpistragglers_jl_tpu.models.router import RequestRouter
    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab=61, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2,
        d_ff=128, attn_window=6,
    )
    params = init_params(cfg, seed=3)
    rng = np.random.default_rng(8)
    prompts = [
        rng.integers(1, cfg.vocab, size=24).astype(np.int32)
        for _ in range(requests)
    ]
    # calibrate the warm tick: run one request to completion first
    # (admission/tick/place programs compile there), then measure a
    # second — compile time in tick_s would blow the pacing and the
    # stall scale
    fleet = _live_fleet(params, cfg, 0.0)
    warm = fleet[0]
    warm.submit(prompts[0], 8)
    while warm.active or warm.pending:
        warm.step()
    warm.submit(prompts[1], 8)
    t0 = time.perf_counter()
    n0 = warm.tick_count
    while warm.active or warm.pending:
        warm.step()
    tick_s = (time.perf_counter() - t0) / max(warm.tick_count - n0, 1)
    stall_s = 4.0 * tick_s  # replica 3 ticks at 1/4 the fleet rate
    # 0.8 utilization, calibrated EMPIRICALLY: a closed-loop burst
    # through the real straggling fleet measures the capacity the
    # single-threaded step loop actually delivers (a tick-math
    # estimate overstates it — the loop serializes replica ticks — and
    # overload on both sides would bury the policy difference under
    # queueing)
    fleet = _live_fleet(params, cfg, stall_s)
    router = RequestRouter(fleet, policy="least_loaded")
    burst = min(24, requests)
    t0 = time.perf_counter()
    for p in prompts[:burst]:
        router.submit(p, 16)
    router.drain()
    fleet_rate = burst / (time.perf_counter() - t0)
    inter = 1.0 / (0.8 * fleet_rate)
    out = {"tick_ms": round(tick_s * 1e3, 2),
           "stall_ms": round(stall_s * 1e3, 2),
           "fleet_req_s": round(fleet_rate, 1),
           "requests": requests}
    p99 = {}
    for policy in ("round_robin", "least_loaded"):
        fleet = _live_fleet(params, cfg, stall_s)
        router = RequestRouter(fleet, policy=policy)
        rrs, step_work, sched_work = _drive_live(
            router, prompts, 16, inter, min_work_s=0.1 * tick_s
        )
        assert all(rr.finished for rr in rrs)
        ttfts = np.asarray([rr.ttft for rr in rrs])
        p99[policy] = float(np.percentile(ttfts, 99))
        out[policy] = {
            "p50_ttft_ms": round(
                float(np.percentile(ttfts, 50)) * 1e3, 2
            ),
            "p99_ttft_ms": round(p99[policy] * 1e3, 2),
            "router_overhead_pct": round(
                max(step_work - sched_work, 0.0) / step_work * 100, 2
            ),
        }
    out["live_p99_x"] = round(p99["round_robin"] / p99["least_loaded"], 2)
    out["p99_margin_ok"] = out["live_p99_x"] >= 1.15
    out["overhead_ok"] = (
        out["least_loaded"]["router_overhead_pct"] <= 5.0
    )
    # -- kill/recover leg: one replica dies mid-run, zero drops ---------
    # (denser arrivals + max_new=24 keep every replica holding live
    # requests, so the killed one actually has in-flight work to
    # re-route when the flip lands)
    fleet = _live_fleet(params, cfg, 0.0)
    router = RequestRouter(fleet, policy="least_loaded")
    n_kill = max(requests // 2, 12)
    rrs, _, _ = _drive_live(
        router, prompts[:n_kill], 24, inter * 0.3,
        kill_at=8, recover_at=n_kill - 4,
    )
    dropped = sum(not rr.finished for rr in rrs)
    out["kill_leg"] = {
        "dropped": dropped,
        "rerouted": router.n_rerouted,
        "zero_drop_ok": dropped == 0 and router.n_rerouted > 0,
    }
    return out


if __name__ == "__main__":
    import json

    out = bench_router_rung(
        requests=int(os.environ.get("ROUTER_BENCH_REQUESTS", "200000"))
    )
    out["live"] = bench_router_live_rung()
    print(json.dumps(out, default=str))
