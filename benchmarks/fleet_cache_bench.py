"""Round-25 fleet prefix-cache rung: fleet hits vs local-only sharing.

One leg, sim-only (unscaled in bench.py — virtual-time bookkeeping
does not track the matmul rate): a many-tenant prefix-heavy day over
a 3-replica fleet — 70% of prompts reuse one of 24 shared prefix
groups (system prompts / few-shot headers), routed ``least_loaded``
so groups land on whichever replica is free — driven two ways on
IDENTICAL seeded arrivals at equal total HBM (the tiered cache adds
host DRAM and peer links, never device memory):

* **local-only** (the r19 baseline): prefix pages are shared only
  while some slot on the SAME replica still holds the group —
  ``least_loaded`` scatters a group across the fleet, so most
  admissions re-prefill a prefix another replica already computed;
* **fleet cache**: the :class:`~mpistragglers_jl_tpu.sim.workload.
  SimFleetCache` hub prices the tiered lookup — host-DRAM spill
  store first, then a reachable peer's HBM — and an admission that
  hits EITHER tier skips its shared prefill chunks, paying the
  planner's byte-priced transfer seconds instead; run TWICE for the
  bit-identity witness.

Headline scalars (bench.py compact line, format in
benchmarks/README.md round-25 note):

* ``fleet_hit_x`` — (local shared admits + fleet tier hits) on the
  cache day over local shared admits on the baseline day; FAILS
  under the pinned 1.5x floor (measured ~13x on the reference day:
  with 24 groups over 3 replicas, local residency is the rare case);
* ``prefill_chip_s_saved`` — fleet hits x shared prefill chunks per
  hit x ``chunk_s``: prefill chip-seconds the tiers returned to the
  fleet, the currency the paper prices stragglers in.

Both cache days (same seed) must agree on the workload digest — the
sim plane's bit-identity witness; spill/fetch/fallback counters stay
OUTSIDE the digest. Zero drops on every leg.
"""

from __future__ import annotations

import math
import time

_N_REP, _SLOTS, _NI, _TICK = 3, 4, 8, 0.02
_CHUNK_S = 0.004  # priced prefill: one chunk of real chip work
_PLEN, _CHUNK, _MNEW = 512, 64, 32
_PFX_LEN, _PFX_SHARE, _GROUPS = 256, 0.7, 24
_RATE = 30.0  # ~0.7 of fleet capacity at these service times
_STORE_GROUPS = 64  # host-DRAM capacity: holds every group warm
_HIT_X_FLOOR = 1.5


def _day(n: int, seed: int, *, fleet: bool):
    from mpistragglers_jl_tpu.models.router import RequestRouter
    from mpistragglers_jl_tpu.sim import (
        SimFleetCache,
        SimReplica,
        VirtualClock,
        lognormal_ticks,
        poisson_arrivals,
        run_router_day,
    )

    clock = VirtualClock()
    cache = SimFleetCache(store_groups=_STORE_GROUPS) if fleet else None
    reps = [
        SimReplica(clock, slots=_SLOTS, n_inner=_NI,
                   prompt_chunk=_CHUNK, chunk_s=_CHUNK_S,
                   cache=cache,
                   tick_s=lognormal_ticks(_TICK, 0.1, seed=2017 + i))
        for i in range(_N_REP)
    ]
    router = RequestRouter(reps, policy="least_loaded", clock=clock)
    arrivals = poisson_arrivals(
        _RATE, n=n, seed=seed, prompt_len=_PLEN, max_new=_MNEW,
        prefix_share=_PFX_SHARE, prefix_len=_PFX_LEN,
        n_prefix_groups=_GROUPS,
        tenants={"a": 0.4, "b": 0.3, "c": 0.2, "d": 0.1},
    )
    report = run_router_day(router, arrivals)
    shared = sum(r.n_shared_admits for r in reps)
    hits = sum(r.n_fleet_hits for r in reps)
    return report, shared, hits, cache


def bench_fleet_cache_rung(requests: int | None = None):
    """The driver rung ``fleet_cache``: local-only vs tiered fleet
    cache on identical prefix-heavy arrivals, with the 1.5x hit-rate
    gate, the chip-seconds-saved readout, and the bit-identity
    witness over the cache day."""
    import os

    n = int(
        requests if requests is not None
        else os.environ.get("FLEET_CACHE_BENCH_REQUESTS", "3000")
    )
    seed = 29
    t0 = time.perf_counter()
    base, base_shared, base_hits, _ = _day(n, seed, fleet=False)
    if base_hits:
        raise AssertionError(
            f"baseline day counted {base_hits} fleet hits with no "
            "cache attached"
        )
    fc1, shared, hits, cache = _day(n, seed, fleet=True)
    fc2, _, hits2, _ = _day(n, seed, fleet=True)
    if fc1.digest() != fc2.digest():
        raise AssertionError(
            f"fleet-cache day not bit-identical: {fc1.digest()} != "
            f"{fc2.digest()}"
        )
    if hits != hits2:
        raise AssertionError(
            f"fleet hit count drifted across replays: {hits} != {hits2}"
        )
    if base.dropped or fc1.dropped:
        raise AssertionError(
            f"dropped requests (base {base.dropped}, fleet "
            f"{fc1.dropped}): the day must complete"
        )
    hit_x = (shared + hits) / max(base_shared, 1)
    if hit_x < _HIT_X_FLOOR:
        raise AssertionError(
            f"fleet_hit_x {hit_x:.2f} under the pinned "
            f"{_HIT_X_FLOOR}x floor: the tiers added nothing over "
            "local residency"
        )
    cache.check()
    chunks_per_hit = math.ceil(_PFX_LEN / _CHUNK)
    saved_s = hits * chunks_per_hit * _CHUNK_S
    st = cache.stats()
    pb, pf = base.p99_ttft(), fc1.p99_ttft()
    return {
        "requests": int(fc1.n),
        "fleet_hit_x": round(hit_x, 2),
        "prefill_chip_s_saved": round(saved_s, 3),
        "fleet_hits": int(hits),
        "fleet_hits_by_src": {
            k: int(v) for k, v in sorted(st["fetches"].items())
        },
        "local_shared_admits": int(shared),
        "baseline_shared_admits": int(base_shared),
        "spills": int(st["spills"]),
        "evictions": int(st["evictions"]),
        "fetch_fallbacks": int(st["fallbacks"]),
        "spill_bytes": int(st["spill_bytes"]),
        "fetch_bytes": int(st["fetch_bytes"]),
        "p99_ttft_x": round(pb / pf, 2) if pf > 0 else None,
        "p99_ttft_ms": {
            "local_only": round(pb * 1e3, 1),
            "fleet_cache": round(pf * 1e3, 1),
        },
        "virtual_day_s": round(fc1.virtual_s, 1),
        "digest": fc1.digest(),
        "wall_s": round(time.perf_counter() - t0, 2),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(bench_fleet_cache_rung(), indent=2, default=str))
