"""Round-16 disaggregation rung: prefill/decode tiers + KV-page migration.

Two halves, mirroring the router rung's shape:

* **sim** (:func:`bench_disagg_rung`, unscaled — virtual-time
  bookkeeping does not track the matmul rate): a mixed long-prompt/
  short-chat diurnal day at EQUAL chip count, unified fleet vs the
  ``sweep_tier_split``-swept disaggregated split, headline
  ``disagg_decode_p99_x`` = unified decode p99 / disaggregated decode
  p99 (per-request mean inter-token gap — the tail a long-prompt burst
  wrecks; acceptance floor 1.5), plus the 4k-request two-tier day's
  bit-identity witness (two runs, one sha256 digest — the
  ``run_router_day`` contract).
* **live** (:func:`bench_disagg_live_rung`, budget-guarded): a REAL
  ``PrefillWorker -> DecodeReplica`` migration on the jitted
  schedulers (token-for-token parity asserted against the oracle, the
  end-to-end handoff wall measured) and the migration ring's transfer
  rate — payload bytes staged through ring-sized memfd frames and read
  back through a consumer mapping, reported as ``disagg_migrate_gbs``
  (the rate the PERF round-16 byte model prices migrations at).

Compact-line scalars (bench.py): ``disagg_decode_p99_x`` and
``disagg_migrate_gbs``. Format documented in benchmarks/README.md
(round-16 note).
"""

from __future__ import annotations

import gc
import os
import time

import numpy as np

_N_REPLICAS = 6
_SPLITS = [(1, 5), (2, 4), (3, 3)]


def _mixed_day_kw(n, seed):
    return dict(
        n=n, period=86_400.0, amplitude=0.8, seed=seed,
        prompt_len=64, max_new=32,
        long_share=0.15, long_prompt_len=2048, long_max_new=32,
    )


def _run_day(fleet_kind, n, seed, *, split=None, threshold=None):
    from mpistragglers_jl_tpu.models.router import RequestRouter
    from mpistragglers_jl_tpu.sim import (
        SimReplica,
        VirtualClock,
        diurnal_arrivals,
        run_router_day,
    )

    clock = VirtualClock()
    mk = dict(slots=4, n_inner=8, prompt_chunk=64, chunk_s=0.02)
    if fleet_kind == "unified":
        fleet = [
            SimReplica(clock, **mk) for _ in range(_N_REPLICAS)
        ]
        router = RequestRouter(fleet, policy="least_loaded",
                               clock=clock)
    else:
        n_p, n_d = split
        fleet = [
            SimReplica(
                clock, tier=("prefill" if i < n_p else "decode"), **mk
            )
            for i in range(n_p + n_d)
        ]
        router = RequestRouter(
            fleet, policy="two_tier", clock=clock, migrate_gbs=5.2,
            migrate_threshold_bytes=threshold,
        )
    # equal chip count, identical arrivals: one rate for every fleet
    # shape, ~0.63 of the unified fleet's short-request capacity
    rate = 0.28 * _N_REPLICAS * 4 / (5 * 0.02)
    report = run_router_day(
        router, diurnal_arrivals(rate, **_mixed_day_kw(n, seed))
    )
    return report, router


def bench_disagg_rung(requests: int | None = None):
    """The sim half (driver rung ``disagg``): swept split vs unified
    at equal chips + the bit-identity witness."""
    if requests is None:
        requests = int(os.environ.get("DISAGG_BENCH_REQUESTS", "4000"))
    from mpistragglers_jl_tpu.sim import sweep_tier_split

    # -- sweep the (n_prefill, n_decode) split + threshold offline ------
    sweep = sweep_tier_split(
        splits=_SPLITS, requests=min(1500, requests), seed=7,
        long_share=0.15, long_prompt_len=2048, load=0.7,
        chunk_s=0.02, prompt_len=64, prompt_chunk=64,
    )
    best_split, best_thr = sweep["best"]
    # -- the 4k-request day, bit-identity witness (two full runs) -------
    d1, r1 = _run_day("disagg", requests, 13, split=best_split,
                      threshold=best_thr)
    d2, _ = _run_day("disagg", requests, 13, split=best_split,
                     threshold=best_thr)
    if d1.digest() != d2.digest():
        raise AssertionError(
            f"two-tier day not bit-identical: {d1.digest()} != "
            f"{d2.digest()}"
        )
    if d1.dropped:
        raise AssertionError(f"{d1.dropped} requests dropped")
    # -- unified fleet, same chips, same arrivals -----------------------
    uni, _ = _run_day("unified", requests, 13)
    if uni.dropped:
        raise AssertionError(f"{uni.dropped} unified requests dropped")
    p99x = uni.p99_decode_itl() / d1.p99_decode_itl()
    if p99x < 1.5:
        raise AssertionError(
            f"disagg_decode_p99_x {p99x:.2f} below the 1.5 acceptance "
            f"floor (unified {uni.p99_decode_itl() * 1e3:.2f} ms vs "
            f"disagg {d1.p99_decode_itl() * 1e3:.2f} ms)"
        )
    return {
        "requests": requests,
        "swept_split": list(best_split),
        "swept_threshold_bytes": best_thr,
        "disagg_decode_p99_x": round(p99x, 2),
        "unified_decode_p99_ms": round(uni.p99_decode_itl() * 1e3, 3),
        "disagg_decode_p99_ms": round(d1.p99_decode_itl() * 1e3, 3),
        "unified_p99_ttft_s": round(uni.p99_ttft(), 3),
        "disagg_p99_ttft_s": round(d1.p99_ttft(), 3),
        "migrated": r1.n_migrated,
        "kept_local": r1.n_kept_local,
        "migrated_mb": round(r1.migrated_bytes / 1e6, 1),
        "replay_digest": d1.digest(),
        "deterministic": True,
        "digest": (
            f"x{p99x:.2f}/{best_split[0]}p{best_split[1]}d"
            f"/{r1.n_migrated}mig"
        ),
    }


def bench_disagg_live_rung():
    """The live half: one real jitted prefill->decode handoff (parity
    asserted) + the migration ring's measured transfer rate."""
    import jax.numpy as jnp

    from mpistragglers_jl_tpu.models.decode import generate_ring_dense
    from mpistragglers_jl_tpu.models.disagg import (
        DecodeReplica,
        MigrationPlanner,
        MigrationRing,
        MigrationRingReader,
        PrefillWorker,
    )
    from mpistragglers_jl_tpu.models.serving import ServingScheduler
    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab=61, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2,
        d_ff=128, attn_window=24,
    )
    params = init_params(cfg, seed=11)
    rng = np.random.default_rng(16)

    def sched():
        return ServingScheduler(
            params, cfg, slots=2, n_inner=2, prompt_chunk=8,
            max_prompt=64, page_tokens=4,
        )

    planner = MigrationPlanner()
    pw = PrefillWorker(sched(), planner=planner)
    dr = DecodeReplica(sched(), planner=planner)
    prompt = rng.integers(1, cfg.vocab, size=9).astype(np.int32)
    r = pw.submit(prompt, max_new=12)
    while not pw.ready():
        pw.step()
    t0 = time.perf_counter()
    ticket = pw.migrate_out(r)
    payload_bytes = ticket.nbytes
    dr.adopt(ticket)
    handoff_ms = (time.perf_counter() - t0) * 1e3
    dr.run()
    oracle = [
        int(t) for t in np.asarray(
            generate_ring_dense(params, jnp.asarray(prompt)[None], 12,
                                cfg)
        )[0]
    ]
    if r.tokens != oracle:
        raise AssertionError("migrated stream diverged from oracle")
    # -- ring transfer rate: bulk payload through memfd frames ----------
    ring = MigrationRing(slot_bytes=4 << 20, slots=4)
    if ring.region is None:  # pragma: no cover - no memfd
        return {
            "skipped": "memfd_create unavailable",
            "handoff_ms": round(handoff_ms, 2),
        }
    reader = MigrationRingReader(ring)
    seg = rng.integers(0, 255, size=4 << 20, dtype=np.uint8)
    moved = 0
    t0 = time.perf_counter()
    for _ in range(16):
        frames = ring.send_segment(seg)
        got = reader.read_segment(frames)
        # ONE-WAY payload bytes: the segment crosses once (staged by
        # the sender, read in place by the consumer). The router
        # prices migration delay as ticket.nbytes / (migrate_gbs*1e9)
        # — a per-payload rate — so counting stage+read here would
        # report a rate 2x what a migration actually achieves and
        # halve every modeled transfer time.
        moved += seg.nbytes
        if got[0] != seg[0] or got[-1] != seg[-1]:
            raise AssertionError("ring payload corrupted")
        ring.release_frames(frames)
        # rebinding `got` next iteration drops the view; its finalizer
        # fires on the refcount edge (no cycles), freeing the slot —
        # a gc.collect() here would bill collector wall to the ring
        del got
    wall = time.perf_counter() - t0
    gbs = moved / wall / 1e9
    gc.collect()
    stalls = ring.stalls
    ring.close()
    return {
        "handoff_ms": round(handoff_ms, 2),
        "handoff_payload_bytes": payload_bytes,
        "disagg_migrate_gbs": round(gbs, 2),
        "ring_stalls": stalls,
    }


if __name__ == "__main__":
    import json

    out = bench_disagg_rung(
        int(os.environ.get("DISAGG_BENCH_REQUESTS", "4000"))
    )
    out["live"] = bench_disagg_live_rung()
    print(json.dumps(out))
