"""Round-21 sim fast-path rung: vectorized day engine vs scalar loop.

Three legs, sim-only (unscaled in bench.py — numpy column passes do
not track the matmul rate the machine calibration measures):

* **parity** — one seeded long-decode day (the shape the fast path is
  FOR: slots=128, n_inner=1, max_new=1024, so the scalar loop scans
  128 slots on every 4 ms tick while each request retires ~1023 ticks
  after its first token) driven through BOTH engines on the identical
  :class:`~mpistragglers_jl_tpu.sim.ArrivalBatch`. The workload
  ``digest()`` must match bit for bit — the witness is the spec, so
  any divergence fails the rung before a single throughput number is
  recorded. The scalar leg's measured events/s is the denominator.
* **throughput** — the FULL 1M-request day on the vectorized engine
  (the scalar loop would need ~7 minutes for the same day; the rung
  prices it from the parity leg's identical per-event cost instead).
  ``simfast_events_x`` = vectorized events/s over scalar events/s;
  FAILS under the pinned 10x floor.
* **budget sweep** — the controller-facing claim: the SAME wall-clock
  decision budget handed to :func:`~..sim.tune.sweep_tenant_weights`
  twice (``fast="never"`` vs ``fast="auto"``, identical candidate
  order, identical seeded day per candidate) must let the fast path
  evaluate a strict superset of the scalar prefix — and because every
  candidate scores identically on either engine (digest parity), the
  deeper grid's best score is never worse. FAILS if the fast sweep
  covers no more of the grid than the scalar one, or scores worse.

Headline scalars (bench.py compact line, benchmarks/README.md):
``simfast_events_x`` (vectorized/scalar events-per-second ratio,
floor 10) and ``simfast_digest_ok`` (bit-identity witness).
"""

from __future__ import annotations

import time

# the long-decode day the fast path is for (see docs/PERF.md "Sim
# plane throughput"): 8 replicas x 128 slots, one decode token per
# 4 ms tick, 1024 new tokens per request -> the scalar loop's cost is
# ~decode_ticks per request while the vectorized engine retires slots
# analytically and skips uneventful ticks entirely
_N_REP, _SLOTS, _NI, _TICK = 8, 128, 1, 0.004
_PLEN, _MNEW, _RATE, _SEED = 96, 1024, 200.0, 3
_PARITY_N = 3_000
_FULL_N = 1_000_000
_FLOOR_X = 10.0
_SWEEP_BUDGET_S = 3.0


def _fleet():
    from mpistragglers_jl_tpu.models.router import RequestRouter
    from mpistragglers_jl_tpu.sim import SimReplica, VirtualClock

    clock = VirtualClock()
    reps = [
        SimReplica(clock, slots=_SLOTS, n_inner=_NI, tick_s=_TICK)
        for _ in range(_N_REP)
    ]
    return RequestRouter(reps, policy="least_loaded", clock=clock)


def _batch(n: int):
    from mpistragglers_jl_tpu.sim import poisson_arrival_batch

    return poisson_arrival_batch(
        _RATE, n=n, seed=_SEED, prompt_len=_PLEN, max_new=_MNEW
    )


def _sweep_grid():
    return [
        {"gold": g, "silver": s, "bronze": 1.0}
        for g in (1.0, 1.5, 2.0, 2.5, 3.0, 4.0, 6.0, 8.0, 10.0, 12.0)
        for s in (1.0, 1.5, 2.0, 3.0)
    ]


def _sweep(fast: str):
    from mpistragglers_jl_tpu.qos import TenantContract
    from mpistragglers_jl_tpu.sim.tune import sweep_tenant_weights

    contracts = [
        TenantContract("gold", cls="latency", weight=4.0, rate=900.0,
                       burst=600.0, hedges=2, ttft_slo=2.0),
        TenantContract("silver", cls="throughput", weight=2.0,
                       rate=700.0, burst=500.0),
        TenantContract("bronze", cls="batch", weight=1.0, rate=500.0,
                       burst=400.0),
    ]
    # long-decode candidate days (max_new=256): the scalar loop pays
    # ~0.7 s per candidate where the vectorized engine pays ~0.14 s,
    # so the same 3 s budget covers ~5x more of the grid
    return sweep_tenant_weights(
        contracts=contracts, candidates=_sweep_grid(), requests=2500,
        max_new=256, seed=11, fast=fast, budget_s=_SWEEP_BUDGET_S,
        timer=time.perf_counter,
    )


def bench_sim_fastpath_rung(full_n: int | None = None):
    """The driver rung ``simfast``: digest bit-identity between the
    two engines, the >= 10x events/s floor on the 1M-request day, and
    the equal-budget deeper-sweep demonstration."""
    import os

    from mpistragglers_jl_tpu.sim import (
        run_router_day,
        run_router_day_fast,
    )

    n_full = int(
        full_n if full_n is not None
        else os.environ.get("SIMFAST_BENCH_REQUESTS", str(_FULL_N))
    )
    t0 = time.perf_counter()

    # -- leg 1: parity + the scalar denominator ------------------------
    parity = _batch(_PARITY_N)
    rep_s = run_router_day(_fleet(), parity, timer=time.perf_counter)
    rep_f = run_router_day_fast(
        _fleet(), parity, timer=time.perf_counter
    )
    digest_ok = rep_s.digest() == rep_f.digest()
    if not digest_ok:
        raise AssertionError(
            f"fast path diverged from the scalar witness: "
            f"{rep_f.digest()} != {rep_s.digest()} — the digest is "
            "the spec, so this is a fast-path bug by definition"
        )
    if rep_f.fastpath != "vectorized":
        raise AssertionError(
            f"parity day fell back to the scalar loop "
            f"({rep_f.fastpath!r}): nothing was measured"
        )
    if rep_s.n_events != rep_f.n_events:
        raise AssertionError(
            f"event accounting diverged: scalar {rep_s.n_events} != "
            f"vectorized {rep_f.n_events}"
        )

    # -- leg 2: the 1M-request day on the vectorized engine ------------
    full = _batch(n_full)
    rep_full = run_router_day_fast(
        _fleet(), full, timer=time.perf_counter
    )
    if rep_full.fastpath != "vectorized":
        raise AssertionError(
            f"full day fell back ({rep_full.fastpath!r})"
        )
    if rep_full.dropped:
        raise AssertionError(
            f"full day dropped {rep_full.dropped} requests"
        )
    events_x = rep_full.events_per_s / rep_s.events_per_s
    if events_x < _FLOOR_X:
        raise AssertionError(
            f"simfast_events_x {events_x:.1f} under the pinned "
            f"{_FLOOR_X:.0f}x floor (vectorized "
            f"{rep_full.events_per_s:.0f} ev/s vs scalar "
            f"{rep_s.events_per_s:.0f} ev/s)"
        )

    # -- leg 3: same decision budget, strictly larger grid -------------
    slow = _sweep("never")
    fast = _sweep("auto")
    if fast["candidates_evaluated"] <= slow["candidates_evaluated"]:
        raise AssertionError(
            f"equal-budget sweep: fast path evaluated "
            f"{fast['candidates_evaluated']} candidates vs scalar "
            f"{slow['candidates_evaluated']} — no deeper search"
        )
    if fast["best_entry"]["score"] > slow["best_entry"]["score"]:
        raise AssertionError(
            "deeper grid scored WORSE than its scalar prefix — "
            "candidate days are seeded identically, so this cannot "
            "happen unless the engines disagree"
        )

    return {
        "requests_full_day": int(rep_full.n),
        "simfast_events_x": round(events_x, 1),
        "simfast_digest_ok": digest_ok,
        "digest": rep_s.digest(),
        "scalar_events_per_s": round(rep_s.events_per_s, 0),
        "fast_events_per_s": round(rep_full.events_per_s, 0),
        "fast_day_wall_s": round(rep_full.wall_s, 2),
        "scalar_parity_wall_s": round(rep_s.wall_s, 2),
        "n_events_full_day": int(rep_full.n_events),
        "sweep_budget_s": _SWEEP_BUDGET_S,
        "sweep_grid": len(_sweep_grid()),
        "sweep_candidates_scalar": slow["candidates_evaluated"],
        "sweep_candidates_fast": fast["candidates_evaluated"],
        "sweep_best_score_scalar": round(
            slow["best_entry"]["score"], 4
        ),
        "sweep_best_score_fast": round(
            fast["best_entry"]["score"], 4
        ),
        "sweep_best_weights_fast": fast["best_entry"]["weights"],
        "wall_s": round(time.perf_counter() - t0, 2),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(bench_sim_fastpath_rung(), indent=2, default=str))
