"""On-chip transformer train-step benchmark: tokens/s and measured-ceiling MFU.

VERDICT round 2 item 1: the model-parallel half of the framework was
correctness-tested on the virtual CPU mesh only — the Pallas flash
attention kernels (ops/flash_attention.py) had never been compiled by
Mosaic and the transformer train step had no tokens/s or MFU number.
This bench closes that gap: it jits the REAL flagship train step
(models/transformer.py ``make_train_step`` — shard_map program with
Ulysses attention calling the compiled flash kernels, custom-VJP
backward, donated-buffer SGD) on whatever chip is present, and reports

* ``tokens_per_s`` — trained tokens per second, pipelined-chain
  methodology (N steps back-to-back, ONE fence; see docs/PERF.md —
  per-step fencing on the tunneled chip times the ~110 ms RPC, not the
  framework). The one remaining fence's round trip is measured
  directly (``fence_rtt_s``) and subtracted from every chain, train
  and ceiling alike, so chain length cannot bias the comparison,
* ``mfu_vs_raw_matmul`` — model matmul FLOPs per second divided by a
  *measured* raw matmul rate of the same dtype on the same chip (never
  vendor peak), the same honest-ceiling methodology as bench.py's
  coded-GEMM metric,
* exactness — the first step's loss vs the dense oracle program on the
  same params/batch (``forward_dense`` with the materializing reference
  attention, no shard_map, no flash kernels), run on-device; reported
  as ``loss_vs_oracle_rel_err``. This is the on-chip numerics guard
  for the Mosaic flash path at full size, complementing
  tests/test_tpu_smoke.py's small-shape gradient check.

FLOP accounting counts model matmul FLOPs only (the standard MFU
convention): fwd = QKV/out-projection/MLP GEMMs + causal attention
(2*B*L^2*D per layer after halving for causality) + the tied logits
head; backward = 2x forward. The flash backward actually recomputes
scores from the saved logsumexp, so the chip executes MORE than the
counted FLOPs — reported MFU is therefore a lower bound on hardware
utilization (the convention used by the scaling literature).

The model config is the flagship single-chip size (~134 M params,
bf16): large enough that the MXU, not dispatch, dominates.

Run standalone: ``python benchmarks/transformer_train_bench.py``
(prints the JSON dict); bench.py embeds the same dict in the driver's
one-line contract.
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["bench_transformer_train", "model_flops_per_step"]


def _timed(thunk) -> float:
    t0 = time.perf_counter()
    thunk()
    return time.perf_counter() - t0


def model_flops_per_step(cfg, batch: int, seq: int) -> float:
    """Matmul FLOPs of one fwd+bwd train step (MFU convention: bwd=2x
    fwd; attention recompute NOT counted — see module docstring)."""
    B, L, D, F, V = batch, seq, cfg.d_model, cfg.d_ff, cfg.vocab
    per_layer = (
        B * L * (6 * D * D + 2 * D * D + 4 * D * F)  # qkv + wo + mlp
        + 2 * B * L * L * D  # causal attention: 4*B*L^2*D halved
    )
    fwd = cfg.n_layers * per_layer + 2 * B * L * D * V  # + tied head
    return 3.0 * fwd  # fwd + 2x fwd for backward


def bench_transformer_train(
    *,
    batch: int = 8,
    seq: int = 2048,
    steps: int = 5,
    chains: int = 3,
    d_model: int = 1024,
    n_layers: int = 8,
    n_heads: int = 8,
    d_ff: int = 4096,
    vocab: int = 32768,
    oracle: bool = True,
) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        make_train_step,
        shard_params,
    )

    cfg = TransformerConfig(
        vocab=vocab,
        d_model=d_model,
        n_heads=n_heads,
        n_layers=n_layers,
        d_ff=d_ff,
        attn="ulysses",
        attn_impl="flash",
        dtype=jnp.bfloat16,
    )
    dev = jax.devices()[0]
    mesh = Mesh(np.asarray([dev]).reshape(1, 1, 1), ("dp", "sp", "tp"))

    params = shard_params(init_params(cfg, seed=0), cfg, mesh)
    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(params)
    )
    rng = np.random.default_rng(0)
    data_sh = NamedSharding(mesh, P("dp", "sp"))
    tokens = jax.device_put(
        rng.integers(0, vocab, (batch, seq + 1), dtype=np.int32), data_sh
    )
    inp, tgt = tokens[:, :-1], tokens[:, 1:]

    step = make_train_step(cfg, mesh, lr=1e-3, donate=True)

    # dense-oracle exactness: the same params/batch through
    # forward_dense with the MATERIALIZING reference attention — no
    # shard_map, no flash kernels — must produce the same loss the
    # sharded flash program reports for its first step. Computed before
    # the first (donating) step while the initial param buffers exist.
    import dataclasses

    from mpistragglers_jl_tpu.models.transformer import forward_dense

    cfg_ref = dataclasses.replace(cfg, attn_impl="reference")

    @jax.jit
    def oracle_loss(params, inp, tgt):
        logits = forward_dense(params, inp, cfg_ref)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)
        return nll.mean()

    # oracle=False for sequence lengths where the MATERIALIZING
    # reference cannot fit (B*H*L^2 f32 scores — reference_attention
    # accumulates in float32, so L=32k is ~34 GB for the score
    # matrices alone): flash attention existing is precisely what
    # makes those lengths runnable, and their numerics are covered by
    # the shorter oracled rungs
    loss_oracle = float(oracle_loss(params, inp, tgt)) if oracle else None

    # warmup: compiles the full program (flash fwd + bwd under Mosaic,
    # shard_map collectives, donated update). Failure here IS the
    # loud signal VERDICT asked for: the non-interpret path broke.
    t0 = time.perf_counter()
    params, loss0 = step(params, inp, tgt)
    loss0 = float(loss0)
    compile_s = time.perf_counter() - t0

    # the tunnel's fixed materialization-fence round trip (~100 ms on
    # this chip, docs/PERF.md): measured directly on a tiny ready
    # buffer, then subtracted from every timed chain below so chain
    # length stops biasing the numbers (a production chip has a ~us
    # fence and the correction vanishes)
    tiny = jax.device_put(np.ones((8,), np.float32), dev)
    tiny_fence = jax.jit(jnp.sum)
    float(tiny_fence(tiny))
    rtt = min(
        _timed(lambda: float(tiny_fence(tiny))) for _ in range(5)
    )

    # pipelined chains: `steps` donated steps back-to-back, one fence
    # (fetching the final loss fences the whole chain: each step's
    # params feed the next, and loss_N depends on params_{N-1})
    chain_s = []
    for _ in range(chains):
        t0 = time.perf_counter()
        for _ in range(steps):
            params, loss = step(params, inp, tgt)
        loss = float(loss)
        chain_s.append((time.perf_counter() - t0 - rtt) / steps)
    per_step = min(chain_s)

    flops = model_flops_per_step(cfg, batch, seq)

    # measured ceiling: raw bf16 matmul on the same chip (DEFAULT
    # precision on bf16 inputs = bf16 MXU passes, the same unit the
    # model's GEMMs run at); min-of-3 fenced chains like bench.py
    mdim = 8192
    a = jax.device_put(
        rng.standard_normal((mdim, mdim)).astype(jnp.bfloat16), dev
    )
    b = jax.device_put(
        rng.standard_normal((mdim, mdim)).astype(jnp.bfloat16), dev
    )
    # the train step is ONE program per step, so the ceiling must be
    # too: dependent matmuls UNROLLED INSIDE one jit program — a
    # per-matmul dispatch loop would fold the tunnel's ~10 ms enqueue
    # cost into the denominator and report MFU > 1. The chain's single
    # fence is removed by the same measured-RTT subtraction as the
    # train chain, so chain length cancels out of the comparison.
    inner = 40

    @jax.jit
    def chain(u, v):
        for _ in range(inner):
            u = jnp.matmul(u, v)
        return u

    fence = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))
    float(fence(chain(a, b)))  # warmup
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        float(fence(chain(a, b)))
        dt = (time.perf_counter() - t0 - rtt) / inner
        best = dt if best is None else min(best, dt)
    raw_flops_s = 2.0 * mdim**3 / best

    sanity = float(loss) < float(loss0)  # training moved the loss down
    return {
        "metric": "transformer-train-step",
        "value": round(per_step, 4),
        "unit": "s",
        "tokens_per_s": round(batch * seq / per_step, 1),
        "model_tflops_per_s": round(flops / per_step / 1e12, 2),
        "mfu_vs_raw_matmul": round(flops / per_step / raw_flops_s, 3),
        "raw_bf16_tflops_per_s": round(raw_flops_s / 1e12, 1),
        "params_m": round(n_params / 1e6, 1),
        "batch": batch,
        "seq": seq,
        "attn": "ulysses+flash(pallas)",
        "dtype": "bfloat16",
        "loss_first": round(loss0, 4),
        "loss_last": round(float(loss), 4),
        "loss_decreased": bool(sanity),
        "loss_oracle": (
            round(loss_oracle, 4) if loss_oracle is not None else None
        ),
        "loss_vs_oracle_rel_err": (
            round(abs(loss0 - loss_oracle) / max(abs(loss_oracle), 1e-9), 6)
            if loss_oracle is not None else None
        ),
        "compile_s": round(compile_s, 1),
        "fence_rtt_s": round(rtt, 4),
        "steps_pipelined": steps,
        "chains_min_of": chains,
    }


if __name__ == "__main__":
    import json
    import os
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    print(json.dumps(bench_transformer_train()))
