"""On-chip transformer train-step benchmark: tokens/s and measured-ceiling MFU.

VERDICT round 2 item 1: the model-parallel half of the framework was
correctness-tested on the virtual CPU mesh only — the Pallas flash
attention kernels (ops/flash_attention.py) had never been compiled by
Mosaic and the transformer train step had no tokens/s or MFU number.
This bench closes that gap: it jits the REAL flagship train step
(models/transformer.py ``make_train_step`` — shard_map program with
Ulysses attention calling the compiled flash kernels, custom-VJP
backward, donated-buffer SGD) on whatever chip is present, and reports

* ``tokens_per_s`` — trained tokens per second, pipelined-chain
  methodology (N steps back-to-back, ONE fence; see docs/PERF.md —
  per-step fencing on the tunneled chip times the ~110 ms RPC, not the
  framework). The one remaining fence's round trip is measured
  directly (``fence_rtt_s``) and subtracted from every chain, train
  and ceiling alike, so chain length cannot bias the comparison,
* ``mfu_vs_raw_matmul`` — model matmul FLOPs per second divided by a
  *measured* raw matmul rate of the same dtype on the same chip (never
  vendor peak), the same honest-ceiling methodology as bench.py's
  coded-GEMM metric,
* exactness — the first step's loss vs the dense oracle program on the
  same params/batch (``forward_dense`` with the materializing reference
  attention, no shard_map, no flash kernels), run on-device; reported
  as ``loss_vs_oracle_rel_err``. This is the on-chip numerics guard
  for the Mosaic flash path at full size, complementing
  tests/test_tpu_smoke.py's small-shape gradient check.

FLOP accounting counts model matmul FLOPs only (the standard MFU
convention): fwd = QKV/out-projection/MLP GEMMs + causal attention
(2*B*L^2*D per layer after halving for causality) + the tied logits
head; backward = 2x forward. The flash backward actually recomputes
scores from the saved logsumexp, so the chip executes MORE than the
counted FLOPs — reported MFU is therefore a lower bound on hardware
utilization (the convention used by the scaling literature).

The model config is the flagship single-chip size (~134 M params,
bf16): large enough that the MXU, not dispatch, dominates.

Run standalone: ``python benchmarks/transformer_train_bench.py``
(prints the JSON dict); bench.py embeds the same dict in the driver's
one-line contract.
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["bench_transformer_train", "model_flops_per_step"]


def _timed(thunk) -> float:
    t0 = time.perf_counter()
    thunk()
    return time.perf_counter() - t0


def _fence_rtt(dev) -> float:
    """The tunnel's fixed materialization-fence round trip, measured on
    a tiny ready buffer (min of 5); subtracted from every timed chain
    so chain length cannot bias the numbers (docs/PERF.md)."""
    import jax
    import jax.numpy as jnp

    tiny = jax.device_put(np.ones((8,), np.float32), dev)
    tiny_fence = jax.jit(jnp.sum)
    float(tiny_fence(tiny))
    return min(_timed(lambda: float(tiny_fence(tiny))) for _ in range(5))


def _min_over_chains(run_once, fence, *, rtt, chains, repeat=1):
    """THE timing discipline for every decode-path rung: call 0 is the
    compile, calls 1..chains run ``repeat`` back-to-back invocations
    and fence ONCE (the device executes its stream in order, so
    fencing the last output fences them all — amortizing the tunnel's
    fence round trip when a single run is RTT-scale), subtract the
    measured ``rtt``, divide by ``repeat``, keep the min. Returns
    ``(best_seconds_per_run, compile_seconds, last_output)``."""
    best, comp, out = None, 0.0, None
    for i in range(chains + 1):
        t0 = time.perf_counter()
        for _ in range(1 if i == 0 else repeat):
            out = run_once()
        fence(out)
        dt = time.perf_counter() - t0
        if i == 0:
            comp = dt
        else:
            dt = (dt - rtt) / repeat
            best = dt if best is None else min(best, dt)
    return best, comp, out


def model_flops_per_step(cfg, batch: int, seq: int) -> float:
    """Matmul FLOPs of one fwd+bwd train step (MFU convention: bwd=2x
    fwd; attention recompute NOT counted — see module docstring).
    GQA narrows the K/V projections by kv_heads/n_heads; attention
    score/PV FLOPs are unchanged (every q head still attends)."""
    B, L, D, F, V = batch, seq, cfg.d_model, cfg.d_ff, cfg.vocab
    kvf = cfg.kv_heads / cfg.n_heads
    per_layer = (
        # q (2D^2) + k,v (4D^2 * kv fraction) + wo (2D^2) + mlp (4DF)
        B * L * ((4 + 4 * kvf) * D * D + 4 * D * F)
        + 2 * B * L * L * D  # causal attention: 4*B*L^2*D halved
    )
    fwd = cfg.n_layers * per_layer + 2 * B * L * D * V  # + tied head
    return 3.0 * fwd  # fwd + 2x fwd for backward


def bench_transformer_train(
    *,
    batch: int = 8,
    seq: int = 2048,
    steps: int = 5,
    chains: int = 3,
    d_model: int = 1024,
    n_layers: int = 8,
    n_heads: int = 8,
    d_ff: int = 4096,
    vocab: int = 32768,
    n_kv_heads: int | None = None,
    remat: bool = False,
    oracle: bool = True,
) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        make_train_step,
        shard_params,
    )

    cfg = TransformerConfig(
        vocab=vocab,
        d_model=d_model,
        n_heads=n_heads,
        n_kv_heads=n_kv_heads,
        n_layers=n_layers,
        d_ff=d_ff,
        attn="ulysses",
        attn_impl="flash",
        remat=remat,
        dtype=jnp.bfloat16,
    )
    dev = jax.devices()[0]
    mesh = Mesh(np.asarray([dev]).reshape(1, 1, 1), ("dp", "sp", "tp"))

    params = shard_params(init_params(cfg, seed=0), cfg, mesh)
    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(params)
    )
    rng = np.random.default_rng(0)
    data_sh = NamedSharding(mesh, P("dp", "sp"))
    tokens = jax.device_put(
        rng.integers(0, vocab, (batch, seq + 1), dtype=np.int32), data_sh
    )
    inp, tgt = tokens[:, :-1], tokens[:, 1:]

    step = make_train_step(cfg, mesh, lr=1e-3, donate=True)

    # dense-oracle exactness: the same params/batch through
    # forward_dense with the MATERIALIZING reference attention — no
    # shard_map, no flash kernels — must produce the same loss the
    # sharded flash program reports for its first step. Computed before
    # the first (donating) step while the initial param buffers exist.
    import dataclasses

    from mpistragglers_jl_tpu.models.transformer import forward_dense

    cfg_ref = dataclasses.replace(cfg, attn_impl="reference")

    @jax.jit
    def oracle_loss(params, inp, tgt):
        logits = forward_dense(params, inp, cfg_ref)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)
        return nll.mean()

    # oracle=False for sequence lengths where the MATERIALIZING
    # reference cannot fit (B*H*L^2 f32 scores — reference_attention
    # accumulates in float32, so L=32k is ~34 GB for the score
    # matrices alone): flash attention existing is precisely what
    # makes those lengths runnable, and their numerics are covered by
    # the shorter oracled rungs
    loss_oracle = float(oracle_loss(params, inp, tgt)) if oracle else None

    # warmup: compiles the full program (flash fwd + bwd under Mosaic,
    # shard_map collectives, donated update). Failure here IS the
    # loud signal VERDICT asked for: the non-interpret path broke.
    t0 = time.perf_counter()
    params, loss0 = step(params, inp, tgt)
    loss0 = float(loss0)
    compile_s = time.perf_counter() - t0

    rtt = _fence_rtt(dev)

    flops = model_flops_per_step(cfg, batch, seq)

    # measured ceiling: raw bf16 matmul on the same chip (DEFAULT
    # precision on bf16 inputs = bf16 MXU passes, the same unit the
    # model's GEMMs run at)
    mdim = 8192
    a = jax.device_put(
        rng.standard_normal((mdim, mdim)).astype(jnp.bfloat16), dev
    )
    b = jax.device_put(
        rng.standard_normal((mdim, mdim)).astype(jnp.bfloat16), dev
    )
    # the train step is ONE program per step, so the ceiling must be
    # too: dependent matmuls UNROLLED INSIDE one jit program — a
    # per-matmul dispatch loop would fold the tunnel's ~10 ms enqueue
    # cost into the denominator and report MFU > 1. The chain's single
    # fence is removed by the same measured-RTT subtraction as the
    # train chain, so chain length cancels out of the comparison.
    inner = 40

    @jax.jit
    def chain(u, v):
        for _ in range(inner):
            u = jnp.matmul(u, v)
        return u

    fence = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))
    float(fence(chain(a, b)))  # warmup (compiles the ceiling chain)

    # ALTERNATED train/ceiling chains (r5, VERDICT item 5): the chip's
    # effective rate drifts minute-to-minute through the tunnel, and a
    # ceiling measured after all the train chains can land in a faster
    # minute than any of them — which deflates the reported MFU below
    # what the hardware actually allowed the step (the r4 0.64 low
    # end). Interleaving means numerator and denominator face the same
    # conditions; min-of-chains on each side then compares
    # like-for-like. Each train chain is `steps` donated steps
    # back-to-back with ONE fence (fetching the final loss fences the
    # chain: each step's params feed the next).
    chain_s = []
    raw_best = None
    for _ in range(chains):
        t0 = time.perf_counter()
        for _ in range(steps):
            params, loss = step(params, inp, tgt)
        loss = float(loss)
        chain_s.append((time.perf_counter() - t0 - rtt) / steps)

        t0 = time.perf_counter()
        float(fence(chain(a, b)))
        dt = (time.perf_counter() - t0 - rtt) / inner
        raw_best = dt if raw_best is None else min(raw_best, dt)
    per_step = min(chain_s)
    raw_flops_s = 2.0 * mdim**3 / raw_best

    sanity = float(loss) < float(loss0)  # training moved the loss down
    return {
        "metric": "transformer-train-step",
        "value": round(per_step, 4),
        "unit": "s",
        "tokens_per_s": round(batch * seq / per_step, 1),
        "model_tflops_per_s": round(flops / per_step / 1e12, 2),
        "mfu_vs_raw_matmul": round(flops / per_step / raw_flops_s, 3),
        "raw_bf16_tflops_per_s": round(raw_flops_s / 1e12, 1),
        "params_m": round(n_params / 1e6, 1),
        "batch": batch,
        "seq": seq,
        "attn": "ulysses+flash(pallas)",
        "dtype": "bfloat16",
        "loss_first": round(loss0, 4),
        "loss_last": round(float(loss), 4),
        "loss_decreased": bool(sanity),
        "loss_oracle": (
            round(loss_oracle, 4) if loss_oracle is not None else None
        ),
        "loss_vs_oracle_rel_err": (
            round(abs(loss0 - loss_oracle) / max(abs(loss_oracle), 1e-9), 6)
            if loss_oracle is not None else None
        ),
        "compile_s": round(compile_s, 1),
        "fence_rtt_s": round(rtt, 4),
        "steps_pipelined": steps,
        "chains_min_of": chains,
    }


def bench_decode(
    *,
    prompt_len: int = 16384,
    n_new: int = 128,
    batch: int = 1,
    d_model: int = 1024,
    n_layers: int = 8,
    n_heads: int = 8,
    n_kv_heads: int | None = 2,
    d_ff: int = 4096,
    vocab: int = 32768,
    chains: int = 2,
    slope_steps: int = 384,
) -> dict:
    """Serving rung (VERDICT r3 missing #2's perf half): long-context
    prefill + greedy KV-cache decode on the chip.

    The whole generation (flash prefill + ``n_new`` cached decode
    steps) runs as ONE jitted program (models/decode.make_generate —
    a lax.scan, zero host round trips between tokens); prefill is also
    timed alone so the per-decoded-token cost is attributable. GQA
    (default kv_heads=2) makes the cache 4x narrower than MHA — the
    serving win the decode path exists for; equivalence to the
    training forward is pinned by tests/test_decode.py."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mpistragglers_jl_tpu.models.decode import (
        init_cache,
        make_generate,
        make_prefill,
        shard_cache,
    )
    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        shard_params,
    )

    cfg = TransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=n_heads,
        n_kv_heads=n_kv_heads, n_layers=n_layers, d_ff=d_ff,
        attn="ulysses", attn_impl="flash", dtype=jnp.bfloat16,
    )
    dev = jax.devices()[0]
    mesh = Mesh(np.asarray([dev]).reshape(1, 1), ("dp", "tp"))
    params = shard_params(init_params(cfg, seed=0), cfg, mesh)
    rng = np.random.default_rng(0)
    prompt = jax.device_put(
        rng.integers(0, vocab, (batch, prompt_len), dtype=np.int32),
        NamedSharding(mesh, P("dp", None)),
    )

    rtt = _fence_rtt(dev)
    compile_s = 0.0

    # prefill alone (cache fill + last-position logits). The zeroed
    # cache is built ONCE, outside the timer: make_prefill does not
    # donate, so every call may reuse it, and timing the ~cache-size
    # host->device transfer would measure the tunnel, not prefill
    prefill = make_prefill(cfg, mesh)
    cache0 = shard_cache(
        init_cache(cfg, batch, prompt_len + n_new, mesh), cfg, mesh
    )
    best_p, c, _ = _min_over_chains(
        lambda: prefill(params, prompt, cache0)[0],
        lambda lg: float(jnp.sum(lg.astype(jnp.float32))),
        rtt=rtt, chains=chains,
    )
    compile_s += c

    # decode cost by SLOPE: total(n2) - total(n1) over n2-n1 extra
    # steps. Differencing ~100 ms totals against a ~100 ms tunnel RTT
    # (the old prefill-subtraction attribution) is noise at +-40 ms —
    # it once printed a ring decode "faster" than the weight-read
    # floor; the slope over a large step delta is the honest number.
    n1 = n_new

    def slope_ms(quantize_kv):
        nonlocal compile_s
        totals = {}
        for nn in (n1, n1 + slope_steps):
            gen = make_generate(
                cfg, mesh, n_new=nn, quantize_kv=quantize_kv
            )
            t, c, _ = _min_over_chains(
                lambda: gen(params, prompt), np.asarray,
                rtt=rtt, chains=chains,
            )
            compile_s += c
            totals[nn] = t
        per = (totals[n1 + slope_steps] - totals[n1]) / slope_steps
        return per * 1e3, totals[n1]

    decode_ms, best_g = slope_ms(False)
    # B=1 int8 under the AUTO default routes the einsum dequant path
    # (below KERNEL_MIN_BATCH — the scan boundary cost isn't amortized)
    decode_q8_ms, _ = slope_ms(True)
    # third variant: the Pallas int8 decode kernel FORCED at B=1 —
    # kept measured so the boundary-cost attribution stays a number,
    # not folklore (batched routing is where the kernel wins; see
    # decode_kernel_attrib.py and the serving rung)
    from mpistragglers_jl_tpu.models.decode import use_decode_kernel

    use_decode_kernel(True)
    try:
        decode_q8k_ms, _ = slope_ms(True)
    except Exception as e:  # never let the experiment kill the rung
        decode_q8k_ms = None
        print(f"int8 kernel variant failed: {e!r}", flush=True)
    finally:
        use_decode_kernel(None)  # restore the batched-AUTO default

    Hkv = cfg.kv_heads
    cache_mb = (
        2 * n_layers * batch * (prompt_len + n_new) * Hkv
        * cfg.head_dim * 2 / 2**20
    )
    # int8: 1 byte/elem + one f32 scale per head_dim row, vs 2 (bf16)
    cache_q8_mb = cache_mb * (1 + 4 / cfg.head_dim) / 2
    return {
        "metric": "decode-rung",
        "prompt_len": prompt_len,
        "n_new": n_new,
        "batch": batch,
        "n_kv_heads": Hkv,
        "kv_cache_mib": round(cache_mb, 1),
        "kv_cache_vs_mha": round(Hkv / n_heads, 3),
        "prefill_s": round(best_p, 4),
        "prefill_tokens_per_s": round(batch * prompt_len / best_p, 1),
        "generate_total_s": round(best_g, 4),
        "decode_ms_per_token": round(decode_ms, 3),
        "decode_tokens_per_s": round(batch * 1e3 / decode_ms, 1),
        "kv_cache_mib_int8": round(cache_q8_mb, 1),
        "decode_ms_per_token_int8": round(decode_q8_ms, 3),
        "int8_decode_speedup": round(decode_ms / decode_q8_ms, 2),
        "decode_ms_per_token_int8_kernel": (
            round(decode_q8k_ms, 3) if decode_q8k_ms else None
        ),
        "decode_slope_steps": slope_steps,
        "compile_s": round(compile_s, 1),
        "fence_rtt_s": round(rtt, 4),
        "chains_min_of": chains,
    }


def bench_window_decode(
    *,
    prompt_len: int = 16384,
    window: int = 1024,
    n_new: int = 128,
    batch: int = 1,
    d_model: int = 1024,
    n_layers: int = 8,
    n_heads: int = 8,
    n_kv_heads: int | None = 2,
    d_ff: int = 4096,
    vocab: int = 32768,
    chains: int = 2,
    slope_steps: int = 384,
) -> dict:
    """Sliding-window serving rung: the O(W) ring cache vs the masked
    ``max_len`` cache, same window semantics (round 4).

    Both run the flagship shape with ``attn_window=window`` as ONE
    jitted generation program; the masked path scores all
    ``prompt_len + n_new`` cache positions per decode step (band-masked
    to W), the ring path stores and scores W slots. At W << prompt_len
    the decode step is cache-bandwidth-bound, so the ring's read
    reduction (~prompt_len/W) is the structural win being priced here;
    token-for-token equality of the two paths is pinned by
    tests/test_window_attention.py."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mpistragglers_jl_tpu.models.decode import (
        init_cache,
        make_generate,
        make_prefill,
        make_ring_generate,
        shard_cache,
    )
    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        shard_params,
    )

    cfg = TransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=n_heads,
        n_kv_heads=n_kv_heads, n_layers=n_layers, d_ff=d_ff,
        attn="ulysses", attn_impl="flash", dtype=jnp.bfloat16,
        attn_window=window,
    )
    dev = jax.devices()[0]
    mesh = Mesh(np.asarray([dev]).reshape(1, 1), ("dp", "tp"))
    params = shard_params(init_params(cfg, seed=0), cfg, mesh)
    rng = np.random.default_rng(0)
    prompt = jax.device_put(
        rng.integers(0, vocab, (batch, prompt_len), dtype=np.int32),
        NamedSharding(mesh, P("dp", None)),
    )
    rtt = _fence_rtt(dev)

    # prefill alone (shared cost: both generators prefill identically
    # through the windowed flash chunk kernel); cache built outside
    # the timer, reused every call (make_prefill does not donate)
    prefill = make_prefill(cfg, mesh)
    cache0 = shard_cache(
        init_cache(cfg, batch, prompt_len + n_new, mesh), cfg, mesh
    )
    compile_s = 0.0
    best_p, c, _ = _min_over_chains(
        lambda: prefill(params, prompt, cache0)[0],
        lambda lg: float(jnp.sum(lg.astype(jnp.float32))),
        rtt=rtt, chains=chains,
    )
    compile_s += c

    # decode cost by SLOPE over a large step delta (see bench_decode:
    # differencing RTT-scale totals is +-40 ms noise; it once printed
    # a ring decode below the weight-read floor)
    def slope_ms(maker):
        nonlocal compile_s
        totals = {}
        for nn in (n_new, n_new + slope_steps):
            gen = maker(cfg, mesh, n_new=nn)
            t, c, _ = _min_over_chains(
                lambda: gen(params, prompt), np.asarray,
                rtt=rtt, chains=chains,
            )
            compile_s += c
            totals[nn] = t
        return (totals[n_new + slope_steps] - totals[n_new]) \
            / slope_steps * 1e3

    masked_ms = slope_ms(make_generate)
    ring_ms = slope_ms(make_ring_generate)
    Hkv = cfg.kv_heads
    bytes_per_pos = 2 * n_layers * batch * Hkv * cfg.head_dim * 2
    return {
        "metric": "window-decode-rung",
        "prompt_len": prompt_len,
        "attn_window": window,
        "n_new": n_new,
        "n_kv_heads": Hkv,
        "kv_cache_mib_masked": round(
            bytes_per_pos * (prompt_len + n_new) / 2**20, 1
        ),
        "kv_cache_mib_ring": round(bytes_per_pos * window / 2**20, 1),
        "prefill_s": round(best_p, 4),
        "decode_ms_per_token_masked": round(masked_ms, 3),
        "decode_ms_per_token_ring": round(ring_ms, 3),
        "ring_speedup": round(masked_ms / ring_ms, 2),
        "decode_tokens_per_s_ring": round(batch * 1e3 / ring_ms, 1),
        "decode_slope_steps": slope_steps,
        "compile_s": round(compile_s, 1),
        "fence_rtt_s": round(rtt, 4),
        "chains_min_of": chains,
    }


def bench_spec_decode(
    *,
    prompt_len: int = 2048,
    n_new: int = 256,
    k: int = 4,
    d_model: int = 1024,
    n_layers: int = 8,
    n_heads: int = 8,
    n_kv_heads: int | None = 2,
    d_ff: int = 4096,
    vocab: int = 32768,
    chains: int = 2,
    draft_layers: int = 2,
) -> dict:
    """Speculative-decoding rung: BOTH drafters (n-gram lookup and the
    truncated-layer model draft) behind the one-forward verify vs plain
    greedy, SAME dense program family, SAME output stream (the
    exactness contract — tests/test_speculative.py). What varies is
    forwards per token: `tokens_per_forward` is the measured acceptance
    economy on this model's own (loop-prone) greedy continuation of a
    random prompt — honest for an untrained checkpoint, and the
    interesting number alongside the wall-clock ratio (each verify
    forward is k+1 tokens wide, so FLOPs per forward rise while cache
    reads per token fall). The model-draft sub-rung reports the same
    numbers for ``draft_layers`` of the checkpoint's own layers used as
    the drafter — on an UNTRAINED checkpoint its acceptance rides the
    near-identity residual stream at init, so treat it as mechanism
    proof, not a quality claim (a trained draft is where it wins on
    non-self-predictable streams)."""
    import jax
    import jax.numpy as jnp

    from mpistragglers_jl_tpu.models.decode import _dense_runner
    from mpistragglers_jl_tpu.models.speculative import (
        make_speculative_dense,
    )
    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=n_heads,
        n_kv_heads=n_kv_heads, n_layers=n_layers, d_ff=d_ff,
        attn="ulysses", attn_impl="flash", dtype=jnp.bfloat16,
    )
    dev = jax.devices()[0]
    params = jax.device_put(init_params(cfg, seed=0), dev)
    rng = np.random.default_rng(0)
    prompt = jax.device_put(
        jnp.asarray(
            rng.integers(0, vocab, (1, prompt_len), dtype=np.int32)
        ),
        dev,
    )
    rtt = _fence_rtt(dev)

    # generation totals here are within ~1 tunnel RTT of the RTT
    # itself, so a single fenced call is subtraction-fragile (an RTT
    # drift of 30 ms flips the ratio) — chain R=4 generations per
    # fence (_min_over_chains repeat)
    R = 4
    compile_s = 0.0
    greedy = _dense_runner(
        cfg, 1, prompt_len, n_new, prompt_len + n_new, 0.0, None, None,
        False,
    )
    key = jax.random.key(0)  # unused at temperature 0
    best_g, c, toks_g = _min_over_chains(
        lambda: greedy(params, prompt, key), np.asarray,
        rtt=rtt, chains=chains, repeat=R,
    )
    compile_s += c
    n_dec = max(n_new - 1, 1)

    def measure(dl):
        nonlocal compile_s
        spec = make_speculative_dense(
            cfg, prompt_len, n_new, k, draft_layers=dl
        )
        best_s, c, packed = _min_over_chains(
            lambda: spec(params, prompt), np.asarray,
            rtt=rtt, chains=chains, repeat=R,
        )
        compile_s += c
        packed = np.asarray(packed)
        toks_s, n_fwd = packed[:n_new], int(packed[n_new])
        return {
            "stream_exact_vs_greedy": bool(
                np.array_equal(np.asarray(toks_g)[0], toks_s)
            ),
            "verify_forwards": int(n_fwd),
            "tokens_per_forward": round(n_dec / max(n_fwd, 1), 2),
            "spec_total_s": round(best_s, 4),
            "spec_speedup": round(best_g / best_s, 2),
        }

    ngram = measure(None)
    model = measure(draft_layers)
    return {
        "metric": "spec-decode-rung",
        "prompt_len": prompt_len,
        "n_new": n_new,
        "draft_k": k,
        "greedy_total_s": round(best_g, 4),
        # top-level fields mirror the n-gram drafter (the default and
        # the round-4 contract keys); model_draft is the round-5
        # truncated-layer sub-rung
        **ngram,
        "model_draft": {"draft_layers": draft_layers, **model},
        "generations_per_fence": R,
        "compile_s": round(compile_s, 1),
        "fence_rtt_s": round(rtt, 4),
        "chains_min_of": chains,
    }


if __name__ == "__main__":
    import json
    import os
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if "--decode" in sys.argv:
        print(json.dumps(bench_decode()))
    elif "--window-decode" in sys.argv:
        print(json.dumps(bench_window_decode()))
    elif "--spec-decode" in sys.argv:
        print(json.dumps(bench_spec_decode()))
    else:
        print(json.dumps(bench_transformer_train()))
