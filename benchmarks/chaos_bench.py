"""Round-20 chaos rung: the storm-with-host-kill episode as a gate.

One leg, sim-only (unscaled in bench.py — virtual-time bookkeeping
does not track the matmul rate): the ``storm_with_host_kill``
scenario from the chaos catalog — a retry-storm day (timeout-and-
resubmit clients on a seeded coin) with ONE correlated host-group
kill (two replicas die together mid-day, then revive) and a 30%-span
router<->replica partition over another two, on the two-class tenant
mix — driven TWICE through :class:`~mpistragglers_jl_tpu.chaos.
ChaosInjector` with every pinned invariant armed inside the run.

Gates (any failure raises — the rung IS the contract):

* **drops only by name** — zero dropped requests, and 100% of shed
  requests carry a reason (``chaos_shed_named_pct``), batch-class
  shed before interactive per the QoS sheddability contract;
* **bounded queue** — the probes' peak fleet depth stays at or under
  the scenario's pinned ceiling (128);
* **non-metastable** — post-storm windowed p99 TTFT within the
  scenario's pinned factor of the pre-storm baseline
  (``chaos_p99_recovery_x``);
* **bit-identity** — the two runs' ChaosReport digests are equal
  (the replay witness; partitions reconciled with no request
  double-retired is checked inside the scenario's own battery).

Headline scalars (bench.py compact line, format in
benchmarks/README.md round-20 note): ``chaos_shed_named_pct`` and
``chaos_p99_recovery_x``.
"""

from __future__ import annotations

import time

_CEILING = 128  # the scenario's pinned hard queue ceiling


def bench_chaos_rung(requests: int | None = None):
    """The driver rung ``chaos``: two replays of the combo episode
    with the invariant battery armed, gated as the module docstring
    states."""
    import os

    from mpistragglers_jl_tpu.chaos import ChaosInjector, get_scenario

    n = int(
        requests if requests is not None
        else os.environ.get("CHAOS_BENCH_REQUESTS", "5000")
    )
    seed = 20
    t0 = time.perf_counter()
    inj = ChaosInjector()
    r1 = inj.run(get_scenario("storm_with_host_kill", seed=seed, n=n))
    r2 = inj.run(get_scenario("storm_with_host_kill", seed=seed, n=n))
    if r1.digest() != r2.digest():
        raise AssertionError(
            f"chaos episode not bit-identical: {r1.digest()} != "
            f"{r2.digest()}"
        )
    if r1.dropped:
        raise AssertionError(
            f"{r1.dropped} requests dropped: shed is the only "
            "sanctioned loss, and it is named"
        )
    if r1.shed_named_pct < 100.0:
        raise AssertionError(
            f"chaos_shed_named_pct {r1.shed_named_pct:.1f} < 100: "
            "a bare drop slipped through the shed door"
        )
    if r1.max_queue_depth > _CEILING:
        raise AssertionError(
            f"peak queue depth {r1.max_queue_depth} over the pinned "
            f"{_CEILING} ceiling"
        )
    rec = float(r1.extras["p99_recovery_x"])
    return {
        "requests": int(r1.n_requests),
        "chaos_shed_named_pct": round(r1.shed_named_pct, 1),
        "chaos_p99_recovery_x": round(rec, 3),
        "resubmits": int(r1.n_resubmits),
        "shed": dict(r1.shed_reasons),
        "partitions": int(r1.n_partitions),
        "stale_cancelled": int(r1.n_stale_cancelled),
        "max_queue_depth": int(r1.max_queue_depth),
        "invariants": list(r1.invariants),
        "digest": r1.digest(),
        "wall_s": round(time.perf_counter() - t0, 2),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(bench_chaos_rung(), indent=2, default=str))
