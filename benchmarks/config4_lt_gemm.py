"""BASELINE config 4: LT/rateless-coded GEMM 16384^2, 16 workers.

The pool returns on the *variable* decodability predicate
(``nwait(epoch, repochs)``, ops/lt.py) — not at a fixed count but at the
first arrival set whose shards peel. Two injected stragglers never make
the epoch; decode runs on device over the arrived shards
(``LTCodedGemm.result_device``). A and B are generated on device
(jax.random), so the ~1 GB operands never cross the host<->device edge;
``vs_baseline`` is the straggler-mitigation factor: the same epoch
forced to wait for all 16 workers over the predicate epoch.
"""

from __future__ import annotations

import json
import os
import sys
import time


sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from mpistragglers_jl_tpu import AsyncPool, asyncmap, waitall
from mpistragglers_jl_tpu.ops import LTCodedGemm

M = KDIM = NCOLS = 16384
N_WORKERS = 16
K = 8
STRAGGLERS = (3, 11)
DELAY_S = 5.0
EPOCHS = 3


def _run_chained(A, B, precision, C_ref, ref_scale, fence, maxabs, *,
                 n_workers=N_WORKERS, k=K, delay_s=DELAY_S,
                 epochs=EPOCHS, stragglers=STRAGGLERS, chains=3):
    """One precision rung: chained epochs, one fence, min of ``chains``.
    Returns (t_coded, err, fresh_counts, rtt, t_all)."""
    import numpy as np

    delay_fn = lambda i, e: delay_s if i in stragglers else 0.0
    lt = LTCodedGemm(
        A, n_workers, k,
        delay_fn=delay_fn,
        precision=precision,
    )
    pool = AsyncPool(n_workers)
    try:
        asyncmap(pool, B, lt.backend, nwait=lt.nwait)  # warmup
        float(fence(lt.result_device(pool)))
        waitall(pool, lt.backend, timeout=3 * delay_s + 10)

        z = jax.device_put(np.ones(8, np.float32), lt.devices[0])
        float(fence(z))
        rtts = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(fence(z))
            rtts.append(time.perf_counter() - t0)
        rtt = min(rtts)

        chain_s, fresh_counts = [], []
        for _ in range(chains):
            t0 = time.perf_counter()
            for _ in range(epochs):
                repochs = asyncmap(pool, B, lt.backend, nwait=lt.nwait)
                fresh_counts.append(int((repochs == pool.epoch).sum()))
                C = lt.result_device(pool)
            float(fence(C))  # in-order device stream: covers every epoch
            chain_s.append((time.perf_counter() - t0 - rtt) / epochs)
        t_coded = min(chain_s)
        err = float(maxabs(C, C_ref)) / ref_scale
        waitall(pool, lt.backend, timeout=3 * delay_s + 10)

        # baseline: bulk-synchronous epoch, pays the injected stragglers
        t0 = time.perf_counter()
        asyncmap(pool, B, lt.backend, nwait=n_workers)
        C_all = lt.result_device(pool)
        float(fence(C_all))
        t_all = time.perf_counter() - t0
        return t_coded, err, fresh_counts, rtt, t_all
    finally:
        lt.backend.shutdown()


def bench_rung(m=8192, n_workers=16, k=8, delay_s=1.0, epochs=2,
               chains=2):
    """Scaled config-4 rung for bench.py's JSON contract: half-size
    operands and 1 s stragglers bound the runtime (the full-size CLI
    below is the comparable-to-BASELINE run). Same machinery: variable
    decodability nwait, chained epochs, one fence, straggler-mitigation
    factor vs the bulk-synchronous epoch."""
    key = jax.random.key(0)
    ka, kb = jax.random.split(key)
    A = jax.random.normal(ka, (m, m), jnp.float32)
    B = jax.random.normal(kb, (m, m), jnp.float32)
    fence = jax.jit(jnp.sum)
    maxabs = jax.jit(lambda c, r: jnp.max(jnp.abs(c - r)))
    C_ref = jax.jit(
        lambda a, b: jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
    )(A, B)
    ref_scale = float(jnp.max(jnp.abs(C_ref)))
    stragglers = (3, 11) if n_workers > 11 else (1,)
    t_coded, err, fresh_counts, rtt, t_all = _run_chained(
        A, B, jax.lax.Precision.HIGHEST, C_ref, ref_scale, fence, maxabs,
        n_workers=n_workers, k=k, delay_s=delay_s, epochs=epochs,
        stragglers=stragglers, chains=chains,
    )
    return {
        "metric": f"lt-coded-gemm-{m}-{n_workers}w-scaled",
        "value": round(t_coded, 4),
        "unit": "s",
        "vs_nwait_all": round(t_all / t_coded, 2),
        "decode_rel_err": err,
        "fresh_at_return": fresh_counts,
        "gflops_per_chip": round(2.0 * m**3 / t_coded / 1e9, 1),
        "injected_straggler_delay_s": delay_s,
        "epochs_pipelined": epochs,
        "chains_min_of": chains,
        "fence_rtt_s": round(rtt, 4),
    }


def main():
    key = jax.random.key(0)
    ka, kb = jax.random.split(key)
    A = jax.random.normal(ka, (M, KDIM), jnp.float32)
    B = jax.random.normal(kb, (KDIM, NCOLS), jnp.float32)

    fence = jax.jit(jnp.sum)
    maxabs = jax.jit(lambda c, r: jnp.max(jnp.abs(c - r)))

    # on-device oracle for the exactness check
    C_ref = jax.jit(
        lambda a, b: jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
    )(A, B)
    ref_scale = float(jnp.max(jnp.abs(C_ref)))

    t_coded, err, fresh_counts, rtt, t_all = _run_chained(
        A, B, jax.lax.Precision.HIGHEST, C_ref, ref_scale, fence, maxabs
    )
    # DEFAULT-precision rung: same epochs, same f32 decode — decode
    # success is unchanged, the worker matmuls ride the fast passes
    t_def, err_def, _, _, _ = _run_chained(
        A, B, None, C_ref, ref_scale, fence, maxabs
    )

    print(json.dumps({
        "metric": "lt-coded-gemm-16384-16w-wallclock",
        "value": round(t_coded, 4),
        "unit": "s",
        "vs_baseline": round(t_all / t_coded, 2),
        "nwait_all_epoch_s": round(t_all, 4),
        "decode_success": True,
        "fresh_at_return": fresh_counts,
        "decode_rel_err": err,
        "gflops_per_chip": round(2.0 * M * KDIM * NCOLS / t_coded / 1e9, 1),
        "injected_straggler_delay_s": DELAY_S,
        "epochs_pipelined": EPOCHS,
        "chains_min_of": 3,
        "fence_rtt_s": round(rtt, 4),
        "default_precision_rung": {
            "value": round(t_def, 4),
            "gflops_per_chip": round(
                2.0 * M * KDIM * NCOLS / t_def / 1e9, 1
            ),
            "decode_rel_err": err_def,
        },
    }))

def main_rateless():
    """Incremental redundancy under a PERMANENT straggler: the static
    window cannot decode (its shard never arrives), the rateless stream
    draws generation-1 shards from the live workers and decodes anyway.
    Reports the shards-consumed-vs-k overhead — the price of
    ratelessness (VERDICT round 1 item 2's measured contract)."""
    import numpy as np

    from mpistragglers_jl_tpu.ops.rateless import RatelessLTGemm

    m = kdim = ncols = 8192
    n, k = 12, 8
    rng = np.random.default_rng(0)
    A = rng.standard_normal((m, kdim)).astype(np.float32)
    B = rng.standard_normal((kdim, ncols)).astype(np.float32)
    dead = 0  # permanent straggler: never returns within any round

    # seed 16 + systematic=False: worker 0's CLASSIC-stream shard is
    # load-bearing — the static window minus it does NOT peel, so
    # decode REQUIRES generation-1 draws (the systematic default would
    # peel this trace within generation 0 and demonstrate nothing; its
    # overhead win is measured by bench.py's rateless_overhead rung)
    rg = RatelessLTGemm(
        A, n, k, seed=16, systematic=False,
        delay_fn=lambda i, e: 3600.0 if i == dead else 0.0,
        precision=jax.lax.Precision.HIGHEST,
    )
    try:
        pool = AsyncPool(n)
        # warmup: compile the worker matmul once, untimed, reusing the
        # full B so the timed shapes match. Fresh-generation draws may
        # still compile the (tiny) device encode once per new support
        # degree inside the timed run — noted in the output.
        import jax.numpy as jnp_

        from mpistragglers_jl_tpu.backends.base import WorkerError

        # B goes device-resident FIRST: a host payload would re-ride
        # the ~26 MB/s tunnel H2D edge (256 MB ~ 10 s) inside every
        # round and can blow the round timeout outright (observed
        # round 3); HBM residency is the coordinator working-memory
        # discipline every other config follows
        B_dev = jax.device_put(jnp_.asarray(B), jax.devices()[0])
        # classic streams build the device source stack on the first
        # fresh-generation draw — a full A upload; pull it off the
        # clock (and out of the round timeouts) like every other
        # one-time setup cost
        rg.prefetch_source()
        rg.backend.dispatch(1, B_dev, 0)
        warm = rg.backend.wait(1, timeout=600)
        if warm is None or isinstance(warm, WorkerError):
            raise RuntimeError(f"warmup failed: {warm!r}")
        t0 = time.perf_counter()
        C = rg.multiply(B_dev, pool, round_timeout=60.0, max_rounds=4)
        wall = time.perf_counter() - t0
        err = float(np.max(np.abs(C - A @ B))) / float(np.max(np.abs(C)))
        print(json.dumps({
            "metric": "lt-rateless-gemm-8192-permanent-straggler",
            "value": round(wall, 4),
            "unit": "s",
            "decode_success": bool(err < 1e-3),
            "decode_rel_err": err,
            "shards_used": rg.stats["shards_used"],
            "k": rg.stats["k"],
            "rateless_overhead": round(rg.stats["overhead"], 3),
            "max_generation": rg.stats["max_generation"],
            "note": "worker 0's shard is load-bearing and never "
            "arrives; decode required fresh-generation draws. Wall "
            "includes one 15 s round_timeout wait per extra round, the "
            "host-peel D2H of all collected shards, and a one-time "
            "device-encode compile per new support degree",
        }))
    finally:
        rg.backend.shutdown()


if __name__ == "__main__":
    main()
    main_rateless()
