"""BASELINE config 4: LT/rateless-coded GEMM 16384^2, 16 workers.

The pool returns on the *variable* decodability predicate
(``nwait(epoch, repochs)``, ops/lt.py) — not at a fixed count but at the
first arrival set whose shards peel. Two injected stragglers never make
the epoch; decode runs on device over the arrived shards
(``LTCodedGemm.result_device``). A and B are generated on device
(jax.random), so the ~1 GB operands never cross the host<->device edge;
``vs_baseline`` is the straggler-mitigation factor: the same epoch
forced to wait for all 16 workers over the predicate epoch.
"""

from __future__ import annotations

import json
import os
import sys
import time


sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from mpistragglers_jl_tpu import AsyncPool, asyncmap, waitall
from mpistragglers_jl_tpu.ops import LTCodedGemm

M = KDIM = NCOLS = 16384
N_WORKERS = 16
K = 8
STRAGGLERS = (3, 11)
DELAY_S = 5.0
EPOCHS = 3


def main():
    key = jax.random.key(0)
    ka, kb = jax.random.split(key)
    A = jax.random.normal(ka, (M, KDIM), jnp.float32)
    B = jax.random.normal(kb, (KDIM, NCOLS), jnp.float32)

    delay_fn = lambda i, e: DELAY_S if i in STRAGGLERS else 0.0
    lt = LTCodedGemm(
        A, N_WORKERS, K,
        delay_fn=delay_fn,
        precision=jax.lax.Precision.HIGHEST,
    )
    fence = jax.jit(jnp.sum)
    maxabs = jax.jit(lambda c, r: jnp.max(jnp.abs(c - r)))

    # on-device oracle for the exactness check
    C_ref = jax.jit(
        lambda a, b: jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
    )(A, B)
    ref_scale = float(jnp.max(jnp.abs(C_ref)))

    pool = AsyncPool(N_WORKERS)
    # warmup epoch: compiles + decode + fence (all workers, untimed)
    asyncmap(pool, B, lt.backend, nwait=lt.nwait)
    float(fence(lt.result_device(pool)))
    waitall(pool, lt.backend)

    times, fresh_counts = [], []
    for _ in range(EPOCHS):
        t0 = time.perf_counter()
        repochs = asyncmap(pool, B, lt.backend, nwait=lt.nwait)
        fresh_counts.append(int((repochs == pool.epoch).sum()))
        C = lt.result_device(pool)
        float(fence(C))
        times.append(time.perf_counter() - t0)
        waitall(pool, lt.backend)
    t_coded = min(times)
    err = float(maxabs(C, C_ref)) / ref_scale

    # baseline: bulk-synchronous epoch, pays the injected stragglers
    t0 = time.perf_counter()
    asyncmap(pool, B, lt.backend, nwait=N_WORKERS)
    C_all = lt.result_device(pool)
    float(fence(C_all))
    t_all = time.perf_counter() - t0
    lt.backend.shutdown()

    print(json.dumps({
        "metric": "lt-coded-gemm-16384-16w-wallclock",
        "value": round(t_coded, 4),
        "unit": "s",
        "vs_baseline": round(t_all / t_coded, 2),
        "nwait_all_epoch_s": round(t_all, 4),
        "decode_success": True,
        "fresh_at_return": fresh_counts,
        "decode_rel_err": err,
        "gflops_per_chip": round(2.0 * M * KDIM * NCOLS / t_coded / 1e9, 1),
        "injected_straggler_delay_s": DELAY_S,
    }))


if __name__ == "__main__":
    main()
