"""BASELINE config 4: LT/rateless-coded GEMM 16384^2, 16 workers.

The pool returns on the *variable* decodability predicate
(``nwait(epoch, repochs)``, ops/lt.py) — not at a fixed count but at the
first arrival set whose shards peel. Two injected stragglers never make
the epoch; decode runs on device over the arrived shards
(``LTCodedGemm.result_device``). A and B are generated on device
(jax.random), so the ~1 GB operands never cross the host<->device edge;
``vs_baseline`` is the straggler-mitigation factor: the same epoch
forced to wait for all 16 workers over the predicate epoch.
"""

from __future__ import annotations

import json
import os
import sys
import time


sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from mpistragglers_jl_tpu import AsyncPool, asyncmap, waitall
from mpistragglers_jl_tpu.ops import LTCodedGemm

M = KDIM = NCOLS = 16384
N_WORKERS = 16
K = 8
STRAGGLERS = (3, 11)
DELAY_S = 5.0
EPOCHS = 3


def main():
    key = jax.random.key(0)
    ka, kb = jax.random.split(key)
    A = jax.random.normal(ka, (M, KDIM), jnp.float32)
    B = jax.random.normal(kb, (KDIM, NCOLS), jnp.float32)

    delay_fn = lambda i, e: DELAY_S if i in STRAGGLERS else 0.0
    lt = LTCodedGemm(
        A, N_WORKERS, K,
        delay_fn=delay_fn,
        precision=jax.lax.Precision.HIGHEST,
    )
    fence = jax.jit(jnp.sum)
    maxabs = jax.jit(lambda c, r: jnp.max(jnp.abs(c - r)))

    # on-device oracle for the exactness check
    C_ref = jax.jit(
        lambda a, b: jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
    )(A, B)
    ref_scale = float(jnp.max(jnp.abs(C_ref)))

    pool = AsyncPool(N_WORKERS)
    # warmup epoch: compiles + decode + fence (all workers, untimed)
    asyncmap(pool, B, lt.backend, nwait=lt.nwait)
    float(fence(lt.result_device(pool)))
    waitall(pool, lt.backend)

    times, fresh_counts = [], []
    for _ in range(EPOCHS):
        t0 = time.perf_counter()
        repochs = asyncmap(pool, B, lt.backend, nwait=lt.nwait)
        fresh_counts.append(int((repochs == pool.epoch).sum()))
        C = lt.result_device(pool)
        float(fence(C))
        times.append(time.perf_counter() - t0)
        waitall(pool, lt.backend)
    t_coded = min(times)
    err = float(maxabs(C, C_ref)) / ref_scale

    # baseline: bulk-synchronous epoch, pays the injected stragglers
    t0 = time.perf_counter()
    asyncmap(pool, B, lt.backend, nwait=N_WORKERS)
    C_all = lt.result_device(pool)
    float(fence(C_all))
    t_all = time.perf_counter() - t0
    lt.backend.shutdown()

    print(json.dumps({
        "metric": "lt-coded-gemm-16384-16w-wallclock",
        "value": round(t_coded, 4),
        "unit": "s",
        "vs_baseline": round(t_all / t_coded, 2),
        "nwait_all_epoch_s": round(t_all, 4),
        "decode_success": True,
        "fresh_at_return": fresh_counts,
        "decode_rel_err": err,
        "gflops_per_chip": round(2.0 * M * KDIM * NCOLS / t_coded / 1e9, 1),
        "injected_straggler_delay_s": DELAY_S,
    }))


def main_rateless():
    """Incremental redundancy under a PERMANENT straggler: the static
    window cannot decode (its shard never arrives), the rateless stream
    draws generation-1 shards from the live workers and decodes anyway.
    Reports the shards-consumed-vs-k overhead — the price of
    ratelessness (VERDICT round 1 item 2's measured contract)."""
    import numpy as np

    from mpistragglers_jl_tpu.ops.rateless import RatelessLTGemm

    m = kdim = ncols = 8192
    n, k = 12, 8
    rng = np.random.default_rng(0)
    A = rng.standard_normal((m, kdim)).astype(np.float32)
    B = rng.standard_normal((kdim, ncols)).astype(np.float32)
    dead = 0  # permanent straggler: never returns within any round

    # seed 16: worker 0's shard is load-bearing — the static window
    # minus it does NOT peel, so decode REQUIRES generation-1 draws
    rg = RatelessLTGemm(
        A, n, k, seed=16,
        delay_fn=lambda i, e: 3600.0 if i == dead else 0.0,
        precision=jax.lax.Precision.HIGHEST,
    )
    try:
        pool = AsyncPool(n)
        # warmup: compile the worker matmul once, untimed, reusing the
        # full B so the timed shapes match. Fresh-generation draws may
        # still compile the (tiny) device encode once per new support
        # degree inside the timed run — noted in the output.
        import jax.numpy as jnp_

        from mpistragglers_jl_tpu.backends.base import WorkerError

        rg.backend.dispatch(1, jnp_.asarray(B), 0)
        warm = rg.backend.wait(1, timeout=600)
        if warm is None or isinstance(warm, WorkerError):
            raise RuntimeError(f"warmup failed: {warm!r}")
        t0 = time.perf_counter()
        C = rg.multiply(B, pool, round_timeout=15.0, max_rounds=4)
        wall = time.perf_counter() - t0
        err = float(np.max(np.abs(C - A @ B))) / float(np.max(np.abs(C)))
        print(json.dumps({
            "metric": "lt-rateless-gemm-8192-permanent-straggler",
            "value": round(wall, 4),
            "unit": "s",
            "decode_success": bool(err < 1e-3),
            "decode_rel_err": err,
            "shards_used": rg.stats["shards_used"],
            "k": rg.stats["k"],
            "rateless_overhead": round(rg.stats["overhead"], 3),
            "max_generation": rg.stats["max_generation"],
            "note": "worker 0's shard is load-bearing and never "
            "arrives; decode required fresh-generation draws. Wall "
            "includes one 15 s round_timeout wait per extra round, the "
            "host-peel D2H of all collected shards, and a one-time "
            "device-encode compile per new support degree",
        }))
    finally:
        rg.backend.shutdown()


if __name__ == "__main__":
    main()
    main_rateless()
