"""In-kernel attribution of the int8 decode-attention kernel.

VERDICT r4 item 2: the contiguous-layout kernel reads the theoretical
minimum bytes yet loses to the bf16 einsum path — prove where the
residual lives. Each variant strips one phase while keeping the SAME
grid, block specs, and DMA pattern, so differences attribute cleanly:

  dma      load K/V blocks, single f32 row-sum — the pure streaming
           floor of this grid/blocking (no dots, no softmax)
  dot      + the per-head MXU score dot (no scales, no softmax: max)
  dequant  + the rank-1 scale corrections
  full     the shipped kernel (online softmax + PV accumulate)

Against them: the bf16-einsum decode step cost and the int8-einsum
(XLA-materialized dequant) cost at the same shape, plus the byte model.

All timings CHAIN ``inner`` data-dependent calls inside one jit (the
output feeds the next call's query) — the in-scan shape, so the
per-call number carries the same launch/carry boundary cost the
generation scan pays, amortized over the batch rows exactly as the
decode scan amortizes it.

Run: ``PYTHONPATH=. python benchmarks/decode_kernel_attrib.py``
— prints the B=1 flagship attribution, then the BATCHED sweep
(B in {1, 4, 8}, the serving regime: the r6 routing work makes batch
the regime where the kernel must land >= 1.0x bf16 in-scan; the AUTO
gate in models/decode.py routes kernel-at-batch from exactly these
numbers).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main(B=1, L=16384, H=8, Hkv=2, D=128, reps=60, bk=8192,
         variants=True):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from mpistragglers_jl_tpu.ops.decode_attention import (
        _LANE,
        _NEG,
        _SUB,
        quantized_decode_attention,
    )
    from mpistragglers_jl_tpu.ops.flash_attention import (
        _CompilerParams,
        _sds,
    )

    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    g = H // Hkv
    q = jax.device_put(
        jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.bfloat16), dev
    )
    cache = {
        "k": jax.device_put(jnp.asarray(
            rng.integers(-127, 128, (B, L, Hkv, D)), jnp.int8), dev),
        "v": jax.device_put(jnp.asarray(
            rng.integers(-127, 128, (B, L, Hkv, D)), jnp.int8), dev),
        "k_s": jax.device_put(jnp.asarray(
            rng.random((B, L, Hkv)) * 0.01, jnp.float32), dev),
        "v_s": jax.device_put(jnp.asarray(
            rng.random((B, L, Hkv)) * 0.01, jnp.float32), dev),
    }
    cache_bf = {
        "k": (cache["k"].astype(jnp.bfloat16)
              * cache["k_s"][..., None].astype(jnp.bfloat16)),
        "v": (cache["v"].astype(jnp.bfloat16)
              * cache["v_s"][..., None].astype(jnp.bfloat16)),
    }
    pos = jnp.int32(L - 1)
    scale = D ** -0.5

    tiny = jax.device_put(np.ones((8,), np.float32), dev)
    fence = jax.jit(jnp.sum)
    float(fence(tiny))
    rtt = min(
        (lambda t0: (float(fence(tiny)), time.perf_counter() - t0)[1])(
            time.perf_counter()
        )
        for _ in range(5)
    )

    # CHAINED timing: `inner` data-dependent invocations inside ONE
    # jitted program (the output feeds the next call's query), so the
    # per-call number is device time — a per-call dispatch loop would
    # measure the tunnel's ~0.3-0.7 ms enqueue instead (the r4 slope
    # lesson; a first draft of this file measured exactly that).
    inner = 24

    def timed(fn_one, q0, *args):
        @jax.jit
        def chain(q0, *args):
            o = q0
            for _ in range(inner):
                o = fn_one(o, *args).astype(q0.dtype).reshape(q0.shape)
            return o

        out = chain(q0, *args)
        float(jnp.sum(out.astype(jnp.float32)))
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            out = chain(q0, *args)
            float(jnp.sum(out.astype(jnp.float32)))
            dt = (time.perf_counter() - t0 - rtt) / inner
            best = dt if best is None else min(best, dt)
        return best * 1e3

    # ---- einsum references ------------------------------------------
    from mpistragglers_jl_tpu.models.decode import _cached_attention

    ein_bf16 = timed(
        lambda q, c: _cached_attention(q, c, pos[None], scale,
                                       use_kernel=False),
        q, cache_bf,
    )
    ein_int8 = timed(
        lambda q, c: _cached_attention(q, c, pos[None], scale,
                                       use_kernel=False),
        q, cache,
    )
    full = timed(
        lambda q, c: quantized_decode_attention(q, c, pos, scale,
                                                block_k=bk),
        q, cache,
    )

    # ---- stripped variants (same grid/specs/DMA, same block pick as
    # the shipped kernel's VMEM model) ---------------------------------
    from mpistragglers_jl_tpu.ops.decode_attention import _pick_block_128

    bk_eff = _pick_block_128(L, bk, Hkv, D)
    nk = L // bk_eff

    def variant(mode):
        def kern(pos_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
                 acc, m_sc, l_sc):
            j = pl.program_id(1)

            @pl.when(j == 0)
            def _init():
                acc[:] = jnp.zeros_like(acc)
                m_sc[:] = jnp.full_like(m_sc, _NEG)
                l_sc[:] = jnp.zeros_like(l_sc)

            kblk = k_ref[0]
            vblk = v_ref[0]
            if mode == "dma":
                # touch every byte, minimal compute: one f32 accumulate
                acc[:1, :1] += (
                    kblk[:1, :1].astype(jnp.float32)
                    + vblk[:1, :1].astype(jnp.float32)
                )
            else:
                ksb = ks_ref[0].astype(jnp.float32)
                vsb = vs_ref[0].astype(jnp.float32)
                for h in range(Hkv):
                    rows = slice(h * _SUB, (h + 1) * _SUB)
                    qh = q_ref[0][rows]
                    kb = kblk[:, h * D:(h + 1) * D].astype(qh.dtype)
                    s = jax.lax.dot_general(
                        qh, kb, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    ) * scale
                    if mode != "dot":
                        s = s * ksb[:, h][None, :]
                    if mode == "full_nosm":
                        vb = vblk[:, h * D:(h + 1) * D].astype(
                            jnp.float32)
                        pv = s * vsb[:, h][None, :]
                        acc[rows] += jax.lax.dot_general(
                            pv, vb, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        )
                    else:
                        # dot / dequant: reduce scores only
                        acc[rows, :1] += s.max(axis=-1, keepdims=True)

            @pl.when(j == nk - 1)
            def _fin():
                o_ref[0] = acc[:].astype(o_ref.dtype)

        rows = Hkv * _SUB
        q3 = jnp.pad(
            q.reshape(B, Hkv, g, D), ((0, 0), (0, 0), (0, _SUB - g),
                                      (0, 0))
        ).reshape(B, rows, D)
        kf = cache["k"].reshape(B, L, Hkv * D)
        vf = cache["v"].reshape(B, L, Hkv * D)

        def run(q3, kf, ks, vf, vs):
            return pl.pallas_call(
                kern,
                grid=(B, nk),
                in_specs=[
                    pl.BlockSpec(memory_space=pltpu.SMEM),
                    pl.BlockSpec((1, rows, D), lambda b, j: (b, 0, 0)),
                    pl.BlockSpec((1, bk_eff, Hkv * D),
                                 lambda b, j: (b, j, 0)),
                    pl.BlockSpec((1, bk_eff, Hkv),
                                 lambda b, j: (b, j, 0)),
                    pl.BlockSpec((1, bk_eff, Hkv * D),
                                 lambda b, j: (b, j, 0)),
                    pl.BlockSpec((1, bk_eff, Hkv),
                                 lambda b, j: (b, j, 0)),
                ],
                out_specs=pl.BlockSpec((1, rows, D),
                                       lambda b, j: (b, 0, 0)),
                out_shape=_sds((B, rows, D), jnp.float32, q3),
                scratch_shapes=[
                    pltpu.VMEM((rows, D), jnp.float32),
                    pltpu.VMEM((rows, _LANE), jnp.float32),
                    pltpu.VMEM((rows, _LANE), jnp.float32),
                ],
                compiler_params=_CompilerParams(
                    dimension_semantics=("parallel", "arbitrary")
                ),
            )(jnp.full((B,), L - 1, jnp.int32), q3, kf, cache["k_s"],
              vf, cache["v_s"])

        def one(q3c, kf, ks, vf, vs):
            return run(q3c, kf, ks, vf, vs)

        return timed(one, q3, kf, cache["k_s"], vf, cache["v_s"])

    out = {
        "shape": f"B={B} L={L} H={H} Hkv={Hkv} D={D} bk={bk_eff} nk={nk}",
        "fence_rtt_ms": round(rtt * 1e3, 2),
        "int8_bytes_mib": round(B * 2 * L * Hkv * D / 2**20, 1),
        "bf16_bytes_mib": round(B * 2 * L * Hkv * D * 2 / 2**20, 1),
        "einsum_bf16_ms": round(ein_bf16, 4),
        "einsum_int8_ms": round(ein_int8, 4),
        "kernel_full_ms": round(full, 4),
        # the acceptance ratio: batched in-scan int8 kernel vs the
        # bf16 einsum step, same chained-call discipline
        "kernel_vs_bf16": round(ein_bf16 / full, 3),
        "einsum_int8_vs_bf16": round(ein_bf16 / ein_int8, 3),
    }
    if variants:
        out.update({
            "kernel_dma_ms": round(variant("dma"), 4),
            "kernel_dot_ms": round(variant("dot"), 4),
            "kernel_dequant_ms": round(variant("dequant"), 4),
            "kernel_nosoftmax_ms": round(variant("full_nosm"), 4),
        })
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    # flagship B=1 attribution (stripped variants included), then the
    # batched sweep — the serving regime the AUTO routing gate serves
    main()
    for B in (4, 8):
        main(B=B, variants=False)
