"""Hierarchical vs flat coded GEMM at equal host-loss resilience.

The round-14 driver rung (ISSUE 9 acceptance): over the SAME simulated
fleet — H hosts of ``n_inner`` chips, heavy-tailed per-chip latency,
one whole host killed mid-run — compare the two code constructions that
both survive the host loss:

* **flat MDS** ``(N, k_flat) = (H * n_inner, (H-1) * n_inner)``: the
  only flat rate that tolerates ``n_inner`` simultaneous chip deaths.
  Once the host is down the decoder needs EVERY surviving chip each
  epoch (zero residual slack), and decode solves one
  ``k_flat x k_flat`` system.
* **hierarchical** (:class:`~mpistragglers_jl_tpu.ops.hierarchical.
  HierarchicalCodedGemm`): rate-(H-1)/H sum-parity outer code across
  hosts over an ``(n_inner, k_inner)`` MDS inner code per host. The
  dead host is simply never waited on and every surviving host keeps
  its own ``k_inner``-of-``n_inner`` slack; decode is ``L`` small
  solves plus an O(n) subtraction pass.

Both recover the exact product every epoch (asserted against ``A @ B``
each epoch — a captured ratio with a wrong decode would be a lie).
Epoch time is VIRTUAL (deterministic; per-chip delay from a seeded
lognormal plus a service term proportional to the per-worker block
rows, so the hierarchical code's extra per-chip compute is priced, not
hidden); decode cost is measured WALL time of the real decode paths.
The kill-one-host leg runs twice and must be bit-identical (virtual
walls AND decoded bytes) — the determinism claim host-loss postmortems
lean on.

Driver scalars (benchmarks/README.md round-14 note):
``hier_vs_flat_decode_x`` (>= 2 gate) and ``hier_hostloss_epoch_ok``;
``hier_vs_flat_epoch_x`` (>= 1.5 gate) rides in the full rung dict.
"""

from __future__ import annotations

import time

import numpy as np


def _per_row_service(rows: int, t_row: float = 40e-6):
    """Service-time model: a worker computing ``rows`` block rows pays
    ``rows * t_row`` virtual seconds of compute on top of its network
    delay — the knob that keeps the comparison honest about the
    hierarchical code's larger per-worker blocks (docs/PERF.md
    round-14)."""
    s = float(rows) * float(t_row)
    return lambda worker, epoch: s


def bench_hierarchical_rung(
    H: int = 4,
    n_inner: int = 8,
    k_inner: int = 6,
    m: int = 1440,
    kdim: int = 256,
    ncols: int = 512,
    epochs: int = 20,
    kill_epoch: int = 6,
    decode_reps: int = 15,
    seed: int = 3,
) -> dict:
    import jax.numpy as jnp

    from mpistragglers_jl_tpu import AsyncPool, SimBackend, asyncmap
    from mpistragglers_jl_tpu.ops import HierarchicalCodedGemm
    from mpistragglers_jl_tpu.ops.coding import MDSCode
    from mpistragglers_jl_tpu.ops.gemm import _block_matmul
    from mpistragglers_jl_tpu.ops.outer_code import partition_groups
    from mpistragglers_jl_tpu.utils import faults

    n = H * n_inner
    k_flat = (H - 1) * n_inner  # equal single-host-loss resilience
    rng = np.random.default_rng(0)
    A = rng.standard_normal((m, kdim)).astype(np.float32)
    B = rng.standard_normal((kdim, ncols)).astype(np.float32)
    C_ref = A @ B
    ref_scale = float(np.max(np.abs(C_ref)))
    part = partition_groups(n, H)
    fleet = faults.compose(
        faults.seeded_lognormal(0.010, 1.0, seed=seed),
        faults.kill_group(part, {H // 2: kill_epoch}),
    )

    def run_hier():
        hg = HierarchicalCodedGemm(
            A, groups=H, n_inner=n_inner, k_inner=k_inner,
            device_backend=False,
        )
        be = SimBackend(
            hg.work, n, delay_fn=fleet,
            service_fn=_per_row_service(hg.block_rows),
        )
        pool = AsyncPool(n)
        walls, max_err, lost = [], 0.0, 0
        for _ in range(epochs):
            t0 = be.clock.now()
            asyncmap(pool, B, be, nwait=hg.nwait)
            walls.append(be.clock.now() - t0)
            try:
                C = hg.result(pool)
            except ValueError:
                lost += 1
                continue
            max_err = max(
                max_err,
                float(np.max(np.abs(C - C_ref))) / ref_scale,
            )
        # decode wall: the real two-level decode path (L small inner
        # solves + the O(n) outer pass), min over reps
        hg.result(pool)  # compile warmup outside the clock
        best = None
        for _ in range(decode_reps):
            t0 = time.perf_counter()
            C = hg.result(pool)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return walls, max_err, lost, best, C, hg

    # -- hierarchical, twice (the bit-identical host-loss claim) ----------
    h_walls, h_err, h_lost, h_decode_s, h_C, hg = run_hier()
    h_walls2, h_err2, h_lost2, _, h_C2, _ = run_hier()
    bitident = (
        h_walls == h_walls2
        and np.array_equal(h_C, h_C2)
        and h_err == h_err2
    )

    # -- flat MDS at the same resilience over the same fleet --------------
    # gaussian parity: at k_flat ~ 24 the Cauchy construction's solve
    # conditioning collapses (rel err > 1 measured); the iid-Gaussian
    # generator is MDS w.p. 1 and keeps the big solve honest — exactly
    # the large-k regime the hierarchical code exists to avoid
    code = MDSCode(n, k_flat, dtype=np.float32, parity="gaussian")
    coded = np.asarray(code.encode_array(A))
    coded_dev = [jnp.asarray(coded[i]) for i in range(n)]

    def flat_work(i, payload, epoch):
        return _block_matmul(coded_dev[int(i)], payload,
                             precision=code.precision)

    be = SimBackend(
        flat_work, n, delay_fn=fleet,
        service_fn=_per_row_service(m // k_flat),
    )
    pool = AsyncPool(n)
    f_walls, f_err = [], 0.0

    def flat_decode():
        # same host-side one-transfer gather discipline as the
        # hierarchical decode path — the comparison prices the solves,
        # not an asymmetric per-shard dispatch tax
        fresh = pool.fresh_indices()
        idx = fresh[:k_flat]
        shards = jnp.asarray(np.stack([
            np.asarray(pool.results[int(i)]) for i in idx
        ]))
        return np.asarray(code.decode_array(shards, idx))

    for _ in range(epochs):
        t0 = be.clock.now()
        asyncmap(pool, B, be, nwait=k_flat)
        f_walls.append(be.clock.now() - t0)
        C = flat_decode()
        f_err = max(
            f_err, float(np.max(np.abs(C - C_ref))) / ref_scale
        )
    flat_decode()  # compile warmup outside the clock
    f_decode_s = None
    for _ in range(decode_reps):
        t0 = time.perf_counter()
        flat_decode()
        dt = time.perf_counter() - t0
        f_decode_s = dt if f_decode_s is None else min(f_decode_s, dt)

    h_mean = float(np.mean(h_walls))
    f_mean = float(np.mean(f_walls))
    epoch_x = f_mean / h_mean
    decode_x = f_decode_s / h_decode_s
    # 1e-3 exactness gate: f32 solves through a kappa~1e3 Cauchy
    # 6-of-8 submatrix plus the parity cancellation chain sit at
    # ~2e-4 relative; anything near 1 means a wrong decode, not
    # rounding (the flat Cauchy construction at k=24 measured 137)
    ok = (
        h_lost == 0 and h_lost2 == 0
        and h_err < 1e-3 and f_err < 1e-3
        and bool(bitident)
    )
    return {
        "fleet": {
            "groups": H, "n_inner": n_inner, "k_inner": k_inner,
            "k_flat": k_flat, "m": m, "kdim": kdim, "ncols": ncols,
            "killed_group": H // 2, "kill_epoch": kill_epoch,
            "delay": f"lognormal(10ms, sigma=1, seed={seed}) + "
                     f"rows*40us service",
        },
        "epochs": epochs,
        "hier_epoch_ms": round(h_mean * 1e3, 3),
        "flat_epoch_ms": round(f_mean * 1e3, 3),
        "hier_vs_flat_epoch_x": round(epoch_x, 2),
        "hier_decode_ms": round(h_decode_s * 1e3, 3),
        "flat_decode_ms": round(f_decode_s * 1e3, 3),
        "hier_vs_flat_decode_x": round(decode_x, 2),
        "hier_decode_rel_err": h_err,
        "flat_decode_rel_err": f_err,
        "hier_lost_epochs": h_lost,
        "hier_bitidentical": bool(bitident),
        "hier_hostloss_epoch_ok": bool(ok),
        "outer": f"parity L={hg.L}/H={H}",
    }


if __name__ == "__main__":
    import json

    print(json.dumps(bench_hierarchical_rung(), default=str, indent=2))
