"""Per-phase attribution of the 134M flagship train step (VERDICT r3 #1).

The 134M rung ran at MFU 0.57-0.76 across sessions while the 470M rung
hit 0.81 in the same run — a third of the chip unattributed. This bench
breaks the step into its four phases, each timed as its own jitted
fwd+bwd program on the real chip with the same shapes the full step
uses, pipelined-chain + fence-RTT-subtracted methodology
(docs/PERF.md):

* ``attention`` — the flash kernel (fwd + custom-vjp bwd, all three
  input grads) at (B, L, H, Dh), once per layer;
* ``mlp_proj``  — LN + QKV/out projections + MLP einsums per layer with
  attention replaced by a cheap mix (the dense-GEMM body), weight grads
  included;
* ``head_loss`` — final LN + tied (B, L, V) logits einsum + token NLL
  (+ backward incl. the embedding grad), from a (B, L, D) activation;
* ``embed``     — token lookup + its scatter-add backward.

Methodology notes (hard-won on this tunnel, docs/PERF.md): every
program RETURNS every gradient it claims to compute (an unused grad is
DCE'd by XLA and silently not timed), and each chain is fenced by a
scalar sum over ALL final outputs (fencing one output of a multi-output
program does not wait for its siblings on the tunneled chip).

Each phase's matmul FLOPs are known in closed form, so the table gives
per-phase TF/s and time share vs FLOP share — the two columns whose
mismatch names the MFU eater. ``sum_of_phases`` vs the measured full
step bounds what the decomposition misses (inter-phase fusion, the
residual adds, LN outside the phases' scopes).

Run: ``PYTHONPATH=. python benchmarks/flagship_phases.py [--quick|--gqa]``
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["profile_flagship_phases"]


def _timed(thunk) -> float:
    t0 = time.perf_counter()
    thunk()
    return time.perf_counter() - t0


def profile_flagship_phases(
    *,
    batch: int = 8,
    seq: int = 2048,
    d_model: int = 1024,
    n_layers: int = 8,
    n_heads: int = 8,
    d_ff: int = 4096,
    vocab: int = 32768,
    n_kv_heads: int | None = None,
    steps: int = 4,
    chains: int = 2,
    block_q: int = 1024,
    block_k: int = 1024,
    full: bool = True,
) -> dict:
    import jax
    import jax.numpy as jnp

    from mpistragglers_jl_tpu.ops.flash_attention import flash_attention

    B, L, D, F, V, H = batch, seq, d_model, d_ff, vocab, n_heads
    Hkv = n_kv_heads or H
    Dh = D // H
    dt = jnp.bfloat16
    rng = np.random.default_rng(0)
    dev = jax.devices()[0]

    def put(*shape):
        return jax.device_put(
            rng.standard_normal(shape).astype(np.float32) * 0.02, dev
        ).astype(dt)

    # fence RTT (tunnel): measured, subtracted from every chain
    tiny = jax.device_put(np.ones((8,), np.float32), dev)
    tiny_fence = jax.jit(jnp.sum)
    float(tiny_fence(tiny))
    rtt = min(_timed(lambda: float(tiny_fence(tiny))) for _ in range(5))

    # fence = scalar sum over EVERY leaf of the final outputs
    @jax.jit
    def fence_all(tree):
        return sum(
            x.astype(jnp.float32).sum() for x in jax.tree.leaves(tree)
        )

    def run_chain(step, carry0, *consts):
        """``step(carry, *consts) -> (carry, aux)``; ``steps`` calls
        back-to-back (carry serializes the chain), ONE all-leaf fence;
        min over ``chains``."""
        carry, aux = step(carry0, *consts)  # compile
        float(fence_all((carry, aux)))
        best = None
        for _ in range(chains):
            t0 = time.perf_counter()
            for _ in range(steps):
                carry, aux = step(carry, *consts)
            float(fence_all((carry, aux)))
            dt_ = (time.perf_counter() - t0 - rtt) / steps
            best = dt_ if best is None else min(best, dt_)
        return best

    phases = {}

    # ---- attention phase: n_layers x flash fwd+bwd ---------------------
    qkv0 = {"q": put(B, L, H, Dh), "k": put(B, L, Hkv, Dh),
            "v": put(B, L, Hkv, Dh)}

    def attn_loss(qkv):
        # each layer's output feeds the next layer's query — WITHOUT
        # this dependency XLA CSE's the n_layers identical flash calls
        # into one and the phase reads 8x too fast (first run of this
        # bench did exactly that: "attention at 296 TF/s", above the
        # chip ceiling)
        q = qkv["q"]
        for _ in range(n_layers):
            q = flash_attention(
                q, qkv["k"], qkv["v"], causal=True,
                block_q=block_q, block_k=block_k,
            )
        return q.astype(jnp.float32).sum()

    @jax.jit
    def attn_step(qkv):
        g = jax.grad(attn_loss)(qkv)  # all three grads, returned whole
        return g, ()

    attn_flops = 3.0 * n_layers * 2 * B * L * L * Dh * H
    t = run_chain(attn_step, qkv0)
    phases["attention"] = {"s": t, "flops": attn_flops}

    # ---- mlp + projections phase (attention = cheap mix) ----------------
    lp = {
        "ln1_s": put(D), "ln1_b": put(D),
        "wq": put(D, H, Dh), "wk": put(D, Hkv, Dh), "wv": put(D, Hkv, Dh),
        "wo": put(H, Dh, D),
        "ln2_s": put(D), "ln2_b": put(D),
        "w1": put(D, F), "b1": put(F), "w2": put(F, D), "b2": put(D),
    }
    x0 = put(B, L, D)

    def body_loss(x, lp):
        from mpistragglers_jl_tpu.models.transformer import _ln, _mlp

        for _ in range(n_layers):
            h = _ln(x, lp["ln1_s"], lp["ln1_b"])
            q = jnp.einsum("bld,dhk->blhk", h, lp["wq"])
            k = jnp.einsum("bld,dhk->blhk", h, lp["wk"])
            v = jnp.einsum("bld,dhk->blhk", h, lp["wv"])
            o = q + (k + v).sum(2, keepdims=True)  # stand-in for attn
            x = x + jnp.einsum("blhk,hkd->bld", o, lp["wo"])
            h2 = _ln(x, lp["ln2_s"], lp["ln2_b"])
            x = x + _mlp(h2, lp) + lp["b2"]
        return x.astype(jnp.float32).sum()

    @jax.jit
    def body_step(x, lp):
        g_x, g_w = jax.grad(body_loss, argnums=(0, 1))(x, lp)
        return g_x.astype(dt), g_w

    body_flops = 3.0 * n_layers * (
        2 * B * L * D * D                 # wq
        + 2 * 2 * B * L * D * Hkv * Dh    # wk + wv
        + 2 * B * L * D * D               # wo
        + 4 * B * L * D * F               # mlp up + down
    )
    t = run_chain(body_step, x0, lp)
    phases["mlp_proj"] = {"s": t, "flops": body_flops}

    # ---- head + loss phase ---------------------------------------------
    emb = put(V, D)
    lnf_s, lnf_b = put(D), put(D)
    tgt = jax.device_put(rng.integers(0, V, (B, L), dtype=np.int32), dev)

    def head_loss(x, emb):
        from mpistragglers_jl_tpu.models.transformer import _ln

        h = _ln(x, lnf_s, lnf_b)
        logits = jnp.einsum("bld,vd->blv", h, emb)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)
        return nll.mean()

    @jax.jit
    def head_step(x, emb):
        g_x, g_emb = jax.grad(head_loss, argnums=(0, 1))(x, emb)
        return g_x.astype(dt), g_emb

    head_flops = 3.0 * 2 * B * L * D * V
    t = run_chain(head_step, x0, emb)
    phases["head_loss"] = {"s": t, "flops": head_flops}

    # ---- embed phase ----------------------------------------------------
    toks = jax.device_put(rng.integers(0, V, (B, L), dtype=np.int32), dev)

    @jax.jit
    def embed_step(emb, toks):
        def f(emb):
            return emb[toks].astype(jnp.float32).sum()

        return jax.grad(f)(emb).astype(dt), ()

    t = run_chain(embed_step, emb, toks)
    phases["embed"] = {"s": t, "flops": 0.0}

    out = {
        "metric": "flagship-phase-profile",
        "batch": batch, "seq": seq, "d_model": d_model,
        "n_layers": n_layers, "vocab": vocab, "n_kv_heads": Hkv,
        "block_q": block_q, "block_k": block_k,
        "fence_rtt_s": round(rtt, 4),
        "sum_of_phases_s": round(sum(p["s"] for p in phases.values()), 4),
        "phases": {},
    }

    # ---- the full step, same session, for the comparison ----------------
    if full:
        from benchmarks.transformer_train_bench import bench_transformer_train

        f = bench_transformer_train(
            batch=batch, seq=seq, d_model=d_model, n_layers=n_layers,
            n_heads=n_heads, d_ff=d_ff, vocab=vocab, steps=steps,
            chains=chains, oracle=False,
        )
        out["full_step_s"] = f["value"]
        out["full_mfu"] = f["mfu_vs_raw_matmul"]
        out["raw_bf16_tflops_per_s"] = f["raw_bf16_tflops_per_s"]
        raw = f["raw_bf16_tflops_per_s"]
    else:
        raw = None

    total_flops = sum(p["flops"] for p in phases.values())
    for name, p in phases.items():
        out["phases"][name] = {
            "s": round(p["s"], 4),
            "time_share_of_sum": round(
                p["s"] / sum(q["s"] for q in phases.values()), 3
            ),
            "flop_share": round(p["flops"] / total_flops, 3),
            "tflops_per_s": round(p["flops"] / p["s"] / 1e12, 1)
            if p["flops"] else None,
            "mfu": round(p["flops"] / p["s"] / 1e12 / raw, 3)
            if p["flops"] and raw else None,
        }
    return out


if __name__ == "__main__":
    import json
    import os
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    kw = {}
    if "--quick" in sys.argv:
        kw = dict(steps=2, chains=1, n_layers=2)
    if "--gqa" in sys.argv:
        kw["n_kv_heads"] = 2
    for a in sys.argv[1:]:
        if a.startswith("--block_k="):
            kw["block_k"] = int(a.split("=")[1])
        if a.startswith("--block_q="):
            kw["block_q"] = int(a.split("=")[1])
        if a.startswith("--seq="):
            kw["seq"] = int(a.split("=")[1])
    print(json.dumps(profile_flagship_phases(**kw), indent=1))
