"""BASELINE config 5: gradient-coded SGD, logistic regression, 1e6x1024.

Every epoch is one ``asyncmap`` with ``nwait = n - s``; the cyclic
gradient code (ops/gradcode.py) recovers the *exact* full-batch gradient
from whichever n-s workers arrive, so the injected stragglers cost
nothing. Data is generated on device (``CodedSGD.synthetic``) — the
4 GB dataset never crosses the host<->device edge. ``vs_baseline`` is
the straggler-mitigation factor: epoch wall-clock forced to
``nwait = n`` (bulk-synchronous, pays the injected delay every epoch)
over the coded epoch.
"""

from __future__ import annotations

import json
import os
import sys
import time


sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpistragglers_jl_tpu import AsyncPool, waitall
from mpistragglers_jl_tpu.models import CodedSGD, LogisticRegression

N = 1_000_000
DIM = 1024
N_WORKERS = 16
S = 2  # tolerate both injected stragglers (nwait = 14)
STRAGGLERS = (2, 9)
DELAY_S = 2.0
EPOCHS = 10
LR = 0.5


def main():
    import jax
    import jax.numpy as jnp

    delay_fn = lambda i, e: DELAY_S if i in STRAGGLERS else 0.0
    sgd = CodedSGD.synthetic(
        N, DIM, N_WORKERS, S, delay_fn=delay_fn, seed=0
    )
    # eval set = worker 0's own first chunk (device-resident)
    X_eval, y_eval = sgd.eval_data()
    eval_loss = jax.jit(sgd.model.loss)

    fence = jax.jit(jnp.sum)
    pool = AsyncPool(N_WORKERS)
    w = jnp.zeros(DIM, dtype=jnp.float32)
    w = sgd.step(pool, w, LR)  # warmup epoch (compiles), untimed
    float(fence(w))
    loss0 = float(eval_loss(w, X_eval, y_eval))

    t0 = time.perf_counter()
    for _ in range(EPOCHS):
        w = sgd.step(pool, w, LR)
    float(fence(w))  # materialization fence for the whole chain
    t_coded = (time.perf_counter() - t0) / EPOCHS
    loss1 = float(eval_loss(w, X_eval, y_eval))
    waitall(pool, sgd.backend)

    # baseline: one bulk-synchronous epoch (waits for the stragglers);
    # the exact same step, just forced to hear from everyone
    t0 = time.perf_counter()
    w2 = sgd.step(pool, w, LR, nwait=N_WORKERS)
    float(fence(w2))
    t_all = time.perf_counter() - t0
    sgd.backend.shutdown()

    print(json.dumps({
        "metric": "gradcoded-sgd-1e6x1024-epoch-wallclock",
        "value": round(t_coded, 4),
        "unit": "s",
        "vs_baseline": round(t_all / t_coded, 2),
        "nwait_all_epoch_s": round(t_all, 4),
        "loss_after_warmup": round(loss0, 5),
        "loss_after_epochs": round(loss1, 5),
        "epochs": EPOCHS,
        "n_workers": N_WORKERS,
        "s": S,
        "injected_straggler_delay_s": DELAY_S,
    }))


if __name__ == "__main__":
    main()
