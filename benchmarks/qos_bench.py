"""Round-19 multi-tenant QoS rung: priced isolation under a 10x flood.

One leg, sim-only (unscaled in bench.py — virtual-time bookkeeping
does not track the matmul rate): a mixed 3-tenant diurnal day over a
4-replica fleet — tenant ``a`` (latency class, weight 4), ``b``
(throughput, weight 4), and ``c`` (batch, weight 1, token-budgeted to
~10% of fleet capacity) — driven four ways on identical compliant
arrivals (``a``+``b`` ride the SAME seeded stream in every leg;
only ``c``'s co-tenant behavior changes):

* **DRR flood-free**: the QoS plane (deficit admission + budget
  door), ``c`` at its contracted rate — the compliant baseline;
* **DRR flood**: ``c`` floods 10x its budget; the bucket sheds the
  sustained overload by name and the deficit rotation paces what
  slips through — run TWICE for the bit-identity witness;
* **FIFO flood**: the same flood with no QoS plane at equal chip
  count — the pre-round-19 behavior the rung prices against.

Headline scalars (bench.py compact line, format in
benchmarks/README.md round-19 note):

* ``qos_isolation_eps`` — the larger compliant tenant's |p99 TTFT
  shift| between the DRR flood and flood-free days, seconds; FAILS
  at or above the pinned 0.05 s epsilon;
* ``qos_util_floor`` — flood-day fleet utilization (busy tick
  seconds / replica-seconds); FAILS under the 0.85 work-conservation
  floor (idle capacity always serves queued work; the diurnal trough
  idles honestly once the flood sheds at the door).

The FIFO leg is the context number: the identical flood moves the
compliant p99 by ORDERS of magnitude without the QoS plane
(``fifo_vs_drr_p99_x``). Both DRR flood days (same seed) must agree
on the workload digest — the sim plane's bit-identity witness.
"""

from __future__ import annotations

import heapq
import time

_N_REP, _SLOTS, _NI, _TICK = 4, 4, 8, 0.02
_PLEN, _CHUNK, _MNEW = 96, 64, 32
_TOK = _PLEN + _MNEW
_PERIOD = 60.0
_AB_RATE, _C_RATE = 70.0, 13.0  # fleet capacity ~133 req/s
_EPS_S = 0.05
# the diurnal trough (amplitude 0.5) legitimately idles part of the
# fleet once the flood sheds at the door — the floor is about never
# idling WHILE work is queued, measured ~0.93 on the reference day
_UTIL_FLOOR = 0.85


def _registry():
    from mpistragglers_jl_tpu.qos import TenantContract, TenantRegistry

    return TenantRegistry([
        TenantContract("a", cls="latency", weight=4.0, ttft_slo=0.5),
        TenantContract("b", cls="throughput", weight=4.0),
        TenantContract("c", cls="batch", weight=1.0,
                       rate=_C_RATE * _TOK * 1.2,
                       burst=_C_RATE * _TOK * 2.0),
    ])


def _streams(n_ab: int, flood: bool, seed: int):
    """Compliant a+b arrivals are IDENTICAL across legs (their own
    seeded diurnal generator); c merges in from a separate stream at
    1x or 10x its contracted rate."""
    from mpistragglers_jl_tpu.sim import (
        diurnal_arrivals,
        poisson_arrivals,
    )

    ab = diurnal_arrivals(
        _AB_RATE, n=n_ab, period=_PERIOD, amplitude=0.5, seed=seed,
        prompt_len=_PLEN, max_new=_MNEW,
        tenants={"a": 0.5, "b": 0.5},
    )
    span = n_ab / _AB_RATE
    c_rate = _C_RATE * (10.0 if flood else 1.0)
    c = poisson_arrivals(
        c_rate, n=max(int(c_rate * span), 1), seed=seed + 17,
        prompt_len=_PLEN, max_new=_MNEW, tenants={"c": 1.0},
    )
    return heapq.merge(ab, c, key=lambda x: x.t)


def _day(n_ab: int, seed: int, *, flood: bool, qos: bool):
    from mpistragglers_jl_tpu.models.router import RequestRouter
    from mpistragglers_jl_tpu.sim import (
        SimReplica,
        VirtualClock,
        lognormal_ticks,
        run_router_day,
    )

    reg = _registry() if qos else None
    clock = VirtualClock()
    reps = [
        SimReplica(clock, slots=_SLOTS, n_inner=_NI,
                   prompt_chunk=_CHUNK, qos=reg,
                   tick_s=lognormal_ticks(_TICK, 0.2, seed=1009 + i))
        for i in range(_N_REP)
    ]
    router = RequestRouter(reps, policy="least_loaded", clock=clock,
                           qos=reg)
    report = run_router_day(router, _streams(n_ab, flood, seed))
    util = sum(r.busy_s for r in reps) / (_N_REP * report.virtual_s)
    return report, util


def bench_qos_rung(requests: int | None = None):
    """The driver rung ``qos``: FIFO vs DRR under the 10x flood at
    equal chip count, with the epsilon/floor gates and the
    bit-identity witness over the flooded day."""
    import os

    n_ab = int(
        requests if requests is not None
        else os.environ.get("QOS_BENCH_REQUESTS", "3500")
    )
    seed = 13
    t0 = time.perf_counter()
    base, _ = _day(n_ab, seed, flood=False, qos=True)
    fl1, util = _day(n_ab, seed, flood=True, qos=True)
    fl2, _ = _day(n_ab, seed, flood=True, qos=True)
    if fl1.digest() != fl2.digest():
        raise AssertionError(
            f"flooded DRR day not bit-identical: {fl1.digest()} != "
            f"{fl2.digest()}"
        )
    pb, pf = base.per_tenant(), fl1.per_tenant()
    eps = max(
        abs(pf[t]["p99_ttft_s"] - pb[t]["p99_ttft_s"])
        for t in ("a", "b")
    )
    if eps >= _EPS_S:
        raise AssertionError(
            f"qos_isolation_eps {eps * 1e3:.1f}ms at or above the "
            f"pinned {_EPS_S * 1e3:.0f}ms epsilon: the 10x flood "
            "moved a compliant tenant's p99"
        )
    if util < _UTIL_FLOOR:
        raise AssertionError(
            f"qos_util_floor {util:.3f} under the {_UTIL_FLOOR} "
            "work-conservation floor: capacity idled while work "
            "was queued"
        )
    if fl1.dropped or base.dropped:
        raise AssertionError(
            f"dropped requests (flood {fl1.dropped}, base "
            f"{base.dropped}): shed is the only sanctioned loss"
        )
    if fl1.n_shed < 1:
        raise AssertionError(
            "the flood day shed nothing: the budget door never fired"
        )
    # FIFO contrast at equal chip count: the same flood, no QoS plane
    fifo, _ = _day(n_ab, seed, flood=True, qos=False)
    pfifo = fifo.per_tenant()
    fifo_p99 = max(pfifo[t]["p99_ttft_s"] for t in ("a", "b"))
    drr_p99 = max(pf[t]["p99_ttft_s"] for t in ("a", "b"))
    return {
        "requests": int(fl1.n),
        "qos_isolation_eps": round(eps, 4),
        "qos_util_floor": round(util, 3),
        "fifo_vs_drr_p99_x": round(fifo_p99 / drr_p99, 1),
        "compliant_p99_ms": {
            t: round(pf[t]["p99_ttft_s"] * 1e3, 1) for t in ("a", "b")
        },
        "fifo_compliant_p99_ms": round(fifo_p99 * 1e3, 1),
        "flood_shed": int(fl1.n_shed),
        "flood_served_c": int(pf["c"]["served"]),
        "virtual_day_s": round(fl1.virtual_s, 1),
        "digest": fl1.digest(),
        "wall_s": round(time.perf_counter() - t0, 2),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(bench_qos_rung(), indent=2, default=str))
