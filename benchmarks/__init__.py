# Namespace package marker so bench.py (the driver's one-line contract)
# can reuse the shared harnesses here instead of duplicating them.
