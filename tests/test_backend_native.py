"""Core pool on the native C++ transport backend.

Same behavioral checklist as the ProcessBackend suite (the reference's
mpiexec execution model, test/runtests.jl:17), but all coordinator-side
I/O runs in the native runtime: framed Unix-socket messaging, epoll
progress thread, native waitany (native/transport.cpp — the libmpi role,
SURVEY component C8). Also covers the raw transport layer directly.
Everything must be module-level picklable for spawn.
"""

import os

import numpy as np
import pytest

from mpistragglers_jl_tpu import AsyncPool, WorkerFailure, asyncmap, waitall
from mpistragglers_jl_tpu.backends.process import (
    RemoteWorkerError,
    WorkerProcessDied,
)
from mpistragglers_jl_tpu.native import NativeBuildError

try:
    from mpistragglers_jl_tpu.backends.native import NativeProcessBackend
    from mpistragglers_jl_tpu.native import transport as T

    T.load_lib()
    _SKIP = None
except NativeBuildError as e:  # pragma: no cover - no compiler in env
    _SKIP = str(e)

pytestmark = pytest.mark.skipif(
    _SKIP is not None, reason=f"native transport unavailable: {_SKIP}"
)


def _echo(i, payload, epoch):
    # the reference's result message layout [rank, t, epoch]
    # (test/kmap2.jl:92-94)
    return np.array([float(i + 1), float(payload[0]), float(epoch)])


def _fail_worker1_epoch2(i, payload, epoch):
    if i == 1 and epoch == 2:
        raise ValueError("boom from native worker")
    return np.array([float(i + 1), float(payload[0]), float(epoch)])


def _exit_worker2(i, payload, epoch):
    if i == 2:
        os._exit(3)  # crashed rank, not a Python exception
    return np.array([float(i + 1), float(payload[0]), float(epoch)])


def _exit_on_negative(i, payload, epoch):
    if i == 1 and payload[0] < 0:
        os._exit(5)
    return np.array([float(i + 1), float(payload[0]), float(epoch)])


class StragglerDelay:
    def __init__(self, straggler: int, slow: float = 0.25, fast: float = 0.001):
        self.straggler = straggler
        self.slow = slow
        self.fast = fast

    def __call__(self, i: int, epoch: int) -> float:
        return self.slow if i == self.straggler else self.fast


# ---------------------------------------------------------------- transport


def _transport_pair(n):
    import tempfile
    import uuid

    path = os.path.join(
        tempfile.gettempdir(), f"msgt-test-{uuid.uuid4().hex[:8]}.sock"
    )
    return T.Coordinator(path, n), path


def test_transport_roundtrip_and_waitany():
    """Raw frames: isend -> worker recv -> worker send -> coord waitany."""
    import threading

    coord, path = _transport_pair(2)
    results = {}

    def worker(rank):
        w = T.Worker(path, rank)
        while True:
            msg = w.recv()
            if msg is None or msg.kind == T.KIND_CONTROL:
                break
            w.send(
                msg.payload + bytes([rank]), seq=msg.seq, epoch=msg.epoch
            )
        w.close()

    threads = [
        __import__("threading").Thread(target=worker, args=(r,), daemon=True)
        for r in range(2)
    ]
    for t in threads:
        t.start()
    try:
        coord.accept(timeout=10)
        assert coord.poll(0) is None  # nothing in flight yet
        coord.isend(0, b"abc", seq=7, epoch=3)
        coord.isend(1, b"xy", seq=8, epoch=3)
        for _ in range(2):
            rank, msg = coord.waitany([0, 1], timeout=10)
            results[rank] = msg
        assert results[0].payload == b"abc\x00"
        assert results[0].seq == 7 and results[0].epoch == 3
        assert results[1].payload == b"xy\x01"
        # waitany over an already-drained set times out rather than hangs
        assert coord.waitany([0, 1], timeout=0.05) is None
        for r in range(2):
            coord.isend(r, b"", kind=T.KIND_CONTROL)
        for t in threads:
            t.join(timeout=5)
    finally:
        coord.close()


def test_transport_large_payload():
    """Multi-MB frames exercise the partial-read/write state machine
    (payloads far exceed socket buffers)."""
    import threading

    coord, path = _transport_pair(1)

    def worker():
        w = T.Worker(path, 0)
        msg = w.recv()
        w.send(msg.payload[::-1], seq=msg.seq)
        w.recv()  # control
        w.close()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        coord.accept(timeout=10)
        blob = np.random.default_rng(0).bytes(8 * 1024 * 1024)
        coord.isend(0, blob, seq=1)
        rank, msg = coord.waitany([0], timeout=30)
        assert rank == 0 and msg.payload == blob[::-1]
        coord.isend(0, b"", kind=T.KIND_CONTROL)
        t.join(timeout=5)
    finally:
        coord.close()


def test_shm_region_pinned_by_live_view_survives_keep_window():
    """Regression (ISSUE 6 satellite): a worker that HOLDS a shm
    ``Message.body`` view across more than ``_shm_keep`` newer
    broadcasts must keep that region mapped and readable — eviction
    raises ``BufferError`` on the pinned mmap, defers to a later
    resolve, and catches up the moment the view is released. Before
    the keep-window hardening this was a use-after-unmap segfault
    scenario; the region dict must also stay bounded (keep + pinned),
    never growing with every broadcast."""
    import threading

    coord, path = _transport_pair(2)
    payloads = [
        np.full(1 << 20, i, np.uint8) for i in range(8)
    ]  # >= 1 MiB each: the shm broadcast path
    done = threading.Event()
    state: dict = {}

    def pinned_worker():
        w = T.Worker(path, 0)
        keep = w._shm_keep
        first = w.recv()
        assert first.body is not None, "broadcast did not ride shm"
        pinned = first.body  # LIVE view held across every broadcast
        for i in range(1, len(payloads)):
            msg = w.recv()
            assert msg.body is not None
            assert bytes(msg.body[:4]) == bytes([i] * 4)
            del msg
        # the pinned region is still mapped and byte-correct
        assert bytes(pinned[:4]) == b"\x00" * 4
        assert bytes(pinned[-4:]) == b"\x00" * 4
        # bounded: keep-window regions + the one pinned survivor
        state["n_regions_pinned"] = len(w._shm_regions)
        assert len(w._shm_regions) <= keep + 1
        del pinned, first
        # released: the next resolve sweeps the dict back to the window
        w.recv()
        state["n_regions_released"] = len(w._shm_regions)
        assert len(w._shm_regions) <= keep
        w.recv()  # control: done
        w.close()
        done.set()

    def drain_worker():
        # second rank only exists so the coordinator takes the shm
        # broadcast path (n_workers >= 2); it drains and exits
        w = T.Worker(path, 1)
        while True:
            msg = w.recv()
            if msg is None or msg.kind == T.KIND_CONTROL:
                break
        w.close()

    threads = [
        threading.Thread(target=pinned_worker, daemon=True),
        threading.Thread(target=drain_worker, daemon=True),
    ]
    for t in threads:
        t.start()
    try:
        coord.accept(timeout=10)
        for i, body in enumerate(payloads):
            pl = coord.payload(body)
            assert isinstance(pl, T.ShmPayload), "memfd unavailable?"
            for rank in range(2):
                assert coord.isend_shared(rank, b"hdr", pl, seq=i)
            pl.release()
        extra = coord.payload(payloads[0])
        assert coord.isend_shared(0, b"hdr", extra, seq=len(payloads))
        extra.release()
        for rank in range(2):
            coord.isend(rank, b"", kind=T.KIND_CONTROL)
        assert done.wait(timeout=30), "pinned worker did not finish"
        for t in threads:
            t.join(timeout=10)
        assert state["n_regions_pinned"] > state["n_regions_released"]
    finally:
        coord.close()


def test_transport_dead_peer_is_sticky():
    """A disconnected worker polls ready with a death marker forever —
    the anti-hang property the reference's Waitall! lacks (SURVEY §5).

    The connect runs in a thread: since the hello exchange became a
    round trip (auth ack), ``Worker()`` blocks until the coordinator's
    ``accept`` admits the rank, so constructing it on the accept thread
    would deadlock."""
    import threading

    coord, path = _transport_pair(1)
    connected = []

    def connect():
        connected.append(T.Worker(path, 0))

    t = threading.Thread(target=connect, daemon=True)
    t.start()
    try:
        coord.accept(timeout=10)
        t.join(timeout=10)
        connected[0].close()  # peer vanishes
        rank, msg = coord.waitany([0], timeout=10)
        assert rank == 0 and msg.kind == T.KIND_DEATH
        assert coord.is_dead(0)
        # sticky: polls keep reporting death, sends fail fast
        assert coord.poll(0).kind == T.KIND_DEATH
        assert not coord.isend(0, b"data")
    finally:
        coord.close()


# ------------------------------------------------------------------- pool


@pytest.mark.slow
def test_full_gather_and_epoch_echo():
    n = 3
    backend = NativeProcessBackend(_echo, n)
    try:
        pool = AsyncPool(n)
        sendbuf = np.array([3.14])
        recvbuf = np.zeros(3 * n)
        for epoch in range(1, 4):
            sendbuf[0] = epoch
            repochs = asyncmap(pool, sendbuf, backend, recvbuf, nwait=n)
            chunks = recvbuf.reshape(n, 3)
            assert list(repochs) == [epoch] * n
            for i in range(n):
                assert chunks[i][0] == i + 1  # chunk j <- worker j
                assert chunks[i][1] == float(epoch)
                assert chunks[i][2] == epoch  # epoch echo
    finally:
        backend.shutdown()
    # shutdown() joins and close()s EVERY Process handle; a closed
    # handle raising on inspection IS the deterministic-release signal
    for proc in backend._procs:
        with pytest.raises(ValueError):
            proc.is_alive()


@pytest.mark.slow
def test_fastest_k_skips_straggler():
    n = 3
    backend = NativeProcessBackend(_echo, n, delay_fn=StragglerDelay(2))
    try:
        pool = AsyncPool(n)
        sendbuf = np.zeros(1)
        for epoch in range(1, 5):
            sendbuf[0] = epoch
            repochs = asyncmap(pool, sendbuf, backend, nwait=2)
            assert int((repochs == epoch).sum()) >= 2
            assert repochs[0] == epoch and repochs[1] == epoch
        assert pool.active[2]  # straggler still tasked
        waitall(pool, backend)
        assert not pool.active.any()
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_remote_exception_carries_traceback():
    n = 3
    backend = NativeProcessBackend(_fail_worker1_epoch2, n)
    try:
        pool = AsyncPool(n)
        payload = np.array([1.0])
        asyncmap(pool, payload, backend, nwait=n)  # epoch 1 fine
        with pytest.raises(WorkerFailure) as excinfo:
            asyncmap(pool, payload, backend, nwait=n)
            waitall(pool, backend)
        err = excinfo.value.error
        assert isinstance(err, RemoteWorkerError)
        assert err.exc_type == "ValueError"
        assert "boom from native worker" in str(err)
        assert "Traceback" in err.remote_traceback
        waitall(pool, backend)  # pool stays recoverable
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_tcp_transport_pool_roundtrip():
    """The multi-host path: same pool, TCP loopback instead of a Unix
    socket (port 0 -> ephemeral, resolved via backend.address)."""
    n = 3
    backend = NativeProcessBackend(
        _echo, n, address="tcp://127.0.0.1:0"
    )
    try:
        assert backend.address.startswith("tcp://127.0.0.1:")
        assert not backend.address.endswith(":0")  # ephemeral resolved
        pool = AsyncPool(n)
        sendbuf = np.array([2.5])
        recvbuf = np.zeros(3 * n)
        repochs = asyncmap(pool, sendbuf, backend, recvbuf, nwait=n)
        assert list(repochs) == [1] * n
        chunks = recvbuf.reshape(n, 3)
        for i in range(n):
            assert chunks[i][0] == i + 1 and chunks[i][1] == 2.5
    finally:
        backend.shutdown()


def _spawn_cli_worker(address, rank):
    """Launch `python -m mpistragglers_jl_tpu.worker` as a real external
    process — exactly what a remote host would run."""
    import subprocess
    import sys

    tests_dir = os.path.dirname(os.path.abspath(__file__))
    repo_root = os.path.dirname(tests_dir)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root, tests_dir, env.get("PYTHONPATH", "")]
    )
    return subprocess.Popen(
        [
            sys.executable, "-m", "mpistragglers_jl_tpu.worker",
            "--address", address, "--rank", str(rank),
            "--work", "test_backend_native:_echo",
        ],
        cwd=tests_dir,
        env=env,
    )


@pytest.mark.slow
def test_external_workers_over_cli():
    """spawn=False + `python -m mpistragglers_jl_tpu.worker`: the
    multi-host deployment model (coordinator binds TCP, workers join
    from outside; the reference's analog is mpiexec + a hostfile).
    accept=False defers the handshake so the ephemeral port is known
    before the workers launch — no hard-coded port to collide on."""
    n = 2
    backend = NativeProcessBackend(
        None, n, spawn=False, address="tcp://127.0.0.1:0", accept=False,
    )
    procs = [_spawn_cli_worker(backend.address, r) for r in range(n)]
    backend.accept(timeout=60)
    try:
        pool = AsyncPool(n)
        repochs = asyncmap(pool, np.array([7.0]), backend, nwait=n)
        assert list(repochs) == [1] * n
        for i in range(n):
            out = np.asarray(pool.results[i])
            assert out[0] == i + 1 and out[1] == 7.0 and out[2] == 1
    finally:
        backend.shutdown()
        for p in procs:
            p.wait(timeout=10)


@pytest.mark.slow
def test_direct_dispatch_snapshots_despite_mutation():
    """Direct Backend-API use (no begin_epoch): every dispatch must
    snapshot the payload at call time — in-place mutation between two
    same-epoch dispatches must not leak cached bytes."""
    backend = NativeProcessBackend(_echo, 2)
    try:
        buf = np.array([1.0])
        backend.dispatch(0, buf, 1)
        buf[0] = 2.0  # mutate before the second same-epoch dispatch
        backend.dispatch(1, buf, 1)
        r0 = backend.wait(0, timeout=30)
        r1 = backend.wait(1, timeout=30)
        assert np.asarray(r0)[1] == 1.0  # worker 0 saw pre-mutation value
        assert np.asarray(r1)[1] == 2.0  # worker 1 saw the mutation
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_direct_dispatch_after_asyncmap_snapshots_mutation():
    """The cache armed inside asyncmap must be disarmed when it returns:
    a manual dispatch at the SAME epoch with a mutated buffer sees the
    new bytes (end_epoch hook)."""
    backend = NativeProcessBackend(_echo, 2)
    try:
        pool = AsyncPool(2)
        buf = np.array([1.0])
        asyncmap(pool, buf, backend, nwait=2)
        buf[0] = 99.0
        backend.dispatch(0, buf, pool.epoch)  # manual re-task, same epoch
        r0 = backend.wait(0, timeout=30)
        assert np.asarray(r0)[1] == 99.0
    finally:
        backend.shutdown()


def test_dispatch_before_accept_raises_not_hangs():
    backend = NativeProcessBackend(
        None, 1, spawn=False, address="tcp://127.0.0.1:0", accept=False
    )
    try:
        with pytest.raises(RuntimeError, match="handshake incomplete"):
            backend.dispatch(0, np.zeros(1), 1)
        with pytest.raises(RuntimeError, match="handshake incomplete"):
            backend.wait_any([0])
    finally:
        backend.shutdown()


def test_malformed_tcp_address_fails_at_create():
    # "tcp://host:5O55" (letter O) must be a bind error NOW, not a unix
    # path or a silent ephemeral port + connect timeout later
    for bad in ("tcp://127.0.0.1:5O55", "tcp://127.0.0.1", "tcp://:123"):
        with pytest.raises(T.TransportError, match="could not bind"):
            T.Coordinator(bad, 1)


def _raise_on_unpickle():
    raise RuntimeError("boom on unpickle")


class ExplodingPayload:
    """Pickles fine on the coordinator, raises when the worker loads it
    — the shape of the classic multi-host serialization mismatch."""

    def __reduce__(self):
        return (_raise_on_unpickle, ())


def test_undeserializable_payload_ships_error_not_dead_worker():
    """A payload that cannot unpickle in the worker must come back as a
    WorkerFailure with the real exception, not a dead rank."""
    backend = NativeProcessBackend(_echo, 1)
    try:
        pool = AsyncPool(1)
        with pytest.raises(WorkerFailure) as excinfo:
            asyncmap(pool, ExplodingPayload(), backend, nwait=1)
        err = excinfo.value.error
        assert isinstance(err, RemoteWorkerError)
        assert err.exc_type == "RuntimeError"
        assert "boom on unpickle" in str(err)
        # the rank survived: next epoch with a good payload works
        repochs = asyncmap(pool, np.array([1.0]), backend, nwait=1, epoch=5)
        assert list(repochs) == [5]
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_asyncmap_timeout_over_native_transport():
    from mpistragglers_jl_tpu import DeadWorkerError

    n = 2
    backend = NativeProcessBackend(
        _echo, n, delay_fn=StragglerDelay(1, slow=0.8)
    )
    try:
        pool = AsyncPool(n)
        with pytest.raises(DeadWorkerError) as excinfo:
            asyncmap(pool, np.zeros(1), backend, nwait=n, timeout=0.2)
        # worker 0's first round-trip may also miss the window on a
        # loaded machine; only the straggler is guaranteed outstanding
        assert 1 in excinfo.value.dead
        waitall(pool, backend)  # drains the tardy worker(s); pool reusable
        repochs = asyncmap(pool, np.zeros(1), backend, nwait=1)
        assert int((repochs == pool.epoch).sum()) >= 1
        waitall(pool, backend)
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_rapid_fire_epochs_over_native_transport():
    """100 back-to-back epochs with mixed nwait forms shake out protocol
    races (seq guards, drain/dispatch interleaving) on the C++ path."""
    n = 3
    backend = NativeProcessBackend(_echo, n)
    try:
        pool = AsyncPool(n)
        sendbuf = np.zeros(1)
        for epoch in range(1, 101):
            sendbuf[0] = epoch
            nwait = (epoch % n) + 1  # cycles 1..n
            repochs = asyncmap(pool, sendbuf, backend, nwait=nwait)
            assert int((repochs == epoch).sum()) >= nwait
            for i in range(n):  # echo integrity on every heard worker
                if pool.results[i] is not None:
                    assert np.asarray(pool.results[i])[2] == repochs[i]
        waitall(pool, backend)
        assert not pool.active.any()
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_backend_lifecycle_does_not_leak_fds():
    """Create/drive/shutdown many native backends: the process fd count
    must come back down (sockets, epoll, eventfd all released)."""
    import gc

    def nfds():
        return len(os.listdir("/proc/self/fd"))

    def cycle():
        b = NativeProcessBackend(_echo, 2)
        try:
            pool = AsyncPool(2)
            asyncmap(pool, np.zeros(1), b, nwait=2)
            waitall(pool, b)
        finally:
            b.shutdown()

    cycle()  # warm up one-time module/library fds before sampling
    gc.collect()
    base = nfds()
    for _ in range(10):
        cycle()
    gc.collect()
    assert nfds() <= base + 3, (
        f"fd count grew {base} -> {nfds()}: transport leaking descriptors"
    )


def test_resolve_callable():
    from mpistragglers_jl_tpu.worker import resolve_callable

    fn = resolve_callable("numpy:linalg.norm")
    assert fn is np.linalg.norm
    with pytest.raises(ValueError, match="module:attribute"):
        resolve_callable("numpy.linalg.norm")
    with pytest.raises(TypeError, match="non-callable"):
        resolve_callable("numpy:pi")


def test_parse_ranks():
    from mpistragglers_jl_tpu.worker import parse_ranks

    assert parse_ranks("3") == [3]
    assert parse_ranks("0-3") == [0, 1, 2, 3]
    assert parse_ranks("0,2,5-7") == [0, 2, 5, 6, 7]
    with pytest.raises(ValueError, match="descending"):
        parse_ranks("5-2")
    with pytest.raises(ValueError, match="duplicate"):
        parse_ranks("1,1")


@pytest.mark.slow
def test_cli_serves_multiple_ranks_one_command():
    """One `-m ...worker --ranks 0-1` process serves both ranks (the
    one-command-per-host deployment shape)."""
    import subprocess
    import sys

    tests_dir = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(tests_dir), tests_dir, env.get("PYTHONPATH", "")]
    )
    backend = NativeProcessBackend(
        None, 2, spawn=False, address="tcp://127.0.0.1:0", accept=False
    )
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "mpistragglers_jl_tpu.worker",
            "--address", backend.address, "--ranks", "0-1",
            "--work", "test_backend_native:_echo",
        ],
        cwd=tests_dir, env=env,
    )
    try:
        backend.accept(timeout=60)
        pool = AsyncPool(2)
        repochs = asyncmap(pool, np.array([4.0]), backend, nwait=2)
        assert list(repochs) == [1, 1]
        for i in range(2):
            out = np.asarray(pool.results[i])
            assert out[0] == i + 1 and out[1] == 4.0
    finally:
        backend.shutdown()
        proc.wait(timeout=15)


@pytest.mark.slow
def test_respawn_recovers_crashed_rank():
    """Elastic recovery: a crashed rank is replaced in place and the
    pool keeps the same index space (new capability over the reference,
    whose dead ranks are permanent — SURVEY §5)."""
    n = 3
    backend = NativeProcessBackend(_exit_on_negative, n)
    try:
        pool = AsyncPool(n)
        with pytest.raises(WorkerFailure):
            asyncmap(pool, np.array([-1.0]), backend, nwait=n)
            waitall(pool, backend)
        waitall(pool, backend)  # drain survivors
        # EOF is observed before the child is reapable; join to avoid
        # racing the OS-level process teardown
        backend._procs[1].join(timeout=10)
        assert not backend._procs[1].is_alive()
        with pytest.raises(RuntimeError):
            backend.respawn(0)  # alive rank: refuse
        backend.respawn(1)
        for epoch in (10, 11):
            repochs = asyncmap(
                pool, np.array([float(epoch)]), backend,
                nwait=n, epoch=epoch,
            )
            assert list(repochs) == [epoch] * n
        assert np.asarray(pool.results[1])[0] == 2.0  # new incarnation works
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_use_after_shutdown_raises_not_segfaults():
    backend = NativeProcessBackend(_echo, 2)
    pool = AsyncPool(2)
    asyncmap(pool, np.array([1.0]), backend, nwait=2)
    backend.shutdown()
    with pytest.raises(RuntimeError):
        backend.dispatch(0, np.array([2.0]), 2)
    with pytest.raises(RuntimeError):
        backend.test(0)
    with pytest.raises(RuntimeError):
        backend.wait_any([0, 1])
    backend.shutdown()  # idempotent


@pytest.mark.slow
def test_dead_worker_fails_fast_not_hangs():
    n = 3
    backend = NativeProcessBackend(_exit_worker2, n)
    try:
        pool = AsyncPool(n)
        with pytest.raises(WorkerFailure) as excinfo:
            asyncmap(pool, np.array([1.0]), backend, nwait=n)
            waitall(pool, backend)
        assert isinstance(excinfo.value.error, WorkerProcessDied)
        assert excinfo.value.error.worker == 2
        # re-dispatch to the dead rank fails fast too (synthetic failure)
        with pytest.raises(WorkerFailure):
            asyncmap(pool, np.array([2.0]), backend, nwait=n)
            waitall(pool, backend)
    finally:
        backend.shutdown()


# ------------------------------------------------------------------- auth


def test_hmac_conformance_against_stdlib():
    """The native HMAC-SHA256 the handshake trusts must match RFC 2104
    (checked against the stdlib implementation, including the >64-byte
    key-hashing path)."""
    import hashlib
    import hmac as stdlib_hmac

    for key, msg in [
        (b"key", b"The quick brown fox jumps over the lazy dog"),
        (b"", b""),
        (b"k" * 100, b"m" * 1000),  # key longer than the SHA-256 block
        (b"secret", bytes(range(256)) * 3),
    ]:
        want = stdlib_hmac.new(key, msg, hashlib.sha256).digest()
        assert T.hmac_sha256(key, msg) == want


def test_auth_token_roundtrip():
    """Workers holding the shared secret are admitted and serve."""
    import tempfile
    import threading
    import uuid

    path = os.path.join(
        tempfile.gettempdir(), f"msgt-auth-{uuid.uuid4().hex[:8]}.sock"
    )
    coord = T.Coordinator(path, 2, token=b"s3cret")

    def worker(rank):
        w = T.Worker(path, rank, token=b"s3cret")
        msg = w.recv()
        if msg is not None and msg.kind == T.KIND_DATA:
            w.send(msg.payload + bytes([rank]), seq=msg.seq)
            w.recv()  # control
        w.close()

    threads = [
        __import__("threading").Thread(target=worker, args=(r,), daemon=True)
        for r in range(2)
    ]
    for t in threads:
        t.start()
    try:
        coord.accept(timeout=10)
        coord.isend(0, b"a", seq=1)
        coord.isend(1, b"b", seq=1)
        got = {}
        for _ in range(2):
            rank, msg = coord.waitany([0, 1], timeout=10)
            got[rank] = msg.payload
        assert got == {0: b"a\x00", 1: b"b\x01"}
        for r in range(2):
            coord.isend(r, b"", kind=T.KIND_CONTROL)
        for t in threads:
            t.join(timeout=5)
    finally:
        coord.close()


def test_auth_rejects_wrong_and_missing_token():
    """A connector without the right secret is refused: its connect
    fails, and the coordinator handshake never admits it."""
    import tempfile
    import threading
    import uuid

    path = os.path.join(
        tempfile.gettempdir(), f"msgt-auth-{uuid.uuid4().hex[:8]}.sock"
    )
    coord = T.Coordinator(path, 1, token=b"right")
    outcomes = []

    def bad_worker(token):
        try:
            w = T.Worker(path, 0, token=token)
        except T.TransportError:
            # no token: can't answer the challenge, fails at connect.
            # wrong token: round 3's MUTUAL handshake also fails at
            # connect — the coordinator rejects the worker's proof and
            # closes before sending its own, so the worker never
            # receives the coordinator proof it now requires
            outcomes.append("refused-at-connect")
            return
        outcomes.append("admitted")  # must not happen
        w.close()

    threads = [
        threading.Thread(target=bad_worker, args=(tok,), daemon=True)
        for tok in (b"wrong", b"")
    ]
    for t in threads:
        t.start()
    try:
        with pytest.raises(T.TransportError):
            coord.accept(timeout=1.0)  # no impostor is ever admitted
        for t in threads:
            t.join(timeout=10)
        assert outcomes == ["refused-at-connect", "refused-at-connect"]
    finally:
        coord.close()


def test_worker_rejects_rogue_coordinator():
    """ADVICE r2 (medium): the handshake is mutual. A rogue listener
    that wins the bind race and ISSUES a well-formed challenge — the
    exact scenario one-way auth waved through — must be rejected by
    the worker, because it cannot produce HMAC(token, 0x02||W) for the
    worker's own challenge W. The worker must fail at connect and
    never enter the data phase (where frames get unpickled)."""
    import socket
    import struct
    import tempfile
    import threading
    import uuid

    path = os.path.join(
        tempfile.gettempdir(), f"msgt-rogue-{uuid.uuid4().hex[:8]}.sock"
    )
    HDR = struct.Struct("<5q")  # len, seq, epoch, tag, kind (KIND_HELLO=2)
    saw = {}
    bound = threading.Event()

    def rogue():
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(1)
        srv.settimeout(10)
        bound.set()
        conn, _ = srv.accept()
        conn.settimeout(10)
        try:
            def recv_exact(n):
                # NOT recv(MSG_WAITALL): under load it has been seen
                # returning short on a socket with a timeout, leaking
                # the response's tail into the post-handshake read and
                # failing the no-data assertion below for the wrong
                # reason
                buf = b""
                while len(buf) < n:
                    chunk = conn.recv(n - len(buf))
                    if not chunk:
                        break
                    buf += chunk
                return buf

            hello = recv_exact(HDR.size)
            saw["hello"] = HDR.unpack(hello)
            # issue a perfectly-formed 16-byte challenge like a real
            # coordinator would
            conn.sendall(HDR.pack(16, 0, 0, 0, 2) + b"C" * 16)
            # the worker answers mac(32) + its challenge W(16)
            resp = recv_exact(HDR.size + 48)
            saw["resp_len"] = HDR.unpack(resp[: HDR.size])[0]
            # ...but we don't know the token: send a garbage proof
            conn.sendall(HDR.pack(32, 0, 0, 0, 2) + b"X" * 32)
            # if the worker were fooled it would proceed to the data
            # phase; give it a beat, then see if it sent anything more
            conn.settimeout(1.0)
            try:
                saw["post"] = conn.recv(4096)
            except socket.timeout:
                saw["post"] = b""
        finally:
            conn.close()
            srv.close()

    t = threading.Thread(target=rogue, daemon=True)
    t.start()
    assert bound.wait(timeout=10)
    with pytest.raises(T.TransportError):
        T.Worker(path, 0, token=b"s3cret")
    t.join(timeout=15)
    assert saw["hello"][4] == 2  # worker sent a hello
    assert saw["resp_len"] == 48  # mac + worker challenge: mutual form
    assert saw.get("post", b"") == b""  # no data ever followed


@pytest.mark.slow
def test_spawned_backend_auto_auth_end_to_end():
    """spawn=True generates a per-backend secret automatically; the
    spawned workers inherit it and the pool works unchanged."""
    backend = NativeProcessBackend(_echo, 2)
    try:
        assert backend._token  # auto-generated, non-empty
        pool = AsyncPool(2)
        asyncmap(pool, np.array([5.0]), backend, nwait=2)
        assert np.asarray(pool.results[0])[1] == 5.0
    finally:
        backend.shutdown()


def test_concurrent_restarts_park_other_ranks_hello():
    """Two external workers restarting at once must both be recoverable:
    rank B's reconnect landing during reaccept(A) is parked, not closed,
    and reaccept(B) adopts the parked socket (ADVICE round 1)."""
    import tempfile
    import threading
    import time as time_mod
    import uuid

    path = os.path.join(
        tempfile.gettempdir(), f"msgt-park-{uuid.uuid4().hex[:8]}.sock"
    )
    coord = T.Coordinator(path, 2, token=b"tok")

    class EchoThread(threading.Thread):
        def __init__(self, rank, die_after: int):
            super().__init__(daemon=True)
            self.rank, self.die_after = rank, die_after

        def run(self):
            w = T.Worker(path, self.rank, token=b"tok")
            served = 0
            while True:
                msg = w.recv()
                if msg is None or msg.kind == T.KIND_CONTROL:
                    break
                w.send(msg.payload, seq=msg.seq, epoch=msg.epoch)
                served += 1
                if self.die_after and served >= self.die_after:
                    break  # simulated crash: close without shutdown
            w.close()

    gen1 = [EchoThread(r, die_after=1) for r in range(2)]
    for t in gen1:
        t.start()
    try:
        coord.accept(timeout=10)
        for r in range(2):
            coord.isend(r, b"x", seq=1)
        for _ in range(2):
            coord.waitany([0, 1], timeout=10)
        for t in gen1:
            t.join(timeout=5)
        # both ranks are now dead; wait for the progress engine's marks
        deadline = time_mod.time() + 5
        while not (coord.is_dead(0) and coord.is_dead(1)):
            assert time_mod.time() < deadline, "death marks never arrived"
            time_mod.sleep(0.01)
        # drain the death markers
        while coord.poll(0) and coord.poll(0).kind != T.KIND_DEATH:
            pass
        while coord.poll(1) and coord.poll(1).kind != T.KIND_DEATH:
            pass
        # both restart concurrently; their hellos race into the backlog
        gen2 = [EchoThread(r, die_after=0) for r in range(2)]
        for t in gen2:
            t.start()
        time_mod.sleep(0.2)  # let both connects land before reaccept
        coord.reaccept(0, timeout=10)  # may park rank 1's hello
        coord.reaccept(1, timeout=10)  # adopts the parked socket
        for r in range(2):
            assert not coord.is_dead(r)
            coord.isend(r, bytes([r]), seq=2)
        got = {}
        for _ in range(2):
            rank, msg = coord.waitany([0, 1], timeout=10)
            got[rank] = msg.payload
        assert got == {0: b"\x00", 1: b"\x01"}
        for r in range(2):
            coord.isend(r, b"", kind=T.KIND_CONTROL)
        for t in gen2:
            t.join(timeout=5)
    finally:
        coord.close()


def test_worker_connect_retries_until_coordinator_binds():
    """run_worker's connect loop retries: a worker started before the
    coordinator binds still joins (ADVICE round 1: one dropped/early
    handshake must not permanently lose the rank)."""
    import tempfile
    import threading
    import time as time_mod
    import uuid

    from mpistragglers_jl_tpu.worker import run_worker

    path = os.path.join(
        tempfile.gettempdir(), f"msgt-retry-{uuid.uuid4().hex[:8]}.sock"
    )

    def serve():
        run_worker(path, 0, lambda r, p, e: p + 1, connect_timeout=10)

    t = threading.Thread(target=serve, daemon=True)
    t.start()  # connects BEFORE the coordinator exists
    time_mod.sleep(0.3)
    backend = NativeProcessBackend(
        None, 1, spawn=False, address=path, connect_timeout=10
    )
    try:
        pool = AsyncPool(1)
        asyncmap(pool, 41, backend, nwait=1)
        assert pool.results[0] == 42
    finally:
        backend.shutdown()
        t.join(timeout=5)


def test_shutdown_fast_when_handshake_never_completed():
    """shutdown() with accept=False terminates spawned workers
    immediately instead of burning join_timeout per worker
    (ADVICE round 1)."""
    import time as time_mod

    backend = NativeProcessBackend(
        _echo, 3, accept=False, join_timeout=5.0
    )
    t0 = time_mod.perf_counter()
    backend.shutdown()
    elapsed = time_mod.perf_counter() - t0
    assert elapsed < 4.0, f"shutdown took {elapsed:.1f}s (join-timeout stall)"


def test_token_holding_worker_refuses_open_coordinator():
    """Fail closed against a downgrade: a worker configured with a
    secret must refuse a peer that acks the hello as an *open*
    transport — the connect-retry loop makes the bind race winnable by
    a rogue listener, and unpickling its frames would be code
    execution (round-2 review finding)."""
    import threading

    coord, path = _transport_pair(1)  # open: no token
    outcome = []

    def connect():
        try:
            T.Worker(path, 0, token=b"must-be-authenticated")
        except T.TransportError:
            outcome.append("refused")
        else:  # pragma: no cover - the failure this test exists to catch
            outcome.append("downgraded")

    t = threading.Thread(target=connect, daemon=True)
    t.start()
    try:
        # the open coordinator may briefly admit the rank before the
        # worker walks away (the refusal is worker-side, by design);
        # either way no authenticated session ever exists
        try:
            coord.accept(timeout=1.0)
        except T.TransportError:
            pass
        t.join(timeout=10)
        assert outcome == ["refused"]
    finally:
        coord.close()


def _tagged_sleep_echo(i, payload, epoch):
    import time as time_mod

    time_mod.sleep(float(payload[1]))
    return float(payload[0])


def test_native_wait_any_duplicate_index_two_tags():
    """wait_any([i, i], tags=[a, b]) must honor BOTH channels of one
    worker (SlotBackend does; the native router must too)."""
    backend = NativeProcessBackend(_tagged_sleep_echo, 1)
    try:
        backend.dispatch(0, np.array([10.0, 0.3]), 1, tag=0)
        backend.dispatch(0, np.array([20.0, 0.0]), 1, tag=1)
        got = {}
        for _ in range(2):
            j, result = backend.wait_any([0, 0], timeout=15, tags=[0, 1])
            assert j == 0
            got[float(result)] = True
        assert sorted(got) == [10.0, 20.0]
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_on_dead_straggle_spawned_workers():
    """on_dead="straggle": a crashed spawned worker becomes an infinite
    straggler — fastest-k epochs keep making progress with NO error
    raised, and respawn + pool.reset_worker rejoins the rank."""
    n = 3
    backend = NativeProcessBackend(
        _exit_on_negative, n, on_dead="straggle"
    )
    try:
        pool = AsyncPool(n)
        sendbuf = np.array([1.0])
        asyncmap(pool, sendbuf, backend, nwait=n)
        # worker 1 self-destructs on the negative payload
        sendbuf[0] = -1.0
        asyncmap(pool, sendbuf, backend, nwait=2, epoch=2)
        assert sorted(pool.fresh_indices(2).tolist()) == [0, 2]
        # subsequent epochs: no failures, survivors answer, rank 1 stays
        # an in-flight straggler
        sendbuf[0] = 3.0
        for ep in (3, 4):
            repochs = asyncmap(pool, sendbuf, backend, nwait=2, epoch=ep)
            assert sorted(pool.fresh_indices(ep).tolist()) == [0, 2]
            assert repochs[1] != ep
        assert pool.active[1]
        # a bounded waitall times out naming the dead rank, not hanging
        from mpistragglers_jl_tpu.pool import DeadWorkerError

        with pytest.raises(DeadWorkerError):
            waitall(pool, backend, timeout=1.0)
        # elastic recovery: respawn + reset, the rank rejoins fully
        backend.respawn(1)
        pool.reset_worker(1)  # the lost dispatch can never complete
        asyncmap(pool, sendbuf, backend, nwait=n, epoch=5)
        assert sorted(pool.fresh_indices(5).tolist()) == [0, 1, 2]
        waitall(pool, backend, timeout=10.0)
    finally:
        backend.shutdown()


def test_native_cross_process_telemetry_aggregation():
    """registry= on the native backend: worker.py's loop (run by the
    spawned processes) piggybacks telemetry frames on the reserved OBS
    tag; the coordinator merges them under worker= labels with
    clock-aligned per-task spans, and the frames never disturb the
    pool's completions (every epoch still harvests normally)."""
    from mpistragglers_jl_tpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    backend = NativeProcessBackend(_echo, 2, registry=reg)
    try:
        pool = AsyncPool(2)
        for _ in range(3):
            asyncmap(pool, np.ones(3), backend, nwait=2)
        waitall(pool, backend)
    finally:
        backend.shutdown()
    for r in range(2):
        c = reg.counter("worker_tasks_total", worker=str(r))
        assert c.value == 3
        h = reg.histogram("worker_task_seconds", worker=str(r))
        assert h.count == 3
    recs = backend.aggregator.recorders()
    assert [r.process for r in recs] == ["worker 0", "worker 1"]
    assert all(len(r.spans) == 3 for r in recs)
    # clock offset estimated from the send/recv stamp pairs (same
    # host: perf_counter is system-wide monotonic, so it is tiny)
    off = backend.aggregator.clock_offset(0)
    assert off is not None and abs(off) < 0.5
