"""Core pool on the OS-process backend — the reference's execution model.

The reference only ever runs as real OS processes under mpiexec
(test/runtests.jl:17); ProcessBackend reproduces that process isolation
(spawned workers, pickled payloads over pipes) while keeping assertions
coordinator-side instead of losing them inside subprocesses (SURVEY §4).
Everything here must be module-level picklable for spawn.
"""

import os

import numpy as np
import pytest

from mpistragglers_jl_tpu import (
    AsyncPool,
    ProcessBackend,
    WorkerFailure,
    asyncmap,
    waitall,
)
from mpistragglers_jl_tpu.backends.process import (
    RemoteWorkerError,
    WorkerProcessDied,
)


def _echo(i, payload, epoch):
    # the reference's result message layout [rank, t, epoch]
    # (test/kmap2.jl:92-94)
    return np.array([float(i + 1), float(payload[0]), float(epoch)])


def _fail_worker1_epoch2(i, payload, epoch):
    if i == 1 and epoch == 2:
        raise ValueError("boom from worker process")
    return np.array([float(i + 1), float(payload[0]), float(epoch)])


def _exit_worker2(i, payload, epoch):
    if i == 2:
        os._exit(3)  # simulate a crashed rank, not a Python exception
    return np.array([float(i + 1), float(payload[0]), float(epoch)])


class StragglerDelay:
    """Picklable deterministic delay: one slow worker, the rest fast."""

    def __init__(self, straggler: int, slow: float = 0.25, fast: float = 0.001):
        self.straggler = straggler
        self.slow = slow
        self.fast = fast

    def __call__(self, i: int, epoch: int) -> float:
        return self.slow if i == self.straggler else self.fast


@pytest.mark.slow
def test_full_gather_and_epoch_echo():
    n = 3
    backend = ProcessBackend(_echo, n)
    try:
        pool = AsyncPool(n)
        sendbuf = np.array([3.14])
        recvbuf = np.zeros(3 * n)
        for epoch in range(1, 4):
            sendbuf[0] = epoch
            repochs = asyncmap(pool, sendbuf, backend, recvbuf, nwait=n)
            chunks = recvbuf.reshape(n, 3)
            assert list(repochs) == [epoch] * n
            for i in range(n):
                assert chunks[i][0] == i + 1  # chunk j <- worker j
                assert chunks[i][1] == float(epoch)  # payload crossed intact
                assert chunks[i][2] == epoch  # epoch echo
    finally:
        backend.shutdown()
    # shutdown() joins and close()s EVERY Process handle; a closed
    # handle raising on inspection IS the deterministic-release signal
    for proc in backend._procs:
        with pytest.raises(ValueError):
            proc.is_alive()


@pytest.mark.slow
def test_fastest_k_skips_straggler_process():
    n = 3
    backend = ProcessBackend(_echo, n, delay_fn=StragglerDelay(2))
    try:
        pool = AsyncPool(n)
        sendbuf = np.zeros(1)
        for epoch in range(1, 5):
            sendbuf[0] = epoch
            repochs = asyncmap(pool, sendbuf, backend, nwait=2)
            fresh = int((repochs == epoch).sum())
            assert fresh >= 2
            assert repochs[0] == epoch and repochs[1] == epoch
        # straggler never made an epoch deadline but stays tasked
        assert pool.active[2]
        waitall(pool, backend)
        assert not pool.active.any()
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_remote_exception_carries_traceback():
    n = 3
    backend = ProcessBackend(_fail_worker1_epoch2, n)
    try:
        pool = AsyncPool(n)
        payload = np.array([1.0])
        asyncmap(pool, payload, backend, nwait=n)  # epoch 1 fine
        with pytest.raises(WorkerFailure) as excinfo:
            asyncmap(pool, payload, backend, nwait=n)
            waitall(pool, backend)
        err = excinfo.value.error
        assert isinstance(err, RemoteWorkerError)
        assert err.exc_type == "ValueError"
        assert "boom from worker process" in str(err)
        assert "Traceback" in err.remote_traceback
        # pool stays recoverable: failed worker marked idle, others drain
        waitall(pool, backend)
    finally:
        backend.shutdown()


def _exit_on_negative(i, payload, epoch):
    if i == 1 and payload[0] < 0:
        os._exit(5)
    return np.array([float(i + 1), float(payload[0]), float(epoch)])


@pytest.mark.slow
def test_respawn_recovers_crashed_rank():
    """Elastic recovery on the pipe backend: dead rank replaced in place
    (the reference's dead ranks are permanent — SURVEY §5)."""
    n = 3
    backend = ProcessBackend(_exit_on_negative, n)
    try:
        pool = AsyncPool(n)
        with pytest.raises(WorkerFailure):
            asyncmap(pool, np.array([-1.0]), backend, nwait=n)
            waitall(pool, backend)
        waitall(pool, backend)
        assert backend._dead[1]
        with pytest.raises(RuntimeError):
            backend.respawn(0)  # alive rank: refuse
        backend.respawn(1)
        assert not backend._dead[1]
        for epoch in (10, 11):
            repochs = asyncmap(
                pool, np.array([float(epoch)]), backend,
                nwait=n, epoch=epoch,
            )
            assert list(repochs) == [epoch] * n
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_dead_worker_process_fails_fast_not_hangs():
    # a crashed rank hangs the reference's Waitall! forever (SURVEY §5);
    # here the EOF on its pipe surfaces as WorkerFailure at harvest
    n = 3
    backend = ProcessBackend(_exit_worker2, n)
    try:
        pool = AsyncPool(n)
        with pytest.raises(WorkerFailure) as excinfo:
            asyncmap(pool, np.array([1.0]), backend, nwait=n)
            waitall(pool, backend)
        assert isinstance(excinfo.value.error, WorkerProcessDied)
        assert excinfo.value.error.worker == 2
    finally:
        backend.shutdown()
