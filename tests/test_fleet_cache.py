"""Fleet-wide tiered prefix cache (ISSUE 20): HBM -> host-DRAM ->
peer-replica fetch.

Four layers: (1) the cache/ primitives in isolation — directory
generations/leases/notifications, the DRAM store's pin-disciplined
slots and tenant spill quotas, the planner's batched byte pricing;
(2) the LIVE path — token-for-token parity of streams served off
spilled-then-fetched pages against the ``generate_ring_dense`` oracle,
including kill/respawn of the owning replica between spill and fetch,
peer fetches over the migration-ring frame format, and the
counter-verified prefill-chunk saving; (3) the sim twin —
bit-identical day replays with the priced spill/fetch model, kill and
partition semantics matching the live hub; (4) the
``sweep_spill_capacity`` controller sweep with its refusal contract.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mpistragglers_jl_tpu.cache import (
    FleetPageDirectory,
    FleetPrefixCache,
    PageMove,
    PageStore,
    SpillFetchPlanner,
)
from mpistragglers_jl_tpu.models.decode import generate_ring_dense
from mpistragglers_jl_tpu.models.serving import ServingScheduler
from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)
from mpistragglers_jl_tpu.obs import MetricsRegistry

CFG = TransformerConfig(
    vocab=61, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2, d_ff=128,
    attn_window=6,
)
PARAMS = init_params(CFG, seed=11)
KCFG = TransformerConfig(
    vocab=97, d_model=256, n_heads=2, n_kv_heads=1, n_layers=2,
    d_ff=256, attn_window=128,
)
KPARAMS = init_params(KCFG, seed=31)
RNG = np.random.default_rng(77)

D1 = b"\x01" * 32
D2 = b"\x02" * 32
D3 = b"\x03" * 32


def _prompt(n, vocab=CFG.vocab):
    return RNG.integers(1, vocab, size=n).astype(np.int32)


def _oracle(p, n, *, params=PARAMS, cfg=CFG):
    toks = generate_ring_dense(params, jnp.asarray(p)[None], n, cfg)
    return [int(t) for t in np.asarray(toks)[0]]


def _drained(sched):
    sched.pool.check()
    assert sched.pool.used == 0 and sched.pool.reserved == 0


# --------------------------------------------------------------------------
# FleetPageDirectory
# --------------------------------------------------------------------------


class TestDirectory:
    def test_publish_locate_dram_first(self):
        d = FleetPageDirectory()
        d.register_replica("a")
        d.register_replica("store")
        d.publish(D1, replica="a", tier="hbm")
        d.publish(D1, replica="store", tier="dram")
        assert d.locate(D1) == [("store", "dram"), ("a", "hbm")]
        assert d.locate(D1, exclude="a") == [("store", "dram")]
        assert D1 in d and d.size == 1
        d.check()

    def test_replica_drop_invalidates_by_generation(self):
        """A respawned replica's stale advertisements can never be
        served: drop bumps the generation, locate prunes."""
        d = FleetPageDirectory()
        d.register_replica("a")
        d.publish(D1, replica="a", tier="hbm")
        d.drop_replica("a")
        assert d.locate(D1) == []
        assert D1 not in d
        # respawn is a fresh generation: old entries stay dead, new
        # publishes live
        d.register_replica("a")
        d.publish(D2, replica="a", tier="hbm")
        assert d.locate(D2) == [("a", "hbm")]
        assert d.locate(D1) == []
        d.check()

    def test_publish_refusals(self):
        d = FleetPageDirectory()
        with pytest.raises(ValueError, match="register"):
            d.publish(D1, replica="ghost", tier="hbm")
        d.register_replica("a")
        with pytest.raises(ValueError, match="tier"):
            d.publish(D1, replica="a", tier="tape")

    def test_withdraw_notifies_subscribers(self):
        d = FleetPageDirectory()
        d.register_replica("a")
        seen = []
        d.subscribe(lambda dg, rep, tier: seen.append((dg, rep, tier)))
        d.publish(D1, replica="a", tier="hbm")
        assert d.withdraw(D1, replica="a", tier="hbm")
        assert not d.withdraw(D1, replica="a", tier="hbm")
        assert seen == [(D1, "a", "hbm")]

    def test_lease_lifecycle(self):
        d = FleetPageDirectory()
        d.register_replica("a")
        d.publish(D1, replica="a", tier="hbm")
        with d.lease(D1, "a", "hbm"):
            assert d.leased(D1)
        assert not d.leased(D1)
        lease = d.lease(D1, "a", "hbm")
        lease.release()
        lease.release()  # idempotent
        assert not d.leased(D1)
        d.check()


# --------------------------------------------------------------------------
# PageStore
# --------------------------------------------------------------------------


def _page(fill, nbytes=64):
    return np.full(nbytes, fill, dtype=np.uint8)


class TestPageStore:
    def test_put_get_roundtrip_zero_copy(self):
        st = PageStore(64, 4)
        assert st.put(D1, _page(7))
        got = st.get(D1)
        assert got is not None and got.nbytes == 64
        np.testing.assert_array_equal(np.asarray(got), _page(7))
        assert st.get(D2) is None
        assert st.put(D1, _page(9))  # present: True, bytes unchanged
        np.testing.assert_array_equal(np.asarray(st.get(D1)), _page(7))
        st.check()
        st.close()

    def test_geometry_mismatch_refused_by_name(self):
        st = PageStore(64, 2)
        with pytest.raises(ValueError, match="geometry"):
            st.put(D1, _page(0, nbytes=32))
        st.close()

    def test_capacity_eviction_is_oldest_first(self):
        d = FleetPageDirectory()
        st = PageStore(64, 2, directory=d)
        st.put(D1, _page(1))
        st.put(D2, _page(2))
        st.put(D3, _page(3))
        assert st.get(D1) is None  # oldest went
        assert st.get(D2) is not None and st.get(D3) is not None
        assert d.locate(D1) == []
        assert st.n_evictions == 1
        st.check()
        st.close()

    def test_leased_page_survives_eviction_pressure(self):
        """A fetch in progress must not watch its source evaporate:
        the eviction scan skips leased digests."""
        d = FleetPageDirectory()
        st = PageStore(64, 2, directory=d)
        st.put(D1, _page(1))
        st.put(D2, _page(2))
        with d.lease(D1, st.name, "dram"):
            st.put(D3, _page(3))
            assert st.get(D1) is not None  # leased: kept
            assert st.get(D2) is None      # next-oldest went instead
        st.check()
        st.close()

    def test_evicted_viewed_slot_bytes_survive_readers(self):
        """Zero-copy discipline: while a served view is live its slot
        stays pinned — a full store REFUSES new pages rather than tear
        the reader's bytes, and the slot frees when the view dies."""
        import gc

        st = PageStore(64, 1)
        st.put(D1, _page(5))
        view = st.get(D1)
        assert not st.put(D2, _page(6))  # D1 evicted, slot view-pinned
        assert st.n_refused == 1
        np.testing.assert_array_equal(np.asarray(view), _page(5))
        del view
        gc.collect()
        assert st.put(D2, _page(6))  # last reader gone: slot reusable
        np.testing.assert_array_equal(np.asarray(st.get(D2)), _page(6))
        st.check()
        st.close()

    def test_tenant_spill_quota(self):
        from mpistragglers_jl_tpu.qos import TenantContract, TenantRegistry

        qos = TenantRegistry([
            TenantContract("bulk", spill_pages=1),
            TenantContract("banned", spill_pages=0),
        ])
        st = PageStore(64, 4, qos=qos)
        assert not st.put(D1, _page(1), tenant="banned")
        assert st.n_refused == 1
        assert st.put(D1, _page(1), tenant="bulk")
        assert st.put(D2, _page(2), tenant="bulk")  # evicts own D1
        assert st.tenant_pages("bulk") == 1
        assert st.get(D1) is None and st.get(D2) is not None
        st.check()
        st.close()


# --------------------------------------------------------------------------
# SpillFetchPlanner
# --------------------------------------------------------------------------


class TestPlanner:
    def test_price_is_alpha_plus_bytes_over_rate(self):
        pl = SpillFetchPlanner(spill_gbs=10.0, fetch_gbs=5.0,
                               alpha_s=1e-5)
        assert pl.price(1 << 20, "spill") == pytest.approx(
            1e-5 + (1 << 20) / 10e9
        )
        assert pl.price(1 << 20, "fetch_peer") == pytest.approx(
            1e-5 + (1 << 20) / 5e9
        )
        with pytest.raises(ValueError, match="kind"):
            pl.price(1, "teleport")

    def test_plan_batches_per_link_at_batch_bytes(self):
        pl = SpillFetchPlanner(batch_bytes=128)
        moves = [
            PageMove(D1, src="r0", dst="store", nbytes=96, kind="spill"),
            PageMove(D2, src="r0", dst="store", nbytes=96, kind="spill"),
            PageMove(D3, src="r1", dst="r0", nbytes=96,
                     kind="fetch_peer"),
        ]
        batches = pl.plan(moves)
        # r0->store splits at 128 bytes; r1->r0 is its own link
        assert [(b["src"], b["dst"], len(b["moves"])) for b in batches] \
            == [("r0", "store", 1), ("r0", "store", 1), ("r1", "r0", 1)]
        assert pl.planned_batches == 3
        for b in batches:
            assert b["seconds"] > 0.0


# --------------------------------------------------------------------------
# live path: spill -> fetch parity against the dense oracle
# --------------------------------------------------------------------------


def _small_sched(hub, *, registry=None):
    """CFG geometry where requests do NOT wrap (Tp=4 + max_new=1 +
    n_inner=1 <= W=6), so retired prefix pages are registered
    non-volatile and eligible for fleet spill."""
    return ServingScheduler(
        PARAMS, CFG, slots=2, n_inner=1, prompt_chunk=2,
        max_prompt=16, page_tokens=2, registry=registry, cache=hub,
    )


class TestLiveSpillFetch:
    def test_spilled_then_fetched_stream_matches_oracle(self):
        """Replica A retires a prompt (pages spill to DRAM); replica B
        serves the same prompt off the fetched page — token-for-token
        the dense oracle, with the hit counted under tier="dram" and
        fewer prefill chunks than A paid."""
        hub = FleetPrefixCache(store_pages=8)
        reg_a, reg_b = MetricsRegistry(), MetricsRegistry()
        p = _prompt(4)
        want = _oracle(p, 1)

        a = _small_sched(hub, registry=reg_a)
        ra = a.submit(p, max_new=1)
        a.run()
        assert ra.tokens == want
        _drained(a)
        assert hub.n_spills >= 1
        assert hub.store.pages >= 1

        b = _small_sched(hub, registry=reg_b)
        rb = b.submit(p, max_new=1)
        b.run()
        assert rb.tokens == want
        _drained(b)
        assert hub.n_fetches["dram"] == 1
        assert reg_b.counter(
            "serving_prefix_share_hits_total", tier="dram"
        ).value == 1
        # the fetched page replaced prefill work: B ran fewer chunks
        chunks_a = reg_a.counter("serving_prefill_chunks_total").value
        chunks_b = reg_b.counter("serving_prefill_chunks_total").value
        assert chunks_b < chunks_a
        hub.check()
        hub.close()

    def test_fetch_survives_owner_kill_and_respawn(self):
        """The acceptance crash shape: the replica that SPILLED dies
        between spill and fetch. DRAM is host state — the page
        survives, a respawned fleet member still fetches it, and the
        stream still equals the oracle."""
        hub = FleetPrefixCache(store_pages=8)
        p = _prompt(4)
        want = _oracle(p, 1)

        a = _small_sched(hub)
        name_a = a.cache_name
        ra = a.submit(p, max_new=1)
        a.run()
        assert ra.tokens == want
        assert hub.store.pages >= 1

        hub.kill(name_a)  # owner dies; its hbm entries invalidate
        assert name_a not in hub.members()

        b = _small_sched(hub)  # respawn as a fresh member
        rb = b.submit(p, max_new=1)
        b.run()
        assert rb.tokens == want
        assert hub.n_fetches["dram"] == 1
        assert hub.n_fallbacks == 0
        _drained(b)
        hub.check()
        hub.close()

    def test_peer_fetch_over_migration_ring_matches_oracle(self):
        """T3: with the DRAM tier disabled, a decoding peer's resident
        registered pages are fetched over the r16 frame format — both
        the owner's stream and the fetcher's equal their oracles."""
        hub = FleetPrefixCache(store_pages=0)  # peer-only fleet
        mk = lambda: ServingScheduler(
            KPARAMS, KCFG, slots=2, n_inner=4, prompt_chunk=8,
            max_prompt=64, page_tokens=16, cache=hub,
        )
        a, b = mk(), mk()
        p = RNG.integers(1, KCFG.vocab, size=40).astype(np.int32)
        want_a = _oracle(p, 40, params=KPARAMS, cfg=KCFG)
        want_b = _oracle(p, 8, params=KPARAMS, cfg=KCFG)

        ra = a.submit(p, max_new=40)
        while not ra.tokens:  # hold A mid-decode: pages stay resident
            a.step()
        rb = b.submit(p, max_new=8)
        b.run()
        a.run()
        assert ra.tokens == want_a
        assert rb.tokens == want_b
        assert hub.n_fetches["peer"] >= 1
        assert hub.n_fetches["dram"] == 0
        _drained(a)
        _drained(b)
        hub.check()
        hub.close()

    def test_partitioned_hub_member_falls_back_to_prefill(self):
        """A partition between spill and fetch: the asker sees nothing
        (fail-to-prefill), the stream is still oracle-exact, and after
        heal the same fetch hits."""
        hub = FleetPrefixCache(store_pages=8)
        p = _prompt(4)
        want = _oracle(p, 1)
        a = _small_sched(hub)
        a.submit(p, max_new=1)
        a.run()
        assert hub.store.pages >= 1

        b = _small_sched(hub)
        hub.partition(b.cache_name)
        rb = b.submit(p, max_new=1)
        b.run()
        assert rb.tokens == want  # re-prefilled, not served
        assert hub.n_fetches["dram"] == 0
        _drained(b)

        hub.heal(b.cache_name)
        rc = b.submit(p, max_new=1)
        b.run()
        assert rc.tokens == want
        assert hub.n_fetches["dram"] == 1
        _drained(b)
        hub.close()

    def test_cache_refused_without_paged_arena(self):
        hub = FleetPrefixCache()
        with pytest.raises(ValueError, match="page"):
            ServingScheduler(PARAMS, CFG, slots=2, cache=hub)

    def test_geometry_drift_refused_at_attach(self):
        hub = FleetPrefixCache(store_pages=4)
        _small_sched(hub)
        with pytest.raises(ValueError, match="geometry"):
            ServingScheduler(
                KPARAMS, KCFG, slots=2, prompt_chunk=8,
                max_prompt=64, page_tokens=16, cache=hub,
            )
        hub.close()


# --------------------------------------------------------------------------
# sim twin: SimFleetCache days
# --------------------------------------------------------------------------


def _sim_day(cache_groups, *, seed=5, n=800, kills=(), partition=None):
    from mpistragglers_jl_tpu.models.router import RequestRouter
    from mpistragglers_jl_tpu.sim import (
        ReplicaPartition,
        SimReplica,
        VirtualClock,
        poisson_arrivals,
        run_router_day,
    )
    from mpistragglers_jl_tpu.sim.workload import SimFleetCache

    clock = VirtualClock()
    cache = (SimFleetCache(store_groups=cache_groups)
             if cache_groups is not None else None)
    reps = [
        SimReplica(clock, slots=4, n_inner=8, tick_s=0.02,
                   prompt_chunk=64, chunk_s=0.004, cache=cache)
        for _ in range(3)
    ]
    router = RequestRouter(reps, policy="least_loaded", clock=clock)
    arrivals = list(poisson_arrivals(
        80.0, n=n, seed=seed, prompt_len=256, max_new=16,
        prefix_share=0.7, prefix_len=128, n_prefix_groups=8,
    ))
    events = []
    if partition is not None:
        events.append(ReplicaPartition(*partition))
    for t, i, until in kills:
        clock.call_at(t, lambda i=i: reps[i].kill())
        clock.call_at(until, lambda i=i: reps[i].revive())
    report = run_router_day(router, arrivals, events=events)
    return report, cache, reps


class TestSimFleetCache:
    def test_day_replays_bit_identically(self):
        r1, c1, f1 = _sim_day(16)
        r2, c2, _ = _sim_day(16)
        assert r1.digest() == r2.digest()
        assert c1.stats() == c2.stats()
        assert sum(r.n_fleet_hits for r in f1) > 0
        assert c1.n_spills > 0
        c1.check()
        # and the cache MOVES the day: priced fetches are not free
        r0, _, _ = _sim_day(None)
        assert r0.digest() != r1.digest()

    def test_counters_stay_outside_digest(self):
        """Same timing, different counter state must digest equal:
        the digest hashes outcomes, not bookkeeping."""
        r1, c1, _ = _sim_day(16)
        c1.n_spills += 100  # bookkeeping-only perturbation
        r2, c2, _ = _sim_day(16)
        assert r1.digest() == r2.digest()

    def test_kill_purges_hbm_but_dram_survives(self):
        from mpistragglers_jl_tpu.sim import SimReplica, VirtualClock
        from mpistragglers_jl_tpu.sim.workload import SimFleetCache

        clock = VirtualClock()
        cache = SimFleetCache(store_groups=8)
        r = SimReplica(clock, slots=2, cache=cache)
        cache.publish_hbm(r.cache_name, "g")
        cache._dram["g2"] = 4096
        r.kill()
        assert cache.stats()["hbm_groups"] == 0
        assert cache.n_replica_drops == 1
        assert cache.fetch("g2", 64) is not None  # dram survived
        assert cache.fetch("g", 64) is None
        # respawn gets a FRESH identity (generation semantics)
        old = r.cache_name
        r.revive()
        assert r.cache_name != old

    def test_partitioned_replica_invisible_and_fallback_counted(self):
        from mpistragglers_jl_tpu.sim.workload import SimFleetCache

        cache = SimFleetCache(store_groups=0)

        class _R:
            pass

        a = cache.register(_R())
        b = cache.register(_R())
        cache.publish_hbm(a, "g")
        assert cache.fetch("g", 64, exclude=b)[0] == "peer"
        cache.partition(a)
        assert cache.fetch("g", 64, exclude=b) is None
        assert cache.n_fallbacks == 1  # known-but-unreachable, named
        cache.heal(a)
        assert cache.fetch("g", 64, exclude=b)[0] == "peer"
        # the owner itself is excluded from its own lookups
        assert cache.fetch("g", 64, exclude=a) is None
        assert cache.n_fallbacks == 1  # a self-only miss is cold, not
        # a fallback: no reachable sibling ever held the group

    def test_fastpath_refuses_cache_days_by_name(self):
        from mpistragglers_jl_tpu.models.router import RequestRouter
        from mpistragglers_jl_tpu.sim import SimReplica, VirtualClock
        from mpistragglers_jl_tpu.sim.fastpath import fastpath_supported
        from mpistragglers_jl_tpu.sim.workload import SimFleetCache

        clock = VirtualClock()
        cache = SimFleetCache()
        reps = [SimReplica(clock, cache=cache) for _ in range(2)]
        router = RequestRouter(reps, policy="least_loaded", clock=clock)
        ok, reason = fastpath_supported(router)
        assert not ok and "fleet cache" in reason


# --------------------------------------------------------------------------
# sweep_spill_capacity
# --------------------------------------------------------------------------


class TestSpillCapacitySweep:
    def test_sweep_prefers_capacity_and_reports_saving(self):
        from mpistragglers_jl_tpu.sim.tune import sweep_spill_capacity

        out = sweep_spill_capacity(
            store_groups_candidates=[0, 64], requests=400, seed=3,
            n_prefix_groups=24,
        )
        assert out["best"] == 64
        assert out["p99_ttft_vs_no_dram"] > 1.0
        by_g = {e["store_groups"]: e for e in out["entries"]}
        assert by_g[64]["fetches"]["dram"] > 0
        assert by_g[0]["fetches"]["dram"] == 0  # no tier, no hits
        assert by_g[64]["prefill_chip_s_saved"] > \
            by_g[0]["prefill_chip_s_saved"]

    def test_sweep_refusals_by_name(self):
        from mpistragglers_jl_tpu.sim.tune import sweep_spill_capacity

        with pytest.raises(ValueError, match="empty"):
            sweep_spill_capacity(store_groups_candidates=[])
        with pytest.raises(ValueError, match="negative"):
            sweep_spill_capacity(store_groups_candidates=[-1])
        with pytest.raises(ValueError, match="shareless"):
            sweep_spill_capacity(store_groups_candidates=[4],
                                 prefix_share=0.0)
        with pytest.raises(ValueError, match="load"):
            sweep_spill_capacity(store_groups_candidates=[4], load=1.0)
        with pytest.raises(ValueError, match="replicas"):
            sweep_spill_capacity(store_groups_candidates=[4],
                                 replicas=1)
