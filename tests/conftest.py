"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective
code is exercised on 8 virtual CPU devices. The axon TPU plugin overrides
``JAX_PLATFORMS`` at interpreter start, so we must also update jax.config,
not just the environment.
"""

import os
import sys

_FLAG = "--xla_force_host_platform_device_count=8"
_existing = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _existing:
    os.environ["XLA_FLAGS"] = (_existing + " " + _FLAG).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# float64 must stay float64 in the coding-layer tests (the reference's
# tests are Float64 throughout, SURVEY §7 "the hard parts"); TPU-path
# tests pin float32 explicitly so this only affects CPU-mesh runs
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the suite's wall-clock is dominated
# by a flat ~1-3 s/test tail of small jit compiles (measured r5 —
# durations show no outliers above 9 s in the default tier, yet it
# spends 12+ min on one core). Caching compiled executables across runs
# turns every repeat run (local dev loops, the driver's green check,
# CI with a cached directory) into mostly cache hits. Correctness is
# unaffected: the cache key covers program, backend, and flags.
_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
)
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
