"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective
code is exercised on 8 virtual CPU devices. The axon TPU plugin overrides
``JAX_PLATFORMS`` at interpreter start, so we must also update jax.config,
not just the environment.
"""

import os
import sys

_FLAG = "--xla_force_host_platform_device_count=8"
_existing = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _existing:
    os.environ["XLA_FLAGS"] = (_existing + " " + _FLAG).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# float64 must stay float64 in the coding-layer tests (the reference's
# tests are Float64 throughout, SURVEY §7 "the hard parts"); TPU-path
# tests pin float32 explicitly so this only affects CPU-mesh runs
jax.config.update("jax_enable_x64", True)
