"""PagePool: the host-side allocator under the paged serving cache
(models/paging.py).

The scheduler-level parity suite (tests/test_serving_paged.py) pins
that paged serving emits oracle-identical streams; THIS suite pins the
allocator's own contracts under churn: no page leaks (free + used ==
total across any admit/retire interleaving), refcounts return to
baseline, registered prefixes never outlive their pages, and the COW
reservation accounting makes mid-decode exhaustion unreachable no
matter which holder of a shared page writes first. Pure host — no jax.
"""

import numpy as np
import pytest

from mpistragglers_jl_tpu.models.paging import (
    NULL_PAGE,
    PagePool,
    PagePoolExhausted,
    prefix_page_digests,
)


def test_basic_alloc_free_accounting():
    pool = PagePool(8, 4)
    assert pool.free == 7 and pool.used == 0  # page 0 reserved
    pids = [pool.alloc() for _ in range(7)]
    assert NULL_PAGE not in pids and len(set(pids)) == 7
    assert pool.free == 0 and pool.used == 7
    with pytest.raises(PagePoolExhausted):
        pool.alloc()
    for pid in pids:
        assert pool.decref(pid)
    assert pool.free == 7 and pool.used == 0
    pool.check()


def test_null_page_is_protected():
    pool = PagePool(4, 2)
    with pytest.raises(ValueError):
        pool.incref(NULL_PAGE)
    with pytest.raises(ValueError):
        pool.decref(NULL_PAGE)
    with pytest.raises(ValueError):
        PagePool(1, 2)  # must hold null + at least one real page
    with pytest.raises(ValueError):
        PagePool(4, 0)


def test_refcount_sharing_lifecycle():
    pool = PagePool(8, 4)
    pid = pool.alloc()
    d = b"digest-0"
    pool.register(d, pid)
    assert pool.lookup(d) == pid
    pool.share(pid, reserve=False)
    assert pool.refcount(pid) == 2 and pool.share_hits == 1
    assert not pool.decref(pid)  # sharer retires: page survives
    assert pool.lookup(d) == pid
    assert pool.decref(pid)  # owner retires: page freed + unregistered
    assert pool.lookup(d) is None
    pool.check()


def test_register_is_first_wins():
    pool = PagePool(8, 4)
    a, b = pool.alloc(), pool.alloc()
    pool.register(b"x", a)
    pool.register(b"x", b)  # duplicate content: original kept
    assert pool.lookup(b"x") == a
    pool.register(b"y", a)  # page already keyed: original key kept
    assert pool.lookup(b"y") is None
    pool.check()


def test_note_write_drops_registration():
    pool = PagePool(8, 4)
    pid = pool.alloc()
    pool.register(b"x", pid, volatile=True)
    assert pool.is_volatile(pid)
    pool.note_write(pid)  # sole owner overwrites: digest now stale
    assert pool.lookup(b"x") is None
    # the wrapping owner still HOLDS the page; its wrapper count
    # clears when it retires, not when it writes
    assert pool.is_volatile(pid)
    pool.decref(pid, wrapper=True)
    pool.check()


def test_wrapper_count_clears_when_wrapping_owner_retires():
    """Review r11: a sticky volatile flag made every later sharer of
    a warm prompt reserve COW pages against an owner that had already
    retired — reservations nobody could ever consume, eroding exactly
    the shared-capacity win. The wrapper COUNT drops with the leaving
    holder, so sharing a page whose remaining holders are all
    non-wrapping costs no reservation."""
    pool = PagePool(8, 4)
    pid = pool.alloc()
    pool.register(b"p", pid, volatile=True)  # wrapping owner
    pool.share(pid, reserve=True)  # short sharer pays while owner lives
    assert pool.reserved == 1
    pool.decref(pid, wrapper=True)  # owner retires before wrapping
    assert not pool.is_volatile(pid)
    assert pool.reserved == 0  # stranded reservation released too
    assert not pool.share_needs_reserve(pid, False)
    pool.share(pid, reserve=False)  # later sharers ride free
    pool.decref(pid)
    pool.decref(pid)
    pool.check()
    assert pool.used == 0

    # symmetric: a WRAPPING sharer joins the count and leaves with it
    pid = pool.alloc()
    pool.register(b"q", pid)  # non-wrapping owner
    pool.share(pid, reserve=True, wrapper=True)
    assert pool.is_volatile(pid)
    pool.decref(pid, wrapper=True)  # wrapping sharer retires
    assert not pool.is_volatile(pid)
    pool.decref(pid)
    pool.check()


def test_cow_reservation_consumed_by_either_holder():
    """The reservation attaches to the PAGE, so whichever holder
    writes first consumes it — the r11 accounting bug this design
    replaced attributed reservations to the sharer and blew up when
    the registering owner wrapped first."""
    for owner_writes_first in (False, True):
        pool = PagePool(4, 2)  # 3 usable pages
        pid = pool.alloc()
        pool.register(b"p", pid, volatile=True)  # owner will wrap
        pool.share(pid, reserve=pool.share_needs_reserve(pid, False))
        assert pool.reserved == 1
        extra = pool.alloc()  # a third party takes the only free page
        del owner_writes_first  # symmetric: cow_alloc is holder-blind
        # the attached reservation still covers the COW
        new = pool.cow_alloc(pid)
        assert pool.reserved == 0 and new not in (pid, extra)
        pool.decref(pid)  # writer leaves the shared page
        for p in (pid, new, extra):
            pool.decref(p)
        pool.check()
        assert pool.used == 0


def test_unreserved_free_pages_cannot_be_stolen():
    pool = PagePool(4, 2)
    pid = pool.alloc()
    pool.register(b"p", pid, volatile=True)
    pool.share(pid, reserve=True)
    pool.alloc()  # 1 of 2 remaining
    with pytest.raises(PagePoolExhausted):
        pool.alloc()  # last free page is reserved for the COW
    assert pool.cow_alloc(pid) != NULL_PAGE  # ...and the COW gets it


def test_stranded_reservation_releases_on_retire():
    pool = PagePool(6, 2)
    pid = pool.alloc()
    pool.register(b"p", pid)
    pool.share(pid, reserve=True)  # sharer wraps but retires unwritten
    assert pool.reserved == 1
    pool.decref(pid)  # sharer retires: refcount 1, 0 possible COWs
    assert pool.reserved == 0
    pool.decref(pid)
    pool.check()


def test_prefix_digests_chain_covers_whole_prefix():
    """Page j's digest keys prompt[:(j+1)*P] — K/V at any position
    depend on every earlier token, so two prompts differing ANYWHERE
    before a page boundary must diverge from that page on."""
    a = np.arange(10, dtype=np.int32)
    b = a.copy()
    b[1] = 99  # differs inside page 0
    da, db = prefix_page_digests(a, 4), prefix_page_digests(b, 4)
    assert len(da) == 2  # only fully covered pages
    assert da[0] != db[0] and da[1] != db[1]
    c = a.copy()
    c[5] = 99  # differs inside page 1: page 0 still shared
    dc = prefix_page_digests(c, 4)
    assert da[0] == dc[0] and da[1] != dc[1]
    assert prefix_page_digests(a, 4, max_pages=1) == da[:1]
    assert prefix_page_digests(a[:3], 4) == []


def test_fuzz_churn_never_leaks():
    """Random admit/share/COW/retire interleavings: the structural
    invariants hold at every step and the pool drains to empty."""
    rng = np.random.default_rng(0)
    pool = PagePool(33, 4)
    # per-request state: (held pids, wraps) — wraps mirrors the
    # scheduler's per-slot flag (writers are always wrappers)
    live: list[tuple[list[int], bool]] = []
    for step in range(2000):
        op = rng.integers(0, 4)
        if op == 0 and pool.can_alloc(3, reserve=0):  # admit fresh
            wraps = bool(rng.integers(0, 2))
            live.append(([pool.alloc() for _ in range(3)], wraps))
            d = rng.integers(0, 6)
            pool.register(bytes([d]), live[-1][0][0], volatile=wraps)
        elif op == 1 and live:  # admit sharing someone's first page
            src = live[rng.integers(0, len(live))][0][0]
            wraps = bool(rng.integers(0, 2))
            need = pool.share_needs_reserve(src, wraps)
            if pool.can_alloc(1, reserve=int(need)):
                pool.share(src, reserve=need, wrapper=wraps)
                live.append(([src, pool.alloc()], wraps))
        elif op == 2 and live:  # a WRAPPING holder writes its page
            idx = rng.integers(0, len(live))
            pids, wraps = live[idx]
            if not wraps:
                continue  # non-wrapping requests never write shared
            pid = pids[0]
            if pool.refcount(pid) > 1:
                new = pool.cow_alloc(pid)
                pool.decref(pid, wrapper=True)
                pids[0] = new
            else:
                pool.note_write(pid)
        elif op == 3 and live:  # retire
            pids, wraps = live.pop(rng.integers(0, len(live)))
            for pid in pids:
                pool.decref(pid, wrapper=wraps)
        pool.check()
    for pids, wraps in live:
        for pid in pids:
            pool.decref(pid, wrapper=wraps)
    pool.check()
    assert pool.used == 0 and pool.free == 32 and pool.reserved == 0
