"""Seeded invariant fuzzer for the pool state machine.

Random (but reproducible) sequences of ``asyncmap`` / ``waitall`` calls
with random nwait forms, epochs, delays, and recvbuf usage, checked
after every step against the reference's §2.1 invariants (SURVEY):

* ``active[i]`` ⇔ the backend owes worker i a result;
* ``repochs[i] == epoch0`` iff never heard from i (results[i] is None);
* fresh_indices ⊆ workers heard from, all stamped with the current epoch;
* after integer-nwait asyncmap, >= nwait workers are fresh AND inactive;
* after waitall, nobody is active;
* recvbuf chunks of fresh workers hold exactly that worker's payload
  echo (chunk-j <- worker-j, the MPI.Gather! layout);
* latency entries are non-negative and only set for heard-from workers.

The reference has nothing like this (its tests are 3 fixed scenarios);
a state machine whose edge cases are its whole reason to exist deserves
adversarial sequences.
"""

import numpy as np
import pytest

from mpistragglers_jl_tpu import (
    AsyncPool,
    LocalBackend,
    asyncmap,
    waitall,
)


def echo(i, payload, epoch):
    # [worker+1, payload echo, epoch] — checkable provenance per chunk
    return np.array([float(i + 1), float(payload[0]), float(epoch)])


class SeededDelays:
    def __init__(self, seed):
        self.rng = np.random.default_rng(seed)
        self.table = {}

    def __call__(self, i, epoch):
        key = (i, epoch)
        if key not in self.table:
            # mostly fast, occasional 30-60 ms straggle
            r = self.rng.random()
            self.table[key] = 0.03 + 0.03 * r if r > 0.8 else 0.001
        return self.table[key]


def check_invariants(pool, epoch0):
    heard = np.array([r is not None for r in pool.results])
    never = pool.repochs == epoch0
    # repochs == epoch0 means never heard from (the fuzzer's live epochs
    # are all > epoch0, so the implication is exact here)
    assert not heard[never].any()
    for i in np.flatnonzero(~heard):
        assert pool.repochs[i] == epoch0
        assert pool.latency[i] == 0.0
    fresh = pool.fresh_indices()
    assert np.all(heard[fresh])
    assert np.all(pool.repochs[fresh] == pool.epoch)
    assert np.all(pool.latency[np.flatnonzero(heard)] >= 0)


@pytest.mark.parametrize("seed", range(8))
def test_random_op_sequences_hold_invariants(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(2, 7))
    epoch0 = int(rng.integers(-3, 4)) * 10  # exercise epoch0 != 0
    backend = LocalBackend(echo, n, delay_fn=SeededDelays(seed))
    try:
        pool = AsyncPool(n, epoch0=epoch0)
        payload = np.zeros(1)
        for step in range(25):
            op = rng.random()
            use_recv = rng.random() < 0.5
            recvbuf = np.zeros(3 * n) if use_recv else None
            if op < 0.70:  # asyncmap with random nwait form
                payload[0] = float(step + 1)
                form = rng.random()
                if form < 0.5:
                    nwait = int(rng.integers(0, n + 1))
                elif form < 0.8:
                    # wait for one specific worker
                    target = int(rng.integers(0, n))
                    nwait = (
                        lambda e, rep, t=target: rep[t] == e
                    )
                else:
                    nwait = n  # full gather
                repochs = asyncmap(
                    pool, payload, backend, recvbuf, nwait=nwait
                )
                assert repochs is pool.repochs  # aliasing contract
                if isinstance(nwait, int):
                    fresh_inactive = (
                        (pool.repochs == pool.epoch) & ~pool.active
                    )
                    assert int(fresh_inactive.sum()) >= nwait
                if use_recv:
                    chunks = recvbuf.reshape(n, 3)
                    for i in pool.fresh_indices():
                        assert chunks[i][0] == i + 1  # provenance
                        assert chunks[i][2] == pool.epoch  # epoch echo
            else:  # waitall (sometimes with a generous timeout)
                t = 10.0 if rng.random() < 0.5 else None
                waitall(pool, backend, recvbuf, timeout=t)
                assert not pool.active.any()
            check_invariants(pool, epoch0)
        waitall(pool, backend)
        assert not pool.active.any()
    finally:
        backend.shutdown()


def test_fuzz_with_retask_pressure():
    """High straggle + nwait=1 maximizes the stale-harvest/re-task path
    (reference src/MPIAsyncPools.jl:177-184); every stale chunk written
    to recvbuf must still satisfy the echo contract for ITS epoch."""
    n = 3
    backend = LocalBackend(
        echo, n, delay_fn=lambda i, e: 0.04 if i != 0 else 0.0
    )
    try:
        pool = AsyncPool(n)
        recvbuf = np.zeros(3 * n)
        payload = np.zeros(1)
        for epoch in range(1, 15):
            payload[0] = epoch
            repochs = asyncmap(pool, payload, backend, recvbuf, nwait=1)
            chunks = recvbuf.reshape(n, 3)
            for i in range(n):
                if pool.results[i] is None:
                    continue
                # chunk holds the payload of the epoch it is stamped with
                assert chunks[i][1] == float(repochs[i])
                assert chunks[i][2] == float(repochs[i])
        waitall(pool, backend, recvbuf)
        assert not pool.active.any()
    finally:
        backend.shutdown()


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_two_pools_tag_channels(seed):
    """Two pools multiplex one backend on distinct tags under random
    interleavings: channel isolation must hold at every step — each
    pool's invariants, recvbuf provenance, and epoch bookkeeping are
    unaffected by the other pool's traffic."""
    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(2, 6))
    backend = LocalBackend(echo, n, delay_fn=SeededDelays(seed))
    try:
        pools = {1: AsyncPool(n), 2: AsyncPool(n)}
        payload = np.zeros(1)
        for step in range(20):
            tag = int(rng.integers(1, 3))
            pool = pools[tag]
            if rng.random() < 0.75:
                # encode (tag, step) in the payload so cross-channel
                # leakage is detectable in the echo
                payload[0] = float(tag * 1000 + step)
                nwait = int(rng.integers(0, n + 1))
                recvbuf = np.zeros(3 * n) if rng.random() < 0.5 else None
                asyncmap(
                    pool, payload, backend, recvbuf, nwait=nwait, tag=tag
                )
                assert np.all(pool.stags[pool.active] == tag)
                if recvbuf is not None:
                    chunks = recvbuf.reshape(n, 3)
                    for i in pool.fresh_indices():
                        # provenance: this channel's payload, not the
                        # other pool's
                        assert chunks[i][1] == payload[0]
            else:
                waitall(pool, backend, timeout=10.0)
                assert not pool.active.any()
            for p in pools.values():
                check_invariants(p, 0)
        for p in pools.values():
            waitall(p, backend, timeout=10.0)
            assert not p.active.any()
    finally:
        backend.shutdown()
