"""int8 KV cache (round 4): half the serving cache bytes, bounded error.

Layout: int8 K/V plus per-(batch, position, head) f32 absmax scales
(models/decode.py ``_kv_quantize``). Dequantization is a rank-1
correction folded into the attention einsums — scores scale by ``k_s``,
probabilities by ``v_s`` — so no full-size dequantized copy exists.
Quantization is a serving-time flag orthogonal to cache layout: masked
max_len, O(W) ring, and chunked-extend paths all share the one write
path (``_cache_write``), which these tests pin pairwise.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mpistragglers_jl_tpu.models.decode import (
    _aligned_quantized_prefill,
    _kv_quantize,
    decode_step_dense,
    generate_dense,
    generate_ring_dense,
    init_cache,
    make_extend,
    make_generate,
    make_prefill,
    make_ring_generate,
    prefill_dense,
    shard_cache,
)
from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig,
    forward_dense,
    init_params,
    shard_params,
)
from mpistragglers_jl_tpu.parallel import make_mesh

CFG = TransformerConfig(
    vocab=61, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2, d_ff=128
)


def _toks(B, L, seed=2):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab, (B, L)), jnp.int32)


def test_quantize_roundtrip_bound():
    """Absmax int8: per-element error <= scale/2 (round-to-nearest)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 5, 3, 16)), jnp.float32)
    xq, s = _kv_quantize(x)
    assert xq.dtype == jnp.int8
    err = jnp.abs(x - xq.astype(jnp.float32) * s[..., None])
    assert float(jnp.max(err - s[..., None] / 2)) <= 1e-6


@pytest.mark.slow
def test_teacher_forced_quantized_error_bounded():
    """int8 teacher-forced decode tracks the exact forward: logit error
    small against the logit scale (int8 absmax keeps ~2 decimal digits
    per row)."""
    params = init_params(CFG, seed=1)
    toks = _toks(2, 12)
    want = forward_dense(params, toks, CFG)
    cache = init_cache(CFG, 2, 12, quantize_kv=True)
    lg, cache = prefill_dense(params, toks[:, :6], cache, CFG)
    worst = 0.0
    for t in range(6, 12):
        lg, cache = decode_step_dense(
            params, toks[:, t], cache, jnp.int32(t), CFG
        )
        worst = max(worst, float(jnp.max(jnp.abs(lg - want[:, t]))))
    scale = float(jnp.std(want))
    assert worst < 0.15 * scale, (worst, scale)


def test_quantized_cache_halves_bytes():
    bf = init_cache(CFG, 2, 64)
    q8 = init_cache(CFG, 2, 64, quantize_kv=True)
    nbytes = lambda c: sum(x.nbytes for x in jax.tree.leaves(c))
    # int8 data is half of bf16... CFG default dtype is f32 in tests, so
    # compare against the quarter-size int8 payload + small scales
    kv_bytes = sum(
        layer[k].nbytes for layer in q8 for k in ("k", "v")
    )
    scale_bytes = sum(
        layer[k].nbytes for layer in q8 for k in ("k_s", "v_s")
    )
    itemsize = np.dtype(CFG.dtype).itemsize
    assert kv_bytes * itemsize == sum(
        layer[k].nbytes for layer in bf for k in ("k", "v")
    )
    # scales are the per-position vectors — D-fold smaller than data
    assert scale_bytes * CFG.head_dim == kv_bytes * 4  # f32 scales
    assert nbytes(q8) < nbytes(bf)


def test_generate_quantized_matches_exact_greedy():
    """On this model the int8 error does not flip the argmax: greedy
    streams agree with the exact cache (seeded, deterministic)."""
    params = init_params(CFG, seed=1)
    prompt = _toks(2, 6)
    want = generate_dense(params, prompt, 6, CFG)
    got = generate_dense(params, prompt, 6, CFG, quantize_kv=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("shape", [(2, 2), (1, 4)])
def test_sharded_quantized_generate_matches_dense(shape):
    """make_generate(quantize_kv=True) over dp x tp == the dense
    quantized generator, incl. tp=4 > kv_heads=2 replicated groups."""
    mesh = make_mesh(shape, ("dp", "tp"))
    params = init_params(CFG, seed=3)
    prompt = _toks(2, 7, seed=4)
    want = generate_dense(params, prompt, 8, CFG, quantize_kv=True)
    gen = make_generate(CFG, mesh, 8, quantize_kv=True)
    got = gen(
        shard_params(params, CFG, mesh),
        jax.device_put(prompt, NamedSharding(mesh, P("dp", None))),
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_ring_quantized_matches_masked_quantized():
    """Quantization composes with the O(W) ring: same band, same int8
    values, same tokens."""
    cfg = dataclasses.replace(CFG, attn_window=5)
    params = init_params(cfg, seed=5)
    prompt = _toks(2, 6, seed=6)
    want = generate_dense(params, prompt, 9, cfg, quantize_kv=True)
    got = generate_ring_dense(params, prompt, 9, cfg, quantize_kv=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    mesh = make_mesh((2, 2), ("dp", "tp"))
    gen = make_ring_generate(cfg, mesh, 9, quantize_kv=True)
    got_sh = gen(
        shard_params(params, cfg, mesh),
        jax.device_put(prompt, NamedSharding(mesh, P("dp", None))),
    )
    np.testing.assert_array_equal(np.asarray(got_sh), np.asarray(want))


def test_aligned_prefill_scan_matches_one_shot():
    """The quantized ring oracle prefill's ``lax.scan``-ed full chunks
    are the same math as one directly traced chunk: every position
    attends the already-quantized cache either way, so the chunk size
    is invisible (the identity generate_ring_dense's docstring claims).
    chunk=4 over a 13-token prompt forces the scan body (3 full chunks)
    plus the ragged tail; chunk=64 traces the whole prompt at once."""
    cfg = dataclasses.replace(CFG, attn_window=5)
    params = init_params(cfg, seed=9)
    prompt = _toks(2, 13, seed=10)

    def run(chunk):
        c = init_cache(cfg, 2, 13, quantize_kv=True)
        return _aligned_quantized_prefill(
            params, prompt, c, cfg, decode_kernel=False, chunk=chunk
        )

    lg_scan, c_scan = run(4)
    lg_one, c_one = run(64)
    # each call returns its LAST chunk's logits; only the final
    # position overlaps (and it is the one generation consumes)
    np.testing.assert_allclose(
        np.asarray(lg_scan[:, -1]), np.asarray(lg_one[:, -1]),
        atol=1e-4, rtol=0,
    )
    for a, b in zip(c_scan, c_one):
        np.testing.assert_array_equal(
            np.asarray(a["k"]), np.asarray(b["k"])
        )
        np.testing.assert_array_equal(
            np.asarray(a["v"]), np.asarray(b["v"])
        )


def test_chunked_extend_quantized_matches_prefill():
    """Streaming prefill vs one-shot with int8 cache. Layer 0's cache
    is BITWISE equal (same embeddings -> same K/V -> same quantizer).
    Deeper layers and logits agree to quantization tolerance only: the
    extend path attends through the quantized cache while one-shot
    prefill's chunk kernel attends the exact chunk K/V, so layer-1+
    activations (hence their K/V, hence the rounding) drift by the
    quantization error — the documented asymmetry of exact-prefill."""
    mesh = make_mesh((1, 2), ("dp", "tp"))
    params = shard_params(init_params(CFG, seed=7), CFG, mesh)
    prompt = jax.device_put(
        _toks(1, 8, seed=8), NamedSharding(mesh, P("dp", None))
    )
    Lmax = 10
    prefill = make_prefill(CFG, mesh, quantize_kv=True)
    c0 = shard_cache(init_cache(CFG, 1, Lmax, mesh, quantize_kv=True),
                     CFG, mesh)
    lg_one, c_one = prefill(params, prompt, c0)
    extend = make_extend(CFG, mesh, quantize_kv=True)
    c = shard_cache(init_cache(CFG, 1, Lmax, mesh, quantize_kv=True),
                    CFG, mesh)
    for i in range(0, 8, 4):
        lg, c = extend(params, prompt[:, i:i + 4], c, jnp.int32(i))
    np.testing.assert_allclose(
        np.asarray(lg[:, -1]), np.asarray(lg_one), atol=1e-2
    )
    for kk in ("k", "v"):  # layer 0: bitwise
        np.testing.assert_array_equal(
            np.asarray(c[0][kk]), np.asarray(c_one[0][kk])
        )
    for la, lb in zip(c[1:], c_one[1:]):  # deeper: dequant tolerance
        for kk in ("k", "v"):
            da = np.asarray(la[kk], np.float32) * np.asarray(
                la[f"{kk}_s"]
            )[..., None]
            db = np.asarray(lb[kk], np.float32) * np.asarray(
                lb[f"{kk}_s"]
            )[..., None]
            np.testing.assert_allclose(da, db, atol=2e-2)


D128 = TransformerConfig(
    vocab=97, d_model=256, n_heads=2, n_kv_heads=1, n_layers=2,
    d_ff=256,
)  # head_dim 128: the decode kernel's lane gate


@pytest.mark.parametrize("window", [None, 128])
def test_batched_auto_kernel_in_scan_matches_einsum(window):
    """B=4 >= KERNEL_MIN_BATCH: the AUTO default routes the in-scan
    decode steps through the Pallas int8 kernel (interpreted on the CI
    mesh) — token streams equal the einsum dequant path exactly, full
    and sliding-window masks both."""
    from mpistragglers_jl_tpu.models.decode import (
        KERNEL_MIN_BATCH,
        use_decode_kernel,
    )

    cfg = dataclasses.replace(D128, attn_window=window)
    params = init_params(cfg, seed=9)
    B = KERNEL_MIN_BATCH
    rng = np.random.default_rng(10)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (B, 6)), jnp.int32)
    use_decode_kernel(False)
    try:
        want = generate_dense(params, prompt, 7, cfg, quantize_kv=True)
    finally:
        use_decode_kernel(None)  # the AUTO default routes at B >= 4
    got = generate_dense(params, prompt, 7, cfg, quantize_kv=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_ring_kernel_in_scan_matches_einsum():
    """The O(W) ring generator at batch: AUTO routes the kernel's
    ring mode inside the decode scan; streams equal the einsum path."""
    from mpistragglers_jl_tpu.models.decode import use_decode_kernel

    cfg = dataclasses.replace(D128, attn_window=128)
    params = init_params(cfg, seed=11)
    rng = np.random.default_rng(12)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (4, 6)), jnp.int32)
    use_decode_kernel(False)
    try:
        want = generate_ring_dense(params, prompt, 8, cfg,
                                   quantize_kv=True)
    finally:
        use_decode_kernel(None)
    got = generate_ring_dense(params, prompt, 8, cfg, quantize_kv=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_auto_skips_kernel_below_min_batch():
    """B=1 under AUTO stays on the einsum path (the per-call scan
    boundary cost isn't amortized): the program must still match the
    forced-einsum stream AND the forced-kernel stream — routing is a
    perf decision, never a numerics one."""
    from mpistragglers_jl_tpu.models.decode import use_decode_kernel

    params = init_params(D128, seed=13)
    rng = np.random.default_rng(14)
    prompt = jnp.asarray(rng.integers(0, D128.vocab, (1, 5)), jnp.int32)
    auto = generate_dense(params, prompt, 6, D128, quantize_kv=True)
    use_decode_kernel(False)
    try:
        ein = generate_dense(params, prompt, 6, D128, quantize_kv=True)
    finally:
        use_decode_kernel(None)
    use_decode_kernel(True)
    try:
        kern = generate_dense(params, prompt, 6, D128, quantize_kv=True)
    finally:
        use_decode_kernel(None)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(ein))
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(kern))


def test_shard_cache_places_scale_leaves():
    mesh = make_mesh((2, 2, 2), ("dp", "ep", "tp"))
    cfg = CFG  # dense: ep unused by specs but mesh may carry it
    c = shard_cache(init_cache(cfg, 2, 16, mesh, quantize_kv=True),
                    cfg, mesh)
    sh = c[0]["k_s"].sharding
    assert sh.spec == P(("dp",), None, "tp")
