"""Hierarchical two-level coded GEMM (ISSUE 9): outer codes, the
two-level predicate, decode identity under host loss, the joint
(outer_rate, inner_nwait) sweep, and the kill-group fault.

The acceptance chain: the decode identity grid (groups H in {2, 4} x
inner MDS/LT x {0, 1} killed groups x f32/bf16, all on ``SimBackend``
— jax-on-CPU, tier-1), a property test that the outer floor refusal
triggers exactly below L = H*rate arrived groups, the pinned
``sweep_hierarchical`` refusal + latency-model-agreement test, and
bit-identical kill-one-host replays. Everything runs on virtual time;
no wall-clock margins anywhere (GC008 discipline by construction).
"""

import itertools
import pickle

import ml_dtypes
import numpy as np
import pytest

from mpistragglers_jl_tpu import AsyncPool, SimBackend, asyncmap, waitall
from mpistragglers_jl_tpu.ops import HierarchicalCodedGemm
from mpistragglers_jl_tpu.ops.outer_code import (
    LTOuter,
    ParityOuter,
    hierarchical_nwait,
    make_outer,
    partition_groups,
)
from mpistragglers_jl_tpu.parallel import host_groups
from mpistragglers_jl_tpu.sim import sweep_hierarchical
from mpistragglers_jl_tpu.utils import faults
from mpistragglers_jl_tpu.utils.straggle import PoolLatencyModel


def _problem(dtype, m=72, kdim=16, ncols=12, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((m, kdim)).astype(np.float32)
    B = rng.standard_normal((kdim, ncols)).astype(np.float32)
    if dtype == "bfloat16":
        A = A.astype(ml_dtypes.bfloat16)
        B = B.astype(ml_dtypes.bfloat16)
    ref = A.astype(np.float32) @ B.astype(np.float32)
    return A, B, ref


# --------------------------------------------------------------------------
# outer codes (pure numpy, no pool)
# --------------------------------------------------------------------------


class TestOuterCodes:
    def test_parity_decodes_from_any_single_missing_group(self):
        rng = np.random.default_rng(1)
        outer = ParityOuter(4)
        src = rng.standard_normal((3, 5, 4)).astype(np.float32)
        G = outer.generator_rows()
        coded = np.einsum("hl,lrc->hrc", G, src)
        for missing in range(4):
            ids = [g for g in range(4) if g != missing]
            assert outer.decodable(ids)
            out = outer.decode([coded[g] for g in ids], ids)
            np.testing.assert_allclose(out, src, rtol=1e-5, atol=1e-6)
        assert not outer.decodable([0, 1])  # two losses: below floor
        with pytest.raises(ValueError, match="outer decodability floor"):
            outer.decode([coded[0], coded[1]], [0, 1])

    def test_parity_select_prefers_pure_sources(self):
        outer = ParityOuter(4)
        assert outer.select([0, 1, 2, 3]) == [0, 1, 2]  # gather only
        assert outer.select([0, 2, 3]) == [0, 2, 3]  # parity recovery
        with pytest.raises(ValueError, match="outer floor"):
            outer.select([1, 3])

    def test_lt_outer_survives_multi_group_loss(self):
        """Rate 2/4: H - L = 2 coded groups, so two simultaneous host
        losses can still decode when the survivors peel."""
        rng = np.random.default_rng(2)
        outer = LTOuter(4, 2, seed=0)
        src = rng.standard_normal((2, 5, 4)).astype(np.float32)
        coded = np.einsum(
            "hl,lrc->hrc", outer.generator_rows(), src
        )
        full = list(range(4))
        assert outer.decodable(full)
        survivors = [
            ids
            for ids in itertools.combinations(full, 2)
            if outer.decodable(list(ids))
        ]
        assert survivors, "no 2-of-4 survivor set peels"
        for ids in survivors:
            out = outer.decode([coded[g] for g in ids], list(ids))
            np.testing.assert_allclose(out, src, rtol=1e-5, atol=1e-6)

    def test_make_outer_rates_and_refusals(self):
        assert make_outer(4).kind == "parity"  # default (H-1)/H
        assert make_outer(4, rate=0.5).kind == "lt"
        assert make_outer(4, rate=0.5).L == 2
        with pytest.raises(ValueError, match="outer decodability floor"):
            make_outer(4, rate=0.05)  # rounds to L=0
        with pytest.raises(ValueError, match="L=5 > H"):
            make_outer(4, rate=1.25)
        with pytest.raises(ValueError, match="rate \\(H-1\\)/H"):
            make_outer(4, rate=0.5, kind="parity")

    def test_partition_groups_contract(self):
        part = partition_groups(8, 2)
        assert [p.tolist() for p in part] == [[0, 1, 2, 3], [4, 5, 6, 7]]
        explicit = partition_groups(4, [[2, 3], [0, 1]])
        assert [p.tolist() for p in explicit] == [[2, 3], [0, 1]]
        with pytest.raises(ValueError, match="evenly"):
            partition_groups(8, 3)
        with pytest.raises(ValueError, match="equal-sized"):
            partition_groups(3, [[0, 1], [2]])
        with pytest.raises(ValueError, match="exactly once"):
            partition_groups(4, [[0, 1], [1, 2]])


# --------------------------------------------------------------------------
# decode identity grid: the ISSUE 9 acceptance matrix
# --------------------------------------------------------------------------


class TestDecodeIdentity:
    TOL = {"float32": 1e-3, "bfloat16": 5e-2}

    @pytest.mark.parametrize("H", [2, 4])
    @pytest.mark.parametrize("inner", ["mds", "lt"])
    @pytest.mark.parametrize("killed", [0, 1])
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_result_equals_plain_matmul(self, H, inner, killed, dtype):
        """hierarchical result == A @ B across (groups) x (inner code)
        x (killed groups) x dtype, on SimBackend — including the
        outer-recovery path when a whole group is dead."""
        A, B, ref = _problem(dtype)
        hg = HierarchicalCodedGemm(
            A, groups=H, n_inner=4, k_inner=3, inner=inner,
            device_backend=False,
        )
        delay = faults.seeded_uniform(0.001, 0.01, seed=7)
        if killed:
            delay = faults.compose(
                delay,
                faults.kill_group(hg.group_indices, {H - 1: 1}),
            )
        be = SimBackend(hg.work, hg.n_workers, delay_fn=delay)
        pool = AsyncPool(hg.n_workers)
        scale = float(np.max(np.abs(ref)))
        for _ in range(2):  # the kill lands on the FIRST epoch already
            asyncmap(pool, B, be, nwait=hg.nwait)
            C = hg.result(pool)
            assert C.shape == ref.shape
            err = float(np.max(np.abs(C - ref))) / scale
            assert err < self.TOL[dtype], (H, inner, killed, dtype, err)
        if killed:
            assert H - 1 not in hg.arrived_groups(pool)

    def test_device_backend_path(self):
        """The default XLADeviceBackend construction (jax-on-CPU): the
        same predicate + decode through the real device backend."""
        A, B, ref = _problem("float32")
        hg = HierarchicalCodedGemm(A, groups=2, n_inner=4, k_inner=3)
        try:
            pool = AsyncPool(hg.n_workers)
            asyncmap(pool, B, hg.backend, nwait=hg.nwait)
            waitall(pool, hg.backend)
            C = hg.result(pool)
            err = np.max(np.abs(C - ref)) / np.max(np.abs(ref))
            assert err < 1e-3
        finally:
            hg.backend.shutdown()

    def test_construction_refusals(self):
        A = np.zeros((12, 4), np.float32)
        with pytest.raises(ValueError, match="n_inner is required"):
            HierarchicalCodedGemm(A, groups=2, k_inner=2)
        with pytest.raises(ValueError, match="divide evenly"):
            # L*k_inner = 3*3 = 9 does not divide the 12 rows
            HierarchicalCodedGemm(
                A, groups=4, n_inner=4, k_inner=3, device_backend=False
            )
        with pytest.raises(ValueError, match="k_inner <= n_inner"):
            HierarchicalCodedGemm(
                A, groups=2, n_inner=2, k_inner=3, device_backend=False
            )
        with pytest.raises(ValueError, match="contradict n_inner"):
            HierarchicalCodedGemm(
                A, groups=[[0, 1], [2, 3]], n_inner=3, k_inner=2,
                device_backend=False,
            )


# --------------------------------------------------------------------------
# the outer floor property: refusal triggers exactly below H*rate groups
# --------------------------------------------------------------------------


class TestOuterFloorProperty:
    def test_predicate_fires_exactly_at_the_floor(self):
        """Parity (H=4, L=3): over EVERY subset of groups, the
        two-level predicate is true iff >= L groups cleared their
        inner floor — never below, always at."""
        A = np.zeros((36, 4), np.float32)
        hg = HierarchicalCodedGemm(
            A, groups=4, n_inner=4, k_inner=3, device_backend=False
        )
        pred = hg.nwait
        epoch = 5
        for r in range(5):
            for groups_up in itertools.combinations(range(4), r):
                repochs = np.zeros(16, dtype=np.int64)
                for g in groups_up:
                    # exactly k_inner fresh members clear the floor
                    repochs[hg.group_indices[g][: hg.k_inner]] = epoch
                assert pred(epoch, repochs) == (len(groups_up) >= hg.L)

    def test_one_fresh_short_of_inner_floor_does_not_arrive(self):
        A = np.zeros((36, 4), np.float32)
        hg = HierarchicalCodedGemm(
            A, groups=4, n_inner=4, k_inner=3, device_backend=False
        )
        repochs = np.zeros(16, dtype=np.int64)
        for g in range(4):
            repochs[hg.group_indices[g][: hg.k_inner - 1]] = 3
        assert not hg.nwait(3, repochs)  # 0 groups arrived

    def test_lt_outer_floor_never_fires_below_L(self):
        A = np.zeros((24, 4), np.float32)
        hg = HierarchicalCodedGemm(
            A, groups=4, n_inner=4, k_inner=3, outer="lt",
            outer_rate=0.5, device_backend=False,
        )
        assert hg.L == 2
        epoch = 2
        for r in range(hg.L):  # every subset strictly below the floor
            for groups_up in itertools.combinations(range(4), r):
                repochs = np.zeros(16, dtype=np.int64)
                for g in groups_up:
                    repochs[hg.group_indices[g][: hg.k_inner]] = epoch
                assert not hg.nwait(epoch, repochs)

    def test_result_refuses_below_floor_naming_both_floors(self):
        A, B, _ = _problem("float32")
        hg = HierarchicalCodedGemm(
            A, groups=4, n_inner=4, k_inner=3, device_backend=False
        )
        # only 2 of 4 groups respond at all: below the L=3 outer floor
        be = SimBackend(
            hg.work, hg.n_workers,
            delay_fn=faults.kill_group(
                hg.group_indices, {2: 0, 3: 0}
            ),
        )
        pool = AsyncPool(hg.n_workers)
        with pytest.raises(Exception):
            # unsatisfiable predicate: bound the call, harvest the error
            asyncmap(pool, B, be, nwait=hg.nwait, timeout=5.0)
        with pytest.raises(ValueError, match="outer floor needs 3"):
            hg.result(pool)


# --------------------------------------------------------------------------
# kill_group: the scheduled whole-host fault
# --------------------------------------------------------------------------


class TestKillGroup:
    def test_delay_fn_conventions(self):
        part = [[0, 1], [2, 3]]
        k = faults.kill_group(part, {1: 3})
        assert k(2, 2) == 0.0 and k(2, 3) == 3600.0 and k(3, 9) == 3600.0
        assert k(0, 100) == 0.0
        assert k.killed_groups == [1]
        # pure + picklable (DelayFn conventions, process workers)
        assert pickle.loads(pickle.dumps(k))(3, 5) == 3600.0
        # duplicate kills keep the earliest epoch
        k2 = faults.kill_group([[0], [0]], {0: 5, 1: 2})
        assert k2(0, 2) == 3600.0
        with pytest.raises(ValueError, match="names group 7"):
            faults.kill_group(part, {7: 1})

    def test_schedule_builder_composes(self):
        part = [[0, 1], [2, 3]]
        sched = faults.FaultSchedule(seed=3).jitter(0.001, 0.002)
        sched.kill_group(part, {0: 4})
        assert "kill_group({0: 4})" in repr(sched)
        assert sched.delay_fn(1, 4) > 3600.0

    def test_kill_one_host_sim_run_is_bit_identical(self):
        """The ISSUE 9 determinism acceptance: a kill-one-host run
        completes every epoch with an exact decode, twice, with
        bit-identical virtual walls AND decoded bytes."""
        A, B, ref = _problem("float32")

        def run():
            hg = HierarchicalCodedGemm(
                A, groups=4, n_inner=4, k_inner=3,
                device_backend=False,
            )
            be = SimBackend(
                hg.work, hg.n_workers,
                delay_fn=faults.compose(
                    faults.seeded_lognormal(0.01, 1.0, seed=5),
                    faults.kill_group(hg.group_indices, {1: 3}),
                ),
            )
            pool = AsyncPool(hg.n_workers)
            walls, outs = [], []
            for _ in range(6):
                t0 = be.clock.now()
                asyncmap(pool, B, be, nwait=hg.nwait)
                walls.append(be.clock.now() - t0)
                outs.append(hg.result(pool))  # every epoch decodes
            return walls, outs

        w1, o1 = run()
        w2, o2 = run()
        scale = float(np.max(np.abs(ref)))
        for C in o1:  # zero lost epochs, all exact
            assert float(np.max(np.abs(C - ref))) / scale < 1e-3
        assert w1 == w2
        assert all(np.array_equal(a, b) for a, b in zip(o1, o2))


# --------------------------------------------------------------------------
# obs: counters + the flight-recorder recovery event
# --------------------------------------------------------------------------


class TestHierObs:
    def test_counters_and_flight_event_on_recovery(self):
        from mpistragglers_jl_tpu.obs import FlightRecorder, MetricsRegistry

        A, B, _ = _problem("float32")
        reg = MetricsRegistry()
        fl = FlightRecorder()
        hg = HierarchicalCodedGemm(
            A, groups=4, n_inner=4, k_inner=3, device_backend=False,
            registry=reg, flight=fl,
        )
        snap = reg.snapshot()
        assert snap["hier_groups"]["series"][0]["value"] == 4
        assert snap["hier_outer_floor"]["series"][0]["value"] == 3
        be = SimBackend(hg.work, hg.n_workers)
        pool = AsyncPool(hg.n_workers)
        # epoch 1: everyone answers -> pure source gather, no recovery
        asyncmap(pool, B, be, nwait=16)
        hg.result(pool)
        snap = reg.snapshot()
        assert snap["hier_outer_recoveries_total"]["series"][0]["value"] == 0
        assert snap["hier_group_losses_total"]["series"][0]["value"] == 0
        inner = {
            s["labels"]["group"]: s["value"]
            for s in snap["hier_inner_decode_total"]["series"]
        }
        # parity group 3 exists dark at 0: constructed, never consumed
        assert inner == {"0": 1, "1": 1, "2": 1, "3": 0}
        assert len(fl) == 0  # no recovery, no event
        # epoch 2: group 1 dead -> outer recovery, counted + recorded
        be2 = SimBackend(
            hg.work, hg.n_workers,
            delay_fn=faults.kill_group(hg.group_indices, {1: 0}),
        )
        pool2 = AsyncPool(hg.n_workers)
        asyncmap(pool2, B, be2, nwait=hg.nwait)
        hg.result(pool2)
        snap = reg.snapshot()
        assert snap["hier_outer_recoveries_total"]["series"][0]["value"] == 1
        assert snap["hier_group_losses_total"]["series"][0]["value"] == 1
        doc = fl.snapshot()
        names = [
            e["name"] for e in doc["traceEvents"] if e.get("ph") == "I"
        ]
        assert "hier outer recovery" in names
        ev = next(
            e for e in doc["traceEvents"]
            if e.get("name") == "hier outer recovery"
        )
        assert ev["args"]["missing_groups"] == [1]

    def test_dark_path_stays_dark(self):
        A, B, _ = _problem("float32")
        hg = HierarchicalCodedGemm(
            A, groups=2, n_inner=4, k_inner=3, device_backend=False
        )
        assert hg._m is None and hg._flight is None
        be = SimBackend(hg.work, hg.n_workers)
        pool = AsyncPool(hg.n_workers)
        asyncmap(pool, B, be, nwait=hg.nwait)
        hg.result(pool)  # no registry, no flight: must not throw


# --------------------------------------------------------------------------
# sweep_hierarchical: refusals + the pinned latency-model agreement
# --------------------------------------------------------------------------


def _pinned_fleet(w, e):
    """Per group of 8: six fast workers (10-16 ms, deterministic
    jitter) + two 1 s stragglers — the inner optimum is sharply 6."""
    j = w % 8
    if j >= 6:
        return 1.0
    return 0.010 + 0.001 * j + 0.005 * ((w * 7 + e) % 3) / 3


class TestSweepHierarchical:
    def test_refuses_below_either_floor(self):
        with pytest.raises(ValueError, match="inner decodability floor"):
            sweep_hierarchical(
                _pinned_fleet, groups=4, n_inner=8,
                candidates=[(0.75, 1)], inner_floor=2, epochs=5,
            )
        with pytest.raises(ValueError, match="outer decodability floor"):
            sweep_hierarchical(
                _pinned_fleet, groups=4, n_inner=8,
                candidates=[(0.05, 6)], epochs=5,
            )
        with pytest.raises(ValueError, match="survive the scheduled"):
            sweep_hierarchical(
                _pinned_fleet, groups=4, n_inner=8,
                candidates=[(1.0, 6)], failures={0: 3}, epochs=5,
            )
        with pytest.raises(ValueError, match="exceeds the 8 workers"):
            sweep_hierarchical(
                _pinned_fleet, groups=4, n_inner=8,
                candidates=[(0.75, 9)], epochs=5,
            )

    def test_refusal_checks_surviving_id_set_not_count(self):
        """Review finding: at k=2 the LT patch distribution draws only
        degree-2 coded shards, so survivors {2, 3} of an (H=4, L=2) LT
        outer can never peel even though their COUNT equals L. The
        count check let this candidate run and priced the 3600 s
        dead-worker stall as data (mean epoch ~3000 s); it must be
        refused like every other below-floor pair."""
        from mpistragglers_jl_tpu.ops.outer_code import LTOuter

        assert not LTOuter(4, 2, seed=0).decodable([2, 3])
        with pytest.raises(ValueError, match="cannot\\s+clear the outer"):
            sweep_hierarchical(
                _pinned_fleet, groups=4, n_inner=4,
                candidates=[(0.5, 3)], failures={0: 2, 1: 2}, epochs=6,
            )

    def test_kill_scheduled_beyond_the_run_leaves_survivors(self):
        """Review finding: a kill epoch past the sweep's horizon never
        fires, so those groups ARE survivors — the cross-check must
        pick one instead of crashing on an empty candidate set."""
        res = sweep_hierarchical(
            _pinned_fleet, groups=2, n_inner=8,
            candidates=[(0.5, 6)], failures={0: 1000, 1: 1000},
            epochs=5,
        )
        assert res["surviving_groups"] == 2
        assert res["check_group"] == 0

    def test_pinned_recommendation_agrees_with_latency_model(self):
        """The ISSUE 9 acceptance pin: on the seeded fleet with one
        scheduled host kill, the sim sweep lands on (0.75, 6) —
        highest feasible outer rate, inner nwait dodging the two
        per-group stragglers — and the PoolLatencyModel cross-check
        over a surviving group agrees."""
        cands = [(r, k) for r in (0.5, 0.75) for k in (4, 6, 8)]
        res = sweep_hierarchical(
            _pinned_fleet, groups=4, n_inner=8, candidates=cands,
            inner_floor=2, epochs=40, failures={2: 10}, seed=3,
        )
        assert res["best"] == (0.75, 6)
        assert res["inner_sim"] == res["inner_model"] == 6
        assert res["agree"] is True
        assert res["check_group"] == 0  # first group NOT killed
        assert res["surviving_groups"] == 3
        # deep stragglers poison k=8 in every rate: pinned ordering
        by = {(r["outer_rate"], r["inner_nwait"]): r for r in res["entries"]}
        assert by[(0.75, 6)]["utility_per_s"] > by[(0.75, 4)]["utility_per_s"]
        assert by[(0.75, 8)]["mean_epoch_s"] >= 1.0
        # bit-identical across calls (virtual time, seeded fleet)
        res2 = sweep_hierarchical(
            _pinned_fleet, groups=4, n_inner=8, candidates=cands,
            inner_floor=2, epochs=40, failures={2: 10}, seed=3,
        )
        assert res["entries"] == res2["entries"]

    def test_model_source_uses_group_stats_directly(self):
        model = PoolLatencyModel(8, seed=1)
        rng = np.random.default_rng(4)
        for w in range(8):
            base = 0.01 if w % 4 != 3 else 0.5
            for x in base + rng.exponential(0.002, 60):
                model.observe(w, x)
        res = sweep_hierarchical(
            model, groups=2, n_inner=4,
            candidates=[(0.5, 2), (0.5, 3)], epochs=15, seed=1,
        )
        assert res["inner_model"] == 3  # wait out all three fast ranks
        assert res["best"][1] == 3 and res["agree"]

    def test_fleet_width_mismatch_is_refused(self):
        model = PoolLatencyModel(6)
        with pytest.raises(ValueError, match="describes 6 workers"):
            sweep_hierarchical(
                model, groups=4, n_inner=8, candidates=[(0.75, 4)],
            )


# --------------------------------------------------------------------------
# multihost wiring
# --------------------------------------------------------------------------


class TestHostGroups:
    def test_even_split_without_a_mesh(self):
        assert host_groups(8, n_hosts=2) == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert host_groups(6, n_hosts=3) == [[0, 1], [2, 3], [4, 5]]
        with pytest.raises(ValueError, match="evenly"):
            host_groups(8, n_hosts=3)
        with pytest.raises(ValueError, match="needs n_workers"):
            host_groups(8)

    def test_single_process_mesh_groups_by_process(self):
        import jax

        from mpistragglers_jl_tpu.parallel import make_multihost_mesh

        n = len(jax.devices())
        mesh = make_multihost_mesh((n,), ("w",))
        groups = host_groups(mesh=mesh)
        # one process in tests: every position lands in its one group
        assert sorted(sum(groups, [])) == list(range(n))
        assert len(groups) == 1

    def test_partition_feeds_hierarchical_gemm(self):
        A, B, ref = _problem("float32")
        groups = host_groups(8, n_hosts=2)
        hg = HierarchicalCodedGemm(
            A, groups=groups, k_inner=3, device_backend=False
        )
        assert hg.H == 2 and hg.n_inner == 4
        be = SimBackend(hg.work, 8)
        pool = AsyncPool(8)
        asyncmap(pool, B, be, nwait=hg.nwait)
        C = hg.result(pool)
        assert np.max(np.abs(C - ref)) / np.max(np.abs(ref)) < 1e-3
