"""Byte-exact buffer parity (SURVEY C5; reference src/MPIAsyncPools.jl:80-84).

The reference type-erases every caller buffer via ``reinterpret(UInt8, ...)``
so a pool is payload-agnostic: mixed dtypes, structured records — anything
with a fixed byte layout — round-trips bit-exactly through ``recvbuf``.
These tests ship float64 + int64 mixed payloads (and structured records)
through the Local, Process, and Native backends and assert bit identity,
and pin down the no-silent-cast contract: a result whose byte width
doesn't fill its chunk is an error, never an ``astype``.
"""

import numpy as np
import pytest

from mpistragglers_jl_tpu import AsyncPool, asyncmap, waitall
from mpistragglers_jl_tpu.backends.local import LocalBackend

# bit patterns that expose value-casting: NaN payloads survive a bitcopy
# but not a float round-trip through a different width; huge int64s lose
# bits through float64
_F64 = np.array([np.pi, -0.0, np.inf, np.float64.__call__(np.nan)])
_I64 = np.array([2**62 + 3, -1, 2**53 + 1, 7], dtype=np.int64)


def _mixed_work(i, payload, epoch):
    """Even workers ship int64, odd workers float64 — same byte width."""
    if i % 2 == 0:
        return _I64 + i
    return _F64 + i


_REC_DT = np.dtype([("id", np.int32), ("x", np.float64), ("tag", "S4")])


def _record_work(i, payload, epoch):
    out = np.zeros(2, dtype=_REC_DT)
    out["id"] = [i, i + 100]
    out["x"] = [np.pi * i, np.nan]
    out["tag"] = [b"abcd", b"wxyz"]
    return out


def _f32_work(i, payload, epoch):
    return np.ones(4, dtype=np.float32)


def _make_backend(kind, work_fn, n):
    if kind == "local":
        return LocalBackend(work_fn, n)
    if kind == "process":
        from mpistragglers_jl_tpu.backends.process import ProcessBackend

        return ProcessBackend(work_fn, n)
    from mpistragglers_jl_tpu.native import NativeBuildError

    try:
        from mpistragglers_jl_tpu.backends.native import NativeProcessBackend

        return NativeProcessBackend(work_fn, n)
    except NativeBuildError as e:  # pragma: no cover - no compiler
        pytest.skip(f"native transport unavailable: {e}")


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["local", "process", "native"])
def test_mixed_dtype_payloads_bit_identical(kind):
    """float64 + int64 payloads land bit-exactly in one recvbuf; the
    caller reinterprets each chunk with its worker's dtype — the
    reference's byte-view contract, not a value cast."""
    n = 4
    backend = _make_backend(kind, _mixed_work, n)
    try:
        pool = AsyncPool(n)
        recvbuf = np.zeros(4 * n)  # float64 arena; 8-byte elements
        asyncmap(pool, np.zeros(1), backend, recvbuf, nwait=n)
        chunks = recvbuf.reshape(n, 4)
        for i in range(n):
            if i % 2 == 0:
                got = chunks[i].view(np.int64)
                assert np.array_equal(got, _I64 + i), got
            else:
                got = chunks[i]
                want = _F64 + i
                assert got.tobytes() == want.tobytes()  # NaN-safe, bitwise
    finally:
        backend.shutdown()


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["local", "process", "native"])
def test_structured_records_roundtrip(kind):
    """A structured-dtype recvbuf (the reference's 'anything isbits')
    gathers worker records bit-exactly."""
    n = 3
    backend = _make_backend(kind, _record_work, n)
    try:
        pool = AsyncPool(n)
        recvbuf = np.zeros(2 * n, dtype=_REC_DT)
        asyncmap(pool, np.zeros(1), backend, recvbuf, nwait=n)
        recs = recvbuf.reshape(n, 2)
        for i in range(n):
            assert recs[i].tobytes() == _record_work(i, None, 1).tobytes()
    finally:
        backend.shutdown()


def test_width_mismatch_errors_not_casts():
    """A float32 result does not fill a float64 chunk: hard error at
    harvest (previously a silent astype — VERDICT round 1, C5)."""
    n = 2
    backend = LocalBackend(_f32_work, n)
    try:
        pool = AsyncPool(n)
        recvbuf = np.zeros(4 * n)  # float64: 2x the bytes of the result
        with pytest.raises(ValueError, match="bit-cop"):
            asyncmap(pool, np.zeros(1), backend, recvbuf, nwait=n)
        waitall(pool, backend)  # pool stays drainable without a recvbuf
        # matching the width works — and is a bitcopy
        pool2 = AsyncPool(n)
        recvbuf32 = np.zeros(4 * n, dtype=np.float32)
        asyncmap(pool2, np.zeros(1), backend, recvbuf32, nwait=n)
        assert np.array_equal(recvbuf32, np.ones(4 * n, dtype=np.float32))
    finally:
        backend.shutdown()


def test_noncontiguous_recvbuf_rejected():
    """Byte views need contiguity; a strided recvbuf would silently
    gather into a throwaway copy, so it is refused up front."""
    backend = LocalBackend(_mixed_work, 2)
    try:
        pool = AsyncPool(2)
        recvbuf = np.zeros(16)[::2]
        with pytest.raises(ValueError, match="contiguous"):
            asyncmap(pool, np.zeros(1), backend, recvbuf, nwait=2)
    finally:
        backend.shutdown()


def test_mis_sized_recvbuf_fails_before_dispatch():
    """Reference parity (src/MPIAsyncPools.jl:72-76): buffer validation
    fires before any communication. With a worker still in flight, an
    asyncmap whose recvbuf chunks can't hold that worker's results
    raises pre-dispatch, not mid-epoch."""

    class Gate:
        """Worker 1 blocks from epoch 2 on, until released."""

        def __init__(self):
            import threading

            self.ev = threading.Event()

        def __call__(self, i, epoch):
            if i == 1 and epoch >= 2 and not self.ev.is_set():
                self.ev.wait(5.0)
            return 0.0

    gate = Gate()
    backend = LocalBackend(
        lambda i, p, e: np.full(4, float(i)), 2, delay_fn=gate
    )
    try:
        pool = AsyncPool(2)
        recvbuf = np.zeros(8)
        asyncmap(pool, np.zeros(1), backend, recvbuf, nwait=2)  # all land
        asyncmap(pool, np.zeros(1), backend, recvbuf, nwait=1)  # 1 stalls
        assert pool.active[1]  # straggler in flight, epoch-1 result known
        bad = np.zeros(4)  # chunks half the known result size
        with pytest.raises(ValueError, match="before dispatching"):
            asyncmap(pool, np.zeros(1), backend, bad, nwait=1)
        gate.ev.set()
        waitall(pool, backend, recvbuf)
        assert not pool.active.any()
    finally:
        backend.shutdown()
