"""Elastic fleet control (round 18, fleet/): closed-loop autoscaling,
sim-in-the-loop re-coding, and coordinator failover.

Three layers, all tier-1 on VirtualClock (the GC008 contract — the
controller reads only its injected clock, so every scenario here
replays bit-identically):

* **signals** — the deterministic rate estimator, the one
  replica-capacity formula, live-gauge snapshots, and fleet-resize
  model extrapolation, each with its refusal contract;
* **controller** — hysteresis bands (dwell/cooldown), zero-drop shrink
  through the router's eject/re-route path, the operator
  ``resize_to``/``FleetResize`` event path, re-coding via
  ``sweep_hierarchical`` (agree flag, decision budget fallback,
  refusal-by-name propagation) and re-policy via
  ``sweep_router_policy`` (structural policies never switched);
* **failover** — coded-checkpoint state round trips, the
  active/standby supervisor surviving a mid-day ``CoordinatorKill``
  with zero drops and a bit-identical replay, and the POOL-plane leg
  on a real ``ProcessBackend``: the standby adopts the living worker
  processes and the ``repochs`` history is continuous across the
  handoff (no epoch lost), with the takeover named in the flight dump.
"""

import os

import numpy as np
import pytest

from mpistragglers_jl_tpu import (
    AsyncPool,
    LocalBackend,
    ProcessBackend,
    asyncmap,
    waitall,
)
from mpistragglers_jl_tpu.fleet import (
    ArrivalRateEstimator,
    ControllerSupervisor,
    FleetCheckpointer,
    FleetController,
    PoolScaler,
    adopt_pool,
    capture_pool,
    fleet_signals,
    replica_capacity_rps,
    resized_model,
    restore_pool,
)
from mpistragglers_jl_tpu.models.router import RequestRouter
from mpistragglers_jl_tpu.obs import FlightRecorder, MetricsRegistry
from mpistragglers_jl_tpu.sim import (
    CoordinatorKill,
    FleetResize,
    SimPrompt,
    SimReplica,
    VirtualClock,
    diurnal_arrivals,
    lognormal_ticks,
    poisson_arrivals,
    run_router_day,
)
from mpistragglers_jl_tpu.utils.straggle import PoolLatencyModel

# the one fleet shape every test here sizes against: slots=2 decode
# rows, n_inner=4 tokens per decode tick, 0.25 s ticks — small enough
# that a full diurnal day is a few thousand requests
SLOTS, NI, TICK, PLEN, CHUNK, MNEW = 2, 4, 0.25, 64, 64, 16
CAP = replica_capacity_rps(
    slots=SLOTS, n_inner=NI, tick_s=TICK, prompt_len=PLEN,
    prompt_chunk=CHUNK, max_new=MNEW,
)


def _fleet(n=4, *, jitter=0.0, clock=None):
    clock = VirtualClock() if clock is None else clock
    reps = [
        SimReplica(
            clock, slots=SLOTS, n_inner=NI, prompt_chunk=CHUNK,
            tick_s=(
                lognormal_ticks(TICK, jitter, seed=1009 + i)
                if jitter else TICK
            ),
        )
        for i in range(n)
    ]
    router = RequestRouter(reps, policy="least_loaded", clock=clock)
    return clock, reps, router


def _controller(router, clock, **kw):
    kw.setdefault("capacity_rps", CAP)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("decision_interval_s", 10.0)
    return FleetController(router, clock=clock, **kw)


def _fitted_model(n=NI, seed=5):
    model = PoolLatencyModel(n, seed=0)
    rng = np.random.default_rng(seed)
    for _ in range(40):
        for w in range(n):
            model.observe(
                w, 0.01 * (1 + 0.3 * w) * float(rng.lognormal(0, 0.3))
            )
    return model


# --------------------------------------------------------------------------
# signals
# --------------------------------------------------------------------------


class TestSignals:
    def test_rate_estimator_tracks_constant_rate(self):
        est = ArrivalRateEstimator(10.0)
        for k in range(1, 1201):  # 20/s for 60 s = 6 tau
            est.observe(k * 0.05)
        assert est.rate(60.0) == pytest.approx(20.0, rel=0.05)

    def test_rate_estimator_warmup_debias(self):
        # after only tau/2 seconds, the raw decayed count has reached
        # ~39% of settled — the debiased estimate is already usable
        est = ArrivalRateEstimator(20.0)
        for k in range(1, 201):  # 20/s for 10 s
            est.observe(k * 0.05)
        raw = est.count / est.tau_s
        assert raw < 0.5 * 20.0  # the bias the divisor removes
        assert est.rate(10.0) == pytest.approx(20.0, rel=0.15)

    def test_rate_estimator_tracks_a_swing_down(self):
        est = ArrivalRateEstimator(5.0, t0=0.0)
        t = 0.0
        for _ in range(200):  # 20/s
            t += 0.05
            est.observe(t)
        for _ in range(40):  # then 2/s for 4 tau
            t += 0.5
            est.observe(t)
        assert est.rate(t) == pytest.approx(2.0, rel=0.25)

    def test_rate_estimator_state_roundtrip_and_refusal(self):
        est = ArrivalRateEstimator(7.5, t0=3.0)
        for k in range(50):
            est.observe(3.0 + k * 0.1)
        clone = ArrivalRateEstimator(1.0)
        clone.load_state_dict(est.state_dict())
        assert clone.rate(10.0) == est.rate(10.0)
        with pytest.raises(ValueError, match="tau_s"):
            ArrivalRateEstimator(0.0)

    def test_replica_capacity_is_the_sweep_arithmetic(self):
        # the identical slot-holding-ticks formula sweep_router_policy
        # sizes offered load with: ceil(prompt/chunk) prefill ticks +
        # ceil((max_new-1)/n_inner) decode ticks per request
        ticks = (
            -(-PLEN // CHUNK) + -(-(MNEW - 1) // NI)
        )
        assert CAP == pytest.approx(SLOTS / (ticks * TICK))

    def test_replica_capacity_refusals(self):
        with pytest.raises(ValueError, match=">= 1"):
            replica_capacity_rps(
                slots=0, n_inner=NI, tick_s=TICK, prompt_len=PLEN,
                prompt_chunk=CHUNK, max_new=MNEW,
            )
        with pytest.raises(ValueError, match="tick_s"):
            replica_capacity_rps(
                slots=SLOTS, n_inner=NI, tick_s=0.0, prompt_len=PLEN,
                prompt_chunk=CHUNK, max_new=MNEW,
            )

    def test_resized_model_cycles_fits(self):
        model = _fitted_model(3)
        grown = resized_model(model, 7)
        assert grown.n_workers == 7
        # rank j is priced like fitted rank j % 3 — a fresh worker
        # never simulates as infinitely fast
        for j in range(7):
            # priced like fitted rank j % 3, but an independent COPY
            # (review regression: aliasing let observes into the twin
            # corrupt the live fits)
            assert grown.workers[j] is not model.workers[j % 3]
            assert (
                grown.workers[j].to_dict()
                == model.workers[j % 3].to_dict()
            )
        with pytest.raises(ValueError, match="fitted"):
            resized_model(PoolLatencyModel(0), 4)
        with pytest.raises(ValueError, match=">= 1"):
            resized_model(model, 0)

    def test_resized_model_fits_are_independent(self):
        """Review regression: resized_model used to ALIAS the live
        model's mutable fits (the same object at several indices), so
        observing into the twin corrupted the live fits."""
        model = _fitted_model(4)
        before = (model.workers[0].count, model.workers[0].mean)
        out = resized_model(model, 8)
        out.observe(0, 5.0)
        out.observe(4, 5.0)  # cycled index of the same source fit
        assert (model.workers[0].count, model.workers[0].mean) == before
        assert out.workers[0].count == before[0] + 1
        assert out.workers[4].count == before[0] + 1

    def test_fleet_signals_snapshot(self):
        clock, reps, router = _fleet(3)
        est = ArrivalRateEstimator(10.0)
        for k in range(1, 101):
            est.observe(k * 0.1)  # 10/s
        for _ in range(4):
            router.submit(SimPrompt(PLEN), MNEW)
        sig = fleet_signals(
            router, est, 10.0, provisioned=3, capacity_rps=CAP,
        )
        assert sig.queue_depth == 4
        assert sig.routable == 3
        assert sig.depth_per_replica == pytest.approx(4 / 3)
        assert sig.utilization == pytest.approx(
            est.rate(10.0) / (3 * CAP)
        )
        assert set(sig.to_dict()) == {
            "t", "rate_rps", "provisioned", "routable", "queue_depth",
            "utilization",
        }


# --------------------------------------------------------------------------
# controller: hysteresis, zero-drop shrink, operator resizes
# --------------------------------------------------------------------------


def _pump_arrivals(ctl, clock, rate, seconds):
    """Feed a constant-rate arrival stamp stream and step the
    controller on its cadence (no data plane — signal-path tests)."""
    t0 = clock.now()
    dt = 1.0 / rate
    t = t0
    decisions = []
    while t < t0 + seconds:
        t += dt
        clock.run_until(t)
        ctl.observe_arrival(t)
        d = ctl.step()
        if d is not None:
            decisions.append(d)
    return decisions


class TestController:
    def test_constructor_refusals(self):
        clock, reps, router = _fleet(3)
        with pytest.raises(ValueError, match="capacity_rps"):
            _controller(router, clock, capacity_rps=0.0)
        with pytest.raises(ValueError, match="min_replicas"):
            _controller(router, clock, min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError, match="min_replicas"):
            _controller(router, clock, max_replicas=9)
        with pytest.raises(ValueError, match="hysteresis"):
            _controller(router, clock, low=0.9, high=0.8)
        with pytest.raises(ValueError, match="decision_interval_s"):
            _controller(router, clock, decision_interval_s=0.0)
        # review regression: 'load'/'n_replicas' are computed by the
        # controller at each resize — passing them in policy_sweep
        # used to construct cleanly and TypeError at the FIRST
        # accepted resize, mid-run
        with pytest.raises(ValueError, match="computed by the"):
            _controller(
                router, clock,
                policy_sweep=dict(load=0.6, requests=50),
            )
        with pytest.raises(ValueError, match="computed by the"):
            _controller(
                router, clock, policy_sweep=dict(n_replicas=4),
            )

    def test_grows_on_sustained_high_util(self):
        clock, reps, router = _fleet(4)
        ctl = _controller(
            router, clock, min_replicas=2, high=0.8, low=0.3,
            dwell_s=30.0,
        )
        ctl.resize_to(2, reason="seed")  # start small
        assert ctl.size == 2
        # offered load ~ 1.5x the 2-replica fleet: sustained breach
        decisions = _pump_arrivals(ctl, clock, 1.5 * 2 * CAP, 120.0)
        grows = [d for d in decisions if d.action == "grow"]
        assert grows, decisions
        assert ctl.size > 2
        assert grows[0].reason == "util_high"
        # the grown replicas are routable again
        assert len(router.routable_replicas) == ctl.size

    def test_dwell_requires_sustained_breach(self):
        clock, reps, router = _fleet(4)
        ctl = _controller(
            router, clock, min_replicas=2, high=0.8, low=0.3,
            dwell_s=1e6,  # effectively never satisfied
        )
        ctl.resize_to(2, reason="seed")
        decisions = _pump_arrivals(ctl, clock, 1.5 * 2 * CAP, 120.0)
        assert [d for d in decisions if d.action == "grow"] == []

    def test_shrinks_on_sustained_low_util(self):
        clock, reps, router = _fleet(4)
        ctl = _controller(
            router, clock, min_replicas=1, high=0.9, low=0.5,
            dwell_s=30.0,
        )
        decisions = _pump_arrivals(ctl, clock, 0.25 * 4 * CAP, 200.0)
        shrinks = [d for d in decisions if d.action == "shrink"]
        assert shrinks and ctl.size < 4
        assert shrinks[0].reason == "util_low"
        # shrink drains from the HIGHEST index; the controller's
        # intent is re-assertable (mark_down, not kill)
        assert shrinks[0].moved[0] == 3

    def test_cooldown_blocks_consecutive_resizes(self):
        clock, reps, router = _fleet(8)
        ctl = _controller(
            router, clock, min_replicas=1, high=0.9, low=0.5,
            dwell_s=0.0, cooldown_s=1e5,
        )
        decisions = _pump_arrivals(ctl, clock, 0.2 * 8 * CAP, 300.0)
        assert len(decisions) == 1  # the second shrink sits in cooldown

    def test_depth_trigger_grows(self):
        clock, reps, router = _fleet(3)
        ctl = _controller(
            router, clock, min_replicas=1, high=1e9,  # util never
            low=0.001, target_util=0.6, depth_high=2.0, dwell_s=0.0,
        )
        ctl.resize_to(1, reason="seed")
        for _ in range(9):  # depth 9 on one replica
            router.submit(SimPrompt(PLEN), MNEW)
        # rate high enough that target sizing wants more than 1
        for k in range(1, 200):
            ctl.observe_arrival(clock.now() + k * 0.02)
        clock.advance(10.0)
        d = ctl.step()
        assert d is not None and d.action == "grow"
        assert d.reason == "depth_high"

    def test_zero_drop_shrink_drains_in_flight(self):
        # requests in flight on the drained replica restart on the
        # survivors — the router's eject/re-route path, driven by the
        # controller instead of a health flip
        clock, reps, router = _fleet(2)
        ctl = _controller(router, clock, min_replicas=1)
        rrs = [router.submit(SimPrompt(PLEN), MNEW) for _ in range(4)]
        on_1 = [rr for rr in rrs if rr.replica == 1]
        assert on_1  # least_loaded spread them
        ctl.resize_to(1, reason="drain-test")
        while router.in_flight:
            nt = router.next_event_at()
            assert nt is not None
            clock.run_until(nt)
            router.step()
        assert all(rr.finished for rr in rrs)
        assert all(rr.rerouted >= 1 for rr in on_1)
        assert router.n_rerouted >= len(on_1)

    def test_hysteresis_grow_blocked_is_named_not_silent(self):
        """Review regression: a hysteresis grow with nothing
        restorable (a replica dead at construction) used to silently
        no-op every cadence — no decision, no telemetry. It now names
        the stall once per onset and resumes when a drain makes a
        replica restorable again."""
        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=SLOTS, n_inner=NI,
                       prompt_chunk=CHUNK, tick_s=TICK)
            for _ in range(3)
        ]
        reps[2].kill()  # dead before the controller was built
        router = RequestRouter(reps, policy="least_loaded",
                               clock=clock)
        reg = MetricsRegistry()
        flight = FlightRecorder()
        ctl = _controller(
            router, clock, min_replicas=1, dwell_s=0.0,
            registry=reg, flight=flight,
        )
        assert ctl.size == 2
        decisions = _pump_arrivals(ctl, clock, 3 * 2 * CAP, 100.0)
        assert decisions == []  # nothing restorable: no resize
        assert ctl.size == 2
        # onset-counted: one named stall, not one per cadence
        assert ctl.n_grow_blocked == 1
        assert reg.counter("fleet_grow_blocked_total").value == 1
        names = [
            e.get("name") for e in flight.snapshot()["traceEvents"]
        ]
        assert names.count("fleet grow blocked") == 1
        # a drain re-arms the edge trigger: shrink, then overload again
        ctl.resize_to(1, reason="operator")
        grows = [
            d for d in _pump_arrivals(ctl, clock, 3 * 2 * CAP, 100.0)
            if d.action == "grow"
        ]
        assert grows and ctl.size == 2  # grew back to the restorable 2
        assert ctl.n_grow_blocked == 2  # then stalled again, by name

    def test_resize_to_refuses_outside_range(self):
        clock, reps, router = _fleet(4)
        ctl = _controller(router, clock, min_replicas=2)
        with pytest.raises(ValueError, match="elastic range"):
            ctl.resize_to(1)
        with pytest.raises(ValueError, match="elastic range"):
            ctl.resize_to(5)
        assert ctl.resize_to(4) is None  # already there: no decision
        d = ctl.resize_to(2, reason="operator")
        assert d.action == "shrink" and d.reason == "operator"
        assert d.size_before == 4 and d.size_after == 2
        assert ctl.chip_seconds(clock.now()) == pytest.approx(0.0)

    def test_chip_seconds_books(self):
        clock, reps, router = _fleet(4)
        ctl = _controller(router, clock, min_replicas=1)
        clock.advance(100.0)
        assert ctl.chip_seconds() == pytest.approx(400.0)
        ctl.resize_to(1)
        clock.advance(50.0)
        # 4 replicas x 100 s + 1 replica x 50 s
        assert ctl.chip_seconds() == pytest.approx(450.0)

    def test_decision_record_shape(self):
        clock, reps, router = _fleet(3)
        ctl = _controller(router, clock, min_replicas=1)
        d = ctl.resize_to(1, reason="operator")
        rec = d.to_dict()
        assert rec["action"] == "shrink"
        assert rec["size"] == [3, 1]
        assert rec["moved"] == [2, 1]
        assert rec["signal"]["provisioned"] == 3
        assert d.seq == 0 and ctl.n_resizes == 1


# --------------------------------------------------------------------------
# re-code on resize: the sweeps are the decision procedure
# --------------------------------------------------------------------------


class TestRecode:
    def _ctl(self, router, clock, **over):
        cfg = dict(
            model=_fitted_model(), n_inner=NI,
            candidates=[(1.0, 2), (1.0, 3), (0.75, 3)],
            inner_floor=2, epochs=10,
        )
        cfg.update(over.pop("recode", {}))
        return _controller(
            router, clock, min_replicas=1, recode=cfg, **over,
        )

    def test_recode_on_resize_records_the_agree_flag(self):
        clock, reps, router = _fleet(4)
        ctl = self._ctl(router, clock)
        d = ctl.resize_to(2)
        rc = d.recode
        assert rc is not None and rc["fallback"] is False
        assert isinstance(rc["agree"], bool)
        assert rc["pair"][1] == rc["inner_sim"]
        assert rc["sweep_digest"] and len(rc["sweep_digest"]) == 12
        assert ctl.code_pair == tuple(rc["pair"])
        # deterministic: the same resize re-derives the same pair
        ctl2 = self._ctl(_fleet(4)[2], clock)
        assert ctl2.resize_to(2).recode == rc

    def test_budget_overrun_falls_back_to_the_model(self):
        clock, reps, router = _fleet(4)
        ctl = self._ctl(router, clock, decision_budget=5)  # 3*10 > 5
        d = ctl.resize_to(2)
        rc = d.recode
        assert rc["fallback"] is True and rc["agree"] is None
        assert rc["budget_cost"] == 30 and rc["budget"] == 5
        # the analytic cross-check IS the decision: optimal_nwait over
        # the resized model, never below the floor
        sub = resized_model(_fitted_model(), NI)
        assert rc["pair"][1] == sub.optimal_nwait(kmin=2, kmax=NI)

    def test_infeasible_candidate_refused_by_name(self):
        clock, reps, router = _fleet(4)
        ctl = self._ctl(
            router, clock,
            recode=dict(candidates=[(1.0, 1)], inner_floor=2),
        )
        with pytest.raises(ValueError, match="decodability floor"):
            ctl.resize_to(2)

    def test_repolicy_applies_the_swept_winner(self):
        clock, reps, router = _fleet(4, jitter=0.2)
        ctl = _controller(
            router, clock, min_replicas=1,
            policy_sweep=dict(
                requests=200, slots=SLOTS, n_inner=NI, tick_s=TICK,
                prompt_len=PLEN, prompt_chunk=CHUNK, max_new=MNEW,
                seed=11,
            ),
        )
        for k in range(1, 400):
            ctl.observe_arrival(k * 0.02)
        clock.run_until(8.0)
        d = ctl.resize_to(3)
        pol = d.policy
        assert pol is not None and "sweep_digest" in pol
        assert 0.05 <= pol["load"] <= 0.95
        assert router.policy == pol["best"]
        if pol["best"] != "least_loaded":
            assert pol.get("applied") is True

    def test_structural_policy_never_switched(self):
        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=SLOTS, n_inner=NI,
                       prompt_chunk=CHUNK, tick_s=TICK)
            for i in range(3)
        ]
        router = RequestRouter(
            reps, policy="hedge_p99", ttft_slo=5.0, clock=clock,
        )
        ctl = _controller(
            router, clock, min_replicas=1,
            policy_sweep=dict(requests=100),
        )
        d = ctl.resize_to(2)
        assert d.policy["kept"] == "hedge_p99"
        assert "structural" in d.policy["refused"]
        assert router.policy == "hedge_p99"

    def test_set_policy_relabels_completion_series(self):
        """Review regression: after a mid-run switch the obs bundle's
        cached policy label (and its per-(replica, outcome) series
        cache) roll over — completions land under the policy that
        routed them, not the construction-time one."""
        reg = MetricsRegistry()
        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=SLOTS, n_inner=NI,
                       prompt_chunk=CHUNK, tick_s=TICK)
        ]
        router = RequestRouter(reps, policy="round_robin",
                               clock=clock, registry=reg)

        def one_request():
            rr = router.submit(SimPrompt(PLEN), MNEW)
            while not rr.finished:
                clock.run_until(router.next_event_at())
                router.step()

        one_request()
        router.set_policy("least_loaded")
        one_request()
        by_policy = {}
        for s in reg.snapshot()["router_requests_total"]["series"]:
            key = s["labels"]["policy"]
            by_policy[key] = by_policy.get(key, 0) + s["value"]
        assert by_policy == {"round_robin": 1.0, "least_loaded": 1.0}

    def test_router_set_policy_contract(self):
        clock, reps, router = _fleet(2)
        router.set_policy("round_robin")
        assert router.policy == "round_robin"
        router.set_policy("round_robin")  # no-op
        with pytest.raises(ValueError, match="unknown policy"):
            router.set_policy("fastest_wins")
        with pytest.raises(ValueError, match="structural"):
            router.set_policy("hedge_p99")
        hr = RequestRouter(
            [SimReplica(VirtualClock())], policy="hedge_p99",
            ttft_slo=1.0, clock=VirtualClock(),
        )
        with pytest.raises(ValueError, match="structural"):
            hr.set_policy("least_loaded")


# --------------------------------------------------------------------------
# the simulated day: autoscale + kill, bit-identical, zero drops
# --------------------------------------------------------------------------

PERIOD = 1800.0
PEAK_UTIL = 0.675
N_FLEET = 6


def _day(seed, *, kill_at=None, tmp, n_requests=None, forced=()):
    clock, reps, router = _fleet(N_FLEET, jitter=0.2)
    ck = FleetCheckpointer(os.path.join(tmp, f"ck{seed}"), n=5, k=3)
    peak = N_FLEET * CAP * PEAK_UTIL
    mean_rate = peak / 1.5  # amplitude 0.5: a 3x diurnal swing
    n = (
        int(mean_rate * PERIOD * 0.97)
        if n_requests is None else n_requests
    )

    def mk():
        return FleetController(
            router, clock=clock, capacity_rps=CAP, min_replicas=2,
            max_replicas=N_FLEET, high=0.85, low=0.5,
            decision_interval_s=15.0, dwell_s=30.0, cooldown_s=60.0,
            rate_tau_s=120.0, checkpointer=ck,
            checkpoint_every_s=90.0,
        )

    sup = ControllerSupervisor(mk, clock=clock, takeover_s=30.0)
    events = list(forced)
    if kill_at is not None:
        events.append(CoordinatorKill(kill_at))
    report = run_router_day(
        router,
        diurnal_arrivals(
            mean_rate, n=n, period=PERIOD, amplitude=0.5, seed=seed,
            prompt_len=PLEN, max_new=MNEW,
        ),
        controller=sup,
        events=events,
    )
    return report, sup, router


class TestElasticDay:
    def test_day_with_kill_zero_drops_and_bit_identical(self, tmp_path):
        """The acceptance scenario: a 3x diurnal swing, one
        coordinator kill mid-day — zero dropped requests, the fleet
        resizes with the day, the standby adopts, and two replays of
        the same seed agree on the digest AND the decision records."""
        kill = PERIOD * 0.45
        r1, s1, _ = _day(3, kill_at=kill, tmp=str(tmp_path))
        r2, s2, _ = _day(3, kill_at=kill, tmp=str(tmp_path / "b"))
        assert r1.dropped == 0
        assert r1.n_failovers == 1 and s1.n_kills == 1
        assert r1.n_resizes >= 2  # the swing actually moved the fleet
        assert r1.digest() == r2.digest()
        assert [d.to_dict() for d in s1.decisions] == [
            d.to_dict() for d in s2.decisions
        ]
        assert r1.n_resizes == r2.n_resizes

    def test_elastic_day_beats_static_peak_chip_time(self, tmp_path):
        r, sup, _ = _day(7, tmp=str(tmp_path))
        assert r.dropped == 0 and r.n_resizes >= 2
        elastic = sup.chip_seconds(r.virtual_s)
        static = N_FLEET * r.virtual_s
        assert static / elastic > 1.15, (elastic, static)

    def test_decisions_stop_while_the_coordinator_is_dead(
        self, tmp_path
    ):
        kill = PERIOD * 0.45
        r, sup, _ = _day(3, kill_at=kill, tmp=str(tmp_path))
        # the supervisor's takeover stamp: no decision lands inside
        # (kill, kill + takeover_s)
        for d in sup.decisions:
            assert not (kill < d.t < kill + 30.0 - 1e-9)

    def test_dead_coordinator_refusals(self, tmp_path):
        clock, reps, router = _fleet(2)
        ck = FleetCheckpointer(tmp_path, n=4, k=2)
        sup = ControllerSupervisor(
            lambda: _controller(
                router, clock, min_replicas=1, checkpointer=ck,
                checkpoint_every_s=5.0,
            ),
            clock=clock,
            takeover_s=10.0,
        )
        sup.kill()
        sup.kill()  # idempotent while dead
        assert sup.n_kills == 1
        with pytest.raises(RuntimeError, match="dead"):
            sup.chip_seconds()
        assert sup.decisions == []
        # a supervised controller without a checkpoint channel is
        # refused at construction: a standby cannot adopt state
        # nobody saved
        with pytest.raises(ValueError, match="checkpointer"):
            ControllerSupervisor(
                lambda: _controller(router, clock, min_replicas=1),
                clock=clock,
            )

    def test_controller_presence_does_not_perturb_the_data_plane(
        self, tmp_path
    ):
        """Digest stability: the same day with a controller whose
        bands never trigger hashes identically to the bare day — the
        control plane observes; only accepted resizes act."""
        clock, reps, router = _fleet(3, jitter=0.2)
        arr = lambda: poisson_arrivals(  # noqa: E731
            0.5 * 3 * CAP, n=600, seed=9, prompt_len=PLEN,
            max_new=MNEW,
        )
        bare = run_router_day(router, arr())
        clock2, reps2, router2 = _fleet(3, jitter=0.2)
        ctl = FleetController(
            router2, clock=clock2, capacity_rps=CAP, min_replicas=3,
            max_replicas=3, high=0.99, low=0.01,
            decision_interval_s=10.0,
        )
        watched = run_router_day(router2, arr(), controller=ctl)
        assert bare.digest() == watched.digest()
        assert watched.n_resizes == 0 and bare.n_resizes == 0

    def test_fleet_resize_event_forces_the_size(self, tmp_path):
        r, sup, router = _day(
            5, tmp=str(tmp_path), n_requests=800,
            forced=(FleetResize(20.0, 2, reason="operator"),),
        )
        assert r.dropped == 0
        ops = [d for d in sup.decisions if d.reason == "operator"]
        assert ops and ops[0].size_after == 2

    def test_event_refusals(self):
        clock, reps, router = _fleet(2)
        with pytest.raises(ValueError, match="no controller"):
            run_router_day(
                router,
                poisson_arrivals(1.0, n=5, seed=0, prompt_len=PLEN,
                                 max_new=MNEW),
                events=[FleetResize(0.5, 1)],
            )
        clock, reps, router = _fleet(2)
        ctl = _controller(router, clock, min_replicas=1)
        with pytest.raises(ValueError, match="supervised"):
            run_router_day(
                router,
                poisson_arrivals(1.0, n=5, seed=0, prompt_len=PLEN,
                                 max_new=MNEW),
                controller=ctl,
                events=[CoordinatorKill(0.5)],
            )

    def test_stalled_day_fails_by_name_with_controller_attached(self):
        """Review regression: a controller's decision cadence is
        always pending, which used to make the drain loop's stall
        guard unreachable — a day whose every replica dies (killed,
        not controller-drained, so grow can never restore them) must
        still fail by name instead of spinning forever."""
        clock, reps, router = _fleet(2)
        ctl = _controller(router, clock, min_replicas=1)
        # the kill lands AFTER the last arrival but before the decode
        # budget completes: in-flight requests freeze as orphans with
        # no routable replica to re-route onto
        clock.call_at(1.0, lambda: [r.kill() for r in reps])
        with pytest.raises(RuntimeError, match="stalled"):
            run_router_day(
                router,
                poisson_arrivals(4.0, n=3, seed=0, prompt_len=PLEN,
                                 max_new=MNEW),
                controller=ctl,
            )

    def test_decision_seqs_unique_across_incarnations(self, tmp_path):
        """Review regression: decisions accepted after the last
        checkpoint keep their seqs in the carried record, so the
        adopting standby's counter is bumped past them — the whole-day
        decision log never holds two records with one seq."""
        clock, reps, router = _fleet(4)
        ck = FleetCheckpointer(tmp_path, n=4, k=2)
        sup = ControllerSupervisor(
            lambda: _controller(
                router, clock, min_replicas=1, checkpointer=ck,
                checkpoint_every_s=1e6,  # only the zeroth checkpoint
            ),
            clock=clock,
            takeover_s=5.0,
        )
        # two decisions AFTER the only checkpoint: seqs 0, 1 carried
        sup.active.resize_to(2)
        sup.active.resize_to(3)
        sup.kill()
        clock.advance(10.0)
        sup.step()  # adopt: restored _seq=0, bumped past the carried
        d = sup.active.resize_to(2)
        assert d is not None
        seqs = [dd.seq for dd in sup.decisions]
        assert seqs == [0, 1, 2]

    def test_workload_report_counters_without_controller(self):
        clock, reps, router = _fleet(2)
        rep = run_router_day(
            router,
            poisson_arrivals(1.0, n=10, seed=0, prompt_len=PLEN,
                             max_new=MNEW),
        )
        assert rep.n_resizes == 0 and rep.n_failovers == 0


# --------------------------------------------------------------------------
# controller state: checkpoint round trip + standby adoption
# --------------------------------------------------------------------------


class TestControllerCheckpoint:
    def test_state_dict_roundtrip(self, tmp_path):
        clock, reps, router = _fleet(4)
        ck = FleetCheckpointer(tmp_path, n=5, k=3)
        ctl = _controller(
            router, clock, min_replicas=1, checkpointer=ck,
        )
        for k in range(1, 120):
            ctl.observe_arrival(k * 0.05)
        clock.run_until(6.0)
        ctl.resize_to(2)
        for _ in range(3):
            router.submit(SimPrompt(PLEN), MNEW)
        ctl.checkpoint()
        state = ck.restore()
        assert [bool(b) for b in state["provisioned"]] == [
            True, True, False, False,
        ]
        assert int(state["book_awaiting"].sum()) == 3
        assert state["inflight_ids"].size == 3
        # a fresh controller on the same router adopts the state
        standby = _controller(
            router, clock, min_replicas=1, checkpointer=ck,
        )
        standby.load_state(state, adopted=True)
        assert standby.size == 2
        assert standby.n_failovers == 1
        assert standby.n_resizes == ctl.n_resizes
        assert standby.estimator.state_dict() == (
            ctl.estimator.state_dict()
        )
        # the restored intent was re-asserted onto the router (the
        # health flip lands at the next step()'s probe, as always)
        router.step()
        assert router.routable_replicas == [0, 1]

    def test_adoption_refuses_a_mismatched_fleet(self, tmp_path):
        clock, reps, router = _fleet(4)
        ck = FleetCheckpointer(tmp_path, n=5, k=3)
        ctl = _controller(router, clock, checkpointer=ck)
        ctl.checkpoint()
        clock2, reps2, router2 = _fleet(3)
        standby = _controller(router2, clock2, min_replicas=1)
        with pytest.raises(ValueError, match="4 replicas"):
            standby.load_state(ck.restore(), adopted=True)

    def test_checkpoint_without_checkpointer_refused(self):
        clock, reps, router = _fleet(2)
        ctl = _controller(router, clock)
        with pytest.raises(ValueError, match="checkpointer"):
            ctl.checkpoint()
        # the cadence-without-channel pairing is refused at
        # CONSTRUCTION, not at the first due step mid-run
        with pytest.raises(ValueError, match="checkpoint_every_s"):
            _controller(router, clock, checkpoint_every_s=10.0)

    def test_kill_before_first_cadence_still_adopts(self, tmp_path):
        """Review regression: the supervisor writes a zeroth
        checkpoint at construction, so a kill BEFORE the first
        checkpoint cadence leaves the standby the construction-time
        state to adopt instead of crashing on an empty directory."""
        clock, reps, router = _fleet(3)
        ck = FleetCheckpointer(tmp_path, n=4, k=2)
        sup = ControllerSupervisor(
            lambda: _controller(
                router, clock, min_replicas=1, checkpointer=ck,
                checkpoint_every_s=1e6,  # cadence never fires
            ),
            clock=clock,
            takeover_s=5.0,
        )
        assert ck.n_saves == 1  # the zeroth checkpoint
        sup.kill()
        clock.advance(10.0)
        sup.step()  # the standby adopts
        assert sup.active is not None
        assert sup.n_failovers == 1
        assert sup.active.size == 3

    def test_grow_never_revives_construction_dead_replicas(self):
        """Review regression: a replica dead BEFORE the controller was
        built is not the controller's to bring back — grow restores
        only controller-drained replicas."""
        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=SLOTS, n_inner=NI,
                       prompt_chunk=CHUNK, tick_s=TICK)
            for _ in range(4)
        ]
        reps[3].kill()  # an operator took it down pre-construction
        router = RequestRouter(reps, policy="least_loaded",
                               clock=clock)
        ctl = _controller(router, clock, min_replicas=1)
        assert ctl.size == 3
        ctl.resize_to(2)  # drains replica 2
        d = ctl.resize_to(3)  # restores replica 2, NOT replica 3
        assert d.moved == [2]
        assert not reps[3].alive
        assert not ctl._provisioned[3]
        # asking beyond the drainable pool is refused by name, not
        # silently no-opped (review regression: the in-range grow used
        # to return None with no decision and no refusal)
        with pytest.raises(ValueError, match="restorable"):
            ctl.resize_to(4)
        assert ctl.size == 3 and not reps[3].alive


# --------------------------------------------------------------------------
# pool plane: capture/adopt + the elastic pair on a real ProcessBackend
# --------------------------------------------------------------------------


def _echo(i, payload, epoch):
    return np.array([float(i + 1), float(payload[0]), float(epoch)])


class _SlowWorker:
    """Picklable: one designated straggler, the rest fast."""

    def __init__(self, slow_rank, slow=0.4, fast=0.002):
        self.slow_rank, self.slow, self.fast = slow_rank, slow, fast

    def __call__(self, i, epoch):
        return self.slow if i == self.slow_rank else self.fast


class TestPoolPlane:
    def test_pool_carry_semantics(self):
        pool = AsyncPool(4, nwait=3)
        backend = LocalBackend(_echo, 4)
        try:
            for _ in range(3):
                asyncmap(pool, np.ones(1), backend, nwait=4)
            waitall(pool, backend)
        finally:
            backend.shutdown()
        carried = pool.carry([0, 1, 2, 5])
        assert carried.epoch == pool.epoch
        assert carried.nwait == 3
        for j in range(3):  # survivors keep their books
            assert carried.repochs[j] == pool.repochs[j]
            assert carried.results[j] is pool.results[j]
        # the joiner is never-heard-from: stale until it answers
        assert carried.repochs[3] == carried.epoch0
        assert carried.results[3] is None
        assert not carried.active[3]
        # nwait clamps into the shrunk range by default
        assert pool.carry([0, 1]).nwait == 2

    def test_capture_restore_roundtrip_and_kind_refusal(self):
        pool = AsyncPool(3, nwait=2)
        backend = LocalBackend(_echo, 3)
        try:
            for _ in range(4):
                asyncmap(pool, np.ones(1), backend, nwait=2)
            state = capture_pool(pool)
            clone = restore_pool(state)
            assert clone.epoch == pool.epoch
            np.testing.assert_array_equal(clone.repochs, pool.repochs)
            np.testing.assert_array_equal(clone.active, pool.active)
            for a, b in zip(clone.results, pool.results):
                if b is None:
                    assert a is None
                else:
                    np.testing.assert_array_equal(a, b)
            # the clone continues on the LIVING backend
            asyncmap(clone, np.ones(1), backend, nwait=2)
            waitall(clone, backend)
        finally:
            backend.shutdown()
        with pytest.raises(ValueError, match="not a pool checkpoint"):
            restore_pool({"kind": "weights"})

    def test_process_backend_coordinator_failover_no_epoch_lost(
        self, tmp_path
    ):
        """The acceptance failover leg: a real ProcessBackend fleet,
        the coordinator dies mid-run WITH a dispatch in flight, the
        standby adopts the worker processes through the coded
        checkpoint — the in-flight result is harvested (fresh or
        stale-then-retask), ``repochs`` history is continuous across
        the handoff, and the flight dump names the takeover."""
        backend = ProcessBackend(
            _echo, 3, delay_fn=_SlowWorker(2),
        )
        ck = FleetCheckpointer(tmp_path, n=4, k=2)
        flight = FlightRecorder()
        try:
            pool = AsyncPool(3)
            for _ in range(2):
                # nwait=2: worker 2 (the straggler) stays in flight
                asyncmap(pool, np.ones(1), backend, nwait=2,
                         timeout=30.0)
            e_cut = pool.epoch
            assert pool.active.any()  # a dispatch IS in flight
            ck.save(capture_pool(pool))
            repochs_cut = pool.repochs.copy()
            del pool  # the coordinator object dies; workers live on

            standby = adopt_pool(ck, flight=flight)
            assert standby.epoch == e_cut
            np.testing.assert_array_equal(
                standby.repochs, repochs_cut
            )
            # the standby's next epoch harvests the in-flight
            # straggler (stale -> retask) and completes: NO epoch lost
            rep = asyncmap(
                standby, np.ones(1), backend, nwait=3, timeout=30.0,
            )
            assert (rep == e_cut + 1).sum() == 3
            waitall(standby, backend, timeout=30.0)
            # repochs history continuous: every worker's stamp moved
            # forward from the cut, none reset below it
            assert (standby.repochs >= repochs_cut).all()
        finally:
            backend.shutdown()
        doc = flight.snapshot()
        names = [e.get("name") for e in doc["traceEvents"]]
        assert "coordinator takeover" in names

    def test_pool_scaler_reaps_and_respawns(self):
        backend = ProcessBackend(_echo, 4)
        try:
            pool = AsyncPool(4)
            asyncmap(pool, np.ones(1), backend, nwait=4, timeout=30.0)
            waitall(pool, backend, timeout=30.0)
            scaler = PoolScaler(pool, backend, min_workers=2)
            with pytest.raises(ValueError, match="elastic range"):
                scaler.resize(1)
            with pytest.raises(ValueError, match="elastic range"):
                scaler.resize(5)
            # shrink: ranks 2, 3 leave and their processes are reaped
            small = scaler.resize(2)
            assert small.ranks == [0, 1]
            assert sorted(backend.dead_workers()) == [2, 3]
            assert scaler.n_reaped == 2
            asyncmap(small, np.ones(1), backend, nwait=2, timeout=30.0)
            waitall(small, backend, timeout=30.0)
            # grow back: dead ranks respawn and are dispatchable
            full = scaler.resize(4)
            assert full.ranks == [0, 1, 2, 3]
            assert backend.dead_workers() == []
            assert scaler.n_respawned == 2
            # survivors carried their repochs; returners are stale
            assert full.repochs[0] == small.repochs[0]
            assert full.repochs[2] == full.epoch0
            rep = asyncmap(
                full, np.ones(1), backend, nwait=4, timeout=30.0,
            )
            assert (rep == full.epoch).sum() == 4
            waitall(full, backend, timeout=30.0)
        finally:
            backend.shutdown()

    def test_pool_scaler_nwait_passthrough(self):
        """Review regression: a shrink below the code's k used to take
        carry's silent clamp (min(old nwait, new rank count)) because
        resize exposed no way to pass the re-derived decodability
        floor."""

        class _Stub:  # carry/reset_worker only — no reap/respawn verbs
            n_workers = 8

        pool = AsyncPool(8, nwait=6)
        scaler = PoolScaler(pool, _Stub(), min_workers=2)
        small = scaler.resize(4, nwait=3)
        assert small.nwait == 3
        # without the passthrough the old clamp semantics still hold
        assert scaler.resize(8).nwait == 3

    def test_native_backend_reap_respawn_pair(self):
        """The same elastic pair on the native C++ transport: reap
        terminates the worker, the epoll thread's sticky dead marker
        surfaces in dead_workers, respawn reconnects the rank."""
        try:
            from mpistragglers_jl_tpu.backends.native import (
                NativeProcessBackend,
            )
            from mpistragglers_jl_tpu.native import transport as T

            T.load_lib()
        except Exception as e:  # pragma: no cover - no toolchain
            pytest.skip(f"native transport unavailable: {e}")
        backend = NativeProcessBackend(_echo, 2)
        try:
            pool = AsyncPool(2)
            asyncmap(pool, np.ones(1), backend, nwait=2, timeout=30.0)
            waitall(pool, backend, timeout=30.0)
            backend.reap(1)
            assert backend.dead_workers() == [1]
            backend.reap(1)  # idempotent
            backend.respawn(1)
            assert backend.dead_workers() == []
            pool.reset_worker(1)
            rep = asyncmap(
                pool, np.ones(1), backend, nwait=2, timeout=30.0,
            )
            assert (rep == pool.epoch).sum() == 2
            waitall(pool, backend, timeout=30.0)
        finally:
            backend.shutdown()

    def test_reap_is_idempotent_and_respawn_pairs(self):
        backend = ProcessBackend(_echo, 2)
        try:
            backend.reap(1)
            assert backend.dead_workers() == [1]
            backend.reap(1)  # idempotent
            assert backend.dead_workers() == [1]
            backend.respawn(1)
            assert backend.dead_workers() == []
            pool = AsyncPool(2)
            rep = asyncmap(
                pool, np.ones(1), backend, nwait=2, timeout=30.0,
            )
            assert (rep == pool.epoch).sum() == 2
            waitall(pool, backend, timeout=30.0)
        finally:
            backend.shutdown()


# --------------------------------------------------------------------------
# observability: the GC004-clean opt-in series
# --------------------------------------------------------------------------


class TestFleetObs:
    def test_metrics_and_flight_series(self, tmp_path):
        reg = MetricsRegistry()
        flight = FlightRecorder()
        clock, reps, router = _fleet(4)
        ck = FleetCheckpointer(tmp_path, n=4, k=2)
        ctl = _controller(
            router, clock, min_replicas=1, checkpointer=ck,
            registry=reg, flight=flight,
        )
        ctl.resize_to(2, reason="operator")
        ctl.resize_to(4, reason="operator")
        snap = reg.snapshot()
        resizes = {
            (s["labels"]["direction"], s["labels"]["reason"]):
            s["value"]
            for s in snap["fleet_resizes_total"]["series"]
        }
        assert resizes == {
            ("shrink", "operator"): 1.0, ("grow", "operator"): 1.0,
        }
        assert reg.gauge("fleet_size").value == 4
        assert reg.gauge("fleet_target_size").value == 4
        assert reg.histogram("fleet_decision_seconds").count == 2
        assert reg.counter("fleet_failovers_total").value == 0
        # a standby adoption advances the failover counter and stamps
        # the takeover event
        ctl.checkpoint()
        standby = _controller(
            router, clock, min_replicas=1, checkpointer=ck,
            registry=reg, flight=flight,
        )
        standby.load_state(ck.restore(), adopted=True)
        assert reg.counter("fleet_failovers_total").value == 1
        names = [
            e.get("name")
            for e in flight.snapshot()["traceEvents"]
        ]
        assert names.count("fleet decision") == 2
        assert "coordinator takeover" in names

    def test_dark_controller_has_no_obs(self):
        clock, reps, router = _fleet(2)
        ctl = _controller(router, clock, min_replicas=1)
        assert ctl._obs is None
        ctl.resize_to(1)  # no obs work on the decision path
