"""Reference-parity suite: the reference's test scenarios, one-to-one.

Each test mirrors a concrete scenario from the reference's mpiexec
scripts (test/kmap1.jl, test/kmap2.jl, driven by test/runtests.jl at
n ∈ {3, 10}) so parity can be checked line against line. Differences are
deliberate and minimal: delays are seeded (deterministic CI) instead of
`rand()`, and worker-side assertions surface coordinator-side as
failures instead of dying inside subprocesses (SURVEY §4).
"""

import time

import numpy as np
import pytest

from mpistragglers_jl_tpu import (
    AsyncPool,
    LocalBackend,
    ProcessBackend,
    asyncmap,
    waitall,
)

ROOT_PAYLOAD = 3.14


def _kmap1_worker(i, payload, epoch):
    # reference worker asserts it received 3.14 then sends its rank
    # (test/kmap1.jl:27-32); here a bad payload raises -> WorkerFailure
    assert payload[0] == pytest.approx(ROOT_PAYLOAD)
    return np.array([float(i + 1)])


class _Kmap2Worker:
    """The reference worker loop body (test/kmap2.jl:76-99): echo
    ``[rank, t, epoch]`` where ``t`` counts tasks this worker ran."""

    def __init__(self):
        self.t = {}

    def __call__(self, i, payload, epoch):
        self.t[i] = self.t.get(i, 0) + 1
        # reference sends 1-based ranks; epoch echoed from the payload
        return np.array([float(i + 1), float(self.t[i]), float(payload[0])])


class _SeededSleep:
    """Deterministic stand-in for ``sleep(max(rand()/10, 0.005))``
    (test/kmap2.jl:95), scaled down 10x to keep 100-epoch loops fast."""

    def __init__(self, seed=0, lo=0.0005, hi=0.005):
        self.rng = np.random.default_rng(seed)
        self.lo, self.hi = lo, hi

    def __call__(self, i, epoch):
        return max(float(self.rng.uniform(0, self.hi)), self.lo)


def test_kmap1_full_gather_each_chunk_from_its_worker():
    """test/kmap1.jl:20-22 at n=3 (runtests.jl:20): nwait=n full gather,
    recvbuf == [1..n] — chunk j came from worker j."""
    n = 3
    backend = LocalBackend(_kmap1_worker, n)
    try:
        pool = AsyncPool(n)
        sendbuf = np.array([ROOT_PAYLOAD])
        recvbuf = np.zeros(n)
        repochs = asyncmap(pool, sendbuf, backend, recvbuf, nwait=n)
        np.testing.assert_allclose(recvbuf, np.arange(1, n + 1))
        assert list(repochs) == [1] * n
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_kmap1_under_real_processes():
    """Same scenario executed as the reference actually runs it — real
    OS processes (runtests.jl:17 spawns ranks via mpiexec)."""
    n = 3
    backend = ProcessBackend(_kmap1_worker, n)
    try:
        pool = AsyncPool(n)
        recvbuf = np.zeros(n)
        asyncmap(pool, np.array([ROOT_PAYLOAD]), backend, recvbuf, nwait=n)
        np.testing.assert_allclose(recvbuf, np.arange(1, n + 1))
    finally:
        backend.shutdown()


@pytest.mark.parametrize("n", [3, 10])
def test_kmap2_fastest_k_100_epochs_with_echo_integrity(n):
    """test/kmap2.jl:32-54 (n=3 and n=10 per runtests.jl:20-45): 100
    epochs at nwait=2, every epoch yields >= 2 fresh responses, and
    every heard-from worker's echoed epoch equals repochs[i]."""
    backend = LocalBackend(
        _Kmap2Worker(), n, delay_fn=_SeededSleep(seed=n)
    )
    try:
        pool = AsyncPool(n)
        sendbuf = np.zeros(1)
        recvbuf = np.zeros(3 * n)
        for epoch in range(1, 101):
            sendbuf[0] = epoch
            repochs = asyncmap(
                pool, sendbuf, backend, recvbuf, nwait=2
            )
            chunks = recvbuf.reshape(n, 3)
            from_this_epoch = 0
            for i in range(n):
                if repochs[i] == 0:
                    continue  # never heard from worker i (kmap2.jl:42-44)
                if repochs[i] == epoch:
                    from_this_epoch += 1
                # workers echo what was sent to them (kmap2.jl:50)
                assert chunks[i][2] == repochs[i]
            assert from_this_epoch >= 2  # kmap2.jl:53
        waitall(pool, backend)
    finally:
        backend.shutdown()


def test_kmap2_waitall_quiesces_100_epochs():
    """test/kmap2.jl:57-61: 100 rounds of asyncmap(nwait=1) + waitall!;
    all workers inactive after every waitall."""
    n = 3
    backend = LocalBackend(
        _Kmap2Worker(), n, delay_fn=_SeededSleep(seed=7)
    )
    try:
        pool = AsyncPool(n)
        sendbuf = np.zeros(1)
        for epoch in range(1, 101):
            sendbuf[0] = epoch
            asyncmap(pool, sendbuf, backend, nwait=1)
            waitall(pool, backend)
            assert not pool.active.any()  # kmap2.jl:60
    finally:
        backend.shutdown()


# The latency-agreement family's one sanctioned real-thread smoke
# (GC008): the claim is exact on SimBackend (test_pool_local.py); this
# real-thread version stays because it pins parity with the
# reference's own wall-clock assertion (test/kmap2.jl:71).
# graftcheck: real-smoke
def test_kmap2_functional_nwait_waits_for_worker_1():
    """test/kmap2.jl:63-72: nwait = (epoch, repochs) -> repochs[1] ==
    epoch waits for a SPECIFIC worker; measured pool.latency[0] matches
    the call's wall-clock."""
    n = 3
    backend = LocalBackend(
        _Kmap2Worker(), n, delay_fn=_SeededSleep(seed=3)
    )
    try:
        pool = AsyncPool(n)
        sendbuf = np.zeros(1)
        pred = lambda epoch, repochs: repochs[0] == epoch  # noqa: E731
        diffs = []
        for epoch in range(101, 201):  # kmap2.jl:66 numbering
            sendbuf[0] = epoch
            t0 = time.perf_counter()
            repochs = asyncmap(
                pool, sendbuf, backend, nwait=pred, epoch=epoch
            )
            delay = time.perf_counter() - t0
            assert repochs[0] == pool.epoch  # kmap2.jl:70
            diffs.append(abs(delay - pool.latency[0]))
        # kmap2.jl:71 asserts atol=1e-3 per call; a per-iteration hard
        # bound is flake bait on loaded CI, so assert the distribution:
        # typically sub-2ms agreement, occasional scheduler hiccups only
        diffs = np.array(diffs)
        assert np.median(diffs) < 2e-3
        assert (diffs < 5e-3).mean() >= 0.9
        waitall(pool, backend)
    finally:
        backend.shutdown()


def test_pool_ranks_default_to_1_to_n_equivalent():
    """test/kmap2.jl:22 asserts pool.ranks == 1:n (Julia 1-based); the
    0-based equivalent here is 0..n-1."""
    assert AsyncPool(4).ranks == [0, 1, 2, 3]
