"""Round-12 zero-copy transport: persistent shm rings (ISSUE 7).

Three layers under test:

* raw native transport — the persistent broadcast arena (fd passed
  once, slots reused across epochs, pin-count acks) and per-worker
  result rings (native/transport.py + native/rings.py);
* NativeProcessBackend end to end — byte-exact round trips for
  f32/int8/non-contiguous payloads, pipe-pickle vs shm-ring identity,
  held-view lifetime across more epochs than the ring is deep;
* ProcessBackend shm rings — pickle protocol-5 out-of-band buffers
  over ``multiprocessing.shared_memory``, pipes carrying only control
  frames, and the read-only payload contract.

The lifetime claims extend PR 6's keep-window eviction regression to
the persistent rings: a held ``Message.body`` (or harvested result)
view must stay readable FOREVER — slot reuse defers (producer falls
back to the copying transport), it never tears.
"""

import os
import threading

import numpy as np
import pytest

from mpistragglers_jl_tpu import AsyncPool, ProcessBackend, asyncmap, waitall
from mpistragglers_jl_tpu.native import NativeBuildError
from mpistragglers_jl_tpu.native import rings as R

try:
    from mpistragglers_jl_tpu.backends.native import NativeProcessBackend
    from mpistragglers_jl_tpu.native import transport as T

    T.load_lib()
    _SKIP = None
except NativeBuildError as e:  # pragma: no cover - no compiler in env
    _SKIP = str(e)

needs_native = pytest.mark.skipif(
    _SKIP is not None, reason=f"native transport unavailable: {_SKIP}"
)

MB = 1 << 20


# ---------------------------------------------------------------- rings.py


def test_ring_alloc_pins_and_generations():
    a = R.RingAlloc(2)
    s0, g0 = a.acquire(("coord",))
    s1, g1 = a.acquire((1, 2))
    assert {s0, s1} == {0, 1} and g1 > g0
    assert a.acquire(("x",)) is None  # full
    a.release(s0, g0, "coord")
    s2, g2 = a.acquire((7,))
    assert s2 == s0 and g2 > g1
    # stale release (old generation) must not free the new occupant
    a.release(s2, g0, 7)
    assert a.acquire(("y",)) is None
    a.release(s2, g2, 7)
    a.release_holder_everywhere(1)
    a.release(s1, g1, 2)
    assert a.pinned == 0


def test_track_release_fires_once_when_last_view_dies():
    """The release hook fires only when every derived buffer is gone.
    The transport serves MEMORYVIEWS of the tracked slice for exactly
    this reason: ``np.frombuffer(ndarray)`` does not keep the ndarray
    object in its base chain, but a memoryview's managed buffer does —
    so any consumer chain built on the served body pins the slot."""
    region = R.MemfdRegion.create(4096)
    if region is None:  # pragma: no cover - no memfd
        pytest.skip("memfd unavailable")
    fired = []
    view = region.view[:128]
    R.track_release(view, fired.append, "released")
    body = memoryview(view)  # what Message.body actually is
    derived = np.frombuffer(body, np.uint8)[10:20]
    sliced = body[5:50]
    del view, body
    assert not fired, "fired while derived buffers were alive"
    del derived
    assert not fired, "fired while a memoryview slice was alive"
    del sliced
    assert fired == ["released"]
    region.close()


# --------------------------------------------------- raw transport: arena


def _pair(n):
    import tempfile
    import uuid

    path = os.path.join(
        tempfile.gettempdir(), f"msgt-ring-{uuid.uuid4().hex[:8]}.sock"
    )
    return T.Coordinator(path, n), path


@needs_native
def test_arena_is_persistent_and_byte_exact():
    """One arena id across every epoch (the per-epoch memfd + mmaps +
    fd-pass setup is gone), one worker-side mapping, byte-exact slot
    views, and ack-driven slot reuse with zero steady-state stalls."""
    coord, path = _pair(2)
    epochs = 10
    state = {}

    def worker(rank):
        w = T.Worker(path, rank)
        n_maps = set()
        while True:
            msg = w.recv()
            if msg is None or msg.kind == T.KIND_CONTROL:
                break
            assert msg.body is not None, "broadcast did not ride the arena"
            n_maps.update(w._arena_regions)
            got = np.frombuffer(msg.body, np.uint8)
            # >= RING_MIN so the echo rides the result ring
            w.send_result(b"p", got[:T.RING_MIN].copy(), seq=msg.seq,
                          epoch=msg.epoch)
            msg = None
            got = None
        state[rank] = n_maps
        w.close()

    ts = [threading.Thread(target=worker, args=(r,), daemon=True)
          for r in range(2)]
    for t in ts:
        t.start()
    try:
        coord.accept(timeout=10)
        aid = None
        for i in range(epochs):
            body = np.full(MB, i, np.uint8)
            pl = coord.arena_payload(body)
            assert pl is not None, (
                f"arena stalled at epoch {i}: {coord.stats}"
            )
            if aid is None:
                aid = pl.arena.id
            assert pl.arena.id == aid, "arena was recreated per epoch"
            for rank in range(2):
                assert coord.isend_shared(rank, b"hdr", pl, seq=i, epoch=i)
            pl.release()
            for _ in range(2):
                r, msg = coord.waitany([0, 1], timeout=10)
                assert msg.kind == T.KIND_DATA
                assert msg.body is not None, "result did not ride a ring"
                got = np.frombuffer(msg.body, np.uint8)
                assert got.shape == (T.RING_MIN,)
                assert got[0] == i and got[-1] == i
                msg = None
                got = None
        for rank in range(2):
            coord.isend(rank, b"", kind=T.KIND_CONTROL)
        for t in ts:
            t.join(timeout=10)
        assert state[0] == {aid} and state[1] == {aid}, (
            "workers mapped more than the one persistent arena"
        )
        assert coord.stats["arena_stalls"] == 0
        assert coord.stats["arena_bytes"] == epochs * 2 * MB
    finally:
        coord.close()


@needs_native
def test_held_arena_view_across_more_epochs_than_slots_stays_readable():
    """The PR 6 eviction regression, persistent-ring edition: a worker
    that HOLDS an arena body view forever pins that slot; the
    coordinator keeps broadcasting through the remaining slots (and
    falls back to the one-shot shm path when all are pinned) — the
    held view stays byte-correct through 3x more epochs than the
    arena has slots."""
    coord, path = _pair(2)
    epochs = T.ARENA_SLOTS * 3
    done = threading.Event()

    def pinner():
        w = T.Worker(path, 0)
        held = None
        seen = 0
        while True:
            msg = w.recv()
            if msg is None or msg.kind == T.KIND_CONTROL:
                break
            assert msg.body is not None
            if held is None:
                held = msg.body  # pin epoch 0's slot forever
            seen += 1
            msg = None
            # the held view stays exactly epoch 0's bytes
            assert bytes(memoryview(held)[:4]) == b"\x00" * 4
            assert bytes(memoryview(held)[-4:]) == b"\x00" * 4
            w.send(b"ok", seq=seen)
        assert seen == epochs
        w.close()
        done.set()

    def drain():
        w = T.Worker(path, 1)
        while True:
            msg = w.recv()
            if msg is None or msg.kind == T.KIND_CONTROL:
                break
            msg = None
            w.send(b"ok")
        w.close()

    ts = [threading.Thread(target=pinner, daemon=True),
          threading.Thread(target=drain, daemon=True)]
    for t in ts:
        t.start()
    try:
        coord.accept(timeout=10)
        for i in range(epochs):
            body = np.full(MB, i, np.uint8)
            pl = coord.arena_payload(body) or coord.payload(body)
            for rank in range(2):
                assert coord.isend_shared(rank, b"h", pl, seq=i, epoch=i)
            pl.release()
            for _ in range(2):
                got = coord.waitany([0, 1], timeout=10)
                assert got is not None
        for rank in range(2):
            coord.isend(rank, b"", kind=T.KIND_CONTROL)
        assert done.wait(timeout=30), "pinned worker did not finish"
        for t in ts:
            t.join(timeout=10)
        # the pinned slot forced at most slots-1 live slots per epoch;
        # the coordinator must have kept going regardless (stall +
        # fallback is allowed, tearing is not — asserted in pinner)
        assert coord.pinned_slots() >= 1  # the held slot is still pinned
    finally:
        coord.close()


@needs_native
def test_held_result_ring_view_outlives_ring_depth():
    """Symmetric lifetime claim for the harvest side: the coordinator
    holds one harvested ring view across 3x ring-depth further
    epochs; the worker wraps its ring (falling back to socket sends
    when every slot is pinned — stall-reported, never torn) and the
    held view stays byte-correct."""
    coord, path = _pair(1)
    epochs = T.RING_SLOTS * 3

    def worker():
        w = T.Worker(path, 0)
        while True:
            msg = w.recv()
            if msg is None or msg.kind == T.KIND_CONTROL:
                break
            i = int(msg.epoch)
            w.send_result(
                b"p", np.full(T.RING_MIN, i % 251, np.uint8),
                seq=msg.seq, epoch=msg.epoch,
            )
            msg = None
        w.close()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        coord.accept(timeout=10)
        held = []  # pin EVERY slot: the first ring-depth views, forever
        socket_fallbacks = 0
        for i in range(epochs):
            coord.isend(0, b"go", seq=i, epoch=i)
            r, msg = coord.waitany([0], timeout=10)
            assert msg.kind == T.KIND_DATA
            if msg.body is not None:
                body = np.frombuffer(msg.body, np.uint8)
            else:
                # ring full (all slots pinned below): the worker fell
                # back to the copying socket send — delivery never
                # waits on the coordinator's GC
                socket_fallbacks += 1
                body = np.frombuffer(msg.payload, np.uint8)[1:]  # "p"
            assert body[0] == i % 251 and body[-1] == i % 251
            if len(held) < T.RING_SLOTS and msg.body is not None:
                held.append((i, body))  # keep the view alive
            for j, h in held:
                assert h[0] == j % 251 and h[-1] == j % 251, (
                    f"held ring view of epoch {j} torn at epoch {i}"
                )
            msg = None
            body = None
        coord.isend(0, b"", kind=T.KIND_CONTROL)
        t.join(timeout=10)
        assert len(held) == T.RING_SLOTS
        for j, h in held:
            assert h[0] == j % 251 and h[-1] == j % 251
        # every slot pinned => the later epochs MUST have fallen back,
        # and the worker must have stall-reported it
        assert socket_fallbacks > 0
        assert coord.stats["ring_stalls"] > 0
        assert coord.stats["ring_bytes"] > 0
        assert coord.pinned_slots() >= T.RING_SLOTS
    finally:
        coord.close()


# --------------------------------------------- end-to-end byte exactness


def _identity(i, payload, epoch):
    return payload


def _identity_tree(i, payload, epoch):
    return {"a": payload["a"], "b": payload["b"], "rank": i}


def _mutator(i, payload, epoch):
    payload[0] = 99.0  # must raise on read-only zero-copy views
    return payload


_CASES = {
    # >= 1 MiB so the broadcast rides the arena; results ride rings
    "f32": np.linspace(0, 1, 300_000, dtype=np.float32),
    "int8": np.arange(1_200_000, dtype=np.int64).astype(np.int8),
    "noncontig": np.arange(2_400_000, dtype=np.float32).reshape(
        2, 1_200_000
    )[:, ::2],
}


def _roundtrip(backend_factory, payload, n=2, epochs=3):
    be = backend_factory()
    try:
        pool = AsyncPool(n)
        outs = []
        for _ in range(epochs):
            asyncmap(pool, payload, be, nwait=n)
            outs.append([np.asarray(pool.results[r]) for r in range(n)])
        waitall(pool, be)
        return outs
    finally:
        be.shutdown()


@needs_native
@pytest.mark.parametrize("case", sorted(_CASES))
def test_native_ring_roundtrip_identity_vs_pipe_pickle(case):
    """The acceptance identity: shm-ring results are byte-for-byte the
    pipe-pickle results for f32, int8, and non-contiguous payloads,
    across epochs (slot reuse included)."""
    payload = _CASES[case]
    ring = _roundtrip(lambda: NativeProcessBackend(_identity, 2), payload)
    pipe = _roundtrip(
        lambda: ProcessBackend(_identity, 2, shm_rings=False), payload
    )
    expect = np.ascontiguousarray(payload)
    for epoch_ring, epoch_pipe in zip(ring, pipe):
        for got_r, got_p in zip(epoch_ring, epoch_pipe):
            assert got_r.dtype == got_p.dtype == expect.dtype
            assert got_r.shape == got_p.shape == expect.shape
            assert np.array_equal(got_r, expect)
            assert np.array_equal(got_p, expect)


@pytest.mark.parametrize("case", sorted(_CASES))
def test_process_shm_ring_roundtrip_identity(case):
    """ProcessBackend's shared-memory rings reproduce the classic
    in-band pickling byte-for-byte (pipes carry only control)."""
    payload = _CASES[case]
    ring = _roundtrip(lambda: ProcessBackend(_identity, 2), payload)
    expect = np.ascontiguousarray(payload)
    for epoch in ring:
        for got in epoch:
            assert got.dtype == expect.dtype
            assert got.shape == expect.shape
            assert np.array_equal(got, expect)


def test_process_shm_ring_pytree_payload_roundtrip():
    """Multi-buffer pickling: a dict of arrays crosses as protocol-5
    out-of-band buffers packed into one slot."""
    payload = {
        "a": np.arange(200_000, dtype=np.float32),
        "b": np.arange(100_000, dtype=np.int8),
    }
    be = ProcessBackend(_identity_tree, 2)
    try:
        pool = AsyncPool(2)
        asyncmap(pool, payload, be, nwait=2)
        for r in range(2):
            out = pool.results[r]
            assert np.array_equal(out["a"], payload["a"])
            assert np.array_equal(out["b"], payload["b"])
            assert out["rank"] == r
        waitall(pool, be)
        assert be.ring_stats["bcast_bytes"] > 0
        assert be.ring_stats["result_bytes"] > 0
    finally:
        be.shutdown()


def test_process_ring_payloads_are_readonly_views():
    """The contract change shm_rings makes: bulk payloads arrive as
    read-only views (native-backend discipline), so an in-place
    mutator fails LOUDLY instead of corrupting the shared slot."""
    from mpistragglers_jl_tpu import WorkerFailure

    payload = np.ones(300_000, np.float32)  # >= PROC_RING_MIN
    be = ProcessBackend(_mutator, 1)
    try:
        pool = AsyncPool(1)
        with pytest.raises(WorkerFailure, match="read-only|not writeable"):
            asyncmap(pool, payload, be, nwait=1)
    finally:
        be.shutdown()
    # and the escape hatch restores the classic mutable private copy
    be = ProcessBackend(_mutator, 1, shm_rings=False)
    try:
        pool = AsyncPool(1)
        asyncmap(pool, payload, be, nwait=1)
        assert np.asarray(pool.results[0])[0] == 99.0
        waitall(pool, be)
    finally:
        be.shutdown()


def test_process_small_payloads_stay_in_band():
    """Below PROC_RING_MIN nothing touches shared memory — the classic
    path, byte-identical and mutable."""
    payload = np.arange(64, dtype=np.float32)
    be = ProcessBackend(_identity, 2)
    try:
        pool = AsyncPool(2)
        asyncmap(pool, payload, be, nwait=2)
        for r in range(2):
            assert np.array_equal(np.asarray(pool.results[r]), payload)
        waitall(pool, be)
        assert be.ring_stats["bcast_bytes"] == 0
        assert be.ring_stats["result_bytes"] == 0
    finally:
        be.shutdown()


@needs_native
def test_native_zero_copy_counters_and_harvested_views_pin_slots():
    """Opt-in obs wiring (GC004 contract): zero-copy byte counters,
    stall counters, and the pinned-slot gauge land in the registry;
    harvested results pin ring slots until released."""
    from mpistragglers_jl_tpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    payload = np.ones(MB // 4, np.float32)  # 1 MiB
    be = NativeProcessBackend(_identity, 2, registry=reg)
    try:
        pool = AsyncPool(2)
        for _ in range(4):
            asyncmap(pool, payload, be, nwait=2)
        waitall(pool, be)
        snap = reg.snapshot()
        zc = {
            s["labels"]["path"]: s["value"]
            for s in snap["transport_zero_copy_bytes_total"]["series"]
        }
        assert zc.get("arena", 0) > 0, "arena bytes never counted"
        assert zc.get("ring", 0) > 0, "ring bytes never counted"
        assert snap["transport_pinned_slots_peak"]["series"][0]["value"] > 0
        # pool.results holds the last epoch's views -> slots pinned now
        assert be._coord.pinned_slots() > 0
    finally:
        be.shutdown()


@needs_native
def test_native_zero_copy_false_forces_copying_transport():
    payload = np.ones(MB // 4, np.float32)
    be = NativeProcessBackend(_identity, 2, zero_copy=False)
    try:
        pool = AsyncPool(2)
        for _ in range(3):
            asyncmap(pool, payload, be, nwait=2)
            for r in range(2):
                assert np.array_equal(
                    np.asarray(pool.results[r]), payload
                )
        waitall(pool, be)
        s = be._coord.stats
        assert s["arena_bytes"] == 0 and s["ring_bytes"] == 0
    finally:
        be.shutdown()


# ------------------------------------------------- migration ring (round 16)
#
# The disaggregation subsystem's cross-process transfer frames
# (models/disagg.py MigrationRing) ride the same rings.py pin-count
# discipline as the broadcast arena and result rings: slots stay pinned
# while any consumer view lives, an all-pinned ring falls back to
# copying frames, and a stale generation is served as a copy — never a
# torn view. These are the lifetime legs the round-16 acceptance
# criterion names.


def _mig_ring(**kw):
    from mpistragglers_jl_tpu.models.disagg import (
        MigrationRing,
        MigrationRingReader,
    )

    kw.setdefault("slot_bytes", 1 << 12)
    kw.setdefault("slots", 4)
    ring = MigrationRing(**kw)
    if ring.region is None:  # pragma: no cover - no memfd on this box
        pytest.skip("memfd_create unavailable")
    return ring, MigrationRingReader(ring)


def test_migration_ring_frames_byte_exact_and_pins_release():
    """Round trip through the consumer's OWN mapping of the fd (not
    the sender's view — the cross-process read path), byte-exact; the
    slot pins drop exactly when the sender releases its frame pins AND
    the last consumer view dies."""
    import gc

    ring, reader = _mig_ring()
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 255, size=3 * (1 << 12) + 17,
                           dtype=np.uint8)
    frames = ring.send_segment(payload)
    assert len(frames) == 4 and ring.stalls == 0
    got = reader.read_segment(frames)  # multi-frame => private copy
    assert np.array_equal(got, payload)
    # single-frame segment: zero-copy view through the reader mapping
    seg = rng.integers(0, 255, size=100, dtype=np.uint8)
    ring.release_frames(frames)
    gc.collect()
    assert ring.pinned == 0
    f3 = ring.send_segment(seg)
    view = reader.read_segment(f3)
    assert np.array_equal(view, seg)
    assert ring.pinned == 1  # sender pin + live consumer view
    ring.release_frames(f3)
    assert ring.pinned == 1  # the view still pins it
    del view
    gc.collect()
    assert ring.pinned == 0
    ring.close()


def test_migration_ring_all_pinned_falls_back_to_copy():
    """Every slot pinned by held consumer views: further sends become
    copying frames (stall counted), stay byte-exact, and the held
    views never tear."""
    import gc

    from mpistragglers_jl_tpu.models.disagg import CopyFrame

    ring, reader = _mig_ring(slots=2)
    rng = np.random.default_rng(1)
    a = rng.integers(0, 255, size=1 << 12, dtype=np.uint8)
    b = rng.integers(0, 255, size=1 << 12, dtype=np.uint8)
    fa, fb = ring.send_segment(a), ring.send_segment(b)
    va = np.frombuffer(reader.read_segment(fa), np.uint8).copy(), \
        reader.read_segment(fa)
    vb = reader.read_segment(fb)
    ring.release_frames(fa)
    ring.release_frames(fb)
    gc.collect()
    assert ring.pinned == 2  # both held by the live views
    c = rng.integers(0, 255, size=200, dtype=np.uint8)
    fc = ring.send_segment(c)
    assert all(isinstance(f, CopyFrame) for f in fc)
    assert ring.stalls >= 1
    assert np.array_equal(reader.read_segment(fc), c)
    # the pinned views kept their bytes through the fallback sends
    assert np.array_equal(va[1], va[0])
    assert np.array_equal(vb, b)
    del va, vb
    gc.collect()
    assert ring.pinned == 0
    ring.close()


def test_migration_ring_stale_generation_served_as_copy():
    """A frame read after its slot was released and reused must come
    back as a private copy (add_holder refuses the stale generation) —
    never a view of the new occupant's bytes."""
    import gc

    ring, reader = _mig_ring(slots=1)
    a = np.full(64, 7, np.uint8)
    fa = ring.send_segment(a)
    ring.release_frames(fa)
    gc.collect()
    b = np.full(64, 9, np.uint8)
    fb = ring.send_segment(b)  # reuses slot 0, new generation
    stale = reader.read_segment(fa)  # old gen: served as a copy
    assert np.array_equal(stale, a) or np.array_equal(stale, b)
    # whichever bytes it saw, it must NOT pin the reused slot
    fresh = reader.read_segment(fb)
    assert np.array_equal(fresh, b)
    ring.release_frames(fb)
    del fresh, stale
    gc.collect()
    assert ring.pinned == 0
    ring.close()
