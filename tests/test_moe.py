"""Expert parallelism: sharded MoE (all_to_all over ep) vs dense oracle.

The reference has exactly one parallelism strategy (SURVEY §2); MoE/ep
is a north-star addition. The correctness bar mirrors the other sharded
program tests: the ep-sharded program must match the dense routing math
exactly when capacity is generous (routing is per-token deterministic,
so local-vs-global capacity bookkeeping only diverges when tokens drop).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mpistragglers_jl_tpu.models.moe import (
    moe_ffn_dense,
    moe_layer_specs,
    switch_route,
)
from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig,
    data_spec,
    forward_dense,
    init_params,
    make_forward,
    make_train_step,
    shard_params,
)
from mpistragglers_jl_tpu.parallel import make_mesh

CFG = TransformerConfig(
    vocab=61, d_model=32, n_heads=4, n_layers=2, d_ff=64,
    n_experts=4, capacity_factor=4.0,
)


def _tokens(cfg, B=8, L=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab, (B, L)), dtype=jnp.int32)


def _place(mesh, cfg, toks):
    return jax.device_put(toks, NamedSharding(mesh, data_spec(cfg)))


def test_switch_route_shapes_and_mass():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((24, 8)), jnp.float32)
    wg = jnp.asarray(rng.standard_normal((8, 4)) * 0.1, jnp.float32)
    dispatch, combine, aux = switch_route(x, wg, capacity=12)
    assert dispatch.shape == (24, 4, 12)
    # generous capacity: every token lands in exactly one slot
    np.testing.assert_allclose(np.asarray(dispatch.sum(axis=(1, 2))), 1.0)
    # each (expert, slot) holds at most one token
    assert float(dispatch.sum(axis=0).max()) <= 1.0
    # combine mass per token equals its gate probability (< 1)
    mass = np.asarray(combine.sum(axis=(1, 2)))
    assert (mass > 0.25 - 1e-6).all() and (mass <= 1.0).all()
    assert float(aux) >= 1.0 - 1e-6  # >= 1, == 1 at perfect balance


def test_router_stays_f32_and_bf16_routing_matches():
    """The router weight is never downcast (r5 review item): at a bf16
    model dtype ``wg`` inits f32 — it is only (D, E), bytes that round
    to zero next to the expert FFNs — and routing from bf16 activations
    through the mixed-precision dot (f32 accumulation via
    preferred_element_type) reproduces the f32 router's decisions:
    identical argmax/slots, gates to bf16-input tolerance."""
    from mpistragglers_jl_tpu.models.moe import _route, init_moe_layer

    rng = np.random.default_rng(21)
    lp = init_moe_layer(rng, d_model=64, d_ff=128, n_experts=4,
                        n_layers=2, dtype=jnp.bfloat16)
    assert lp["wg"].dtype == jnp.float32  # not downcast at init
    assert lp["we1"].dtype == jnp.bfloat16  # experts do follow dtype
    x = jnp.asarray(rng.standard_normal((256, 64)), jnp.float32)
    e32, s32, g32, aux32 = _route(x, lp["wg"])
    eb, sb, gb, auxb = _route(x.astype(jnp.bfloat16), lp["wg"])
    np.testing.assert_array_equal(np.asarray(eb), np.asarray(e32))
    np.testing.assert_array_equal(np.asarray(sb), np.asarray(s32))
    np.testing.assert_allclose(
        np.asarray(gb), np.asarray(g32), atol=2e-2
    )
    np.testing.assert_allclose(float(auxb), float(aux32), atol=2e-2)


def test_switch_route_capacity_drops_overflow():
    # all tokens to one expert, capacity 3 -> exactly 3 survive
    x = jnp.ones((10, 4), jnp.float32)
    wg = jnp.zeros((4, 2), jnp.float32).at[:, 0].set(5.0)
    dispatch, _, _ = switch_route(x, wg, capacity=3)
    assert float(dispatch.sum()) == 3.0
    # survivors are the FIRST three tokens (arrival order)
    np.testing.assert_allclose(
        np.asarray(dispatch.sum(axis=(1, 2))[:4]), [1, 1, 1, 0]
    )


@pytest.mark.parametrize(
    "shape,axes",
    [
        ((1, 1, 1, 4), ("dp", "sp", "tp", "ep")),
        ((2, 1, 2, 2), ("dp", "sp", "tp", "ep")),
        ((1, 2, 2, 2), ("dp", "sp", "tp", "ep")),
    ],
)
@pytest.mark.slow
def test_moe_sharded_forward_matches_dense(shape, axes):
    mesh = make_mesh(shape, axes)
    params = init_params(CFG, seed=1)
    toks = _tokens(CFG)
    want = forward_dense(params, toks, CFG)
    got = make_forward(CFG, mesh)(
        shard_params(params, CFG, mesh), _place(mesh, CFG, toks)
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )


@pytest.mark.slow
def test_moe_sharded_grads_match_dense():
    mesh = make_mesh((2, 1, 1, 2), ("dp", "sp", "tp", "ep"))
    params = init_params(CFG, seed=4)
    rng = np.random.default_rng(5)
    data = jnp.asarray(rng.integers(0, CFG.vocab, (8, 17)), jnp.int32)
    toks, tgts = data[:, :-1], data[:, 1:]

    def dense_loss(params):
        logits = forward_dense(params, toks, CFG).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, tgts[..., None], axis=-1).mean()

    g_want = jax.grad(dense_loss)(params)

    from functools import partial

    from mpistragglers_jl_tpu.models.transformer import (
        _loss_local,
        param_specs,
    )

    loss_fn = jax.jit(
        jax.shard_map(
            partial(_loss_local, cfg=CFG),
            mesh=mesh,
            in_specs=(param_specs(CFG), data_spec(CFG), data_spec(CFG)),
            out_specs=P(),
        )
    )
    g_got = jax.grad(loss_fn)(
        shard_params(params, CFG, mesh),
        _place(mesh, CFG, toks), _place(mesh, CFG, tgts),
    )
    flat_w, _ = jax.tree.flatten(g_want)
    flat_g, _ = jax.tree.flatten(g_got)
    for a, b in zip(flat_g, flat_w):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3
        )


def test_moe_train_step_reduces_loss_and_stays_sharded():
    cfg = TransformerConfig(
        **{**CFG.__dict__, "moe_aux_coef": 0.01}
    )
    mesh = make_mesh((2, 1, 2, 2), ("dp", "sp", "tp", "ep"))
    params = shard_params(init_params(cfg, seed=2), cfg, mesh)
    step = make_train_step(cfg, mesh, lr=0.1)
    rng = np.random.default_rng(3)
    data = jnp.asarray(rng.integers(0, cfg.vocab, (8, 17)), jnp.int32)
    toks = _place(mesh, cfg, data[:, :-1])
    tgts = _place(mesh, cfg, data[:, 1:])
    losses = []
    for _ in range(10):
        params, loss = step(params, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.1, losses
    # expert weights stay ep-sharded through the update
    we1_spec = tuple(params["layers"][0]["we1"].sharding.spec)
    assert "ep" in we1_spec


def test_moe_dense_ffn_dropped_tokens_ride_residual():
    # capacity_factor small enough to drop: output rows for dropped
    # tokens are exactly zero (residual-only), not garbage
    rng = np.random.default_rng(7)
    from mpistragglers_jl_tpu.models.moe import init_moe_layer

    mp = init_moe_layer(rng, 16, 32, n_experts=2, n_layers=1,
                        dtype=jnp.float32)
    # force everything to expert 0 with tiny capacity: the router logit
    # is x @ wg, so positive features + a positive column-0 router win
    mp["wg"] = jnp.zeros((16, 2)).at[:, 0].set(8.0).astype(jnp.float32)
    x = jnp.asarray(
        np.abs(rng.standard_normal((1, 10, 16))) + 0.1, jnp.float32
    )
    y, _ = moe_ffn_dense(x, mp, capacity_factor=0.4)  # C = 2
    norms = np.linalg.norm(np.asarray(y[0]), axis=-1)
    assert (norms[:2] > 0).all() and np.allclose(norms[2:], 0.0)


def test_moe_specs_cover_params():
    params = init_params(CFG, seed=0)
    from mpistragglers_jl_tpu.models.transformer import param_specs

    jax.tree.map(lambda p, s: None, params, param_specs(CFG))
    assert set(moe_layer_specs()) <= set(params["layers"][0])


def test_gather_dispatch_equals_onehot_einsum():
    """The gather/scatter routing (switch_route_indices) must reproduce
    the Mesh-TF one-hot einsum formulation EXACTLY — same slots, same
    capacity drops, same gate weighting — at a capacity tight enough
    to actually drop tokens."""
    import numpy as np

    from mpistragglers_jl_tpu.models.moe import (
        _combine_per_token,
        _expert_ffn,
        _gather_dispatch,
        _route_tables,
        _scatter_combine,
        switch_route,
    )

    rng = np.random.default_rng(0)
    T, D, E, F = 64, 16, 4, 32
    C = 8  # < T/E * anything skewed: forces drops
    x2d = jnp.asarray(rng.standard_normal((T, D)), jnp.float32)
    wg = jnp.asarray(rng.standard_normal((D, E)), jnp.float32)
    mp = {
        "we1": jnp.asarray(rng.standard_normal((E, D, F)) * 0.1, jnp.float32),
        "be1": jnp.zeros((E, F), jnp.float32),
        "we2": jnp.asarray(rng.standard_normal((E, F, D)) * 0.1, jnp.float32),
        "be2": jnp.asarray(rng.standard_normal((E, D)) * 0.1, jnp.float32),
    }
    # one-hot path
    dispatch, combine, aux_a = switch_route(x2d, wg, C)
    xe_a = jnp.einsum("td,tec->ecd", x2d, dispatch)
    ye_a = _expert_ffn(xe_a, mp) + mp["be2"][:, None, :]
    y_a = jnp.einsum("ecd,tec->td", ye_a, combine)
    dropped = np.asarray(dispatch.sum(axis=(1, 2)) == 0)
    assert dropped.any(), "pick a tighter capacity: no drops exercised"
    # gather path (per-token combine, the hot form)
    table, expert, slot, gate, aux_b = _route_tables(x2d, wg, C)
    xe_b = _gather_dispatch(x2d, table, expert, slot)
    np.testing.assert_allclose(np.asarray(xe_a), np.asarray(xe_b), atol=1e-6)
    ye_b = _expert_ffn(xe_b, mp) + mp["be2"][:, None, :]
    kg = jnp.where(slot < C, gate, 0.0)
    y_b = _combine_per_token(ye_b, table, expert, slot) * kg[:, None]
    np.testing.assert_allclose(
        np.asarray(y_a), np.asarray(y_b), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_allclose(float(aux_a), float(aux_b), rtol=1e-6)
    # dropped tokens produce exactly zero in both
    assert np.all(np.abs(np.asarray(y_b))[dropped] < 1e-7)
    # the scatter-add oracle agrees with the per-token combine too
    gate_pad = jnp.concatenate([gate, jnp.zeros((1,), gate.dtype)])
    g = gate_pad[table]
    y_c = _scatter_combine(ye_b * g[..., None], table, T)
    np.testing.assert_allclose(
        np.asarray(y_b), np.asarray(y_c), atol=1e-6
    )


@pytest.mark.slow
def test_gather_form_gradients_match_onehot_oracle():
    """The custom VJPs (gather-form backward for dispatch AND combine)
    must produce the one-hot einsum formulation's gradients exactly —
    d/dx, d/d(expert weights), d/d(router) all compared, drops
    included."""
    import numpy as np

    from mpistragglers_jl_tpu.models.moe import (
        _combine_per_token,
        _expert_ffn,
        _gather_dispatch,
        _route,
        _route_tables,
        switch_route,
    )

    rng = np.random.default_rng(7)
    T, D, E, F = 48, 12, 4, 24
    C = 6  # force drops
    x2d = jnp.asarray(rng.standard_normal((T, D)), jnp.float32)
    mp = {
        "wg": jnp.asarray(rng.standard_normal((D, E)), jnp.float32),
        "we1": jnp.asarray(rng.standard_normal((E, D, F)) * 0.1,
                           jnp.float32),
        "be1": jnp.zeros((E, F), jnp.float32),
        "we2": jnp.asarray(rng.standard_normal((E, F, D)) * 0.1,
                           jnp.float32),
        "be2": jnp.asarray(rng.standard_normal((E, D)) * 0.1,
                           jnp.float32),
    }

    def loss_onehot(x2d, mp):
        dispatch, combine, _ = switch_route(x2d, mp["wg"], C)
        xe = jnp.einsum("td,tec->ecd", x2d, dispatch)
        ye = _expert_ffn(xe, mp) + mp["be2"][:, None, :]
        y = jnp.einsum("ecd,tec->td", ye, combine)
        return jnp.sum(y ** 2)

    def loss_gather(x2d, mp):
        table, expert, slot, gate, _ = _route_tables(x2d, mp["wg"], C)
        xe = _gather_dispatch(x2d, table, expert, slot)
        ye = _expert_ffn(xe, mp) + mp["be2"][:, None, :]
        kg = jnp.where(slot < C, gate, 0.0).astype(x2d.dtype)
        y = _combine_per_token(ye, table, expert, slot) * kg[:, None]
        return jnp.sum(y ** 2)

    la, ga = jax.value_and_grad(loss_onehot, argnums=(0, 1))(x2d, mp)
    lb, gb = jax.value_and_grad(loss_gather, argnums=(0, 1))(x2d, mp)
    np.testing.assert_allclose(float(la), float(lb), rtol=1e-6)
    flat_a = jax.tree.leaves(ga)
    flat_b = jax.tree.leaves(gb)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )
