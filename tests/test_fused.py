"""Fused pool ↔ mesh decode: asyncmap map step + in-place psum_scatter.

The round-1 gap this closes: the pool path decoded by gathering shards to
one device, and the mesh decode was only ever fed a synthetic ``repochs``.
Here ``repochs`` comes from real asyncmap arrivals with injected
stragglers, and the decode consumes ``pool.results`` where they sit —
one shard per mesh device, assembled zero-copy.

Reference bar: the ``repochs``-as-decode-mask contract at
src/MPIAsyncPools.jl:145-188.
"""

import jax
import numpy as np
import pytest

from mpistragglers_jl_tpu.parallel import (
    PoolMeshCodedGemm,
    PoolMeshMatDotGemm,
    make_mesh,
)
from mpistragglers_jl_tpu.pool import AsyncPool, asyncmap, waitall

N = 8
K = 6
STRAGGLERS = (0, 7)


def _delay(i, epoch):
    # two permanent stragglers, deterministic (SURVEY §7: injection, not
    # randomness, is the test mechanism of record)
    return 0.25 if i in STRAGGLERS else 0.0


@pytest.fixture
def mesh():
    assert len(jax.devices()) >= N, "conftest must provide 8 virtual devices"
    return make_mesh(N)


def test_fused_epoch_decodes_with_real_stragglers(mesh):
    rng = np.random.default_rng(0)
    A = rng.standard_normal((K * 16, 24)).astype(np.float32)
    B = rng.standard_normal((24, 12)).astype(np.float32)
    fg = PoolMeshCodedGemm(A, mesh, K, delay_fn=_delay, dtype=np.float32)
    pool = AsyncPool(N)
    try:
        decoded = fg.epoch(pool, B)
        # repochs is REAL: both stragglers must be stale at return (their
        # 0.25 s stall dwarfs the fast workers' compute)
        fresh = pool.fresh_indices()
        assert len(fresh) >= K
        for s in STRAGGLERS:
            assert pool.repochs[s] != pool.epoch
            assert pool.active[s]
        np.testing.assert_allclose(fg.full(decoded), A @ B, atol=1e-3)
    finally:
        waitall(pool, fg.backend, timeout=5.0)
        fg.shutdown()


def test_decode_output_stays_sharded_no_device0_gather(mesh):
    """The decoded array must be sharded across the mesh — one block per
    device — not gathered onto a single device."""
    rng = np.random.default_rng(1)
    A = rng.standard_normal((K * 8, 16)).astype(np.float32)
    B = rng.standard_normal((16, 8)).astype(np.float32)
    fg = PoolMeshCodedGemm(A, mesh, K, dtype=np.float32)
    pool = AsyncPool(N)
    try:
        decoded = fg.epoch(pool, B, nwait=N)
        shard_devs = {s.device for s in decoded.addressable_shards}
        assert len(shard_devs) == N, (
            f"decode landed on {len(shard_devs)} device(s); expected one "
            f"block per mesh device"
        )
        # the pool's result shards themselves live on their worker device
        for i in range(N):
            assert pool.results[i].device == fg.devices[i]
        np.testing.assert_allclose(fg.full(decoded), A @ B, atol=1e-3)
    finally:
        fg.shutdown()


def test_fused_multi_epoch_stale_harvest(mesh):
    """Straggler results from epoch e arrive during epoch e+1: the pool
    harvests them as stale, re-tasks, and the decode still only uses
    fresh shards."""
    rng = np.random.default_rng(2)
    A = rng.standard_normal((K * 8, 16)).astype(np.float32)
    fg = PoolMeshCodedGemm(A, mesh, K, delay_fn=_delay, dtype=np.float32)
    pool = AsyncPool(N)
    try:
        for e in range(3):
            B = rng.standard_normal((16, 8)).astype(np.float32)
            decoded = fg.epoch(pool, B)
            np.testing.assert_allclose(fg.full(decoded), A @ B, atol=1e-3)
    finally:
        waitall(pool, fg.backend, timeout=5.0)
        fg.shutdown()


def test_decode_from_pool_requires_k_fresh(mesh):
    rng = np.random.default_rng(3)
    A = rng.standard_normal((K * 4, 8)).astype(np.float32)
    B = rng.standard_normal((8, 4)).astype(np.float32)
    fg = PoolMeshCodedGemm(A, mesh, K, dtype=np.float32)
    pool = AsyncPool(N)
    try:
        # wait for K-1 only: decode must refuse (never heard from enough)
        asyncmap(pool, B, fg.backend, nwait=K - 1)
        fresh = pool.fresh_indices()
        if len(fresh) < K:  # racy fast workers may already exceed K-1
            with pytest.raises(ValueError, match="fresh"):
                fg.decode_from_pool(pool)
    finally:
        waitall(pool, fg.backend, timeout=5.0)
        fg.shutdown()


def test_fused_matdot_psum_decode(mesh):
    """MatDot fusion: decode is one weighted psum over resident
    evaluations; result replicated, exact with 2 stragglers stale."""
    rng = np.random.default_rng(4)
    A = rng.standard_normal((24, 16)).astype(np.float32)
    B = rng.standard_normal((16, 8)).astype(np.float32)
    md = PoolMeshMatDotGemm(A, mesh, p=2, delay_fn=_delay, dtype=np.float32)
    pool = AsyncPool(N)
    try:
        C = md.epoch(pool, B)
        for s in STRAGGLERS:
            assert pool.repochs[s] != pool.epoch
        np.testing.assert_allclose(np.asarray(C), A @ B, atol=1e-3)
    finally:
        waitall(pool, md.backend, timeout=5.0)
        md.shutdown()


def test_fused_epoch_changed_payload_width(mesh):
    """A stale shard whose width no longer matches the current epoch's B
    enters the combine as a zero placeholder, not a shape error."""
    rng = np.random.default_rng(5)
    A = rng.standard_normal((K * 4, 8)).astype(np.float32)
    fg = PoolMeshCodedGemm(A, mesh, K, delay_fn=_delay, dtype=np.float32)
    pool = AsyncPool(N)
    try:
        B1 = rng.standard_normal((8, 4)).astype(np.float32)
        fg.epoch(pool, B1)
        B2 = rng.standard_normal((8, 6)).astype(np.float32)  # new width
        decoded = fg.epoch(pool, B2)
        np.testing.assert_allclose(fg.full(decoded), A @ B2, atol=1e-3)
    finally:
        waitall(pool, fg.backend, timeout=5.0)
        fg.shutdown()


def test_pool_size_mismatch_rejected(mesh):
    rng = np.random.default_rng(6)
    A = rng.standard_normal((K * 4, 8)).astype(np.float32)
    fg = PoolMeshCodedGemm(A, mesh, K, dtype=np.float32)
    try:
        with pytest.raises(ValueError, match="one-to-one"):
            fg.epoch(AsyncPool(N + 2), np.zeros((8, 4), np.float32))
        with pytest.raises(ValueError, match="one-to-one"):
            fg.decode_from_pool(AsyncPool(N - 1))
    finally:
        fg.shutdown()


@pytest.mark.parametrize("mesh_d", [1, 2, 4])
def test_folded_pool_on_smaller_mesh(mesh_d):
    """n_workers > mesh devices (the single-bench-chip layout): workers
    fold onto devices in contiguous groups, the adopter stacks each
    group device-side, and the folded combine must decode exactly like
    the one-worker-per-device path — stragglers included."""
    mesh = make_mesh(mesh_d)
    rng = np.random.default_rng(1)
    A = rng.standard_normal((K * 16, 24)).astype(np.float32)
    B = rng.standard_normal((24, 12)).astype(np.float32)
    fg = PoolMeshCodedGemm(
        A, mesh, K, n_workers=N, delay_fn=_delay, dtype=np.float32
    )
    assert fg.fold == N // mesh_d
    pool = AsyncPool(N)
    try:
        decoded = fg.epoch(pool, B, timeout=30.0)
        # output stays sharded over the mesh axis
        assert decoded.shape[0] == N
        C = fg.full(decoded)
        np.testing.assert_allclose(C, A @ B, rtol=2e-4, atol=2e-4)
        # stragglers really were left behind at decode time
        fresh = pool.fresh_indices()
        assert len(fresh) >= K
        waitall(pool, fg.backend, timeout=30.0)
        # second epoch reuses the cached weights / placeholder machinery
        decoded = fg.epoch(pool, B + 1.0, timeout=30.0)
        np.testing.assert_allclose(
            fg.full(decoded), A @ (B + 1.0), rtol=2e-4, atol=2e-4
        )
        waitall(pool, fg.backend, timeout=30.0)
    finally:
        fg.shutdown()


def test_folded_pool_rejects_ragged_fold():
    mesh = make_mesh(3)
    A = np.zeros((K * 4, 8), np.float32)
    with pytest.raises(ValueError, match="multiple of the mesh axis"):
        PoolMeshCodedGemm(A, mesh, K, n_workers=N)  # 8 over 3 devices


@pytest.mark.parametrize("mesh_d", [1, 4])
def test_folded_pool_batch_mode(mesh_d):
    """batch=True: one stacked map program per device; the adopter
    adopts each group's already-stacked result (zero copies). Must
    decode exactly like the per-worker dispatch path."""
    mesh = make_mesh(mesh_d)
    rng = np.random.default_rng(2)
    A = rng.standard_normal((K * 16, 24)).astype(np.float32)
    B = rng.standard_normal((24, 12)).astype(np.float32)
    fg = PoolMeshCodedGemm(A, mesh, K, n_workers=N, dtype=np.float32,
                           batch=True)
    pool = AsyncPool(N)
    try:
        decoded = fg.epoch(pool, B, timeout=30.0)
        np.testing.assert_allclose(
            fg.full(decoded), A @ B, rtol=2e-4, atol=2e-4
        )
        # the batched map really fired: every HARVESTED result is a
        # lazy slice of its device group's stacked program (the k-wait
        # leaves late workers as None — the adopter masks them)
        from mpistragglers_jl_tpu.backends.xla import StackedSlice

        fresh = pool.fresh_indices()
        assert len(fresh) >= K
        assert all(
            isinstance(pool.results[int(i)], StackedSlice) for i in fresh
        )
        waitall(pool, fg.backend, timeout=30.0)
        # drained: now ALL results are slices and whole groups hit the
        # zero-copy adoption fast path
        assert all(
            isinstance(pool.results[i], StackedSlice) for i in range(N)
        )
        decoded = fg.decode_from_pool(pool, epoch=pool.epoch)
        np.testing.assert_allclose(
            fg.full(decoded), A @ B, rtol=2e-4, atol=2e-4
        )
        decoded = fg.epoch(pool, B * 2.0, timeout=30.0)
        np.testing.assert_allclose(
            fg.full(decoded), A @ (B * 2.0), rtol=2e-4, atol=2e-4
        )
        waitall(pool, fg.backend, timeout=30.0)
    finally:
        fg.shutdown()


def test_select_coded_gemm_probes_and_picks(mesh):
    """Measured auto-selection (VERDICT r4 item 4): both candidates are
    probed on this session, a winner survives with the decision + both
    measurements recorded, the loser is shut down, and the winner
    decodes exactly."""
    from mpistragglers_jl_tpu.parallel import select_coded_gemm

    rng = np.random.default_rng(5)
    A = rng.standard_normal((K * 8, 16)).astype(np.float32)
    B = rng.standard_normal((16, 10)).astype(np.float32)
    g = select_coded_gemm(A, mesh, K, B, probe_epochs=2, chains=1,
                          dtype=np.float32)
    sel = g.selection
    assert sel["picked"] in ("fused", "unfused")
    assert sel["fused_ms"] > 0 and sel["unfused_ms"] > 0
    assert sel["mesh_devices"] == N
    picked_ms = sel[f"{sel['picked']}_ms"]
    assert picked_ms == min(sel["fused_ms"], sel["unfused_ms"])
    pool = AsyncPool(N)
    decoded = g.epoch(pool, B)
    C = g.full(decoded)
    np.testing.assert_allclose(C[: A.shape[0]], A @ B, atol=1e-3)
    waitall(pool, g.backend)
    g.shutdown()


def test_select_coded_gemm_forwards_nondefault_axis():
    """Regression (r5 review): ``select_coded_gemm`` popped ``axis``
    for its device lookup but never forwarded it to the fused
    candidate, so any mesh axis not named 'w' crashed inside
    PoolMeshCodedGemm. A 'pool'-named axis must probe, pick, and
    decode exactly like the default."""
    from mpistragglers_jl_tpu.parallel import select_coded_gemm

    mesh = make_mesh(N, ("pool",))
    rng = np.random.default_rng(6)
    A = rng.standard_normal((K * 8, 16)).astype(np.float32)
    B = rng.standard_normal((16, 10)).astype(np.float32)
    g = select_coded_gemm(A, mesh, K, B, probe_epochs=1, chains=1,
                          axis="pool", dtype=np.float32)
    try:
        assert g.selection["picked"] in ("fused", "unfused")
        pool = AsyncPool(N)
        decoded = g.epoch(pool, B)
        np.testing.assert_allclose(
            g.full(decoded)[: A.shape[0]], A @ B, atol=1e-3
        )
        waitall(pool, g.backend)
    finally:
        g.shutdown()
