"""Multi-host pool end-to-end (VERDICT round 1, item 5).

The advertised flow, actually run: a coordinator with
``NativeProcessBackend(spawn=False, address="tcp://...")``, worker
processes joined via the CLI (``python -m mpistragglers_jl_tpu.worker``)
— each running **jitted** jax compute — one worker SIGKILLed mid-run and
re-adopted via ``reaccept``, training continuing through it. Loopback
TCP stands in for the network; the command pair for two real hosts is in
examples/multihost_jax_worker.py.
"""

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from mpistragglers_jl_tpu import AsyncPool, asyncmap, waitall
from mpistragglers_jl_tpu.native import NativeBuildError

try:
    from mpistragglers_jl_tpu.backends.native import NativeProcessBackend
    from mpistragglers_jl_tpu.native import transport as T

    T.load_lib()
    _SKIP = None
except NativeBuildError as e:  # pragma: no cover - no compiler in env
    _SKIP = str(e)

pytestmark = pytest.mark.skipif(
    _SKIP is not None, reason=f"native transport unavailable: {_SKIP}"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SECRET = "e2e-test-secret"


def _start_cli_worker(rank: int, address: str) -> subprocess.Popen:
    """One CLI worker process, exactly as a remote host would run it."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MSGT_AUTH"] = SECRET
    env["JAX_PLATFORMS"] = "cpu"  # workers own their device locally
    env["JAX_ENABLE_X64"] = "1"   # exactness vs the float64 oracle
    return subprocess.Popen(
        [
            sys.executable, "-m", "mpistragglers_jl_tpu.worker",
            "--address", address, "--ranks", str(rank),
            "--work", "examples.multihost_jax_worker:work",
        ],
        env=env,
    )


@pytest.mark.slow
def test_tcp_cli_workers_jitted_sgd_with_kill_and_reaccept():
    from examples.multihost_jax_worker import DIM, reference_grad

    n = 3
    backend = NativeProcessBackend(
        None, n, spawn=False, address="tcp://127.0.0.1:0",
        auth=SECRET, accept=False, connect_timeout=120.0,
        on_dead="straggle",  # elastic mode: dead ranks just never answer
    )
    procs: dict[int, subprocess.Popen] = {}
    try:
        for r in range(n):
            procs[r] = _start_cli_worker(r, backend.address)
        backend.accept(timeout=120.0)

        pool = AsyncPool(n)
        # non-degenerate start: at w=0 every logit is exactly 0 and the
        # stable-BCE max/abs kinks make the subgradient
        # implementation-defined — any nonzero w is off the kink
        w0 = np.random.default_rng(7).standard_normal(DIM) * 0.1
        w = w0.copy()
        lr = 0.5

        def epoch(ep, nwait):
            nonlocal w
            asyncmap(pool, w, backend, nwait=nwait, epoch=ep)
            fresh = pool.fresh_indices(ep)
            g = np.mean(
                [np.asarray(pool.results[i]) for i in fresh], axis=0
            )
            w = w - lr * g
            return fresh

        # --- phase 1: all ranks healthy; jitted grads must be EXACT ---
        epoch(1, nwait=n)
        want = reference_grad(w0, range(n))
        got = np.mean(
            [np.asarray(pool.results[i]) for i in range(n)], axis=0
        )
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12)
        for ep in range(2, 6):
            epoch(ep, nwait=n)

        # --- phase 2: SIGKILL rank 1 mid-run; pool keeps going -------
        # straggle mode: the dead rank is an infinite straggler
        # (reference SURVEY §5 semantics); fastest-2 epochs continue
        # over the survivors with no errors raised at all
        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait(timeout=30)
        ep = 6
        for _ in range(4):
            fresh = epoch(ep, nwait=2)
            assert sorted(int(i) for i in fresh) == [0, 2]
            ep += 1
        assert backend._coord.is_dead(1)
        assert pool.active[1]  # in-flight forever, like the reference

        # --- phase 3: restart the CLI process; reaccept re-adopts it --
        procs[1] = _start_cli_worker(1, backend.address)
        backend.reaccept(1, timeout=120.0)
        pool.reset_worker(1)  # the lost dispatch can never complete
        fresh = epoch(ep, nwait=n)
        assert sorted(int(i) for i in fresh) == [0, 1, 2]
        ep += 1

        # --- training converged through all of it ---------------------
        final_grad = reference_grad(w, range(n))
        first_grad = reference_grad(w0, range(n))
        assert np.linalg.norm(final_grad) < 0.5 * np.linalg.norm(
            first_grad
        ), (np.linalg.norm(final_grad), np.linalg.norm(first_grad))
        waitall(pool, backend, timeout=30.0)
        assert not pool.active.any()
    finally:
        backend.shutdown()
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    p.kill()
