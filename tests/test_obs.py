"""Observability subsystem (mpistragglers_jl_tpu/obs).

Contracts under test:

* the registry — get-or-create identity, thread-safe counts, fixed
  log-bucket histograms (edge buckets, concurrent get-or-create,
  +Inf round-trips), and a Prometheus text exposition that parses
  LINE BY LINE (a scrape either reads every line or the export is
  broken);
* the unified timeline — a serving-scheduler run and a pool asyncmap
  loop merge into ONE Chrome trace-event JSON (valid JSON, non-negative
  span durations, worker/coordinator AND scheduler-tick tracks) with
  the summary()'s waitall-drain accounting alongside;
* the opt-in contract — a dark scheduler allocates no registry objects
  and its tick path's residual guard cost is bounded far below the 5%
  budget (the no-op fast path the tracer established for the pool,
  extended to every instrumented layer);
* the live telemetry plane — cross-process aggregation (worker-local
  registries piggybacked on result frames, counter-delta semantics
  across respawns, clock-aligned spans), the flight recorder's bounded
  postmortem ring + watchdog, and the HTTP exporter's /metrics,
  /healthz, /trace, /flight round-trips against a real straggling
  ProcessBackend pool and an instrumented scheduler.
"""

import json
import math
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mpistragglers_jl_tpu import AsyncPool, LocalBackend, asyncmap, waitall
from mpistragglers_jl_tpu.backends.base import DeadWorkerError
from mpistragglers_jl_tpu.backends.process import ProcessBackend
from mpistragglers_jl_tpu.obs import (
    DEFAULT_BUCKETS,
    FlightRecorder,
    MetricsRegistry,
    ObsServer,
    SpanRecorder,
    TelemetryAggregator,
    WorkerTelemetry,
    annotate,
    dump_merged_chrome_trace,
)
from mpistragglers_jl_tpu.utils import (
    EpochTracer,
    HedgedServer,
    PoolLatencyModel,
    faults,
)


def echo_work(i, payload, epoch):
    return payload * (i + 1)


def _get(url, timeout=10.0):
    """(status, body bytes) for a GET, 4xx/5xx included."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class PerWorkerDelay:
    """Picklable per-worker delay (spawned process workers need a
    module-level class; faults.per_worker closes over a lambda)."""

    def __init__(self, delays):
        self.delays = list(delays)

    def __call__(self, i, epoch):
        return self.delays[i]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_get_or_create_identity_and_kinds(self):
        reg = MetricsRegistry()
        c = reg.counter("a_total", help="h")
        assert reg.counter("a_total") is c
        assert reg.counter("a_total", route="x") is not c
        with pytest.raises(ValueError):
            reg.gauge("a_total")
        with pytest.raises(ValueError):
            reg.counter("bad name!")
        c.inc()
        c.inc(2.5)
        with pytest.raises(ValueError):
            c.inc(-1)
        assert c.value == 3.5
        # names are exactly the Prometheus grammar: a wider registry
        # grammar would need a lossy export mapping under which two
        # families ("a.b", "a_b") collide into one invalid exposition
        with pytest.raises(ValueError):
            reg.counter("a.b")
        with pytest.raises(ValueError):
            reg.counter("1ab")
        with pytest.raises(ValueError):
            reg.counter("latência_total")  # unicode isalnum trap

    def test_gauge_and_histogram(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth")
        g.set(4)
        g.inc()
        g.dec(2)
        assert g.value == 3
        h = reg.histogram("lat_seconds")
        assert h.bounds == DEFAULT_BUCKETS
        for v in (1e-5, 2e-3, 2e-3, 0.5, 1e9):
            h.observe(v)
        assert h.count == 5 and h.sum == pytest.approx(1e9 + 0.504012)
        assert h.quantile(0.5) <= h.quantile(0.95)
        assert h.quantile(1.0) == math.inf  # overflow bucket
        assert reg.histogram("empty").quantile(0.5) is None
        with pytest.raises(ValueError):
            reg.histogram("bad", buckets=(2.0, 1.0))
        # re-registration: same grid (or None = don't care) returns the
        # instrument, a conflicting grid raises instead of silently
        # routing out-of-range observes into +Inf
        w = reg.histogram("width", buckets=(1.0, 2.0, 4.0))
        assert reg.histogram("width") is w
        assert reg.histogram("width", buckets=(1, 2, 4)) is w
        with pytest.raises(ValueError):
            reg.histogram("width", buckets=(1.0, 2.0))
        # the grid is per FAMILY: a new labeled series inherits it
        # (disjoint le sets would misaggregate sum-by-le quantiles),
        # and a conflicting grid on any series of the family raises
        w2 = reg.histogram("width", worker="1")
        assert w2.bounds == (1.0, 2.0, 4.0)
        with pytest.raises(ValueError):
            reg.histogram("width", worker="2", buckets=(8.0,))

    def test_label_names_validated(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("a_total", **{"região": "eu"})  # unicode kwarg
        with pytest.raises(ValueError):
            reg.counter("a_total", __reserved="x")
        with pytest.raises(ValueError):
            reg.histogram("h_seconds", le="0.1")  # bucket-label clash
        reg.gauge("g", le="ok")  # reserved only where it collides

    def test_thread_safety_exact_counts(self):
        """Writers off the coordinator thread (the native transport's
        harvest thread case) must not lose increments."""
        reg = MetricsRegistry()
        c = reg.counter("c_total")
        h = reg.histogram("h_seconds")

        def w():
            for _ in range(5000):
                c.inc()
                h.observe(0.001)

        ts = [threading.Thread(target=w) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == 40000
        assert h.count == 40000

    def test_prometheus_parses_line_by_line(self):
        reg = MetricsRegistry()
        reg.counter("serving_tokens_total", help="tokens").inc(7)
        reg.counter("route_total", route="kernel").inc()
        reg.counter("route_total", route="einsum").inc(3)
        reg.gauge("queue_depth").set(2)
        h = reg.histogram("ttft_seconds")
        h.observe(0.01)
        h.observe(3.0)
        text = reg.to_prometheus()
        line_re = re.compile(
            r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*( .*)?"
            r"|[a-zA-Z_:][a-zA-Z0-9_:]*"
            r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
            r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})?"
            r" (-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|\+Inf|-Inf))$"
        )
        lines = text.splitlines()
        assert lines, "empty exposition"
        for line in lines:
            assert line_re.match(line), f"unparseable line: {line!r}"
        # histogram expansion: cumulative buckets end at count
        bucket = [ln for ln in lines if ln.startswith("ttft_seconds_bucket")]
        assert bucket[-1].startswith('ttft_seconds_bucket{le="+Inf"}')
        assert bucket[-1].endswith(" 2")
        assert "ttft_seconds_count 2" in lines
        # both labeled series of one family export under one TYPE
        assert sum(1 for ln in lines if ln.startswith("# TYPE route_total")) == 1

    def test_prometheus_help_escaping_roundtrips(self):
        """Exposition 0.0.4 conformance (round 22): HELP text escapes
        backslash as ``\\\\`` and newline as ``\\n`` — byte-exact
        round-trip through the spec's unescaping, not the old
        newline->space flattening. Label values were already
        conformant; pinned here beside the HELP arm."""
        reg = MetricsRegistry()
        help_text = 'rate in req\\s\nsecond line with "quotes"'
        reg.counter("tricky_total", help=help_text).inc()
        reg.counter(
            "labeled_total", path='a\\b\n"c"'
        ).inc()
        text = reg.to_prometheus()
        lines = text.splitlines()
        # every line is still single-line (no raw newline leaked)
        help_line = next(
            ln for ln in lines if ln.startswith("# HELP tricky_total")
        )
        escaped = help_line[len("# HELP tricky_total "):]
        assert "\n" not in escaped
        assert escaped == (
            'rate in req\\\\s\\nsecond line with "quotes"'
        )

        # the spec's unescaping recovers the original exactly
        def unescape_help(s):
            out, i = [], 0
            while i < len(s):
                if s[i] == "\\" and i + 1 < len(s):
                    out.append(
                        {"\\": "\\", "n": "\n"}[s[i + 1]]
                    )
                    i += 2
                else:
                    out.append(s[i])
                    i += 1
            return "".join(out)

        assert unescape_help(escaped) == help_text
        # label values: backslash, quote, and newline all escaped
        sample = next(
            ln for ln in lines if ln.startswith("labeled_total{")
        )
        assert 'path="a\\\\b\\n\\"c\\""' in sample
        assert "\n" not in sample

    def test_json_snapshot_roundtrips(self):
        reg = MetricsRegistry()
        reg.counter("a_total").inc(2)
        reg.histogram("b_seconds").observe(0.1)
        snap = json.loads(reg.to_json())
        assert snap["a_total"]["series"][0]["value"] == 2
        hist = snap["b_seconds"]["series"][0]["value"]
        assert hist["count"] == 1 and hist["p50"] > 0


# ---------------------------------------------------------------------------
# tracer summary: waitall drains no longer vanish
# ---------------------------------------------------------------------------


class TestSummaryWaitall:
    def test_waitall_drains_counted(self):
        """A straggler whose results only ever land in waitall used to
        vanish: dispatched but never counted as an arrival. Now every
        dispatch is accounted (delivered_rate == 1 after a full
        drain) and the drain shows up in n_waitall_arrivals."""
        backend = LocalBackend(
            echo_work, 3, delay_fn=faults.per_worker([0.002, 0.002, 0.08])
        )
        tracer = EpochTracer()
        try:
            pool = AsyncPool(3)
            for _ in range(3):
                asyncmap(pool, np.zeros(1), backend, nwait=2,
                         tracer=tracer)
                waitall(pool, backend, tracer=tracer)
        finally:
            backend.shutdown()
        s = tracer.summary()
        arrivals = s["n_fresh"] + s["n_stale"]
        assert s["n_dispatched"] == arrivals == 9
        assert s["delivered_rate"] == 1.0
        assert s["n_waitall_arrivals"] >= 3  # the straggler's drains
        # straggler_rate keeps its asyncmap-only meaning: worker 2
        # never made the nwait=2 cut inside its own epoch
        assert s["straggler_rate"] == pytest.approx(1 / 3)

    def test_waitall_only_trace_still_accounts(self):
        """A tracer attached only to a shutdown drain (the
        CodedGradTrainer.fit pattern with an untraced loop) must not
        collapse to a bare {'epochs': 0} — the drains ARE the data."""
        backend = LocalBackend(echo_work, 2)
        tracer = EpochTracer()
        try:
            pool = AsyncPool(2)
            asyncmap(pool, np.zeros(1), backend, nwait=0)  # untraced
            waitall(pool, backend, tracer=tracer)
        finally:
            backend.shutdown()
        s = tracer.summary()
        assert s["epochs"] == 0 and s["wall_mean_s"] is None
        assert s["n_waitall_arrivals"] == 2
        assert s["n_fresh"] + s["n_stale"] == 2
        assert s["straggler_rate"] == 0.0  # no in-trace dispatches

    def test_asyncmap_only_run_unchanged(self):
        backend = LocalBackend(echo_work, 2)
        tracer = EpochTracer()
        try:
            pool = AsyncPool(2)
            for _ in range(4):
                asyncmap(pool, np.zeros(1), backend, nwait=2,
                         tracer=tracer)
        finally:
            backend.shutdown()
        s = tracer.summary()
        assert s["epochs"] == 4
        assert s["n_waitall_arrivals"] == 0
        assert s["straggler_rate"] == 0.0
        assert s["delivered_rate"] == 1.0


# ---------------------------------------------------------------------------
# hedge / latency-model registry export
# ---------------------------------------------------------------------------


class TestRegistryExports:
    def test_hedge_metrics(self):
        reg = MetricsRegistry()
        backend = LocalBackend(
            echo_work, 4,
            delay_fn=faults.per_worker([0.001, 0.001, 0.001, 0.001]),
        )
        srv = HedgedServer(backend, registry=reg)
        try:
            for _ in range(5):
                srv.request(np.ones(2), hedge=2)
            srv.drain()
        finally:
            backend.shutdown()
        assert reg.counter("hedge_requests_total").value == 5
        assert reg.counter("hedge_dispatches_total").value >= 5
        assert reg.histogram("hedge_width").count == 5
        assert reg.histogram("hedge_winner_latency_seconds").count == 5
        wins = sum(
            reg.counter("hedge_wins_total", rank=str(r)).value
            for r in range(4)
        )
        assert wins == 5
        assert "hedge_width_bucket" in reg.to_prometheus()

    def test_latency_model_publish(self):
        reg = MetricsRegistry()
        model = PoolLatencyModel(2)
        for _ in range(6):
            model.observe(0, 0.01)
            model.observe(1, 0.05)
        model.publish(reg)
        m0 = reg.gauge("pool_worker_latency_mean_seconds", worker="0")
        m1 = reg.gauge("pool_worker_latency_mean_seconds", worker="1")
        assert m0.value == pytest.approx(0.01)
        assert m1.value == pytest.approx(0.05)
        assert reg.gauge(
            "pool_worker_latency_samples", worker="1"
        ).value == 6
        # re-publish overwrites, never duplicates series
        n = len(reg)
        model.observe(0, 0.02)
        model.publish(reg)
        assert len(reg) == n


# ---------------------------------------------------------------------------
# merged timeline: scheduler + pool in one trace
# ---------------------------------------------------------------------------


def _pool_traced_run():
    backend = LocalBackend(
        echo_work, 3, delay_fn=faults.per_worker([0.03, 0.002, 0.002])
    )
    tracer = EpochTracer()
    try:
        pool = AsyncPool(3)
        for _ in range(3):
            asyncmap(pool, np.zeros(1), backend, nwait=2, tracer=tracer)
        waitall(pool, backend, tracer=tracer)
    finally:
        backend.shutdown()
    return tracer


class TestMergedTimeline:
    def test_span_recorder_chrome_shape(self, tmp_path):
        rec = SpanRecorder("demo")
        with rec.span("outer", track="t", x=1):
            time.sleep(0.002)
        rec.add("retro", 1.0, 0.5, track="t")
        rec.add("clamped", 1.0, -0.5, track="t")  # clock hiccup
        rec.count("depth", 3)
        path = tmp_path / "one.json"
        n = rec.dump_chrome_trace(path)
        doc = json.loads(path.read_text())
        evs = doc["traceEvents"]
        assert n == 4
        assert all(e["dur"] >= 0 for e in evs if e["ph"] == "X")
        assert any(
            e["ph"] == "M" and e["name"] == "process_name"
            and e["args"]["name"] == "demo"
            for e in evs
        )
        assert any(e["ph"] == "C" for e in evs)

    def test_pool_and_recorder_merge(self, tmp_path):
        """A pool tracer and a host recorder land in one valid trace
        under distinct pids, every span non-negative, pool worker /
        coordinator track metadata intact."""
        tracer = _pool_traced_run()
        rec = SpanRecorder("train")
        with rec.span("step 1", track="train"):
            time.sleep(0.001)
        path = tmp_path / "merged.json"
        n = dump_merged_chrome_trace(
            path, tracers=[tracer], recorders=[rec]
        )
        doc = json.loads(path.read_text())
        evs = doc["traceEvents"]
        spans = [e for e in evs if e["ph"] == "X"]
        assert len(spans) == n
        assert all(e["dur"] >= 0 and e["ts"] >= 0 for e in spans)
        names = {
            (e["pid"], e["args"]["name"])
            for e in evs
            if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert (0, "coordinator") in names
        assert any(nm.startswith("worker") for p, nm in names if p == 0)
        procs = {
            e["pid"]: e["args"]["name"]
            for e in evs
            if e["ph"] == "M" and e["name"] == "process_name"
        }
        assert procs == {0: "pool", 1: "train"}
        # both sources contributed spans, on their own processes
        assert {e["pid"] for e in spans} == {0, 1}

    def test_recorder_cap_is_visible_not_silent(self, tmp_path):
        """A long-lived writer hits max_events: new events drop, the
        drop is counted and surfaces as a truncation marker in the
        exported trace (never a silent end-of-run)."""
        rec = SpanRecorder("s", max_events=3)
        for i in range(5):
            rec.add(f"e{i}", float(i), 0.5)
        rec.count("q", 1)
        assert len(rec) == 3 and rec.dropped == 3
        assert "3 dropped" in repr(rec)
        path = tmp_path / "capped.json"
        rec.dump_chrome_trace(path)
        doc = json.loads(path.read_text())
        assert any(
            e["ph"] == "I" and "3 events dropped" in e["name"]
            for e in doc["traceEvents"]
        )

    def test_annotate_is_safe_everywhere(self):
        with annotate("anything"):
            pass


# ---------------------------------------------------------------------------
# serving scheduler instrumentation (jax; tiny config)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_serving():
    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab=37, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=64, attn_window=6,
    )
    return cfg, init_params(cfg, seed=3)


def _sched(cfg, params, **kw):
    from mpistragglers_jl_tpu.models.serving import ServingScheduler

    return ServingScheduler(
        params, cfg, slots=2, n_inner=4, prompt_chunk=8, max_prompt=32,
        **kw,
    )


class TestServingObservability:
    def test_instrumented_run_exports_everything(
        self, tiny_serving, tmp_path
    ):
        """The acceptance run: >= 3 requests submit->retire through an
        instrumented scheduler + one traced pool loop -> ONE merged
        Chrome trace with scheduler-tick and pool-worker tracks, and a
        Prometheus dump carrying queue depth, tokens/s, the TTFT
        histogram, and kernel-route counters."""
        cfg, params = tiny_serving
        reg = MetricsRegistry()
        rec = SpanRecorder("serving")
        sched = _sched(cfg, params, registry=reg, spans=rec)
        rng = np.random.default_rng(0)
        reqs = [
            sched.submit(rng.integers(1, cfg.vocab, size=p), max_new=m)
            for p, m in [(5, 6), (11, 4), (3, 8), (7, 5)]
        ]
        sched.run()
        assert all(r.finished for r in reqs)

        # series
        assert reg.counter("serving_ticks_total").value >= 2
        # the counter records DELIVERED tokens (the EOS-clamped tail
        # the retirement trim strips is never counted), so after full
        # drain it equals the streams exactly
        assert reg.counter("serving_tokens_total").value == sum(
            len(r.tokens) for r in reqs
        )
        # the per-tick span token counts cover the same population
        # (admission first-tokens included), so they cross-check
        assert sum(
            args["tokens"] for _, name, _, _, args in rec.spans
            if name.startswith("tick ")
        ) == reg.counter("serving_tokens_total").value
        assert reg.histogram("serving_ttft_seconds").count == len(reqs)
        assert reg.histogram("serving_intertoken_seconds").count > 0
        assert reg.counter("serving_admitted_total").value == len(reqs)
        assert (
            reg.counter("serving_retired_total", reason="length").value
            == len(reqs)
        )
        assert reg.counter("serving_prefill_chunks_total").value >= 5
        prom = reg.to_prometheus()
        for want in (
            "serving_queue_depth",
            "serving_tokens_per_s",
            "serving_ttft_seconds_bucket",
            "serving_kernel_route_total",
        ):
            assert want in prom, want

        # merged timeline with a pool run
        tracer = _pool_traced_run()
        path = tmp_path / "unified.json"
        dump_merged_chrome_trace(
            path, tracers=[tracer], recorders=[rec]
        )
        doc = json.loads(path.read_text())
        evs = doc["traceEvents"]
        spans = [e for e in evs if e["ph"] == "X"]
        assert all(e["dur"] >= 0 for e in spans)
        names = [e["name"] for e in spans]
        assert any(n.startswith("tick ") for n in names)
        assert any(n.startswith("asyncmap") for n in names)
        assert {"admit", "decode", "retire"} <= set(names)
        threads = {
            e["args"]["name"]
            for e in evs
            if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert "scheduler" in threads and "coordinator" in threads

    def test_greedy_stream_unchanged_by_instrumentation(
        self, tiny_serving
    ):
        cfg, params = tiny_serving
        rng = np.random.default_rng(5)
        p = rng.integers(1, cfg.vocab, size=9)
        dark = _sched(cfg, params)
        r1 = dark.submit(p, max_new=7)
        dark.run()
        lit = _sched(
            cfg, params, registry=MetricsRegistry(),
            spans=SpanRecorder(),
        )
        r2 = lit.submit(p, max_new=7)
        lit.run()
        assert r1.tokens == r2.tokens

    def test_dark_tick_does_no_observability_work(
        self, tiny_serving, monkeypatch
    ):
        """With nothing attached the tick path must allocate no
        registry objects and read no clocks: every metric constructor
        AND the serving module's perf_counter are boobytrapped, then a
        full submit->retire run executes."""
        from mpistragglers_jl_tpu.obs import metrics as m
        from mpistragglers_jl_tpu.models import serving

        def boom(*a, **k):
            raise AssertionError(
                "dark scheduler touched the observability layer"
            )

        for cls in (m.Counter, m.Gauge, m.Histogram, m.MetricsRegistry):
            monkeypatch.setattr(cls, "__init__", boom)

        class NoClock:
            perf_counter = staticmethod(boom)

            def __getattr__(self, name):  # anything else: real time
                return getattr(time, name)

        monkeypatch.setattr(serving, "time", NoClock())
        cfg, params = tiny_serving
        sched = _sched(cfg, params)
        r = sched.submit(np.arange(1, 6, dtype=np.int32), max_new=6)
        sched.run()
        assert r.finished and len(r.tokens) == 6

    def test_noop_overhead_under_budget(self, tiny_serving):
        """The no-op fast-path benchmark (acceptance: instrumentation
        disabled costs <= 5% of a scheduler tick). The dark tick's
        entire observability residue is a handful of ``obs is not
        None`` guards (the raising-clock test above proves nothing
        else runs); measure that guard bundle directly against a
        measured decode tick — nanoseconds vs milliseconds, so the
        bound holds with orders of magnitude to spare and no timing
        flake."""
        cfg, params = tiny_serving
        sched = _sched(cfg, params)
        sched.submit(np.arange(1, 4, dtype=np.int32), max_new=10_000)
        sched.step()  # admits + compiles the decode scan
        t0 = time.perf_counter()
        for _ in range(5):
            sched.step()
        tick_s = (time.perf_counter() - t0) / 5

        def guards(s):
            # the exact per-tick residue: the obs-None checks step()
            # and its admission/first-token/prefill hooks perform
            obs = s._obs
            if obs is not None:
                pass
            if s._obs is not None:
                pass
            if s._obs is not None:
                pass
            if obs is not None:
                pass
            if obs is not None:
                pass
            return obs

        reps = 20_000
        t0 = time.perf_counter()
        for _ in range(reps):
            guards(sched)
        guard_s = (time.perf_counter() - t0) / reps
        assert guard_s <= 0.05 * tick_s, (
            f"disabled-path guards cost {guard_s * 1e6:.2f} µs vs tick "
            f"{tick_s * 1e3:.2f} ms — no-op fast path regressed"
        )


# ---------------------------------------------------------------------------
# coded training instrumentation
# ---------------------------------------------------------------------------


class TestCodedTrainObservability:
    def test_step_metrics_and_tracer_bridge(self):
        import jax.numpy as jnp

        from mpistragglers_jl_tpu.models.coded_train import (
            CodedGradTrainer,
        )

        def loss(params, batch):
            x, y = batch
            return jnp.mean((x @ params["w"] - y) ** 2)

        rng = np.random.default_rng(0)
        chunks = [
            (
                jnp.asarray(rng.standard_normal((4, 3)), jnp.float32),
                jnp.asarray(rng.standard_normal((4,)), jnp.float32),
            )
            for _ in range(6)
        ]
        reg = MetricsRegistry()
        rec = SpanRecorder("train")
        tracer = EpochTracer()
        tr = CodedGradTrainer(
            loss,
            {"w": jnp.zeros((3,), jnp.float32)},
            lambda j: chunks[j],
            n_workers=6,
            s=2,
            tracer=tracer,
            registry=reg,
            spans=rec,
        )
        params, hist = tr.fit(epochs=3, lr=0.1, eval_every=None)
        assert reg.counter("train_steps_total").value == 3
        assert reg.histogram("train_step_seconds").count == 3
        assert reg.gauge("train_decode_fresh_k").value >= 4
        recovered = sum(
            reg.counter(
                "train_worker_recovered_total", worker=str(i)
            ).value
            for i in range(6)
        )
        assert recovered == 3 * 4  # k = n - s shards per step
        assert len(tracer.records) >= 3
        assert len(rec.spans) == 3
        assert all(nm.startswith("coded step") for _, nm, *_ in rec.spans)
        assert tr.last_fresh.size >= 4
        tr.backend.shutdown()


# ---------------------------------------------------------------------------
# histogram edge cases (fixed log grid)
# ---------------------------------------------------------------------------


class TestHistogramEdges:
    def test_extreme_values_land_in_edge_buckets(self):
        """Below the first bound -> first bucket; above the last ->
        the +Inf overflow bucket; neither is dropped or misfiled."""
        reg = MetricsRegistry()
        h = reg.histogram("edge_seconds")
        lo, hi = DEFAULT_BUCKETS[0], DEFAULT_BUCKETS[-1]
        h.observe(lo / 1e3)     # far below the first bound
        h.observe(0.0)          # degenerate zero
        h.observe(hi * 1e3)     # far above the last bound
        counts = h.bucket_counts()
        assert counts[0] == 2           # both sub-bound values
        assert counts[-1] == 1          # the overflow
        assert h.count == 3
        assert h.quantile(0.5) == lo    # covered by the first bucket
        assert h.quantile(1.0) == math.inf
        # exact-bound values are cumulative-<= (le semantics)
        h.observe(lo)
        assert h.bucket_counts()[0] == 3

    def test_concurrent_get_or_create_same_labeled_series(self):
        """Eight threads racing get-or-create of ONE labeled series
        must all receive the same instrument and lose no increments
        (the registry's lock covers creation; the instrument's lock
        covers counts)."""
        reg = MetricsRegistry()
        got = []

        def w():
            for _ in range(1000):
                c = reg.counter("race_total", worker="7")
                c.inc()
            got.append(reg.counter("race_total", worker="7"))

        ts = [threading.Thread(target=w) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(reg) == 1
        assert all(g is got[0] for g in got)
        assert got[0].value == 8000

    def test_prometheus_inf_roundtrip_and_le_cumulativity(self):
        """The exposition's bucket lines are CUMULATIVE, ordered by
        ``le``, end at the ``+Inf`` bucket, and ``+Inf`` == ``_count``
        — including when samples land below the first and above the
        last bound; every ``le`` value (incl. +Inf) parses back to the
        float grid."""
        reg = MetricsRegistry()
        h = reg.histogram("rt_seconds")
        for v in (1e-9, 2e-3, 0.5, 1e9, 1e9):
            h.observe(v)
        lines = reg.to_prometheus().splitlines()
        brx = re.compile(r'rt_seconds_bucket\{le="([^"]+)"\} (\d+)')
        buckets = [
            (m.group(1), int(m.group(2)))
            for m in map(brx.match, lines) if m
        ]
        assert len(buckets) == len(DEFAULT_BUCKETS) + 1
        les = [float(le) for le, _ in buckets]   # "+Inf" -> inf
        assert les == sorted(les) and les[-1] == math.inf
        assert les[:-1] == [pytest.approx(b) for b in DEFAULT_BUCKETS]
        cums = [c for _, c in buckets]
        assert cums == sorted(cums)              # cumulative
        assert cums[0] == 1                      # the below-first value
        assert cums[-1] == 5 == h.count          # +Inf == _count
        assert cums[-2] == 3                     # the two overflows
        assert "rt_seconds_count 5" in lines

    def test_merge_deltas_validation(self):
        """Cross-process merge rejects grid mismatches and negative
        deltas (a shrinking histogram is an upstream protocol bug)."""
        reg = MetricsRegistry()
        h = reg.histogram("m_seconds")
        n = len(DEFAULT_BUCKETS) + 1
        h.merge_deltas([1] * n, 2.5, n)
        assert h.count == n and h.sum == 2.5
        with pytest.raises(ValueError, match="grid"):
            h.merge_deltas([1] * (n - 1), 0.0, 1)
        with pytest.raises(ValueError, match=">= 0"):
            h.merge_deltas([-1] + [0] * (n - 1), 0.0, 0)


# ---------------------------------------------------------------------------
# flight recorder: the bounded postmortem ring
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_keeps_recent_and_marks_eviction(self, tmp_path):
        fr = FlightRecorder(capacity=4)
        for i in range(9):
            fr.span(f"s{i}", float(i), 0.5)
        assert len(fr) == 4 and fr.evicted == 5
        doc = fr.dump(tmp_path / "f.json")
        names = [
            e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"
        ]
        assert names == ["s5", "s6", "s7", "s8"]  # the RECENT past
        assert any(
            "5 older entries evicted" in e["name"]
            for e in doc["traceEvents"] if e.get("ph") == "I"
        )
        # the file round-trips as the same valid JSON
        assert json.loads((tmp_path / "f.json").read_text()) == doc

    def test_counter_records_deltas(self):
        fr = FlightRecorder()
        fr.counter("tok_total", 10)
        fr.counter("tok_total", 25)
        fr.counter("tok_total", 25)
        evs = [
            e for e in fr.snapshot()["traceEvents"]
            if e.get("ph") == "C"
        ]
        assert [e["args"]["delta"] for e in evs] == [10, 15, 0]
        assert [e["args"]["tok_total"] for e in evs] == [10, 25, 25]

    def test_one_pid_per_src(self):
        fr = FlightRecorder()
        fr.span("a", 0.0, 1.0, src="coordinator")
        fr.span("b", 0.0, 1.0, src="worker 0")
        fr.span("c", 0.5, 1.0, src="worker 1")
        doc = fr.snapshot()
        procs = {
            e["args"]["name"]: e["pid"]
            for e in doc["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"
        }
        assert set(procs) == {"coordinator", "worker 0", "worker 1"}
        assert len(set(procs.values())) == 3

    def test_trip_dumps_to_armed_path(self, tmp_path):
        path = tmp_path / "trip.json"
        fr = FlightRecorder().arm(str(path))
        fr.span("work", 0.0, 1.0)
        fr.trip("pool wait past deadline")
        assert path.exists() and fr.dumps == [str(path)]
        doc = json.loads(path.read_text())
        assert any(
            "pool wait past deadline" in e["name"]
            for e in doc["traceEvents"] if e.get("ph") == "I"
        )

    def test_watchdog_fires_once_per_stall_episode(self, tmp_path):
        fr = FlightRecorder()
        stamp = [time.perf_counter()]
        wd = fr.watchdog(
            "probe", lambda: stamp[0], stall_s=0.1,
            path=str(tmp_path / "wd.json"),
        )
        try:
            deadline = time.perf_counter() + 5.0
            while wd.fired == 0 and time.perf_counter() < deadline:
                time.sleep(0.02)
            assert wd.fired == 1
            time.sleep(0.3)           # still stalled: must NOT re-fire
            assert wd.fired == 1
            # activity resumes; wait until a poll OBSERVED it (re-arm
            # is the poll thread's doing, so loop instead of sleeping
            # a fixed margin a loaded box could miss)
            deadline = time.perf_counter() + 5.0
            while not wd._armed and time.perf_counter() < deadline:
                stamp[0] = time.perf_counter()
                time.sleep(0.02)
            assert wd._armed
            stamp[0] -= 10.0                 # ...then stalls again
            deadline = time.perf_counter() + 5.0
            while wd.fired == 1 and time.perf_counter() < deadline:
                stamp[0] = time.perf_counter() - 10.0  # stay stalled
                time.sleep(0.02)
            assert wd.fired == 2
        finally:
            fr.close()
        assert (tmp_path / "wd.json").exists()

    def test_pool_deadline_expiry_trips_flight(self, tmp_path):
        """asyncmap with flight= attached: a wait past the deadline
        dumps the ring BEFORE DeadWorkerError propagates — the hang
        artifact exists even though nothing after the raise runs."""
        path = tmp_path / "deadline.json"
        fr = FlightRecorder().arm(str(path))
        backend = LocalBackend(
            echo_work, 2, delay_fn=faults.per_worker([0.5, 0.5])
        )
        try:
            pool = AsyncPool(2)
            with pytest.raises(DeadWorkerError):
                asyncmap(pool, np.ones(2), backend, nwait=2,
                         timeout=0.05, flight=fr)
            assert path.exists()
            doc = json.loads(path.read_text())
            assert any(
                "wait past deadline" in e["name"]
                for e in doc["traceEvents"] if e.get("ph") == "I"
            )
            # the pool stays usable: drain the tardy workers
            waitall(pool, backend, flight=fr)
        finally:
            backend.shutdown()
        names = [
            e["name"] for e in fr.snapshot()["traceEvents"]
            if e.get("ph") == "X"
        ]
        assert any(n.startswith("asyncmap") for n in names)
        assert any(n.startswith("waitall") for n in names)


# ---------------------------------------------------------------------------
# cross-process aggregation (unit level)
# ---------------------------------------------------------------------------


class TestAggregation:
    def test_counter_deltas_across_respawns(self):
        """Counters stay monotonic across worker restarts: same-boot
        frames add deltas, a new boot's full value adds on top (never
        double-counted, never reset)."""
        reg = MetricsRegistry()
        agg = TelemetryAggregator(reg)
        w = WorkerTelemetry(3)
        w.registry.counter("worker_tasks_total").inc(3)
        agg.merge(3, w.snapshot())
        w.registry.counter("worker_tasks_total").inc(2)
        agg.merge(3, w.snapshot())      # cumulative 5 -> delta 2
        merged = reg.counter("worker_tasks_total", worker="3")
        assert merged.value == 5
        w2 = WorkerTelemetry(3)         # the respawn: fresh boot id
        assert w2.boot != w.boot
        w2.registry.counter("worker_tasks_total").inc(4)
        agg.merge(3, w2.snapshot())
        assert merged.value == 9        # 5 + 4, not 4, not 5
        # replayed cumulative value adds nothing
        agg.merge(3, w2.snapshot())
        assert merged.value == 9

    def test_histogram_merges_bucketwise_without_double_count(self):
        reg = MetricsRegistry()
        agg = TelemetryAggregator(reg)
        w = WorkerTelemetry(0)
        for v in (1e-4, 2e-3, 0.3):
            w.registry.histogram("worker_task_seconds").observe(v)
        agg.merge(0, w.snapshot())
        agg.merge(0, w.snapshot())      # same cumulative state: no-op
        h = reg.histogram("worker_task_seconds", worker="0")
        assert h.count == 3
        assert h.sum == pytest.approx(0.3021)
        w.registry.histogram("worker_task_seconds").observe(0.5)
        agg.merge(0, w.snapshot())
        assert h.count == 4

    def test_clock_offset_translates_worker_spans(self):
        """A worker whose clock runs 5 s ahead: the min-delay offset
        estimate recovers the skew and its spans land on the
        coordinator axis in the merged recorder."""
        reg = MetricsRegistry()
        agg = TelemetryAggregator(reg)
        skew = 5.0
        w = WorkerTelemetry(1)
        # coordinator dispatches at t=10 (its clock)
        agg.note_dispatch(1, seq=7, t=10.0)
        # worker: receives at 15.001, computes, sends at 15.021
        w.span("task e1", 15.002, 0.018)
        frame = w.snapshot(pair=(7, 10.001 + skew, 10.021 + skew))
        # coordinator receives at 10.022
        agg.merge(1, frame, t_recv_c=10.022)
        off = agg.clock_offset(1)
        assert off == pytest.approx(skew, abs=2e-3)
        (rec,) = agg.recorders()
        assert rec.process == "worker 1"
        (span,) = rec.spans
        _, name, t0, dur, _ = span
        assert name == "task e1"
        assert t0 == pytest.approx(10.002, abs=5e-3)  # coord axis
        assert dur == pytest.approx(0.018)
        # a respawn kills the offset with the incarnation, even when
        # the new boot's FIRST frame carries no pair sample (e.g. a
        # drain frame): reusing the dead clock's offset would scatter
        # the new process's spans far off-axis (review finding)
        w2 = WorkerTelemetry(1)
        agg.merge(1, w2.snapshot())
        assert agg.clock_offset(1) is None

    def test_malformed_frames_are_dropped(self):
        agg = TelemetryAggregator(MetricsRegistry())
        agg.merge(0, {"v": 999})        # wrong version
        agg.merge(0, "not a dict")
        agg.merge(0, {"v": 1, "boot": "b", "spans": [("bad",)]})
        assert agg.frames_merged == 1   # only the version-1 frame
        assert agg.recorders() == []


# ---------------------------------------------------------------------------
# the live telemetry plane: HTTP round-trips against real processes
# ---------------------------------------------------------------------------


class TestLiveTelemetryPlane:
    def test_live_scrape_roundtrip(self, tiny_serving, tmp_path):
        """The acceptance run, all on CPU: an ObsServer on port 0 over
        an instrumented ServingScheduler + a straggling ProcessBackend
        pool. /metrics mid-run carries worker-labeled series that
        ORIGINATED in the worker processes (cross-process aggregation);
        /healthz flips 503 when a worker process is killed and recovers
        after respawn; /trace and the watchdog-triggered /flight dump
        load as valid Chrome/Perfetto JSON with one pid per worker
        process."""
        cfg, params = tiny_serving
        reg = MetricsRegistry()
        rec = SpanRecorder("serving")
        fl = FlightRecorder()
        srv = ObsServer(reg, flight=fl).start()
        backend = ProcessBackend(
            echo_work, 3,
            delay_fn=PerWorkerDelay([0.001, 0.001, 0.05]),
            registry=reg, flight=fl, exporter=srv,
        )
        sched = _sched(cfg, params, registry=reg, spans=rec,
                       flight=fl, exporter=srv)
        try:
            assert srv.port != 0  # port 0 bind resolved
            # -- instrumented scheduler serves while the pool loops
            r = sched.submit(
                np.arange(1, 6, dtype=np.int32), max_new=6
            )
            sched.run()
            assert r.finished
            pool = AsyncPool(3)
            for _ in range(4):
                asyncmap(pool, np.ones(4), backend, nwait=2,
                         flight=fl)
            # -- /metrics MID-RUN: the straggler is still grinding its
            # last dispatch, yet the fast workers' frames are merged
            status, body = _get(srv.url + "/metrics")
            assert status == 200
            prom = body.decode()
            by_worker = {
                m.group(1): float(m.group(2))
                for m in re.finditer(
                    r'worker_tasks_total\{worker="(\d)"\} '
                    r'([0-9.]+)', prom
                )
            }
            assert set(by_worker) >= {"0", "1"}  # originated in-process
            assert all(v >= 1 for v in by_worker.values())
            assert "serving_ticks_total" in prom  # coordinator series
            waitall(pool, backend, flight=fl)
            status, body = _get(srv.url + "/metrics")
            by_worker = {
                m.group(1): float(m.group(2))
                for m in re.finditer(
                    r'worker_tasks_total\{worker="(\d)"\} '
                    r'([0-9.]+)', body.decode()
                )
            }
            assert set(by_worker) == {"0", "1", "2"}
            # /metrics.json mirrors the same families
            status, body = _get(srv.url + "/metrics.json")
            assert status == 200
            snap = json.loads(body)
            assert "worker_tasks_total" in snap

            # -- /healthz: healthy -> kill -> 503 -> respawn -> healthy
            status, body = _get(srv.url + "/healthz")
            assert status == 200 and json.loads(body)["ok"]
            backend._procs[1].terminate()
            deadline = time.perf_counter() + 30.0
            while (
                1 not in backend.dead_workers()
                and time.perf_counter() < deadline
            ):
                time.sleep(0.02)
            # assert the waited-for condition itself: a timed-out wait
            # falling through to the healthz assert would fail with a
            # misleading message on a loaded box
            assert 1 in backend.dead_workers(), (
                "worker 1 death not detected within 30s"
            )
            status, body = _get(srv.url + "/healthz")
            doc = json.loads(body)
            assert status == 503 and not doc["ok"]
            assert "1" in doc["checks"]["pool"]["detail"]
            assert doc["checks"]["pool"]["age_s"] >= 0
            backend.respawn(1)
            status, body = _get(srv.url + "/healthz")
            assert status == 200 and json.loads(body)["ok"]
            # the respawned rank computes again (fresh boot id merges
            # without double-counting — TestAggregation pins the math)
            asyncmap(pool, np.ones(4), backend, nwait=3)
            waitall(pool, backend)

            # -- /trace: valid Chrome JSON, one pid per worker process
            status, body = _get(srv.url + "/trace")
            assert status == 200
            trace = json.loads(body)
            procs = {
                e["args"]["name"]: e["pid"]
                for e in trace["traceEvents"]
                if e.get("ph") == "M" and e["name"] == "process_name"
            }
            workers = {n for n in procs if n.startswith("worker ")}
            assert workers == {"worker 0", "worker 1", "worker 2"}
            assert len({procs[n] for n in workers}) == 3  # one pid each
            assert "serving" in procs  # the scheduler's recorder too
            spans = [
                e for e in trace["traceEvents"] if e.get("ph") == "X"
            ]
            assert all(e["dur"] >= 0 for e in spans)
            assert any(
                e["name"].startswith("task e") for e in spans
            )  # spans recorded INSIDE worker processes

            # -- watchdog-triggered /flight dump: the scheduler goes
            # quiet; the liveness probe trips an automatic ring dump
            dump_path = tmp_path / "flight.json"
            wd = fl.watchdog(
                "scheduler", lambda: sched.last_tick_at,
                stall_s=0.15, path=str(dump_path),
            )
            deadline = time.perf_counter() + 30.0
            while (
                wd.fired == 0 and time.perf_counter() < deadline
            ):
                time.sleep(0.02)
            assert wd.fired >= 1, (
                "flight watchdog did not fire within 30s of the "
                "scheduler going quiet"
            )
            fdoc = json.loads(dump_path.read_text())
            fprocs = {
                e["args"]["name"]: e["pid"]
                for e in fdoc["traceEvents"]
                if e.get("ph") == "M" and e["name"] == "process_name"
            }
            fworkers = {
                n for n in fprocs if n.startswith("worker ")
            }
            assert len(fworkers) == 3  # one pid per worker process
            assert len({fprocs[n] for n in fworkers}) == 3
            assert any(
                "watchdog" in e["name"]
                for e in fdoc["traceEvents"] if e.get("ph") == "I"
            )
            # the live endpoint serves the same ring
            status, body = _get(srv.url + "/flight")
            assert status == 200
            assert json.loads(body)["traceEvents"]
        finally:
            fl.close()
            backend.shutdown()
            srv.close()

    def test_exporter_only_scheduler_stamps_tick_liveness(
        self, tiny_serving
    ):
        """A scheduler built with ONLY exporter= (no registry/spans/
        flight) must still stamp last_tick_at — its registered
        /healthz tick-freshness check reads it, and a never-set stamp
        would report an actively-ticking scheduler as stuck forever
        (review finding)."""
        cfg, params = tiny_serving
        srv = ObsServer().start()
        sched = _sched(cfg, params, exporter=srv)
        try:
            sched.submit(np.arange(1, 5, dtype=np.int32), max_new=4)
            sched.step()
            assert sched.last_tick_at is not None
            status, body = _get(srv.url + "/healthz")
            assert status == 200, body
            sched.run()
            status, _ = _get(srv.url + "/healthz")
            assert status == 200
            # same mechanism through the PUBLIC registration API: a
            # dark scheduler registered later must start stamping too
            dark = _sched(cfg, params)
            assert not dark._stamp_ticks
            srv.register_scheduler(dark, name="late")
            dark.submit(np.arange(1, 4, dtype=np.int32), max_new=3)
            dark.step()
            assert dark.last_tick_at is not None
            status, _ = _get(srv.url + "/healthz")
            assert status == 200
        finally:
            srv.close()

    def test_hedge_health_and_unknown_routes(self):
        reg = MetricsRegistry()
        srv = ObsServer(reg).start()
        backend = LocalBackend(echo_work, 2)
        hedge = HedgedServer(backend, registry=reg, exporter=srv)
        try:
            status, body = _get(srv.url + "/healthz")
            assert status == 200
            hedge._dead.add(1)  # bench a replica
            status, body = _get(srv.url + "/healthz")
            doc = json.loads(body)
            assert status == 503
            assert "1" in doc["checks"]["hedge"]["detail"]
            hedge.reset_dead(1)
            status, _ = _get(srv.url + "/healthz")
            assert status == 200
            status, _ = _get(srv.url + "/nope")
            assert status == 404
            status, body = _get(srv.url + "/")
            assert "/metrics" in json.loads(body)["endpoints"]
        finally:
            backend.shutdown()
            srv.close()

    def test_server_without_registry_404s_metrics(self):
        srv = ObsServer().start()
        try:
            status, _ = _get(srv.url + "/metrics")
            assert status == 404
            status, _ = _get(srv.url + "/flight")
            assert status == 404
            # /trace works with zero sources: an empty valid trace
            status, body = _get(srv.url + "/trace")
            assert status == 200
            assert json.loads(body)["traceEvents"] == []
        finally:
            srv.close()
