"""Causal tracing + conservation audit (ISSUE 17): the TraceBook
follows one request door to door across every serving plane, and the
audit turns the prose conservation claims into executable invariants.

Four layers: (1) TraceBook unit semantics (dense mint order, retry
lineage both ways, waterfall arithmetic, find_last for re-routed
TTFT); (2) event coverage — a plain day, a hedge race, a two-tier
migration, a partition + re-route, a retry storm, and a DRR-paced QoS
day each stamp their documented taxonomy and pass the audit; (3) the
ISSUE acceptance: seeded storm_with_host_kill traced end to end —
audit zero discrepancies across two replays, books byte-identical,
and the traced digest equal to the dark one (tracing is
digest-neutral); (4) the audit names its failures — a deliberately
broken book yields the offending invariant AND trace ids, and
unarmed invariants are listed as skipped with reasons, so "passed" is
never confused with "not checked"."""

import json
import urllib.request

import pytest

from mpistragglers_jl_tpu.chaos import ChaosInjector, get_scenario
from mpistragglers_jl_tpu.models.router import RequestRouter
from mpistragglers_jl_tpu.obs import (
    TERMINAL_KINDS,
    AuditResult,
    ObsServer,
    TraceBook,
    audit,
)
from mpistragglers_jl_tpu.qos import TenantContract, TenantRegistry
from mpistragglers_jl_tpu.sim import (
    ReplicaPartition,
    RetryPolicy,
    VirtualClock,
)
from mpistragglers_jl_tpu.sim.workload import (
    SimPrompt,
    SimReplica,
    poisson_arrivals,
    run_router_day,
)


def _day(trace=None, *, n=120, rate=30.0, policy="least_loaded",
         n_rep=3, qos=None, retry=None, events=(), tenants=None,
         seed=3):
    """One seeded router day on virtual time; returns (report, book,
    router). trace=None runs dark over the identical stream."""
    clock = VirtualClock()
    reps = [
        SimReplica(clock, slots=4, n_inner=8, tick_s=0.02,
                   qos=qos,
                   tier=("prefill" if policy == "two_tier" and i < 1
                         else "decode"),
                   chunk_s=0.005)
        for i in range(n_rep)
    ]
    router = RequestRouter(reps, policy=policy, clock=clock,
                           qos=qos, trace=trace)
    rep = run_router_day(
        router,
        poisson_arrivals(rate, n=n, seed=seed, prompt_len=64,
                         max_new=8, tenants=tenants),
        retry=retry, events=list(events),
    )
    return rep, trace, router


def _book_fingerprint(book):
    """The full observable ledger of a book, for byte-identity."""
    return (
        list(book.iter_events()),
        {t: book.parent(t) for t in book.ids()
         if book.parent(t) is not None},
    )


# --------------------------------------------------------------------------
# TraceBook unit semantics
# --------------------------------------------------------------------------


class TestTraceBook:
    def test_mint_is_dense_and_ordered(self):
        book = TraceBook()
        assert [book.mint() for _ in range(5)] == list(range(5))
        assert len(book) == 5
        assert 4 in book and 5 not in book

    def test_lineage_links_both_ways(self):
        book = TraceBook()
        a = book.mint()
        b = book.mint(parent=a)
        c = book.mint()
        book.link(c, a)
        book.link(c, a)  # idempotent
        assert book.parent(b) == book.parent(c) == a
        assert book.children(a) == [b, c]
        assert book.parent(a) is None

    def test_waterfall_arithmetic(self):
        book = TraceBook()
        t = book.mint()
        book.event(t, "submitted", 10.0, tenant="chat")
        book.event(t, "admitted", 10.5)
        book.event(t, "first_token", 11.0)
        book.event(t, "retired", 12.0, outcome="done", tokens=8)
        wf = book.waterfall(t)
        assert wf["t0"] == 10.0
        assert wf["ttft"] == 1.0 and wf["latency"] == 2.0
        assert wf["outcome"] == "retired"
        assert [e["dt"] for e in wf["events"]] == [0.0, 0.5, 1.0, 2.0]
        assert wf["events"][0]["attrs"] == {"tenant": "chat"}

    def test_waterfall_ttft_uses_last_first_token(self):
        """A re-route restarts the stream; the scheduler's TTFT stamp
        restarts with it, and the waterfall must agree."""
        book = TraceBook()
        t = book.mint()
        book.event(t, "submitted", 0.0)
        book.event(t, "first_token", 1.0)
        book.event(t, "evacuated", 1.5, replica=0)
        book.event(t, "rerouted", 1.5, replica=1)
        book.event(t, "first_token", 3.0)
        book.event(t, "retired", 4.0)
        assert book.waterfall(t)["ttft"] == 3.0
        assert book.find(t, "first_token")[1] == 1.0
        assert book.find_last(t, "first_token")[1] == 3.0

    def test_terminal_and_cohorts(self):
        book = TraceBook()
        plain = book.mint()
        for k, t in (("submitted", 0.0), ("retired", 1.0)):
            book.event(plain, k, t)
        shed = book.mint()
        book.event(shed, "submitted", 0.0)
        book.event(shed, "shed", 0.0, reason="overload")
        hedged = book.mint()
        for k in ("submitted", "hedge_fired", "hedge_won", "retired"):
            book.event(hedged, k, 0.0)
        open_ = book.mint()
        book.event(open_, "submitted", 0.0)
        assert book.terminal(plain)[0] == "retired"
        assert book.terminal(shed)[0] == "shed"
        assert book.terminal(open_) is None
        assert book.cohort(plain) == "served"
        assert book.cohort(shed) == "shed"
        assert book.cohort(hedged) == "hedged"
        assert book.cohort(open_) == "open"
        assert TERMINAL_KINDS == ("retired", "shed", "cancelled")

    def test_unknown_trace_refused(self):
        with pytest.raises(KeyError, match="unknown trace id"):
            TraceBook().waterfall(0)


# --------------------------------------------------------------------------
# event coverage: every plane stamps its documented taxonomy
# --------------------------------------------------------------------------


class TestEventCoverage:
    def test_plain_day_lifecycle_and_neutral_digest(self):
        dark, _, _ = _day(None)
        rep, book, _ = _day(TraceBook())
        # tracing never perturbs the day
        assert rep.digest() == dark.digest()
        assert len(book) == rep.n
        for r in rep.requests:
            assert r.trace is not None
            kinds = book.kinds(r.trace)
            assert kinds[0] == "submitted"
            assert "first_token" in kinds and kinds[-1] == "retired"
            # timestamps are monotone within a trace
            ts = [t for _, t, _ in book.events(r.trace)]
            assert ts == sorted(ts)
        res = audit(book, rep)
        assert res.ok, res.failures

    def test_replay_books_are_byte_identical(self):
        _, b1, _ = _day(TraceBook())
        _, b2, _ = _day(TraceBook())
        assert _book_fingerprint(b1) == _book_fingerprint(b2)

    def test_hedge_race_events(self):
        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=2, n_inner=8, prompt_chunk=64,
                       tick_s=lambda t, m=(1.0, 6.0)[i]: 0.01 * m)
            for i in range(2)
        ]
        book = TraceBook()
        router = RequestRouter(reps, policy="hedge_p99",
                               ttft_slo=0.03, clock=clock, trace=book)
        rrs = [router.submit(SimPrompt(64), 16) for _ in range(6)]
        while router.in_flight:
            clock.run_until(router.next_event_at())
            router.step()
        assert router.n_hedges > 0
        armed = [t for t in book.ids()
                 if book.find(t, "hedge_armed") is not None]
        fired = [t for t in book.ids()
                 if book.find(t, "hedge_fired") is not None]
        assert armed and fired
        for t in fired:
            kinds = book.kinds(t)
            # every fired leg resolves: won, cancelled, or abandoned
            assert (kinds.count("hedge_fired")
                    == kinds.count("hedge_won")
                    + kinds.count("hedge_cancelled")
                    + kinds.count("hedge_abandoned"))
            assert book.cohort(t) == "hedged"
        assert all(rr.trace is not None for rr in rrs)
        res = audit(book)
        assert res.ok and "hedge_legs" in res.checked

    def test_two_tier_migration_events(self):
        rep, book, _ = _day(TraceBook(), policy="two_tier")
        migrated = [t for t in book.ids()
                    if book.cohort(t) == "migrated"]
        assert migrated  # the prefill tier handed streams over
        for t in migrated:
            kinds = book.kinds(t)
            assert kinds.count("migrate_out") == kinds.count("adopt")
            out = book.find(t, "migrate_out")
            assert out[2]["nbytes"] > 0
        res = audit(book, rep)
        assert res.ok and "migration_pairing" in res.checked
        assert res.counts["migrate_out"] == res.counts["adopts"] > 0

    def test_partition_abandon_and_reroute_events(self):
        rep, book, _ = _day(
            TraceBook(), n_rep=4, rate=60.0, n=240,
            events=[ReplicaPartition(1.0, (2, 3), 3.0)],
        )
        abandoned = [t for t in book.ids()
                     if book.find(t, "partition_abandoned")]
        assert abandoned  # legs were caught behind the partition
        for t in abandoned:
            assert book.find(t, "rerouted") is not None
            assert book.terminal(t)[0] == "retired"  # zero drops
            assert book.cohort(t) == "rescued"
        assert audit(book, rep).ok

    def test_retry_resubmit_child_lineage(self):
        retry = RetryPolicy(timeout_s=0.05, max_retries=2,
                            backoff=1.5, jitter_s=0.02, seed=9)
        rep, book, _ = _day(TraceBook(), rate=90.0, n=260, n_rep=2,
                            retry=retry)
        assert rep.n_resubmits > 0
        children = [t for t in book.ids()
                    if book.find(t, "retry_resubmit") is not None]
        assert len(children) == rep.n_resubmits
        for c in children:
            ev = book.find(c, "retry_resubmit")
            parent = ev[2]["parent"]
            assert book.parent(c) == parent
            assert c in book.children(parent)
            assert ev[2]["attempt"] >= 1
        assert audit(book, rep).ok

    def test_qos_day_stamps_drr_and_shed(self):
        reg = TenantRegistry([
            TenantContract("chat", cls="latency", weight=4.0,
                           ttft_slo=0.5),
            TenantContract("bulk", cls="batch", weight=1.0),
        ])
        rep, book, router = _day(
            TraceBook(), qos=reg, rate=80.0, n=240, n_rep=2,
            tenants={"chat": 0.5, "bulk": 0.5},
        )
        queued = [t for t in book.ids()
                  if book.find(t, "drr_queued") is not None]
        assert queued  # the deficit rotation actually paced the day
        for t in queued:
            q = book.find(t, "drr_queued")
            p = book.find(t, "drr_picked")
            assert p is not None and p[1] >= q[1]
            assert q[2]["tenant"] in ("chat", "bulk")
        assert audit(book, rep).ok


# --------------------------------------------------------------------------
# the ISSUE acceptance: traced chaos day, conserved and digest-neutral
# --------------------------------------------------------------------------


class TestChaosAcceptance:
    def test_storm_traced_conserved_and_digest_neutral(self):
        """storm_with_host_kill with tracing armed: the audit finds
        zero discrepancies across two replays, the two books are
        byte-identical, and the traced digest equals the dark one."""
        dark = ChaosInjector().run(
            get_scenario("storm_with_host_kill", seed=5, n=1800)
        )
        books, reports = [], []
        for _ in range(2):
            book = TraceBook("storm")
            r = ChaosInjector(trace=book).run(
                get_scenario("storm_with_host_kill", seed=5, n=1800)
            )
            # the injector armed the audit inside the run and it held
            assert "trace_conservation" in r.invariants
            books.append(book)
            reports.append(r)
        assert reports[0].digest() == reports[1].digest() \
            == dark.digest()
        assert _book_fingerprint(books[0]) == \
            _book_fingerprint(books[1])
        view = books[0].audit_view()
        assert view["open"] == 0 and view["traces"] > 1800
        assert view["shed"] > 0  # the storm really shed


# --------------------------------------------------------------------------
# audit: failures are NAMED, skips are reasoned
# --------------------------------------------------------------------------


class TestAuditNaming:
    def test_double_terminal_named_with_trace_ids(self):
        book = TraceBook()
        t = book.mint()
        book.event(t, "submitted", 0.0)
        book.event(t, "retired", 1.0)
        book.event(t, "retired", 2.0)  # the double-retire bug
        res = audit(book)
        assert not res.ok
        (f,) = res.failures
        assert f.invariant == "terminal_exactly_once"
        assert f.trace_ids == [t]
        assert "double-retire" in f.detail
        d = f.to_dict()
        assert d["invariant"] == "terminal_exactly_once"

    def test_unmatched_migration_and_hedge_named(self):
        book = TraceBook()
        m = book.mint()
        for k in ("submitted", "migrate_out", "retired"):
            book.event(m, k, 0.0)  # migrate_out with no adopt
        h = book.mint()
        for k in ("submitted", "hedge_fired", "retired"):
            book.event(h, k, 0.0)  # fired leg never resolved
        res = audit(book)
        by_inv = {f.invariant: f for f in res.failures}
        assert by_inv["migration_pairing"].trace_ids == [m]
        assert by_inv["hedge_legs"].trace_ids == [h]

    def test_open_trace_orphans_only_at_end_of_day(self):
        book = TraceBook()
        t = book.mint()
        book.event(t, "submitted", 0.0)
        # mid-day (no report): an open trace is not a violation
        assert audit(book).ok

        class _Rep:  # minimal end-of-day report: no requests traced
            requests = ()
            n = 0
            outcomes = {}
            dropped = 0

        res = audit(book, _Rep())
        assert not res.ok
        assert res.failures[0].invariant == "terminal_exactly_once"
        assert "never resolved" in res.failures[0].detail
        assert res.failures[0].trace_ids == [t]

    def test_skips_are_reasoned_not_silent(self):
        res = audit(TraceBook())
        assert isinstance(res, AuditResult) and res.ok
        assert res.skipped["report_reconciliation"] == \
            "no report passed"
        assert res.skipped["pool_drain"] == "no pool passed"
        assert "token_conservation_counter" in res.skipped
        # checked and skipped never overlap
        assert not set(res.checked) & set(res.skipped)
        d = res.to_dict()
        assert d["ok"] and d["skipped"] == res.skipped

    def test_token_counter_cross_check(self):
        from mpistragglers_jl_tpu.obs import MetricsRegistry

        book = TraceBook()
        t = book.mint()
        book.event(t, "submitted", 0.0)
        book.event(t, "retired", 1.0, tokens=8)
        reg = MetricsRegistry()
        reg.counter("serving_tokens_total").inc(8)
        res = audit(book, None, reg)
        assert res.ok
        assert "token_conservation_counter" in res.checked
        reg.counter("serving_tokens_total").inc(1)  # drift
        res = audit(book, None, reg)
        assert any(
            f.invariant == "token_conservation_counter"
            for f in res.failures
        )


# --------------------------------------------------------------------------
# surfacing: the waterfall over real HTTP reproduces the timings
# --------------------------------------------------------------------------


class TestHTTPSurfacing:
    def test_trace_endpoint_reproduces_timings_exactly(self):
        rep, book, _ = _day(TraceBook(), n=40)
        tid = next(iter(book.ids()))
        wf = book.waterfall(tid)
        with ObsServer() as srv:
            srv.add_tracebook(book)
            http_wf = json.loads(urllib.request.urlopen(
                f"{srv.url}/trace/{tid}").read())
            assert http_wf == wf  # the whole body, timestamps included
            assert http_wf["ttft"] == wf["ttft"]
            assert http_wf["latency"] == wf["latency"]
            adoc = json.loads(urllib.request.urlopen(
                srv.url + "/audit").read())
            assert adoc["ok"] and adoc["books"][0]["book"] == book.name
            # unknown and malformed ids are named refusals
            for bad, code in (("/trace/999999", 404),
                              ("/trace/xyz", 400)):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(srv.url + bad)
                assert ei.value.code == code

    def test_audit_endpoint_503_on_violation(self):
        book = TraceBook()
        t = book.mint()
        book.event(t, "submitted", 0.0)
        book.event(t, "retired", 1.0)
        book.event(t, "retired", 2.0)
        with ObsServer() as srv:
            srv.add_tracebook(book)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + "/audit")
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert not body["ok"]
            assert body["books"][0]["failures"][0]["invariant"] == \
                "terminal_exactly_once"

    def test_books_merge_into_perfetto_doc(self):
        _, book, router = _day(TraceBook(), n=30)
        srv = ObsServer()
        try:
            srv.register_router(router)  # auto-adds the attached book
            doc = srv.trace_doc()
            names = {
                e["args"]["name"] for e in doc["traceEvents"]
                if e.get("ph") == "M"
                and e["name"] == "process_name"
            }
            assert book.name in names
        finally:
            srv.close()
