"""Pipeline parallelism: ppermute microbatch pipeline vs sequential oracle.

The generic engine (pipeline_spmd) is checked against plain sequential
layer application; the transformer integration is checked against the
dense oracle for loss AND gradients — the gradient check is the one
that matters, since the backward pipeline comes from autodiff through
scan + ppermute and any schedule bug shows up there first.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig,
    forward_dense,
    init_params,
)
from mpistragglers_jl_tpu.parallel import make_mesh
from mpistragglers_jl_tpu.parallel.pipeline import (
    _pipeline_loss_local,
    make_pipeline_train_step,
    pipeline_param_specs,
    pipeline_spmd,
    shard_params_pipeline,
    stack_layers,
)

CFG = TransformerConfig(
    vocab=61, d_model=32, n_heads=4, n_layers=4, d_ff=64
)


def _affine_stage(stacked, x):
    def one(h, lp):
        return jnp.tanh(h @ lp["w"] + lp["b"]), None

    x, _ = jax.lax.scan(one, x, stacked)
    return x


@pytest.mark.parametrize("pp,n_micro", [(2, 2), (4, 2), (4, 4), (8, 2)])
def test_pipeline_spmd_matches_sequential(pp, n_micro):
    rng = np.random.default_rng(0)
    n_layers, B, D = 8, 8, 6
    layers = [
        {
            "w": jnp.asarray(rng.standard_normal((D, D)) / np.sqrt(D),
                             jnp.float32),
            "b": jnp.asarray(rng.standard_normal(D) * 0.1, jnp.float32),
        }
        for _ in range(n_layers)
    ]
    x = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)
    want = x
    for lp in layers:
        want = jnp.tanh(want @ lp["w"] + lp["b"])

    mesh = make_mesh((pp,), ("pp",))
    f = jax.jit(
        jax.shard_map(
            partial(pipeline_spmd, _affine_stage, axis="pp",
                    n_microbatch=n_micro),
            mesh=mesh,
            in_specs=({"w": P("pp"), "b": P("pp")}, P()),
            out_specs=P(),
        )
    )
    got = f(stack_layers(layers), x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-6, rtol=1e-6
    )


def _data(cfg, B=8, L=16, seed=3):
    rng = np.random.default_rng(seed)
    d = jnp.asarray(rng.integers(0, cfg.vocab, (B, L + 1)), jnp.int32)
    return d[:, :-1], d[:, 1:]


def _dense_loss(params, toks, tgts, cfg):
    logits = forward_dense(params, toks, cfg).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, tgts[..., None], axis=-1).mean()


@pytest.mark.slow
@pytest.mark.parametrize("shape", [(2, 4), (1, 4), (4, 2)])
def test_pipeline_loss_and_grads_match_dense(shape):
    mesh = make_mesh(shape, ("dp", "pp"))
    params = init_params(CFG, seed=1)
    toks, tgts = _data(CFG)

    want_loss = _dense_loss(params, toks, tgts, CFG)
    g_want = jax.grad(_dense_loss)(params, toks, tgts, CFG)
    g_want["layers"] = stack_layers(g_want["layers"])

    loss_fn = jax.jit(
        jax.shard_map(
            partial(_pipeline_loss_local, cfg=CFG, n_microbatch=2),
            mesh=mesh,
            in_specs=(pipeline_param_specs(CFG), P("dp"), P("dp")),
            out_specs=P(),
        )
    )
    sp = shard_params_pipeline(params, CFG, mesh)
    place = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    got_loss, g_got = jax.value_and_grad(loss_fn)(
        sp, place(toks), place(tgts)
    )
    np.testing.assert_allclose(
        float(got_loss), float(want_loss), atol=1e-5, rtol=1e-5
    )
    flat_w, _ = jax.tree.flatten(g_want)
    flat_g, _ = jax.tree.flatten(g_got)
    for a, b in zip(flat_g, flat_w):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3
        )


@pytest.mark.slow
def test_pipeline_train_step_reduces_loss_and_stays_sharded():
    mesh = make_mesh((2, 4), ("dp", "pp"))
    params = shard_params_pipeline(init_params(CFG, seed=2), CFG, mesh)
    step = make_pipeline_train_step(CFG, mesh, n_microbatch=2, lr=0.1)
    toks, tgts = _data(CFG, seed=5)
    place = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    toks, tgts = place(toks), place(tgts)
    losses = []
    for _ in range(10):
        params, loss = step(params, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.1, losses
    # the stacked layer params stay pp-sharded through the update
    assert "pp" in tuple(params["layers"]["wq"].sharding.spec)


def test_pipeline_validates_divisibility():
    mesh = make_mesh((1, 4), ("dp", "pp"))
    bad = TransformerConfig(**{**CFG.__dict__, "n_layers": 3})
    with pytest.raises(ValueError, match="divisible"):
        make_pipeline_train_step(bad, mesh, n_microbatch=2)
    with pytest.raises(ValueError, match="microbatch"):
        # B=6 local batch not divisible by 4 microbatches
        f = jax.shard_map(
            partial(pipeline_spmd, _affine_stage, axis="pp",
                    n_microbatch=4),
            mesh=make_mesh((4,), ("pp",)),
            in_specs=({"w": P("pp"), "b": P("pp")}, P()),
            out_specs=P(),
        )
        rng = np.random.default_rng(0)
        layers = stack_layers(
            [
                {"w": jnp.eye(4, dtype=jnp.float32),
                 "b": jnp.zeros(4, jnp.float32)}
                for _ in range(4)
            ]
        )
        f(layers, jnp.zeros((6, 4), jnp.float32))


# ------------------------------------------------------------------ 1F1B


def _grads_1f1b(cfg, mesh, params, toks, tgts, n_microbatch):
    from mpistragglers_jl_tpu.parallel.pipeline import _1f1b_loss_grads_local

    grad_fn = jax.jit(
        jax.shard_map(
            partial(_1f1b_loss_grads_local, cfg=cfg,
                    n_microbatch=n_microbatch),
            mesh=mesh,
            in_specs=(pipeline_param_specs(cfg), P("dp"), P("dp")),
            out_specs=(P(), pipeline_param_specs(cfg)),
        )
    )
    sp = shard_params_pipeline(params, cfg, mesh)
    place = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    return grad_fn(sp, place(toks), place(tgts))


@pytest.mark.slow
@pytest.mark.parametrize("shape", [(2, 4), (1, 4), (4, 2)])
def test_1f1b_loss_and_grads_match_dense(shape):
    """The interleaved fwd/bwd schedule computes the same loss AND the
    same gradients as the dense oracle — the hand-written backward
    wavefront (ring residuals, vjp recompute, grad ppermutes) is exact,
    not approximate."""
    mesh = make_mesh(shape, ("dp", "pp"))
    params = init_params(CFG, seed=1)
    toks, tgts = _data(CFG)
    want_loss = _dense_loss(params, toks, tgts, CFG)
    g_want = jax.grad(_dense_loss)(params, toks, tgts, CFG)
    g_want["layers"] = stack_layers(g_want["layers"])

    got_loss, g_got = _grads_1f1b(CFG, mesh, params, toks, tgts, 2)
    np.testing.assert_allclose(
        float(got_loss), float(want_loss), atol=1e-5, rtol=1e-5
    )
    for a, b in zip(jax.tree.leaves(g_got), jax.tree.leaves(g_want)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3
        )


@pytest.mark.slow
@pytest.mark.parametrize("pp", [2, 4])
def test_1f1b_moe_pipeline_loss_decreases(pp):
    """MoE stages are pipeline-legal under 1F1B (VERDICT round 1 item 4:
    the dense-only guard is gone): expert layers run inside their stage,
    the Switch aux loss rides the payload to the head, and training
    makes progress at pp=2 and pp=4."""
    cfg = TransformerConfig(
        vocab=61, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        n_experts=4, moe_aux_coef=0.01,
    )
    mesh = make_mesh((8 // pp, pp), ("dp", "pp"))
    params = shard_params_pipeline(init_params(cfg, seed=3), cfg, mesh)
    step = make_pipeline_train_step(
        cfg, mesh, n_microbatch=2, lr=0.1, schedule="1f1b"
    )
    toks, tgts = _data(cfg, seed=7)
    place = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    toks, tgts = place(toks), place(tgts)
    losses = []
    for _ in range(10):
        params, loss = step(params, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.05, losses
    # expert tables stay pp-sharded on the stacked layer axis
    assert "pp" in tuple(params["layers"]["we1"].sharding.spec)


def test_gpipe_schedule_rejects_moe():
    """The fill/drain schedule stays dense-only, pointing at 1F1B."""
    cfg = TransformerConfig(
        vocab=61, d_model=32, n_heads=4, n_layers=4, d_ff=64, n_experts=4
    )
    mesh = make_mesh((2, 4), ("dp", "pp"))
    with pytest.raises(NotImplementedError, match="1f1b"):
        make_pipeline_train_step(
            cfg, mesh, n_microbatch=2, schedule="gpipe"
        )


def test_bubble_fraction_metric():
    from mpistragglers_jl_tpu.parallel.pipeline import bubble_fraction

    assert bubble_fraction(1, 4) == 0.0                    # no pipeline
    assert bubble_fraction(4, 4) == pytest.approx(6 / 10)  # 2(p-1)/(M+2(p-1))
    assert bubble_fraction(4, 4, "gpipe") == pytest.approx(3 / 7)
    assert bubble_fraction(4, 32) == pytest.approx(6 / 38)
    # more microbatches always shrink the bubble
    assert bubble_fraction(4, 64) < bubble_fraction(4, 8)
    with pytest.raises(ValueError):
        bubble_fraction(4, 4, "pipedream")


@pytest.mark.slow
def test_gpipe_schedule_train_step_reduces_loss():
    """The fill/drain schedule's full train step stays wired (the 1F1B
    default must not orphan it)."""
    mesh = make_mesh((2, 4), ("dp", "pp"))
    params = shard_params_pipeline(init_params(CFG, seed=4), CFG, mesh)
    step = make_pipeline_train_step(
        CFG, mesh, n_microbatch=2, lr=0.1, schedule="gpipe"
    )
    toks, tgts = _data(CFG, seed=9)
    place = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    toks, tgts = place(toks), place(tgts)
    losses = []
    for _ in range(8):
        params, loss = step(params, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.05, losses


# --------------------------------------------------------------- circular


def _undo_devmajor(a):
    """(pp, v, lpc, ...) device-major chunks back to (L, ...) layers."""
    ppx, vx, lpc = a.shape[0], a.shape[1], a.shape[2]
    return jnp.swapaxes(a, 0, 1).reshape(vx * ppx * lpc, *a.shape[3:])


@pytest.mark.parametrize("shape,v,n_micro", [
    ((2, 4), 2, 4),   # 2 chunks/device, one wave
    ((4, 2), 2, 2),
    ((2, 2), 4, 4),   # deep interleave, two waves
])
@pytest.mark.slow
def test_circular_loss_and_grads_match_dense(shape, v, n_micro):
    """The interleaved virtual-stage schedule (device-major chunks,
    payload-riding stage counters, seamless wave injection) computes the
    dense oracle's loss AND gradients exactly — including multi-wave
    runs where microbatches lap the ring while others are mid-flight."""
    from mpistragglers_jl_tpu.parallel.pipeline import (
        _circular_loss_local,
        pipeline_param_specs_circular,
    )

    cfg = TransformerConfig(
        vocab=37, d_model=32, n_heads=4, n_layers=8, d_ff=64
    )
    mesh = make_mesh(shape, ("dp", "pp"))
    params = init_params(cfg, seed=1)
    toks, tgts = _data(cfg)
    want_loss = _dense_loss(params, toks, tgts, cfg)
    g_want = jax.grad(_dense_loss)(params, toks, tgts, cfg)
    g_want["layers"] = stack_layers(g_want["layers"])

    sp = shard_params_pipeline(params, cfg, mesh, virtual_stages=v)
    loss_fn = jax.jit(
        jax.shard_map(
            partial(_circular_loss_local, cfg=cfg,
                    n_microbatch=n_micro, v=v),
            mesh=mesh,
            in_specs=(
                pipeline_param_specs_circular(cfg), P("dp"), P("dp")
            ),
            out_specs=P(),
        )
    )
    place = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    got_loss, g_got = jax.value_and_grad(loss_fn)(
        sp, place(toks), place(tgts)
    )
    np.testing.assert_allclose(
        float(got_loss), float(want_loss), atol=1e-5, rtol=1e-5
    )
    for k, b in g_want["layers"].items():
        a = _undo_devmajor(jnp.asarray(g_got["layers"][k]))
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3
        )
    for k in ("emb", "lnf_s", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_got[k]), np.asarray(g_want[k]),
            atol=1e-4, rtol=1e-3,
        )


@pytest.mark.slow
def test_circular_train_step_reduces_loss():
    cfg = TransformerConfig(
        vocab=61, d_model=32, n_heads=4, n_layers=8, d_ff=64
    )
    mesh = make_mesh((2, 4), ("dp", "pp"))
    params = shard_params_pipeline(
        init_params(cfg, seed=3), cfg, mesh, virtual_stages=2
    )
    step = make_pipeline_train_step(
        cfg, mesh, n_microbatch=4, lr=0.1, schedule="circular",
        virtual_stages=2,
    )
    toks, tgts = _data(cfg, seed=11)
    place = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    toks, tgts = place(toks), place(tgts)
    losses = []
    for _ in range(8):
        params, loss = step(params, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.05, losses


def test_circular_validation_and_bubble():
    from mpistragglers_jl_tpu.parallel.pipeline import bubble_fraction

    cfg = TransformerConfig(
        vocab=61, d_model=32, n_heads=4, n_layers=8, d_ff=64
    )
    mesh = make_mesh((2, 4), ("dp", "pp"))
    with pytest.raises(ValueError, match="v\\*pp"):
        make_pipeline_train_step(
            cfg, mesh, n_microbatch=4, schedule="circular",
            virtual_stages=3,  # 8 layers not divisible by 12 chunks
        )
    # the interleave divides the fill/drain bubble by v
    assert bubble_fraction(4, 8, "circular:2") == pytest.approx(3 / 19)
    assert bubble_fraction(4, 8, "circular:4") == pytest.approx(3 / 35)
    assert bubble_fraction(4, 8, "circular:2") < bubble_fraction(4, 8, "gpipe")
    moe = TransformerConfig(
        vocab=61, d_model=32, n_heads=4, n_layers=8, d_ff=64, n_experts=2
    )
    with pytest.raises(NotImplementedError, match="1f1b"):
        make_pipeline_train_step(
            moe, mesh, n_microbatch=4, schedule="circular"
        )


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.parametrize("pp,M", [(2, 4), (4, 8), (8, 8)])
def test_measured_bubble_matches_formula(schedule, pp, M):
    """The per-tick busy trace emitted by the EXECUTING schedules (scan
    ys, circular's from real carried ring state) integrates to exactly
    the analytic bubble_fraction for gpipe and 1F1B (VERDICT r3 weak #3:
    the formula was never validated by a measured trace)."""
    from mpistragglers_jl_tpu.parallel.pipeline import (
        bubble_fraction,
        measure_bubble,
    )

    mesh = make_mesh((pp,), ("pp",))
    r = measure_bubble(mesh, M, schedule)
    assert r["measured"] == pytest.approx(r["formula"], abs=1e-12)
    # structure, not just the mean: per-device busy counts are exact
    busy = r["busy"]
    if schedule == "gpipe":
        assert busy.shape == (pp, M + pp - 1)
        assert (busy.sum(axis=1) == M).all()  # M real microbatches each
    else:
        assert busy.shape == (pp, M + 2 * (pp - 1), 2)
        # M forward and M backward slots per device
        assert (busy[:, :, 0].sum(axis=1) == M).all()
        assert (busy[:, :, 1].sum(axis=1) == M).all()


@pytest.mark.parametrize("pp,M,v", [(2, 4, 2), (4, 8, 2), (4, 8, 4)])
def test_measured_bubble_circular_implementation_overhead(pp, M, v):
    """The circular engine's measured bubble exceeds the analytic
    formula by EXACTLY the one extra final-emission ring hop its
    implementation spends (T = vM + pp vs the ideal vM + pp - 1):
    measured = pp/(vM + pp). The trace makes that overhead a pinned
    number instead of an unvalidated claim."""
    from mpistragglers_jl_tpu.parallel.pipeline import (
        bubble_fraction,
        measure_bubble,
    )

    mesh = make_mesh((pp,), ("pp",))
    r = measure_bubble(mesh, M, "circular", v=v)
    T = v * M + pp
    assert r["ticks"] == T
    assert r["measured"] == pytest.approx(pp / T, abs=1e-12)
    formula = bubble_fraction(pp, M, f"circular:{v}")
    assert r["formula"] == pytest.approx(formula)
    assert r["measured"] > formula  # the documented implementation gap
    # every device still does exactly v*M real chunk applications
    assert (r["busy"].sum(axis=1) == v * M).all()


@pytest.mark.slow
@pytest.mark.parametrize("schedule", ["1f1b", "gpipe", "circular"])
def test_optax_pipeline_train_step_adamw(schedule):
    """AdamW over the pipeline schedules (VERDICT r3 missing #3): loss
    decreases, moments shard exactly like the stage params (pp-sharded,
    no replicated optimizer copies), and the 1F1B trajectory matches
    the gpipe trajectory (same grads, same optimizer)."""
    import optax

    from mpistragglers_jl_tpu.parallel.pipeline import (
        make_optax_pipeline_train_step,
    )

    cfg = TransformerConfig(
        vocab=61, d_model=32, n_heads=4, n_layers=8, d_ff=64
    )
    mesh = make_mesh((2, 4), ("dp", "pp"))
    vkw = {"virtual_stages": 2} if schedule == "circular" else {}
    params = shard_params_pipeline(
        init_params(cfg, seed=3), cfg, mesh,
        virtual_stages=vkw.get("virtual_stages"),
    )
    tx = optax.adamw(1e-2)
    step, init_state = make_optax_pipeline_train_step(
        cfg, mesh, tx, n_microbatch=4, schedule=schedule, **vkw
    )
    opt_state = init_state(params)
    # moments inherit the stage params' pp shardings leaf-for-leaf
    adam = next(s for s in jax.tree.leaves(
        opt_state, is_leaf=lambda s: hasattr(s, "mu")
    ) if hasattr(s, "mu"))
    for p_leaf, m_leaf in zip(
        jax.tree.leaves(params), jax.tree.leaves(adam.mu)
    ):
        assert p_leaf.sharding == m_leaf.sharding
    toks, tgts = _data(cfg, seed=11)
    place = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    toks, tgts = place(toks), place(tgts)
    losses = []
    for _ in range(6):
        params, opt_state, loss = step(params, opt_state, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.02, losses


@pytest.mark.slow
def test_optax_pipeline_1f1b_matches_gpipe_trajectory():
    """1F1B computes grads in its own scan (no autodiff-through-scan);
    driving the SAME AdamW from both must give the same loss curve."""
    import optax

    from mpistragglers_jl_tpu.parallel.pipeline import (
        make_optax_pipeline_train_step,
    )

    cfg = TransformerConfig(
        vocab=61, d_model=32, n_heads=4, n_layers=4, d_ff=64
    )
    mesh = make_mesh((2, 2), ("dp", "pp"))
    toks, tgts = _data(cfg, seed=5)
    place = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    toks, tgts = place(toks), place(tgts)
    curves = {}
    for schedule in ("1f1b", "gpipe"):
        params = shard_params_pipeline(init_params(cfg, seed=0), cfg, mesh)
        step, init_state = make_optax_pipeline_train_step(
            cfg, mesh, optax.adamw(1e-2), n_microbatch=4,
            schedule=schedule,
        )
        opt_state = init_state(params)
        losses = []
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, toks, tgts)
            losses.append(float(loss))
        curves[schedule] = losses
    np.testing.assert_allclose(
        curves["1f1b"], curves["gpipe"], rtol=2e-4
    )
