"""Real-chip smoke tests: the non-interpret (Mosaic) Pallas path.

The CI mesh forces 8 virtual CPU devices (conftest.py), so every other
test runs the flash-attention kernels in Pallas interpret mode. VERDICT
round 2 called this out: the Mosaic lowering of the kernels had never
been compiled anywhere. These tests compile and run the REAL path —
flash fwd+bwd and a tiny ulysses+flash train step — in a subprocess
whose environment lets JAX pick the hardware backend again, and SKIP
(visibly) when no TPU is attached. On a machine with a chip they fail
loudly if the non-interpret path stops compiling; bench.py's
``transformer_train`` rung provides the same guarantee on the driver.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import jax
if jax.default_backend() not in ("tpu",):
    print("NOTPU", jax.default_backend())
    raise SystemExit(0)

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpistragglers_jl_tpu.ops.flash_attention import flash_attention
from mpistragglers_jl_tpu.parallel.ring_attention import reference_attention

# --- flash fwd + bwd, compiled (interpret=False is implied on TPU) ---
B, L, H, D = 1, 512, 4, 64
ks = jax.random.split(jax.random.PRNGKey(0), 3)
q, k, v = (jax.random.normal(kk, (B, L, H, D), jnp.float32) for kk in ks)

o = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))(q, k, v)
ref = reference_attention(q, k, v, causal=True)
assert float(jnp.abs(o - ref).max()) < 2e-2, "flash fwd diverged"

gf = jax.jit(jax.grad(
    lambda q, k, v: flash_attention(q, k, v, causal=True).sum(),
    argnums=(0, 1, 2)))
gr = jax.jit(jax.grad(
    lambda q, k, v: reference_attention(q, k, v, causal=True).sum(),
    argnums=(0, 1, 2)))
for a, b in zip(gf(q, k, v), gr(q, k, v)):
    assert float(jnp.abs(a - b).max()) < 5e-2, "flash bwd diverged"

# --- tiny ulysses+flash train step through shard_map on the chip ---
from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig, init_params, make_train_step, shard_params)

cfg = TransformerConfig(vocab=128, d_model=128, n_heads=2, n_layers=2,
                        d_ff=256, attn="ulysses", attn_impl="flash",
                        dtype=jnp.bfloat16)
mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1),
            ("dp", "sp", "tp"))
params = shard_params(init_params(cfg, 0), cfg, mesh)
rng = np.random.default_rng(0)
toks = jax.device_put(rng.integers(0, 128, (2, 257), dtype=np.int32),
                      NamedSharding(mesh, P("dp", "sp")))
step = make_train_step(cfg, mesh, lr=1e-2, donate=True)
params, l0 = step(params, toks[:, :-1], toks[:, 1:])
params, l1 = step(params, toks[:, :-1], toks[:, 1:])
assert float(l1) < float(l0), (float(l0), float(l1))
print("TPUOK", float(l0), float(l1))
"""


def _hw_env():
    """Child env with the conftest's CPU pinning undone so JAX can see
    the hardware again (the axon plugin rides PYTHONPATH)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.slow
def test_flash_attention_mosaic_compiles_on_tpu():
    res = subprocess.run(
        [sys.executable, "-c", _CHILD],
        env=_hw_env(),
        capture_output=True,
        text=True,
        timeout=580,
        cwd=_REPO,
    )
    out = res.stdout + res.stderr
    if "NOTPU" in res.stdout:
        pytest.skip(f"no TPU attached: {res.stdout.strip()}")
    assert res.returncode == 0, f"Mosaic path failed:\n{out[-4000:]}"
    assert "TPUOK" in res.stdout, out[-4000:]
