"""Aux subsystems: tracing, deterministic fault injection, checkpoint.

All-new capability vs the reference (SURVEY §5: tracing/fault-injection/
checkpoint all absent there); tests run on the thread backend, no JAX.
"""

import json

import numpy as np
import pytest

from mpistragglers_jl_tpu import AsyncPool, asyncmap, waitall, LocalBackend
from mpistragglers_jl_tpu.backends.base import WorkerFailure
from mpistragglers_jl_tpu.utils import (
    EpochTracer,
    faults,
    load_state_dict,
    restore,
    save,
    state_dict,
)


def echo_work(worker, payload, epoch):
    return np.concatenate([[worker, epoch], payload])


class TestFaults:
    def test_seeded_schedules_are_deterministic(self):
        for factory in (
            faults.seeded_uniform(0.0, 1.0, seed=3),
            faults.seeded_lognormal(0.01, 1.0, seed=3),
            faults.intermittent(0.5, 1.0, seed=3),
        ):
            a = [factory(w, e) for w in range(4) for e in range(10)]
            b = [factory(w, e) for w in range(4) for e in range(10)]
            assert a == b

    def test_seeded_uniform_range_and_spread(self):
        fn = faults.seeded_uniform(0.1, 0.2, seed=0)
        vals = [fn(w, e) for w in range(8) for e in range(50)]
        assert all(0.1 <= v < 0.2 for v in vals)
        assert np.std(vals) > 0.01  # actually varies

    def test_straggler_every(self):
        fn = faults.straggler(2, 0.5, every=3, offset=1)
        assert fn(2, 1) == 0.5 and fn(2, 4) == 0.5
        assert fn(2, 2) == 0.0 and fn(1, 1) == 0.0

    def test_per_worker_and_compose(self):
        fn = faults.compose(
            faults.per_worker({1: 0.2}), faults.fixed(0.05)
        )
        assert fn(1, 0) == pytest.approx(0.25)
        assert fn(0, 0) == pytest.approx(0.05)

    def test_dead_from_only_after_epoch(self):
        fn = faults.dead_from(0, epoch=5, delay=99.0)
        assert fn(0, 4) == 0.0 and fn(0, 5) == 99.0 and fn(1, 9) == 0.0

    def test_schedule_builder_composes_and_reprs(self):
        sched = (
            faults.FaultSchedule(seed=7)
            .jitter(0.0, 0.001)
            .straggler(1, 0.3)
            .dead_from(3, epoch=2)
        )
        fn = sched.delay_fn
        assert fn(1, 0) >= 0.3
        assert fn(3, 2) >= 3600.0
        assert "straggler" in repr(sched) and "seed=7" in repr(sched)

    def test_failing_raises_worker_failure(self):
        work = faults.failing(echo_work, workers=1, epochs=2)
        backend = LocalBackend(work, 3)
        try:
            pool = AsyncPool(3)
            payload = np.zeros(2)
            asyncmap(pool, payload, backend, epoch=1)  # fine
            with pytest.raises(WorkerFailure) as ei:
                asyncmap(pool, payload, backend, epoch=2)
                waitall(pool, backend)
            assert ei.value.worker == 1 and ei.value.epoch == 2
        finally:
            backend.shutdown()


class TestTracer:
    def test_records_dispatch_and_arrivals(self):
        backend = LocalBackend(echo_work, 4)
        tracer = EpochTracer()
        try:
            pool = AsyncPool(4)
            payload = np.arange(3.0)
            for _ in range(3):
                asyncmap(pool, payload, backend, nwait=4, tracer=tracer)
        finally:
            backend.shutdown()
        assert len(tracer.records) == 3
        for r in tracer.records:
            assert r.call == "asyncmap"
            assert r.n_fresh == 4 and r.n_stale == 0 and r.n_retask == 0
            kinds = [e.kind for e in r.events]
            assert kinds.count("dispatch") == 4
            assert kinds.count("arrival") == 4
            assert r.wall > 0
            assert len(r.repochs) == 4 and len(r.latency) == 4

    def test_straggler_epochs_show_stale_and_retask(self):
        # worker 0 stalls every epoch; nwait=2 of 3 so it straggles, and
        # its late results surface as stale arrivals/drains later
        backend = LocalBackend(
            echo_work, 3, delay_fn=faults.straggler(0, 0.15)
        )
        tracer = EpochTracer()
        try:
            pool = AsyncPool(3)
            payload = np.arange(2.0)
            for _ in range(4):
                asyncmap(pool, payload, backend, nwait=2, tracer=tracer)
            waitall(pool, backend, tracer=tracer)
        finally:
            backend.shutdown()
        maps = [r for r in tracer.records if r.call == "asyncmap"]
        assert all(r.n_fresh >= 2 for r in maps)
        total_stale = sum(r.n_stale for r in tracer.records)
        total_retask = sum(r.n_retask for r in tracer.records)
        # worker 0's late results must have shown up somewhere
        assert total_stale + total_retask > 0
        assert tracer.records[-1].call == "waitall"

    def test_summary_and_jsonl(self, tmp_path):
        backend = LocalBackend(
            echo_work, 3, delay_fn=faults.seeded_uniform(0.0, 0.01, seed=1)
        )
        tracer = EpochTracer()
        try:
            pool = AsyncPool(3)
            for _ in range(5):
                asyncmap(pool, np.zeros(1), backend, nwait=3, tracer=tracer)
        finally:
            backend.shutdown()
        s = tracer.summary()
        assert s["epochs"] == 5
        assert s["n_fresh"] == 15 and s["straggler_rate"] == 0.0
        assert s["arrival_p95_s"] >= s["arrival_p50_s"] > 0
        path = tmp_path / "trace.jsonl"
        tracer.dump_jsonl(path)
        lines = [json.loads(x) for x in path.read_text().splitlines()]
        assert len(lines) == 5
        assert all(len(rec["events"]) == 6 for rec in lines)

    def test_trace_replay_reproduces_straggler_pattern(self, tmp_path):
        """record -> dump_jsonl -> faults.from_trace -> replay: the
        replayed run shows the same straggler (same worker slow, same
        ordering of arrivals) as the recorded one."""
        n = 3
        record_delays = faults.per_worker([0.002, 0.002, 0.08])
        backend = LocalBackend(echo_work, n, delay_fn=record_delays)
        tracer = EpochTracer()
        try:
            pool = AsyncPool(n)
            for _ in range(4):
                asyncmap(pool, np.zeros(1), backend, nwait=2, tracer=tracer)
            waitall(pool, backend, tracer=tracer)
        finally:
            backend.shutdown()
        path = tmp_path / "trace.jsonl"
        tracer.dump_jsonl(path)

        replay = faults.from_trace(path)
        # recorded latencies resurface keyed by (worker, epoch)
        # clearly the straggler, but below the missing floor (one-
        # sided: sleep overshoot on loaded CI only pushes it up a bit)
        assert 0.06 <= replay(2, 1) <= 0.5
        assert replay(0, 1) < 0.05
        # unknown epochs fall back to the worker's median latency
        assert 0.06 <= replay(2, 999) <= 0.5
        assert replay(0, 999) < 0.05
        # unknown workers replay as a long stall, not zero
        assert replay(7, 1) >= 1.0

        backend2 = LocalBackend(echo_work, n, delay_fn=replay)
        tracer2 = EpochTracer()
        try:
            pool2 = AsyncPool(n)
            for _ in range(4):
                asyncmap(
                    pool2, np.zeros(1), backend2, nwait=2, tracer=tracer2
                )
            waitall(pool2, backend2, tracer=tracer2)
        finally:
            backend2.shutdown()
        # same straggler in the replay: worker 2 never fresh inside its
        # epoch during the nwait=2 phase
        for r in tracer2.records:
            if r.call == "asyncmap":
                assert r.repochs[0] == r.epoch and r.repochs[1] == r.epoch
        assert tracer2.summary()["straggler_rate"] == pytest.approx(
            tracer.summary()["straggler_rate"], abs=0.2
        )

    def test_chrome_trace_export(self, tmp_path):
        backend = LocalBackend(
            echo_work, 3,
            delay_fn=faults.per_worker([0.08, 0.005, 0.005]),
        )
        tracer = EpochTracer()
        try:
            pool = AsyncPool(3)
            for _ in range(3):
                asyncmap(pool, np.zeros(1), backend, nwait=2, tracer=tracer)
            waitall(pool, backend, tracer=tracer)
        finally:
            backend.shutdown()
        path = tmp_path / "trace.json"
        n = tracer.dump_chrome_trace(path)
        doc = json.loads(path.read_text())
        evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert n == len(evs)
        coord = [e for e in evs if e["tid"] == -1]
        spans = [e for e in evs if e["tid"] >= 0]
        assert len(coord) == 4  # 3 asyncmap + 1 waitall
        # every worker task dispatched was eventually harvested: spans
        # cover all dispatches, including the straggler's cross-epoch one
        dispatches = sum(
            1 for r in tracer.records for e in r.events
            if e.kind in ("dispatch", "retask")
        )
        assert len(spans) == dispatches
        assert all(e["dur"] >= 0 for e in evs)
        stale = [e for e in spans if "(stale)" in e["name"]]
        assert stale, "straggler must produce at least one stale span"
        names = {e["args"]["name"] for e in doc["traceEvents"] if e["ph"] == "M"}
        assert "coordinator" in names and "worker 0" in names

    def test_untraced_calls_unaffected(self):
        backend = LocalBackend(echo_work, 2)
        try:
            pool = AsyncPool(2)
            repochs = asyncmap(pool, np.zeros(1), backend)
            assert (repochs == 1).all()
        finally:
            backend.shutdown()


class TestCheckpoint:
    def _run_pool(self, epochs=3):
        backend = LocalBackend(echo_work, 3)
        try:
            pool = AsyncPool(3, nwait=2)
            for _ in range(epochs):
                asyncmap(pool, np.zeros(2), backend)
            waitall(pool, backend)
        finally:
            backend.shutdown()
        return pool

    def test_roundtrip_dict(self):
        pool = self._run_pool()
        state = state_dict(pool)
        clone = load_state_dict(state)
        assert clone.ranks == pool.ranks
        assert clone.epoch == pool.epoch and clone.epoch0 == pool.epoch0
        assert clone.nwait == pool.nwait
        np.testing.assert_array_equal(clone.repochs, pool.repochs)
        np.testing.assert_array_equal(clone.sepochs, pool.sepochs)
        np.testing.assert_allclose(clone.latency, pool.latency)
        assert not clone.active.any()

    def test_resume_continues_epoch_numbering(self):
        pool = self._run_pool(epochs=4)
        clone = load_state_dict(state_dict(pool))
        backend = LocalBackend(echo_work, 3)
        try:
            repochs = asyncmap(pool, np.zeros(2), backend, nwait=3)
            assert (repochs == 5).all()
            # the resumed clone picks up the same next epoch
            backend2 = LocalBackend(echo_work, 3)
            try:
                repochs2 = asyncmap(clone, np.zeros(2), backend2, nwait=3)
                assert (repochs2 == 5).all()
            finally:
                backend2.shutdown()
        finally:
            backend.shutdown()

    def test_refuses_active_pool(self):
        backend = LocalBackend(
            echo_work, 2, delay_fn=faults.fixed(0.2)
        )
        try:
            pool = AsyncPool(2)
            asyncmap(pool, np.zeros(1), backend, nwait=0)
            with pytest.raises(RuntimeError, match="still active"):
                state_dict(pool)
            # allow_active drops in-flight work
            state = state_dict(pool, allow_active=True)
            clone = load_state_dict(state)
            assert not clone.active.any()
            waitall(pool, backend)
        finally:
            backend.shutdown()

    def test_file_roundtrip(self, tmp_path):
        pool = self._run_pool()
        path = tmp_path / "pool.json"
        save(pool, path)
        clone = restore(path)
        assert clone.epoch == pool.epoch
        np.testing.assert_array_equal(clone.repochs, pool.repochs)

    def test_rejects_unknown_format(self):
        with pytest.raises(ValueError, match="format"):
            load_state_dict({"format": "bogus"})


class TestDeadWorkerDetection:
    def test_waitall_timeout_with_injected_death(self):
        from mpistragglers_jl_tpu import DeadWorkerError

        backend = LocalBackend(
            echo_work, 3, delay_fn=faults.dead_from(2, epoch=1)
        )
        try:
            pool = AsyncPool(3)
            repochs = asyncmap(pool, np.zeros(1), backend, nwait=2, epoch=1)
            assert (repochs[:2] == 1).all()
            tracer = EpochTracer()
            with pytest.raises(DeadWorkerError) as ei:
                waitall(pool, backend, timeout=0.2, tracer=tracer)
            assert ei.value.dead == [2]
            # the failure trace is flushed, not lost: the waitall record
            # exists and names only the one worker being drained
            assert tracer.records[-1].call == "waitall"
            assert tracer.records[-1].nwait == 1
        finally:
            backend.shutdown()
