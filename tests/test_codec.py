"""Zero-copy payload codec (native/codec.py) — VERDICT round 1 item 7.

Raw contiguous ndarrays travel as header-prefix + raw bytes (decode is a
frombuffer VIEW, not a copy); everything else falls back to pickle.
Transport-level shm broadcast is exercised through the backend suites;
here the codec contract itself is pinned.
"""

import numpy as np
import pytest

from mpistragglers_jl_tpu.native import codec


def _roundtrip(obj):
    prefix, body = codec.encode(obj)
    # socket framing: prefix + body contiguous
    if isinstance(body, np.ndarray):
        wire = bytearray(prefix) + bytearray(body.reshape(-1).view(np.uint8))
    else:
        wire = bytearray(prefix) + bytearray(body)
    return codec.decode(wire)


def test_raw_arrays_bit_exact():
    for arr in [
        np.array([np.pi, -0.0, np.inf, np.nan]),
        np.arange(24, dtype=np.int64).reshape(2, 3, 4),
        np.array(7.5, dtype=np.float32),          # 0-d
        np.zeros((0, 3), dtype=np.uint8),          # empty
        np.array([2**62, -1], dtype=np.int64),
    ]:
        got = _roundtrip(arr)
        assert got.dtype == arr.dtype and got.shape == arr.shape
        assert got.tobytes() == arr.tobytes()


def test_raw_decode_is_a_view_not_a_copy():
    arr = np.arange(8, dtype=np.float64)
    prefix, body = codec.encode(arr)
    assert body is arr  # send side: the array itself, zero-copy
    wire = bytearray(prefix) + bytearray(body.view(np.uint8))
    got = codec.decode(wire)
    assert got.base is not None  # view over the frame buffer
    wire[len(prefix)] ^= 0xFF    # mutate the buffer through the view
    assert got[0] != arr[0]


def test_out_of_band_body():
    arr = np.arange(6, dtype=np.int32).reshape(2, 3)
    prefix, body = codec.encode(arr)
    got = codec.decode(prefix, memoryview(body.reshape(-1).view(np.uint8)))
    assert np.array_equal(got, arr)


def test_noncontiguous_input_is_made_contiguous():
    arr = np.arange(16, dtype=np.float32).reshape(4, 4)[:, ::2]
    got = _roundtrip(arr)
    assert np.array_equal(got, arr)


def test_pickle_fallbacks():
    rec = np.zeros(2, dtype=[("a", np.int32), ("b", "S3")])
    rec["a"] = [1, 2]
    rec["b"] = [b"xy", b"zzz"]
    for obj in [rec, {"k": [1, 2.5]}, "text", 42, None,
                np.array([{}, []], dtype=object)]:
        prefix, body = codec.encode(obj)
        assert prefix[0] == codec.MAGIC_PICKLE
        got = _roundtrip(obj)
        if isinstance(obj, np.ndarray):
            assert got.dtype == obj.dtype
            assert list(got) == list(obj)
        else:
            assert got == obj


def test_unknown_magic_rejected():
    with pytest.raises(ValueError, match="magic"):
        codec.decode(b"\x7fgarbage")
    with pytest.raises(ValueError, match="magic"):
        codec.decode(b"")
