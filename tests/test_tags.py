"""Tag-multiplexed channels: two pools sharing one backend.

The reference multiplexes message classes over one MPI communicator with
tags (data tag 0 / control tag 1 convention at test/kmap2.jl:11-12, the
``tag`` kwarg at src/MPIAsyncPools.jl:68), so two pools — or a data and
a control stream — can share a transport without crosstalk. These tests
pin that capability on every backend: each tag is an isolated channel
with its own in-flight slot per worker, results never cross channels,
and a pool harvests with the tag its dispatch was posted on
(``pool.stags``, the analog of an MPI request remembering its tag).
"""

import time

import numpy as np
import pytest

from mpistragglers_jl_tpu import AsyncPool, asyncmap, waitall
from mpistragglers_jl_tpu.backends.local import LocalBackend


def _tagged_echo(i, payload, epoch):
    """payload = [stream_id, sleep_seconds]; result identifies the
    stream so crosstalk is detectable."""
    stream, sleep_s = int(payload[0]), float(payload[1])
    if sleep_s > 0:
        time.sleep(sleep_s)
    return np.array([stream * 10 + i], dtype=np.int64)


def _make_backend(kind, work_fn, n):
    if kind == "local":
        return LocalBackend(work_fn, n)
    if kind == "process":
        from mpistragglers_jl_tpu.backends.process import ProcessBackend

        return ProcessBackend(work_fn, n)
    from mpistragglers_jl_tpu.native import NativeBuildError

    try:
        from mpistragglers_jl_tpu.backends.native import NativeProcessBackend

        return NativeProcessBackend(work_fn, n)
    except NativeBuildError as e:  # pragma: no cover - no compiler
        pytest.skip(f"native transport unavailable: {e}")


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["local", "process", "native"])
def test_two_pools_one_backend_no_crosstalk(kind):
    """Pool A (tag 1, slow work) and pool B (tag 2, fast work) share one
    backend; B completes while A's work is still in flight, and each
    pool harvests only its own stream's results."""
    n = 2
    backend = _make_backend(kind, _tagged_echo, n)
    try:
        pool_a = AsyncPool(n)
        pool_b = AsyncPool(n)
        # dispatch A's slow epoch and return immediately (nwait=0)
        asyncmap(pool_a, np.array([1.0, 0.5]), backend, nwait=0, tag=1)
        assert pool_a.active.all()
        assert list(pool_a.stags) == [1, 1]
        # B's fast epoch completes on its own channel while A is in flight
        asyncmap(pool_b, np.array([2.0, 0.0]), backend, nwait=n, tag=2)
        got_b = sorted(int(r[0]) for r in pool_b.results)
        assert got_b == [20, 21]
        assert pool_a.active.all()  # untouched by B's harvest
        # now drain A; its results come from its own channel
        waitall(pool_a, backend)
        got_a = sorted(int(r[0]) for r in pool_a.results)
        assert got_a == [10, 11]
        assert not pool_a.active.any()
        # epochs advanced independently
        assert pool_a.repochs.tolist() == [1, 1]
        assert pool_b.repochs.tolist() == [1, 1]
    finally:
        backend.shutdown()


@pytest.mark.parametrize("kind", ["local", "process", "native"])
def test_concurrent_channels_same_worker(kind):
    """One worker can hold one outstanding task per tag simultaneously
    (MPI semantics: tags are independent request streams)."""
    backend = _make_backend(kind, _tagged_echo, 1)
    try:
        backend.dispatch(0, np.array([7.0, 0.2]), 1, tag=7)
        backend.dispatch(0, np.array([3.0, 0.0]), 1, tag=3)
        # the tag-3 result is routed to its channel even though the
        # tag-7 dispatch is still computing
        r3 = backend.wait(0, timeout=10, tag=3)
        assert int(np.asarray(r3)[0]) == 30
        r7 = backend.wait(0, timeout=10, tag=7)
        assert int(np.asarray(r7)[0]) == 70
    finally:
        backend.shutdown()


@pytest.mark.parametrize("kind", ["local", "process", "native"])
def test_double_dispatch_same_tag_rejected(kind):
    """The one-outstanding-per-channel discipline still holds within a
    tag (the pool's ``active`` invariant)."""
    backend = _make_backend(kind, _tagged_echo, 1)
    try:
        backend.dispatch(0, np.array([1.0, 0.3]), 1, tag=4)
        if kind in ("local", "process"):
            # SlotBackend enforces occupancy explicitly
            with pytest.raises(RuntimeError, match="outstanding"):
                backend.dispatch(0, np.array([1.0, 0.0]), 1, tag=4)
        backend.wait(0, timeout=10, tag=4)
    finally:
        backend.shutdown()


def test_wait_any_mixed_tags_local():
    """wait_any accepts per-index tags: two pools' hot loops can block
    on their own channels over the same worker set."""
    backend = _make_backend("local", _tagged_echo, 2)
    try:
        backend.dispatch(0, np.array([5.0, 0.4]), 1, tag=5)
        backend.dispatch(1, np.array([6.0, 0.0]), 1, tag=6)
        got = backend.wait_any([0, 1], timeout=10, tags=[5, 6])
        assert got is not None
        i, result = got
        assert i == 1 and int(np.asarray(result)[0]) == 61
        got = backend.wait_any([0], timeout=10, tags=[5])
        i, result = got
        assert i == 0 and int(np.asarray(result)[0]) == 50
        with pytest.raises(ValueError, match="align"):
            backend.wait_any([0, 1], tags=[1])
    finally:
        backend.shutdown()


@pytest.mark.slow
def test_control_data_split_native():
    """The kmap2 convention, library-grade: a data pool (tag 0) and a
    low-rate control pool (tag 1) multiplex one native transport; a
    control probe completes while data epochs run."""
    from mpistragglers_jl_tpu.native import NativeBuildError

    try:
        from mpistragglers_jl_tpu.backends.native import NativeProcessBackend

        backend = NativeProcessBackend(_tagged_echo, 2)
    except NativeBuildError as e:  # pragma: no cover - no compiler
        pytest.skip(f"native transport unavailable: {e}")
    try:
        data_pool = AsyncPool(2)
        ctrl_pool = AsyncPool(2)
        for epoch in range(1, 4):
            asyncmap(
                data_pool, np.array([1.0, 0.05]), backend,
                nwait=0, tag=0, epoch=epoch,
            )
            # control heartbeat rides tag 1 while data is in flight
            asyncmap(
                ctrl_pool, np.array([9.0, 0.0]), backend,
                nwait=2, tag=1, epoch=epoch,
            )
            assert sorted(int(r[0]) for r in ctrl_pool.results) == [90, 91]
            waitall(data_pool, backend)
            assert sorted(int(r[0]) for r in data_pool.results) == [10, 11]
            assert data_pool.repochs.tolist() == [epoch, epoch]
    finally:
        backend.shutdown()


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["local", "process", "native"])
def test_subset_pools_with_tags(kind):
    """Rank-subset routing (pool index i -> ranks[i], reference
    src/MPIAsyncPools.jl:21,:137-138) composes with tag channels:
    disjoint-subset pools on distinct tags of one backend each drive
    exactly their own workers, and an OVERLAPPING worker can serve two
    pools simultaneously on different tags (one outstanding task per
    (worker, tag) channel — MPI request semantics)."""
    backend = _make_backend(kind, _tagged_echo, 6)
    try:
        pa = AsyncPool([0, 2, 4])
        pb = AsyncPool([1, 3])
        # A's slow epoch in flight on tag 1; B completes on tag 2
        asyncmap(pa, np.array([1.0, 0.3]), backend, nwait=0, tag=1)
        asyncmap(pb, np.array([2.0, 0.0]), backend, nwait=2, tag=2)
        # results encode stream*10 + BACKEND worker id: proof of routing
        assert sorted(int(r[0]) for r in pb.results) == [21, 23]
        # worker 2 is busy for pool A on tag 1 — a different pool can
        # still task it on tag 3 while that dispatch is outstanding
        pc = AsyncPool([2, 5])
        asyncmap(pc, np.array([3.0, 0.0]), backend, nwait=2, tag=3)
        assert sorted(int(r[0]) for r in pc.results) == [32, 35]
        waitall(pa, backend)
        assert sorted(int(r[0]) for r in pa.results) == [10, 12, 14]
    finally:
        backend.shutdown()
