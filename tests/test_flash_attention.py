"""Pallas flash attention vs the materializing oracle.

Runs in Pallas interpret mode on the CPU mesh (conftest.py); the same
kernels compile on a real chip (grid/block tiling is TPU-legal:
trailing-singleton lse layout, lane-aligned blocks).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mpistragglers_jl_tpu.ops.flash_attention import flash_attention
from mpistragglers_jl_tpu.parallel import make_mesh
from mpistragglers_jl_tpu.parallel.ring_attention import (
    make_ulysses_attention,
    reference_attention,
)


def _qkv(shape, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal(shape), dtype=dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "shape", [(2, 128, 2, 16), (1, 256, 4, 32), (2, 64, 1, 8)]
)
def test_forward_matches_reference(causal, shape):
    q, k, v = _qkv(shape)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_multiple_kv_blocks_online_softmax():
    # 4 k-blocks forces several online-softmax rescale steps
    q, k, v = _qkv((1, 256, 2, 16), seed=3)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_block_fallback_non_divisible():
    # L=96 does not divide the default 128 block; blocks shrink to fit
    q, k, v = _qkv((1, 96, 2, 16), seed=4)
    got = flash_attention(q, k, v, causal=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_bfloat16():
    q, k, v = _qkv((1, 128, 2, 16), seed=5, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    assert got.dtype == jnp.bfloat16
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32),
        np.asarray(want, dtype=np.float32),
        atol=3e-2, rtol=3e-2,
    )


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    q, k, v = _qkv((1, 128, 2, 16), seed=6)
    w = jnp.asarray(
        np.random.default_rng(7).standard_normal(q.shape), jnp.float32
    )

    def loss(attn):
        return lambda q, k, v: jnp.sum(attn(q, k, v) * w)

    gf = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=64, block_k=64
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        loss(lambda q, k, v: reference_attention(q, k, v, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5,
            err_msg=f"d{name}",
        )


def test_grad_under_jit():
    q, k, v = _qkv((1, 128, 2, 16), seed=8)
    f = jax.jit(
        jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=64, block_k=64) ** 2
        ))
    )
    g = f(q, k, v)
    assert g.shape == q.shape and bool(jnp.all(jnp.isfinite(g)))


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_impl(causal):
    # flash as the per-device kernel inside Ulysses sequence parallelism
    mesh = make_mesh(4, "sp")
    q, k, v = _qkv((2, 128, 4, 16), seed=9)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    uly = make_ulysses_attention(mesh, causal=causal, impl="flash")
    got = uly(qs, ks, vs)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_odd_length_fallback_runs_and_matches():
    """A prime sequence length has no 8-aligned divisor; _pick_block
    falls back to one whole-dimension block, which must still be exact
    (interpret mode here; the VMEM guard covers compiled TPU runs)."""
    from mpistragglers_jl_tpu.ops.flash_attention import _pick_block

    L = 37  # prime
    assert _pick_block(L, 1024) == L
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, L, 2, 8)), jnp.float32)
        for _ in range(3)
    )
    got = flash_attention(q, k, v, causal=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
    )


def test_odd_length_fallback_vmem_guard():
    """A prime length too large for one VMEM-resident block must raise
    the clear padding error instead of handing Mosaic an impossible
    tiling (VERDICT r3 weak #6)."""
    import pytest

    L = 65537  # prime, ~big: one (L, L) fallback block cannot fit VMEM
    q = jnp.zeros((1, L, 1, 64), jnp.bfloat16)
    with pytest.raises(ValueError, match="multiple of 8"):
        flash_attention(q, q, q, causal=True, interpret=False)


def test_oversize_aligned_block_vmem_guard():
    """Explicitly tuned oversize blocks get the same clear error as the
    odd-L fallback (the PERF round-4 sweep's 2048-block Mosaic OOM)."""
    import pytest

    q = jnp.zeros((1, 2048, 1, 128), jnp.bfloat16)
    with pytest.raises(ValueError, match="lower block_q/block_k"):
        flash_attention(
            q, q, q, causal=True, block_q=2048, block_k=2048,
            interpret=False,
        )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("hkv", [1, 2, 4])
def test_fused_backward_matches_split_and_reference(causal, hkv):
    """The single-pass backward (shared s/dp recompute + partial dk/dv
    reduction) must produce the same gradients as the split kernels
    and the dense reference — GQA group-sums included."""
    rng = np.random.default_rng(3)
    mk = lambda h: jnp.asarray(
        rng.standard_normal((2, 32, h, 8)), jnp.float32
    )
    q, k, v = mk(4), mk(hkv), mk(hkv)

    def loss(impl):
        def f(q, k, v):
            o = flash_attention(
                q, k, v, causal=causal, block_q=16, block_k=16,
                bwd_impl=impl,
            )
            return (o.astype(jnp.float32) ** 2).sum()

        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_fused = loss("fused")
    g_split = loss("split")

    def f_ref(q, k, v):
        o = reference_attention(q, k, v, causal=causal)
        return (o.astype(jnp.float32) ** 2).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, c, name in zip(g_fused, g_split, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5,
            err_msg=f"fused vs split d{name}",
        )
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(c), atol=1e-4, rtol=1e-4,
            err_msg=f"fused vs reference d{name}",
        )


def test_bwd_impl_auto_and_validation():
    from mpistragglers_jl_tpu.ops.flash_attention import _use_fused_bwd

    # auto resolves to split everywhere: the fused variant measured
    # SLOWER on the chip (its partial-buffer HBM traffic outweighs the
    # dot saving) — see _use_fused_bwd's docstring
    assert not _use_fused_bwd()
    q = jnp.zeros((1, 16, 1, 8), jnp.float32)
    import pytest

    with pytest.raises(ValueError, match="bwd_impl"):
        flash_attention(q, q, q, bwd_impl="nope")
