"""Virtual-time simulation (sim/): clock, SimBackend, replay, tuning.

The ISSUE 5 acceptance chain lives in ``TestEndToEnd``: a REAL
``ProcessBackend`` run is recorded, replayed through ``SimBackend``
with exact fresh-set reproduction; the autotuner's recommendation is
cross-checked against ``PoolLatencyModel.optimal_nwait``; and a
1k-epoch simulated ``asyncmap`` loop (real pool.py, virtual clock)
finishes in under 2 s wall with bit-identical repochs across two runs.
Everything else pins the pieces: deterministic event ordering, the
Backend protocol's error contract, thread rendezvous, instrumentation
into the shared obs/ plane, and trace parsing per the replay label
contract.
"""

import json
import threading
import time

import numpy as np
import pytest

from mpistragglers_jl_tpu import (
    AsyncPool,
    DeadWorkerError,
    ProcessBackend,
    SimBackend,
    VirtualClock,
    WorkerFailure,
    asyncmap,
    waitall,
)
from mpistragglers_jl_tpu.sim import (
    ReplayTrace,
    compare,
    model_delay_fn,
    recommend_nwait,
    replay,
    sweep_code_rate,
    sweep_hedge,
    sweep_nwait,
)
from mpistragglers_jl_tpu.utils import EpochTracer, faults
from mpistragglers_jl_tpu.utils.straggle import PoolLatencyModel


def _echo(i, payload, epoch):
    return np.asarray([i, epoch], dtype=np.int64)


# --------------------------------------------------------------------------
# VirtualClock
# --------------------------------------------------------------------------


class TestVirtualClock:
    def test_time_only_moves_when_advanced(self):
        clock = VirtualClock()
        assert clock.now() == 0.0
        clock.advance(2.5)
        assert clock.now() == 2.5
        clock.run_until(1.0)  # never backwards
        assert clock.now() == 2.5

    def test_events_fire_in_time_then_schedule_order(self):
        clock = VirtualClock()
        fired = []
        clock.call_at(2.0, lambda: fired.append("b"))
        clock.call_at(1.0, lambda: fired.append("a"))
        clock.call_at(2.0, lambda: fired.append("c"))  # ties: schedule order
        clock.call_later(3.0, lambda: fired.append("d"))
        clock.run_until(2.0)
        assert fired == ["a", "b", "c"]
        assert clock.next_event() == 3.0
        clock.run_all()
        assert fired == ["a", "b", "c", "d"] and clock.now() == 3.0

    def test_callback_may_schedule_earlier_followup(self):
        clock = VirtualClock()
        fired = []
        clock.call_at(1.0, lambda: clock.call_later(
            0.5, lambda: fired.append(("follow", clock.now()))
        ))
        clock.call_at(10.0, lambda: fired.append(("late", clock.now())))
        clock.run_all()
        assert fired == [("follow", 1.5), ("late", 10.0)]

    def test_thread_rendezvous_is_deterministic(self):
        """Two registered threads sleeping different cadences interleave
        identically on every run: wake order is virtual-time order, not
        the OS scheduler's mood."""

        def run_once():
            clock = VirtualClock()
            log = []

            def worker(name, period, n):
                clock.register()
                try:
                    for k in range(n):
                        clock.sleep(period)
                        log.append((name, round(clock.now(), 9)))
                finally:
                    clock.unregister()

            ts = [
                threading.Thread(target=worker, args=("a", 0.3, 4)),
                threading.Thread(target=worker, args=("b", 0.5, 3)),
            ]
            clock.expect(2)  # don't advance before both have parked
            for t in ts:
                t.start()
            clock.run_until(2.0)
            for t in ts:
                t.join(timeout=5.0)
            return log

        first = run_once()
        assert first == run_once()  # bit-identical interleaving
        assert first == sorted(first, key=lambda x: x[1])
        assert ("a", 0.3) in first and ("b", 0.5) in first
        assert ("a", 1.2) in first and ("b", 1.5) in first

    def test_unadvanced_sleep_diagnoses_instead_of_hanging(self):
        clock = VirtualClock(stall_timeout=0.05)
        with pytest.raises(RuntimeError, match="never"):
            clock.sleep(1.0)  # nobody will advance: error, not a hang


# --------------------------------------------------------------------------
# SimBackend protocol + determinism
# --------------------------------------------------------------------------


class TestSimBackend:
    def test_protocol_error_contract_matches_slot_backend(self):
        be = SimBackend(_echo, 2, delay_fn=faults.fixed(1.0))
        be.dispatch(0, np.zeros(1), 1)
        with pytest.raises(RuntimeError, match="outstanding"):
            be.dispatch(0, np.zeros(1), 1)
        with pytest.raises(RuntimeError, match="no outstanding"):
            be.wait(1)
        with pytest.raises(ValueError, match="empty"):
            be.wait_any([])
        with pytest.raises(ValueError, match="align"):
            be.wait_any([0], tags=[0, 1])
        with pytest.raises(RuntimeError, match="block forever"):
            be.wait_any([1])  # nothing in flight, unbounded wait
        assert be.test(0) is None  # not yet arrived at vnow=0
        assert be.wait(0, timeout=0.25) is None  # virtual timeout
        assert be.clock.now() == 0.25
        out = be.wait(0)
        assert out.tolist() == [0, 1] and be.clock.now() == 1.0
        be.shutdown()
        with pytest.raises(RuntimeError, match="shut down"):
            be.dispatch(0, np.zeros(1), 2)

    def test_payload_snapshot_survives_caller_mutation(self):
        got = []
        be = SimBackend(
            lambda i, p, e: got.append(p.copy()) or p.sum(), 1
        )
        buf = np.ones(4)
        be.dispatch(0, buf, 1)
        buf[:] = 99.0  # in-flight send must not see this
        be.wait(0)
        assert got[0].tolist() == [1.0, 1.0, 1.0, 1.0]

    def test_wait_any_breaks_ties_by_dispatch_order(self):
        be = SimBackend(_echo, 3, delay_fn=faults.fixed(0.5))
        for i in (2, 0, 1):  # dispatch order != index order
            be.dispatch(i, np.zeros(1), 1)
        winners = []
        for _ in range(3):
            i, _r = be.wait_any([0, 1, 2])
            winners.append(i)
        assert winners == [2, 0, 1]  # identical done_at: dispatch order

    def test_interrupts_abort_instead_of_masquerading_as_faults(self):
        """work_fn runs eagerly on the CALLING thread (unlike the
        thread/process backends), so KeyboardInterrupt must propagate
        out of dispatch — not be swallowed into a WorkerError that
        later blames an innocent simulated worker."""

        def interrupted(i, payload, epoch):
            raise KeyboardInterrupt

        be = SimBackend(interrupted, 2)
        with pytest.raises(KeyboardInterrupt):
            be.dispatch(0, np.zeros(1), 1)

    def test_worker_exception_surfaces_as_worker_failure(self):
        work = faults.failing(_echo, workers=1, epochs=2)
        be = SimBackend(work, 3)
        pool = AsyncPool(3)
        asyncmap(pool, np.zeros(1), be, nwait=3, epoch=1)
        with pytest.raises(WorkerFailure, match="worker 1"):
            asyncmap(pool, np.zeros(1), be, nwait=3, epoch=2)
        # the pool stays recoverable, reference contract
        asyncmap(pool, np.zeros(1), be, nwait=3, epoch=3)
        waitall(pool, be)

    def test_bit_reproducible_arrival_orders(self):
        def run():
            be = SimBackend(
                _echo, 8,
                delay_fn=faults.seeded_lognormal(0.02, 1.0, seed=7),
            )
            pool = AsyncPool(8)
            reps = [
                asyncmap(pool, np.zeros(1), be, nwait=5).copy()
                for _ in range(50)
            ]
            waitall(pool, be)
            order = [(e.worker, e.epoch, e.t_done) for e in be.events]
            return reps, order, be.clock.now()

        r1, o1, t1 = run()
        r2, o2, t2 = run()
        assert all((a == b).all() for a, b in zip(r1, r2))
        assert o1 == o2 and t1 == t2

    def test_virtual_latency_feeds_latency_model(self):
        be = SimBackend(
            _echo, 4, delay_fn=faults.per_worker([0.01, 0.02, 0.03, 0.4])
        )
        pool = AsyncPool(4)
        model = PoolLatencyModel(4)
        for _ in range(3):
            asyncmap(pool, np.zeros(1), be, nwait=4)
            be.observe_into(model)
        means = [w.mean for w in model.workers]
        assert means == pytest.approx([0.01, 0.02, 0.03, 0.4], rel=1e-9)

    def test_model_delay_fn_deterministic_and_prior_for_silent(self):
        model = PoolLatencyModel(3, seed=0)
        rng = np.random.default_rng(0)
        for x in 0.05 + rng.exponential(0.02, 200):
            model.observe(0, x)
        for x in 0.10 + rng.exponential(0.01, 200):
            model.observe(1, x)
        # worker 2 silent
        fn = model_delay_fn(model, seed=3)
        draws = [[fn(w, e) for e in range(50)] for w in range(3)]
        again = [[fn(w, e) for e in range(50)] for w in range(3)]
        assert draws == again  # pure in (seed, worker, epoch)
        assert min(draws[0]) >= 0.05 and min(draws[1]) >= 0.10
        # silent worker draws the pooled prior, not zero
        assert min(draws[2]) >= 0.05
        assert np.mean(draws[2]) == pytest.approx(
            np.mean([np.mean(draws[0]), np.mean(draws[1])]), rel=0.6
        )

    def test_instrumentation_lands_in_shared_obs_plane(self):
        from mpistragglers_jl_tpu.obs import (
            MetricsRegistry,
            SpanRecorder,
            merged_chrome_trace,
        )

        reg = MetricsRegistry()
        spans = SpanRecorder("sim")
        be = SimBackend(
            _echo, 4, delay_fn=faults.per_worker([0.01, 0.02, 0.03, 0.2]),
            registry=reg, spans=spans,
        )
        pool = AsyncPool(4)
        for _ in range(3):
            asyncmap(pool, np.zeros(1), be, nwait=3)
        waitall(pool, be)
        snap = reg.snapshot()

        def val(name):
            return snap[name]["series"][0]["value"]

        assert val("sim_tasks_dispatched_total") == be.n_dispatched
        assert val("sim_tasks_delivered_total") == be.n_delivered
        assert val("sim_virtual_time_seconds") == pytest.approx(
            be.clock.now()
        )
        # simulated worker spans merge into the same Perfetto documents
        # as live fleets (virtual seconds on the time axis)
        doc, n = merged_chrome_trace(recorders=[spans])
        assert n == be.n_delivered
        names = {
            e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"
        }
        assert any(name.startswith("task e") for name in names)


# --------------------------------------------------------------------------
# replay
# --------------------------------------------------------------------------


def _recorded_local_run(tmp_path=None):
    """A small REAL thread-backend run with distinct per-worker speeds
    and one hard straggler, traced; returns (tracer, delays)."""
    from mpistragglers_jl_tpu.backends.local import LocalBackend

    delays = faults.compose(
        faults.per_worker([0.02, 0.05, 0.08, 0.0]),
        faults.straggler(3, 0.6),
    )
    backend = LocalBackend(_echo, 4, delay_fn=delays)
    tracer = EpochTracer()
    pool = AsyncPool(4)
    try:
        for _ in range(5):
            asyncmap(pool, np.zeros(1), backend, nwait=3, tracer=tracer)
        waitall(pool, backend, tracer=tracer)
    finally:
        backend.shutdown()
    return tracer


class TestReplay:
    def test_same_policy_replay_reproduces_fresh_sets(self):
        tracer = _recorded_local_run()
        trace = ReplayTrace.from_tracer(tracer)
        assert trace.n_workers == 4 and len(trace.epochs) == 5
        res = replay(trace)  # recorded nwait
        drift = compare(trace, res)
        assert drift["fresh_exact_rate"] == 1.0
        assert drift["wall_drift_max_s"] < 0.05  # thread-sched overhead
        for snap in trace.epochs:
            assert snap.fresh == frozenset({0, 1, 2})

    def test_jsonl_roundtrip_equals_in_memory(self, tmp_path):
        tracer = _recorded_local_run()
        path = tmp_path / "trace.jsonl"
        tracer.dump_jsonl(path)
        a = replay(ReplayTrace.from_tracer(tracer))
        b = replay(ReplayTrace.from_jsonl(path))
        assert [r["fresh"] for r in a.epochs] == [
            r["fresh"] for r in b.epochs
        ]
        assert a.walls.tolist() == b.walls.tolist()

    def test_chrome_doc_replay_per_label_contract(self, tmp_path):
        tracer = _recorded_local_run()
        path = tmp_path / "trace.json"
        tracer.dump_chrome_trace(path)
        trace = ReplayTrace.from_chrome(str(path))
        res = replay(trace, nwait=3)
        assert compare(trace, res)["fresh_exact_rate"] == 1.0

    def test_chrome_dead_worker_needs_explicit_width(self, tmp_path):
        """Chrome docs only draw ARRIVED tasks, so a worker dead for
        the whole recording has no track and the inferred fleet comes
        up one short — the documented caveat; n_workers= restores the
        true width and the dead rank replays as a missing-stall."""
        be = SimBackend(
            _echo, 3, delay_fn=faults.dead_from(2, 0, delay=100.0)
        )
        tracer = EpochTracer()
        pool = AsyncPool(3)
        for _ in range(2):
            asyncmap(pool, np.zeros(1), be, nwait=2, tracer=tracer)
        path = tmp_path / "dead.json"
        tracer.dump_chrome_trace(path)
        inferred = ReplayTrace.from_chrome(str(path))
        assert inferred.n_workers == 2  # rank 2 invisible: the caveat
        full = ReplayTrace.from_chrome(str(path), n_workers=3)
        assert full.n_workers == 3
        res = replay(full, nwait=2, drain=False)
        assert all(2 not in r["fresh"] for r in res.epochs)

    def test_chrome_doc_without_pool_spans_is_rejected(self):
        doc = {"traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "serving"}},
        ]}
        with pytest.raises(ValueError, match="pool"):
            ReplayTrace.from_chrome(doc)

    def test_counterfactual_nwait_changes_the_story(self):
        """The point of the plane: the same incident priced under a
        different policy. nwait=4 must wait out the 0.6 s straggler
        every epoch; the recorded nwait=3 never does."""
        trace = ReplayTrace.from_tracer(_recorded_local_run())
        fast = replay(trace)  # recorded nwait=3
        slow = replay(trace, nwait=4)
        assert fast.summary()["wall_mean_s"] < 0.12
        assert slow.summary()["wall_mean_s"] > 0.5
        assert all(r["fresh"] == frozenset(range(4)) for r in slow.epochs)

    def test_empty_and_callable_nwait_traces_are_refused(self):
        with pytest.raises(ValueError, match="empty"):
            ReplayTrace([])
        rec = {
            "epoch": 1, "call": "asyncmap", "nwait": "<callable>",
            "wall_s": 0.1, "repochs": [1, 1], "latency_s": [0.1, 0.1],
            "events": [],
        }
        trace = ReplayTrace([rec])
        with pytest.raises(ValueError, match="callable"):
            replay(trace)
        # explicit nwait unblocks it
        res = replay(trace, nwait=2)
        assert len(res.epochs) == 1


# --------------------------------------------------------------------------
# tune
# --------------------------------------------------------------------------


class TestTune:
    def test_sweep_dodges_designated_straggler(self):
        sweep = sweep_nwait(
            faults.compose(
                faults.per_worker([0.01] * 7 + [0.0]),
                faults.straggler(7, 1.0),
            ),
            n_workers=8, epochs=30, floor=2,
        )
        assert sweep.best == 7  # everyone but the straggler
        assert sweep.entry(8)["mean_epoch_s"] == pytest.approx(1.0)
        assert sweep.entry(7)["mean_epoch_s"] == pytest.approx(0.01)
        assert "<- best" in sweep.table()

    def test_floor_is_enforced_not_clamped(self):
        delay = faults.fixed(0.01)
        with pytest.raises(ValueError, match="decodability floor"):
            sweep_nwait(
                delay, n_workers=4, floor=3, nwait_values=[2, 3, 4],
            )
        sweep = sweep_nwait(delay, n_workers=4, floor=3, epochs=5)
        assert sweep.best >= 3
        assert all(r["nwait"] >= 3 for r in sweep.entries)

    def test_code_rate_sweep_prices_recovered_work(self):
        """6 fast workers + 2 slow: k=6 recovers the most work per
        virtual second; k=8 pays the stragglers, k=2 wastes capacity."""
        sweep = sweep_code_rate(
            faults.compose(
                faults.per_worker([0.01] * 6 + [0.0] * 2),
                faults.straggler((6, 7), 0.8),
            ),
            n_workers=8, k_values=[2, 4, 6, 8], epochs=20,
        )
        assert sweep.best == 6

    def test_hedge_sweep_recommends_narrowest_tail_free_width(self):
        res = sweep_hedge(
            lambda i, e: 0.3 if (e + i) % 4 == 0 else 0.01,
            n_workers=4, widths=[1, 2, 3], requests=16,
        )
        by_w = {r["width"]: r for r in res["entries"]}
        assert by_w[1]["p95_latency_s"] > 0.25  # eats stalls
        assert by_w[2]["p95_latency_s"] == pytest.approx(0.01)
        assert res["recommended_width"] == 2  # width 3 buys nothing

    def test_trace_source_resolves_pool_size(self):
        trace = ReplayTrace.from_tracer(_recorded_local_run())
        # floor 3 = an (n=4, k=3) code: the sweep prices nwait 3 vs 4
        # on the recorded incident and dodges the 0.6 s straggler
        sweep = sweep_nwait(trace, epochs=5, floor=3)
        assert sweep.best == 3
        assert sweep.entry(4)["mean_epoch_s"] > 5 * (
            sweep.entry(3)["mean_epoch_s"]
        )
        with pytest.raises(TypeError, match="latency source"):
            sweep_nwait(object(), n_workers=4)


# --------------------------------------------------------------------------
# straggle.py contract the tuner leans on (determinism fix, ISSUE 5)
# --------------------------------------------------------------------------


def test_optimal_nwait_is_deterministic_across_calls():
    """The fixed failure: a shared RNG advanced across calls, so two
    identical ``optimal_nwait`` calls could disagree near a utility
    tie. Predictions are now pure functions of (fitted state, seed)."""
    model = PoolLatencyModel(6, seed=11)
    rng = np.random.default_rng(1)
    for i in range(6):
        for x in 0.01 * (i + 1) + rng.exponential(0.02, 40):
            model.observe(i, x)
    draws = model.sample_latencies(256)
    assert (draws == model.sample_latencies(256)).all()
    picks = {model.optimal_nwait() for _ in range(5)}
    assert len(picks) == 1
    times = {model.expected_epoch_time(4) for _ in range(5)}
    assert len(times) == 1


# --------------------------------------------------------------------------
# acceptance: the ISSUE 5 end-to-end chain
# --------------------------------------------------------------------------


class _AcceptanceDelays:
    """Picklable (module-level class) for ProcessBackend workers:
    distinct fast speeds + one hard straggler on rank 3."""

    BASE = (0.05, 0.08, 0.11, 0.0)

    def __call__(self, i, epoch):
        return 0.6 if i == 3 else self.BASE[i]


def _proc_work(i, payload, epoch):
    return np.asarray([i, epoch], dtype=np.int64)


class TestEndToEnd:
    def test_process_backend_record_replay_fresh_sets_exact(self):
        """Record a 4-worker straggling ProcessBackend run via
        EpochTracer; replay through SimBackend with the same nwait;
        per-epoch fresh-worker sets reproduce EXACTLY and epoch
        latencies land within tolerance of the recorded walls."""
        backend = ProcessBackend(
            _proc_work, 4, delay_fn=_AcceptanceDelays()
        )
        tracer = EpochTracer()
        pool = AsyncPool(4)
        try:
            for _ in range(4):
                asyncmap(
                    pool, np.zeros(1), backend, nwait=3, tracer=tracer
                )
            waitall(pool, backend, tracer=tracer, timeout=30.0)
        finally:
            backend.shutdown()
        trace = ReplayTrace.from_tracer(tracer)
        res = replay(trace)  # same (recorded) nwait
        drift = compare(trace, res)
        assert drift["epochs"] == 4
        assert drift["fresh_exact_rate"] == 1.0, (trace.epochs, res.epochs)
        # recorded walls carry real process/pickle overhead the
        # injected delays cannot; the drift bound is the honest claim
        assert drift["wall_drift_max_s"] < 0.12, drift

    def test_autotuner_agrees_with_model_optimal_nwait(self):
        """A seeded-lognormal fleet (6 fast workers, 2 heavy
        stragglers) is fitted into a PoolLatencyModel; the sim
        autotuner — running the REAL pool loop on virtual time —
        recommends the same nwait as the model's analytic
        ``optimal_nwait``, and so does a sweep over the RAW lognormal
        fleet (not the fitted model), so the agreement is not an
        artifact of sharing distributions."""
        n = 8
        # a pronounced service floor (tight lognormal around 50 ms)
        # makes the utility landscape sharply peaked at k=6: waiting
        # for all six fast workers amortizes the floor, the two 1 s
        # stragglers poison anything deeper — every estimator must
        # land on 6, regardless of its tail family
        fleet = faults.compose(
            faults.seeded_lognormal(0.05, 0.05, seed=5),
            faults.straggler((6, 7), 1.0),
        )
        model = PoolLatencyModel(n, seed=2)
        for e in range(150):
            for i in range(n):
                model.observe(i, fleet(i, e))
        rec = recommend_nwait(model, floor=2, epochs=200, seed=9)
        assert rec["agree"], rec
        assert rec["sim_nwait"] == model.optimal_nwait(kmin=2) == 6
        raw = sweep_nwait(fleet, n_workers=n, epochs=120, floor=2)
        assert raw.best == 6

    # The virtual-to-wall speedup claim IS a wall-clock measurement
    # (the sanctioned kind: a GROSS ceiling on how much real time the
    # simulator may burn — the old < 2.0 s bound had ~6% headroom over
    # the ~1.9 s baseline on a loaded dev box, i.e. it was itself the
    # flake class GC008 exists to kill).
    # graftcheck: real-smoke
    def test_1k_epochs_wall_bounded_bit_identical(self):
        """Real pool.py code on the virtual clock: 1k epochs of a
        16-worker lognormal fleet well inside a 10 s gross wall
        ceiling, repochs bit-identical across two runs."""

        def run():
            be = SimBackend(
                _echo, 16,
                delay_fn=faults.seeded_lognormal(0.01, 1.0, seed=3),
            )
            pool = AsyncPool(16)
            reps = [
                asyncmap(pool, np.zeros(1), be, nwait=12).copy()
                for _ in range(1000)
            ]
            waitall(pool, be)
            return np.stack(reps), be.clock.now()

        t0 = time.perf_counter()
        reps1, v1 = run()
        wall = time.perf_counter() - t0
        assert wall < 10.0, f"1k sim epochs took {wall:.2f}s wall"
        reps2, v2 = run()
        assert (reps1 == reps2).all()
        assert v1 == v2
        assert v1 > 10.0  # simulated far more virtual than wall time
