"""Training checkpoint/resume: model pytrees + pool bookkeeping together
(utils/train_checkpoint.py). The reference's only resume hook is the
``epoch0`` kwarg (SURVEY §5 'Checkpoint / resume: absent')."""

import numpy as np
import pytest

from mpistragglers_jl_tpu import AsyncPool, LocalBackend, asyncmap, waitall
from mpistragglers_jl_tpu.utils import TrainCheckpointer, load_state_dict

import jax.numpy as jnp


def test_pytree_and_pool_roundtrip(tmp_path):
    ckpt = TrainCheckpointer(tmp_path / "ck")
    pool = AsyncPool(3, epoch0=5)
    backend = LocalBackend(lambda i, p, e: p + i, 3)
    try:
        for _ in range(4):
            asyncmap(pool, np.zeros(2), backend, nwait=3)
        waitall(pool, backend)
    finally:
        backend.shutdown()
    state = {
        "w": jnp.arange(6.0).reshape(2, 3),
        "opt": {"mu": jnp.ones(3), "step": 7},
    }
    d = ckpt.save(9, state, pool=pool)
    assert ckpt.latest_step() == 9
    back, pool_state, step = ckpt.restore()
    assert step == 9
    np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(state["w"]))
    np.testing.assert_array_equal(
        np.asarray(back["opt"]["mu"]), np.ones(3)
    )
    assert int(back["opt"]["step"]) == 7
    pool2 = load_state_dict(pool_state)
    assert pool2.epoch == pool.epoch == 9
    assert pool2.epoch0 == 5
    np.testing.assert_array_equal(pool2.repochs, pool.repochs)
    np.testing.assert_allclose(pool2.latency, pool.latency)
    assert d.endswith("step_9")


def test_active_pool_refused_unless_allowed(tmp_path):
    ckpt = TrainCheckpointer(tmp_path / "ck")
    pool = AsyncPool(2)
    backend = LocalBackend(
        lambda i, p, e: p, 2,
        delay_fn=lambda i, e: 0.2 if i == 1 else 0.0,
    )
    try:
        asyncmap(pool, np.zeros(1), backend, nwait=1)
        assert pool.active[1]
        with pytest.raises(RuntimeError, match="still active"):
            ckpt.save(1, {"w": jnp.zeros(1)}, pool=pool)
        ckpt.save(1, {"w": jnp.zeros(1)}, pool=pool, allow_active=True)
        _, pool_state, _ = ckpt.restore(1)
        pool2 = load_state_dict(pool_state)
        assert not pool2.active.any()  # in-flight work dropped on restore
        waitall(pool, backend)
    finally:
        backend.shutdown()


def test_keep_prunes_old_steps(tmp_path):
    ckpt = TrainCheckpointer(tmp_path / "ck", keep=2)
    for s in (1, 2, 3, 4):
        ckpt.save(s, {"x": jnp.full(1, float(s))})
    assert ckpt.steps() == [3, 4]
    with pytest.raises(FileNotFoundError):
        TrainCheckpointer(tmp_path / "empty").restore()


def test_rollback_save_is_not_self_destructed(tmp_path):
    # saving a LOWER step after a rollback must not delete itself
    import os

    ckpt = TrainCheckpointer(tmp_path / "ck", keep=2)
    ckpt.save(10, {"x": jnp.zeros(1)})
    ckpt.save(20, {"x": jnp.ones(1)})
    d = ckpt.save(6, {"x": jnp.full(1, 6.0)})
    assert os.path.isdir(d)
    assert 6 in ckpt.steps() and len(ckpt.steps()) == 2
    state, _, step = ckpt.restore(6)
    assert float(np.asarray(state["x"])[0]) == 6.0 and step == 6


def test_resume_matches_uninterrupted_training(tmp_path):
    """Save at epoch 5, restore into a fresh coordinator, continue — the
    final weights and epoch numbering match a run that never stopped."""

    def make_backend():
        return LocalBackend(
            lambda i, w, e: (w - 0.1 * (w - i)) / 1.0, 4
        )

    def train(pool, backend, w, epochs):
        for _ in range(epochs):
            asyncmap(pool, w, backend, nwait=4)
            w = np.mean([np.asarray(r) for r in pool.results], axis=0)
        waitall(pool, backend)
        return w

    # uninterrupted: 10 epochs
    b1 = make_backend()
    try:
        w_full = train(AsyncPool(4), b1, np.zeros(3), 10)
    finally:
        b1.shutdown()

    # interrupted: 5 epochs, checkpoint, "crash", restore, 5 more
    ckpt = TrainCheckpointer(tmp_path / "ck")
    b2 = make_backend()
    try:
        pool = AsyncPool(4)
        w_half = train(pool, b2, np.zeros(3), 5)
        ckpt.save(5, {"w": jnp.asarray(w_half)}, pool=pool)
    finally:
        b2.shutdown()
    del pool, w_half

    state, pool_state, step = ckpt.restore()
    pool3 = load_state_dict(pool_state)
    assert step == 5 and pool3.epoch == 5
    b3 = make_backend()
    try:
        w_resumed = train(pool3, b3, np.asarray(state["w"]), 5)
    finally:
        b3.shutdown()
    np.testing.assert_allclose(w_resumed, w_full, rtol=1e-6)
    assert pool3.epoch == 10  # epoch numbering continued, not restarted


@pytest.mark.slow
def test_1f1b_pipeline_resume_matches_uninterrupted(tmp_path):
    """Checkpoint/resume composes with the 1F1B pipeline train step:
    save mid-training, restore into a fresh step function, and the
    resumed trajectory matches the uninterrupted one exactly."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpistragglers_jl_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from mpistragglers_jl_tpu.parallel import make_mesh
    from mpistragglers_jl_tpu.parallel.pipeline import (
        make_pipeline_train_step,
        shard_params_pipeline,
    )
    from mpistragglers_jl_tpu.utils.train_checkpoint import TrainCheckpointer

    cfg = TransformerConfig(
        vocab=31, d_model=16, n_heads=2, n_layers=4, d_ff=32
    )
    mesh = make_mesh((2, 2), ("dp", "pp"))
    step = make_pipeline_train_step(
        cfg, mesh, n_microbatch=2, lr=0.1, schedule="1f1b"
    )
    rng = np.random.default_rng(0)
    data = rng.integers(0, cfg.vocab, (4, 9))
    place = lambda a: jax.device_put(
        jnp.asarray(a, jnp.int32), NamedSharding(mesh, P("dp"))
    )
    toks, tgts = place(data[:, :-1]), place(data[:, 1:])

    params = shard_params_pipeline(init_params(cfg, seed=1), cfg, mesh)

    # uninterrupted: 6 steps straight
    ref = params
    for _ in range(6):
        ref, _ = step(ref, toks, tgts)

    # interrupted: 3 steps, checkpoint, "restart", 3 more
    ckpt = TrainCheckpointer(tmp_path / "pp")
    cur = params
    for _ in range(3):
        cur, _ = step(cur, toks, tgts)
    ckpt.save(3, cur)

    # target= restores with the live pytree's shardings (the library's
    # own re-placement path)
    restored, _, step_no = ckpt.restore(target=cur)
    assert step_no == 3
    for _ in range(3):
        restored, _ = step(restored, toks, tgts)

    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(ref)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )
