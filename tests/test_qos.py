"""Multi-tenant QoS plane: DRR exactness, page-quota reclaim, the shed
and hedge-entitlement doors, and the measured 10x-flood isolation claim.

Fairness is a measured claim here, not prose: the deficit scheduler's
2:1 weight ratio admits EXACTLY 2:1 over a saturated window, deficits
carry so a short-changed tenant catches up exactly, quota reclaim
never touches a page a live holder reads (pool drains to baseline for
both tenants), and the headline — tenant C flooding 10x its token
budget moves compliant tenants' p99 TTFT by less than a pinned
epsilon while fleet utilization stays above a work-conservation floor
— replays bit-identically on VirtualClock (sim-pure by construction;
the jax half reuses the tiny test_serving_paged configs).
"""

import heapq

import numpy as np
import pytest

from mpistragglers_jl_tpu.qos import (
    DeficitScheduler,
    TenantContract,
    TenantRegistry,
    TokenBucket,
)

# --------------------------------------------------------------------------
# contracts + token buckets (pure)
# --------------------------------------------------------------------------


def test_contract_validation_refuses_by_name():
    with pytest.raises(ValueError, match="SLO class"):
        TenantContract("x", cls="golden")
    with pytest.raises(ValueError, match="weight"):
        TenantContract("x", weight=0.0)
    with pytest.raises(ValueError, match="burst without rate"):
        TenantContract("x", burst=10.0)
    with pytest.raises(ValueError, match="page quota"):
        TenantContract("x", pages=0)
    with pytest.raises(ValueError, match="hedge entitlement"):
        TenantContract("x", hedges=-1)
    reg = TenantRegistry([TenantContract("a")])
    with pytest.raises(ValueError, match="already registered"):
        reg.add(TenantContract("a"))
    with pytest.raises(KeyError, match="unknown tenant 'ghost'"):
        reg.get("ghost")


def test_sheddable_follows_class():
    assert TenantContract("x", cls="batch").sheddable
    assert not TenantContract("x", cls="latency").sheddable
    assert not TenantContract("x", cls="throughput").sheddable


def test_token_bucket_refill_is_pure_in_injected_now():
    b = TokenBucket(10.0, 20.0)
    assert b.take(20, 0.0)          # starts full
    assert not b.take(1, 0.0)       # empty, no time passed
    assert b.take(10, 1.0)          # 10 tokens refilled over 1s
    assert b.level(100.0) == 20.0   # refill caps at burst
    # time never flows backwards through the bucket
    assert b.level(50.0) == 20.0


def test_aggregate_rate_unbounded_when_any_tenant_unlimited():
    reg = TenantRegistry([
        TenantContract("a", rate=10.0), TenantContract("b", rate=5.0),
    ])
    assert reg.aggregate_rate() == 15.0
    reg2 = TenantRegistry([
        TenantContract("a", rate=10.0), TenantContract("b"),
    ])
    assert reg2.aggregate_rate() is None


# --------------------------------------------------------------------------
# DeficitScheduler exactness (pure)
# --------------------------------------------------------------------------


def _drr(weights, **kw):
    reg = TenantRegistry([
        TenantContract(t, weight=w) for t, w in weights.items()
    ])
    return DeficitScheduler(reg, **kw)


def test_weights_two_to_one_admit_exactly_two_to_one():
    """The ISSUE's exactness claim: weights 2:1 over a saturated
    window of uniform requests admit EXACTLY 2:1 — the full pick
    sequence is the weighted rotation a, a, b, ..."""
    drr = _drr({"a": 2.0, "b": 1.0})
    for i in range(12):
        drr.enqueue("a", f"a{i}", 5.0)
        drr.enqueue("b", f"b{i}", 5.0)
    seq = [drr.pick()[0] for _ in range(12)]
    assert seq == ["a", "a", "b"] * 4
    assert seq.count("a") == 2 * seq.count("b")


def test_deficits_carry_while_backlogged():
    """A tenant whose head costs more than one quantum is NOT starved:
    the visit's credit carries and it is served exactly when the
    accumulated deficit covers the cost."""
    drr = _drr({"x": 1.0, "y": 1.0}, quantum_unit=4.0)
    for i in range(3):
        drr.enqueue("x", f"x{i}", 6.0)
        drr.enqueue("y", f"y{i}", 6.0)
    # round 1 grants 4 < 6 to each (deficits carry at 4); round 2
    # grants again: 8 >= 6 serves both, leaving exactly 2
    t, item, c = drr.pick()
    assert (t, item) == ("x", "x0")
    assert drr.deficit("x") == 2.0
    t, item, _ = drr.pick()
    assert (t, item) == ("y", "y0")
    assert drr.deficit("y") == 2.0
    # the carried 2 + one fresh quantum = 6: served with zero credit
    # left — catch-up is exact, never approximate
    assert drr.pick()[1] == "x1"
    assert drr.deficit("x") == 0.0


def test_idle_credit_forfeited_at_reentry_not_at_empty():
    """Credit never survives an idle period — but the forfeit fires
    when the tenant RE-ENTERS the rotation (fresh enqueue onto an
    empty queue), not at the emptying pick, so a restore() of a
    failed pick keeps its exact carry."""
    drr = _drr({"x": 1.0, "y": 1.0}, quantum_unit=100.0)
    drr.enqueue("x", "x0", 1.0)
    drr.enqueue("y", "y0", 1.0)
    assert drr.pick()[0] == "x"
    assert drr.deficit("x") == 99.0  # carried until reentry
    drr.enqueue("x", "x1", 1.0)
    assert drr.deficit("x") == 0.0  # idle time never banks


def test_restore_after_emptying_pick_keeps_carried_credit():
    """The failed-admission contract is exact even when the pick
    emptied the queue: restore() reinstates the pre-pick deficit
    (leftover + refunded cost), so the tenant's catch-up credit never
    silently evaporates on a deferral."""
    drr = _drr({"a": 1.0, "b": 1.0}, quantum_unit=30.0)
    drr.enqueue("a", "a0", 40.0)
    t, item, c = drr.pick()  # two visits accrue 60, serve, 20 left
    assert (t, item) == ("a", "a0") and drr.deficit("a") == 20.0
    drr.restore(t, item, c)
    assert drr.deficit("a") == 60.0  # exactly the pre-pick credit
    # the retry serves from the carry alone, no fresh grant needed
    assert drr.pick()[1] == "a0"
    assert drr.deficit("a") == 20.0


def test_work_conserving_lone_tenant_gets_everything():
    """Idle capacity always serves whoever is queued: a lone
    backlogged tenant is served on every pick regardless of weight."""
    drr = _drr({"x": 0.25, "y": 4.0})
    for i in range(5):
        drr.enqueue("x", i, 100.0)
    assert [drr.pick()[0] for _ in range(5)] == ["x"] * 5
    assert drr.pick() is None


def test_restore_refunds_and_requeues_front():
    drr = _drr({"a": 1.0, "b": 1.0})
    drr.enqueue("a", "a0", 5.0)
    drr.enqueue("a", "a1", 5.0)
    t, item, c = drr.pick()
    assert item == "a0"
    d = drr.deficit("a")
    drr.restore(t, item, c)
    assert drr.deficit("a") == d + c  # charge refunded
    assert drr.total == 2
    assert drr.pick()[1] == "a0"  # front of the queue, not the back


def test_skip_passes_over_tenant_without_charge():
    drr = _drr({"a": 2.0, "b": 1.0})
    drr.enqueue("a", "a0", 5.0)
    drr.enqueue("b", "b0", 5.0)
    t, item, _ = drr.pick(skip={"a"})
    assert (t, item) == ("b", "b0")
    assert drr.backlog("a") == 1
    assert drr.pick()[0] == "a"


def test_unknown_tenant_enqueue_refused_by_name():
    drr = _drr({"a": 1.0})
    with pytest.raises(KeyError, match="unknown tenant 'ghost'"):
        drr.enqueue("ghost", "x", 1.0)


def test_remove_and_clear():
    drr = _drr({"a": 1.0})
    drr.enqueue("a", "a0", 1.0)
    drr.enqueue("a", "a1", 1.0)
    assert drr.remove("a1") and not drr.remove("a1")
    assert drr.total == 1
    drr.clear()
    assert drr.total == 0 and drr.pick() is None


# --------------------------------------------------------------------------
# the scheduler plane (jax, tiny configs)
# --------------------------------------------------------------------------

jax = pytest.importorskip("jax")

from mpistragglers_jl_tpu.models.decode import generate_ring_dense  # noqa: E402
from mpistragglers_jl_tpu.models.serving import ServingScheduler  # noqa: E402
from mpistragglers_jl_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    init_params,
)

CFG = TransformerConfig(
    vocab=61, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2, d_ff=128,
    attn_window=6,
)
PARAMS = init_params(CFG, seed=11)
# wide-window config: horizon Tp + max_new + n_inner fits W=24, so
# requests never wrap and their covered prefix pages are COLD-cache
# eligible at retirement (the reclaim scenarios)
WCFG = TransformerConfig(
    vocab=61, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2, d_ff=128,
    attn_window=24,
)
WPARAMS = init_params(WCFG, seed=13)
RNG = np.random.default_rng(77)


def _prompt(n, vocab=61):
    return RNG.integers(1, vocab, size=n).astype(np.int32)


def _registry(**tenants):
    return TenantRegistry([
        TenantContract(t, **kw) for t, kw in tenants.items()
    ])


def test_scheduler_submit_requires_known_tenant():
    reg = _registry(a={})
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=4,
                             prompt_chunk=8, max_prompt=32, qos=reg)
    with pytest.raises(ValueError, match="needs tenant="):
        sched.submit(_prompt(4), max_new=4)
    with pytest.raises(KeyError, match="unknown tenant 'ghost'"):
        sched.submit(_prompt(4), max_new=4, tenant="ghost")
    with pytest.raises(ValueError, match="at least one TenantContract"):
        ServingScheduler(PARAMS, CFG, slots=2, n_inner=4,
                         prompt_chunk=8, max_prompt=32,
                         qos=TenantRegistry())


def test_qos_streams_match_oracle_token_for_token():
    """The oracle identity survives DRR admission: every stream of a
    mixed-tenant paged qos scheduler equals generate_ring_dense."""
    reg = _registry(a=dict(weight=2.0), b=dict(weight=1.0))
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=4,
                             prompt_chunk=8, max_prompt=32,
                             page_tokens=2, qos=reg)
    cases = [(_prompt(3), 9), (_prompt(11), 6), (_prompt(8), 7),
             (_prompt(1), 5), (_prompt(6), 12), (_prompt(9), 4)]
    reqs = [
        sched.submit(p, max_new=n, tenant="a" if i % 2 else "b")
        for i, (p, n) in enumerate(cases)
    ]
    sched.run()
    for req, (p, n) in zip(reqs, cases):
        toks = generate_ring_dense(
            PARAMS, np.asarray(p)[None], n, CFG
        )
        assert req.tokens == [int(t) for t in np.asarray(toks)[0]]
    sched.pool.check()


def test_drr_admission_order_two_to_one_on_the_real_scheduler():
    """slots=1 makes admission order observable: uniform queued
    requests from a (weight 2) and b (weight 1) admit in the exact
    weighted rotation a, a, b — the scheduler consults the DRR pick,
    not FIFO."""
    reg = _registry(a=dict(weight=2.0), b=dict(weight=1.0))
    sched = ServingScheduler(PARAMS, CFG, slots=1, n_inner=4,
                             prompt_chunk=8, max_prompt=32, qos=reg)
    reqs = []
    for i in range(6):
        reqs.append((
            "a", sched.submit(_prompt(4), max_new=4, tenant="a")
        ))
    for i in range(3):
        reqs.append((
            "b", sched.submit(_prompt(4), max_new=4, tenant="b")
        ))
    sched.run()
    order = sorted(reqs, key=lambda tr: tr[1].admitted_tick)
    assert [t for t, _ in order] == ["a", "a", "b"] * 3


def test_retired_prefix_pages_go_cold_and_reshare():
    """A retiring request's covered prefix pages stay RESIDENT (cold,
    attributed to the tenant) and a later same-prefix admission shares
    them — the prefill skip survives the retirement, which FIFO-era
    residency scoping never allowed."""
    reg = _registry(a=dict())
    sched = ServingScheduler(WPARAMS, WCFG, slots=2, n_inner=4,
                             prompt_chunk=4, max_prompt=32,
                             page_tokens=4, cache_pages=24, qos=reg)
    p = _prompt(8)  # 2 fully covered pages at P=4
    r1 = sched.submit(p, max_new=4, tenant="a")
    sched.run()
    assert r1.finished
    assert len(sched._cold) == 2  # the covered pages stayed
    assert sched._cold_count["a"] == 2
    used_cold = sched.pool.used
    share0 = sched.pool.share_hits
    r2 = sched.submit(p, max_new=4, tenant="a")
    sched.run()
    # the admission share cap (Tp-1)//P applies to cold pages exactly
    # as to hot ones — the prompt's LAST token must prefill, so of the
    # two covered pages only the first re-shares
    assert sched.pool.share_hits == share0 + 1
    # the oracle identity holds through the cold-page share
    toks = generate_ring_dense(WPARAMS, np.asarray(p)[None], 4, WCFG)
    assert r2.tokens == [int(t) for t in np.asarray(toks)[0]]
    # warm transfer moved them back to cold at r2's retirement
    assert len(sched._cold) == 2 and sched.pool.used == used_cold
    sched.pool.check()


def test_page_quota_defers_tenant_but_never_the_rotation():
    """Tenant b's quota cannot fit two concurrent requests: its second
    request DEFERS while tenant a keeps admitting — per-tenant
    backpressure, not FIFO head-of-line blocking — and admits once
    b's first retires."""
    # each request: horizon 8 + 4 + 4 = 16 -> 4 pages at P=4
    reg = _registry(a=dict(weight=1.0), b=dict(weight=1.0, pages=4))
    sched = ServingScheduler(WPARAMS, WCFG, slots=3, n_inner=4,
                             prompt_chunk=4, max_prompt=32,
                             page_tokens=4, cache_pages=32, qos=reg)
    b1 = sched.submit(_prompt(8), max_new=4, tenant="b")
    b2 = sched.submit(_prompt(8, 53), max_new=4, tenant="b")
    a1 = sched.submit(_prompt(8, 47), max_new=4, tenant="a")
    sched.step()
    # b1 and a1 admitted; b2 over quota (4 held + 4 planned > 4)
    assert b1.admitted_tick == 1 and a1.admitted_tick == 1
    assert b2.admitted_tick is None
    sched.run()
    assert b2.finished  # admitted after b1's pages came back
    assert b2.admitted_tick > 1
    sched.pool.check()


def test_quota_reclaim_never_touches_a_shared_page():
    """The COW-aware reclaim contract: pool pressure evicts COLD
    refcount-1 pages (the flooding tenant's first), and a prefix page
    a compliant holder still pins (refcount > 1) is NEVER yanked —
    then the pool drains to baseline for both tenants."""
    reg = _registry(a=dict(weight=1.0),
                    c=dict(weight=1.0, pages=12))
    sched = ServingScheduler(WPARAMS, WCFG, slots=4, n_inner=4,
                             prompt_chunk=4, max_prompt=32,
                             page_tokens=4, cache_pages=13, qos=reg)
    shared_prompt = _prompt(8)
    # a1 decodes long and a2 SHARES its prefix pages: refcount 2
    a1 = sched.submit(shared_prompt, max_new=20, tenant="a")
    sched.step()
    a2 = sched.submit(shared_prompt, max_new=20, tenant="a")
    sched.step()
    assert sched.pool.share_hits >= 1
    shared_pids = [
        int(pid) for pid in sched._pt_host[0][:2]
        if sched.pool.refcount(int(pid)) > 1
    ]
    assert shared_pids, "the prefix pages must actually be shared"
    # c churns short requests: each retirement leaves cold pages, and
    # under a 12-page pool the next admission must RECLAIM them
    evicted_before = len(sched._cold)
    for i in range(4):
        sched.submit(_prompt(8, vocab=31 + i), max_new=4, tenant="c")
    for _ in range(40):
        sched.step()
        if all(r.finished for r in (a1, a2)):
            break
    sched.run()
    # the shared pages were never evicted mid-flight: both sharers'
    # streams completed and equal the oracle
    toks = generate_ring_dense(
        WPARAMS, np.asarray(shared_prompt)[None], 20, WCFG
    )
    want = [int(t) for t in np.asarray(toks)[0]]
    assert a1.tokens == want and a2.tokens == want
    # pool drains to baseline for BOTH tenants: evict the cold tail
    # and nothing is left allocated or reserved
    sched.pool.check()
    while sched._evict_cold_page():
        pass
    assert sched.pool.used == 0 and sched.pool.reserved == 0
    assert sched._tenant_pages == {} and sched._cold_count == {}
    sched.pool.check()


def test_adoption_reclaims_cold_pages_instead_of_parking():
    """The two-tier liveness contract under qos: a migration adoption
    whose destination pool is held up by COLD pages reclaims them
    (cache, not entitlement) instead of refusing — a captured stream
    is resident nowhere while its migration waits."""
    reg = _registry(a=dict())
    kw = dict(slots=2, n_inner=4, prompt_chunk=4, max_prompt=32,
              page_tokens=4, qos=reg)
    src = ServingScheduler(WPARAMS, WCFG, cache_pages=24, **kw)
    # destination: 9 usable pages, 8 of them soon cold (2 retired
    # requests x 4 pages each, 2 registered + 2 freed per request)
    dst = ServingScheduler(WPARAMS, WCFG, cache_pages=9, **kw)
    for i in range(2):
        dst.submit(_prompt(8, 41 + i), max_new=4, tenant="a")
        dst.run()
    assert len(dst._cold) == 4 and dst.pool.free < 8
    r = src.submit(_prompt(8, 59), max_new=12, tenant="a")
    for _ in range(3):
        src.step()
    assert r.tokens and not r.finished
    state = src.export_page_state(r)
    cold_before = dict(dst._cold)
    assert dst.can_adopt_state(state)  # reclaim headroom, not a park
    # the PREDICATE only counted the headroom — probing feasibility
    # must never drain a replica's cold prefix cache as a side effect
    # (the router probes every replica per step)
    assert dst._cold == cold_before
    dst.adopt_page_state(state)  # the adopt itself reclaims
    dst.run()
    assert r.finished
    toks = generate_ring_dense(
        WPARAMS, np.asarray(state["prompt"])[None], 12, WCFG
    )
    assert r.tokens == [int(t) for t in np.asarray(toks)[0]]
    dst.pool.check()


def test_cancel_returns_quota_everywhere():
    """Cancel at every lifecycle stage returns the tenant's quota
    attribution: queued, mid-admission, decoding."""
    reg = _registry(a=dict(pages=8))
    sched = ServingScheduler(WPARAMS, WCFG, slots=1, n_inner=4,
                             prompt_chunk=4, max_prompt=32,
                             page_tokens=4, cache_pages=24, qos=reg)
    r1 = sched.submit(_prompt(8), max_new=8, tenant="a")
    r2 = sched.submit(_prompt(8, 43), max_new=8, tenant="a")
    assert sched.cancel(r2) and r2.reason == "cancelled"  # queued
    sched.step()
    assert sched.cancel(r1)  # decoding (or mid-admission)
    assert sched._tenant_usage("a") == len(sched._cold)
    while sched._evict_cold_page():
        pass
    assert sched.pool.used == 0
    sched.pool.check()


# --------------------------------------------------------------------------
# the router + sim plane (numpy-only, virtual time)
# --------------------------------------------------------------------------

from mpistragglers_jl_tpu.models.router import RequestRouter  # noqa: E402
from mpistragglers_jl_tpu.obs import MetricsRegistry  # noqa: E402
from mpistragglers_jl_tpu.obs.flight import FlightRecorder  # noqa: E402
from mpistragglers_jl_tpu.sim import (  # noqa: E402
    SimReplica,
    VirtualClock,
    lognormal_ticks,
    poisson_arrivals,
    run_router_day,
    sweep_tenant_weights,
)

# the flood scenario every headline claim shares: a 4-replica fleet at
# ~70% compliant load, tenant c contracted to ~10% and flooding 10x it
_N_REP, _SLOTS, _NI, _TICK = 4, 4, 8, 0.02
_PLEN, _CHUNK, _MNEW = 96, 64, 32
_AB_RATE, _C_RATE = 70.0, 13.0
_TOK = _PLEN + _MNEW
_EPS_S = 0.05      # pinned isolation epsilon (measured ~0.011)
_UTIL_FLOOR = 0.9  # pinned work-conservation floor (measured ~0.96)


def _flood_registry():
    return TenantRegistry([
        TenantContract("a", cls="latency", weight=4.0, ttft_slo=0.5),
        TenantContract("b", cls="throughput", weight=4.0),
        TenantContract("c", cls="batch", weight=1.0,
                       rate=_C_RATE * _TOK * 1.2,
                       burst=_C_RATE * _TOK * 2.0),
    ])


def _flood_streams(flood: bool):
    """Compliant a+b arrivals are the IDENTICAL stream in both days
    (separate seeded generators merged by time), so the epsilon claim
    compares the same requests under different co-tenant behavior."""
    ab = poisson_arrivals(
        _AB_RATE, n=2100, seed=11, prompt_len=_PLEN, max_new=_MNEW,
        tenants={"a": 0.5, "b": 0.5},
    )
    c = poisson_arrivals(
        _C_RATE * (10 if flood else 1),
        n=3000 if flood else 300, seed=29,
        prompt_len=_PLEN, max_new=_MNEW, tenants={"c": 1.0},
    )
    return heapq.merge(ab, c, key=lambda x: x.t)


def _flood_day(flood: bool, *, qos=True, registry=None, flight=None):
    reg = _flood_registry() if qos else None
    clock = VirtualClock()
    reps = [
        SimReplica(clock, slots=_SLOTS, n_inner=_NI,
                   prompt_chunk=_CHUNK, qos=reg,
                   tick_s=lognormal_ticks(_TICK, 0.2, seed=1009 + i))
        for i in range(_N_REP)
    ]
    router = RequestRouter(reps, policy="least_loaded", clock=clock,
                           qos=reg, registry=registry, flight=flight)
    report = run_router_day(router, _flood_streams(flood))
    util = sum(r.busy_s for r in reps) / (_N_REP * report.virtual_s)
    return report, util, router


def test_tenant_mix_never_moves_arrival_times():
    """The r16 long_share pattern extended: the tenant label rides the
    SAME coin, so arrival times (and prompt classes) are bit-identical
    at every tenant mix, including none."""
    bare = [a.t for a in poisson_arrivals(50, n=400, seed=3)]
    mixed = list(poisson_arrivals(
        50, n=400, seed=3, tenants={"x": 0.6, "y": 0.4}
    ))
    assert bare == [a.t for a in mixed]
    assert {a.tenant for a in mixed} == {"x", "y"}
    with pytest.raises(ValueError, match="sum to 1"):
        list(poisson_arrivals(50, n=4, seed=0,
                              tenants={"x": 0.5, "y": 0.4}))


def test_shed_requests_are_named_and_counted():
    """An over-budget batch tenant's requests come back immediately
    with outcome == "shed": named, counted per tenant+reason in the
    registry, stamped into the flight ring — and never routed."""
    registry = MetricsRegistry()
    flight = FlightRecorder(256)
    report, _, router = _flood_day(
        True, registry=registry, flight=flight
    )
    assert report.n_shed > 500
    assert report.outcomes["shed"] == report.n_shed
    per = report.per_tenant()
    assert per["c"]["shed"] == report.n_shed
    assert per["a"]["shed"] == 0 and per["b"]["shed"] == 0
    shed = [r for r in report.requests if r.outcome == "shed"]
    assert all(r.replica is None and r.tenant == "c" for r in shed)
    prom = registry.to_prometheus()
    assert 'qos_shed_total{reason="budget",tenant="c"}' in prom
    assert 'router_requests_total{' in prom and 'tenant="a"' in prom
    doc = flight.snapshot()
    assert any(
        e.get("name") == "qos shed" for e in doc["traceEvents"]
    ), "shed must stamp a flight instant event"


def test_flood_isolation_epsilon_and_work_conservation_floor():
    """THE acceptance claim: tenant c flooding 10x its token budget
    moves compliant tenants' p99 TTFT by less than the pinned epsilon
    while fleet utilization stays above the work-conservation floor,
    bit-identically across two replays."""
    base, _, _ = _flood_day(False)
    fl1, util, _ = _flood_day(True)
    fl2, _, _ = _flood_day(True)
    assert fl1.digest() == fl2.digest()  # the bit-identity witness
    pb, pf = base.per_tenant(), fl1.per_tenant()
    for t in ("a", "b"):
        shift = abs(pf[t]["p99_ttft_s"] - pb[t]["p99_ttft_s"])
        assert shift < _EPS_S, (
            f"compliant tenant {t} p99 moved {shift * 1e3:.1f}ms "
            f">= the pinned {_EPS_S * 1e3:.0f}ms epsilon"
        )
    assert util >= _UTIL_FLOOR, (
        f"flood-day utilization {util:.3f} under the "
        f"{_UTIL_FLOOR} work-conservation floor"
    )
    assert fl1.dropped == 0


def test_drr_alone_beats_fifo_by_orders_of_magnitude():
    """Even WITHOUT the shed door (no token budgets), the deficit
    rotation bounds the compliant tail: under the same 10x flood,
    FIFO compliant p99 diverges (queues behind c) while DRR holds it
    within a second."""
    reg = TenantRegistry([
        TenantContract("a", weight=4.0),
        TenantContract("b", weight=4.0),
        TenantContract("c", weight=1.0),  # no rate: nothing sheds
    ])

    def day(qos_reg):
        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=_SLOTS, n_inner=_NI,
                       prompt_chunk=_CHUNK, qos=qos_reg,
                       tick_s=lognormal_ticks(_TICK, 0.2,
                                              seed=1009 + i))
            for i in range(_N_REP)
        ]
        router = RequestRouter(
            reps, policy="least_loaded", clock=clock, qos=qos_reg
        )
        return run_router_day(router, _flood_streams(True))

    drr_day = day(reg)
    fifo_day = day(None)
    for t in ("a", "b"):
        drr_p99 = drr_day.per_tenant()[t]["p99_ttft_s"]
        fifo_p99 = fifo_day.per_tenant()[t]["p99_ttft_s"]
        assert drr_p99 < 1.0 < fifo_p99, (t, drr_p99, fifo_p99)
        assert fifo_p99 / drr_p99 > 10.0


def test_hedge_isolation_entitlement_counted_and_refused():
    """A tenant's hedge_p99 re-dispatches draw from its OWN
    entitlement: outstanding hedge legs never exceed it, dues beyond
    it are refused and counted, and the other tenant's hedges (and
    slots) are untouched."""
    reg = TenantRegistry([
        TenantContract("a", weight=1.0, hedges=1),
        TenantContract("b", weight=1.0),  # unlimited
    ])
    clock = VirtualClock()
    # replica 0 wedged 50x: anything placed there misses the deadline
    reps = [
        SimReplica(clock, slots=2, n_inner=8, prompt_chunk=64,
                   qos=reg, tick_s=1.0 if i == 0 else 0.02)
        for i in range(3)
    ]
    router = RequestRouter(reps, policy="hedge_p99", ttft_slo=0.1,
                           clock=clock, qos=reg)
    rrs = [
        router.submit(96, 8, tenant="a" if i % 2 == 0 else "b")
        for i in range(12)
    ]
    max_out_a = 0
    for _ in range(3000):
        nt = router.next_event_at()
        if nt is None:
            break
        clock.run_until(nt)
        router.step()
        max_out_a = max(max_out_a, router._hedges_out.get("a", 0))
    assert all(r.finished for r in rrs)
    # the entitlement held at every step, and at least one due hedge
    # was refused by it while b's hedges fired freely
    assert max_out_a <= 1
    assert router.n_hedges_refused >= 1
    assert any(r.hedged for r in rrs if r.tenant == "b")
    # refused hedges never became legs: tenant a's extra dispatches
    # are bounded by the entitlement, so b's slots were never squeezed
    assert sum(r.hedged for r in rrs if r.tenant == "a") <= 1


def test_router_submit_requires_known_tenant():
    reg = TenantRegistry([TenantContract("a")])
    clock = VirtualClock()
    reps = [SimReplica(clock, qos=reg)]
    router = RequestRouter(reps, clock=clock, qos=reg)
    with pytest.raises(ValueError, match="needs tenant="):
        router.submit(8, 4)
    with pytest.raises(KeyError, match="unknown tenant 'ghost'"):
        router.submit(8, 4, tenant="ghost")


def test_budget_door_charges_int_prompts_at_full_length():
    """The sim protocol's bare-int prompt means "a prompt of that
    many tokens": the budget door must charge prompt + max_new, not
    np.size(int) == 1 — an undercharge would let a flood through."""
    assert RequestRouter._prompt_tokens(96) == 96
    assert RequestRouter._prompt_tokens(np.int64(96)) == 96
    assert RequestRouter._prompt_tokens(np.arange(7)) == 7
    reg = TenantRegistry([
        TenantContract("c", cls="batch", rate=50.0, burst=104.0),
    ])
    clock = VirtualClock()
    reps = [SimReplica(clock, qos=reg)]
    router = RequestRouter(reps, clock=clock, qos=reg)
    assert router.submit(96, 8, tenant="c").outcome != "shed"
    # the first submit drained the 104-token burst exactly; the next
    # is shed — with the np.size undercharge it would sail through
    assert router.submit(96, 8, tenant="c").outcome == "shed"


def test_non_sheddable_class_is_paced_not_shed():
    """An over-budget latency tenant is never shed: the request
    routes (counted in n_over_budget) and the DRR weight paces it."""
    reg = TenantRegistry([
        TenantContract("a", cls="latency", weight=1.0, rate=100.0,
                       burst=150.0, ttft_slo=1.0),
    ])
    clock = VirtualClock()
    reps = [SimReplica(clock, slots=4, n_inner=8, prompt_chunk=64,
                       qos=reg)]
    router = RequestRouter(reps, clock=clock, qos=reg)
    report = run_router_day(router, poisson_arrivals(
        20.0, n=100, seed=5, prompt_len=64, max_new=16,
        tenants={"a": 1.0},
    ))
    assert report.n_shed == 0
    assert router.n_over_budget > 0
    assert report.outcomes == {"ok": 100}


# --------------------------------------------------------------------------
# sweep_tenant_weights: refusals by name + a working sweep
# --------------------------------------------------------------------------


def _contracts(lat_slo=2.0, rates=(800.0, 800.0)):
    return [
        TenantContract("lat", cls="latency", weight=1.0,
                       rate=rates[0], ttft_slo=lat_slo),
        TenantContract("bat", cls="batch", weight=1.0, rate=rates[1]),
    ]


def test_sweep_refuses_infeasible_aggregate_budget():
    with pytest.raises(ValueError,
                       match="aggregate token budget.*capacity"):
        sweep_tenant_weights(
            contracts=_contracts(rates=(50_000.0, 50_000.0)),
            candidates=[{"lat": 1.0, "bat": 1.0}],
            requests=10,
        )


def test_sweep_refuses_latency_class_without_slo():
    contracts = [
        TenantContract("lat", cls="latency", rate=100.0),
        TenantContract("bat", cls="batch", rate=100.0),
    ]
    with pytest.raises(ValueError, match="latency-class tenant "
                                         "'lat' has no ttft_slo"):
        sweep_tenant_weights(
            contracts=contracts,
            candidates=[{"lat": 1.0, "bat": 1.0}], requests=10,
        )


def test_sweep_refuses_unbudgeted_tenant_and_bad_candidates():
    contracts = [
        TenantContract("lat", cls="latency", ttft_slo=1.0),
    ]
    with pytest.raises(ValueError, match="no token budget"):
        sweep_tenant_weights(contracts=contracts,
                             candidates=[{"lat": 1.0}], requests=10)
    with pytest.raises(ValueError, match="must name exactly"):
        sweep_tenant_weights(
            contracts=_contracts(),
            candidates=[{"lat": 1.0}], requests=10,
        )
    with pytest.raises(ValueError, match="must be > 0"):
        sweep_tenant_weights(
            contracts=_contracts(),
            candidates=[{"lat": 0.0, "bat": 1.0}], requests=10,
        )


def test_sweep_refuses_when_no_candidate_meets_the_slo():
    with pytest.raises(ValueError,
                       match="no candidate meets every latency"):
        sweep_tenant_weights(
            contracts=_contracts(lat_slo=1e-6),
            candidates=[{"lat": 1.0, "bat": 1.0}],
            requests=200, seed=0,
        )


def test_sweep_recommends_and_is_deterministic():
    kw = dict(
        contracts=_contracts(),
        candidates=[{"lat": 1.0, "bat": 1.0},
                    {"lat": 4.0, "bat": 1.0}],
        requests=400, seed=0,
    )
    out1 = sweep_tenant_weights(**kw)
    out2 = sweep_tenant_weights(**kw)
    assert out1["best"] in [c for c in kw["candidates"]]
    assert [e["score"] for e in out1["entries"]] == \
        [e["score"] for e in out2["entries"]]
    assert out1["aggregate_budget_tok_s"] < out1["capacity_tok_s"]
