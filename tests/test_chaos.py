"""Chaos plane (ISSUE 15): correlated faults, retry storms, overload
shedding, partitions — and the pinned survival invariants over sim/.

Four layers: (1) the new utils/faults builders (partition,
correlated_kill) are pure, picklable, and replay bit-identically on
SimBackend; (2) the router's partition/heal and overload-shed
machinery is pinned at the unit level (partition != death, rejoin
never double-retires, every shed is named, queues stay bounded);
(3) the chaos scenario catalog runs end-to-end through ChaosInjector
with every invariant held and a bit-identical ChaosReport digest
across replays — including the metastable-recovery claim (a retry
storm that drives offered load past 1 and subsides returns p99 to a
pinned factor of the pre-storm baseline); (4) the fleet controller
does not flap under a retry storm (hysteresis's first adversarial
test)."""

import heapq
import pickle

import numpy as np
import pytest

from mpistragglers_jl_tpu import AsyncPool, SimBackend, asyncmap, waitall
from mpistragglers_jl_tpu.chaos import (
    SCENARIOS,
    ChaosInjector,
    ChaosReport,
    InvariantViolation,
    ReplicaKill,
    get_scenario,
)
from mpistragglers_jl_tpu.models.router import RequestRouter
from mpistragglers_jl_tpu.qos import (
    SHED_ORDER,
    TenantContract,
    TenantRegistry,
    shed_rank,
)
from mpistragglers_jl_tpu.sim import (
    ReplicaPartition,
    RetryPolicy,
    SimReplica,
    VirtualClock,
    poisson_arrivals,
    run_router_day,
)
from mpistragglers_jl_tpu.utils import faults


def _echo(worker, payload, epoch):
    return payload + worker


# --------------------------------------------------------------------------
# utils/faults: partition + correlated_kill builders
# --------------------------------------------------------------------------


class TestFaultBuilders:
    GROUPS = [[0, 1], [2, 3], [4, 5], [6, 7]]

    def test_partition_window_semantics(self):
        """Members stall until the window closes (the result crosses
        the instant the partition heals); outsiders and epochs outside
        the window are instant."""
        p = faults.partition(
            [self.GROUPS[1]], 10, 16, epoch_s=0.5
        )
        assert p(2, 9) == 0.0          # before the window
        assert p(2, 10) == 3.0         # 6 epochs x 0.5 s left
        assert p(3, 15) == 0.5         # last epoch inside
        assert p(2, 16) == 0.0         # healed
        assert p(0, 12) == 0.0         # not a member
        assert faults.partition(
            [self.GROUPS[1]], 10, 16, epoch_s=0.5
        )(2, 12) == p(2, 12)           # pure in (worker, epoch)

    def test_partition_refusals(self):
        with pytest.raises(ValueError, match="from_epoch"):
            faults.partition([self.GROUPS[0]], 5, 5)
        with pytest.raises(ValueError, match="epoch_s"):
            faults.partition([self.GROUPS[0]], 1, 2, epoch_s=0.0)

    def test_correlated_kill_span_and_clamp(self):
        ck = faults.correlated_kill(
            self.GROUPS, epicenter=2, at_epoch=5, span=3
        )
        assert ck.killed_groups == [2, 3]  # clamped at the end
        assert ck(4, 4) == 0.0 and ck(4, 5) == 3600.0
        assert ck(7, 9) == 3600.0
        assert ck(0, 9) == 0.0  # outside the blast radius
        with pytest.raises(ValueError, match="epicenter"):
            faults.correlated_kill(
                self.GROUPS, epicenter=9, at_epoch=5
            )
        with pytest.raises(ValueError, match="span"):
            faults.correlated_kill(
                self.GROUPS, epicenter=0, at_epoch=5, span=0
            )

    @pytest.mark.parametrize("mk", [
        lambda g: faults.partition([g[1], g[2]], 3, 7, epoch_s=0.1),
        lambda g: faults.correlated_kill(
            g, epicenter=1, at_epoch=6, span=2
        ),
    ])
    def test_picklable_and_bit_identical_sim_replay(self, mk):
        """The kill_group contract: a pure picklable class whose
        schedule replays an asyncmap run on SimBackend bit-identically
        — repochs, event stream, and final virtual time all equal."""
        sched = mk(self.GROUPS)
        clone = pickle.loads(pickle.dumps(sched))
        grid = [(w, e) for w in range(8) for e in range(12)]
        assert [sched(w, e) for w, e in grid] == [
            clone(w, e) for w, e in grid
        ]

        def run(fn):
            be = SimBackend(_echo, 8, delay_fn=faults.compose(
                faults.seeded_lognormal(0.02, 0.5, seed=3), fn,
            ))
            pool = AsyncPool(8)
            reps = [
                asyncmap(pool, np.zeros(1), be, nwait=4).copy()
                for _ in range(10)
            ]
            waitall(pool, be)
            order = [
                (ev.worker, ev.epoch, ev.t_done) for ev in be.events
            ]
            return reps, order, be.clock.now()

        r1, o1, t1 = run(sched)
        r2, o2, t2 = run(clone)
        assert all((a == b).all() for a, b in zip(r1, r2))
        assert o1 == o2 and t1 == t2

    def test_fault_schedule_builders(self):
        s = (faults.FaultSchedule(seed=2)
             .partition([self.GROUPS[0]], 2, 4, epoch_s=0.1)
             .correlated_kill(self.GROUPS, epicenter=3, at_epoch=8))
        assert "partition" in repr(s) and "correlated_kill" in repr(s)
        fn = s.delay_fn
        assert fn(0, 2) > 0.0 and fn(6, 9) >= 3600.0
        assert fn(4, 2) == 0.0


# --------------------------------------------------------------------------
# router: partition != death, rejoin without double-retire
# --------------------------------------------------------------------------


def _mini_fleet(n=2, **kw):
    clock = VirtualClock()
    reps = [
        SimReplica(clock, slots=2, n_inner=4, prompt_chunk=64,
                   tick_s=0.01)
        for _ in range(n)
    ]
    router = RequestRouter(
        reps, policy="least_loaded", clock=clock, **kw
    )
    return clock, reps, router


def _drive(clock, router, until):
    while True:
        nt = router.next_event_at()
        if nt is None or nt > until:
            break
        clock.run_until(nt)
        router.step()
    clock.run_until(until)
    router.step()


class TestRouterPartition:
    def test_partition_keeps_ticking_and_heal_cancels_stale(self):
        """Heal BEFORE the stale leg finishes: the leg progressed
        behind the partition (partition != death — in-flight work
        burns capacity), the re-routed copy is authoritative, the
        stale leg is withdrawn, and the request completes exactly
        once."""
        clock, reps, router = _mini_fleet()
        rr = router.submit(64, 64)     # long decode on replica 0
        assert rr.replica == 0
        _drive(clock, router, 0.015)   # admitted, first chunk run
        leg0 = rr._legs[0][1]
        router.partition(0)
        assert rr.replica == 1 and rr.rerouted == 1
        assert not leg0.finished       # NOT cancelled: unreachable
        emitted_at_partition = leg0.n_emitted
        _drive(clock, router, 0.05)    # both replicas tick
        assert leg0.n_emitted > emitted_at_partition  # kept ticking
        router.heal(0)
        assert leg0.finished and leg0.reason == "cancelled"
        assert router.n_stale_cancelled == 1
        _drive(clock, router, 2.0)
        assert rr.finished and rr.outcome == "rerouted"
        assert router.n_completed == router.n_submitted == 1
        assert router.n_partitions == router.n_partitions_healed == 1

    def test_heal_after_stale_leg_finished_never_double_retires(self):
        """Heal AFTER the isolated side finished the leg: its tokens
        were unreachable when produced, the finished leg is discarded,
        and the request still completes exactly once (via the
        re-routed copy)."""
        clock, reps, router = _mini_fleet()
        rr = router.submit(64, 8)      # short request
        _drive(clock, router, 0.015)
        leg0 = rr._legs[0][1]
        router.partition(0)
        _drive(clock, router, 1.0)     # isolated side finishes leg0
        assert leg0.finished and leg0.reason == "length"
        assert rr.finished             # re-routed copy completed too
        n_done_before = router.n_completed
        router.heal(0)
        _drive(clock, router, 1.5)
        assert router.n_completed == n_done_before == 1
        assert router.n_stale_cancelled == 0  # nothing to withdraw
        assert reps[0].active == 0     # no zombie slot after rejoin

    def test_partition_refusals_and_probe_pinning(self):
        clock, reps, router = _mini_fleet()
        router.partition(0)
        with pytest.raises(ValueError, match="already partitioned"):
            router.partition(0)
        with pytest.raises(ValueError, match="not partitioned"):
            router.heal(1)
        # the health probe must not flip a partitioned replica back
        router.step()
        assert 0 not in router.routable_replicas
        router.heal(0)
        router.step()
        assert 0 in router.routable_replicas

    def test_partition_event_in_day_stream(self):
        """ReplicaPartition fires partition at t and heal at `until`
        on the clock — the whole day drains with a reconciled ledger,
        bit-identically."""

        def day():
            clock, reps, router = _mini_fleet(n=3)
            arr = poisson_arrivals(
                60.0, n=300, seed=11, prompt_len=64, max_new=16,
            )
            rep = run_router_day(
                router, arr,
                events=[ReplicaPartition(1.0, (2,), 2.5)],
            )
            return rep, router

        rep1, router1 = day()
        rep2, router2 = day()
        assert rep1.digest() == rep2.digest()
        assert rep1.dropped == 0
        assert router1.n_partitions == router1.n_partitions_healed == 1
        assert router1.n_completed == router1.n_submitted
        assert rep1.n_partitions == 1
        with pytest.raises(ValueError, match="heal after"):
            ReplicaPartition(2.0, (0,), 2.0)


# --------------------------------------------------------------------------
# router: overload shedding by name
# --------------------------------------------------------------------------


class TestOverloadShed:
    def test_soft_ceiling_sheds_classless_by_name(self):
        clock, reps, router = _mini_fleet(shed_depth=4)
        assert router.shed_depth_hard == 8  # default 2x soft
        shed = []
        for _ in range(30):
            rr = router.submit(64, 16)
            if rr.outcome == "shed":
                shed.append(rr)
        assert shed, "30 instant submits never crossed depth 4"
        assert all(r.shed_reason == "overload" for r in shed)
        assert all(r.finished and r.replica is None for r in shed)
        assert router.queue_depth <= 8
        assert router.n_shed == len(shed)

    def test_batch_sheds_before_interactive(self):
        """The QoS sheddability contract under overload: at the soft
        ceiling only the batch class sheds; interactive work keeps
        routing until the hard ceiling, then sheds with the hard
        reason — and every shed carries a reason either way."""
        reg = TenantRegistry([
            TenantContract("chat", cls="latency", weight=1.0),
            TenantContract("bulk", cls="batch", weight=1.0),
        ])
        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=2, n_inner=4, prompt_chunk=64,
                       tick_s=0.01, qos=reg)
            for _ in range(2)
        ]
        router = RequestRouter(
            reps, policy="least_loaded", clock=clock, qos=reg,
            shed_depth=4, shed_depth_hard=10,
        )
        outcomes = {"chat": [], "bulk": []}
        for k in range(40):
            t = "chat" if k % 2 else "bulk"
            rr = router.submit(64, 16, tenant=t)
            outcomes[t].append(rr)
        bulk_shed = [r for r in outcomes["bulk"] if r.outcome == "shed"]
        chat_shed = [r for r in outcomes["chat"] if r.outcome == "shed"]
        assert bulk_shed and bulk_shed[0].shed_reason == "overload"
        assert chat_shed  # the hard ceiling eventually sheds everyone
        assert all(
            r.shed_reason == "overload_hard" for r in chat_shed
        )
        # batch shed strictly first (submission order interleaves)
        assert (bulk_shed[0].t_submit, bulk_shed[0].id) < (
            chat_shed[0].t_submit, chat_shed[0].id
        )
        assert router.queue_depth <= 10

    def test_overload_shed_never_charges_the_token_bucket(self):
        """The overload door sits BEFORE the budget door: a request
        the fleet refuses under overload must not drain its tenant's
        token bucket (the r19 refund convention — refusals never keep
        the charge), or the overload penalty would leak into the
        budget plane as spurious post-storm "budget" sheds."""
        reg = TenantRegistry([
            TenantContract("bulk", cls="batch", weight=1.0,
                           rate=1e4, burst=1e6),
        ])
        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=2, n_inner=4, prompt_chunk=64,
                       tick_s=0.01, qos=reg)
            for _ in range(2)
        ]
        router = RequestRouter(
            reps, policy="least_loaded", clock=clock, qos=reg,
            shed_depth=4, shed_depth_hard=8,
        )
        bucket = router._buckets["bulk"]
        level_before = None
        shed = 0
        for _ in range(30):
            rr = router.submit(64, 16, tenant="bulk")
            if rr.outcome == "shed":
                if level_before is None:
                    level_before = bucket.level(clock.now())
                shed += 1
        assert shed > 0 and rr.shed_reason == "overload"
        # every shed after the first left the bucket untouched
        assert bucket.level(clock.now()) == level_before

    def test_shed_ceiling_validation(self):
        with pytest.raises(ValueError, match="shed_depth must be"):
            _mini_fleet(shed_depth=0)
        with pytest.raises(ValueError, match="without shed_depth"):
            _mini_fleet(shed_depth_hard=8)
        with pytest.raises(ValueError, match="at or above"):
            _mini_fleet(shed_depth=8, shed_depth_hard=4)

    def test_sim_replica_queue_ceiling_raises_by_name(self):
        clock = VirtualClock()
        rep = SimReplica(clock, slots=1, max_queue=2)
        rep.submit(16, 4)
        rep.submit(16, 4)  # pending == 2 == the ceiling
        with pytest.raises(RuntimeError, match="queue ceiling"):
            rep.submit(16, 4)
        with pytest.raises(ValueError, match="max_queue"):
            SimReplica(clock, max_queue=0)

    def test_shed_order_constants(self):
        assert SHED_ORDER[0] == "batch"
        assert shed_rank("batch") == 0
        assert shed_rank("latency") == len(SHED_ORDER) - 1
        assert TenantContract("t", cls="batch").shed_rank == 0
        with pytest.raises(ValueError, match="unknown SLO class"):
            shed_rank("gold")


# --------------------------------------------------------------------------
# retry clients: the metastable-failure generator
# --------------------------------------------------------------------------


class TestRetryClients:
    def test_retry_policy_validation(self):
        with pytest.raises(ValueError, match="timeout_s"):
            RetryPolicy(0.0)
        with pytest.raises(ValueError, match="max_retries"):
            RetryPolicy(1.0, max_retries=-1)
        with pytest.raises(ValueError, match="backoff"):
            RetryPolicy(1.0, backoff=0.5)
        with pytest.raises(ValueError, match="jitter_s"):
            RetryPolicy(1.0, jitter_s=-0.1)

    def test_resubmit_at_seeded_and_backed_off(self):
        p = RetryPolicy(1.0, backoff=2.0, jitter_s=0.5, seed=4)
        assert p.resubmit_at(10.0, 3, 0) == pytest.approx(
            RetryPolicy(1.0, backoff=2.0, jitter_s=0.5,
                        seed=4).resubmit_at(10.0, 3, 0)
        )
        base0 = RetryPolicy(1.0, backoff=2.0).resubmit_at(10.0, 3, 0)
        base1 = RetryPolicy(1.0, backoff=2.0).resubmit_at(10.0, 3, 1)
        assert base0 == 11.0 and base1 == 12.0  # timeout doubles
        # jitter stays within its band and differs across indices
        j = [p.resubmit_at(0.0, i, 0) - 1.0 for i in range(8)]
        assert all(0.0 <= x < 0.5 for x in j)
        assert len(set(j)) > 1

    def test_storm_amplifies_then_is_bounded(self):
        """A capacity dip ignites resubmissions; the amplification is
        bounded by max_retries and sheds are never retried — and the
        whole storm replays bit-identically."""

        def day():
            clock = VirtualClock()
            reps = [
                SimReplica(clock, slots=2, n_inner=4,
                           prompt_chunk=64, tick_s=0.01)
                for _ in range(4)
            ]
            router = RequestRouter(
                reps, policy="least_loaded", clock=clock,
                shed_depth=24,
            )
            n = 600
            rate = 120.0
            arr = poisson_arrivals(
                rate, n=n, seed=9, prompt_len=64, max_new=16,
            )
            events = [ReplicaKill(1.0, (1, 2, 3), 3.0)]
            rep = run_router_day(
                router, arr, events=events,
                retry=RetryPolicy(timeout_s=0.15, max_retries=2,
                                  jitter_s=0.05, seed=2),
            )
            return rep

        r1, r2 = day(), day()
        assert r1.digest() == r2.digest()
        assert r1.n_resubmits == r2.n_resubmits > 0
        assert r1.n_resubmits <= 2 * 600  # max_retries bound
        assert r1.n == 600 + r1.n_resubmits  # attempts in the report
        assert r1.dropped == 0

    def test_no_retry_day_is_byte_identical_to_pre_chaos_driver(self):
        """retry=None keeps the drive loop event-for-event: the digest
        of a plain day equals the digest of the same day driven with
        an explicitly absent retry policy."""

        def day(**kw):
            clock = VirtualClock()
            reps = [
                SimReplica(clock, slots=2, n_inner=4,
                           prompt_chunk=64, tick_s=0.01)
                for _ in range(3)
            ]
            router = RequestRouter(
                reps, policy="least_loaded", clock=clock
            )
            arr = poisson_arrivals(
                80.0, n=400, seed=21, prompt_len=64, max_new=16,
            )
            return run_router_day(router, arr, **kw)

        assert day().digest() == day(retry=None).digest()


# --------------------------------------------------------------------------
# the episode suite: every catalog scenario, invariants held, digest
# bit-identical
# --------------------------------------------------------------------------


_SMALL = {
    "overload_shed": {"n": 1500},
    "retry_storm": {"n": 1500},
    "network_partition": {"n": 1200},
    "correlated_host_kill": {"n": 1200},
    "prefix_churn": {"steps": 800},
    "storm_with_host_kill": {"n": 1800},
    "partition_mid_fetch": {"n": 1200},
}


class TestEpisodeSuite:
    @pytest.mark.parametrize("name", sorted(SCENARIOS))
    def test_episode_invariants_and_bit_identity(self, name):
        inj = ChaosInjector()
        r1 = inj.run(get_scenario(name, seed=5, **_SMALL[name]))
        r2 = inj.run(get_scenario(name, seed=5, **_SMALL[name]))
        assert isinstance(r1, ChaosReport)
        assert r1.digest() == r2.digest()
        assert r1.invariants  # the battery actually ran
        assert r1.shed_named_pct == 100.0
        assert r1.dropped == 0
        # a different seed is a different episode
        r3 = inj.run(get_scenario(name, seed=6, **_SMALL[name]))
        assert r3.digest() != r1.digest()

    def test_acceptance_combo_episode(self):
        """ISSUE 15 acceptance: retry storm + correlated host-group
        kill + 30%-span partition completes on VirtualClock with zero
        invariant violations — queue bounded, every shed named with
        batch before interactive, partitions reconciled with no
        double-retire, no drops, and an identical digest across two
        runs (pinned by test_episode_invariants_and_bit_identity;
        here the combo's specifics)."""
        inj = ChaosInjector()
        r = inj.run(get_scenario(
            "storm_with_host_kill", seed=5,
            **_SMALL["storm_with_host_kill"],
        ))
        assert r.n_resubmits > 0                  # the storm
        assert r.n_partitions == 2                # the partition span
        assert r.shed_reasons.get("overload", 0) > 0
        assert r.max_queue_depth <= 128           # the pinned ceiling
        assert r.extras["p99_recovery_x"] <= 4.0  # non-metastable
        assert "bounded_queue" in r.invariants
        assert "shed_by_name" in r.invariants

    def test_metastable_recovery_pinned(self):
        """Satellite: the retry storm drives offered load past 1 and
        subsides; p99 returns to within the pinned factor of the
        pre-storm baseline, bit-identically across two replays."""
        inj = ChaosInjector()
        r1 = inj.run(get_scenario("retry_storm", seed=5, n=1500))
        r2 = inj.run(get_scenario("retry_storm", seed=5, n=1500))
        assert r1.digest() == r2.digest()
        assert r1.extras["p99_recovery_x"] == (
            r2.extras["p99_recovery_x"]
        ) <= 3.0
        assert r1.n_resubmits > 0

    def test_prefix_churn_counters(self):
        r = ChaosInjector().run(
            get_scenario("prefix_churn", seed=5, steps=800)
        )
        ex = r.extras
        assert ex["admits"] > 0 and ex["retires"] > 0
        assert ex["cow_copies"] > 0      # the reservation churn ran
        assert ex["rollbacks"] > 0       # stranded reservations ran
        assert ex["share_hits"] > 0
        assert len(ex["churn_digest"]) == 16

    def test_unknown_scenario_refused_by_name(self):
        with pytest.raises(ValueError, match="unknown chaos scenario"):
            get_scenario("thundering_herd")
        with pytest.raises(TypeError, match="ChaosScenario"):
            ChaosInjector().run("retry_storm")

    def test_injector_obs_and_flight_capture(self):
        """registry= exports the episode counters; flight= holds the
        episode instants (begin/end, sheds, partitions) — and the
        flight-capture invariant actually verified them."""
        from mpistragglers_jl_tpu.obs import FlightRecorder
        from mpistragglers_jl_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        fr = FlightRecorder(capacity=8192)
        inj = ChaosInjector(registry=reg, flight=fr)
        r = inj.run(get_scenario(
            "storm_with_host_kill", seed=5,
            **_SMALL["storm_with_host_kill"],
        ))
        assert "flight_captured" in r.invariants
        prom = reg.to_prometheus()
        assert 'chaos_episodes_total{scenario="storm_with_host_kill"}' \
            in prom
        assert "chaos_max_queue_depth" in prom
        assert "router_shed_total" in prom
        assert "router_partitions_total" in prom
        eps = fr.instants("chaos episode")
        assert [e["phase"] for e in eps] == ["begin", "end"]
        assert eps[1]["digest"] == r.digest()
        assert fr.instants("replica partitioned")
        assert fr.instants("partition healed")
        assert fr.instants("qos shed")

    def test_dark_injector_is_dark(self):
        inj = ChaosInjector()
        assert inj.registry is None and inj.flight is None
        r = inj.run(get_scenario("overload_shed", seed=5, n=1500))
        assert "flight_captured" not in r.invariants


# --------------------------------------------------------------------------
# fleet: the controller must not flap under a retry storm
# --------------------------------------------------------------------------


class TestFleetNoFlap:
    def test_hysteresis_survives_a_retry_storm(self):
        """A storm whipsaws the arrival-rate and utilization signals;
        dwell + cooldown must keep the controller from chasing it —
        at most one grow/shrink direction flip over the whole day, and
        the day still drains with zero drops."""
        from mpistragglers_jl_tpu.fleet import FleetController

        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=4, n_inner=8, prompt_chunk=64,
                       tick_s=0.02)
            for _ in range(8)
        ]
        router = RequestRouter(
            reps, policy="least_loaded", clock=clock,
            shed_depth=64, shed_depth_hard=128,
        )
        cap = 4 / (6 * 0.02)  # service_ticks_per_request arithmetic
        rate = 0.6 * 8 * cap
        n = 2400
        span = n / rate
        base = poisson_arrivals(
            rate, n=n, seed=3, prompt_len=96, max_new=32,
        )
        burst = poisson_arrivals(
            0.8 * 8 * cap, n=int(0.8 * 8 * cap * 0.25 * span),
            seed=91, start=0.35 * span, prompt_len=96, max_new=32,
        )
        ctl = FleetController(
            router, clock=clock, capacity_rps=cap, min_replicas=4,
            max_replicas=8, decision_interval_s=1.0, dwell_s=2.0,
            cooldown_s=4.0, rate_tau_s=5.0,
        )
        rep = run_router_day(
            router,
            heapq.merge(base, burst, key=lambda a: a.t),
            controller=ctl,
            retry=RetryPolicy(timeout_s=0.35, max_retries=2,
                              jitter_s=0.2, seed=7),
        )
        assert rep.dropped == 0
        assert ctl.n_direction_flips <= 1, (
            f"controller flapped: {ctl.n_resizes} resizes, "
            f"{ctl.n_direction_flips} direction flips — "
            f"{[d.action for d in ctl.decisions]}"
        )
        assert ctl.n_resizes <= 4

    def test_direction_flip_counter_semantics(self):
        """The flap detector counts REVERSALS, not resizes: two
        shrinks then a grow is one flip."""
        from mpistragglers_jl_tpu.fleet import FleetController

        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=4, tick_s=0.02) for _ in range(6)
        ]
        router = RequestRouter(
            reps, policy="least_loaded", clock=clock
        )
        ctl = FleetController(
            router, clock=clock, capacity_rps=30.0, min_replicas=2,
            max_replicas=6, decision_interval_s=10.0,
        )
        ctl.resize_to(4, reason="test")
        ctl.resize_to(3, reason="test")
        assert ctl.n_direction_flips == 0
        ctl.resize_to(5, reason="test")
        assert ctl.n_direction_flips == 1
        ctl.resize_to(6, reason="test")
        assert ctl.n_direction_flips == 1
        state = ctl.state_dict()
        assert state["n_direction_flips"] == 1
        assert state["last_action"] == 1  # grow


# --------------------------------------------------------------------------
# report mechanics
# --------------------------------------------------------------------------


class TestChaosReport:
    def test_digest_covers_chaos_counters(self):
        a = ChaosReport("s", 1, extras={"x": 1.0})
        b = ChaosReport("s", 1, extras={"x": 1.0})
        assert a.digest() == b.digest()
        assert ChaosReport("s", 2).digest() != a.digest()
        assert ChaosReport(
            "s", 1, max_queue_depth=9, extras={"x": 1.0}
        ).digest() != a.digest()

    def test_shed_named_pct_vacuous_on_no_sheds(self):
        assert ChaosReport("s", 0).shed_named_pct == 100.0

    def test_invariant_violation_is_assertion(self):
        assert issubclass(InvariantViolation, AssertionError)

    def test_replica_kill_validation(self):
        with pytest.raises(ValueError, match="revive"):
            ReplicaKill(2.0, (0,), 1.0)
        with pytest.raises(ValueError, match="no replicas"):
            ReplicaKill(1.0, (), 2.0)
