"""graftcheck (tools/graftcheck): the tier-1 static-analysis gate.

Three layers: (1) the fixture corpus pins each rule's exact findings —
rule ids AND line numbers — plus the good twin staying clean; (2) the
suppression/baseline/cache machinery round-trips; (3) the SELF-RUN:
the analyzer over the whole shipped package must be clean, fast, and
must not import jax — this is the test that makes every invariant in
the rule catalog gate every future PR.
"""

import json
import os
import subprocess
import sys

import pytest

from mpistragglers_jl_tpu.tools.graftcheck import (
    Baseline,
    BaselineError,
    run,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "mpistragglers_jl_tpu")
_FIX = os.path.join(_REPO, "tests", "graftcheck_fixtures")


def _findings(target, **kw):
    res = run([os.path.join(_FIX, target)], **kw)
    return res


def _keys(findings):
    return [(f.rule, f.line) for f in findings]


# --------------------------------------------------------------------------
# fixture corpus: exact rule ids + line numbers per checker
# --------------------------------------------------------------------------


@pytest.mark.parametrize(
    "bad,expected",
    [
        ("gc001_bad_pkg", [("GC001", 6)]),
        ("gc001_hermetic_bad_pkg", [("GC001", 6)]),
        ("gc002_bad.py", [("GC002", 11), ("GC002", 17), ("GC002", 21)]),
        (
            # lines 48/51 are the round-17 shard_map extension: a host
            # clock in the shard_map-wrapped callable itself and an
            # .item() in the lax.scan body nested inside it — both
            # resolve through the shard_map boundary
            "gc003_bad.py",
            [("GC003", 16), ("GC003", 17), ("GC003", 18),
             ("GC003", 25), ("GC003", 30),
             ("GC003", 48), ("GC003", 51), ("GC003", 68)],
        ),
        ("gc004_bad.py", [("GC004", 6), ("GC004", 12), ("GC004", 17),
                          ("GC004", 22), ("GC004", 26),
                          ("GC004", 33), ("GC004", 40),
                          ("GC004", 47), ("GC004", 48),
                          ("GC004", 55), ("GC004", 56),
                          ("GC004", 63), ("GC004", 64),
                          ("GC004", 71), ("GC004", 72),
                          ("GC004", 80), ("GC004", 81),
                          ("GC004", 89), ("GC004", 90),
                          ("GC004", 98), ("GC004", 99),
                          ("GC004", 106),
                          ("GC004", 113), ("GC004", 114),
                          ("GC004", 122), ("GC004", 123)]),
        (
            "gc005_bad.py",
            [("GC005", 17), ("GC005", 18), ("GC005", 21),
             ("GC005", 22)],
        ),
        (
            # the round-20 shed-by-name contract: bare drops at exact
            # lines — outcome="shed" with no shed_reason sibling (6,
            # 27), reason-less/None/empty shed and drop calls (12, 17,
            # 22), the trivially empty reason stamp (28), a call
            # nested inside a compound statement reported ONCE (34 —
            # the per-statement re-walk double-counted it, review
            # finding), and a nested def's call attributed to the
            # inner function once (40)
            "gc010_bad.py",
            [("GC010", 6), ("GC010", 12), ("GC010", 17),
             ("GC010", 22), ("GC010", 27), ("GC010", 28),
             ("GC010", 34), ("GC010", 40)],
        ),
        (
            # the round-21 witness-single-source contract: digest
            # witness columns written outside sim/workload.py (6, 7 —
            # plain, 16 — self-write, 17 — annotated) and a second
            # digest() definition (10)
            "gc011_bad_pkg",
            [("GC011", 6), ("GC011", 7), ("GC011", 10),
             ("GC011", 16), ("GC011", 17)],
        ),
        (
            # ISSUE 18 replay-purity: at-source RNG/uuid/urandom/
            # environ hits (17-23), set iteration reaching the digest
            # (31), hash()/id() order reaching sort keys (40, 41) and
            # the event heap (44), and the two interprocedural flows —
            # a helper's returned set order reaching a sim digest (52)
            # and a kwarg carrying set order into the helper's own
            # hashlib sink (58)
            "gc012_bad_pkg",
            [("GC012", 17), ("GC012", 18), ("GC012", 19),
             ("GC012", 20), ("GC012", 21), ("GC012", 22),
             ("GC012", 23), ("GC012", 31), ("GC012", 40),
             ("GC012", 41), ("GC012", 44), ("GC012", 52),
             ("GC012", 58)],
        ),
        (
            # stale suppressions: a retired finding (12), the dead
            # half of a two-rule comment (17), a typo'd rule id (23),
            # and a blanket disable=all covering nothing (28); the
            # comment on line 7 suppresses a live GC010 and stays
            # silent
            "gc013_bad.py",
            [("GC013", 12), ("GC013", 17), ("GC013", 23),
             ("GC013", 28)],
        ),
    ],
)
def test_bad_fixture_exact_findings(bad, expected):
    res = _findings(bad)
    assert _keys(res.fresh) == expected
    assert not res.baselined


@pytest.mark.parametrize(
    "good",
    ["gc001_good_pkg", "gc001_hermetic_good_pkg", "gc002_good.py",
     "gc003_good.py", "gc004_good.py", "gc005_good.py",
     "gc010_good.py", "gc011_good_pkg", "gc012_good_pkg",
     "gc013_good.py"],
)
def test_good_fixture_clean(good):
    res = _findings(good)
    assert res.fresh == [], [f.format() for f in res.fresh]


def test_rule_subset_isolates_one_checker():
    res = _findings("gc003_bad.py", rules=["GC005"])
    assert res.fresh == []
    with pytest.raises(ValueError, match="unknown rules"):
        _findings("gc003_bad.py", rules=["GC999"])


# --------------------------------------------------------------------------
# suppression / baseline / cache round-trips
# --------------------------------------------------------------------------


def test_suppression_roundtrip():
    """Line 38 of gc003_bad.py carries `# graftcheck: disable=GC003`:
    the finding moves to the suppressed bucket, never to fresh."""
    res = _findings("gc003_bad.py")
    assert ("GC003", 38) in _keys(res.suppressed)
    assert ("GC003", 38) not in _keys(res.fresh)


def test_baseline_roundtrip(tmp_path):
    entry = {
        "rule": "GC004",
        "path": "gc004_bad.py",
        "symbol": "tick",
        "justification": "fixture: exercising the ledger",
    }
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"cap": 1, "entries": [entry]}))
    res = _findings("gc004_bad.py", baseline_path=str(bl))
    assert _keys(res.baselined) == [("GC004", 6)]
    assert _keys(res.fresh) == [("GC004", 12), ("GC004", 17),
                                ("GC004", 22), ("GC004", 26),
                                ("GC004", 33), ("GC004", 40),
                                ("GC004", 47), ("GC004", 48),
                                ("GC004", 55), ("GC004", 56),
                                ("GC004", 63), ("GC004", 64),
                                ("GC004", 71), ("GC004", 72),
                                ("GC004", 80), ("GC004", 81),
                                ("GC004", 89), ("GC004", 90),
                                ("GC004", 98), ("GC004", 99),
                                ("GC004", 106),
                                ("GC004", 113), ("GC004", 114),
                                ("GC004", 122), ("GC004", 123)]
    assert res.baseline_size == 1


def test_baseline_stale_entry_fails(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({
        "cap": 1,
        "entries": [{
            "rule": "GC004", "path": "gc004_bad.py",
            "symbol": "no_such_function",
            "justification": "matches nothing",
        }],
    }))
    with pytest.raises(BaselineError, match="stale"):
        _findings("gc004_bad.py", baseline_path=str(bl))


def test_baseline_cap_and_justification_enforced():
    entry = {
        "rule": "GC004", "path": "p.py", "symbol": "f",
        "justification": "ok",
    }
    with pytest.raises(BaselineError, match="capped"):
        Baseline([entry, {**entry, "symbol": "g"}], cap=1)
    with pytest.raises(BaselineError, match="justification"):
        Baseline([{**entry, "justification": "  "}], cap=5)
    with pytest.raises(BaselineError, match="missing"):
        Baseline([{"rule": "GC004"}], cap=5)


def test_cache_roundtrip(tmp_path):
    cache = str(tmp_path / "cache.json")
    first = _findings("gc005_bad.py", cache_path=cache)
    assert os.path.exists(cache)
    second = _findings("gc005_bad.py", cache_path=cache)
    assert _keys(second.fresh) == _keys(first.fresh)
    # cached findings carry the full identity, not just the keys
    assert [f.format() for f in second.fresh] == [
        f.format() for f in first.fresh
    ]


def test_cache_keyed_by_rule_subset(tmp_path):
    """A --rules subset run must not poison the cache for a later full
    scan (review finding): the subset's partial results are keyed
    separately, so the full scan re-analyzes and reports everything."""
    cache = str(tmp_path / "cache.json")
    subset = _findings("gc003_bad.py", cache_path=cache,
                       rules=["GC005"])
    assert subset.fresh == []
    full = _findings("gc003_bad.py", cache_path=cache)
    assert ("GC003", 16) in _keys(full.fresh)
    # and the reverse: the full-run cache must not leak other rules'
    # findings into a subset run
    again = _findings("gc003_bad.py", cache_path=cache,
                      rules=["GC005"])
    assert again.fresh == []


def test_baseline_scoped_to_partial_scans():
    """Baseline entries out of scope for a rules subset or a sub-path
    scan must not die with a stale-baseline error (review finding:
    docs' own --rules example exited 2). The shipped baseline is empty
    since the PoolLatencyModel.publish entry retired, so these runs
    also prove the empty ledger is never itself an error."""
    from mpistragglers_jl_tpu.tools.graftcheck import DEFAULT_BASELINE

    sub = run(
        [os.path.join(_PKG, "models")],
        baseline_path=DEFAULT_BASELINE,
    )
    assert sub.ok
    subset = run(
        [_PKG], baseline_path=DEFAULT_BASELINE,
        rules=["GC003", "GC005"],
    )
    assert subset.ok
    # staleness on a COVERING scan keeps working: pinned by
    # test_baseline_stale_entry_fails (entry under the scan root,
    # matching nothing -> BaselineError)


def test_nonempty_baseline_matches_on_subpath_and_single_file(tmp_path):
    """Finding paths are package-root-relative no matter where inside
    the package a scan starts (package_base walks up past
    __init__.py), so a baseline entry keeps matching on sub-path and
    single-file scans. The shipped baseline went empty this round, so
    this is pinned against a synthetic package + ledger — the walk-up
    relativization must not rot unnoticed (review finding)."""
    pkg = tmp_path / "pkg" / "inner"
    pkg.mkdir(parents=True)
    (tmp_path / "pkg" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(
        "def tick(payload, tracer=None):\n"
        "    tracer.begin('t')\n"
        "    return payload\n"
    )
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"cap": 1, "entries": [{
        "rule": "GC004",
        "path": "pkg/inner/mod.py",
        "symbol": "tick",
        "justification": "fixture: pinning sub-path relativization",
    }]}))
    for target in (
        str(tmp_path / "pkg"),              # package root
        str(pkg),                           # sub-path
        str(pkg / "mod.py"),                # single file
    ):
        res = run([target], baseline_path=str(bl))
        assert res.ok, "\n".join(f.format() for f in res.fresh)
        assert [f.key() for f in res.baselined] == [
            ("GC004", "pkg/inner/mod.py", "tick")
        ], target


def test_required_registry_param_is_export_target_not_flagged():
    """PoolLatencyModel.publish(registry) — a REQUIRED registry param —
    is an export target, not a dark-path kwarg: GC004 no longer flags
    it (the baseline entry that used to document this false positive
    is retired; the shipped baseline is empty), and sub-path /
    single-file scans of the clean tree stay clean with nothing
    baselined."""
    from mpistragglers_jl_tpu.tools.graftcheck import DEFAULT_BASELINE

    for target in (
        os.path.join(_PKG, "utils"),
        os.path.join(_PKG, "utils", "straggle.py"),
    ):
        res = run([target], baseline_path=DEFAULT_BASELINE)
        assert res.ok, "\n".join(f.format() for f in res.fresh)
        assert res.baselined == []
        assert res.baseline_size == 0


def test_missing_baseline_is_config_error():
    """A typo'd baseline path must be exit-2 loud, not a silent
    ledger-off run (review finding)."""
    with pytest.raises(BaselineError, match="not found"):
        run([os.path.join(_FIX, "gc004_bad.py")],
            baseline_path="/no/such/baseline.json")


def test_identical_content_distinct_paths_not_conflated(tmp_path):
    """GC002's verdict depends on the file's PATH (CompilerParams is
    legal only in its home module), so two identical-content files
    must be analyzed separately — the result record is keyed on
    (relpath, sha), not content alone (review finding)."""
    pkg = tmp_path / "pkg" / "ops"
    pkg.mkdir(parents=True)
    (tmp_path / "pkg" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    src = (
        "from jax.experimental.pallas import tpu as pltpu\n"
        "def params():\n"
        "    return pltpu.CompilerParams()\n"
    )
    (pkg / "flash_attention.py").write_text(src)  # the home: legal
    (pkg / "attn_copy.py").write_text(src)  # same bytes: violation
    for cache in (None, str(tmp_path / "c.json")):
        res = run([str(tmp_path / "pkg")], cache_path=cache)
        assert [(f.rule, f.path) for f in res.fresh] == [
            ("GC002", "pkg/ops/attn_copy.py")
        ], [f.format() for f in res.fresh]


def test_gc004_nested_early_return_does_not_prove(tmp_path):
    """An `if x is None: return` nested inside another conditional
    dominates nothing outside its block: the deref after the enclosing
    `if` still runs with x=None when the condition is false, and must
    be flagged (review finding). The same guard at the function's top
    level, or at the top level of a closure, still proves."""
    p = tmp_path / "m.py"
    p.write_text(
        "def f(payload, flag, tracer=None):\n"
        "    if flag:\n"
        "        if tracer is None:\n"
        "            return payload\n"
        "    tracer.begin('t')\n"  # line 5: unguarded when not flag
        "    return payload\n"
        "\n"
        "def g(tracer=None):\n"
        "    def inner():\n"
        "        if tracer is None:\n"
        "            return None\n"
        "        return tracer.begin('t')\n"  # closure top level: ok
        "    inner()\n"
        "    tracer.begin('t')\n"  # line 14: inner's guard is local
        "    return None\n"
    )
    res = run([str(p)], rules=["GC004"])
    assert [(f.rule, f.line) for f in res.fresh] == [
        ("GC004", 5), ("GC004", 14)
    ], [f.format() for f in res.fresh]


def test_cache_rejects_malformed_entries(tmp_path):
    """Cache contents are untrusted: a structurally invalid record is
    a miss (re-analyzed), never a crash or a replayed fabrication
    (review finding)."""
    from mpistragglers_jl_tpu.tools.graftcheck.core import _Cache

    c = _Cache(str(tmp_path / "c.json"), salt="s")
    c.data["sha1"] = [{"rule": "GC001"}]  # missing fields
    c.data["sha2"] = "not-a-list"
    c.data["sha3"] = [{"rule": "GC001", "path": "p", "line": 1,
                       "col": 0, "symbol": "s", "message": "m",
                       "extra": "smuggled"}]
    assert c.get("sha1") is None
    assert c.get("sha2") is None
    assert c.get("sha3") is None
    assert c.get("absent") is None


# --------------------------------------------------------------------------
# GC001 hermetic subpackage roots (ISSUE 5: sim/ proven jax-free)
# --------------------------------------------------------------------------


def test_hermetic_marker_makes_subpackage_its_own_closure_root():
    """The bad fixture's top root never imports its ``sim``
    subpackage, so the top-root walk alone would miss the jax leak
    entirely; the ``# graftcheck: hermetic-root`` marker in
    ``sim/__init__.py`` is what makes it a finding — and the finding
    names the hermetic root, not the (blind) top root."""
    res = _findings("gc001_hermetic_bad_pkg")
    assert _keys(res.fresh) == [("GC001", 6)]
    (f,) = res.fresh
    assert "gc001_hermetic_bad_pkg.sim" in f.message
    # the package-shaped control: strip the marker and the same tree
    # scans clean, proving the marker (not the layout) adds the root
    import ast as _ast

    from mpistragglers_jl_tpu.tools.graftcheck.checkers import (
        gc001_import_hygiene as gc001,
    )
    from mpistragglers_jl_tpu.tools.graftcheck.core import load_modules

    mods = load_modules([os.path.join(_FIX, "gc001_hermetic_bad_pkg")])
    for m in mods:
        if m.path.endswith(os.path.join("sim", "__init__.py")):
            m.source = m.source.replace(gc001.HERMETIC_MARKER, "# x")
    got = list(gc001.ImportHygiene().check_project(mods))
    assert got == []


def test_shipped_sim_subpackage_is_a_hermetic_root():
    """The real ``sim/`` declares the marker, so its closure is proven
    accelerator-free as a root of its own and survives any future
    detachment from the package root's ``__init__`` walk (the
    detection mechanics are pinned by the fixture pair; this pins that
    the shipped tree actually opts in)."""
    from mpistragglers_jl_tpu.tools.graftcheck.checkers import (
        gc001_import_hygiene as gc001,
    )

    src = os.path.join(_PKG, "sim", "__init__.py")
    with open(src) as f:
        assert gc001.HERMETIC_MARKER in f.read()


def test_hermetic_and_top_root_findings_deduplicate(tmp_path):
    """A violation reachable from BOTH the top root and a hermetic
    subroot is one finding, not two (reported under the first root
    that reaches it) — while two DISTINCT forbidden imports sharing
    one source line stay two findings (the dedup key includes the
    imported name, not just the line)."""
    pkg = tmp_path / "dualpkg"
    (pkg / "sub").mkdir(parents=True)
    (pkg / "__init__.py").write_text("from . import sub\n")
    (pkg / "sub" / "__init__.py").write_text(
        "# graftcheck: hermetic-root\nimport jax, torch\n"
    )
    res = run([str(pkg)], rules=["GC001"])
    assert len(res.fresh) == 2  # jax AND torch, once each
    assert all(f.rule == "GC001" for f in res.fresh)
    assert {f.line for f in res.fresh} == {2}


def test_gc003_shard_map_nested_body_single_attribution(tmp_path):
    """The round-17 extension: GC003 collects shard_map-wrapped
    callables as traced regions, resolves lax bodies nested inside
    them, and attributes each leak ONCE to the innermost traced
    function (the naive walk re-reported a nested body's leak for
    every enclosing traced region)."""
    p = tmp_path / "m.py"
    p.write_text(
        "import time\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "\n"
        "def outer(xs, mesh):\n"
        "    def window(x):\n"
        "        t0 = time.time()\n"              # line 7: window's own
        "        def body(c, t):\n"
        "            return c + t.item(), t\n"    # line 9: body's own
        "        return jax.lax.scan(body, jnp.zeros(()), x), t0\n"
        "    return jax.shard_map(window, mesh=mesh, in_specs=None,\n"
        "                         out_specs=None)(xs)\n"
    )
    res = run([str(p)], rules=["GC003"])
    assert [(f.rule, f.line) for f in res.fresh] == [
        ("GC003", 7), ("GC003", 9)
    ], [f.format() for f in res.fresh]
    assert "window" in res.fresh[0].message
    assert "body" in res.fresh[1].message


def test_package_self_run_is_clean():
    """The shipped tree passes its own analyzer: zero fresh findings
    against the checked-in baseline. Every future PR inherits this
    gate."""
    from mpistragglers_jl_tpu.tools.graftcheck import DEFAULT_BASELINE

    res = run([_PKG], baseline_path=DEFAULT_BASELINE)
    assert res.ok, "\n".join(f.format() for f in res.fresh)
    # GC001-GC005 + the v2 set (ISSUE 8) + GC010 shed-by-name (r20)
    # + GC011 witness-single-source (r21) + GC012 replay-purity and
    # GC013 stale-suppression (ISSUE 18)
    assert res.n_rules == 13
    assert res.n_files > 50  # the whole package, not a subset


def test_cli_self_run_subprocess_no_jax():
    """CLI contract: `python -m mpistragglers_jl_tpu.tools.graftcheck
    mpistragglers_jl_tpu/` exits 0 on the shipped tree AND the tool
    itself never imports jax (stdlib ast only) — asserted inside the
    subprocess, where nothing else has polluted sys.modules."""
    code = (
        "import sys\n"
        "from mpistragglers_jl_tpu.tools.graftcheck.__main__ "
        "import main\n"
        "rc = main(['mpistragglers_jl_tpu', '--no-cache', '-q'])\n"
        "bad = [m for m in sys.modules"
        " if m == 'jax' or m.startswith('jax.')]\n"
        "assert not bad, f'graftcheck pulled in jax: {bad}'\n"
        "sys.exit(rc)\n"
    )
    env = dict(os.environ)
    # drop any sitecustomize that preloads jax (same discipline as
    # test_import_is_jax_free)
    env["PYTHONPATH"] = _REPO
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=_REPO, env=env,
        timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_exit_codes():
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO

    def cli(*args):
        return subprocess.run(
            [sys.executable, "-m",
             "mpistragglers_jl_tpu.tools.graftcheck", *args],
            capture_output=True, text=True, cwd=_REPO, env=env,
            timeout=120,
        )

    bad = cli(os.path.join(_FIX, "gc002_bad.py"),
              "--baseline", "none", "--no-cache")
    assert bad.returncode == 1
    assert "GC002" in bad.stdout
    good = cli(os.path.join(_FIX, "gc002_good.py"),
               "--baseline", "none", "--no-cache")
    assert good.returncode == 0
    missing = cli("definitely/not/a/path.py")
    assert missing.returncode == 2
    rules = cli("--list-rules")
    assert rules.returncode == 0
    for rule in ("GC001", "GC002", "GC003", "GC004", "GC005",
                 "GC006", "GC007", "GC008", "GC009", "GC010",
                 "GC011", "GC012", "GC013"):
        assert rule in rules.stdout
    # the argparse banner derives its range from the live registry —
    # the hardcoded "(GC001-GC009)" went stale twice (ISSUE 18)
    helptext = cli("--help")
    assert "GC001-GC013" in helptext.stdout


# --------------------------------------------------------------------------
# GC012 replay-purity: interprocedural taint (ISSUE 18)
# --------------------------------------------------------------------------


def test_gc012_interprocedural_return_names_helper_source():
    """The finding sits in sim/day.py (the sink), but the message
    indicts the helper module's list()-over-set — taint crossed the
    module boundary through the engine's function summaries."""
    res = _findings("gc012_bad_pkg", rules=["GC012"])
    by_line = {f.line: f for f in res.fresh}
    f = by_line[52]
    assert f.path == "gc012_bad_pkg/sim/day.py"
    assert "digest input" in f.message
    assert "gc012_bad_pkg/helpers.py" in f.message


def test_gc012_interprocedural_kwarg_into_helper_sink():
    """The reverse direction: sim/ passes a set-ordered value as a
    KWARG into a helper whose body feeds it to hashlib — the finding
    lands at the call site, naming the parameter and the callee."""
    res = _findings("gc012_bad_pkg", rules=["GC012"])
    by_line = {f.line: f for f in res.fresh}
    f = by_line[58]
    assert "`payload`" in f.message
    assert "gc012_bad_pkg.helpers:stamp" in f.message


def test_gc012_order_sources_are_sink_gated():
    """hash() in the local key function (line 36) is not a finding on
    its own — it surfaces only at the sort that consumes it (line 40),
    with the source's file:line in the message."""
    res = _findings("gc012_bad_pkg", rules=["GC012"])
    by_line = {f.line: f for f in res.fresh}
    assert 36 not in by_line
    assert "gc012_bad_pkg/sim/day.py:36" in by_line[40].message


def test_gc012_aux_cache_reuses_module_records(tmp_path):
    """Touching ONE file invalidates the whole-tree project key but
    not the sibling modules' aux records: the second run rebuilds only
    the touched module and replays day.py's sources/sinks/summaries
    through record_from_json — findings must be byte-identical."""
    import shutil

    pkg = tmp_path / "gc012_bad_pkg"
    shutil.copytree(os.path.join(_FIX, "gc012_bad_pkg"), pkg)
    cache = str(tmp_path / "c.json")
    first = run([str(pkg)], cache_path=cache, rules=["GC012"])
    helpers = pkg / "helpers.py"
    helpers.write_text(helpers.read_text() + "\n# touched\n")
    second = run([str(pkg)], cache_path=cache, rules=["GC012"])
    assert [f.format() for f in second.fresh] == [
        f.format() for f in first.fresh
    ]
    assert len(first.fresh) == 13


# --------------------------------------------------------------------------
# GC013 stale suppressions (ISSUE 18)
# --------------------------------------------------------------------------


def test_gc013_half_stale_names_only_the_dead_rule():
    """A two-rule comment whose GC010 half still fires is reported
    ONLY for the GC005 half; the typo'd and blanket comments name
    themselves in the message."""
    res = _findings("gc013_bad.py")
    msgs = {f.line: f.message for f in res.fresh}
    assert "disable=GC005" in msgs[17]
    assert "disable=GC010" not in msgs[17]
    assert "disable=GC910" in msgs[23]
    assert "disable=all" in msgs[28]


def test_gc013_rules_subset_never_fakes_staleness():
    """Under --rules, a suppression for an INACTIVE rule cannot be
    judged stale (its findings were never computed), and unknown/all
    names are only judged on a full-registry run — so a subset run
    reports exactly the one provably dead active-rule suppression."""
    res = _findings("gc013_bad.py", rules=["GC010", "GC013"])
    assert _keys(res.fresh) == [("GC013", 12)]


# --------------------------------------------------------------------------
# whole-tree project cache + SARIF (ISSUE 18 satellites)
# --------------------------------------------------------------------------


def test_warm_clean_rerun_parses_nothing(tmp_path, monkeypatch):
    """With the per-file cache AND the whole-tree project cache hot, a
    clean re-run never builds an AST: ast.parse is forbidden outright
    and the run still completes with identical (empty) findings."""
    import ast as _ast

    target = os.path.join(_PKG, "sim")
    cache = str(tmp_path / "c.json")
    first = run([target], cache_path=cache)
    assert first.ok, "\n".join(f.format() for f in first.fresh)

    def boom(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("warm clean re-run must not parse")

    monkeypatch.setattr(_ast, "parse", boom)
    second = run([target], cache_path=cache)
    assert second.ok
    assert second.fresh == []
    assert second.n_files == first.n_files


def test_cli_sarif_report(tmp_path):
    """--sarif PATH: fresh findings as plain results, baselined ones
    suppressed kind=external, in-source comments kind=inSource; the
    driver catalog carries the full registry; an unwritable target is
    a loud exit-2 config error."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO

    def cli(*args):
        return subprocess.run(
            [sys.executable, "-m",
             "mpistragglers_jl_tpu.tools.graftcheck", *args],
            capture_output=True, text=True, cwd=_REPO, env=env,
            timeout=120,
        )

    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"cap": 1, "entries": [{
        "rule": "GC004", "path": "gc004_bad.py", "symbol": "tick",
        "justification": "fixture: exercising the ledger",
    }]}))
    out = tmp_path / "report.sarif"
    r = cli(os.path.join(_FIX, "gc004_bad.py"),
            "--baseline", str(bl), "--no-cache",
            "--sarif", str(out), "-q")
    assert r.returncode == 1, r.stdout + r.stderr
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    sarif_run = doc["runs"][0]
    catalog = {x["id"] for x in sarif_run["tool"]["driver"]["rules"]}
    assert {"GC001", "GC012", "GC013"} <= catalog
    results = sarif_run["results"]
    plain = [x for x in results if "suppressions" not in x]
    external = [
        x for x in results
        if any(s["kind"] == "external"
               for s in x.get("suppressions", []))
    ]
    assert len(plain) == 25 and len(external) == 1
    loc = external[0]["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 6
    assert loc["artifactLocation"]["uriBaseId"] == "SRCROOT"

    # in-source suppressions (gc003_bad.py lines 38/56) + '-' = stdout
    r = cli(os.path.join(_FIX, "gc003_bad.py"),
            "--baseline", "none", "--no-cache", "--sarif", "-", "-q")
    assert r.returncode == 1
    doc, _end = json.JSONDecoder().raw_decode(
        r.stdout, r.stdout.index("{")
    )
    kinds = [
        s["kind"] for x in doc["runs"][0]["results"]
        for s in x.get("suppressions", [])
    ]
    assert kinds.count("inSource") == 2

    unwritable = cli(os.path.join(_FIX, "gc002_good.py"),
                     "--baseline", "none", "--no-cache",
                     "--sarif", str(tmp_path / "no" / "dir" / "r"))
    assert unwritable.returncode == 2
    assert "--sarif" in unwritable.stderr


def test_bad_snippet_injection_fails_package_scan(tmp_path):
    """Acceptance shape: copying any bad fixture into a scanned tree
    flips the exit to non-zero — the gate actually gates."""
    import shutil

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    shutil.copy(
        os.path.join(_FIX, "gc005_bad.py"), pkg / "harvest.py"
    )
    res = run([str(pkg)])
    assert not res.ok
    assert {f.rule for f in res.fresh} == {"GC005"}
