"""The package must work with no C++ toolchain present.

The reference needs nothing but Julia + an external libmpi; our native
layer (native/__init__.py) claims "consumers fall back to a pure NumPy
implementation when no compiler is available, so the package never
hard-fails on import". These tests pin that claim:

* automatic numpy fallback when the native build fails (in-process,
  by making ``native.load`` raise the way a missing g++ does);
* a subprocess "clean machine" run: fresh interpreter, broken native
  toolchain, no jax import — ``import mpistragglers_jl_tpu`` + a full
  LocalBackend asyncmap epoch + byte-exact RS coding all succeed.
"""

import subprocess
import sys

import numpy as np
import pytest

from mpistragglers_jl_tpu import native
from mpistragglers_jl_tpu.native import NativeBuildError
from mpistragglers_jl_tpu.utils.rs_gf256 import RSGF256


def test_auto_fallback_when_toolchain_broken(monkeypatch):
    def broken_load(name, configure=None):
        raise NativeBuildError("g++ unavailable or hung: simulated")

    monkeypatch.setattr(native, "load", broken_load)
    with pytest.warns(RuntimeWarning, match="numpy fallback"):
        rs = RSGF256(8, 6)  # prefer_native=True is the default
    assert rs.impl == "numpy"
    data = np.random.default_rng(0).integers(0, 256, (6, 257), dtype=np.uint8)
    coded = rs.encode(data)
    out = rs.decode(coded[[7, 1, 6, 0, 4, 2]], [7, 1, 6, 0, 4, 2])
    np.testing.assert_array_equal(out, data)


_CLEAN_MACHINE = r"""
import sys, warnings

# This environment preloads jax via sitecustomize, so "jax absent" can't
# be observed passively; evict it and install an import blocker instead —
# if the package (or the paths exercised below) imports jax, this fails.
for _mod in [m for m in sys.modules if m == "jax" or m.startswith("jax.")]:
    del sys.modules[_mod]

class _NoJax:
    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith("jax."):
            raise ImportError("jax import blocked: clean-machine test")
        return None

sys.meta_path.insert(0, _NoJax())

# Break the native toolchain before anything can use it: build() is the
# single chokepoint every native consumer funnels through.
import mpistragglers_jl_tpu.native as native
def _no_gxx(name, *, force=False):
    raise native.NativeBuildError("g++ unavailable or hung: simulated")
native.build = _no_gxx
native._loaded.clear()

import numpy as np
import mpistragglers_jl_tpu as m

# LocalBackend pool end-to-end: one full-gather epoch (kmap1 scenario)
pool = m.AsyncPool(3)
backend = m.LocalBackend(lambda i, p, e: np.array([i + 1.0]), 3)
recvbuf = np.zeros(3)
repochs = m.asyncmap(pool, np.array([3.14]), backend, recvbuf, nwait=3)
m.waitall(pool, backend, recvbuf)
backend.shutdown()
assert list(repochs) == [1, 1, 1], repochs
assert list(recvbuf) == [1.0, 2.0, 3.0], recvbuf

# RS codec auto-falls back to numpy, still byte-exact
from mpistragglers_jl_tpu.utils.rs_gf256 import RSGF256
with warnings.catch_warnings():
    warnings.simplefilter("ignore", RuntimeWarning)
    rs = RSGF256(5, 3)
assert rs.impl == "numpy", rs.impl
data = np.arange(3 * 64, dtype=np.uint8).reshape(3, 64)
np.testing.assert_array_equal(rs.decode(rs.encode(data)[[4, 2, 0]], [4, 2, 0]), data)
print("CLEAN_MACHINE_OK")
"""


def test_clean_machine_subprocess():
    proc = subprocess.run(
        [sys.executable, "-c", _CLEAN_MACHINE],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "CLEAN_MACHINE_OK" in proc.stdout
