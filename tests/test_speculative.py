"""Speculative decoding (models/speculative.py): the output IS the
greedy stream — speculation only changes how many forwards it takes.

Exact equality with ``generate_dense`` is the load-bearing contract
(accept-iff-argmax-matches + correction token = greedy by induction;
the cache-consistency argument is the module docstring). Acceptance
(forwards saved) varies with stream predictability and is asserted
only where it is structurally guaranteed.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mpistragglers_jl_tpu.models.decode import generate_dense
from mpistragglers_jl_tpu.models.speculative import (
    _bigram_draft,
    generate_speculative_dense,
)
from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)

CFG = TransformerConfig(
    vocab=61, d_model=48, n_heads=4, n_layers=2, d_ff=96
)


def _prompt(L, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab, (1, L)), jnp.int32)


@pytest.mark.parametrize("k", [1, 3, 4, 8])
@pytest.mark.parametrize("Tp,n_new", [(8, 17), (3, 5), (12, 30)])
def test_speculative_equals_greedy(Tp, n_new, k):
    params = init_params(CFG, seed=1)
    prompt = _prompt(Tp, seed=Tp * 31 + k)
    want = generate_dense(params, prompt, n_new, CFG)
    got, iters = generate_speculative_dense(
        params, prompt, n_new, CFG, k=k
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert 0 < iters <= n_new - 1 or (n_new == 1 and iters == 0)


def test_repetitive_prompt_equals_greedy_and_accepts():
    """A strongly periodic prompt: lookup drafting must still be exact,
    and untrained greedy streams loop, so some drafts accept — fewer
    verify forwards than tokens."""
    params = init_params(CFG, seed=2)
    base = _prompt(6, seed=9)
    prompt = jnp.tile(base, (1, 4))  # period-6 repetition, Tp=24
    n_new = 24
    want = generate_dense(params, prompt, n_new, CFG)
    got, iters = generate_speculative_dense(
        params, prompt, n_new, CFG, k=4
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert iters < n_new - 1, (
        f"no draft ever accepted on a periodic stream ({iters} forwards "
        f"for {n_new} tokens)"
    )


def test_n_new_one_needs_no_decode_forward():
    params = init_params(CFG, seed=3)
    prompt = _prompt(5, seed=4)
    want = generate_dense(params, prompt, 1, CFG)
    got, iters = generate_speculative_dense(params, prompt, 1, CFG)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert iters == 0  # prefill's argmax is the whole answer


def test_bigram_draft_lookup_semantics():
    """Draft = the continuation of the most recent earlier occurrence
    of the current bigram; fallback repeats the last token."""
    buf = jnp.asarray([5, 7, 1, 2, 3, 5, 7, 9, 0, 0], jnp.int32)
    # cursor=7 (known through index 6): current bigram (buf[5], buf[6])
    # = (5, 7); its only EARLIER occurrence is p=0 (p=5 is the current
    # bigram itself, excluded): continuation after it is [1, 2, 3]
    dr = _bigram_draft(buf, jnp.int32(7), 3)
    np.testing.assert_array_equal(np.asarray(dr), [1, 2, 3])
    # no earlier occurrence: repeat last token
    buf2 = jnp.asarray([1, 2, 3, 4, 5, 0, 0, 0], jnp.int32)
    dr2 = _bigram_draft(buf2, jnp.int32(5), 3)
    np.testing.assert_array_equal(np.asarray(dr2), [5, 5, 5])


@pytest.mark.parametrize("tp", [2, 4])
def test_sharded_speculative_matches_dense(tp):
    """make_speculative over dp=1 x tp: identical post-psum logits on
    every member -> identical drafts, acceptance, and packed output."""

    from mpistragglers_jl_tpu.models.speculative import make_speculative
    from mpistragglers_jl_tpu.models.transformer import shard_params
    from mpistragglers_jl_tpu.parallel import make_mesh

    mesh = make_mesh((1, tp), ("dp", "tp"))
    params = init_params(CFG, seed=4)
    prompt = _prompt(8, seed=44)
    want, want_iters = generate_speculative_dense(
        params, prompt, 15, CFG, k=4
    )
    run = make_speculative(CFG, mesh, 8, 15, k=4)
    packed = np.asarray(run(shard_params(params, CFG, mesh), prompt))
    np.testing.assert_array_equal(packed[None, :15], np.asarray(want))
    assert int(packed[15]) == want_iters


def test_sharded_speculative_rejects_dp():
    from mpistragglers_jl_tpu.models.speculative import make_speculative
    from mpistragglers_jl_tpu.parallel import make_mesh

    mesh = make_mesh((2, 2), ("dp", "tp"))
    with pytest.raises(ValueError, match="per-stream"):
        make_speculative(CFG, mesh, 8, 4)


def test_sharded_speculative_rejects_moe():
    """MoE's all_to_all marks the loop carries ep-varying, which the
    replicated-control-flow scheme cannot express — refuse up front
    rather than dying in the while_loop type check."""
    import dataclasses

    from mpistragglers_jl_tpu.models.speculative import make_speculative
    from mpistragglers_jl_tpu.parallel import make_mesh

    cfg = dataclasses.replace(CFG, n_experts=2)
    mesh = make_mesh((1, 2, 2), ("dp", "ep", "tp"))
    with pytest.raises(ValueError, match="dense configs only"):
        make_speculative(cfg, mesh, 8, 4)


def test_prompt_length_mismatch_is_trace_error():
    """A prompt shorter than the compiled Tp would attend unwritten
    zero K/V and diverge SILENTLY — it must be a loud error instead
    (reproduced: 9/10 random short prompts produced non-greedy
    streams before the guard)."""
    from mpistragglers_jl_tpu.models.speculative import (
        make_speculative_dense,
    )

    params = init_params(CFG, seed=0)
    run = make_speculative_dense(CFG, 8, 5)
    with pytest.raises(ValueError, match="compiled for Tp=8"):
        run(params, _prompt(6))


def test_validation():
    params = init_params(CFG, seed=0)
    with pytest.raises(ValueError, match="B=1"):
        generate_speculative_dense(
            params, jnp.zeros((2, 4), jnp.int32), 4, CFG
        )
    with pytest.raises(ValueError, match="prompt >= 2"):
        generate_speculative_dense(
            params, jnp.zeros((1, 1), jnp.int32), 4, CFG
        )
    with pytest.raises(ValueError, match="n_new"):
        generate_speculative_dense(
            params, jnp.zeros((1, 4), jnp.int32), 0, CFG
        )
    with pytest.raises(ValueError, match="draft length"):
        generate_speculative_dense(
            params, jnp.zeros((1, 4), jnp.int32), 4, CFG, k=0
        )


@pytest.mark.parametrize("k", [1, 4])
@pytest.mark.parametrize("Tp,n_new", [(8, 17), (3, 5)])
def test_model_draft_equals_greedy(Tp, n_new, k):
    """Truncated-layer model draft behind the same verify loop: the
    stream is still EXACTLY greedy (any draft is correct; only
    acceptance varies)."""
    params = init_params(CFG, seed=1)
    prompt = _prompt(Tp, seed=Tp * 13 + k)
    want = generate_dense(params, prompt, n_new, CFG)
    got, iters = generate_speculative_dense(
        params, prompt, n_new, CFG, k=k, draft_layers=1
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert 0 < iters <= n_new - 1 or (n_new == 1 and iters == 0)


def test_sharded_model_draft_matches_dense():
    from mpistragglers_jl_tpu.models.speculative import make_speculative
    from mpistragglers_jl_tpu.models.transformer import shard_params
    from mpistragglers_jl_tpu.parallel import make_mesh

    mesh = make_mesh((1, 4), ("dp", "tp"))
    params = init_params(CFG, seed=4)
    prompt = _prompt(8, seed=45)
    want, want_iters = generate_speculative_dense(
        params, prompt, 12, CFG, k=3, draft_layers=1
    )
    run = make_speculative(CFG, mesh, 8, 12, k=3, draft_layers=1)
    packed = np.asarray(run(shard_params(params, CFG, mesh), prompt))
    np.testing.assert_array_equal(packed[None, :12], np.asarray(want))
    assert int(packed[12]) == want_iters


def test_draft_layers_validation():
    params = init_params(CFG, seed=1)
    for bad in (0, CFG.n_layers, -1):
        with pytest.raises(ValueError, match="draft_layers"):
            generate_speculative_dense(
                params, _prompt(4), 5, CFG, draft_layers=bad
            )


def test_model_draft_perfect_acceptance_when_truncation_exact():
    """Alignment guard for the model drafter: zero the top layer's
    residual contributions (wo, w2, b2) so the 1-layer truncation
    computes EXACTLY the full model's logits — every draft must then
    be accepted and the forward count collapses to ceil((n_new-1)/(k+1)).
    An off-by-one anywhere in the draft cache positions would break
    this immediately."""
    import jax.numpy as jnp

    params = init_params(CFG, seed=1)
    lp = params["layers"][1]
    for kk in ("wo", "w2", "b2"):
        lp[kk] = jnp.zeros_like(lp[kk])
    n_new, k = 40, 4
    prompt = _prompt(8, seed=3)
    want = generate_dense(params, prompt, n_new, CFG)
    got, fwd = generate_speculative_dense(
        params, prompt, n_new, CFG, k=k, draft_layers=1
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert fwd == -(-(n_new - 1) // (k + 1)), fwd
