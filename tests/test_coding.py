"""Unit tests for the erasure-coding layer (no pool involved).

MDS encode/decode-from-any-k, LT peeling, gradient-code decode weights.
"""

import itertools

import numpy as np
import pytest

from mpistragglers_jl_tpu.ops import MDSCode, LTCode, GradientCode
from mpistragglers_jl_tpu.ops.lt import robust_soliton


class TestMDS:
    def test_systematic_prefix(self):
        code = MDSCode(8, 6)
        assert np.allclose(code.G[:6], np.eye(6))

    def test_encode_decode_every_k_subset(self):
        # exactness from EVERY k-of-n subset — the MDS property itself
        rng = np.random.default_rng(0)
        n, k = 6, 4
        code = MDSCode(n, k, dtype=np.float64)
        blocks = rng.standard_normal((k, 8, 5))
        coded = np.asarray(code.encode(blocks))
        for idx in itertools.combinations(range(n), k):
            idx = list(idx)
            out = np.asarray(code.decode(coded[idx], idx))
            assert np.allclose(out, blocks, atol=1e-8), f"subset {idx}"

    def test_encode_decode_f32_accuracy(self):
        rng = np.random.default_rng(1)
        n, k = 8, 6
        code = MDSCode(n, k, dtype=np.float32)
        blocks = rng.standard_normal((k, 16, 8)).astype(np.float32)
        coded = np.asarray(code.encode(blocks))
        # worst case: all-parity decode
        idx = [0, 3, 4, 5, 6, 7]
        out = np.asarray(code.decode(coded[idx], idx))
        assert np.allclose(out, blocks, atol=1e-3)

    def test_gaussian_parity(self):
        rng = np.random.default_rng(2)
        code = MDSCode(10, 7, parity="gaussian", dtype=np.float64)
        blocks = rng.standard_normal((7, 4, 3))
        coded = np.asarray(code.encode(blocks))
        idx = [2, 3, 5, 6, 7, 8, 9]
        assert np.allclose(
            np.asarray(code.decode(coded[idx], idx)), blocks, atol=1e-8)

    def test_encode_array_roundtrip(self):
        rng = np.random.default_rng(3)
        code = MDSCode(5, 3, dtype=np.float64)
        A = rng.standard_normal((12, 7))
        coded = np.asarray(code.encode_array(A))
        assert coded.shape == (5, 4, 7)
        out = np.asarray(code.decode_array(coded[[1, 3, 4]], [1, 3, 4]))
        assert np.allclose(out, A, atol=1e-8)

    def test_n_equals_k_is_identity(self):
        code = MDSCode(4, 4)
        assert np.allclose(code.G, np.eye(4))

    def test_validation(self):
        with pytest.raises(ValueError):
            MDSCode(4, 5)
        with pytest.raises(ValueError):
            MDSCode(4, 0)
        with pytest.raises(ValueError):
            MDSCode(8, 6, parity="bogus")
        code = MDSCode(6, 4, dtype=np.float64)
        blocks = np.zeros((4, 2, 2))
        coded = np.asarray(code.encode(blocks))
        with pytest.raises(ValueError):  # duplicate indices
            code.decode(coded[[0, 0, 1, 2]], [0, 0, 1, 2])
        with pytest.raises(ValueError):  # wrong count
            code.decode(coded[[0, 1, 2]], [0, 1, 2])
        with pytest.raises(ValueError):  # wrong block count to encode
            code.encode(np.zeros((3, 2, 2)))


class TestLT:
    def test_robust_soliton_is_distribution(self):
        for k in (4, 16, 64):
            mu = robust_soliton(k)
            assert mu.shape == (k,)
            assert abs(mu.sum() - 1.0) < 1e-12
            assert (mu >= 0).all()

    def test_shard_indices_deterministic(self):
        code = LTCode(16, seed=5)
        for s in range(20):
            a = code.shard_indices(s)
            b = code.shard_indices(s)
            assert np.array_equal(a, b)
            assert len(set(a.tolist())) == len(a)
            assert 1 <= len(a) <= 16

    def test_peel_decode_roundtrip(self):
        rng = np.random.default_rng(4)
        k = 8
        code = LTCode(k, seed=0)
        blocks = rng.standard_normal((k, 6, 4))
        # collect shards until peelable, then decode
        ids = []
        s = 0
        while not code.peelable(ids):
            ids.append(s)
            s += 1
        G = code.generator_rows(ids)
        shards = np.einsum("nk,krc->nrc", G, blocks)
        out = code.decode(shards, ids)
        assert np.allclose(out, blocks, atol=1e-10)

    def test_peelable_matches_decode(self):
        # whenever peelable says False, decode must raise; when True, it
        # must succeed — over many random arrival subsets
        rng = np.random.default_rng(5)
        k = 6
        code = LTCode(k, seed=1)
        blocks = rng.standard_normal((k, 3, 2))
        all_ids = list(range(18))
        G = code.generator_rows(all_ids)
        shards = np.einsum("nk,krc->nrc", G, blocks)
        for _ in range(30):
            m = rng.integers(1, len(all_ids))
            sub = sorted(rng.choice(len(all_ids), size=m, replace=False).tolist())
            ids = [all_ids[i] for i in sub]
            if code.peelable(ids):
                out = code.decode(shards[sub], ids)
                assert np.allclose(out, blocks, atol=1e-10)
            else:
                with pytest.raises(ValueError):
                    code.decode(shards[sub], ids)


class TestGradientCode:
    def test_exact_recovery_all_subsets(self):
        n, s = 6, 2
        gc = GradientCode(n, s, seed=0)
        rng = np.random.default_rng(6)
        grads = rng.standard_normal((n, 5))  # per-chunk gradients
        coded = gc.B @ grads  # what each worker computes
        total = grads.sum(axis=0)
        # every (n-s)-subset must reproduce the exact total gradient
        for idx in itertools.combinations(range(n), n - s):
            idx = list(idx)
            a = gc.decode_weights(idx)
            assert np.allclose(a @ coded[idx], total, atol=1e-8), idx

    def test_support_is_cyclic_window(self):
        gc = GradientCode(5, 2)
        assert gc.support(0) == [0, 1, 2]
        assert gc.support(3) == [3, 4, 0]
        assert gc.support(4) == [4, 0, 1]

    def test_more_than_minimum_workers_ok(self):
        gc = GradientCode(6, 2, seed=1)
        rng = np.random.default_rng(7)
        grads = rng.standard_normal((6, 4))
        coded = gc.B @ grads
        a = gc.decode_weights([0, 1, 2, 3, 4])  # 5 > n-s = 4
        assert np.allclose(a @ coded[[0, 1, 2, 3, 4]], grads.sum(0), atol=1e-8)

    def test_too_few_workers_raises(self):
        gc = GradientCode(6, 2)
        with pytest.raises(ValueError):
            gc.decode_weights([0, 1, 2])

    def test_s_zero_is_uncoded(self):
        gc = GradientCode(4, 0)
        assert np.count_nonzero(gc.B - np.diag(np.diag(gc.B))) == 0
        a = gc.decode_weights([0, 1, 2, 3])
        assert np.allclose(a * np.diag(gc.B), 1.0)

    def test_validation(self):
        with pytest.raises(ValueError):
            GradientCode(4, 4)
        with pytest.raises(ValueError):
            GradientCode(4, -1)


class TestLTNativePeel:
    """native/lt_peel.cpp vs the NumPy peeling loop: identical schedule,
    identical results, same stall behavior, all dtypes."""

    def _shards(self, code, k, ids, blocks):
        G = code.generator_rows(ids)
        return np.einsum("nk,krc->nrc", G, blocks)

    def test_native_matches_numpy_f64(self):
        from mpistragglers_jl_tpu.ops.lt import _load_native

        _load_native()  # skip-proof: raises -> toolchain truly missing
        rng = np.random.default_rng(11)
        k = 12
        code = LTCode(k, seed=3)
        ids = []
        s = 0
        while not code.peelable(ids):
            ids.append(s)
            s += 1
        blocks = rng.standard_normal((k, 7, 5))
        shards = self._shards(code, k, ids, blocks)
        a = code.decode(shards, ids, prefer_native=True)
        b = code.decode(shards, ids, prefer_native=False)
        # the release ORDER may differ (worklist vs rescan), so results
        # agree to rounding, not bitwise
        assert np.allclose(a, b, atol=1e-12)
        assert np.allclose(a, blocks, atol=1e-10)

    def test_native_f32_and_int_dtypes(self):
        rng = np.random.default_rng(12)
        k = 6
        code = LTCode(k, seed=2)
        ids = []
        s = 0
        while not code.peelable(ids):
            ids.append(s)
            s += 1
        for dtype, atol in ((np.float32, 1e-5), (np.int64, 0)):
            blocks = rng.integers(-50, 50, (k, 4, 3)).astype(dtype)
            shards = self._shards(code, k, ids, blocks.astype(np.float64))
            out = code.decode(shards.astype(dtype), ids)
            assert out.dtype == dtype
            assert np.allclose(out, blocks, atol=atol)

    def test_native_stall_raises(self):
        code = LTCode(8, seed=0)
        # a single shard cannot decode 8 blocks (unless degree-1 chain,
        # so pick ids until peelable is False with >= 1 shard)
        ids = [0]
        assert not code.peelable(ids)
        shards = np.zeros((1, 2, 2))
        with pytest.raises(ValueError, match="stalled"):
            code.decode(shards, ids, prefer_native=True)
