"""ThreadSanitizer run of the native transport (SURVEY §5 'race
detection / sanitizers' — the reference has none; round 1 shipped a
state-machine fuzzer, this adds the real thing).

The transport's epoll progress thread races caller threads on peer
state, send queues, completion deques, and payload handles by design;
one such race was an ADVICE finding in round 1. This test compiles
transport.cpp together with a C++ harness under ``-fsanitize=thread``
and drives the hot paths (auth handshake, 200 mixed-payload epochs with
a concurrent prober thread, mid-run death + reaccept, shm fd passing,
shutdown). TSAN runs with ``halt_on_error=1``: any detected race exits
non-zero and fails the test with the report attached.

TSAN must own the whole process, so this is a standalone binary, not a
.so in the pytest interpreter.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE = os.path.join(_REPO, "mpistragglers_jl_tpu", "native")


def _sanitizer_usable(flag: str) -> bool:
    import shutil
    import tempfile

    gxx = shutil.which("g++")
    if gxx is None:
        return False
    with tempfile.TemporaryDirectory() as d:
        src = os.path.join(d, "t.cpp")
        with open(src, "w") as f:
            f.write("int main(){return 0;}\n")
        probe = os.path.join(d, "t")
        r = subprocess.run(
            [gxx, flag, src, "-o", probe], capture_output=True
        )
        if r.returncode != 0:
            return False
        # the runtime itself can be unusable (e.g. high-entropy ASLR
        # kernels vs older libtsan abort at startup): require a clean RUN
        r = subprocess.run([probe], capture_output=True, timeout=30)
        return r.returncode == 0


@pytest.mark.slow
@pytest.mark.parametrize(
    "flag,env_opts",
    [
        ("-fsanitize=thread", {"TSAN_OPTIONS": "halt_on_error=1 exitcode=66"}),
        # ASAN implies LeakSanitizer: frame/payload buffers, payload
        # handles, shm regions, and peer state must all be released by
        # destroy/close — a leak or heap error fails the run
        ("-fsanitize=address", {"ASAN_OPTIONS": "halt_on_error=1 exitcode=66 detect_leaks=1"}),
        # ASan+UBSan combined: the frame codec does the pointer-cast /
        # length-arithmetic work (size headers, offset math into
        # payload buffers, enum kinds off the wire) where undefined
        # behavior hides without corrupting memory — shift overflows,
        # misaligned loads, out-of-range enums. One combined binary
        # (the probe compiles the joint flag, skipping cleanly where
        # either runtime is absent); UBSan halts like ASan so a UB
        # report is a test failure, not a stderr footnote.
        (
            "-fsanitize=address,undefined",
            {
                "ASAN_OPTIONS": "halt_on_error=1 exitcode=66 detect_leaks=1",
                "UBSAN_OPTIONS": "halt_on_error=1 exitcode=66 print_stacktrace=1",
            },
        ),
    ],
    ids=["tsan", "asan+lsan", "asan+ubsan"],
)
def test_transport_under_sanitizer(tmp_path, flag, env_opts):
    if not _sanitizer_usable(flag):
        pytest.skip(f"g++ {flag} not usable on this host")
    binary = str(tmp_path / "san_harness")
    build = subprocess.run(
        [
            "g++", "-std=c++17", "-O1", "-g", flag,
            os.path.join(_NATIVE, "tsan_harness.cpp"),
            os.path.join(_NATIVE, "transport.cpp"),
            "-o", binary, "-lpthread",
        ],
        capture_output=True, text=True, timeout=300,
    )
    assert build.returncode == 0, build.stderr[-3000:]
    env = dict(os.environ)
    env.update(env_opts)
    run = subprocess.run(
        [binary], capture_output=True, text=True, timeout=600, env=env,
    )
    sys.stderr.write(run.stderr[-4000:])
    assert run.returncode == 0, (
        f"{flag}-instrumented transport run failed "
        f"(rc={run.returncode}):\n{run.stderr[-4000:]}"
    )
    assert "reaccept ok" in run.stdout
