"""ThreadSanitizer run of the native transport (SURVEY §5 'race
detection / sanitizers' — the reference has none; round 1 shipped a
state-machine fuzzer, this adds the real thing).

The transport's epoll progress thread races caller threads on peer
state, send queues, completion deques, and payload handles by design;
one such race was an ADVICE finding in round 1. This test compiles
transport.cpp together with a C++ harness under ``-fsanitize=thread``
and drives the hot paths (auth handshake, 200 mixed-payload epochs with
a concurrent prober thread, mid-run death + reaccept, shm fd passing,
shutdown — plus the round-12 ring phase: the persistent result-ring
protocol with worker->coordinator SCM_RIGHTS announces, concurrent
producer/consumer access to one shared mapping, ack-frame slot
reclamation, and a deliberately pinned slot the producer must wrap
around). TSAN runs with ``halt_on_error=1``: any detected race exits
non-zero and fails the test with the report attached.

TSAN must own the whole process, so this is a standalone binary, not a
.so in the pytest interpreter.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE = os.path.join(_REPO, "mpistragglers_jl_tpu", "native")


# The probe exercises the sync primitives the transport actually uses:
# threads, a shared mutex, and a TIMED condition-variable wait
# (msgt_coord_waitany's timeout path). A trivial `int main` is not
# enough — some glibc/libtsan combinations run it cleanly yet emit a
# bogus "double lock of a mutex" on any pthread_cond_timedwait (seen
# on the round-12 driver box at the SEED commit, nondeterministic
# report site), which would fail the harness without any real finding.
_PROBE_SRC = """
#include <condition_variable>
#include <chrono>
#include <mutex>
#include <thread>
std::mutex mu;
std::condition_variable cv;
bool flag = false;
int counter = 0;
int main() {
  std::thread w([] {
    for (int i = 0; i < 100; i++) {
      std::unique_lock<std::mutex> lk(mu);
      cv.wait_until(lk,
          std::chrono::steady_clock::now() + std::chrono::milliseconds(1),
          [] { return flag; });
      counter++;
    }
  });
  for (int i = 0; i < 100; i++) {
    { std::lock_guard<std::mutex> lk(mu); counter++; }
    cv.notify_all();
  }
  w.join();
  return counter == 200 ? 0 : 1;
}
"""


def _sanitizer_usable(flag: str, env_opts=None) -> bool:
    import shutil
    import tempfile

    gxx = shutil.which("g++")
    if gxx is None:
        return False
    with tempfile.TemporaryDirectory() as d:
        src = os.path.join(d, "t.cpp")
        with open(src, "w") as f:
            f.write(_PROBE_SRC)
        probe = os.path.join(d, "t")
        r = subprocess.run(
            [gxx, "-std=c++17", flag, src, "-o", probe, "-lpthread"],
            capture_output=True,
        )
        if r.returncode != 0:
            return False
        # the runtime itself can be unusable (high-entropy ASLR kernels
        # vs older libtsan abort at startup; timed-condvar interceptor
        # mismatches report phantom mutex bugs): require a clean RUN of
        # the real primitive mix under the same halt-on-error options
        env = dict(os.environ)
        env.update(env_opts or {})
        r = subprocess.run(
            [probe], capture_output=True, timeout=60, env=env
        )
        return r.returncode == 0


@pytest.mark.slow
@pytest.mark.parametrize(
    "flag,env_opts",
    [
        ("-fsanitize=thread", {"TSAN_OPTIONS": "halt_on_error=1 exitcode=66"}),
        # ASAN implies LeakSanitizer: frame/payload buffers, payload
        # handles, shm regions, and peer state must all be released by
        # destroy/close — a leak or heap error fails the run
        ("-fsanitize=address", {"ASAN_OPTIONS": "halt_on_error=1 exitcode=66 detect_leaks=1"}),
        # ASan+UBSan combined: the frame codec does the pointer-cast /
        # length-arithmetic work (size headers, offset math into
        # payload buffers, enum kinds off the wire) where undefined
        # behavior hides without corrupting memory — shift overflows,
        # misaligned loads, out-of-range enums. One combined binary
        # (the probe compiles the joint flag, skipping cleanly where
        # either runtime is absent); UBSan halts like ASan so a UB
        # report is a test failure, not a stderr footnote.
        (
            "-fsanitize=address,undefined",
            {
                "ASAN_OPTIONS": "halt_on_error=1 exitcode=66 detect_leaks=1",
                "UBSAN_OPTIONS": "halt_on_error=1 exitcode=66 print_stacktrace=1",
            },
        ),
    ],
    ids=["tsan", "asan+lsan", "asan+ubsan"],
)
def test_transport_under_sanitizer(tmp_path, flag, env_opts):
    if not _sanitizer_usable(flag, env_opts):
        pytest.skip(f"g++ {flag} not usable on this host")
    binary = str(tmp_path / "san_harness")
    build = subprocess.run(
        [
            "g++", "-std=c++17", "-O1", "-g", flag,
            os.path.join(_NATIVE, "tsan_harness.cpp"),
            os.path.join(_NATIVE, "transport.cpp"),
            "-o", binary, "-lpthread",
        ],
        capture_output=True, text=True, timeout=300,
    )
    assert build.returncode == 0, build.stderr[-3000:]
    env = dict(os.environ)
    env.update(env_opts)
    run = subprocess.run(
        [binary], capture_output=True, text=True, timeout=600, env=env,
    )
    sys.stderr.write(run.stderr[-4000:])
    assert run.returncode == 0, (
        f"{flag}-instrumented transport run failed "
        f"(rc={run.returncode}):\n{run.stderr[-4000:]}"
    )
    assert "reaccept ok" in run.stdout
    # round-12 ring phase: fd-passing announce, concurrent
    # producer/consumer on shared pages, ack-driven slot reclaim with
    # a deliberately pinned slot — must have completed, not bailed
    assert "ring ok" in run.stdout
