"""Request router over scheduler replicas (models/router.py).

Three layers of contract:

* **replica hooks** — ``ServingScheduler.cancel`` withdraws a request
  from the queue, mid-admission, or mid-decode, returning its slot
  (and, paged, its pages);
* **live routing** — a router over REAL schedulers serves every stream
  token-for-token equal to the single-request oracle, balances load,
  routes shared prefixes to the replica already holding their pages,
  and hedges a stalled replica's requests (first-token-wins, loser
  cancelled);
* **health plane** — a replica whose health flips is ejected (its
  in-flight requests re-routed, zero drops) then resumed on recovery,
  and the ObsServer aggregate ``/healthz`` reports per-replica status
  while going 503 only when NO replica is admittable.

Policy-pricing and determinism claims live in tests/test_sim_workload.py
(virtual time); this file owns the live/jax half plus the health and
observability satellites.
"""

import dataclasses
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from mpistragglers_jl_tpu.models.decode import generate_ring_dense
from mpistragglers_jl_tpu.models.router import (
    ROUTER_POLICIES,
    RequestRouter,
)
from mpistragglers_jl_tpu.models.serving import ServingScheduler
from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)
from mpistragglers_jl_tpu.obs import FlightRecorder, MetricsRegistry
from mpistragglers_jl_tpu.obs.export import ObsServer
from mpistragglers_jl_tpu.sim import SimPrompt, SimReplica, VirtualClock
from mpistragglers_jl_tpu.utils.hedge import RequestHedge

CFG = TransformerConfig(
    vocab=61, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2,
    d_ff=128, attn_window=6,
)
PARAMS = init_params(CFG, seed=11)
RNG = np.random.default_rng(31)


def _prompt(n):
    return RNG.integers(1, CFG.vocab, size=n).astype(np.int32)


def _oracle(prompt, n_new):
    toks = generate_ring_dense(
        PARAMS, jnp.asarray(prompt)[None], n_new, CFG
    )
    return [int(t) for t in np.asarray(toks)[0]]


def _sched(**kw):
    kw.setdefault("slots", 2)
    kw.setdefault("n_inner", 4)
    kw.setdefault("prompt_chunk", 8)
    kw.setdefault("max_prompt", 64)
    return ServingScheduler(PARAMS, CFG, **kw)


def _get(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# --------------------------------------------------------------------------
# ServingScheduler.cancel — the replica hook
# --------------------------------------------------------------------------


class TestSchedulerCancel:
    def test_cancel_queued_request(self):
        s = _sched(slots=1)
        a = s.submit(_prompt(5), max_new=8)
        b = s.submit(_prompt(5), max_new=8)
        assert s.cancel(b) is True
        assert b.finished and b.reason == "cancelled"
        assert s.pending == 1  # only a remains queued
        s.run()
        assert a.tokens == _oracle(a.prompt, 8)

    def test_cancel_decoding_request_frees_slot(self):
        s = _sched(slots=1)
        a = s.submit(_prompt(5), max_new=40)
        b = s.submit(_prompt(5), max_new=8)
        s.step(); s.step()
        assert a.tokens and not a.finished  # decoding
        assert s.cancel(a) is True
        assert a.reason == "cancelled"
        s.run()
        # b got the freed slot and its stream is untouched by a's life
        assert b.tokens == _oracle(b.prompt, 8)

    def test_cancel_mid_admission_dense(self):
        s = _sched(slots=1, prompt_chunk=4)
        a = s.submit(_prompt(16), max_new=8)  # 4 chunks
        s.step()  # admission starts, not finished
        assert s.active == 1 and not a.tokens
        assert s.cancel(a) is True
        assert s.active == 0
        assert not s.cancel(a)  # idempotent: already finished

    def test_cancel_unknown_request_is_false(self):
        s = _sched()
        other = _sched()
        r = other.submit(_prompt(4), max_new=4)
        assert s.cancel(r) is False
        assert not r.finished

    def test_cancel_paged_returns_pages(self):
        s = _sched(slots=2, page_tokens=3)
        base_free = s.pool.free
        # cancel at every lifecycle stage; the pool must drain back
        # to its baseline each time (mid-admission pages live in the
        # plan, not the device table — the leak the hook must not have)
        q = s.submit(_prompt(5), max_new=12)           # queued
        assert s.cancel(q) and s.pool.free == base_free
        a = s.submit(_prompt(16), max_new=12)
        s.step()                                        # admitting
        assert s.cancel(a) and s.pool.free == base_free
        d = s.submit(_prompt(5), max_new=12)
        s.step(); s.step()                              # decoding
        assert d.tokens and s.cancel(d)
        assert s.pool.free == base_free

    def test_cancelled_never_counts_as_retired_metric(self):
        reg = MetricsRegistry()
        s = _sched(slots=1, registry=reg)
        a = s.submit(_prompt(5), max_new=6)
        s.step()
        s.cancel(a)
        s.run()
        snap = reg.snapshot()
        retired = sum(
            series["value"]
            for series in snap["serving_retired_total"]["series"]
        ) if "serving_retired_total" in snap else 0
        assert retired == 0


# --------------------------------------------------------------------------
# RequestHedge bookkeeping
# --------------------------------------------------------------------------


class TestRequestHedge:
    def test_due_fires_once_in_deadline_order(self):
        h = RequestHedge()
        a, b, c = object(), object(), object()
        h.arm(a, 2.0); h.arm(b, 1.0); h.arm(c, 5.0)
        assert h.next_deadline() == 1.0
        assert h.due(2.0) == [b, a]  # (deadline, arm-seq) order
        assert h.due(2.0) == []      # exactly once
        assert len(h) == 1
        h.disarm(c)
        assert h.next_deadline() is None

    def test_rearm_supersedes_and_ties_fire_in_arm_order(self):
        h = RequestHedge()
        a, b = object(), object()
        h.arm(a, 1.0)
        h.arm(b, 1.0)
        h.arm(a, 3.0)  # re-arm: the 1.0 deadline becomes a tombstone
        assert h.due(1.0) == [b]
        assert h.next_deadline() == 3.0
        assert h.due(3.0) == [a]

    def test_disarm_unknown_is_noop(self):
        h = RequestHedge()
        h.disarm(object())
        assert len(h) == 0


# --------------------------------------------------------------------------
# live routing over real schedulers
# --------------------------------------------------------------------------


class TestLiveRouting:
    def test_policy_validation(self):
        with pytest.raises(ValueError, match="unknown policy"):
            RequestRouter([_sched()], policy="fastest")
        with pytest.raises(ValueError, match="ttft_slo"):
            RequestRouter([_sched()], policy="hedge_p99")
        with pytest.raises(ValueError, match="at least one replica"):
            RequestRouter([])
        with pytest.raises(ValueError, match="max_new"):
            RequestRouter([_sched()]).submit(_prompt(4), max_new=0)
        assert set(ROUTER_POLICIES) == {
            "round_robin", "least_loaded", "prefix_affinity",
            "hedge_p99", "two_tier",
        }
        # two_tier needs an actual two-tier fleet shape
        with pytest.raises(ValueError, match="EACH tier"):
            RequestRouter([_sched()], policy="two_tier")

    @pytest.mark.parametrize("policy", ["round_robin", "least_loaded"])
    def test_streams_equal_oracle_across_replicas(self, policy):
        scheds = [_sched() for _ in range(3)]
        router = RequestRouter(scheds, policy=policy)
        prompts = [_prompt(3 + i % 4) for i in range(7)]
        rrs = [router.submit(p, max_new=6) for p in prompts]
        router.drain()
        for rr, p in zip(rrs, prompts):
            assert rr.finished and rr.outcome == "ok"
            assert rr.ttft is not None and rr.latency >= rr.ttft
            assert list(rr.tokens) == _oracle(p, 6)
        # round_robin spread them over every replica
        if policy == "round_robin":
            assert {rr.replica for rr in rrs} == {0, 1, 2}

    def test_least_loaded_picks_the_empty_replica(self):
        scheds = [_sched(slots=4), _sched(slots=4)]
        router = RequestRouter(scheds, policy="least_loaded")
        for _ in range(3):
            router.submit(_prompt(4), max_new=16)
        rr = router.submit(_prompt(4), max_new=16)
        # 3 on replica 0's books vs 0 on replica 1 never happens:
        # least-loaded alternates as depth grows
        depth = [s.pending + s.active for s in scheds]
        assert abs(depth[0] - depth[1]) <= 1
        router.drain()
        assert rr.finished

    def test_prefix_affinity_follows_resident_pages(self):
        # a wider window so a shared system prompt fits unwrapped AND
        # the first sharer stays resident while the second arrives
        # (wrapped prompts are neither shared nor registered, and a
        # retired holder's pages leave the prefix table — the paged-
        # cache contract); params are window-independent
        cfg = dataclasses.replace(CFG, attn_window=48)
        scheds = [
            ServingScheduler(PARAMS, cfg, slots=2, n_inner=4,
                             prompt_chunk=4, max_prompt=64,
                             page_tokens=4)
            for _ in range(3)
        ]
        router = RequestRouter(scheds, policy="prefix_affinity")
        system = _prompt(12)  # 3 page-aligned prefix pages at P=4
        p1 = np.concatenate([system, _prompt(4)])
        p2 = np.concatenate([system, _prompt(4)])
        r1 = router.submit(p1, max_new=24)  # horizon 44 < W: no wrap
        # tick until r1's prefix pages are registered (admission done)
        for _ in range(12):
            router.step()
            if r1.tokens:
                break
        assert r1.tokens and not r1.finished  # resident, decoding
        r2 = router.submit(p2, max_new=4)
        assert r2.replica == r1.replica  # routed to the pages
        router.drain()
        assert scheds[r1.replica].pool.share_hits > 0
        toks = generate_ring_dense(
            PARAMS, jnp.asarray(p2)[None], 4, cfg
        )
        assert list(r2.tokens) == [int(t) for t in np.asarray(toks)[0]]

    # the one real-thread hedging smoke of this family (virtual-time
    # siblings in tests/test_sim_workload.py carry the exact claims)
    # graftcheck: real-smoke
    def test_hedge_p99_live_first_token_wins(self):
        class Stalled(ServingScheduler):
            """A replica wedged for its next 3 ticks (sleeping, no
            progress — the stuck-scheduler signature): TTFT blows the
            SLO while the request sits in its queue, then the replica
            recovers and finds its leg already cancelled."""

            stalls = 3

            def step(self):
                if self.stalls > 0:
                    self.stalls -= 1
                    time.sleep(0.06)
                    return []
                return super().step()

        slow = Stalled(PARAMS, CFG, slots=2, n_inner=4,
                       prompt_chunk=8, max_prompt=64)
        fast = _sched()
        router = RequestRouter([slow, fast], policy="hedge_p99",
                               ttft_slo=0.05)
        rr = router.submit(_prompt(5), max_new=6)
        assert rr.replica == 0
        router.drain()
        assert rr.finished
        assert rr.hedged and rr.outcome == "hedge_won"
        assert rr.replica == 1  # the fast replica's token won
        assert router.n_hedges == 1
        assert list(rr.tokens) == _oracle(rr.prompt, 6)
        # the losing leg was cancelled on the slow replica
        assert slow.active == 0 and slow.pending == 0


# --------------------------------------------------------------------------
# health plane: ejection, re-route, recovery, /healthz aggregate
# --------------------------------------------------------------------------


class TestHealthPlane:
    def _sim_router(self, n=4, **kw):
        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=2, n_inner=8, prompt_chunk=64,
                       tick_s=0.01)
            for _ in range(n)
        ]
        return clock, reps, RequestRouter(reps, clock=clock, **kw)

    def _run(self, clock, router, until_idle=True, max_events=10_000):
        for _ in range(max_events):
            nt = router.next_event_at()
            if nt is None:
                return
            clock.run_until(nt)
            router.step()
            if until_idle and router.in_flight == 0:
                return

    def test_kill_ejects_reroutes_and_recover_resumes(self):
        clock, reps, router = self._sim_router()
        flight = FlightRecorder()
        router._obs = None  # rebuilt below with flight only
        router2 = RequestRouter(reps, clock=clock, flight=flight,
                                policy="round_robin")
        rrs = [router2.submit(SimPrompt(64), 64) for _ in range(8)]
        victim = rrs[1].replica
        reps[victim].kill()
        router2.step()  # health flip observed: eject + re-route
        assert victim not in router2.routable_replicas
        # eviction CANCELLED the abandoned legs (a drained-but-alive
        # replica must not decode zombie streams after recovery); the
        # killed SimReplica wiped its books, so nothing was cancellable
        assert reps[victim].pending == 0 and reps[victim].active == 0
        assert all(
            rr.replica != victim for rr in rrs if not rr.finished
        )
        # nothing routes there while it is down
        for _ in range(4):
            assert router2.submit(SimPrompt(64), 8).replica != victim
        self._run(clock, router2)
        assert all(rr.finished for rr in rrs)  # zero dropped
        # flight recorder carries the ejection instant event
        doc = flight.snapshot()
        names = {e.get("name") for e in doc["traceEvents"]}
        assert "replica ejected" in names
        # recovery: the replica takes traffic again
        reps[victim].revive()
        router2.step()
        assert victim in router2.routable_replicas
        seen = {
            router2.submit(SimPrompt(64), 8).replica
            for _ in range(len(reps))
        }
        assert victim in seen
        self._run(clock, router2)
        doc = flight.snapshot()
        names = {e.get("name") for e in doc["traceEvents"]}
        assert "replica restored" in names

    def test_mark_down_and_up_are_manual_overrides(self):
        clock, reps, router = self._sim_router(n=2)
        router.mark_down(0)
        router.step()
        assert router.routable_replicas == [1]
        router.mark_up(0)
        router.step()
        assert router.routable_replicas == [0, 1]

    def test_mark_down_cancels_legs_on_the_drained_replica(self):
        """An operator drain (mark_down of a replica that is still
        ALIVE) must cancel the re-routed requests' abandoned legs —
        otherwise the drained replica decodes zombie streams for their
        whole budget and resumes with its slots full."""
        clock, reps, router = self._sim_router(n=2)
        rrs = [router.submit(SimPrompt(64), 64) for _ in range(4)]
        on0 = sum(rr.replica == 0 for rr in rrs)
        assert on0 > 0
        router.mark_down(0)
        router.step()
        assert reps[0].n_cancelled == on0
        assert reps[0].pending == 0 and reps[0].active == 0
        self._run(clock, router)
        assert all(rr.finished for rr in rrs)

    def test_healthz_aggregate_503_only_when_none_admittable(self):
        clock, reps, router = self._sim_router()
        with ObsServer() as srv:
            srv.register_router(router)
            # all up: 200, detail carries every replica
            status, body = _get(srv.url + "/healthz")
            assert status == 200
            doc = json.loads(body)
            detail = doc["checks"]["router"]["detail"]
            assert "4/4 replicas routable" in detail
            for i in range(4):
                assert f"replica {i}:" in detail
            # one dead: DEGRADED detail but still 200 — the router
            # routes around it, that is not an outage
            reps[0].kill()
            router.step()
            status, body = _get(srv.url + "/healthz")
            assert status == 200
            detail = json.loads(body)["checks"]["router"]["detail"]
            assert "3/4 replicas routable" in detail
            assert "replica 0: ejected" in detail
            # all dead: NOW it is an outage — 503
            for r in reps[1:]:
                r.kill()
            router.step()
            status, body = _get(srv.url + "/healthz")
            assert status == 503
            assert "0/4 replicas routable" in (
                json.loads(body)["checks"]["router"]["detail"]
            )
            # recovery flips it back
            reps[2].revive()
            router.step()
            status, _ = _get(srv.url + "/healthz")
            assert status == 200

    def test_exporter_kwarg_registers_the_check(self):
        clock, reps, router = self._sim_router(n=2)
        srv = ObsServer()
        RequestRouter(reps, clock=clock, exporter=srv)
        ok, doc = srv.healthz()
        assert ok and "router" in doc["checks"]

    def test_live_scheduler_statuses_report_tick_freshness(self):
        scheds = [_sched(), _sched()]
        for s in scheds:
            s.enable_tick_stamping()  # a dark scheduler never stamps
        router = RequestRouter(scheds)
        rr = router.submit(_prompt(4), max_new=4)
        router.drain()
        assert rr.finished
        statuses = router.replica_statuses()
        assert statuses[0][0] is True
        assert "last tick" in statuses[0][1]  # freshness detail


# --------------------------------------------------------------------------
# router observability (registry + flight, opt-in)
# --------------------------------------------------------------------------


class TestRouterObservability:
    def test_metrics_series(self):
        reg = MetricsRegistry()
        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=2, n_inner=8, prompt_chunk=64,
                       tick_s=lambda t, m=(1.0, 6.0)[i]: 0.01 * m)
            for i in range(2)
        ]
        router = RequestRouter(reps, policy="hedge_p99",
                               ttft_slo=0.03, clock=clock,
                               registry=reg)
        rrs = [router.submit(SimPrompt(64), 16) for _ in range(6)]
        while router.in_flight:
            clock.run_until(router.next_event_at())
            router.step()
        snap = reg.snapshot()
        done = {
            (s["labels"]["replica"], s["labels"]["outcome"]):
            s["value"]
            for s in snap["router_requests_total"]["series"]
        }
        assert sum(done.values()) == 6
        assert all(
            s["labels"]["policy"] == "hedge_p99"
            for s in snap["router_requests_total"]["series"]
        )
        assert snap["router_hedge_fired_total"]["series"][0][
            "value"
        ] == router.n_hedges > 0
        assert reg.histogram("router_ttft_seconds").count == 6
        assert reg.histogram("router_queue_wait_seconds").count == 6
        # per-replica depth gauges exist for both replicas
        for i in range(2):
            reg.gauge("router_replica_depth", replica=str(i))
        assert reg.gauge("router_routable_replicas").value == 2

    def test_flight_hedge_fire_event(self):
        flight = FlightRecorder()
        clock = VirtualClock()
        reps = [
            SimReplica(clock, slots=2, n_inner=8, prompt_chunk=64,
                       tick_s=0.01 * (1.0, 6.0)[i])
            for i in range(2)
        ]
        router = RequestRouter(reps, policy="hedge_p99",
                               ttft_slo=0.03, clock=clock,
                               flight=flight)
        router.submit(SimPrompt(64), 16)
        router.submit(SimPrompt(64), 16)
        while router.in_flight:
            clock.run_until(router.next_event_at())
            router.step()
        doc = flight.snapshot()
        names = [e.get("name") for e in doc["traceEvents"]]
        assert "hedge fired" in names

    def test_dark_router_has_no_obs(self):
        router = RequestRouter([_sched()])
        assert router._obs is None
