"""GC002 bad fixture: shimmed jax APIs used without importing
_jax_compat, plus a direct pltpu.CompilerParams access outside its
home module. Violation lines pinned by the fixture test."""

import jax
from jax.experimental import pallas as pl  # noqa: F401
from jax.experimental.pallas import tpu as pltpu


def sharded(f, mesh, spec):
    return jax.shard_map(  # GC002 line 11: no _jax_compat import
        f, mesh=mesh, in_specs=spec, out_specs=spec
    )


def axis(name):
    return jax.lax.axis_size(name)  # GC002 line 17


def params():
    return pltpu.CompilerParams(  # GC002 line 21: outside flash home
        dimension_semantics=("parallel",)
    )
