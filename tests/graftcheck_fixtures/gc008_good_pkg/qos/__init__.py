"""GC008 good fixture, qos half: tenant-budget code on the injected
clock only — the TokenBucket discipline (``now`` enters through the
caller's clock argument, never an OS-clock import), so a tenant-mixed
day replays bit-identically on VirtualClock."""


def refill(bucket, now):
    if now > bucket.last:
        bucket.tokens = min(
            bucket.burst,
            bucket.tokens + bucket.rate * (now - bucket.last),
        )
        bucket.last = now
    return bucket.tokens
