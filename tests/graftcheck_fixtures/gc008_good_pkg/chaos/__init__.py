"""GC008 good fixture, chaos half: episode probes on the injected
virtual clock only — the ChaosInjector discipline (``now`` comes from
the scenario's VirtualClock, timing from the scenario's seed), so an
episode that fails replays bit-identically."""


def probe(router, state, clock):
    now = clock.now()
    if router.in_flight and now - state["last"] > 30.0:
        raise AssertionError("deadlock")
    state["last"] = now
    return now
