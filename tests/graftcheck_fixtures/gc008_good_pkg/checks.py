"""GC008 good fixture, margin half: the sanctioned shapes — exact
virtual-time claims, gross (>= 1 s) real ceilings, relative
comparisons, and ONE marked real smoke whose sub-second margin is
thereby sanctioned."""

import time


def exact_on_virtual_time(clock, run, latency):
    t0 = clock.now()
    run()
    assert clock.now() - t0 == latency  # exact: no margin at all


def gross_ceiling(run):
    t0 = time.perf_counter()
    run()
    assert time.perf_counter() - t0 < 4.0  # >= 1 s: a failure
    # detector, not a scheduler race


def relative_budget(run, budget):
    t0 = time.perf_counter()
    run()
    wall = time.perf_counter() - t0
    assert wall < budget + 0.5  # relative to a caller bound: allowed


# graftcheck: real-smoke
def real_thread_smoke(run, latency):
    t0 = time.perf_counter()
    run()
    assert abs(time.perf_counter() - t0 - latency) < 0.1  # sanctioned
