"""GC008 good fixture, fleet half: decision code on the injected
clock/timer only — the FleetController discipline (wall seconds enter
through the call site's ``timer=``, never an OS-clock import)."""


def decide(controller, signals):
    t0 = controller.timer()  # injected: clock.now in sim, any live
    if signals.utilization > controller.high:
        controller.grow()
    controller.decision_s = controller.timer() - t0
    return controller.decision_s
