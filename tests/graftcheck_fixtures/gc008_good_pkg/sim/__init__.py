"""GC008 good fixture, sim half: the virtual-time plane reads only
its own clock."""


def advance(clock, dt):
    t0 = clock.now()
    clock.run_until(t0 + dt)
    return clock.now() - t0  # virtual elapsed: exact, reproducible
