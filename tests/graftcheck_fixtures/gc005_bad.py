"""GC005 bad fixture: cross-thread writes with no lock. Violation
lines pinned by the fixture test."""

import threading


class Harvester:
    def __init__(self):
        self.results = {}
        self.closed = False  # __init__ writes are exempt
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self.closed:
            self.results = dict(self.results)  # GC005 line 17
            self.closed = self.closed or False  # GC005 line 18

    def reset(self):
        self.results = {}  # GC005 line 21: races _loop, unlocked
        self.closed = False  # GC005 line 22

    def read_only(self):
        return len(self.results)  # reads are out of scope
