"""GC002 good fixture: the module-level _jax_compat import makes the
shimmed spellings safe on lagging toolchains; CompilerParams is
reached through the flash module's alias."""

import jax
from mpistragglers_jl_tpu import _jax_compat  # noqa: F401
from mpistragglers_jl_tpu.ops.flash_attention import _CompilerParams


def sharded(f, mesh, spec):
    return jax.shard_map(
        f, mesh=mesh, in_specs=spec, out_specs=spec
    )


def axis(name):
    return jax.lax.axis_size(name)


def params():
    return _CompilerParams(dimension_semantics=("parallel",))
