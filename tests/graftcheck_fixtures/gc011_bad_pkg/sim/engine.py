"""GC011 bad fixture: witness writes outside the home module.
Violation lines pinned by tests/test_graftcheck.py."""


def finish(rep, ft, done):
    rep.ttft = ft  # GC011 line 6: witness column written locally
    rep.latency = done  # GC011 line 7: the other column


def digest(report):  # GC011 line 10: a second witness definition
    return hash(report)


class View:
    def close(self, arr):
        self.latency = arr  # GC011 line 16: self-write, same contract
        self.ttft: list = []  # GC011 line 17: annotated assignment too
