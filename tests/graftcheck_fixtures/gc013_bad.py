"""GC013 bad fixture: stale suppressions. One disable comment earns
its keep (the GC010 it covers still fires — no GC013 there); the
rest suppress nothing. Violation lines pinned by the fixture test."""


def refuse(obs, rr):
    obs.shed(rr)  # graftcheck: disable=GC010
    return rr


def fixed_long_ago(obs, rr):
    obs.shed(rr, reason="overload")  # graftcheck: disable=GC010
    return rr


def half_stale(obs, rr):
    # graftcheck: disable=GC010,GC005
    obs.shed(rr)
    return rr


def typo(obs, rr):
    obs.shed(rr, reason="hot")  # graftcheck: disable=GC910
    return rr


def all_for_nothing(obs, rr):
    obs.shed(rr, reason="warm")  # graftcheck: disable=all
    return rr
