"""GC010 good fixture: every shed shape the rule accepts."""


def shed_at_door(rr, reason):
    """The RequestRouter._shed_at_door shape: the request carries its
    reason, dark or not."""
    rr.outcome = "shed"
    rr.shed_reason = str(reason)
    return rr


def refuse(obs, rr, reason):
    """A *reason*-named positional is identifiable."""
    obs.shed(rr, reason, 0.0)
    return rr


def refuse_literal(obs, rr):
    """A non-empty string literal positional is identifiable."""
    obs.shed(rr, "overload")
    return rr


def refuse_kw(queue, rr, why):
    """reason= with any non-trivial expression passes."""
    queue.drop(rr, reason=f"quota:{why}")
    return rr


def constructor_clear(rr):
    """Clearing shed_reason where nothing sheds is construction-time
    state, not a drop (rule 3 fires only in functions that shed)."""
    rr.shed_reason = None
    rr.outcome = None
    return rr


def unrelated(book, rr):
    """`dropped`/`hedge` are not shed words (segment match, not
    substring)."""
    book.dropped_total = 1
    return rr
