"""GC011 good half: OUTSIDE the sim package the rule does not apply —
other planes may keep their own digest()s and latency fields."""


class ChaosReport:
    def __init__(self, spans):
        self.latency = spans
        self.ttft = None

    def digest(self):
        return hash(tuple(self.latency))
