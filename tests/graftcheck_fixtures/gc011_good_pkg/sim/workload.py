"""GC011 good half: the HOME module — the one place the witness
columns are stamped and the one digest definition."""


class WorkloadReport:
    def __init__(self, served):
        self.ttft = [r.ft for r in served]
        self.latency = [r.done for r in served]

    @classmethod
    def from_arrays(cls, ttft, latency):
        rep = cls.__new__(cls)
        rep.ttft = ttft
        rep.latency = latency
        return rep

    def digest(self):
        return hash((tuple(self.ttft), tuple(self.latency)))
