"""GC011 good half: everything a NON-home sim module may do with the
witness — read it, pass it as keywords, expose request-view
properties, bind locals — without ever assigning the columns."""

from .workload import WorkloadReport


def finish(ft, done):
    ttft = list(ft)  # a plain local, not an attribute write
    latency = list(done)
    return WorkloadReport.from_arrays(ttft=ttft, latency=latency)


def check(rep):
    return rep.digest() == rep.digest() and len(rep.ttft) >= 0


class RequestView:
    @property
    def ttft(self):  # a property DEF is a read surface, not a write
        return self._ft - self._sub

    @property
    def latency(self):
        return self._done - self._sub
