"""Reachable from the root, but every jax touch is lazy."""

from typing import TYPE_CHECKING

import numpy as np

if TYPE_CHECKING:  # never executes: sanctioned
    import jax


class Pool:
    def run(self, x):
        import jax  # lazy: first device use pays it, import does not

        return jax.numpy.asarray(np.asarray(x))
