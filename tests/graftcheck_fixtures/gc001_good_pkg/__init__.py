"""GC001 good fixture: jax stays behind lazy imports and
TYPE_CHECKING, exactly the escape hatches the rule sanctions."""

from .core import Pool  # noqa: F401
