"""GC009 good fixture, Python half: kind table and ctypes signatures
in sync with the sibling transport.cpp. ``KIND_ACK`` is
Python-internal (no cpp twin) at a non-colliding value, and pointer
FLAVOR varies by call site (c_char_p vs c_void_p vs POINTER) — all
legal marshals of a C pointer."""

import ctypes

KIND_DATA = 0
KIND_CONTROL = 1
KIND_DEATH = 2
KIND_ACK = 8  # Python-internal: resolves to KIND_DATA on the wire


def _configure(lib):
    lib.msgt_create.restype = ctypes.c_void_p
    lib.msgt_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.msgt_send.restype = ctypes.c_int
    lib.msgt_send.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.msgt_take.restype = ctypes.c_int64
    lib.msgt_take.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]
    lib.msgt_destroy.restype = None
    lib.msgt_destroy.argtypes = [ctypes.c_void_p]
