// GC009 good fixture, C++ half: in sync with the sibling transport.py.
#include <cstdint>

constexpr int64_t KIND_DATA = 0;
constexpr int64_t KIND_CONTROL = 1;
constexpr int64_t KIND_DEATH = 2;

extern "C" {

void* msgt_create(const char* addr, int n) { return nullptr; }

int msgt_send(void* h, int rank, int64_t seq, const uint8_t* data,
              int64_t len) {
  return 0;
}

int64_t msgt_take(void* h, int rank, uint8_t* buf, int64_t cap) {
  return 0;
}

void msgt_destroy(void* h) {}

}  // extern "C"
