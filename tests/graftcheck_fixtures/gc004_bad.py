"""GC004 bad fixture: opt-in contract violations. Violation lines
pinned by the fixture test."""


def tick(payload, tracer=None):
    tracer.begin("tick", 0, 0)  # GC004 line 6: unguarded deref
    return payload


def observe(payload, registry=None):
    if registry is not None:
        registry.counter("serving.bad.name").inc()  # GC004 line 12
    return payload


def serve(payload, exporter=None):
    exporter.add_health("pool", None)  # GC004 line 17: unguarded deref
    return payload


def record(payload, flight=None):
    flight.event("dispatch")  # GC004 line 22: unguarded deref
    return payload


def publish(payload, registry=False):  # GC004 line 26: non-None default
    return payload


def page_pool_tick(pool, registry=None):
    # the paged-cache telemetry shape: sampling pool occupancy into
    # the registry without the None guard
    registry.gauge("serving_cache_pages_free").set(pool)  # GC004 line 33
    return pool


def harvest_ring(frame, registry=None):
    # the round-12 zero-copy transport telemetry shape: mirroring the
    # coordinator's ring stats into the registry without the None guard
    registry.counter("transport_zero_copy_bytes_total").inc(frame)  # GC004 line 40
    return frame


def hier_decode(arrived, registry=None, flight=None):
    # the round-14 hierarchical-decode telemetry shape: counting an
    # outer-code recovery without the None guards
    registry.counter("hier_outer_recoveries_total").inc()  # GC004 line 47
    flight.event("hier outer recovery")  # GC004 line 48
    return arrived


def route_request(replica, registry=None, flight=None):
    # the round-15 router telemetry shape: counting a routed request
    # and stamping the hedge-fire instant event without the None guards
    registry.counter("router_requests_total").inc()  # GC004 line 55
    flight.event("hedge fired", replica=replica)  # GC004 line 56
    return replica


def migrate_ticket(ticket, registry=None, flight=None):
    # the round-16 disaggregation telemetry shape: counting a landed
    # KV-page migration without the None guards
    registry.counter("disagg_migrations_total").inc()  # GC004 line 63
    flight.event("kv migrated", pages=ticket)  # GC004 line 64
    return ticket


def fused_harvest(repochs, registry=None, flight=None):
    # the round-17 device-coordination telemetry shape: counting a
    # K-epoch window harvest without the None guards
    registry.counter("devcoord_harvests_total").inc()  # GC004 line 71
    flight.span("devcoord window", 0.0, 0.0)  # GC004 line 72
    return repochs


def fleet_decide(decision, registry=None, flight=None):
    # the round-18 fleet-controller telemetry shape: counting an
    # accepted resize and stamping the decision instant event without
    # the None guards
    registry.counter("fleet_resizes_total").inc()  # GC004 line 80
    flight.event("fleet decision", seq=decision)  # GC004 line 81
    return decision


def qos_admit(tenant, registry=None, flight=None):
    # the round-19 multi-tenant QoS telemetry shape: counting a DRR
    # admission and stamping the reclaim instant event without the
    # None guards
    registry.counter("qos_admitted_total").inc()  # GC004 line 89
    flight.event("qos reclaim", tenant=tenant)  # GC004 line 90
    return tenant


def chaos_inject(episode, registry=None, flight=None):
    # the round-20 chaos-plane telemetry shape: counting a completed
    # episode and stamping the begin/end instants without the None
    # guards
    registry.counter("chaos_episodes_total").inc()  # GC004 line 98
    flight.event("chaos episode", scenario=episode)  # GC004 line 99
    return episode


def trace_append(tid, trace=None):
    # the round-22 causal-tracing shape: appending a lifecycle event
    # to the trace book without the None guard
    trace.event(tid, "first_token", 0.0)  # GC004 line 106
    return tid


def window_roll(now, series=None, slo=None):
    # the round-24 windowed-SLO shape: rolling the series store and
    # evaluating the burn policy without the None guards
    series.maybe_roll(now)  # GC004 line 113
    slo.maybe_roll(now)  # GC004 line 114
    return now


def cache_publish(digest, registry=None, flight=None):
    # the round-25 fleet-cache shape: counting a directory publish on
    # the size gauge and stamping the spill instant without the None
    # guards
    registry.gauge("cache_directory_size").set(digest)  # GC004 line 122
    flight.event("page spilled", digest=digest)  # GC004 line 123
    return digest
