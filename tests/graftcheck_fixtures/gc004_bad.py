"""GC004 bad fixture: opt-in contract violations. Violation lines
pinned by the fixture test."""


def serve(payload, registry):  # GC004 line 5: public, no default
    registry.counter("serving_requests_total").inc()
    return payload


def tick(payload, tracer=None):
    tracer.begin("tick", 0, 0)  # GC004 line 11: unguarded deref
    return payload


def observe(payload, registry=None):
    if registry is not None:
        registry.counter("serving.bad.name").inc()  # GC004 line 17
    return payload
