"""GC006 bad fixture: a lock-order cycle (one lexical, one through an
intra-class call), a non-reentrant self-re-acquisition, and three
blocking-under-lock shapes. Violation lines pinned by the fixture
test."""

import pickle
import threading
import time


class Pump:
    def __init__(self, conn, cond):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._conn = conn
        self._cond = cond

    def forward(self):
        with self._a:
            with self._b:  # GC006 line 20: a->b, but reap takes b->a
                return self._drain()  # GC006 line 21: _a is a Lock
                # (non-reentrant) and _drain re-acquires it while held

    def reap(self):
        with self._b:
            self._take_a()  # the b->a edge rides the call graph

    def _take_a(self):
        with self._a:
            pass

    def _drain(self):
        with self._a:
            return None

    def pull(self):
        with self._a:
            return self._conn.recv()  # GC006 line 38: recv under lock

    def park(self):
        with self._b:
            self._cond.wait()  # GC006 line 42: wait with no timeout

    def snapshot(self, obj):
        with self._b:
            data = pickle.dumps(obj)  # GC006 line 46: pickle under lock
            time.sleep(0.01)  # GC006 line 47: sleep under lock
        return data


class ThreeWay:
    """A 3-lock cycle no pairwise reverse-edge test can see: a->b,
    b->c, c->a — three threads interleaving these deadlock."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:  # GC006 line 62: the a->b leg of a 3-cycle
                pass

    def bc(self):
        with self._b:
            with self._c:
                pass

    def ca(self):
        with self._c:
            with self._a:
                pass
