"""GC013 good fixture: every disable comment suppresses a finding
that still exists — same-line, line-above, and blanket forms."""


def refuse(obs, rr):
    obs.shed(rr)  # graftcheck: disable=GC010
    return rr


def refuse_above(obs, rr):
    # graftcheck: disable=GC010
    obs.shed(rr)
    return rr


def blanket(obs, rr):
    obs.shed(rr)  # graftcheck: disable=all
    return rr
