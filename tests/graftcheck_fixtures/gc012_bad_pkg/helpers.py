"""Helpers OUTSIDE every replay plane: nothing here is flagged
at-source, but taint crosses into ``sim/`` through a return value
(``unordered_ids``) and through a kwarg into a digest sink
(``stamp``). The findings land in sim/day.py, naming these lines."""

import hashlib


def unordered_ids(events):
    ids = {e.node for e in events}
    return list(ids)  # order-revealing: list() over a set


def stamp(payload, *, salt=b""):
    h = hashlib.sha256()
    h.update(salt)
    h.update(payload)  # param sink: `payload` is a digest input
    return h.hexdigest()
