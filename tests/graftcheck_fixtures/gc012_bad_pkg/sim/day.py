"""GC012 bad fixture: a deliberately impure day engine. Every
source class fires, plus two interprocedural flows through
``..helpers``. Violation lines pinned by the fixture test."""

import hashlib
import heapq
import os
import random
import uuid

import numpy as np

from ..helpers import stamp, unordered_ids


def seed_state():
    rng = np.random.default_rng()  # GC012: unseeded default_rng
    jitter = np.random.normal()  # GC012: module-global RNG state
    token = uuid.uuid4()  # GC012: uuid4
    salt = os.urandom(8)  # GC012: OS entropy
    draw = random.random()  # GC012: process-global RNG
    mode = os.environ.get("DAY_MODE", "fast")  # GC012: environ in sim
    level = os.getenv("DAY_LEVEL")  # GC012: getenv in sim
    return rng, jitter, token, salt, draw, mode, level


def digest_events(events):
    nodes = {e.node for e in events}
    h = hashlib.sha256()
    for n in nodes:  # set iteration order...
        h.update(n)  # GC012: ...reaches the digest here
    return h.hexdigest()


def rank(e):
    return hash(e)  # id-order: sink-gated, flagged at the sort below


def order_events(events):
    events.sort(key=rank)  # GC012: hash()-ordered sort key
    events.sort(key=lambda e: id(e))  # GC012: id()-ordered sort key
    heap = []
    for e in events:
        heapq.heappush(heap, (hash(e), e))  # GC012: heap event order
    return heap


def day_digest(events):
    ids = unordered_ids(events)  # helper returns set-order
    h = hashlib.sha256()
    for i in ids:
        h.update(i)  # GC012: helper's set order reaches the digest
    return h.hexdigest()


def day_stamp(events):
    tags = list({e.tag for e in events})
    return stamp(payload=b"|".join(tags))  # GC012: kwarg into sink
