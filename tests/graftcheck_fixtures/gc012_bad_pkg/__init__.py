# GC012 bad fixture package root — intentionally empty.
