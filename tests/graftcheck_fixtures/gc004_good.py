"""GC004 good fixture: every guard shape the rule accepts."""


def serve(payload, registry=None):
    if registry is not None:
        registry.counter("serving_requests_total").inc()
    return payload


def tick(payload, tracer=None, registry=None):
    if tracer is None:
        return payload  # early-return: everything below is guarded
    tracer.begin("tick", 0, 0)
    depth = registry.gauge("queue_depth") if registry is not None else None
    ok = registry is not None and registry.counter("ticks_total")
    if ok:
        ok.inc()
    forward(payload, tracer=tracer, registry=registry)  # bare forward
    return depth


def forward(payload, *, tracer=None, registry=None):
    del tracer, registry
    return payload


def branchy(payload, tracer=None):
    """The plain if/else guard (no early return): the else branch is
    proven not-None and must not be re-visited unguarded."""
    if tracer is None:
        payload = payload * 2
    else:
        tracer.begin("tick", 0, 0)
    return payload


class _Bundle:
    """Private helper on the instrumented side of the guard: a
    required registry is its contract, not a dark-path kwarg."""

    def __init__(self, registry):
        self.requests = registry.counter("hedge_requests_total")


def publish(model, registry):
    """PUBLIC function with a REQUIRED registry: an export target (the
    PoolLatencyModel.publish pattern) — the action's subject is the
    registry, there is no publish-to-nothing, so no None default and
    no guards; non-None by contract."""
    registry.gauge("pool_worker_latency_mean_seconds").set(model)


def scrape(payload, exporter=None, flight=None):
    """The new telemetry-plane kwargs honor the same guard shapes."""
    if exporter is not None:
        exporter.add_health("pool", None)
    ok = flight is not None and flight.snapshot()
    return payload if ok else None


def route_request(replica, registry=None, flight=None):
    """The round-15 router telemetry shape, correctly guarded: the
    completion counter and the hedge-fire instant event each behind
    their own None check."""
    if registry is not None:
        registry.counter("router_requests_total").inc()
    if flight is not None:
        flight.event("hedge fired", replica=replica)
    return replica


def page_pool_tick(pool, registry=None):
    """The paged-cache telemetry shape with the guard: occupancy
    gauges and share/COW counters only touch the registry inside the
    is-not-None arm (models/serving.py _ServingObs discipline)."""
    if registry is not None:
        registry.gauge("serving_cache_pages_free").set(pool)
        registry.counter("serving_prefix_share_hits_total").inc()
        registry.counter("serving_cow_copies_total").inc(0)
    return pool


def harvest_ring(frame, registry=None):
    """The round-12 zero-copy transport telemetry shape with the
    guard: counter deltas and the pinned-slot gauge only touch the
    registry inside the is-not-None arm (backends/native.py
    _publish_transport discipline)."""
    if registry is not None:
        registry.counter(
            "transport_zero_copy_bytes_total", path="ring"
        ).inc(frame)
        registry.counter("transport_ring_full_stalls_total").inc(0)
        registry.gauge("transport_pinned_slots").set(frame)
    return frame


def hier_decode(arrived, registry=None, flight=None):
    """The hierarchical-decode telemetry shape, guarded: recovery
    counters and the flight instant event behind the opt-in checks."""
    if registry is not None:
        registry.counter("hier_outer_recoveries_total").inc()
    ok = flight is not None and flight.event("hier outer recovery")
    return arrived if ok else None


def migrate_ticket(ticket, registry=None, flight=None):
    """The round-16 disaggregation telemetry shape, guarded: the
    migration counters and the per-handoff flight instant event only
    fire inside the is-not-None arms (models/router.py _RouterObs
    two-tier discipline)."""
    if registry is not None:
        registry.counter("disagg_migrations_total").inc()
        registry.counter("disagg_migrated_pages_total").inc(ticket)
        registry.histogram("disagg_migration_seconds").observe(0.0)
    ok = flight is not None and flight.event("kv migrated")
    return ticket if ok else None


def fused_harvest(repochs, registry=None, flight=None):
    """The round-17 device-coordination telemetry shape, guarded: the
    window counters, the harvest-latency histogram, and the per-window
    flight span only fire inside the is-not-None arms
    (parallel/device_coord.py DeviceCoordinator discipline)."""
    if registry is not None:
        registry.counter("devcoord_fused_epochs_total").inc(repochs)
        registry.counter("devcoord_harvests_total").inc()
        registry.histogram("devcoord_harvest_seconds").observe(0.0)
        registry.gauge("devcoord_epochs_per_harvest").set(repochs)
    ok = flight is not None and flight.span("devcoord window", 0.0, 0.0)
    return repochs if ok else None


def fleet_decide(decision, registry=None, flight=None):
    """The round-18 fleet-controller telemetry shape, guarded: the
    resize counter, sizing gauges, decision histogram, and the
    per-decision flight instant event only fire inside the is-not-None
    arms (fleet/controller.py _FleetObs discipline)."""
    if registry is not None:
        registry.counter("fleet_resizes_total").inc()
        registry.gauge("fleet_size").set(decision)
        registry.gauge("fleet_target_size").set(decision)
        registry.histogram("fleet_decision_seconds").observe(0.0)
        registry.counter("fleet_failovers_total").inc(0)
    ok = flight is not None and flight.event("fleet decision")
    return decision if ok else None


def qos_admit(tenant, registry=None, flight=None):
    """The round-19 multi-tenant QoS telemetry shape, guarded: the
    per-tenant admission/shed counters, deficit and quota gauges, the
    per-tenant TTFT histogram, and the reclaim/shed flight instant
    events only fire inside the is-not-None arms (models/serving.py
    _ServingObs qos hooks + models/router.py _RouterObs discipline)."""
    if registry is not None:
        registry.counter("qos_admitted_total").inc()
        registry.counter("qos_shed_total").inc(0)
        registry.counter("qos_hedge_refused_total").inc(0)
        registry.gauge("qos_deficit").set(tenant)
        registry.gauge("qos_pages_quota_used").set(tenant)
        registry.histogram("qos_ttft_seconds").observe(0.0)
    ok = flight is not None and flight.event("qos reclaim")
    return tenant if ok else None


def chaos_inject(episode, registry=None, flight=None):
    """The round-20 chaos-plane telemetry shape, guarded: the episode
    and probe counters, the peak-depth gauge, and the begin/end flight
    instants only fire inside the is-not-None arms
    (chaos/injector.py ChaosInjector._emit discipline)."""
    if registry is not None:
        registry.counter("chaos_episodes_total").inc()
        registry.counter("chaos_invariant_probes_total").inc(0)
        registry.gauge("chaos_max_queue_depth").set(episode)
    ok = flight is not None and flight.event("chaos episode")
    return episode if ok else None


def trace_append(tid, trace=None):
    """The round-22 causal-tracing shape, guarded: lifecycle events
    only append inside the is-not-None arm (obs/tracing.py TraceBook
    discipline — the book's owner stamps on its own clock), and the
    mint-at-door path early-returns the dark case."""
    if trace is not None:
        trace.event(tid, "submitted", 0.0, tenant=None)
    ok = trace is not None and trace.mint()
    return tid if ok else None


def window_roll(now, series=None, slo=None):
    """The round-24 windowed-SLO shape, guarded: the series store
    rolls and the burn policy evaluates only inside the is-not-None
    arms (sim/workload.py run_router_day obs_roll discipline — the
    policy rolls its own store, so a day driving both pays two None
    checks)."""
    if series is not None:
        series.maybe_roll(now)
    if slo is not None:
        slo.maybe_roll(now)
    ok = slo is not None and slo.fast_burn_firing()
    return now if ok else None


def cache_publish(digest, registry=None, flight=None):
    """The round-25 fleet-cache shape, guarded: the directory-size
    gauge, the spill/fetch byte counters with their src label, and
    the spill flight instant all live inside is-not-None arms
    (cache/directory.py + cache/store.py discipline — a dark fleet
    cache spills and fetches with zero observability cost)."""
    if registry is not None:
        registry.gauge("cache_directory_size").set(digest)
        registry.counter("cache_spill_bytes_total").inc(0)
        registry.counter("cache_fetch_bytes_total", src="dram").inc(0)
    ok = flight is not None and flight.event("page spilled")
    return digest if ok else None
