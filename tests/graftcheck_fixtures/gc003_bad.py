"""GC003 bad fixture: host effects and tracer leaks inside traced
code. Violation lines pinned by the fixture test; one site carries a
suppression to pin the round-trip."""

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp


@jax.jit
def leaky(x):
    t0 = time.perf_counter()  # GC003 line 16: host clock
    noise = np.random.normal(size=3)  # GC003 line 17: host RNG
    if x > 0:  # GC003 line 18: Python branch on traced arg
        x = x + jnp.asarray(noise)
    return x, t0


@functools.partial(jax.jit, donate_argnums=(0,))
def casty(x):
    return float(x)  # GC003 line 25: concretizes the tracer


def scanner(xs):
    def body(carry, x):
        stamp = time.time()  # GC003 line 30: host clock in scan body
        return carry + x, stamp

    return jax.lax.scan(body, jnp.zeros(()), xs)


@jax.jit
def suppressed(x):
    t = time.time()  # graftcheck: disable=GC003  (pinned round-trip)
    return x, t
