"""GC003 bad fixture: host effects and tracer leaks inside traced
code. Violation lines pinned by the fixture test; one site carries a
suppression to pin the round-trip."""

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp


@jax.jit
def leaky(x):
    t0 = time.perf_counter()  # GC003 line 16: host clock
    noise = np.random.normal(size=3)  # GC003 line 17: host RNG
    if x > 0:  # GC003 line 18: Python branch on traced arg
        x = x + jnp.asarray(noise)
    return x, t0


@functools.partial(jax.jit, donate_argnums=(0,))
def casty(x):
    return float(x)  # GC003 line 25: concretizes the tracer


def scanner(xs):
    def body(carry, x):
        stamp = time.time()  # GC003 line 30: host clock in scan body
        return carry + x, stamp

    return jax.lax.scan(body, jnp.zeros(()), xs)


@jax.jit
def suppressed(x):
    t = time.time()  # graftcheck: disable=GC003  (pinned round-trip)
    return x, t


def fused_window(xs, mesh):
    # the round-17 device-coordination shape: the whole epoch scan
    # nests inside ONE shard_map-wrapped callable, so leaks both in
    # the wrapped fn and in the scan body it contains must resolve
    # through the shard_map boundary
    def window(x):
        w0 = time.time()  # GC003 line 48: host clock in shard_map'd fn

        def body(carry, t):
            return carry + t.item(), t  # GC003 line 51: .item() in body

        out = jax.lax.scan(body, jnp.zeros(()), x)
        return out, w0

    f = jax.shard_map(  # graftcheck: disable=GC002  (fixture file)
        window, mesh=mesh, in_specs=None, out_specs=None
    )
    return f(xs)


@jax.jit
def closure_branch(xs, lo):
    # the scan body is its own traced region under the _walk_own dedup,
    # but `lo` is the ENCLOSING jit fn's tracer — the branch on it must
    # still be attributed (to the body, once)
    def body(carry, t):
        if lo > 0:  # GC003 line 68: branch on closed-over tracer
            carry = carry + t
        return carry, t

    return jax.lax.scan(body, jnp.zeros(()), xs)
