"""Reachable from the package root; the jax import below is the
violation (line 6 — pinned by the fixture test)."""

import numpy as np  # the sanctioned hard dependency

import jax  # GC001: module-level accelerator-stack import


class Pool:
    def run(self, x):
        return jax.numpy.asarray(np.asarray(x))
