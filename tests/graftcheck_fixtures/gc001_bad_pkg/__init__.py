"""GC001 bad fixture: the root pulls in a module that imports jax at
module level — the closure is no longer jax-free."""

from .core import Pool  # noqa: F401
