"""GC005 good fixture: the same shape with every cross-thread write
under the lock (and a single-writer attribute, which is exempt)."""

import threading


class Harvester:
    def __init__(self):
        self.results = {}
        self.closed = False
        self.stats = None
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            with self._lock:
                if self.closed:
                    return
                self.results = dict(self.results)

    def reset(self):
        with self._lock:
            self.results = {}
            self.closed = False

    def summarize(self):
        self.stats = len(self.results)  # single writer: exempt
        return self.stats
