"""GC008 bad fixture, sim half: a virtual-time module that secretly
reads the OS clock. Violation lines pinned by the fixture test."""

from time import perf_counter  # GC008: OS-clock import in sim
import time


def advance(clock, dt):
    t0 = time.perf_counter()  # GC008: wall clock in the sim plane
    clock.run_until(clock.now() + dt)
    time.sleep(0.001)  # GC008: real sleep in the sim plane
    return time.perf_counter() - t0  # GC008

import time as _t


def settle():
    _t.sleep(0.01)  # GC008: wall sleep through an import alias
