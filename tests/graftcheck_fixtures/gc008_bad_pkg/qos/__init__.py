"""GC008 bad fixture, qos half: tenant-budget code that secretly
reads the OS clock — a bucket refilled from the wall can never replay
a tenant-mixed day bit-identically. Violation lines pinned by the
fixture test."""

import time


def refill(bucket):
    now = time.perf_counter()  # GC008: OS clock in a budget refill
    bucket.tokens = min(
        bucket.burst,
        bucket.tokens + bucket.rate * (time.monotonic() - bucket.last),  # GC008
    )
    bucket.last = now
    return bucket.tokens
