"""GC008 bad fixture, fleet half: control-plane decision code that
secretly reads the OS clock — a controller like this can never replay
bit-identically. Violation lines pinned by the fixture test."""

import time


def decide(controller, signals):
    t0 = time.perf_counter()  # GC008: OS clock in a decision function
    if signals.utilization > controller.high:
        controller.grow()
    controller.decision_s = time.perf_counter() - t0  # GC008
    return controller.decision_s
