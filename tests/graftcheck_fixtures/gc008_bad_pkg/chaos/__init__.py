"""GC008 bad fixture, chaos half: an episode probe that secretly
reads the OS clock — a chaos scenario timed off the wall can never
replay bit-identically, which is the plane's whole witness. Violation
lines pinned by the fixture test."""

import time


def probe(router, state):
    now = time.monotonic()  # GC008: OS clock in an episode probe
    if router.in_flight and now - state["last"] > 30.0:
        raise AssertionError("deadlock")
    state["last"] = time.perf_counter()  # GC008
    return now
