"""GC008 bad fixture, margin half: asserts comparing wall-clock-
derived values against sub-second literals — the flake family.
Violation lines pinned by the fixture test."""

import time

import numpy as np


def timing_margin_direct(run):
    t0 = time.perf_counter()
    run()
    assert time.perf_counter() - t0 < 0.04  # GC008: direct margin


def timing_margin_tainted(run, latency):
    errs = []
    for _ in range(100):
        t0 = time.perf_counter()
        run()
        delay = time.perf_counter() - t0
        errs.append(abs(delay - latency))
    assert float(np.median(errs)) < 5e-3  # GC008: taint via append
