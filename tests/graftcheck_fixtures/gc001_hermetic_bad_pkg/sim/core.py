"""Reachable only from the hermetic subpackage root; the jax import
below is the violation (line 6 — pinned by the fixture test)."""

import numpy as np  # the sanctioned hard dependency

import jax  # GC001: module-level accelerator import in a hermetic root


class Sim:
    def run(self, x):
        return jax.numpy.asarray(np.asarray(x))
