# graftcheck: hermetic-root
"""A self-declared hermetic subpackage whose closure leaks jax."""

from .core import Sim  # noqa: F401
