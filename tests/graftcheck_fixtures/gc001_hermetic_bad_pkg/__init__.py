"""GC001 hermetic-root bad fixture: the top root never imports the
``sim`` subpackage (it would stay invisible to the top-root walk), but
``sim/__init__.py`` declares itself a hermetic root — so its closure
is walked on its own and the jax import inside it is a finding."""
