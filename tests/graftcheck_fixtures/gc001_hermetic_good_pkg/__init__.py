"""GC001 hermetic-root good twin: the hermetic subpackage's closure is
genuinely accelerator-free (lazy jax import inside a function is the
sanctioned escape hatch, exactly as in the real package root)."""
