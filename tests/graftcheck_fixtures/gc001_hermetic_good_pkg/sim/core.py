"""Hermetic closure member with only sanctioned imports."""

import numpy as np  # the sanctioned hard dependency


class Sim:
    def run(self, x):
        # lazy device import: the sanctioned escape hatch — import cost
        # is paid only by callers that actually reach for jax
        import jax

        return jax.numpy.asarray(np.asarray(x))
