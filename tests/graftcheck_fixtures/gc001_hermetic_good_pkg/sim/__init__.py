# graftcheck: hermetic-root
"""A self-declared hermetic subpackage whose closure stays clean."""

from .core import Sim  # noqa: F401
