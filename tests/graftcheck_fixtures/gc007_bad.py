"""GC007 bad fixture: every slot-lifetime violation shape — an
unchecked acquire, a leaked pin, a tracked view escaping bare, a
frombuffer re-wrap of a tracked view, and a frombuffer over a derived
ndarray. Violation lines pinned by the fixture test."""

import numpy as np

from . import track_release  # fixture stub; never imported at check time


class Producer:
    def __init__(self, ring):
        self.ring = ring

    def stage_unchecked(self, u8):
        slot, gen = self.ring.alloc.acquire(("coord",))  # GC007: no
        # None check — crashes exactly when every slot is pinned
        self.ring.view[slot:slot + u8.nbytes] = u8
        self.ring.alloc.release(slot, gen, "coord")
        return slot

    def stage_leaky(self, u8):
        got = self.ring.alloc.acquire(("coord",))  # GC007: no release,
        # no registration, no escape — the slot pins forever
        if got is None:
            return False
        self.ring.view[0:u8.nbytes] = u8
        return True


class Server:
    def __init__(self, mm, ring):
        self.mm = mm
        self.ring = ring

    def serve_bare(self, slot, gen, blen):
        view = np.frombuffer(self.mm, np.uint8)[:blen]
        track_release(view, self.ring.alloc.release, slot, gen, "c")
        return view  # GC007: bare escape — a consumer re-wrap drops
        # the tracked slice and the slot recycles under a live view

    def serve_rewrapped(self, slot, gen, blen):
        view = np.frombuffer(self.mm, np.uint8)[:blen]
        track_release(view, self.ring.alloc.release, slot, gen, "c")
        return np.frombuffer(view, np.uint8)  # GC007: frombuffer
        # keeps only the ROOT buffer; the finalizer fires early

    def serve_derived(self, blen):
        base = np.frombuffer(self.mm, np.uint8)
        sliced = base[:blen]
        return np.frombuffer(sliced, np.uint8)  # GC007: derived
        # ndarray — the intermediate slice drops out of the base chain
