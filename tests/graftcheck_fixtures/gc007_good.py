"""GC007 good fixture: the same shapes, disciplined — acquires
None-checked with copying fallbacks, pins released / transferred
(constructor and return-marker escapes), tracked views served only as
``memoryview(view)``."""

import numpy as np

from . import track_release  # fixture stub; never imported at check time


class Payload:
    def __init__(self, ring, slot, gen, nbytes):
        self.ring, self.slot, self.gen, self.nbytes = (
            ring, slot, gen, nbytes,
        )


class Producer:
    def __init__(self, ring):
        self.ring = ring

    def stage(self, u8):
        got = self.ring.alloc.acquire(("coord",))
        if got is None:
            return None  # all pinned: the caller's copying fallback
        slot, gen = got
        self.ring.view[0:u8.nbytes] = u8
        return Payload(self.ring, slot, gen, u8.nbytes)  # pin escapes
        # into the payload object, whose release() discharges it

    def stage_marker(self, u8):
        got = self.ring.alloc.acquire(("parent",))
        if got is None:
            return None
        slot, gen = got
        self.ring.view[0:u8.nbytes] = u8
        return (slot, gen, u8.nbytes)  # control-marker escape: the
        # peer that receives the marker acks the release


class Server:
    def __init__(self, mm, ring):
        self.mm = mm
        self.ring = ring

    def serve(self, slot, gen, blen):
        view = np.frombuffer(self.mm, np.uint8)[:blen]
        track_release(view, self.ring.alloc.release, slot, gen, "c")
        return memoryview(view)  # every derived buffer holds the slice


class WalrusProducer:
    """The walrus-loop acquire shape the rule's docstring sanctions:
    `(got := ...acquire(...)) is None` IS the None test."""

    def __init__(self, ring):
        self.ring = ring

    def stage_spin(self, u8, reap):
        while (got := self.ring.alloc.acquire(("coord",))) is None:
            reap()  # free dead holders' pins, then retry
        slot, gen = got
        self.ring.view[0:u8.nbytes] = u8
        return (slot, gen)
