"""GC003 good fixture: every allowance the rule grants — static
shape/dtype/`is None` branching inside traced code, and free use of
host clocks OUTSIDE it."""

import time

import jax
import jax.numpy as jnp


@jax.jit
def clean(x, eos_id=None):
    if eos_id is None:  # static config test: allowed
        eos_id = 0
    if x.shape[0] > 4:  # shape is a trace-time constant: allowed
        x = x[:4]
    n = len(x.shape)  # len(): allowed
    return jnp.where(x > 0, x, eos_id) * n


def host_step(xs):
    t0 = time.perf_counter()  # not traced: allowed

    def body(carry, x):
        return carry + jnp.square(x), x.dtype.type(0)

    out = jax.lax.scan(body, jnp.zeros(()), xs)
    return out, time.perf_counter() - t0


def fused_window(xs, mesh, payload=None):
    # shard_map-wrapped scan using only the static allowances: shape
    # tests, `is None` config branching, and clocks OUTSIDE the traced
    # region
    t0 = time.perf_counter()  # not traced: allowed

    def window(x):
        if payload is None:  # static config test: allowed
            scale = 1
        else:
            scale = 2
        if x.shape[0] > 4:  # shape is a trace-time constant: allowed
            x = x[:4]

        def body(carry, t):
            return carry + jnp.square(t) * scale, t

        return jax.lax.scan(body, jnp.zeros(()), x)

    f = jax.shard_map(  # graftcheck: disable=GC002  (fixture file)
        window, mesh=mesh, in_specs=None, out_specs=None
    )
    return f(xs), time.perf_counter() - t0


@jax.jit
def closure_static(xs, ref):
    # closed-over enclosing tracer used only behind static accessors
    # inside the nested scan body: allowed
    def body(carry, t):
        if ref.shape[0] > 4:  # shape is a trace-time constant: allowed
            return carry + t, t
        return carry, t

    return jax.lax.scan(body, jnp.zeros(()), xs)
