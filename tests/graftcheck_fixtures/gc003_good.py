"""GC003 good fixture: every allowance the rule grants — static
shape/dtype/`is None` branching inside traced code, and free use of
host clocks OUTSIDE it."""

import time

import jax
import jax.numpy as jnp


@jax.jit
def clean(x, eos_id=None):
    if eos_id is None:  # static config test: allowed
        eos_id = 0
    if x.shape[0] > 4:  # shape is a trace-time constant: allowed
        x = x[:4]
    n = len(x.shape)  # len(): allowed
    return jnp.where(x > 0, x, eos_id) * n


def host_step(xs):
    t0 = time.perf_counter()  # not traced: allowed

    def body(carry, x):
        return carry + jnp.square(x), x.dtype.type(0)

    out = jax.lax.scan(body, jnp.zeros(()), xs)
    return out, time.perf_counter() - t0
