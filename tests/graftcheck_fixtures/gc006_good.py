"""GC006 good fixture: the same shapes, disciplined — one global lock
order (always ``_a`` before ``_b``), the re-acquired lock is an RLock,
and every blocking call happens outside the critical section (or
carries a timeout)."""

import pickle
import threading
import time


class Pump:
    def __init__(self, conn, cond):
        self._a = threading.RLock()
        self._b = threading.Lock()
        self._conn = conn
        self._cond = cond

    def forward(self):
        with self._a:
            with self._b:  # a -> b, the one sanctioned order
                return self._drain()

    def reap(self):
        with self._a:
            with self._b:  # same order as forward: no cycle
                pass

    def _drain(self):
        with self._a:  # RLock: re-entry from forward is legal
            return None

    def pull(self):
        with self._a:
            pending = True
        if pending:
            return self._conn.recv()  # blocking AFTER the lock drops

    def park(self):
        with self._b:
            self._cond.wait(timeout=1.0)  # bounded: a missed notify
            # surfaces as a timeout, not a hang

    def snapshot(self, obj):
        data = pickle.dumps(obj)  # serialize outside the lock
        time.sleep(0.01)
        with self._b:
            self._last = data
        return data


class Spawner:
    """Thread-entry closure: `worker` runs on its OWN thread holding
    nothing, so its `_b` acquisition must not merge into `start`'s
    held stack — merging would fabricate an a->b edge and, with
    `reorder`'s b->a, a phantom cycle."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def start(self):
        with self._a:
            def worker():
                with self._b:
                    pass
            t = threading.Thread(target=worker)
            t.start()
            return t

    def reorder(self):
        with self._b:
            with self._a:  # b->a: a cycle only if start really did a->b
                pass
