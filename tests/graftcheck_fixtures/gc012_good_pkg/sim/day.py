"""GC012 good fixture: the same day engine, replay-pure. Seeded
RNG constructions terminate taint; every set is sorted before its
order can matter; event order comes from a sequence counter."""

import hashlib
import heapq
import random

import numpy as np

from ..helpers import ordered_ids, stamp


def seed_state(seed):
    rng = np.random.default_rng((0x9E3779B9, seed))
    lane = np.random.default_rng(seed + 1)
    rnd = random.Random(0xC4A05 ^ seed)
    return rng, lane, rnd


def digest_events(events):
    h = hashlib.sha256()
    for n in sorted({e.node for e in events}):
        h.update(n)
    return h.hexdigest()


def order_events(events):
    events.sort(key=lambda e: (e.t, e.node))
    heap = []
    for seq, e in enumerate(events):
        heapq.heappush(heap, (seq, e))
    return heap


def day_digest(events):
    ids = ordered_ids(events)
    h = hashlib.sha256()
    for i in ids:
        h.update(i)
    return h.hexdigest()


def day_stamp(events):
    tags = sorted({e.tag for e in events})
    return stamp(payload=b"|".join(tags))
