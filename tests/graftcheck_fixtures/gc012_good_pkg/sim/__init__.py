# The `sim` component puts every module below inside the replay scope.
