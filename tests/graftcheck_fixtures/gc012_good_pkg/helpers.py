"""The good twin of gc012_bad_pkg/helpers.py: the set is sorted
before it leaves, so the order taint never forms, and the digest
helper only ever receives deterministic bytes."""

import hashlib


def ordered_ids(events):
    return sorted({e.node for e in events})


def stamp(payload, *, salt=b""):
    h = hashlib.sha256()
    h.update(salt)
    h.update(payload)
    return h.hexdigest()
