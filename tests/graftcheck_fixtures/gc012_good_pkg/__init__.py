# GC012 good fixture package root — intentionally empty.
