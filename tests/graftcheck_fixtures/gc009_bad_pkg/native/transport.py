"""GC009 bad fixture, Python half: every drift shape against the
sibling transport.cpp. Violation lines pinned by the fixture test.
(KIND_DEATH is missing here and msgt_destroy is unconfigured — both
anchor at line 1.)"""

import ctypes

KIND_DATA = 0
KIND_CONTROL = 5  # GC009: cpp says 1
KIND_ACK = 2  # GC009: Python-internal, but collides with KIND_DEATH
KIND_EXTRA = 7  # GC009: exists only here, not a documented internal


def _configure(lib):
    lib.msgt_create.restype = ctypes.c_void_p
    lib.msgt_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.msgt_send.restype = ctypes.c_int
    lib.msgt_send.argtypes = [  # GC009: arg 2 is int64_t in the cpp
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.msgt_take.restype = ctypes.c_int  # GC009: cpp returns int64_t
    lib.msgt_take.argtypes = [  # GC009: arity 3 vs the cpp's 4
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.msgt_gone.restype = None  # GC009: cpp exports no msgt_gone


def _configure_extra(lib):
    lib.msgt_count.argtypes = [ctypes.c_void_p]  # GC009: argtypes but
    # no restype for an int64_t-returning export — c_int truncation
