"""GC010 bad fixture: bare drops. Violation lines pinned by the
fixture test."""


def shed_overload(rr, book):
    rr.outcome = "shed"  # GC010 line 6: no sibling shed_reason
    book.pop(rr, None)
    return rr


def refuse(obs, rr):
    obs.shed(rr)  # GC010 line 12: shed call with no reason
    return rr


def refuse_masked(obs, rr):
    obs.shed(rr, reason=None)  # GC010 line 17: reason in name only
    return rr


def drop_request(queue, rr):
    queue.drop(rr, "")  # GC010 line 22: empty string is not a reason
    return rr


def shed_with_empty_stamp(rr):
    rr.outcome = "shed"
    rr.shed_reason = None  # GC010 lines 27+28: trivial reason
    return rr


def shed_nested(obs, rr, cond):
    if cond:
        obs.shed(rr)  # GC010 line 34: ONE finding, not one per level
    return rr


def outer_with_nested(obs, rr):
    def inner():
        obs.shed(rr)  # GC010 line 40: attributed to inner, once
    return inner
