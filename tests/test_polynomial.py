"""Polynomial-coded GEMM: both-factor partitioning, decode from any pq.

New capability beyond the reference (which has no coded layer at all,
SURVEY §2) and beyond the BASELINE MDS/LT configs: per-worker compute is
1/(pq) of the product, with recovery threshold pq out of n workers.
"""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from mpistragglers_jl_tpu import AsyncPool, asyncmap, waitall
from mpistragglers_jl_tpu.ops import PolyCodedGemm, PolynomialCode


class TestPolynomialCode:
    def test_validation(self):
        with pytest.raises(ValueError, match="n >= p\\*q"):
            PolynomialCode(2, 3, 5)
        with pytest.raises(ValueError, match="p, q >= 1"):
            PolynomialCode(0, 2, 4)
        code = PolynomialCode(2, 2, 6)
        with pytest.raises(ValueError, match="distinct shard indices"):
            code.decode(np.zeros((4, 2, 2)), [0, 1, 2, 2])
        with pytest.raises(ValueError, match="expected 2 A-blocks"):
            code.encode_A(np.zeros((3, 2, 2)))

    def test_points_are_distinct_chebyshev(self):
        code = PolynomialCode(2, 3, 9)
        assert len(set(np.round(code.points, 12))) == 9
        assert np.all(np.abs(code.points) < 1.0)

    @pytest.mark.parametrize("p,q,n", [(2, 2, 6), (1, 3, 4), (3, 1, 5), (2, 3, 8)])
    def test_decode_every_pq_subset(self, p, q, n):
        rng = np.random.default_rng(0)
        m, kd, nc = 4 * p, 8, 6 * q
        A = rng.standard_normal((m, kd)).astype(np.float64)
        B = rng.standard_normal((kd, nc)).astype(np.float64)
        code = PolynomialCode(p, q, n, dtype=np.float64)
        A_enc = code.encode_A(A.reshape(p, m // p, kd))
        w = nc // q
        Bq = B.reshape(kd, q, w)
        C_true = A @ B
        # every worker's evaluation
        evals = []
        for i in range(n):
            B_enc = np.einsum("l,klw->kw", code.VB[i], Bq)
            evals.append(np.asarray(A_enc[i]) @ B_enc)
        # any pq of them decode to the exact product
        for idx in itertools.combinations(range(n), p * q):
            shards = np.stack([evals[i] for i in idx])
            C = np.asarray(code.assemble(code.decode(shards, list(idx))))
            np.testing.assert_allclose(C, C_true, rtol=1e-8, atol=1e-8)

    def test_f32_conditioning_acceptable(self):
        # Chebyshev points keep the worst-case pq=6 subset solvable in f32
        rng = np.random.default_rng(1)
        p, q, n = 2, 3, 8
        m, kd, nc = 8, 16, 12
        A = rng.standard_normal((m, kd)).astype(np.float32)
        B = rng.standard_normal((kd, nc)).astype(np.float32)
        code = PolynomialCode(p, q, n)
        A_enc = code.encode_A(A.reshape(p, m // p, kd))
        Bq = B.reshape(kd, q, nc // q)
        evals = [
            np.asarray(A_enc[i]) @ np.einsum("l,klw->kw", code.VB[i], Bq)
            for i in range(n)
        ]
        scale = float(np.max(np.abs(A @ B)))
        for idx in itertools.combinations(range(n), p * q):
            C = np.asarray(code.assemble(code.decode(
                np.stack([evals[i] for i in idx]), list(idx)
            )))
            rel = float(np.max(np.abs(C - A @ B))) / scale
            assert rel < 1e-3, (idx, rel)


class TestPolyCodedGemm:
    def test_decodes_exactly_with_stragglers(self):
        rng = np.random.default_rng(0)
        p, q, n = 2, 2, 6
        A = rng.standard_normal((32, 24)).astype(np.float32)
        B = rng.standard_normal((24, 16)).astype(np.float32)
        stragglers = (1, 4)
        delay_fn = lambda i, e: 0.25 if i in stragglers else 0.0
        pg = PolyCodedGemm(A, p, q, n, delay_fn=delay_fn)
        pool = AsyncPool(n)
        try:
            C_true = A @ B
            scale = float(np.max(np.abs(C_true)))
            for epoch in range(1, 4):
                repochs = asyncmap(pool, B, pg.backend, nwait=pg.nwait)
                C = pg.result(pool)
                rel = float(np.max(np.abs(C - C_true))) / scale
                assert rel < 1e-4, rel
            for i in stragglers:
                assert pool.repochs[i] != pool.epoch
            waitall(pool, pg.backend)
        finally:
            pg.backend.shutdown()

    def test_result_requires_pq_fresh(self):
        rng = np.random.default_rng(0)
        pg = PolyCodedGemm(
            rng.standard_normal((8, 8)).astype(np.float32), 2, 2, 4
        )
        pool = AsyncPool(4)
        try:
            with pytest.raises(ValueError, match="need k=4"):
                pg.result(pool)  # nothing dispatched yet
        finally:
            pg.backend.shutdown()

    def test_worker_validates_b_shape(self):
        rng = np.random.default_rng(0)
        pg = PolyCodedGemm(
            rng.standard_normal((8, 8)).astype(np.float32), 2, 2, 4
        )
        pool = AsyncPool(4)
        try:
            B_bad = rng.standard_normal((8, 7)).astype(np.float32)
            from mpistragglers_jl_tpu import WorkerFailure

            with pytest.raises(WorkerFailure, match="divide evenly"):
                asyncmap(pool, B_bad, pg.backend, nwait=4)
                waitall(pool, pg.backend)
        finally:
            pg.backend.shutdown()

    def test_validation(self):
        A = np.zeros((9, 4), dtype=np.float32)
        with pytest.raises(ValueError, match="divide evenly"):
            PolyCodedGemm(A, 2, 2, 6)
