"""Continuous-batching serving scheduler (models/serving.py).

North-star serving scope — the reference is transport-only (SURVEY §2).
The oracle for every stream is the single-request ring generator
(models/decode.py ``generate_ring_dense``): the scheduler's batched
per-row step must reproduce it token-for-token for every request, no
matter how admissions, retirements, and slot reuse interleave.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpistragglers_jl_tpu.models.decode import generate_ring_dense
from mpistragglers_jl_tpu.models.serving import (
    Request,
    ServingScheduler,
    make_serving_scan,
    serving_decode_step_dense,
)
from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)
from mpistragglers_jl_tpu.parallel import make_mesh

CFG = TransformerConfig(
    vocab=61, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2, d_ff=128,
    attn_window=6,
)
PARAMS = init_params(CFG, seed=11)
RNG = np.random.default_rng(12)


def _prompt(n):
    return RNG.integers(1, CFG.vocab, size=n).astype(np.int32)


def _oracle(prompt, n_new, eos_id=None):
    toks = generate_ring_dense(
        PARAMS, jnp.asarray(prompt)[None], n_new, CFG, eos_id=eos_id
    )
    out = [int(t) for t in np.asarray(toks)[0]]
    if eos_id is not None and eos_id in out:
        out = out[: out.index(eos_id) + 1]
    return out


def test_single_request_matches_oracle():
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=4,
                             prompt_chunk=8, max_prompt=64)
    p = _prompt(5)
    r = sched.submit(p, max_new=13)
    sched.run()
    assert r.finished and r.reason == "length"
    assert r.tokens == _oracle(p, 13)


def test_batch_matches_oracle_every_request():
    """8 concurrent requests, varied prompt lengths and budgets — each
    stream equals its independent oracle (batching changes wall-clock,
    never content)."""
    sched = ServingScheduler(PARAMS, CFG, slots=4, n_inner=4,
                             prompt_chunk=8, max_prompt=64)
    reqs = [
        (sched.submit(p, max_new=n), p, n)
        for p, n in [(_prompt(3), 9), (_prompt(11), 6), (_prompt(8), 17),
                     (_prompt(1), 5), (_prompt(20), 8), (_prompt(6), 12),
                     (_prompt(15), 4), (_prompt(9), 10)]
    ]
    sched.run()
    for r, p, n in reqs:
        assert r.finished
        assert r.tokens == _oracle(p, n), f"request {r.id}"


def test_admission_queues_beyond_slots_and_reuses():
    """More requests than slots: the extras wait, retirements free
    slots, every slot is reused, and reuse never corrupts a stream
    (the kpos mask + row overwrite discipline)."""
    S = 2
    sched = ServingScheduler(PARAMS, CFG, slots=S, n_inner=2,
                             prompt_chunk=8, max_prompt=32)
    reqs = [(sched.submit(_prompt(4 + i), max_new=5 + i), 4 + i, 5 + i)
            for i in range(6)]
    assert sched.pending == 6 - 0  # nothing admitted before a tick
    sched.run()
    for r, plen, n in reqs:
        assert r.finished
        assert len(r.tokens) == n
    # 6 requests through 2 slots: at least one slot served >= 3
    admit_ticks = sorted(r.admitted_tick for r, _, _ in reqs)
    assert admit_ticks[0] == 1 and admit_ticks[-1] > 1


def test_straggling_requests_slot_reuse_mid_flight():
    """Requests arriving WHILE others decode (straggling admissions):
    short requests retire and their slots serve late arrivals; the
    long-running request's stream is unperturbed."""
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=2,
                             prompt_chunk=8, max_prompt=32)
    p_long = _prompt(6)
    r_long = sched.submit(p_long, max_new=24)
    p_short = _prompt(3)
    r_short = sched.submit(p_short, max_new=4)
    late = []
    for _ in range(30):
        sched.step()
        if r_short.finished and not late:
            # the short request's slot is free mid-flight; add two
            # stragglers that must reuse it
            late = [(sched.submit(_prompt(5), 6), 5, 6),
                    (sched.submit(_prompt(2), 3), 2, 3)]
        if (r_long.finished and late
                and all(r.finished for r, _, _ in late)):
            break
    assert r_long.finished and r_short.finished
    assert r_long.tokens == _oracle(p_long, 24)
    assert r_short.tokens == _oracle(p_short, 4)
    for r, _, _ in late:
        assert r.finished and len(r.tokens) == r.max_new
        assert r.admitted_tick > r_short.retired_tick - 1


def test_eos_retirement():
    """Rows retire at EOS with the tail stripped; an EOS-free oracle
    prefix check pins content."""
    # find an eos_id that actually occurs early in some greedy stream
    p = _prompt(7)
    free_run = _oracle(p, 16)
    eos = free_run[3]
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=4,
                             prompt_chunk=8, max_prompt=32, eos_id=eos)
    r = sched.submit(p, max_new=16)
    sched.run()
    assert r.finished and r.reason == "eos"
    assert r.tokens == _oracle(p, 16, eos_id=eos)
    assert r.tokens[-1] == eos and eos not in r.tokens[:-1]


def test_chunked_prefill_interleaves_with_decode():
    """A long prompt admits chunk-by-chunk: in-flight decode keeps
    producing tokens during the admission ticks (the bounded-stall
    property), and the long prompt's stream still matches its oracle."""
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=2,
                             prompt_chunk=4, max_prompt=64)
    r_first = sched.submit(_prompt(4), max_new=40)  # admits in 1 chunk
    sched.step()
    tokens_before = len(r_first.tokens)
    p_long = _prompt(23)  # 6 chunks of 4
    r_long = sched.submit(p_long, max_new=6)
    # during the long admission, the first request must keep decoding
    sched.step()
    assert len(r_first.tokens) > tokens_before
    assert r_long.admitted_tick is not None and not r_long.tokens
    sched.run()
    assert r_long.tokens == _oracle(p_long, 6)
    assert r_first.tokens == _oracle(np.asarray(r_first.prompt), 40)


def test_request_validation():
    sched = ServingScheduler(PARAMS, CFG, slots=1, n_inner=1,
                             prompt_chunk=4, max_prompt=8)
    with pytest.raises(ValueError, match="exceeds max_prompt"):
        sched.submit(_prompt(9), max_new=2)
    with pytest.raises(ValueError, match="max_new"):
        Request(_prompt(3), 0)
    with pytest.raises(ValueError, match="empty"):
        Request(np.zeros(0, np.int32), 3)
    no_window = dataclasses.replace(CFG, attn_window=None)
    with pytest.raises(ValueError, match="ring cache"):
        ServingScheduler(PARAMS, no_window, slots=1)
    moe = dataclasses.replace(
        CFG, n_experts=2, d_model=64, attn="ulysses"
    )
    with pytest.raises(ValueError, match="dense-FFN"):
        ServingScheduler(init_params(moe, seed=1), moe, slots=1)


@pytest.mark.slow
def test_sharded_serving_scan_matches_dense():
    """The dp x tp serving tick (the driver-dryrun leg) reproduces the
    dense per-row step exactly on the virtual mesh."""
    S, n_inner = 4, 3
    mesh = make_mesh((2, 2), ("dp", "tp"))
    scan = make_serving_scan(CFG, mesh, n_inner)
    tok = jnp.asarray(RNG.integers(1, CFG.vocab, S), jnp.int32)
    pos = jnp.asarray([6, 3, 9, 7], jnp.int32)
    done = jnp.zeros((S,), bool)
    W = CFG.attn_window
    key = jax.random.key(0)
    mk = lambda k: jax.random.normal(  # noqa: E731
        k, (S, W, CFG.kv_heads, CFG.head_dim), CFG.dtype
    ) * 0.1
    caches = []
    ks = jax.random.split(key, 2 * CFG.n_layers)
    for i in range(CFG.n_layers):
        caches.append({"k": mk(ks[2 * i]), "v": mk(ks[2 * i + 1])})
    # dense reference: n_inner greedy steps by hand
    dtok, dpos, dcaches = tok, pos, caches
    want = []
    for _ in range(n_inner):
        lg, dcaches = serving_decode_step_dense(
            PARAMS, dtok, dpos, dcaches, CFG
        )
        dtok = jnp.argmax(lg, axis=-1).astype(tok.dtype)
        dpos = dpos + 1
        want.append(dtok)
    want = jnp.stack(want, axis=1)
    keys = jax.random.split(jax.random.key(0), S)
    got = scan(PARAMS, tok, pos, done,
               [dict(c) for c in caches], keys)  # donated: pass copies
    np.testing.assert_array_equal(np.asarray(got[4]), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got[0]),
                                  np.asarray(want[:, -1]))


def test_admission_time_retirement_in_step_return():
    """max_new=1 retires at admission; step() must report it (review
    r5 finding: it was freed but missing from the returned list)."""
    sched = ServingScheduler(PARAMS, CFG, slots=1, n_inner=2,
                             prompt_chunk=8, max_prompt=16)
    p = _prompt(4)
    r = sched.submit(p, max_new=1)
    retired = sched.step()
    assert r.finished and retired == [r]
    assert r.tokens == _oracle(p, 1)


def test_quantized_scheduler_matches_quantized_oracle():
    """quantize_kv=True serves the int8 ring cache end-to-end; streams
    equal the quantized single-request oracle AS AN IDENTITY: the
    oracle's quantized-ring prefill attends the already-quantized cache
    (decode.py ``_dense_runner``), the only math the scheduler's
    chunked admission can evaluate (raw K/V of earlier chunks are gone
    once written), and per-position absmax quantization makes chunking
    itself invisible — so exact token equality is the contract, not an
    empirical coincidence of this checkpoint."""
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=3,
                             prompt_chunk=8, max_prompt=32,
                             quantize_kv=True)
    pairs = [(sched.submit(p, max_new=n), p, n)
             for p, n in [(_prompt(5), 8), (_prompt(9), 6),
                          (_prompt(3), 11)]]
    sched.run()
    for r, p, n in pairs:
        toks = generate_ring_dense(
            PARAMS, jnp.asarray(p)[None], n, CFG, quantize_kv=True
        )
        assert r.tokens == [int(t) for t in np.asarray(toks)[0]], (
            f"request {r.id}"
        )


def test_quantized_parity_is_chunk_size_invariant():
    """The load-bearing premise of the identity above (ADVICE r5 ->
    repaired in PR 1): admission attends the ALREADY-QUANTIZED cache,
    and because quantization is per-position absmax (a position's
    scale never depends on its neighbours), the chunking itself must
    be invisible — the same request must emit the same stream at ANY
    prompt_chunk, including one larger than the whole prompt (the
    oracle's shape). If this ever breaks, the scheduler==oracle parity
    silently degrades from identity to coincidence; this test makes
    that failure loud and names the property, not just the symptom."""
    prompts = [(_prompt(11), 7), (_prompt(4), 9)]
    streams = []
    for chunk in (2, 4, 8, 16):
        sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=3,
                                 prompt_chunk=chunk, max_prompt=32,
                                 quantize_kv=True)
        reqs = [sched.submit(p, max_new=n) for p, n in prompts]
        sched.run()
        streams.append([r.tokens for r in reqs])
    for other in streams[1:]:
        assert other == streams[0]
    # and the chunk-invariant stream IS the oracle stream
    for (p, n), toks in zip(prompts, streams[0]):
        want = generate_ring_dense(
            PARAMS, jnp.asarray(p)[None], n, CFG, quantize_kv=True
        )
        assert toks == [int(t) for t in np.asarray(want)[0]]


def test_quantized_scheduler_kernel_tick_matches_oracle():
    """head_dim-128 config at S=4 slots: the scheduler's tick routes
    the batched int8 Pallas ring kernel (AUTO gate — S >= 4 amortizes
    the per-call scan boundary; interpreted on the CI mesh) while the
    B=1 oracle stays einsum — streams must still be identical, which
    pins kernel-vs-einsum parity through the full serving path."""
    cfg = TransformerConfig(
        vocab=97, d_model=256, n_heads=2, n_kv_heads=1, n_layers=2,
        d_ff=256, attn_window=128,
    )
    params = init_params(cfg, seed=31)
    sched = ServingScheduler(params, cfg, slots=4, n_inner=3,
                             prompt_chunk=8, max_prompt=32,
                             quantize_kv=True)
    assert sched.use_kernel  # the whole point: the tick is kernelized
    pairs = [(sched.submit(p, max_new=n), p, n)
             for p, n in [(_prompt(5), 8), (_prompt(9), 6),
                          (_prompt(3), 10), (_prompt(7), 7),
                          (_prompt(12), 5)]]
    sched.run()
    for r, p, n in pairs:
        toks = generate_ring_dense(
            params, jnp.asarray(p)[None], n, cfg, quantize_kv=True
        )
        assert r.tokens == [int(t) for t in np.asarray(toks)[0]], (
            f"request {r.id}"
        )


def test_sharded_serving_scan_quantized():
    """The sharded tick accepts the int8 cache layout (scale leaves
    sharded like K/V) and matches the dense per-row step."""
    from mpistragglers_jl_tpu.models.decode import _kv_quantize

    S, n_inner = 4, 2
    mesh = make_mesh((2, 2), ("dp", "tp"))
    scan = make_serving_scan(CFG, mesh, n_inner, quantize_kv=True)
    tok = jnp.asarray(RNG.integers(1, CFG.vocab, S), jnp.int32)
    pos = jnp.asarray([7, 4, 8, 6], jnp.int32)
    done = jnp.zeros((S,), bool)
    W = CFG.attn_window
    key = jax.random.key(3)
    caches = []
    ks = jax.random.split(key, 2 * CFG.n_layers)
    for i in range(CFG.n_layers):
        kf = jax.random.normal(
            ks[2 * i], (S, W, CFG.kv_heads, CFG.head_dim), CFG.dtype
        ) * 0.1
        vf = jax.random.normal(
            ks[2 * i + 1], (S, W, CFG.kv_heads, CFG.head_dim), CFG.dtype
        ) * 0.1
        kq, ksc = _kv_quantize(kf)
        vq, vsc = _kv_quantize(vf)
        caches.append({"k": kq, "v": vq, "k_s": ksc, "v_s": vsc})
    dtok, dpos, dc = tok, pos, caches
    for _ in range(n_inner):
        lg, dc = serving_decode_step_dense(PARAMS, dtok, dpos, dc, CFG)
        dtok = jnp.argmax(lg, axis=-1).astype(tok.dtype)
        dpos = dpos + 1
    keys = jax.random.split(jax.random.key(0), S)
    got = scan(PARAMS, tok, pos, done, [dict(c) for c in caches], keys)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(dtok))


def test_sharded_serving_scan_gqa_wider_tp():
    """kv_heads < tp: the replicated-groups cache layout (global head
    axis = tp slots, slot t holding kv head t*kv/tp) reproduces the
    dense per-row step — the same layout make_ring_generate uses."""
    from mpistragglers_jl_tpu.models.decode import _cache_heads_global

    S, n_inner = 4, 2
    mesh = make_mesh((1, 4), ("dp", "tp"))
    assert CFG.kv_heads == 2 and mesh.shape["tp"] == 4
    scan = make_serving_scan(CFG, mesh, n_inner)
    Hc = _cache_heads_global(CFG, mesh)
    assert Hc == 4  # tp slots
    tok = jnp.asarray(RNG.integers(1, CFG.vocab, S), jnp.int32)
    pos = jnp.asarray([5, 8, 3, 7], jnp.int32)
    done = jnp.zeros((S,), bool)
    W = CFG.attn_window
    key = jax.random.key(9)
    caches_dense, caches_rep = [], []
    ks = jax.random.split(key, 2 * CFG.n_layers)
    head_map = jnp.arange(Hc) * CFG.kv_heads // Hc  # slot -> kv head
    for i in range(CFG.n_layers):
        kf = jax.random.normal(
            ks[2 * i], (S, W, CFG.kv_heads, CFG.head_dim), CFG.dtype
        ) * 0.1
        vf = jax.random.normal(
            ks[2 * i + 1], (S, W, CFG.kv_heads, CFG.head_dim), CFG.dtype
        ) * 0.1
        caches_dense.append({"k": kf, "v": vf})
        caches_rep.append({
            "k": kf[:, :, head_map], "v": vf[:, :, head_map],
        })
    dtok, dpos, dc = tok, pos, caches_dense
    for _ in range(n_inner):
        lg, dc = serving_decode_step_dense(PARAMS, dtok, dpos, dc, CFG)
        dtok = jnp.argmax(lg, axis=-1).astype(tok.dtype)
        dpos = dpos + 1
    keys = jax.random.split(jax.random.key(0), S)
    got = scan(PARAMS, tok, pos, done, caches_rep, keys)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(dtok))


def test_sampled_serving_matches_sampled_oracle():
    """temperature/top-k serving: each request's sampled stream equals
    ``generate_ring_dense`` with the SAME key (the per-row pick uses
    decode.py's exact (key, pos, row 0) fold discipline), through
    admission order, retirement, and slot reuse."""
    temp, tk = 0.8, 7
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=3,
                             prompt_chunk=8, max_prompt=32,
                             temperature=temp, top_k=tk)
    pairs = []
    for i, (plen, n) in enumerate([(5, 9), (11, 6), (3, 12), (8, 7)]):
        p = _prompt(plen)
        key = jax.random.key(100 + i)
        pairs.append((sched.submit(p, n, key=key), p, n, key))
    sched.run()
    for r, p, n, key in pairs:
        want = generate_ring_dense(
            PARAMS, jnp.asarray(p)[None], n, CFG,
            temperature=temp, top_k=tk, key=key,
        )
        assert r.tokens == [int(t) for t in np.asarray(want)[0]], (
            f"request {r.id}"
        )


def test_sampled_serving_default_keys_differ_per_request():
    """Without explicit keys, two identical prompts sample DIFFERENT
    streams (id-derived keys) — no accidental stream coupling."""
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=3,
                             prompt_chunk=8, max_prompt=32,
                             temperature=1.0)
    p = _prompt(6)
    r1 = sched.submit(p, 12)
    r2 = sched.submit(p, 12)
    sched.run()
    assert r1.tokens != r2.tokens


def test_sampling_validation():
    with pytest.raises(ValueError, match="temperature"):
        ServingScheduler(PARAMS, CFG, slots=1, temperature=-0.5)
    with pytest.raises(ValueError, match="top_k"):
        ServingScheduler(PARAMS, CFG, slots=1, temperature=1.0, top_k=0)
    sched = ServingScheduler(PARAMS, CFG, slots=1, prompt_chunk=8,
                             max_prompt=16)
    with pytest.raises(ValueError, match="greedy scheduler"):
        sched.submit(_prompt(3), 4, key=jax.random.key(1))


def test_clear_cached_programs_drops_all_model_caches():
    """models.clear_cached_programs is the one chokepoint for dropping
    lru-cached jitted program factories (bench uses it between rung
    blocks to release HBM) — it must clear every registered cache."""
    from mpistragglers_jl_tpu.models import clear_cached_programs
    from mpistragglers_jl_tpu.models import decode, serving, speculative

    sched = ServingScheduler(PARAMS, CFG, slots=1, n_inner=1,
                             prompt_chunk=4, max_prompt=8)
    r = sched.submit(_prompt(3), 2)
    sched.run()
    assert r.finished
    generate_ring_dense(PARAMS, jnp.asarray(_prompt(3))[None], 2, CFG)
    caches = (
        decode._dense_runner, speculative._spec_runner,
        serving._serving_scan_dense, serving._extend_chunk_dense,
        serving._finish_admit_dense, serving._place_dense,
    )
    assert any(c.cache_info().currsize > 0 for c in caches)
    clear_cached_programs()
    for c in caches:
        assert c.cache_info().currsize == 0, c
