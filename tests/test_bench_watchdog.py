"""Regression: the bench budget watchdog must pre-empt and flush.

BENCH_r05 on the driver box recorded ``rc: 124, parsed: null`` — jax
backend discovery hung inside ``_wire_compile_cache`` BEFORE the old
watchdog thread was started, so nothing could pre-empt and ``timeout
870`` killed bench.py with zero contract output. The round-12
hardening arms the watchdog before the first jax touch (and keeps
bench.py's module-level imports numpy-light so the guard covers the
whole jax load). These tests pin the contract the driver depends on:
under ANY budget — including one so tiny it elapses during the jax
import — ``python bench.py`` exits 0 and its LAST stdout line is
parseable JSON.
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_tiny_budget_flushes_partial_contract_and_exits_zero():
    """An artificially tiny budget elapses while jax is still loading
    (mid-"rung" from the watchdog's point of view): the run must exit
    0 with a parseable compact last line — never rc 124 / empty
    stdout. Also covers the boot-hang shape of BENCH_r05: the budget
    is over before the first rung even starts."""
    env = dict(os.environ)
    env["BENCH_BUDGET_S"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    run = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=_REPO, capture_output=True, text=True, timeout=600, env=env,
    )
    assert run.returncode == 0, (
        f"bench.py rc={run.returncode}\nstderr: {run.stderr[-3000:]}"
    )
    lines = [ln for ln in run.stdout.splitlines() if ln.strip()]
    assert lines, f"no stdout at all; stderr: {run.stderr[-3000:]}"
    last = json.loads(lines[-1])  # the driver's tail-capture contract
    assert isinstance(last, dict) and "metric" in last
    # a partial flush says so visibly, in the compact line itself, OR
    # the budget somehow sufficed and a real contract printed — either
    # way the driver parses a last line. On any realistic machine the
    # 1 s budget elapses during the jax import and the watchdog path
    # is what ran:
    if "watchdog" in last:
        assert "deadline elapsed" in str(last["watchdog"]) or (
            "partial" in str(last["watchdog"])
        )
    # the compact line must survive a ~2000-char tail capture
    assert len(lines[-1]) < 2000


def test_contract_line_is_robust_to_minimal_and_odd_snapshots():
    """_contract_line must produce a short JSON line from whatever the
    watchdog snapshot holds — empty dict, partial rungs, numpy scalars
    — because it runs at the moment things are already going wrong."""
    import numpy as np

    import bench

    for snap in (
        {},
        {"watchdog": "deadline elapsed mid-rung; partial contract"},
        {"metric": "m", "value": np.float32(1.5),
         "graftcheck": {"digest": "5r/0f/b0/1.00s"},
         "transformer_train": {"skipped": "budget"}},
    ):
        s = bench._contract_line(snap)
        parsed = json.loads(s)
        assert isinstance(parsed, dict)
        assert len(s) < 2000
        if snap.get("watchdog"):
            assert parsed["watchdog"] == snap["watchdog"]
