"""Device-resident coordination (round 17): fused K-epoch windows.

The contract under test, in priority order:

1. **Reference parity, bit-identical, per epoch**: the (K, n)
   ``repochs`` history a fused window harvests equals — row for row,
   bit for bit — what the host ``asyncmap`` loop produces on a
   :class:`SimBackend` under the SAME injected-delay schedule, at
   every K, stale workers' shards masked by the on-device arrival
   mask exactly as the host loop masks them (in-flight state carried
   across window boundaries included).
2. **Decode identity**: the per-epoch on-device decode equals
   ``A @ B`` across K x {mds, lt} x {0, 1 straggler}, including the
   hierarchical vmapped-inner + parity-outer path under a straggling
   host group and the mesh ``psum_scatter`` path.
3. **sweep_harvest_k** refusals are named refusals, never clamps.
"""

import numpy as np
import pytest

from mpistragglers_jl_tpu import (
    AsyncPool,
    SimBackend,
    asyncmap,
    asyncmap_fused,
)
from mpistragglers_jl_tpu.obs import MetricsRegistry
from mpistragglers_jl_tpu.ops.coded_gemm import CodedGemm, LTCodedGemm
from mpistragglers_jl_tpu.ops.hierarchical import HierarchicalCodedGemm
from mpistragglers_jl_tpu.parallel import make_mesh
from mpistragglers_jl_tpu.parallel.device_coord import (
    DeviceCoordinator,
    stage_delays,
)
from mpistragglers_jl_tpu.parallel.fused import PoolMeshCodedGemm
from mpistragglers_jl_tpu.sim import sweep_harvest_k
from mpistragglers_jl_tpu.utils import faults

N, K_CODE = 8, 6
RNG = np.random.default_rng(7)
A = RNG.standard_normal((K_CODE * 3, 16))
B = RNG.standard_normal((16, 5))


def _straggle(base, slow, extra=30.0):
    """``base`` delays with worker ``slow`` permanently +``extra``s."""

    def fn(w, e):
        return base(w, e) + (extra if w == slow else 0.0)

    return fn


def _host_hist(delay_fn, n, nwait, epochs, payload=B):
    """The reference: the REAL asyncmap loop on SimBackend."""
    be = SimBackend(lambda i, p, e: p, n, delay_fn=delay_fn)
    pool = AsyncPool(n)
    hist = np.empty((epochs, n), dtype=np.int64)
    for e in range(epochs):
        hist[e] = asyncmap(pool, payload, be, nwait=nwait).copy()
    return hist, pool


# --------------------------------------------------------------------------
# reference parity: bit-identical repochs, epoch for epoch, at every K
# --------------------------------------------------------------------------


@pytest.mark.parametrize("window", [1, 8])
@pytest.mark.parametrize("straggler", [None, 2])
def test_repochs_parity_bit_identical(window, straggler):
    delay = faults.seeded_lognormal(0.01, 0.8, seed=5)
    if straggler is not None:
        delay = _straggle(delay, straggler)
    epochs = 48
    host, host_pool = _host_hist(delay, N, K_CODE, epochs)
    cg = CodedGemm(A, N, K_CODE, dtype=np.float64)
    try:
        coord = cg.coordinator(delay_fn=delay)
        pool = AsyncPool(N)
        fused = np.concatenate([
            asyncmap_fused(pool, B, coord, epochs=window)
            for _ in range(epochs // window)
        ])
        assert np.array_equal(host, fused)
        # the pool leaves the window in the host loop's end state —
        # in-flight workers (the straggler) carried across boundaries
        assert np.array_equal(host_pool.active, pool.active)
        assert np.array_equal(host_pool.sepochs, pool.sepochs)
        assert np.array_equal(host_pool.repochs, pool.repochs)
        assert pool.epoch == host_pool.epoch
        if straggler is not None:
            assert pool.active[straggler]  # still in flight
            assert pool.repochs[straggler] == 0  # never heard from
    finally:
        cg.backend.shutdown()


def test_parity_with_stale_harvest_and_retask():
    """A finite straggler lands mid-later-epoch: the host loop
    stale-harvests and re-tasks it; the fused window must stamp the
    identical stale epochs (phase-3 re-task semantics, reference
    src/MPIAsyncPools.jl:177-184)."""
    base = faults.seeded_lognormal(0.005, 0.3, seed=11)

    def delay(w, e):
        # worker 5 straggles ~2.5 epochs, then answers: stale stamps
        return base(w, e) + (0.04 if w == 5 else 0.0)

    epochs = 40
    host, _ = _host_hist(delay, N, K_CODE, epochs)
    # the schedule must actually exercise stale stamps, or this test
    # pins nothing: some row must show worker 5 at an older epoch
    stale_rows = np.sum(host[1:, 5] < np.arange(2, epochs + 1))
    assert stale_rows > 0
    cg = CodedGemm(A, N, K_CODE, dtype=np.float64)
    try:
        coord = cg.coordinator(delay_fn=delay)
        pool = AsyncPool(N)
        fused = np.concatenate([
            asyncmap_fused(pool, B, coord, epochs=8)
            for _ in range(epochs // 8)
        ])
        assert np.array_equal(host, fused)
    finally:
        cg.backend.shutdown()


# --------------------------------------------------------------------------
# decode identity: on-device decode == A @ B at every K x code x straggler
# --------------------------------------------------------------------------


@pytest.mark.parametrize("window", [1, 8])
@pytest.mark.parametrize("code", ["mds", "lt"])
@pytest.mark.parametrize("straggler", [None, 2])
def test_decode_identity(window, code, straggler):
    delay = faults.seeded_lognormal(0.01, 0.6, seed=3)
    ref = np.max(np.abs(A @ B))
    if code == "mds":
        g = CodedGemm(A, N, K_CODE, dtype=np.float64)
        nwait = K_CODE
    else:
        g = LTCodedGemm(A, N, K_CODE, seed=1, dtype=np.float64)
        nwait = N
    try:
        if straggler is not None:
            if code == "lt":
                # an integer nwait cannot promise every subset peels:
                # pick a straggler whose complement provably decodes
                straggler = next(
                    w for w in range(N)
                    if g.code.peelable(
                        [g.shard_ids[i] for i in range(N) if i != w]
                    )
                )
            delay = _straggle(delay, straggler)
            nwait = N - 1
        coord = (
            g.coordinator(delay_fn=delay, nwait=nwait)
            if code == "mds"
            else g.coordinator(delay_fn=delay, nwait=nwait)
        )
        pool = AsyncPool(N)
        hist = asyncmap_fused(pool, B, coord, epochs=window)
        dec = np.asarray(coord.last_decoded)
        assert dec.shape[0] == window
        for j in range(window):
            assert np.max(np.abs(dec[j] - A @ B)) / ref < 1e-9
        if straggler is not None:
            assert np.all(hist[:, straggler] == 0)
    finally:
        g.backend.shutdown()


def test_per_epoch_staged_payloads():
    """An (epochs, d, c) payload stack stages per-epoch inputs up
    front; each epoch decodes against ITS payload."""
    cg = CodedGemm(A, N, K_CODE, dtype=np.float64)
    try:
        coord = cg.coordinator()
        pool = AsyncPool(N)
        Bs = RNG.standard_normal((4, 16, 5))
        asyncmap_fused(pool, Bs, coord, epochs=4)
        dec = np.asarray(coord.last_decoded)
        for j in range(4):
            ref = np.max(np.abs(A @ Bs[j]))
            assert np.max(np.abs(dec[j] - A @ Bs[j])) / ref < 1e-9
    finally:
        cg.backend.shutdown()


def test_pool_interop_after_window():
    """Harvest leaves ``pool.results``/``repochs`` consistent enough
    that the HOST decode path decodes the same product from the same
    pool — the two coordination modes share one pool contract."""
    cg = CodedGemm(A, N, K_CODE, dtype=np.float64)
    try:
        coord = cg.coordinator(
            delay_fn=faults.seeded_lognormal(0.01, 0.5, seed=2)
        )
        pool = AsyncPool(N)
        asyncmap_fused(pool, B, coord, epochs=8)
        fresh = pool.fresh_indices()
        assert fresh.size >= K_CODE
        host_decode = cg.result(pool)
        ref = np.max(np.abs(A @ B))
        assert np.max(np.abs(host_decode - A @ B)) / ref < 1e-9
    finally:
        cg.backend.shutdown()


# --------------------------------------------------------------------------
# hierarchical: vmapped inner decode + parity outer, in the scan body
# --------------------------------------------------------------------------


def _hier_fixture(group_straggle: bool):
    H, ni, ki = 3, 4, 3
    n = H * ni
    Ah = RNG.standard_normal(((H - 1) * ki * 2, 10))
    Bh = RNG.standard_normal((10, 4))
    base = faults.seeded_lognormal(0.005, 0.5, seed=9)

    def delay(w, e):
        slow = group_straggle and 4 <= w < 8  # host group 1
        return base(w, e) + (50.0 if slow else 0.0)

    hg = HierarchicalCodedGemm(
        Ah, groups=H, n_inner=ni, k_inner=ki, dtype=np.float64,
        device_backend=False,
    )
    return hg, Ah, Bh, delay, n


@pytest.mark.parametrize("group_straggle", [False, True])
def test_hierarchical_window(group_straggle):
    """The two-level completion rule, the vmapped inner decode
    (ops/hierarchical.decode_groups) and the parity-outer
    reconstruction all run inside the scan: repochs parity is
    bit-identical to the host loop under ``hg.nwait``, and the decode
    equals A @ B even with a whole host group straggling (outer
    reconstruction on device)."""
    hg, Ah, Bh, delay, n = _hier_fixture(group_straggle)
    epochs = 16
    be = SimBackend(hg.work, n, delay_fn=delay)
    pool_h = AsyncPool(n)
    host = np.stack([
        asyncmap(pool_h, Bh, be, nwait=hg.nwait).copy()
        for _ in range(epochs)
    ])
    coord = DeviceCoordinator.for_hierarchical(hg, delay_fn=delay)
    pool = AsyncPool(n)
    fused = np.concatenate([
        asyncmap_fused(pool, Bh, coord, epochs=8)
        for _ in range(epochs // 8)
    ])
    assert np.array_equal(host, fused)
    dec = np.asarray(coord.last_decoded)[-1]
    ref = np.max(np.abs(Ah @ Bh))
    assert np.max(np.abs(dec - Ah @ Bh)) / ref < 1e-9
    if group_straggle:
        # the straggling group never went fresh: its shards were
        # masked and the source came back through the parity pass
        assert np.all(fused[:, 4:8] == 0)


def test_hierarchical_factory_refusals():
    hg, *_ = _hier_fixture(False)
    with pytest.raises(ValueError, match="int nwait does not apply"):
        DeviceCoordinator(
            np.stack([np.asarray(b) for b in hg.blocks]),
            decode="hierarchical", groups=hg.H, k_inner=hg.k_inner,
            inner_G=hg._inner_G, nwait=9,
        )
    lt_inner = HierarchicalCodedGemm(
        A[: 2 * 3 * 2], groups=3, n_inner=4, k_inner=3, inner="lt",
        dtype=np.float64, device_backend=False,
    )
    with pytest.raises(ValueError, match="MDS-inner"):
        DeviceCoordinator.for_hierarchical(lt_inner)


# --------------------------------------------------------------------------
# mesh path: shard_map scan, psum_scatter decode, ppermute return ring
# --------------------------------------------------------------------------


def test_mesh_window():
    base = faults.seeded_lognormal(0.01, 0.7, seed=3)
    delay = _straggle(base, 2)
    mesh = make_mesh(8)
    fg = PoolMeshCodedGemm(A, mesh, K_CODE, dtype=np.float64)
    try:
        coord = fg.device_coordinator(delay_fn=delay)
        pool = AsyncPool(N)
        fused = asyncmap_fused(pool, B, coord, epochs=6)
        host, _ = _host_hist(delay, N, K_CODE, 6)
        assert np.array_equal(host, fused)
        ref = np.max(np.abs(A @ B))
        # decode output uses the collectives layout: block j on
        # device j, blocks >= k zero
        dec = coord.full(np.asarray(coord.last_decoded)[-1])
        assert np.max(np.abs(dec - A @ B)) / ref < 1e-9
        # the final epoch's product returned to every device over the
        # ppermute ring
        full = np.asarray(coord.last_window["last_full"])
        assert np.max(np.abs(full - A @ B)) / ref < 1e-9
    finally:
        fg.shutdown()


def test_mesh_window_refusals():
    mesh = make_mesh(8)
    fg = PoolMeshCodedGemm(A, mesh, K_CODE, n_workers=16,
                           dtype=np.float64)
    try:
        with pytest.raises(ValueError, match="one worker per mesh"):
            fg.device_coordinator()
    finally:
        fg.shutdown()
    blocks = np.zeros((8, 3, 4))
    with pytest.raises(ValueError, match="flat MDS"):
        DeviceCoordinator(
            blocks, decode="lt", G=np.ones((8, 6)), k=6, nwait=8,
            mesh=mesh,
        )


# --------------------------------------------------------------------------
# construction / staging / continuation guards
# --------------------------------------------------------------------------


def test_constructor_refusals():
    blocks = np.zeros((N, 3, 4))
    G = np.ones((N, K_CODE))
    with pytest.raises(ValueError, match="nwait=2 must sit in"):
        DeviceCoordinator(blocks, decode="mds", G=G, k=K_CODE, nwait=2)
    with pytest.raises(ValueError, match="nwait=9 must sit in"):
        DeviceCoordinator(blocks, decode="mds", G=G, k=K_CODE, nwait=9)
    with pytest.raises(ValueError, match="unknown decode"):
        DeviceCoordinator(blocks, decode="raptor", G=G, k=K_CODE)
    with pytest.raises(ValueError, match="needs G and k"):
        DeviceCoordinator(blocks, decode="mds")
    with pytest.raises(ValueError, match="stack"):
        DeviceCoordinator(np.zeros((N, 3)), decode="mds", G=G, k=K_CODE)


def test_run_window_guards():
    cg = CodedGemm(A, N, K_CODE, dtype=np.float64)
    try:
        coord = cg.coordinator()
        pool = AsyncPool(N)
        with pytest.raises(ValueError, match="epochs must be >= 1"):
            coord.run_window(pool, B, epochs=0)
        with pytest.raises(ValueError, match="laid out for"):
            coord.run_window(AsyncPool(4), B, epochs=1)
        with pytest.raises(ValueError, match="carry 3 epochs"):
            coord.run_window(pool, np.zeros((3, 16, 5)), epochs=2)
        # a pool with host-loop work in flight cannot enter a window
        busy = AsyncPool(N)
        busy.active[1] = True
        with pytest.raises(ValueError, match="quiescent"):
            coord.run_window(busy, B, epochs=1)
    finally:
        cg.backend.shutdown()


def test_stage_delays_contract():
    d = stage_delays(lambda w, e: -1.0 if w == 0 else w + e, 3, 5, 2)
    assert d.shape == (2, 3)
    assert d[0, 0] == 0.0  # clamped like SimBackend
    assert d[0, 1] == 6.0 and d[1, 2] == 8.0
    assert np.all(stage_delays(None, 4, 0, 3) == 0.0)


def test_reset_forgets_in_flight_state():
    delay = _straggle(faults.seeded_lognormal(0.01, 0.5, seed=1), 3)
    cg = CodedGemm(A, N, K_CODE, dtype=np.float64)
    try:
        coord = cg.coordinator(delay_fn=delay)
        pool = AsyncPool(N)
        asyncmap_fused(pool, B, coord, epochs=4)
        assert pool.active[3]
        coord.reset()
        for i in np.flatnonzero(pool.active):
            pool.reset_worker(i)  # the elastic-recovery pair
        # a quiescent pool re-enters cleanly at the next epoch
        hist = asyncmap_fused(pool, B, coord, epochs=2)
        assert hist.shape == (2, N)
        assert pool.epoch == 6
    finally:
        cg.backend.shutdown()


# --------------------------------------------------------------------------
# observability (GC004 opt-in contract)
# --------------------------------------------------------------------------


class _SpanLog:
    def __init__(self):
        self.spans = []

    def span(self, name, t0, dur, **kw):
        self.spans.append((name, kw))


def test_obs_wiring():
    reg = MetricsRegistry()
    fl = _SpanLog()
    cg = CodedGemm(A, N, K_CODE, dtype=np.float64)
    try:
        coord = cg.coordinator(registry=reg, flight=fl)
        pool = AsyncPool(N)
        asyncmap_fused(pool, B, coord, epochs=8)
        asyncmap_fused(pool, B, coord, epochs=8)
        assert reg.counter("devcoord_fused_epochs_total").value == 16
        assert reg.counter("devcoord_harvests_total").value == 2
        assert reg.gauge("devcoord_epochs_per_harvest").value == 8
        assert reg.histogram("devcoord_harvest_seconds").count == 2
        assert len(fl.spans) == 2
        assert fl.spans[0][1]["epochs"] == 8
        # dark coordinator stays dark: only `is None` checks
        dark = cg.coordinator()
        dark_pool = AsyncPool(N)
        asyncmap_fused(dark_pool, B, dark, epochs=2)
        assert dark._m is None
    finally:
        cg.backend.shutdown()


# --------------------------------------------------------------------------
# sweep_harvest_k: the K sweep priced on virtual time, refusals by name
# --------------------------------------------------------------------------


def _sweep_delay():
    return faults.seeded_lognormal(0.02, 0.6, seed=4)


def test_sweep_harvest_k_prices_the_amdahl_trade():
    out = sweep_harvest_k(
        _sweep_delay(), n_workers=8, nwait=6, epochs=64,
        k_values=(1, 4, 16, 64),
        host_epoch_s=2e-3, host_harvest_s=4e-3,
    )
    ks = [e["K"] for e in out["entries"]]
    assert ks == [1, 4, 16, 64]
    # staleness grows with K (a window holds results longer) …
    stale = [e["staleness_s"] for e in out["entries"]]
    assert stale == sorted(stale)
    # … while amortized host cost shrinks, so the unbounded sweep
    # recommends the largest K and overhead_x is monotone
    rates = [e["epochs_per_s"] for e in out["entries"]]
    assert rates == sorted(rates)
    assert out["best"] == 64
    assert out["best_entry"]["overhead_x"] > 1.0
    assert out["entries"][0]["n_harvests"] == 64
    assert out["best_entry"]["n_harvests"] == 1
    assert out["host_loop_epochs_per_s"] > 0


def test_sweep_harvest_k_staleness_refusal_by_message():
    with pytest.raises(
        ValueError, match="violates the staleness bound"
    ):
        sweep_harvest_k(
            _sweep_delay(), n_workers=8, nwait=6, epochs=64,
            k_values=(1, 64), staleness_bound_s=0.2,
        )
    # a bound every candidate clears does not refuse
    out = sweep_harvest_k(
        _sweep_delay(), n_workers=8, nwait=6, epochs=64,
        k_values=(1, 2), staleness_bound_s=1e6,
    )
    assert out["best"] == 2


def test_sweep_harvest_k_window_refusals_by_message():
    with pytest.raises(
        ValueError, match="must cover at least 1 epoch"
    ):
        sweep_harvest_k(
            _sweep_delay(), n_workers=8, nwait=6, epochs=16,
            k_values=(0, 4),
        )
    with pytest.raises(ValueError, match="exceeds the 16-epoch run"):
        sweep_harvest_k(
            _sweep_delay(), n_workers=8, nwait=6, epochs=16,
            k_values=(4, 32),
        )
    with pytest.raises(ValueError, match="nwait must be in"):
        sweep_harvest_k(
            _sweep_delay(), n_workers=8, nwait=9, epochs=16,
            k_values=(4,),
        )
