"""Paged serving cache: parity, sharing, COW, and capacity contracts.

The PR 1 oracle identity is unchanged by the page refactor: a paged
scheduler's every stream equals ``generate_ring_dense`` token-for-token
— greedy and sampled, fp and int8, einsum gather and Pallas page-table
kernel, across page sizes and any admission/retirement/COW
interleaving. The einsum fallback gathers each slot's ring view with
``jnp.take`` and runs the SAME per-row attention as the slot ring, so
parity here is parity by construction being *verified*, not an
empirical coincidence (models/serving.py ``_paged_gather``).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpistragglers_jl_tpu.models.decode import generate_ring_dense
from mpistragglers_jl_tpu.models.serving import ServingScheduler
from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)
from mpistragglers_jl_tpu.obs import MetricsRegistry

# same shapes as tests/test_serving.py so the jitted oracles are shared
CFG = TransformerConfig(
    vocab=61, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2, d_ff=128,
    attn_window=6,
)
PARAMS = init_params(CFG, seed=11)
RNG = np.random.default_rng(21)

# head_dim-128 config: the int8 Pallas kernel's page-table mode routes
# (interpreted on the CI mesh); W=128 admits PAGE_TOKENS in {16, 64}
KCFG = TransformerConfig(
    vocab=97, d_model=256, n_heads=2, n_kv_heads=1, n_layers=2,
    d_ff=256, attn_window=128,
)
KPARAMS = init_params(KCFG, seed=31)


def _prompt(n, vocab=CFG.vocab):
    return RNG.integers(1, vocab, size=n).astype(np.int32)


def _oracle(p, n, *, params=PARAMS, cfg=CFG, quantize_kv=False,
            eos_id=None, **kw):
    toks = generate_ring_dense(
        params, jnp.asarray(p)[None], n, cfg, quantize_kv=quantize_kv,
        eos_id=eos_id, **kw,
    )
    out = [int(t) for t in np.asarray(toks)[0]]
    if eos_id is not None and eos_id in out:
        out = out[: out.index(eos_id) + 1]
    return out


def _drained(sched):
    """Post-run pool invariants: zero leaks, refcounts at baseline."""
    sched.pool.check()
    assert sched.pool.used == 0 and sched.pool.reserved == 0


@pytest.mark.parametrize("page_tokens", [2, 3, 6])
def test_paged_batch_matches_oracle_under_churn(page_tokens):
    """The slot-churn schedule of test_serving.py on the paged cache,
    at every page size dividing the window (6): queueing beyond slots,
    reuse, wrap, varied budgets — every stream equals its oracle and
    the pool drains leak-free."""
    sched = ServingScheduler(PARAMS, CFG, slots=3, n_inner=4,
                             prompt_chunk=8, max_prompt=64,
                             page_tokens=page_tokens)
    reqs = [
        (sched.submit(p, max_new=n), p, n)
        for p, n in [(_prompt(3), 9), (_prompt(11), 6), (_prompt(8), 17),
                     (_prompt(1), 5), (_prompt(20), 8), (_prompt(6), 12),
                     (_prompt(15), 4), (_prompt(9), 10)]
    ]
    sched.run()
    for r, p, n in reqs:
        assert r.finished
        assert r.tokens == _oracle(p, n), f"request {r.id} (P={page_tokens})"
    _drained(sched)


def test_shared_prefix_divergence_cow_matches_oracle():
    """Two prompts sharing a page-aligned system prefix but diverging
    after it: the second admission shares the prefix pages, both
    requests wrap the window (forcing COW of the shared pages), and
    BOTH streams still equal their independent oracles — the COW copy
    never mutated the page the other slot was reading."""
    sys_prompt = _prompt(4)
    pa = np.concatenate([sys_prompt, _prompt(2)])
    pb = np.concatenate([sys_prompt, _prompt(2)])
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=3,
                             prompt_chunk=8, max_prompt=64,
                             page_tokens=2)
    ra = sched.submit(pa, max_new=14)
    rb = sched.submit(pb, max_new=14)
    sched.run()
    assert ra.tokens == _oracle(pa, 14)
    assert rb.tokens == _oracle(pb, 14)
    assert sched.pool.share_hits > 0, "prefix sharing never fired"
    assert sched.pool.cow_copies > 0, "COW never fired (wrap schedule?)"
    _drained(sched)


def test_prefix_share_skips_prefill_counter_verified():
    """A prefix-sharing admission must SKIP the shared pages' prefill
    chunks — pinned through serving_prefill_chunks_total, not timing.
    (Sharing needs a resident registrant whose pages are still prefix
    content: W=128 so neither request wraps; r1 stays mid-decode while
    r2 admits.)"""
    reg = MetricsRegistry()
    p = _prompt(40, KCFG.vocab)
    sched = ServingScheduler(KPARAMS, KCFG, slots=2, n_inner=2,
                             prompt_chunk=8, max_prompt=64,
                             page_tokens=16, registry=reg)
    chunks = reg.counter("serving_prefill_chunks_total")
    r1 = sched.submit(p, max_new=8)
    while not r1.tokens:
        sched.step()  # r1 fully admitted (5 chunks), still decoding
    c1 = chunks.value
    assert c1 == 5
    r2 = sched.submit(p, max_new=8)
    sched.run()
    # identical 40-token prompt at P=16: (40-1)//16 = 2 pages shared
    # -> 32 tokens skip prefill; the remaining 8 are one 8-token chunk
    assert chunks.value - c1 == 1
    assert sched.pool.share_hits == 2
    assert r1.tokens == _oracle(p, 8, params=KPARAMS, cfg=KCFG)
    assert r2.tokens == _oracle(p, 8, params=KPARAMS, cfg=KCFG)
    _drained(sched)


def test_page_capacity_defers_admission_fifo():
    """A pool too small for every request at once: admission defers
    (FIFO) until retirements return pages, every request still serves
    exactly, and the pool never leaks. This is the capacity contract —
    cache_pages bounds concurrency, not correctness."""
    # each request needs ceil(min(6, Tp+max_new+n_inner)/2) = 3 pages;
    # 4 usable pages => strictly one resident request at a time
    sched = ServingScheduler(PARAMS, CFG, slots=3, n_inner=2,
                             prompt_chunk=8, max_prompt=32,
                             page_tokens=2, cache_pages=5)
    reqs = [(sched.submit(_prompt(3 + i), max_new=4 + i), 3 + i, 4 + i)
            for i in range(4)]
    sched.step()
    assert sched.active == 1 and sched.pending == 3  # pages, not slots
    sched.run()
    for r, plen, n in reqs:
        assert r.finished and len(r.tokens) == n
    admit_ticks = [r.admitted_tick for r, _, _ in reqs]
    assert admit_ticks == sorted(admit_ticks)  # FIFO, no reordering
    _drained(sched)


def test_paged_quantized_matches_quantized_oracle():
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=3,
                             prompt_chunk=8, max_prompt=32,
                             quantize_kv=True, page_tokens=3)
    pairs = [(sched.submit(p, max_new=n), p, n)
             for p, n in [(_prompt(5), 8), (_prompt(9), 6),
                          (_prompt(3), 11)]]
    sched.run()
    for r, p, n in pairs:
        assert r.tokens == _oracle(p, n, quantize_kv=True), (
            f"request {r.id}"
        )
    _drained(sched)


def test_paged_sampled_matches_sampled_oracle():
    """Sampling through the paged tick: per-request keys, same fold
    discipline — streams equal ``generate_ring_dense`` with the same
    key through admission order, retirement, and page churn."""
    temp, tk = 0.8, 7
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=3,
                             prompt_chunk=8, max_prompt=32,
                             temperature=temp, top_k=tk, page_tokens=2)
    pairs = []
    for i, (plen, n) in enumerate([(5, 9), (11, 6), (3, 12), (8, 7)]):
        p = _prompt(plen)
        key = jax.random.key(300 + i)
        pairs.append((sched.submit(p, n, key=key), p, n, key))
    sched.run()
    for r, p, n, key in pairs:
        want = _oracle(p, n, temperature=temp, top_k=tk, key=key)
        assert r.tokens == want, f"request {r.id}"
    _drained(sched)


def test_paged_eos_retirement_returns_pages():
    p = _prompt(7)
    free_run = _oracle(p, 16)
    eos = free_run[3]
    sched = ServingScheduler(PARAMS, CFG, slots=2, n_inner=4,
                             prompt_chunk=8, max_prompt=32,
                             eos_id=eos, page_tokens=2)
    r = sched.submit(p, max_new=16)
    sched.run()
    assert r.finished and r.reason == "eos"
    assert r.tokens == _oracle(p, 16, eos_id=eos)
    _drained(sched)


@pytest.mark.parametrize("page_tokens", [16, 64])
@pytest.mark.parametrize("quantize_kv", [False, True])
def test_paged_page_sizes_and_kernel_tick_match_oracle(
    page_tokens, quantize_kv
):
    """PAGE_TOKENS in {16, 64} at head_dim 128 under a slot-reuse
    schedule, fp AND int8. The int8 variant is the kernel-tick leg:
    S=4 routes the Pallas page-table mode (per-slot page rows in
    scalar-prefetch SMEM) while the B=1 oracle stays einsum — the
    identity pins kernel-vs-gather parity through the full path."""
    sched = ServingScheduler(KPARAMS, KCFG, slots=4, n_inner=3,
                             prompt_chunk=8, max_prompt=32,
                             quantize_kv=quantize_kv,
                             page_tokens=page_tokens)
    if quantize_kv:
        assert sched.use_kernel  # the whole point of this leg
    pairs = [(sched.submit(p, max_new=n), p, n)
             for p, n in [(_prompt(5, KCFG.vocab), 8),
                          (_prompt(9, KCFG.vocab), 6),
                          (_prompt(3, KCFG.vocab), 10),
                          (_prompt(7, KCFG.vocab), 7),
                          (_prompt(12, KCFG.vocab), 5)]]
    sched.run()
    for r, p, n in pairs:
        want = _oracle(p, n, params=KPARAMS, cfg=KCFG,
                       quantize_kv=quantize_kv)
        assert r.tokens == want, f"request {r.id}"
    _drained(sched)


def test_page_pool_metrics_exported():
    """The opt-in page-pool series: occupancy gauges track the pool
    and the share/COW counters match its lifetime tallies."""
    reg = MetricsRegistry()
    p = _prompt(40, KCFG.vocab)
    sched = ServingScheduler(KPARAMS, KCFG, slots=2, n_inner=2,
                             prompt_chunk=8, max_prompt=64,
                             page_tokens=16, registry=reg)
    r1 = sched.submit(p, max_new=8)
    while not r1.tokens:
        sched.step()  # registration happens at admission finish
    assert reg.gauge("serving_cache_pages_used").value == sched.pool.used
    assert reg.gauge("serving_cache_pages_free").value == sched.pool.free
    r2 = sched.submit(p, max_new=8)
    sched.run()
    assert r1.finished and r2.finished
    assert (reg.counter("serving_prefix_share_hits_total",
                        tier="hbm").value
            == sched.pool.share_hits > 0)
    assert (reg.counter("serving_cow_copies_total").value
            == sched.pool.cow_copies)
    assert reg.gauge("serving_cache_pages_used").value == 0
    # the names survive the Prometheus exposition round trip
    text = reg.to_prometheus()
    for name in ("serving_cache_pages_free", "serving_cache_pages_used",
                 "serving_prefix_share_hits_total",
                 "serving_cow_copies_total"):
        assert f"\n{name}" in text or text.startswith(name)


def test_paged_validation():
    with pytest.raises(ValueError, match="divide the attention window"):
        ServingScheduler(PARAMS, CFG, slots=1, page_tokens=4)  # W=6
    with pytest.raises(ValueError, match="cache_pages without"):
        ServingScheduler(PARAMS, CFG, slots=1, cache_pages=8)
    with pytest.raises(ValueError, match="cannot hold even one"):
        ServingScheduler(PARAMS, CFG, slots=1, page_tokens=2,
                         cache_pages=3)  # needs W/P + 1 = 4


def test_default_scheduler_is_not_paged():
    sched = ServingScheduler(PARAMS, CFG, slots=1, n_inner=1,
                             prompt_chunk=4, max_prompt=8)
    assert not sched.paged and sched.pool is None
