"""Parity suite for the vectorized router-day engine (sim/fastpath.py).

The contract under test is ISSUE-16's non-negotiable: the fast path
must reproduce the scalar loop's ``digest()`` BIT-IDENTICALLY on every
seeded day — plain, QoS, elastic, chaos. The digest witness is the
spec; any divergence is a fast-path bug by definition. Elastic and
chaos days satisfy it through the documented scalar fallback, which
this suite pins too (reason string AND digest equality).

Beyond the witness, ``_assert_books`` compares the full observable
ledger — router counters, per-replica books (tick_count, busy_s,
retires, cancels, shared admits), DRR scheduler internals, and token
bucket levels — because the fast path hands the REAL QoS objects back
and the controller reads those books for its next decision.
"""

import heapq

import numpy as np
import pytest

from mpistragglers_jl_tpu.models.router import RequestRouter
from mpistragglers_jl_tpu.qos import TenantContract, TenantRegistry
from mpistragglers_jl_tpu.sim import (
    ArrivalBatch,
    ReplicaPartition,
    RetryPolicy,
    VirtualClock,
    diurnal_arrival_batch,
    fastpath_supported,
    poisson_arrival_batch,
    run_router_day_fast,
)
from mpistragglers_jl_tpu.sim.workload import (
    FleetResize,
    SimReplica,
    diurnal_arrivals,
    lognormal_ticks,
    poisson_arrivals,
    run_router_day,
)


def _fleet(n=4, slots=4, n_inner=8, tick=0.02, sigma=0.0, seed=0,
           policy="least_loaded", qos=None, dead=(), **router_kw):
    clock = VirtualClock()
    reps = []
    for i in range(n):
        tick_s = (
            tick if sigma == 0.0
            else lognormal_ticks(tick, sigma, seed=seed * 1009 + i)
        )
        r = SimReplica(clock, slots=slots, n_inner=n_inner,
                       tick_s=tick_s, qos=qos)
        if i in dead:
            r.kill()
        reps.append(r)
    router = RequestRouter(reps, policy=policy, clock=clock,
                           qos=qos, **router_kw)
    return clock, reps, router


def _assert_books(rep_s, rep_f, reps_s, reps_f, router_s, router_f):
    """Scalar report/fleet vs fast report/fleet: witness first, then
    every non-witness book the decision planes read."""
    assert rep_s.digest() == rep_f.digest()
    assert rep_s.outcomes == rep_f.outcomes
    assert rep_s.shed_reasons == rep_f.shed_reasons
    assert rep_s.dropped == rep_f.dropped
    assert rep_s.n_resubmits == rep_f.n_resubmits
    assert rep_s.virtual_s == rep_f.virtual_s
    assert rep_s.n_events == rep_f.n_events
    np.testing.assert_array_equal(
        rep_s.decode_itl, rep_f.decode_itl
    )
    for attr in ("n_submitted", "n_completed", "n_shed", "n_hedges",
                 "n_hedges_refused", "n_over_budget", "_rr"):
        assert getattr(router_s, attr) == getattr(router_f, attr), attr
    for a, b in zip(reps_s, reps_f):
        for attr in ("tick_count", "busy_s", "last_tick_at",
                     "next_tick_at", "n_retired", "n_cancelled",
                     "n_shared_admits"):
            assert getattr(a, attr) == getattr(b, attr), attr
        da, db = a._drr, b._drr
        if da is not None:
            assert da._order == db._order
            assert da._deficit == db._deficit
            assert da._cursor == db._cursor
            assert da._n == db._n
            assert da._max_cost == db._max_cost


def _run_both(mk_fleet, arrivals_fn, batch, **day_kw):
    _, reps_s, router_s = mk_fleet()
    rep_s = run_router_day(router_s, arrivals_fn(), **day_kw)
    _, reps_f, router_f = mk_fleet()
    rep_f = run_router_day_fast(router_f, batch, **day_kw)
    return rep_s, rep_f, reps_s, reps_f, router_s, router_f


# --------------------------------------------------------------------------
# plain days
# --------------------------------------------------------------------------


class TestPlainDayParity:
    def test_least_loaded_lognormal(self):
        kw = dict(prompt_len=96, max_new=32)
        out = _run_both(
            lambda: _fleet(sigma=0.3, seed=2),
            lambda: poisson_arrivals(50.0, n=2000, seed=2, **kw),
            poisson_arrival_batch(50.0, n=2000, seed=2, **kw),
        )
        assert out[1].fastpath == "vectorized"
        _assert_books(*out)

    def test_prefix_affinity_multichunk(self):
        kw = dict(prompt_len=400, max_new=24, prefix_share=0.5,
                  prefix_len=256, n_prefix_groups=6)
        out = _run_both(
            lambda: _fleet(policy="prefix_affinity", sigma=0.25,
                           seed=5),
            lambda: poisson_arrivals(30.0, n=1200, seed=5, **kw),
            poisson_arrival_batch(30.0, n=1200, seed=5, **kw),
        )
        assert out[1].fastpath == "vectorized"
        _assert_books(*out)

    def test_round_robin_same_tick_retire(self):
        # max_new=1 retires at its admission tick — the residency
        # net-no-op corner of the fused slot scan
        kw = dict(prompt_len=32, max_new=1)
        out = _run_both(
            lambda: _fleet(policy="round_robin", n_inner=1, tick=0.01),
            lambda: poisson_arrivals(120.0, n=1500, seed=8, **kw),
            poisson_arrival_batch(120.0, n=1500, seed=8, **kw),
        )
        _assert_books(*out)

    def test_hedge_p99(self):
        kw = dict(prompt_len=64, max_new=16)
        out = _run_both(
            lambda: _fleet(policy="hedge_p99", sigma=0.35, seed=3,
                           ttft_slo=0.25),
            lambda: poisson_arrivals(45.0, n=1500, seed=3, **kw),
            poisson_arrival_batch(45.0, n=1500, seed=3, **kw),
        )
        assert out[1].n_hedges == out[0].n_hedges
        _assert_books(*out)

    def test_overload_shed(self):
        kw = dict(prompt_len=96, max_new=32)
        out = _run_both(
            lambda: _fleet(n=2, shed_depth=8, shed_depth_hard=20),
            lambda: poisson_arrivals(90.0, n=1500, seed=6, **kw),
            poisson_arrival_batch(90.0, n=1500, seed=6, **kw),
        )
        rep_s, rep_f = out[0], out[1]
        assert rep_s.n_shed > 0
        assert rep_s.shed_reasons == rep_f.shed_reasons
        _assert_books(*out)

    def test_retry_storm(self):
        kw = dict(prompt_len=96, max_new=32)
        retry = dict(timeout_s=0.1, max_retries=3, jitter_s=0.2,
                     seed=4)
        out = _run_both(
            lambda: _fleet(n=2, shed_depth=10, shed_depth_hard=30),
            lambda: poisson_arrivals(80.0, n=1200, seed=4, **kw),
            poisson_arrival_batch(80.0, n=1200, seed=4, **kw),
            retry=RetryPolicy(**retry),
        )
        assert out[0].n_resubmits > 0
        _assert_books(*out)

    def test_diurnal(self):
        kw = dict(prompt_len=64, max_new=16)
        out = _run_both(
            lambda: _fleet(sigma=0.2, seed=7),
            lambda: diurnal_arrivals(40.0, n=1500, period=120.0,
                                     seed=7, **kw),
            diurnal_arrival_batch(40.0, n=1500, period=120.0, seed=7,
                                  **kw),
        )
        assert out[1].fastpath == "vectorized"
        _assert_books(*out)

    def test_dead_replica(self):
        kw = dict(prompt_len=64, max_new=16)
        out = _run_both(
            lambda: _fleet(dead=(1,)),
            lambda: poisson_arrivals(35.0, n=900, seed=9, **kw),
            poisson_arrival_batch(35.0, n=900, seed=9, **kw),
        )
        _assert_books(*out)


# --------------------------------------------------------------------------
# QoS days
# --------------------------------------------------------------------------


def _contracts():
    return [
        TenantContract("gold", cls="latency", weight=4.0, rate=900.0,
                       burst=600.0, hedges=2, ttft_slo=2.0),
        TenantContract("silver", cls="throughput", weight=2.0,
                       rate=700.0, burst=500.0),
        TenantContract("bronze", cls="batch", weight=1.0, rate=500.0,
                       burst=400.0),
    ]


class TestQosDayParity:
    def _mk(self, **kw):
        reg = TenantRegistry(_contracts())
        return lambda: _fleet(qos=reg, **kw)

    def test_drr_and_buckets(self):
        tenants = {"gold": 0.4, "silver": 0.35, "bronze": 0.25}
        kw = dict(prompt_len=96, max_new=32, tenants=tenants)
        out = _run_both(
            self._mk(),
            lambda: poisson_arrivals(45.0, n=1500, seed=11, **kw),
            poisson_arrival_batch(45.0, n=1500, seed=11, **kw),
        )
        assert out[1].fastpath == "vectorized"
        _assert_books(*out)
        # the shared TokenBucket objects end at identical levels
        _, _, _, _, rs, rf = out
        for nm in ("gold", "silver", "bronze"):
            bs, bf = rs._buckets[nm], rf._buckets[nm]
            assert bs.tokens == bf.tokens and bs._last == bf._last

    def test_qos_shed_and_budget(self):
        tenants = {"gold": 0.4, "silver": 0.3, "bronze": 0.3}
        kw = dict(prompt_len=96, max_new=32, tenants=tenants)
        out = _run_both(
            self._mk(n=2, shed_depth=10, shed_depth_hard=24),
            lambda: poisson_arrivals(70.0, n=1200, seed=13, **kw),
            poisson_arrival_batch(70.0, n=1200, seed=13, **kw),
        )
        assert out[0].n_shed > 0
        _assert_books(*out)

    def test_qos_hedge_entitlements(self):
        tenants = {"gold": 0.5, "silver": 0.3, "bronze": 0.2}
        kw = dict(prompt_len=64, max_new=16, tenants=tenants)
        out = _run_both(
            self._mk(policy="hedge_p99", sigma=0.35, seed=17,
                     ttft_slo=0.25),
            lambda: poisson_arrivals(40.0, n=1200, seed=17, **kw),
            poisson_arrival_batch(40.0, n=1200, seed=17, **kw),
        )
        assert (out[4].n_hedges_refused
                == out[5].n_hedges_refused)
        _assert_books(*out)

    def test_qos_retry(self):
        tenants = {"gold": 0.4, "silver": 0.3, "bronze": 0.3}
        kw = dict(prompt_len=96, max_new=32, tenants=tenants)
        out = _run_both(
            self._mk(n=2, shed_depth=8, shed_depth_hard=20),
            lambda: poisson_arrivals(65.0, n=1000, seed=19, **kw),
            poisson_arrival_batch(65.0, n=1000, seed=19, **kw),
            retry=RetryPolicy(timeout_s=0.8, max_retries=2,
                              jitter_s=0.15, seed=19),
        )
        _assert_books(*out)

    def test_untenanted_on_qos_router_falls_back(self):
        # the scalar door raises on tenant=None under qos; the fast
        # path must not accept what the scalar path refuses
        mk = self._mk()
        _, _, router = mk()
        batch = poisson_arrival_batch(30.0, n=50, seed=1,
                                      prompt_len=64, max_new=8)
        rep = None
        with pytest.raises(ValueError, match="tenant"):
            rep = run_router_day_fast(router, batch)
        assert rep is None


# --------------------------------------------------------------------------
# elastic / chaos days: the documented scalar-fallback boundary
# --------------------------------------------------------------------------


class TestFallbackParity:
    def test_partition_event_day(self):
        def scalar():
            _, reps, router = _fleet(n=3)
            rep = run_router_day(
                router,
                poisson_arrivals(60.0, n=600, seed=11, prompt_len=64,
                                 max_new=16),
                events=[ReplicaPartition(1.0, (2,), 2.5)],
            )
            return rep

        _, _, router = _fleet(n=3)
        batch = poisson_arrival_batch(60.0, n=600, seed=11,
                                      prompt_len=64, max_new=16)
        rep_f = run_router_day_fast(
            router, batch, events=[ReplicaPartition(1.0, (2,), 2.5)]
        )
        assert rep_f.fastpath == (
            "scalar-fallback: control-plane events in stream"
        )
        assert scalar().digest() == rep_f.digest()

    def test_resize_event_day(self):
        # FleetResize needs a controller to act on; the controller
        # alone already routes the day to the scalar loop
        from mpistragglers_jl_tpu.fleet import FleetController

        def day(fast):
            clock, reps, router = _fleet(n=4)
            ctl = FleetController(
                router, clock=clock, capacity_rps=4 / (6 * 0.02),
                min_replicas=2, max_replicas=4,
            )
            arrivals = poisson_arrivals(
                50.0, n=600, seed=21, prompt_len=64, max_new=16,
            )
            events = [FleetResize(2.0, 2), FleetResize(6.0, 4)]
            if fast:
                return run_router_day_fast(
                    router, arrivals, controller=ctl, events=events
                )
            return run_router_day(
                router, arrivals, controller=ctl, events=events
            )

        rep_f = day(fast=True)
        assert rep_f.fastpath.startswith("scalar-fallback")
        assert day(fast=False).digest() == rep_f.digest()

    def test_elastic_controller_day(self):
        from mpistragglers_jl_tpu.fleet import FleetController

        def day(fast):
            clock, reps, router = _fleet(
                n=6, shed_depth=64, shed_depth_hard=128
            )
            cap = 4 / (6 * 0.02)
            ctl = FleetController(
                router, clock=clock, capacity_rps=cap,
                min_replicas=3, max_replicas=6,
                decision_interval_s=1.0, dwell_s=2.0, cooldown_s=4.0,
            )
            arrivals = poisson_arrivals(
                0.5 * 6 * cap, n=900, seed=23, prompt_len=96,
                max_new=32,
            )
            if fast:
                return run_router_day_fast(
                    router, arrivals, controller=ctl
                )
            return run_router_day(router, arrivals, controller=ctl)

        rep_f = day(fast=True)
        assert rep_f.fastpath == (
            "scalar-fallback: controller attached (elastic day)"
        )
        assert day(fast=False).digest() == rep_f.digest()

    def test_chaos_clock_injection_falls_back(self):
        # anything already scheduled on the clock (chaos episodes
        # inject via clock.call_at) disqualifies the vectorized engine
        clock, _, router = _fleet()
        clock.call_at(5.0, lambda: None)
        ok, reason = fastpath_supported(router)
        assert not ok and "chaos" in reason

    def test_tracing_attached_falls_back(self):
        # round 22: a traced day records per-request lifecycle events
        # the vectorized engine never stamps — the fallback is named
        from mpistragglers_jl_tpu.obs import TraceBook

        _, _, router = _fleet()
        router.attach_trace(TraceBook())
        ok, reason = fastpath_supported(router)
        assert not ok and reason == "tracing attached"

    def test_series_slo_attached_falls_back(self):
        # round 24: a windowed day rolls the series store (and the
        # burn policy) on the drive loop — the vectorized engine has
        # no loop to hook, so the fallback is named
        from mpistragglers_jl_tpu.obs import (
            MetricsRegistry,
            SeriesStore,
            SloObjective,
            SloPolicy,
        )

        _, _, router = _fleet()
        reg = MetricsRegistry()
        series = SeriesStore(reg, window_s=1.0)
        ok, reason = fastpath_supported(router, series=series)
        assert not ok and reason == "series/slo attached"
        slo = SloPolicy(series, [SloObjective(
            "ttft-p99", "latency", 0.5, q=0.99,
        )])
        ok, reason = fastpath_supported(router, slo=slo)
        assert not ok and reason == "series/slo attached"

    def test_used_router_falls_back(self):
        _, _, router = _fleet()
        batch = poisson_arrival_batch(40.0, n=200, seed=1,
                                      prompt_len=64, max_new=8)
        run_router_day_fast(router, batch)
        ok, reason = fastpath_supported(router)
        assert not ok

    def test_two_tier_falls_back(self):
        clock = VirtualClock()
        fleet = [
            SimReplica(clock, slots=4, n_inner=8, tick_s=0.02,
                       tier="prefill" if i < 1 else "decode",
                       chunk_s=0.01)
            for i in range(3)
        ]
        router = RequestRouter(fleet, policy="two_tier", clock=clock)
        ok, reason = fastpath_supported(router)
        assert not ok


# --------------------------------------------------------------------------
# property-style sweep: seeds x (retry, partition, resize)
# --------------------------------------------------------------------------


class TestPropertySweep:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize(
        "retry,event",
        [
            (None, None),
            ("retry", None),
            (None, "partition"),
            (None, "resize"),
            ("retry", "partition"),
            ("retry", "resize"),
        ],
    )
    def test_digest_parity(self, seed, retry, event):
        rp = (
            None if retry is None
            else RetryPolicy(timeout_s=0.6, max_retries=2,
                            jitter_s=0.1, seed=seed)
        )
        events = {
            None: [],
            "partition": [ReplicaPartition(1.0, (1,), 2.0)],
            "resize": [FleetResize(1.5, 2), FleetResize(4.0, 3)],
        }[event]

        def day(fast):
            clock, _, router = _fleet(n=3, sigma=0.2, seed=seed,
                                      shed_depth=16,
                                      shed_depth_hard=40)
            ctl = None
            if event == "resize":
                # FleetResize acts through a controller; attaching
                # one is itself a fallback boundary
                from mpistragglers_jl_tpu.fleet import FleetController

                ctl = FleetController(
                    router, clock=clock, capacity_rps=4 / (6 * 0.02),
                    min_replicas=2, max_replicas=3,
                )
            arrivals = poisson_arrivals(
                55.0, n=400, seed=seed, prompt_len=64, max_new=16,
            )
            if fast:
                return run_router_day_fast(
                    router, arrivals, controller=ctl,
                    events=list(events), retry=rp,
                )
            return run_router_day(
                router, arrivals, controller=ctl,
                events=list(events), retry=rp,
            )

        rep_f = day(fast=True)
        rep_s = day(fast=False)
        assert rep_s.digest() == rep_f.digest()
        assert rep_s.outcomes == rep_f.outcomes
        if event is None:
            assert rep_f.fastpath == "vectorized"
        else:
            assert rep_f.fastpath.startswith("scalar-fallback")


# --------------------------------------------------------------------------
# batch generators and the events/s counter
# --------------------------------------------------------------------------


class TestArrivalBatch:
    def test_poisson_batch_equals_generator(self):
        kw = dict(prompt_len=200, max_new=24, prefix_share=0.3,
                  prefix_len=128, n_prefix_groups=4, long_share=0.1,
                  long_prompt_len=1024, long_max_new=64,
                  tenants={"a": 0.6, "b": 0.4})
        batch = poisson_arrival_batch(25.0, n=800, seed=42, **kw)
        gen = list(poisson_arrivals(25.0, n=800, seed=42, **kw))
        assert len(batch) == len(gen)
        for a, b in zip(batch, gen):
            assert a.t == b.t
            assert a.prompt.length == b.prompt.length
            assert a.prompt.prefix == b.prompt.prefix
            assert a.prompt.prefix_len == b.prompt.prefix_len
            assert a.max_new == b.max_new
            assert a.tenant == b.tenant

    def test_diurnal_batch_equals_generator(self):
        kw = dict(prompt_len=64, max_new=8)
        batch = diurnal_arrival_batch(30.0, n=500, period=90.0,
                                      amplitude=0.6, seed=5, **kw)
        gen = list(diurnal_arrivals(30.0, n=500, period=90.0,
                                    amplitude=0.6, seed=5, **kw))
        assert len(batch) == len(gen)
        for a, b in zip(batch, gen):
            assert a.t == b.t and a.max_new == b.max_new

    def test_from_arrivals_roundtrip(self):
        gen = list(poisson_arrivals(20.0, n=100, seed=3,
                                    prompt_len=64, max_new=8))
        batch = ArrivalBatch.from_arrivals(gen)
        for a, b in zip(batch, gen):
            assert (a.t, a.prompt.length, a.max_new) == (
                b.t, b.prompt.length, b.max_new)

    def test_merged_streams_ingest(self):
        # heapq.merge of two seeded streams (the burst idiom) ingests
        # through from_arrivals and runs vectorized
        base = poisson_arrivals(30.0, n=300, seed=1, prompt_len=64,
                                max_new=8)
        burst = poisson_arrivals(50.0, n=100, seed=2, start=3.0,
                                 prompt_len=64, max_new=8)
        merged = list(heapq.merge(base, burst, key=lambda a: a.t))
        _, _, router = _fleet()
        rep_f = run_router_day_fast(router, merged)
        _, _, router2 = _fleet()
        base = poisson_arrivals(30.0, n=300, seed=1, prompt_len=64,
                                max_new=8)
        burst = poisson_arrivals(50.0, n=100, seed=2, start=3.0,
                                 prompt_len=64, max_new=8)
        rep_s = run_router_day(
            router2, heapq.merge(base, burst, key=lambda a: a.t)
        )
        assert rep_f.fastpath == "vectorized"
        assert rep_s.digest() == rep_f.digest()


class TestEventsPerS:
    def test_counter_requires_timer(self):
        _, _, router = _fleet()
        batch = poisson_arrival_batch(40.0, n=300, seed=1,
                                      prompt_len=64, max_new=8)
        rep = run_router_day_fast(router, batch)
        assert rep.n_events > 0
        assert rep.wall_s is None and rep.events_per_s is None

    def test_counter_with_timer_and_cross_path_equality(self):
        ticks = [0.0]

        def timer():
            ticks[0] += 0.5
            return ticks[0]

        _, _, router = _fleet()
        batch = poisson_arrival_batch(40.0, n=300, seed=1,
                                      prompt_len=64, max_new=8)
        rep_f = run_router_day_fast(router, batch, timer=timer)
        _, _, router2 = _fleet()
        rep_s = run_router_day(
            router2,
            poisson_arrivals(40.0, n=300, seed=1, prompt_len=64,
                             max_new=8),
            timer=timer,
        )
        # n_events is a real event count, identical across paths;
        # events_per_s divides it by the injected timer's wall
        assert rep_f.n_events == rep_s.n_events
        assert rep_f.events_per_s == rep_f.n_events / rep_f.wall_s
        # digest is untouched by the self-measurement (non-witness)
        assert rep_f.digest() == rep_s.digest()


# --------------------------------------------------------------------------
# tune wiring: same decision, bigger grid per budget
# --------------------------------------------------------------------------


class TestTuneFastWiring:
    def test_router_policy_sweep_identical(self):
        from mpistragglers_jl_tpu.sim.tune import sweep_router_policy

        a = sweep_router_policy(requests=500, seed=5, fast="never")
        b = sweep_router_policy(requests=500, seed=5, fast="auto")
        assert a == b

    def test_bad_fast_value_refused(self):
        from mpistragglers_jl_tpu.sim.tune import sweep_router_policy

        with pytest.raises(ValueError, match="fast"):
            sweep_router_policy(requests=50, fast="always")

    def test_tenant_weights_budget_requires_timer(self):
        from mpistragglers_jl_tpu.sim.tune import sweep_tenant_weights

        with pytest.raises(ValueError, match="timer"):
            sweep_tenant_weights(
                contracts=_contracts(),
                candidates=[{"gold": 1.0, "silver": 1.0,
                             "bronze": 1.0}],
                budget_s=1.0,
            )

    def test_tenant_weights_budget_cuts_grid(self):
        from mpistragglers_jl_tpu.sim.tune import sweep_tenant_weights

        cands = [
            {"gold": g, "silver": 2.0, "bronze": 1.0}
            for g in (2.0, 4.0, 8.0)
        ]
        ticks = iter(float(i) for i in range(100))
        res = sweep_tenant_weights(
            contracts=_contracts(), candidates=cands, requests=400,
            seed=1, budget_s=0.5, timer=lambda: next(ticks),
        )
        # the injected timer charges ~1s per candidate: exactly one
        # fits a 0.5s budget (the first always runs)
        assert res["candidates_evaluated"] == 1
        assert res["budget_exhausted"]
        assert len(res["entries"]) == 1

    def test_deeper_grid_improves_decision_at_same_seed(self):
        # the controller-facing claim behind the fast path: the grid a
        # scalar budget affords (a prefix) scores no better than the
        # full grid the fast path affords in the same wall budget —
        # the bench rung (sim_fastpath_bench) measures the wall side;
        # this pins the decision side deterministically
        from mpistragglers_jl_tpu.sim.tune import sweep_tenant_weights

        grid = [
            {"gold": g, "silver": s, "bronze": 1.0}
            for g in (1.0, 2.0, 4.0, 8.0)
            for s in (1.0, 2.0)
        ]
        full = sweep_tenant_weights(
            contracts=_contracts(), candidates=grid, requests=400,
            seed=7, fast="auto",
        )
        prefix = sweep_tenant_weights(
            contracts=_contracts(), candidates=grid[:2], requests=400,
            seed=7, fast="auto",
        )
        assert (full["best_entry"]["score"]
                <= prefix["best_entry"]["score"])
        assert full["candidates_evaluated"] == len(grid)
