"""One-shot SPMD launcher (VERDICT round 1, missing #3).

Reference bar: the whole topology up with one command,
``mpiexec -n N julia script.jl`` (test/runtests.jl:17). The launcher is
exercised end-to-end as a subprocess, the way a user runs it.
"""

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launcher(nranks, script, *extra, timeout=120):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "mpistragglers_jl_tpu.launch",
         "-n", str(nranks), *extra, script],
        capture_output=True, text=True, timeout=timeout, env=env,
    )


def test_spmd_example_end_to_end():
    """The shipped example runs to completion under the launcher: the
    coordinator's 10-epoch nwait=1 loop over launcher-started workers."""
    proc = _run_launcher(
        3, os.path.join(REPO, "examples", "spmd_launch_example.py")
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "done: epochs=10 workers=2" in proc.stdout
    assert proc.stdout.count("epoch ") == 10


def test_failed_rank_fails_the_launch(tmp_path):
    """mpiexec semantics: any rank exiting non-zero fails the job."""
    script = tmp_path / "boom.py"
    script.write_text(textwrap.dedent("""
        import sys
        from mpistragglers_jl_tpu import launch
        ctx = launch.init()
        if ctx.is_coordinator:
            backend = ctx.coordinator_backend(connect_timeout=30)
            backend.shutdown()
            sys.exit(3)   # coordinator fails after a clean shutdown
        ctx.serve(lambda i, p, e: p)
    """))
    proc = _run_launcher(3, str(script), timeout=90)
    assert proc.returncode == 3


def test_init_outside_launcher_raises():
    from mpistragglers_jl_tpu import launch

    env_backup = os.environ.pop("MSGT_RANK", None)
    try:
        import pytest

        with pytest.raises(RuntimeError, match="MSGT_RANK"):
            launch.init()
    finally:
        if env_backup is not None:
            os.environ["MSGT_RANK"] = env_backup
