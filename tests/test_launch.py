"""One-shot SPMD launcher (VERDICT round 1, missing #3).

Reference bar: the whole topology up with one command,
``mpiexec -n N julia script.jl`` (test/runtests.jl:17). The launcher is
exercised end-to-end as a subprocess, the way a user runs it.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launcher(nranks, script, *extra, timeout=120):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "mpistragglers_jl_tpu.launch",
         "-n", str(nranks), *extra, script],
        capture_output=True, text=True, timeout=timeout, env=env,
    )


@pytest.mark.slow
def test_spmd_example_end_to_end():
    """The shipped example runs to completion under the launcher: the
    coordinator's 10-epoch nwait=1 loop over launcher-started workers."""
    proc = _run_launcher(
        3, os.path.join(REPO, "examples", "spmd_launch_example.py")
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "done: epochs=10 workers=2" in proc.stdout
    assert proc.stdout.count("epoch ") == 10


@pytest.mark.slow
def test_failed_rank_fails_the_launch(tmp_path):
    """mpiexec semantics: any rank exiting non-zero fails the job."""
    script = tmp_path / "boom.py"
    script.write_text(textwrap.dedent("""
        import sys
        from mpistragglers_jl_tpu import launch
        ctx = launch.init()
        if ctx.is_coordinator:
            backend = ctx.coordinator_backend(connect_timeout=30)
            backend.shutdown()
            sys.exit(3)   # coordinator fails after a clean shutdown
        ctx.serve(lambda i, p, e: p)
    """))
    proc = _run_launcher(3, str(script), timeout=90)
    assert proc.returncode == 3


def test_init_outside_launcher_raises():
    from mpistragglers_jl_tpu import launch

    env_backup = os.environ.pop("MSGT_RANK", None)
    try:
        import pytest

        with pytest.raises(RuntimeError, match="MSGT_RANK"):
            launch.init()
    finally:
        if env_backup is not None:
            os.environ["MSGT_RANK"] = env_backup


def test_parse_hosts_and_assign_ranks():
    from mpistragglers_jl_tpu.launch import assign_ranks, parse_hosts

    hosts = parse_hosts("a:2,b", None)
    assert hosts == [("a", 2), ("b", None)]
    spans = assign_ranks(6, hosts)
    assert spans == [("a", range(0, 2)), ("b", range(2, 6))]
    # uncapped hosts split the remainder, earlier hosts take the extra
    spans = assign_ranks(5, [("a", None), ("b", None)])
    assert spans == [("a", range(0, 3)), ("b", range(3, 5))]
    import pytest

    with pytest.raises(ValueError, match="must match"):
        assign_ranks(5, [("a", 2), ("b", 2)])


def test_parse_hostfile_mpiexec_style(tmp_path):
    from mpistragglers_jl_tpu.launch import parse_hosts

    hf = tmp_path / "hosts.txt"
    hf.write_text("# cluster\nnode1 slots=4\nnode2:2\nnode3\n")
    assert parse_hosts(None, str(hf)) == [
        ("node1", 4), ("node2", 2), ("node3", None)
    ]


@pytest.mark.slow
def test_multihost_two_process_groups(tmp_path):
    """The VERDICT r2 'one command' bar: --hosts with a faked ssh
    models two hosts as two local process groups with separate tmpdirs
    over TCP; the whole 1-coordinator + 4-worker topology comes up from
    ONE launcher invocation and the epochs complete."""
    import socket

    fake = tmp_path / "fake_ssh.py"
    fake.write_text(textwrap.dedent("""
        import os, subprocess, sys
        # argv: [prog, host, remote-shell-command] — like `ssh host cmd`
        host, cmd = sys.argv[1], sys.argv[2]
        d = os.path.join(os.environ["FAKE_HOST_ROOT"], host)
        os.makedirs(d, exist_ok=True)
        env = dict(os.environ)
        env["TMPDIR"] = d                      # separate 'filesystem'
        sys.exit(subprocess.call(["bash", "-c", cmd], env=env))
    """))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["FAKE_HOST_ROOT"] = str(tmp_path / "hosts")
    proc = subprocess.run(
        [sys.executable, "-m", "mpistragglers_jl_tpu.launch",
         "-n", "5", "--hosts", "localhost:1,hostB",
         "--address", f"tcp://127.0.0.1:{port}",
         "--launcher", f"{sys.executable} {fake}",
         os.path.join(REPO, "examples", "spmd_launch_example.py")],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-2000:])
    assert "done: epochs=10 workers=4" in proc.stdout
    # the remote group really ran under the fake host's tmpdir
    assert (tmp_path / "hosts" / "hostB").is_dir()


@pytest.mark.slow
def test_multihost_remote_rank_failure_propagates(tmp_path):
    """A non-zero exit inside the REMOTE span fails the launch (ssh
    span runner exits with the span's worst code, mpiexec-style)."""
    import socket

    fake = tmp_path / "fake_ssh.py"
    fake.write_text(textwrap.dedent("""
        import subprocess, sys
        sys.exit(subprocess.call(["bash", "-c", sys.argv[2]]))
    """))
    script = tmp_path / "boom.py"
    script.write_text(textwrap.dedent("""
        import sys
        from mpistragglers_jl_tpu import launch
        ctx = launch.init()
        if ctx.is_coordinator:
            try:
                backend = ctx.coordinator_backend(connect_timeout=15)
                backend.shutdown()
            except Exception:
                pass  # the dead remote never connects; rank 2's code wins
            sys.exit(0)
        if ctx.rank == 2:
            sys.exit(7)   # remote worker dies before serving
        ctx.serve(lambda i, p, e: p)
    """))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "mpistragglers_jl_tpu.launch",
         "-n", "3", "--hosts", "localhost:2,hostB",
         "--address", f"tcp://127.0.0.1:{port}",
         "--launcher", f"{sys.executable} {fake}",
         "--grace", "5", str(script)],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert proc.returncode == 7, (proc.returncode, proc.stderr[-2000:])


def test_parse_hosts_ipv6():
    import pytest

    from mpistragglers_jl_tpu.launch import parse_hosts

    assert parse_hosts("[fe80::1]:4,[::1]", None) == [
        ("fe80::1", 4), ("::1", None)
    ]
    with pytest.raises(ValueError, match="bracket IPv6"):
        parse_hosts("fe80::1", None)


def test_remote_cmd_keeps_secret_off_argv():
    """The auth token must never appear on the ssh command line (argv
    is world-readable via ps on both hosts); it rides stdin."""
    from mpistragglers_jl_tpu.launch import _remote_cmd

    env = {"MSGT_NRANKS": "4", "MSGT_ADDRESS": "tcp://h:1", 
           "MSGT_AUTH": "topsecret123"}
    cmd = _remote_cmd("ssh", "hostB", range(1, 4), env, 5.0,
                      "job.py", [])
    assert not any("topsecret123" in part for part in cmd)
    assert any("MSGT_ADDRESS" in part for part in cmd)


@pytest.mark.slow
def test_multihost_spmd_example_single_host():
    """The one-liner example (examples/multihost_spmd.py) also runs
    single-host under the launcher — same script, no --hosts."""
    proc = _run_launcher(
        3, os.path.join(REPO, "examples", "multihost_spmd.py"),
        timeout=150,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "done: workers=2" in proc.stdout


@pytest.mark.slow
def test_span_watchdog_reaps_on_stdin_eof(tmp_path):
    """The remote-side guarantee: when the launch channel (stdin pipe)
    EOFs — launcher death or abort — the span runner kills its rank
    processes instead of orphaning them, and exits with the worst
    ALREADY-OBSERVED rank code so an early failure survives teardown."""
    import signal

    import pytest
    import time

    script = tmp_path / "mixed.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        from mpistragglers_jl_tpu import launch
        ctx = launch.init()
        open(os.environ["PIDDIR"] + f"/rank{ctx.rank}.pid", "w").write(
            str(os.getpid()))
        if ctx.rank == 1:
            sys.exit(5)       # early failure, must survive teardown
        time.sleep(300)       # hang: only the watchdog can end this
    """))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MSGT_NRANKS"] = "3"
    env["MSGT_ADDRESS"] = "tcp://127.0.0.1:1"  # never dialed here
    env["PIDDIR"] = str(tmp_path)
    proc = subprocess.Popen(
        [sys.executable, "-m", "mpistragglers_jl_tpu.launch",
         "--_span", "1:3", "-n", "3", "--grace", "2", str(script)],
        stdin=subprocess.PIPE, env=env,
    )
    proc.stdin.write(b"secret\n")  # the auth line the span expects
    proc.stdin.flush()
    # wait until rank 2 is up AND rank 1 has fully exited with its
    # failure code — the watchdog must OBSERVE the failure before the
    # channel dies, which is the scenario this test pins
    deadline = time.monotonic() + 30
    while True:
        if time.monotonic() >= deadline:
            proc.kill()
            pytest.fail(
                "span never reached the armed state (rank files: "
                f"{sorted(p.name for p in tmp_path.iterdir())})"
            )
        if (tmp_path / "rank2.pid").exists() and (
            tmp_path / "rank1.pid"
        ).exists():
            pid1 = int((tmp_path / "rank1.pid").read_text())
            try:
                os.kill(pid1, 0)
            except ProcessLookupError:
                break  # rank 1 is gone (exit 5 recorded)
        time.sleep(0.1)
    pid2 = int((tmp_path / "rank2.pid").read_text())
    proc.stdin.close()  # the launch channel dies
    rc = proc.wait(timeout=30)
    assert rc == 5, rc  # rank 1's observed failure, not a kill code
    # the hung rank was reaped, not orphaned
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            os.kill(pid2, 0)
        except ProcessLookupError:
            break
        time.sleep(0.2)
    else:
        os.kill(pid2, signal.SIGKILL)
        raise AssertionError(f"rank 2 (pid {pid2}) survived stdin EOF")


def test_remote_span_broken_pipe_fails_clean(monkeypatch, capsys):
    """Advisor r3: an ssh process that dies before reading the auth
    token (bad host, ssh missing) breaks the stdin pipe; the launcher
    must tear down already-spawned ranks and exit with a clean nonzero
    code — not escape with a BrokenPipeError traceback that orphans
    them."""
    import pytest

    from mpistragglers_jl_tpu import launch

    events = []

    class FakeLocal:
        stdin = None

        def __init__(self):
            self.signaled = False

        def poll(self):
            return 0 if self.signaled else None

        def send_signal(self, sig):
            self.signaled = True
            events.append(("signal", sig))

        def wait(self, timeout=None):
            events.append("local-reaped")
            return 0

        def kill(self):  # pragma: no cover
            events.append("local-killed")

    class FakeStdin:
        def write(self, b):
            raise BrokenPipeError("Broken pipe")

        def flush(self):  # pragma: no cover
            pass

        def close(self):
            pass

    class FakeRemote:
        def __init__(self, *a, **kw):
            self.stdin = FakeStdin()

        def poll(self):
            return 255

        def wait(self, timeout=None):
            return 255

        def send_signal(self, sig):  # pragma: no cover
            pass

        def kill(self):  # pragma: no cover
            pass

    local = FakeLocal()
    monkeypatch.setattr(launch, "_spawn_rank", lambda *a, **kw: local)
    monkeypatch.setattr(launch.subprocess, "Popen", FakeRemote)
    with pytest.raises(SystemExit) as ei:
        launch.main(
            ["-n", "2", "--hosts", "localhost:1,deadhost",
             "--address", "tcp://127.0.0.1:1", "script.py"]
        )
    assert ei.value.code == 255  # the dead span's exit code wins
    # the already-spawned local rank was interrupted and reaped
    assert ("signal", __import__("signal").SIGINT) in events
    assert "local-reaped" in events
    err = capsys.readouterr().err
    assert "span on 'deadhost' failed before start" in err


@pytest.mark.slow
def test_remote_span_dying_after_token_aborts_promptly(tmp_path):
    """The sibling of the broken-pipe case: the ssh process consumes the
    auth token, THEN crashes. The job must abort with the span's code
    promptly — not hang until the coordinator's own timeout while it
    waits for workers that will never connect."""
    import socket
    import time

    fake = tmp_path / "fake_ssh_die.py"
    fake.write_text(
        "import sys, time\n"
        "sys.stdin.readline()\n"
        "time.sleep(0.5)\n"
        "sys.exit(9)\n"
    )
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "mpistragglers_jl_tpu.launch",
         "-n", "3", "--hosts", "localhost:2,deadhost",
         "--address", f"tcp://127.0.0.1:{port}",
         "--launcher", f"{sys.executable} {fake}",
         os.path.join(REPO, "examples", "spmd_launch_example.py")],
        capture_output=True, text=True, timeout=120, env=env,
    )
    took = time.monotonic() - t0
    assert proc.returncode == 9, (proc.returncode, proc.stderr[-2000:])
    assert "remote span exited 9" in proc.stderr
    # prompt: well under the coordinator's connect timeout
    assert took < 30, took
