"""Sliding-window attention (round 4): the Mistral-style band through
every kernel and the serving path.

Contract: ``TransformerConfig(attn_window=W)`` makes position q attend
positions (q-W, q] only. The reference oracle implements the band as a
plain mask; the flash kernels must match it (they additionally SKIP
blocks entirely left of the band); ring and Ulysses must match the
dense oracle under sequence sharding; the KV-cache decode path masks
the same band, so teacher-forced decode equals the windowed training
forward.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mpistragglers_jl_tpu.models.decode import (
    _ring_from_cache,
    decode_step_dense,
    decode_step_ring_dense,
    generate_dense,
    generate_ring_dense,
    init_cache,
    init_ring_cache,
    make_ring_generate,
    prefill_dense,
)
from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig,
    forward_dense,
    init_params,
    make_forward,
    shard_params,
)
from mpistragglers_jl_tpu.ops.flash_attention import flash_attention
from mpistragglers_jl_tpu.parallel import make_mesh
from mpistragglers_jl_tpu.parallel.ring_attention import (
    reference_attention,
)

CFG = TransformerConfig(
    vocab=61, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2, d_ff=128,
    attn_window=5,
)


def _qkv(Hq, Hkv, B=2, L=32, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda h: jnp.asarray(rng.standard_normal((B, L, h, D)),
                               jnp.float32)
    return mk(Hq), mk(Hkv), mk(Hkv)


def test_reference_window_band_semantics():
    """The oracle's band: position q sees exactly (q-W, q]."""
    q, k, v = _qkv(1, 1, B=1, L=8)
    W = 3
    out = reference_attention(q, k, v, causal=True, window=W)
    # hand-build the same thing row by row
    for t in range(8):
        lo = max(0, t - W + 1)
        qs = q[:, t:t + 1]
        want = reference_attention(
            qs, k[:, lo:t + 1], v[:, lo:t + 1], causal=False
        )
        np.testing.assert_allclose(
            np.asarray(out[:, t:t + 1]), np.asarray(want),
            atol=1e-5, rtol=1e-5,
        )


@pytest.mark.parametrize("bwd", ["split", "fused"])
@pytest.mark.parametrize("hkv", [1, 4])
@pytest.mark.parametrize("W", [1, 5, 16, 100])
def test_flash_window_matches_reference(W, hkv, bwd):
    """Flash (block-skipping + in-block band mask) vs the oracle —
    values and all three grads, GQA included, both backward impls;
    W=100 > L pins window-larger-than-sequence == full causal."""
    q, k, v = _qkv(4, hkv, L=32)

    def f_flash(q, k, v):
        o = flash_attention(
            q, k, v, causal=True, window=W, block_q=8, block_k=8,
            bwd_impl=bwd,
        )
        return (o.astype(jnp.float32) ** 2).sum()

    def f_ref(q, k, v):
        o = reference_attention(q, k, v, causal=True, window=W)
        return (o.astype(jnp.float32) ** 2).sum()

    o_got = flash_attention(
        q, k, v, causal=True, window=W, block_q=8, block_k=8
    )
    o_want = reference_attention(q, k, v, causal=True, window=W)
    np.testing.assert_allclose(
        np.asarray(o_got), np.asarray(o_want), atol=1e-5, rtol=1e-5
    )
    g_got = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_got, g_want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
            err_msg=f"d{n} W={W}",
        )


@pytest.mark.parametrize(
    "shape,attn",
    [
        ((2, 2, 2), "ring"),
        ((1, 4, 2), "ring"),
        ((2, 2, 2), "ulysses"),
    ],
)
def test_sharded_window_forward_matches_dense(shape, attn):
    """The band crosses sequence shards: ring/Ulysses with attn_window
    must match the dense windowed oracle."""
    cfg = dataclasses.replace(CFG, attn=attn)
    mesh = make_mesh(shape, ("dp", "sp", "tp"))
    params = init_params(cfg, seed=1)
    rng = np.random.default_rng(2)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (4, 16)), jnp.int32)
    want = forward_dense(params, toks, cfg)
    # sanity: the window really changes the function
    full = forward_dense(
        params, toks, dataclasses.replace(cfg, attn_window=None)
    )
    assert not np.allclose(np.asarray(want), np.asarray(full), atol=1e-3)
    fwd = make_forward(cfg, mesh)
    got = fwd(
        shard_params(params, cfg, mesh),
        jax.device_put(toks, NamedSharding(mesh, P("dp", "sp"))),
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )


def test_windowed_decode_teacher_forced():
    """The serving path masks the same band: prefill + decode steps
    reproduce the windowed training forward position-for-position."""
    cfg = CFG
    params = init_params(cfg, seed=3)
    rng = np.random.default_rng(4)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (2, 12)), jnp.int32)
    want = forward_dense(params, toks, cfg)
    cache = init_cache(cfg, 2, 12)
    lg, cache = prefill_dense(params, toks[:, :6], cache, cfg)
    np.testing.assert_allclose(
        np.asarray(lg), np.asarray(want[:, :6]), atol=1e-4, rtol=1e-4
    )
    for t in range(6, 12):
        lg, cache = decode_step_dense(
            params, toks[:, t], cache, jnp.int32(t), cfg
        )
        np.testing.assert_allclose(
            np.asarray(lg), np.asarray(want[:, t]), atol=1e-4,
            rtol=1e-4, err_msg=f"position {t}",
        )


def test_window_validation():
    with pytest.raises(ValueError, match="attn_window must be"):
        TransformerConfig(attn_window=0)
    q, k, v = _qkv(2, 2, L=8)
    with pytest.raises(ValueError, match="window must be"):
        flash_attention(q, k, v, causal=True, window=0)


@pytest.mark.slow
@pytest.mark.parametrize("Tp", [3, 12])
def test_ring_decode_teacher_forced(Tp):
    """The O(W) ring cache reproduces the windowed training forward
    position-for-position, through multiple slot wraparounds (decode
    runs to position 19 with W=5, so every slot is overwritten at least
    once) and through the Tp < W warmup (Tp=3 leaves unwritten slots
    that must self-mask)."""
    cfg = CFG
    L = 20
    params = init_params(cfg, seed=3)
    rng = np.random.default_rng(4)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (2, L)), jnp.int32)
    want = forward_dense(params, toks, cfg)
    cache = init_cache(cfg, 2, Tp)
    lg, cache = prefill_dense(params, toks[:, :Tp], cache, cfg)
    ring = [_ring_from_cache(cl, Tp, cfg.attn_window) for cl in cache]
    for t in range(Tp, L):
        lg, ring = decode_step_ring_dense(
            params, toks[:, t], ring, jnp.int32(t), cfg
        )
        np.testing.assert_allclose(
            np.asarray(lg), np.asarray(want[:, t]), atol=1e-4,
            rtol=1e-4, err_msg=f"position {t}",
        )


@pytest.mark.parametrize("Tp", [3, 12])
def test_ring_generate_matches_masked_generate(Tp):
    """generate_ring_dense == generate_dense token-for-token on a
    window config: same band, different storage. n_new=13 with W=5
    wraps every slot."""
    cfg = CFG
    params = init_params(cfg, seed=5)
    rng = np.random.default_rng(6)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, Tp)), jnp.int32)
    want = generate_dense(params, prompt, 13, cfg)
    got = generate_ring_dense(params, prompt, 13, cfg)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_ring_generate_sampled_matches_masked():
    """Sampling draws from identical logits streams (same fold-in key
    schedule), so the sampled token streams agree too."""
    cfg = CFG
    params = init_params(cfg, seed=7)
    rng = np.random.default_rng(8)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, 6)), jnp.int32)
    key = jax.random.key(9)
    want = generate_dense(
        params, prompt, 8, cfg, temperature=0.8, top_k=7, key=key
    )
    got = generate_ring_dense(
        params, prompt, 8, cfg, temperature=0.8, top_k=7, key=key
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("shape", [(2, 2), (1, 4)])
def test_sharded_ring_generate_matches_dense(shape):
    """make_ring_generate over dp x tp == the dense ring generator —
    including tp=4 > kv_heads=2, the replicated-groups cache layout."""
    cfg = CFG
    mesh = make_mesh(shape, ("dp", "tp"))
    params = init_params(cfg, seed=10)
    rng = np.random.default_rng(11)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, 7)), jnp.int32)
    want = generate_ring_dense(params, prompt, 9, cfg)
    gen = make_ring_generate(cfg, mesh, 9)
    got = gen(
        shard_params(params, cfg, mesh),
        jax.device_put(prompt, NamedSharding(mesh, P("dp", None))),
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_ring_cache_is_O_window():
    """The structural claim: ring leaves are (B, W, Hkv, Dh) however
    long the stream — no max_len anywhere in the layout."""
    cfg = CFG
    ring = init_ring_cache(cfg, batch=3)
    for layer in ring:
        assert layer["k"].shape == (
            3, cfg.attn_window, cfg.kv_heads, cfg.head_dim
        )
        assert layer["v"].shape == layer["k"].shape


def test_ring_requires_window():
    cfg = dataclasses.replace(CFG, attn_window=None)
    with pytest.raises(ValueError, match="sliding-window"):
        init_ring_cache(cfg, batch=1)
    params = init_params(cfg, seed=0)
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="sliding-window"):
        generate_ring_dense(params, prompt, 2, cfg)


@pytest.mark.parametrize("maker_kind", ["ring", "ulysses"])
def test_standalone_wrappers_take_window(maker_kind):
    from mpistragglers_jl_tpu.parallel.ring_attention import (
        make_ring_attention,
        make_ulysses_attention,
    )

    mesh = make_mesh((4,), ("sp",))
    q, k, v = _qkv(4, 4, L=32)
    maker = (
        make_ring_attention if maker_kind == "ring"
        else make_ulysses_attention
    )
    f = maker(mesh, causal=True, window=5)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    got = f(*(jax.device_put(x, spec) for x in (q, k, v)))
    want = reference_attention(q, k, v, causal=True, window=5)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
    )


def test_public_ring_from_cache_matches_private_and_guards():
    """ADVICE r4: the prefill->ring handoff is public API now; the
    guard rejects a source cache too short to hold the prompt (a
    clamped dynamic_update_slice would otherwise corrupt the ring
    silently)."""
    from mpistragglers_jl_tpu.models.decode import ring_from_cache

    cfg = CFG
    Tp = 7
    params = init_params(cfg, seed=5)
    rng = np.random.default_rng(6)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (2, Tp)), jnp.int32)
    cache = init_cache(cfg, 2, Tp)
    _, cache = prefill_dense(params, toks, cache, cfg)
    pub = ring_from_cache(cache, Tp, cfg)
    priv = [_ring_from_cache(cl, Tp, cfg.attn_window) for cl in cache]
    for a, b in zip(pub, priv):
        for kk in a:
            np.testing.assert_array_equal(np.asarray(a[kk]),
                                          np.asarray(b[kk]))
    short = init_cache(cfg, 2, Tp - 2)
    with pytest.raises(ValueError, match="positions < prompt"):
        ring_from_cache(short, Tp, cfg)
    # prefilling a too-short arena refuses at trace time, too
    with pytest.raises(ValueError, match="does not fit the cache"):
        prefill_dense(params, toks, short, cfg)


def test_use_decode_kernel_toggle_takes_effect_after_compile():
    """ADVICE r4: the kernel toggle must not be silently ignored for
    shapes whose dense runner already compiled. The flag is part of the
    runner cache key, so a toggle selects a different (new) program
    while every already-compiled program for the other setting stays
    cached for reuse."""
    from mpistragglers_jl_tpu.models.decode import (
        _dense_runner,
        use_decode_kernel,
    )

    # the flag can route only on lane-aligned head_dim + quantized cache
    cfg = dataclasses.replace(
        CFG, d_model=256, n_heads=2, n_kv_heads=1, d_ff=64
    )
    assert cfg.head_dim == 128
    params = init_params(cfg, seed=9)
    rng = np.random.default_rng(10)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (1, 4)), jnp.int32)
    generate_dense(params, prompt, 3, cfg, quantize_kv=True)
    before = _dense_runner.cache_info().currsize
    assert before > 0
    use_decode_kernel(True)
    try:
        # same call re-traces under the new flag: a NEW cache entry,
        # nothing evicted (programs for the other setting survive)
        generate_dense(params, prompt, 3, cfg, quantize_kv=True)
        assert _dense_runner.cache_info().currsize == before + 1
        # the flag is INERT for bf16 caches: no extra entry, cache hit
        generate_dense(params, prompt, 3, cfg)
        n_after_bf16 = _dense_runner.cache_info().currsize
        hits0 = _dense_runner.cache_info().hits
        use_decode_kernel(False)
        generate_dense(params, prompt, 3, cfg)
        assert _dense_runner.cache_info().currsize == n_after_bf16
        assert _dense_runner.cache_info().hits == hits0 + 1
        # toggling back reuses the original quantized entry too
        use_decode_kernel(True)
        use_decode_kernel(False)
        generate_dense(params, prompt, 3, cfg, quantize_kv=True)
        assert _dense_runner.cache_info().currsize == n_after_bf16
    finally:
        use_decode_kernel(None)  # restore the batched-AUTO default
