"""graftcheck v2 (GC006-GC009): interprocedural concurrency & lifetime
analysis — the tier-1 gate for the rules ISSUE 8 added.

Same three layers as test_graftcheck.py: (1) the fixture corpus pins
each new rule's exact findings (rule ids AND line numbers) plus the
good twin staying clean; (2) the semantic contracts that make each
rule trustworthy (re-entrant locks don't fabricate cycles, the
real-smoke marker sanctions exactly one function, the fixture corpus
is pruned from recursive scans); (3) the SELF-RUNS: the four new
rules are clean over the shipped package AND the tests/benchmarks
trees (the acceptance scan), and the GC009 mutation test proves the
protocol gate actually gates — perturbing one KIND_* value or one
ctypes argtypes entry in a copied tree flips the exit non-zero with
the exact rule id.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

from mpistragglers_jl_tpu.tools.graftcheck import run

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "mpistragglers_jl_tpu")
_FIX = os.path.join(_REPO, "tests", "graftcheck_fixtures")

NEW_RULES = ["GC006", "GC007", "GC008", "GC009"]


def _findings(target, **kw):
    return run([os.path.join(_FIX, target)], **kw)


def _keys(findings):
    return [(f.rule, f.line) for f in findings]


# --------------------------------------------------------------------------
# fixture corpus: exact rule ids + line numbers per checker
# --------------------------------------------------------------------------


@pytest.mark.parametrize(
    "bad,expected",
    [
        (
            "gc006_bad.py",
            [("GC006", 20), ("GC006", 21), ("GC006", 38),
             ("GC006", 42), ("GC006", 46), ("GC006", 47),
             ("GC006", 62)],  # 62: the 3-lock cycle (SCC, not
            # pairwise — a->b->c->a)
        ),
        (
            "gc007_bad.py",
            [("GC007", 16), ("GC007", 23), ("GC007", 39),
             ("GC007", 45), ("GC007", 51)],
        ),
        (
            "gc008_bad_pkg",
            [("GC008", 10), ("GC008", 13),  # chaos/: OS clock in an
             # episode probe — the round-20 chaos-plane purity
             ("GC008", 13), ("GC008", 23),
             ("GC008", 9), ("GC008", 12),  # fleet/: OS clock in a
             # decision function — the round-18 control-plane purity
             ("GC008", 10), ("GC008", 13),  # qos/: OS clock in a
             # tenant-budget refill — the round-19 QoS-plane purity
             ("GC008", 4), ("GC008", 9), ("GC008", 11), ("GC008", 12),
             ("GC008", 18)],  # 18: wall sleep through `import time
            # as _t` — alias-proof matching
        ),
        (
            "gc009_bad_pkg",
            [("GC009", 1), ("GC009", 1), ("GC009", 9), ("GC009", 10),
             ("GC009", 11), ("GC009", 18), ("GC009", 22),
             ("GC009", 23), ("GC009", 27),
             ("GC009", 31)],  # 31: argtypes-but-no-restype for an
            # int64_t-returning export (c_int truncation)
        ),
    ],
)
def test_bad_fixture_exact_findings(bad, expected):
    res = _findings(bad)
    assert _keys(res.fresh) == expected, [
        f.format() for f in res.fresh
    ]
    assert not res.baselined


@pytest.mark.parametrize(
    "good",
    ["gc006_good.py", "gc007_good.py", "gc008_good_pkg",
     "gc009_good_pkg"],
)
def test_good_fixture_clean(good):
    res = _findings(good)
    assert res.fresh == [], [f.format() for f in res.fresh]


# --------------------------------------------------------------------------
# semantic contracts per rule
# --------------------------------------------------------------------------


def test_gc006_reentrant_reacquire_is_not_a_cycle():
    """The good fixture's `forward` holds _a and _b and calls a helper
    that re-enters _a (an RLock): a re-entrant acquisition of an
    already-held lock can never block, so it must create neither a
    self-deadlock finding nor a fabricated _b -> _a ordering edge
    (the bug the first cut of the edge builder had)."""
    res = _findings("gc006_good.py", rules=["GC006"])
    assert res.fresh == [], [f.format() for f in res.fresh]
    # while the SAME shape over a non-reentrant Lock is the bad
    # fixture's line-21 self-deadlock finding
    bad = _findings("gc006_bad.py", rules=["GC006"])
    assert ("GC006", 21) in _keys(bad.fresh)


def test_gc007_transfer_shapes_discharge_the_obligation():
    """Both sanctioned pin transfers — constructor escape (the
    ArenaPayload pattern) and returned control marker (the
    _MARK_RESULT pattern) — satisfy the release obligation; the
    leak-shaped twin without either is the bad fixture's line-23
    finding."""
    good = _findings("gc007_good.py", rules=["GC007"])
    assert good.fresh == []
    bad = _findings("gc007_bad.py", rules=["GC007"])
    assert ("GC007", 23) in _keys(bad.fresh)


def test_gc008_real_smoke_marker_sanctions_one_function():
    """gc008_good_pkg/checks.py carries a sub-second wall-clock assert
    inside `real_thread_smoke`, sanctioned ONLY by the
    `# graftcheck: real-smoke` marker on the line above the def —
    strip the marker and the same tree produces exactly that
    finding."""
    import ast as _ast  # noqa: F401  (parity with test_graftcheck)

    from mpistragglers_jl_tpu.tools.graftcheck.checkers import (
        gc008_wall_clock as gc008,
    )
    from mpistragglers_jl_tpu.tools.graftcheck.core import (
        load_modules,
    )

    res = _findings("gc008_good_pkg", rules=["GC008"])
    assert res.fresh == []
    mods = load_modules([os.path.join(_FIX, "gc008_good_pkg")])
    checker = gc008.WallClock()
    got = []
    for m in mods:
        if m.path.endswith("checks.py"):
            m.source = m.source.replace(
                gc008.REAL_SMOKE_MARKER, "# x"
            )
            m._lines = None  # re-split the patched source
        got += list(checker.check_module(m))
    assert [(f.rule, f.symbol) for f in got] == [
        ("GC008", "real_thread_smoke")
    ], [f.format() for f in got]


def test_gc008_applies_to_tests_and_benchmarks_roots():
    """The satellite contract: the timing-margin lint actually guards
    where the flakes live. The shipped tests/ and benchmarks/ trees
    are clean under GC008 (the PR's deflake ports + the marked real
    smokes), and the fixture corpus is pruned from the recursive scan
    by its `.graftcheck-skip` marker — without the pruning this run
    would drown in deliberate fixture violations."""
    res = run(
        [os.path.join(_REPO, "tests"),
         os.path.join(_REPO, "benchmarks")],
        rules=["GC008"],
    )
    assert res.fresh == [], [f.format() for f in res.fresh]
    scanned = res.n_files
    # the fixture corpus was skipped: scanning it alone finds files
    only_fix = run([_FIX], rules=["GC008"])
    assert only_fix.n_files > 0
    full = run(
        [os.path.join(_REPO, "tests")], rules=["GC008"]
    )
    assert full.n_files < scanned + only_fix.n_files


def test_gc008_covers_the_fleet_package():
    """Round-18: the control plane joined the virtual-time plane — the
    shipped fleet/ package is clean under GC008's purity half
    (decision code reads only its injected clock; wall seconds enter
    via the caller's timer=), and the fixture's fleet twin pins the
    OS-clock-in-a-decision-function leak shape by line."""
    res = run([os.path.join(_PKG, "fleet")], rules=["GC008"])
    assert res.fresh == [], [f.format() for f in res.fresh]
    bad = _findings("gc008_bad_pkg", rules=["GC008"])
    fleet_hits = [
        (f.rule, f.line) for f in bad.fresh
        if os.sep + "fleet" + os.sep in f.path
    ]
    assert fleet_hits == [("GC008", 9), ("GC008", 12)], [
        f.format() for f in bad.fresh
    ]


def test_gc008_covers_the_chaos_package():
    """Round-20: the chaos plane joined the virtual-time plane — the
    shipped chaos/ package is clean under GC008's purity half (an
    episode's timing comes from the scenario's seed and the injected
    VirtualClock, never the OS clock: bit-identical replay is the
    plane's whole witness), and the fixture's chaos twin pins the
    OS-clock-in-an-episode-probe leak shape by line."""
    res = run([os.path.join(_PKG, "chaos")], rules=["GC008"])
    assert res.fresh == [], [f.format() for f in res.fresh]
    bad = _findings("gc008_bad_pkg", rules=["GC008"])
    chaos_hits = [
        (f.rule, f.line) for f in bad.fresh
        if os.sep + "chaos" + os.sep in f.path
    ]
    assert chaos_hits == [("GC008", 10), ("GC008", 13)], [
        f.format() for f in bad.fresh
    ]


def test_gc008_covers_the_qos_package():
    """Round-19: the QoS plane joined the virtual-time plane — the
    shipped qos/ package is clean under GC008's purity half (token
    buckets refill and deficit rotations advance only from the
    caller-injected ``now``), and the fixture's qos twin pins the
    OS-clock-in-a-budget-refill leak shape by line."""
    res = run([os.path.join(_PKG, "qos")], rules=["GC008"])
    assert res.fresh == [], [f.format() for f in res.fresh]
    bad = _findings("gc008_bad_pkg", rules=["GC008"])
    qos_hits = [
        (f.rule, f.line) for f in bad.fresh
        if os.sep + "qos" + os.sep in f.path
    ]
    assert qos_hits == [("GC008", 10), ("GC008", 13)], [
        f.format() for f in bad.fresh
    ]


def test_skip_marker_prunes_recursive_scans_only(tmp_path):
    """A directory holding `.graftcheck-skip` is pruned when reached
    recursively but still analyzable as an explicit root."""
    pkg = tmp_path / "tree"
    (pkg / "skipped").mkdir(parents=True)
    (pkg / "kept.py").write_text("X = 1\n")
    (pkg / "skipped" / ".graftcheck-skip").write_text("")
    (pkg / "skipped" / "mod.py").write_text("Y = 2\n")
    rec = run([str(pkg)])
    assert rec.n_files == 1
    direct = run([str(pkg / "skipped")])
    assert direct.n_files == 1


def test_gc006_clean_on_the_lock_heavy_modules():
    """The hand-audited modules the tentpole names: ProcessBackend's
    _cond/_ring_lock/_send_lock are only ever held one at a time, and
    the native Coordinator's _zlock is an RLock whose finalizer
    re-entry is sanctioned — GC006 agrees with the audit."""
    for rel in (
        os.path.join("backends", "process.py"),
        os.path.join("native", "transport.py"),
        os.path.join("sim", "clock.py"),
        "obs",
    ):
        res = run([os.path.join(_PKG, rel)], rules=["GC006"])
        assert res.fresh == [], (rel, [f.format() for f in res.fresh])


# --------------------------------------------------------------------------
# GC009: the mutation test — the gate actually gates
# --------------------------------------------------------------------------


def _mutated_tree(tmp_path, mutate):
    """Copy the real transport pair into a tmp tree and apply
    ``mutate(source) -> source`` to the .py half."""
    native = tmp_path / "native"
    native.mkdir()
    src_dir = os.path.join(_PKG, "native")
    for name in ("transport.py", "transport.cpp"):
        shutil.copy(os.path.join(src_dir, name), native / name)
    p = native / "transport.py"
    src = p.read_text()
    out = mutate(src)
    assert out != src, "mutation did not apply"
    p.write_text(out)
    return str(tmp_path)


def _cli(*args):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO
    return subprocess.run(
        [sys.executable, "-m",
         "mpistragglers_jl_tpu.tools.graftcheck", *args],
        capture_output=True, text=True, cwd=_REPO, env=env,
        timeout=120,
    )


def test_gc009_mutation_kind_value_flips_exit(tmp_path):
    """Perturb one KIND_* value in a copied transport.py: the scan
    exits non-zero and names GC009 at the perturbed line."""
    tree = _mutated_tree(
        tmp_path,
        lambda s: s.replace("KIND_CONTROL = 1", "KIND_CONTROL = 9", 1),
    )
    r = _cli(tree, "--rules", "GC009", "--baseline", "none",
             "--no-cache")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "GC009" in r.stdout
    assert "KIND_CONTROL" in r.stdout


def test_gc009_mutation_argtypes_entry_flips_exit(tmp_path):
    """Perturb one ctypes argtypes entry (a 64-bit parameter narrowed
    to c_int): exit non-zero, GC009 named, the drifted function and
    argument index in the message."""
    old = (
        "    lib.msgt_coord_isend.argtypes = [\n"
        "        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, "
        "ctypes.c_int64,\n"
    )
    new = old.replace(
        "ctypes.c_int64, ctypes.c_int64,",
        "ctypes.c_int64, ctypes.c_int,",
    )
    tree = _mutated_tree(tmp_path, lambda s: s.replace(old, new, 1))
    r = _cli(tree, "--rules", "GC009", "--baseline", "none",
             "--no-cache")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "GC009" in r.stdout
    assert "msgt_coord_isend" in r.stdout
    assert "argument 3" in r.stdout


def test_gc009_unmutated_pair_is_clean(tmp_path):
    """Control: the same copy WITHOUT a mutation scans clean — the
    mutation tests above fail because of the mutation, nothing else."""
    native = tmp_path / "native"
    native.mkdir()
    src_dir = os.path.join(_PKG, "native")
    for name in ("transport.py", "transport.cpp"):
        shutil.copy(os.path.join(src_dir, name), native / name)
    res = run([str(tmp_path)], rules=["GC009"])
    assert res.fresh == [], [f.format() for f in res.fresh]


# --------------------------------------------------------------------------
# self-runs: the acceptance scans
# --------------------------------------------------------------------------


def test_new_rules_clean_on_package_and_tests_tree():
    """ISSUE 8 acceptance: `--rules GC006,GC007,GC008,GC009` runs
    clean on the package + tests tree (the fixture corpus prunes
    itself via `.graftcheck-skip`)."""
    res = run(
        [_PKG, os.path.join(_REPO, "tests"),
         os.path.join(_REPO, "benchmarks")],
        rules=NEW_RULES,
    )
    assert res.fresh == [], "\n".join(f.format() for f in res.fresh)
    assert res.n_rules == 4


def test_cli_new_rules_listed_and_clean():
    rules = _cli("--list-rules")
    assert rules.returncode == 0
    for rule in NEW_RULES:
        assert rule in rules.stdout
    r = _cli(
        "mpistragglers_jl_tpu", "tests", "benchmarks",
        "--rules", ",".join(NEW_RULES), "--no-cache", "-q",
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_new_rules_ride_the_cache(tmp_path):
    """The per-file cache machinery serves the v2 rules too: a warm
    re-run reproduces the bad fixture's findings exactly from cache
    (same identity, not just the same keys)."""
    cache = str(tmp_path / "cache.json")
    first = _findings("gc006_bad.py", cache_path=cache,
                      rules=["GC006"])
    assert os.path.exists(cache)
    second = _findings("gc006_bad.py", cache_path=cache,
                       rules=["GC006"])
    assert [f.format() for f in second.fresh] == [
        f.format() for f in first.fresh
    ]
    assert len(first.fresh) == 7


def test_gc009_is_project_wide_and_never_cached(tmp_path):
    """GC009 reads a sibling .cpp the per-file sha cache cannot key,
    so it must run live every time: mutate the .cpp (NOT the .py)
    between two cached runs and the second run must see the drift."""
    native = tmp_path / "native"
    native.mkdir()
    src_dir = os.path.join(_PKG, "native")
    for name in ("transport.py", "transport.cpp"):
        shutil.copy(os.path.join(src_dir, name), native / name)
    cache = str(tmp_path / "cache.json")
    clean = run([str(tmp_path)], rules=["GC009"], cache_path=cache)
    assert clean.fresh == []
    cpp = native / "transport.cpp"
    cpp.write_text(
        cpp.read_text().replace(
            "constexpr int64_t KIND_CONTROL = 1;",
            "constexpr int64_t KIND_CONTROL = 9;", 1,
        )
    )
    drifted = run([str(tmp_path)], rules=["GC009"], cache_path=cache)
    assert any(
        "KIND_CONTROL" in f.message for f in drifted.fresh
    ), [f.format() for f in drifted.fresh]
