"""The two halves meet: gradient-coded pool training of pytree models.

models/coded_train.py lifts BASELINE config 5 (flat logreg weights)
to arbitrary pytrees via ravel_pytree — flagship transformer included.
The load-bearing claim is EXACTNESS: training under injected stragglers
with ``nwait = n - s`` follows the same parameter trajectory as
bulk-synchronous full-batch SGD, because the gradient-code decode
reconstructs the exact mean-of-chunks gradient from any n-s arrivals
(ops/gradcode.py; the arrival set is the pool's ``repochs`` freshness
mask, reference src/MPIAsyncPools.jl:109,:168).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpistragglers_jl_tpu.models.coded_train import (
    CodedGradTrainer,
    transformer_chunk_loss,
)
from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)
from mpistragglers_jl_tpu.pool import AsyncPool, waitall

CFG = TransformerConfig(
    vocab=37, d_model=32, n_heads=4, n_layers=2, d_ff=64
)
N, S = 6, 2
ROWS, L = 4, 12  # tokens per chunk: (ROWS, L+1)


def _chunk_fn(j):
    rng = np.random.default_rng((13, j))
    return jnp.asarray(
        rng.integers(0, CFG.vocab, (ROWS, L + 1)), jnp.int32
    )


def _slow_two(i, epoch):
    """Workers 0 and 3 are hard stragglers every epoch."""
    return 0.25 if i in (0, 3) else 0.0


def _make(delay_fn=None, tx=None, seed=0):
    return CodedGradTrainer(
        transformer_chunk_loss(CFG), init_params(CFG, seed=1), _chunk_fn,
        N, S, delay_fn=delay_fn, tx=tx, seed=seed,
    )


def _direct_full_batch_sgd(params, lr, epochs):
    """Oracle: bulk-synchronous SGD on the mean of per-chunk losses."""
    loss_fn = transformer_chunk_loss(CFG)

    def total_loss(p):
        return sum(loss_fn(p, _chunk_fn(j)) for j in range(N)) / N

    g = jax.jit(jax.grad(total_loss))
    for _ in range(epochs):
        params = jax.tree.map(lambda p, gg: p - lr * gg, params, g(params))
    return params


@pytest.mark.slow
def test_straggler_trajectory_matches_bulk_sync():
    """3 coded epochs with two injected hard stragglers == 3 direct
    full-batch SGD epochs, leaf for leaf. THE exactness claim."""
    tr = _make(delay_fn=_slow_two)
    pool = AsyncPool(N)
    params = init_params(CFG, seed=1)
    for e in range(3):
        params = tr.step(pool, params, lr=0.1)
    # the stragglers really did miss epochs: the pool saw only n-s fresh
    assert len(pool.fresh_indices()) < N
    waitall(pool, tr.backend)
    want = _direct_full_batch_sgd(init_params(CFG, seed=1), 0.1, 3)
    flat_got = jax.flatten_util.ravel_pytree(params)[0]
    flat_want = jax.flatten_util.ravel_pytree(want)[0]
    np.testing.assert_allclose(
        np.asarray(flat_got), np.asarray(flat_want), atol=2e-4, rtol=2e-3
    )


@pytest.mark.slow
def test_fit_loss_decreases_and_drains():
    tr = _make(delay_fn=_slow_two)
    params, hist = tr.fit(epochs=4, lr=0.1)
    assert len(hist) == 4
    assert hist[-1] < hist[0]
    # backend reusable after fit's waitall drain
    params, hist2 = tr.fit(epochs=2, lr=0.1, params=params)
    assert hist2[-1] < hist[0]


@pytest.mark.slow
def test_optax_path_runs_and_learns():
    optax = pytest.importorskip("optax")
    tr = _make(tx=optax.adamw(3e-3))
    params, hist = tr.fit(epochs=4)
    assert hist[-1] < hist[0]


def test_lr_tx_exclusive():
    tr = _make()
    pool = AsyncPool(N)
    params = init_params(CFG, seed=1)
    with pytest.raises(ValueError, match="exactly one"):
        tr.step(pool, params)  # neither lr nor tx
    optax = pytest.importorskip("optax")
    tr2 = _make(tx=optax.sgd(0.1))
    with pytest.raises(ValueError, match="exactly one"):
        tr2.step(pool, params, lr=0.1)  # both


@pytest.mark.slow
def test_bulk_sync_nwait_n_equals_coded():
    """nwait=n (no straggler tolerance used) decodes identically —
    the code is exact for ANY >= n-s arrival set."""
    tr = _make()
    pool_a, pool_b = AsyncPool(N), AsyncPool(N)
    p0 = init_params(CFG, seed=1)
    pa = tr.step(pool_a, p0, lr=0.1, nwait=N)
    waitall(pool_a, tr.backend)
    tr2 = _make(delay_fn=_slow_two)
    pb = tr2.step(pool_b, p0, lr=0.1)
    waitall(pool_b, tr2.backend)
    fa = jax.flatten_util.ravel_pytree(pa)[0]
    fb = jax.flatten_util.ravel_pytree(pb)[0]
    np.testing.assert_allclose(
        np.asarray(fa), np.asarray(fb), atol=1e-4, rtol=1e-3
    )
