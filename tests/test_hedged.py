"""Hedged requests (utils/hedge.py): first-response-wins over subset
pools of one shared backend — the serving-side dual of fastest-k.

Deterministic delay schedules make every claim checkable: the winner is
the fast replica, a stalled loser's rank stays out of new subsets until
its late result is harvested, and the measured request latency tracks
the fast replica's injected delay, not the straggler's.
"""

import time

import numpy as np
import pytest

from mpistragglers_jl_tpu.backends.local import LocalBackend
from mpistragglers_jl_tpu.utils import HedgedServer

N = 4
SLOW, FAST = 0.25, 0.01


def _work(i, payload, epoch):
    # echo enough to identify (replica, payload) pairs
    return np.asarray([i, int(payload[0]), epoch], dtype=np.int64)


def _mk_backend(slow_ranks=(0,)):
    def delay(i, epoch):
        return SLOW if i in slow_ranks else FAST

    return LocalBackend(_work, N, delay_fn=delay)


def test_winner_is_fast_replica_and_latency_tracks_it():
    backend = _mk_backend(slow_ranks=(0,))
    srv = HedgedServer(backend)
    t0 = time.perf_counter()
    result, rank, lat = srv.request(
        np.asarray([7], np.int64), replicas=[0, 1]
    )
    wall = time.perf_counter() - t0
    assert rank == 1  # the fast one
    assert result[0] == 1 and result[1] == 7
    assert lat < SLOW / 2  # paid the fast delay, not the stall
    assert wall < SLOW  # the request never waited for the straggler
    srv.drain()
    backend.shutdown()


def test_loser_rank_excluded_until_harvested():
    """Deflaked (the one pre-existing tier-1 failure, CHANGES.md): the
    old assertion demanded the request-2 WINNER be rank 2 or 3, but
    rank 1 — freed the moment it won request 1 — is a legitimate
    member of the new subset, and with identical FAST delays on every
    idle replica the winner among them is a thread-scheduling race
    (a wall-clock coin flip on a loaded CPU box, failing on unmodified
    HEAD). The claim this test actually pins is about SUBSET
    membership, which is deterministic: the busy loser's rank stays
    out of new subsets until its late result is harvested — so assert
    the dispatched subset (and hence the winner) excludes rank 0, not
    which of the equally-fast members won."""
    backend = _mk_backend(slow_ranks=(0,))
    srv = HedgedServer(backend)
    srv.request(np.asarray([1], np.int64), replicas=[0, 1])
    # rank 0 is still grinding its losing dispatch
    assert srv._busy_ranks() == {0}
    _, rank2, _ = srv.request(np.asarray([2], np.int64), hedge=2)
    assert rank2 != 0  # the busy rank cannot win a subset it isn't in
    assert srv.last_hedge_width == 2  # no narrowing: 3 ranks idle
    new_subsets = [k for k in srv._pools if k != (0, 1)]
    assert new_subsets and all(0 not in k for k in new_subsets)
    # after the stall elapses, harvest frees rank 0 for new subsets
    time.sleep(SLOW + 0.05)
    srv._harvest()
    assert 0 not in srv._busy_ranks()
    srv.drain()
    backend.shutdown()


def test_round_robin_spreads_load():
    backend = _mk_backend(slow_ranks=())
    srv = HedgedServer(backend)
    seen = set()
    for q in range(4):
        _, rank, _ = srv.request(np.asarray([q], np.int64), hedge=2)
        seen.add(rank)
        srv.drain()  # settle both replicas between requests
    assert len(seen) >= 2  # the cursor rotated subsets
    backend.shutdown()


def test_hedge_narrows_when_losers_hold_ranks():
    """Best-effort width: with rank 0 still grinding a losing dispatch,
    a hedge=4 request degrades to the 3 idle replicas instead of
    refusing (a thinner hedge is a latency risk; a refused request is
    an outage)."""
    backend = _mk_backend(slow_ranks=(0,))
    srv = HedgedServer(backend)
    srv.request(np.asarray([1], np.int64), replicas=[0, 1], timeout=5.0)
    assert srv._busy_ranks() == {0}
    _, rank, _ = srv.request(np.asarray([2], np.int64), hedge=4)
    assert rank in {1, 2, 3}
    assert any(len(k) == 3 for k in srv._pools)  # the narrowed subset
    srv.drain()
    backend.shutdown()


def test_hedge_one_is_plain_dispatch():
    backend = _mk_backend(slow_ranks=())
    srv = HedgedServer(backend)
    _, rank, _ = srv.request(np.asarray([3], np.int64), hedge=1)
    assert rank in range(N)
    srv.drain()
    backend.shutdown()


def test_validation():
    backend = _mk_backend()
    srv = HedgedServer(backend)
    with pytest.raises(ValueError, match="hedge"):
        srv.request(np.asarray([1], np.int64), hedge=0)
    backend.shutdown()


def test_dead_loser_does_not_poison_later_requests():
    """A replica that dies AFTER losing its hedge must not raise into
    an unrelated later request: its request was already served, so the
    failure is recorded, the rank benched, and serving continues."""

    def work(i, payload, epoch):
        if i == 0:
            time.sleep(FAST * 3)  # lose first, then die
            raise RuntimeError("replica 0 exploded after losing")
        return _work(i, payload, epoch)

    backend = LocalBackend(work, N)
    srv = HedgedServer(backend)
    _, rank1, _ = srv.request(
        np.asarray([1], np.int64), replicas=[0, 1], timeout=5.0
    )
    assert rank1 == 1
    time.sleep(FAST * 4)  # let the loser finish dying
    _, rank2, _ = srv.request(np.asarray([2], np.int64), hedge=2)
    assert rank2 != 0
    assert len(srv.failures) == 1
    assert srv.failures[0].worker == 0
    assert 0 in srv._dead
    # benched: later picks never include the dead rank
    for q in range(3, 6):
        _, rank, _ = srv.request(np.asarray([q], np.int64), hedge=2)
        assert rank != 0
    srv.drain()
    # repair hook returns it to rotation
    srv.reset_dead(0)
    assert 0 not in srv._dead
    backend.shutdown()


def test_all_dead_raises_immediately():
    """Every rank benched -> an immediate, accurate error (the harvest
    loop can never revive dead ranks, so waiting would hang)."""
    backend = _mk_backend(slow_ranks=())
    srv = HedgedServer(backend)
    srv._dead = {0, 1, 2, 3}
    with pytest.raises(RuntimeError, match="dead"):
        srv.request(np.asarray([1], np.int64), hedge=2)
    backend.shutdown()


def test_tail_latency_win_under_random_stalls():
    """The Tail-at-Scale claim, deterministically: replica r stalls on
    requests where (q + r) % 4 == 0, so single-assignment eats a stall
    every 4th request while hedge=2 (consecutive ranks never both
    stall) never does."""

    def delay(i, epoch):
        return SLOW if (epoch + i) % 4 == 0 else FAST

    backend = LocalBackend(_work, N, delay_fn=delay)
    srv = HedgedServer(backend)
    hedged = []
    for q in range(8):
        t0 = time.perf_counter()
        srv.request(np.asarray([q], np.int64), hedge=2)
        hedged.append(time.perf_counter() - t0)
        srv.drain()  # isolate per-request timing
    assert max(hedged) < SLOW, hedged  # no request paid a stall
    srv.drain()
    backend.shutdown()


def test_single_deadline_not_double_timeout():
    """One request budget covers pick + wait (ADVICE r4: the caller's
    timeout used to apply twice — idle-rank wait AND asyncmap — for a
    worst case near 2x). The regression-sensitive shape: every rank is
    busy losing for most of the budget, frees in time for the pick, and
    the dispatched request then stalls — the asyncmap leg must get only
    the REMAINING budget (~budget-SLOW), not a fresh full window."""
    backend = _mk_backend(slow_ranks=(0, 1, 2, 3))  # everyone stalls
    srv = HedgedServer(backend)
    # occupy every rank with a losing dispatch (give up immediately;
    # the workers grind on for SLOW seconds)
    for r in range(N):
        with pytest.raises(TimeoutError):
            srv.request(
                np.asarray([r], np.int64), replicas=[r], timeout=0.01
            )
    budget = SLOW + 0.12  # pick frees at ~SLOW; ~0.12 s remains
    t0 = time.perf_counter()
    with pytest.raises((RuntimeError, TimeoutError),
                       match="request budget|did not respond"):
        srv.request(np.asarray([9], np.int64), hedge=2, timeout=budget)
    wall = time.perf_counter() - t0
    # buggy double-application: asyncmap gets a fresh `budget` window
    # after the ~SLOW pick wait -> wall ~= SLOW + budget ~= 0.62 s.
    # fixed: wall ~= budget. Assert well below the buggy wall.
    assert wall < budget + 0.5 * SLOW, (
        f"request consumed {wall:.3f}s against a {budget:.2f}s budget "
        "— the deadline was applied more than once"
    )
    time.sleep(SLOW + 0.05)
    srv.drain()
    backend.shutdown()


def test_hedge_width_is_observable():
    """A narrowed hedge is surfaced (ADVICE r4): width lands in
    last_hedge_width and in the history tuple."""
    backend = _mk_backend(slow_ranks=(0,))
    srv = HedgedServer(backend)
    srv.request(np.asarray([1], np.int64), hedge=2, replicas=[0, 1])
    assert srv.last_hedge_width == 2
    # rank 0 still busy losing; ask for width 4 -> narrows to 3
    _, rank, _ = srv.request(np.asarray([2], np.int64), hedge=4)
    assert srv.last_hedge_width == 3
    assert srv.history[-1][2] == 3
    time.sleep(SLOW + 0.05)
    srv.drain()
    backend.shutdown()
