"""Hedged requests (utils/hedge.py): first-response-wins over subset
pools of one shared backend — the serving-side dual of fastest-k.

Deterministic delay schedules make every claim checkable: the winner is
the fast replica, a stalled loser's rank stays out of new subsets until
its late result is harvested, and the measured request latency tracks
the fast replica's injected delay, not the straggler's.

Re-rooted on virtual time (ISSUE 5): every test whose claim is about
LATENCY or ARRIVAL ORDER — the flake family that needed deflaking in
PRs 3/4 — now runs the same ``HedgedServer`` on a ``SimBackend``,
where "the winner paid FAST, not SLOW" is an exact virtual-clock
equality and the old ``sleep(SLOW + 0.05)`` settling margins are a
costless ``clock.run_until``. The thread-backend tests that remain
below exercise what sim cannot: real thread death, real deadline
budgets, and one full real-backend request smoke."""

import time

import numpy as np
import pytest

from mpistragglers_jl_tpu import SimBackend
from mpistragglers_jl_tpu.backends.local import LocalBackend
from mpistragglers_jl_tpu.utils import HedgedServer

N = 4
SLOW, FAST = 0.25, 0.01


def _work(i, payload, epoch):
    # echo enough to identify (replica, payload) pairs
    return np.asarray([i, int(payload[0]), epoch], dtype=np.int64)


def _mk_backend(slow_ranks=(0,)):
    def delay(i, epoch):
        return SLOW if i in slow_ranks else FAST

    return LocalBackend(_work, N, delay_fn=delay)


def _mk_sim(delay):
    return SimBackend(_work, N, delay_fn=delay)


def test_winner_is_fast_replica_and_latency_tracks_it():
    """Virtual time makes the Tail-at-Scale claim exact: the hedge
    pays the fast replica's delay to the nanosecond, and the straggler
    costs the request nothing (formerly `lat < SLOW / 2` against the
    wall clock — a margin bet; now an equality)."""
    backend = _mk_sim(lambda i, e: SLOW if i == 0 else FAST)
    srv = HedgedServer(backend)
    t0 = backend.clock.now()
    result, rank, _ = srv.request(
        np.asarray([7], np.int64), replicas=[0, 1]
    )
    assert rank == 1  # the fast one
    assert result[0] == 1 and result[1] == 7
    # the request advanced virtual time by exactly the winner's delay
    assert backend.clock.now() - t0 == pytest.approx(FAST, abs=1e-12)
    assert backend.last_latency[rank] == pytest.approx(FAST, abs=1e-12)
    backend.quiesce()  # let the loser land before the drain barrier
    srv.drain()


def test_loser_rank_excluded_until_harvested():
    """Deflaked in PR 3, exact since ISSUE 5: the busy loser's rank
    stays out of new subsets until its late result is harvested. The
    PR 3 deflake had already reduced this to the deterministic
    subset-membership claim (the old winner-identity assertion was a
    thread race on equally-fast replicas, failing on unmodified HEAD);
    on virtual time even the settling sleep (`SLOW + 0.05`) becomes an
    exact `run_until(SLOW)` — the harvest boundary is a clock value,
    not a margin."""
    backend = _mk_sim(lambda i, e: SLOW if i == 0 else FAST)
    srv = HedgedServer(backend)
    srv.request(np.asarray([1], np.int64), replicas=[0, 1])
    # rank 0 is still grinding its losing dispatch
    assert srv._busy_ranks() == {0}
    _, rank2, _ = srv.request(np.asarray([2], np.int64), hedge=2)
    assert rank2 != 0  # the busy rank cannot win a subset it isn't in
    assert srv.last_hedge_width == 2  # no narrowing: 3 ranks idle
    new_subsets = [k for k in srv._pools if k != (0, 1)]
    assert new_subsets and all(0 not in k for k in new_subsets)
    # one tick before the stall elapses the loser is still busy; AT
    # the stall boundary the harvest frees it — exact, not a margin
    backend.clock.run_until(SLOW - 1e-9)
    srv._harvest()
    assert srv._busy_ranks() == {0}
    backend.clock.run_until(SLOW)
    srv._harvest()
    assert 0 not in srv._busy_ranks()
    srv.drain()


def test_round_robin_spreads_load():
    backend = _mk_backend(slow_ranks=())
    srv = HedgedServer(backend)
    seen = set()
    for q in range(4):
        _, rank, _ = srv.request(np.asarray([q], np.int64), hedge=2)
        seen.add(rank)
        srv.drain()  # settle both replicas between requests
    assert len(seen) >= 2  # the cursor rotated subsets
    backend.shutdown()


def test_hedge_narrows_when_losers_hold_ranks():
    """Best-effort width: with rank 0 still grinding a losing dispatch,
    a hedge=4 request degrades to the 3 idle replicas instead of
    refusing (a thinner hedge is a latency risk; a refused request is
    an outage)."""
    backend = _mk_backend(slow_ranks=(0,))
    srv = HedgedServer(backend)
    srv.request(np.asarray([1], np.int64), replicas=[0, 1], timeout=5.0)
    assert srv._busy_ranks() == {0}
    _, rank, _ = srv.request(np.asarray([2], np.int64), hedge=4)
    assert rank in {1, 2, 3}
    assert any(len(k) == 3 for k in srv._pools)  # the narrowed subset
    srv.drain()
    backend.shutdown()


def test_hedge_one_is_plain_dispatch():
    backend = _mk_backend(slow_ranks=())
    srv = HedgedServer(backend)
    _, rank, _ = srv.request(np.asarray([3], np.int64), hedge=1)
    assert rank in range(N)
    srv.drain()
    backend.shutdown()


def test_validation():
    backend = _mk_backend()
    srv = HedgedServer(backend)
    with pytest.raises(ValueError, match="hedge"):
        srv.request(np.asarray([1], np.int64), hedge=0)
    backend.shutdown()


def test_dead_loser_does_not_poison_later_requests():
    """A replica that dies AFTER losing its hedge must not raise into
    an unrelated later request: its request was already served, so the
    failure is recorded, the rank benched, and serving continues."""

    def work(i, payload, epoch):
        if i == 0:
            time.sleep(FAST * 3)  # lose first, then die
            raise RuntimeError("replica 0 exploded after losing")
        return _work(i, payload, epoch)

    backend = LocalBackend(work, N)
    srv = HedgedServer(backend)
    _, rank1, _ = srv.request(
        np.asarray([1], np.int64), replicas=[0, 1], timeout=5.0
    )
    assert rank1 == 1
    time.sleep(FAST * 4)  # let the loser finish dying
    _, rank2, _ = srv.request(np.asarray([2], np.int64), hedge=2)
    assert rank2 != 0
    assert len(srv.failures) == 1
    assert srv.failures[0].worker == 0
    assert 0 in srv._dead
    # benched: later picks never include the dead rank
    for q in range(3, 6):
        _, rank, _ = srv.request(np.asarray([q], np.int64), hedge=2)
        assert rank != 0
    srv.drain()
    # repair hook returns it to rotation
    srv.reset_dead(0)
    assert 0 not in srv._dead
    backend.shutdown()


def test_all_dead_raises_immediately():
    """Every rank benched -> an immediate, accurate error (the harvest
    loop can never revive dead ranks, so waiting would hang)."""
    backend = _mk_backend(slow_ranks=())
    srv = HedgedServer(backend)
    srv._dead = {0, 1, 2, 3}
    with pytest.raises(RuntimeError, match="dead"):
        srv.request(np.asarray([1], np.int64), hedge=2)
    backend.shutdown()


def test_tail_latency_win_under_random_stalls():
    """The Tail-at-Scale claim, exactly: replica r stalls on requests
    where (q + r) % 4 == 0, so single-assignment eats a stall every
    4th request while hedge=2 (consecutive ranks never both stall)
    never does. On virtual time the claim sharpens from `max(hedged)
    < SLOW` (a wall-clock margin that lost races on loaded boxes —
    the PR 3/4 flake family) to `every request == FAST`."""

    def delay(i, epoch):
        return SLOW if (epoch + i) % 4 == 0 else FAST

    backend = _mk_sim(delay)
    srv = HedgedServer(backend)
    hedged = []
    for q in range(8):
        t0 = backend.clock.now()
        srv.request(np.asarray([q], np.int64), hedge=2)
        hedged.append(backend.clock.now() - t0)
        srv.drain()  # isolate per-request timing
    # no request paid ANY stall (approx: virtual timestamps are exact
    # event times, but float addition along the clock is not exact)
    assert hedged == pytest.approx([FAST] * 8, abs=1e-12), hedged
    srv.drain()


def test_single_deadline_not_double_timeout():
    """One request budget covers pick + wait (ADVICE r4: the caller's
    timeout used to apply twice — idle-rank wait AND asyncmap — for a
    worst case near 2x). The regression-sensitive shape: every rank is
    busy losing for most of the budget, frees in time for the pick, and
    the dispatched request then stalls — the asyncmap leg must get only
    the REMAINING budget (~budget-SLOW), not a fresh full window."""
    backend = _mk_backend(slow_ranks=(0, 1, 2, 3))  # everyone stalls
    srv = HedgedServer(backend)
    # occupy every rank with a losing dispatch (give up immediately;
    # the workers grind on for SLOW seconds)
    for r in range(N):
        with pytest.raises(TimeoutError):
            srv.request(
                np.asarray([r], np.int64), replicas=[r], timeout=0.01
            )
    budget = SLOW + 0.12  # pick frees at ~SLOW; ~0.12 s remains
    t0 = time.perf_counter()
    with pytest.raises((RuntimeError, TimeoutError),
                       match="request budget|did not respond"):
        srv.request(np.asarray([9], np.int64), hedge=2, timeout=budget)
    wall = time.perf_counter() - t0
    # buggy double-application: asyncmap gets a fresh `budget` window
    # after the ~SLOW pick wait -> wall ~= SLOW + budget ~= 0.62 s.
    # fixed: wall ~= budget. Assert well below the buggy wall.
    assert wall < budget + 0.5 * SLOW, (
        f"request consumed {wall:.3f}s against a {budget:.2f}s budget "
        "— the deadline was applied more than once"
    )
    time.sleep(SLOW + 0.05)
    srv.drain()
    backend.shutdown()


def test_hedge_width_is_observable():
    """A narrowed hedge is surfaced (ADVICE r4): width lands in
    last_hedge_width and in the history tuple. On virtual time the
    defensive settling sleep (`SLOW + 0.05`) the thread version needed
    before its drain is gone — `quiesce()` IS the settled state."""
    backend = _mk_sim(lambda i, e: SLOW if i == 0 else FAST)
    srv = HedgedServer(backend)
    srv.request(np.asarray([1], np.int64), hedge=2, replicas=[0, 1])
    assert srv.last_hedge_width == 2
    # rank 0 still busy losing; ask for width 4 -> narrows to 3
    _, rank, _ = srv.request(np.asarray([2], np.int64), hedge=4)
    assert srv.last_hedge_width == 3
    assert srv.history[-1][2] == 3
    backend.quiesce()
    srv.drain()
