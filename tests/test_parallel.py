"""Sharded collectives + mesh coded GEMM on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mpistragglers_jl_tpu.ops import MDSCode
from mpistragglers_jl_tpu.parallel import (
    MeshCodedGemm,
    distributed_mds_decode,
    make_mesh,
    masked_psum_scatter_combine,
    ring_allgather,
)


def test_make_mesh():
    mesh = make_mesh(8)
    assert mesh.shape == {"w": 8}
    mesh2 = make_mesh((2, 4), ("dp", "tp"))
    assert mesh2.shape == {"dp": 2, "tp": 4}
    with pytest.raises(ValueError):
        make_mesh(16)
    with pytest.raises(ValueError):
        make_mesh((2, 4), ("dp",))


def test_masked_combine_weighted_sum():
    mesh = make_mesh(4)
    combine = masked_psum_scatter_combine(mesh)
    rng = np.random.default_rng(0)
    shards = rng.standard_normal((4, 3, 2)).astype(np.float32)
    weights = rng.standard_normal((4, 4)).astype(np.float32)
    sh = jax.device_put(jnp.asarray(shards), NamedSharding(mesh, P("w")))
    out = np.asarray(combine(sh, jnp.asarray(weights)))
    ref = np.einsum("jw,wrc->jrc", weights, shards)
    assert out.shape == (4, 3, 2)
    assert np.allclose(out, ref, atol=1e-5)


def test_distributed_mds_decode_with_stragglers():
    mesh = make_mesh(8)
    n, k = 8, 6
    code = MDSCode(n, k, dtype=np.float32)
    rng = np.random.default_rng(1)
    blocks = rng.standard_normal((k, 4, 3)).astype(np.float32)
    coded = np.asarray(code.encode(blocks))
    decode = distributed_mds_decode(mesh, code)
    # workers 2 and 5 are stale: their shard data is garbage
    repochs = np.full(n, 7)
    repochs[[2, 5]] = 3
    dirty = coded.copy()
    dirty[[2, 5]] = 999.0  # decode must not look at stale data
    sh = jax.device_put(jnp.asarray(dirty), NamedSharding(mesh, P("w")))
    out = np.asarray(decode(sh, repochs, epoch=7))
    assert np.allclose(out[:k], blocks, atol=1e-3)
    assert np.allclose(out[k:], 0.0, atol=1e-6)


def test_distributed_decode_insufficient_fresh():
    mesh = make_mesh(8)
    code = MDSCode(8, 6, dtype=np.float32)
    decode = distributed_mds_decode(mesh, code)
    repochs = np.zeros(8)
    sh = jax.device_put(
        jnp.zeros((8, 2, 2)), NamedSharding(mesh, P("w")))
    with pytest.raises(ValueError):
        decode(sh, repochs, epoch=1)


def test_ring_allgather():
    mesh = make_mesh(8)
    gather = ring_allgather(mesh)
    rng = np.random.default_rng(2)
    blocks = rng.standard_normal((8, 2, 3)).astype(np.float32)
    sh = jax.device_put(jnp.asarray(blocks), NamedSharding(mesh, P("w")))
    out = np.asarray(gather(sh))  # (8, 16, 3): per-device full copies
    full = blocks.reshape(16, 3)
    for dev in range(8):
        assert np.allclose(out[dev], full, atol=0), f"device {dev}"


class TestMeshCodedGemm:
    def test_full_epoch_exact(self):
        rng = np.random.default_rng(3)
        mesh = make_mesh(8)
        n, k = 8, 6
        A = rng.standard_normal((96, 32)).astype(np.float32)
        B = rng.standard_normal((32, 16)).astype(np.float32)
        mg = MeshCodedGemm(A, mesh, k)
        decoded = mg.epoch(B, epoch=1)
        C = mg.full(decoded)
        assert np.allclose(C, A @ B, atol=1e-3)

    def test_epoch_with_stale_mask(self):
        rng = np.random.default_rng(4)
        mesh = make_mesh(8)
        n, k = 8, 6
        A = rng.standard_normal((48, 16)).astype(np.float32)
        B = rng.standard_normal((16, 8)).astype(np.float32)
        mg = MeshCodedGemm(A, mesh, k)
        repochs = np.full(n, 5)
        repochs[[0, 7]] = 1  # two stragglers stale
        decoded = mg.epoch(B, repochs=repochs, epoch=5)
        assert np.allclose(mg.full(decoded), A @ B, atol=1e-3)

    def test_output_stays_sharded(self):
        rng = np.random.default_rng(5)
        mesh = make_mesh(4)
        A = rng.standard_normal((24, 8)).astype(np.float32)
        B = rng.standard_normal((8, 4)).astype(np.float32)
        mg = MeshCodedGemm(A, mesh, 3)
        decoded = mg.epoch(B, epoch=1)
        # decoded is sharded over the mesh, not gathered
        assert len(decoded.sharding.device_set) == 4


class TestMeshMatDotGemm:
    """MatDot on the mesh: decode = one weighted psum over the axis
    (parallel/mesh_gemm.py MeshMatDotGemm)."""

    def _setup(self, p=2, n=8):
        from mpistragglers_jl_tpu.parallel import MeshMatDotGemm, make_mesh

        rng = np.random.default_rng(0)
        m, kd, cols = 16, 8 * p, 12
        A = rng.standard_normal((m, kd)).astype(np.float32)
        B = rng.standard_normal((kd, cols)).astype(np.float32)
        mesh = make_mesh(n)
        return MeshMatDotGemm(A, mesh, p=p), A, B

    def test_full_arrival_exact(self):
        mg, A, B = self._setup()
        C = np.asarray(mg.epoch(B, epoch=1))
        scale = float(np.max(np.abs(A @ B)))
        assert float(np.max(np.abs(C - A @ B))) / scale < 1e-4

    def test_straggler_masked_weighted_psum(self):
        mg, A, B = self._setup()
        # epochs stamped: devices 2 and 6 stale -> weight 0 in the psum
        repochs = np.full(8, 5)
        repochs[[2, 6]] = 4
        C = np.asarray(mg.epoch(B, repochs, epoch=5))
        scale = float(np.max(np.abs(A @ B)))
        assert float(np.max(np.abs(C - A @ B))) / scale < 1e-4
        # weights: zeros exactly on stale devices, 2p-1 nonzero
        w = mg.decode_weights(repochs, 5)
        assert w[2] == 0 and w[6] == 0
        assert np.count_nonzero(w) == mg.k

    def test_below_threshold_refuses(self):
        mg, A, B = self._setup(p=4, n=8)  # k = 7
        repochs = np.full(8, 1)
        repochs[:2] = 0  # only 6 fresh < 7
        with pytest.raises(ValueError, match="need 2p-1=7"):
            mg.epoch(B, repochs, epoch=1)
