"""KV-cache inference path (VERDICT r3 missing #2).

Correctness contract: the incremental forward is the SAME function as
the training forward, evaluated causally — so teacher-forced decode
logits must match ``forward_dense`` position by position, prefill must
match it on the prompt, and the sharded (dp x tp) programs must match
the dense oracle; greedy generation must agree between the dense and
sharded programs, GQA/MQA and replicated-groups cache layouts included.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mpistragglers_jl_tpu.models.decode import (
    cache_specs,
    decode_step_dense,
    generate_dense,
    init_cache,
    make_decode_step,
    make_extend,
    make_generate,
    make_prefill,
    prefill_dense,
    shard_cache,
)
from mpistragglers_jl_tpu.models.transformer import (
    TransformerConfig,
    forward_dense,
    init_params,
    shard_params,
)
from mpistragglers_jl_tpu.parallel import make_mesh

CFG = TransformerConfig(
    vocab=61, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2, d_ff=128
)


def _tokens(cfg, B=2, L=12, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab, (B, L)), jnp.int32)


@pytest.mark.slow
@pytest.mark.parametrize("hkv", [None, 2, 1])
def test_teacher_forced_decode_matches_dense_forward(hkv):
    """Prefill the first half, decode the second half teacher-forced;
    every step's logits must equal the training forward's at that
    position."""
    cfg = dataclasses.replace(CFG, n_kv_heads=hkv)
    params = init_params(cfg, seed=1)
    toks = _tokens(cfg, B=2, L=12)
    want = forward_dense(params, toks, cfg)  # (B, L, V)

    Tp = 6
    cache = init_cache(cfg, 2, 12)
    logits, cache = prefill_dense(params, toks[:, :Tp], cache, cfg)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(want[:, :Tp]), atol=1e-4, rtol=1e-4
    )
    # kv cache holds kv_heads heads — the GQA memory win is structural
    assert cache[0]["k"].shape == (2, 12, cfg.kv_heads, cfg.head_dim)
    for t in range(Tp, 12):
        lg, cache = decode_step_dense(
            params, toks[:, t], cache, jnp.int32(t), cfg
        )
        np.testing.assert_allclose(
            np.asarray(lg), np.asarray(want[:, t]), atol=1e-4, rtol=1e-4,
            err_msg=f"position {t}",
        )


def test_prefill_flash_matches_reference_prefill():
    cfg = dataclasses.replace(CFG, attn="ulysses", attn_impl="flash")
    params = init_params(cfg, seed=2)
    toks = _tokens(cfg, B=2, L=8)
    c0 = init_cache(cfg, 2, 8)
    lg_flash, c_flash = prefill_dense(params, toks, c0, cfg)
    lg_ref, c_ref = prefill_dense(
        params, toks, c0, dataclasses.replace(cfg, attn_impl="reference")
    )
    np.testing.assert_allclose(
        np.asarray(lg_flash), np.asarray(lg_ref), atol=1e-4, rtol=1e-4
    )
    for a, b in zip(jax.tree.leaves(c_flash), jax.tree.leaves(c_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.parametrize(
    "shape,hkv",
    [
        ((2, 4), 4),  # kv heads shard over tp
        ((2, 4), 2),  # kv_heads < tp: replicated-groups cache
        ((1, 8), 1),  # MQA at tp=8
    ],
)
@pytest.mark.slow
def test_sharded_prefill_and_decode_match_dense(shape, hkv):
    cfg = dataclasses.replace(CFG, n_kv_heads=hkv)
    mesh = make_mesh(shape, ("dp", "tp"))
    params = init_params(cfg, seed=3)
    toks = _tokens(cfg, B=4, L=12, seed=3)
    want = forward_dense(params, toks, cfg)

    sp = shard_params(params, cfg, mesh)
    cache = shard_cache(init_cache(cfg, 4, 12, mesh), cfg, mesh)
    prefill = make_prefill(cfg, mesh)
    step = make_decode_step(cfg, mesh)
    tok_sh = NamedSharding(mesh, P("dp", None))
    Tp = 6
    lg, cache = prefill(sp, jax.device_put(toks[:, :Tp], tok_sh), cache)
    np.testing.assert_allclose(
        np.asarray(lg), np.asarray(want[:, Tp - 1]), atol=1e-4, rtol=1e-4
    )
    for t in range(Tp, 12):
        lg, cache = step(
            sp,
            jax.device_put(toks[:, t], NamedSharding(mesh, P("dp"))),
            cache, jnp.int32(t),
        )
        np.testing.assert_allclose(
            np.asarray(lg), np.asarray(want[:, t]), atol=1e-4, rtol=1e-4,
            err_msg=f"position {t}",
        )


@pytest.mark.parametrize("hkv", [2, 1])
def test_sharded_generate_matches_dense_generate(hkv):
    cfg = dataclasses.replace(CFG, n_kv_heads=hkv)
    mesh = make_mesh((2, 4), ("dp", "tp"))
    params = init_params(cfg, seed=4)
    prompt = _tokens(cfg, B=2, L=8, seed=5)
    want = generate_dense(params, prompt, 6, cfg)
    assert want.shape == (2, 6)

    gen = make_generate(cfg, mesh, n_new=6)
    got = gen(
        shard_params(params, cfg, mesh),
        jax.device_put(prompt, NamedSharding(mesh, P("dp", None))),
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_generate_dense_is_greedy_self_consistent():
    """Feeding generated tokens back through the training forward
    reproduces the same greedy choices (the cache is not drifting)."""
    cfg = CFG
    params = init_params(cfg, seed=6)
    prompt = _tokens(cfg, B=1, L=5, seed=7)
    out = generate_dense(params, prompt, 5, cfg)
    seq = jnp.concatenate([prompt, out], axis=1)
    logits = forward_dense(params, seq, cfg)
    # position t's logits predict token t+1 greedily, for the generated tail
    pred = jnp.argmax(logits[:, prompt.shape[1] - 1:-1], axis=-1)
    np.testing.assert_array_equal(np.asarray(pred), np.asarray(out))


@pytest.mark.slow
def test_moe_decode_dense_oracle():
    cfg = dataclasses.replace(
        CFG, n_experts=2, d_model=32, n_heads=4, n_kv_heads=2, d_ff=64
    )
    params = init_params(cfg, seed=8)
    toks = _tokens(cfg, B=2, L=10, seed=8)
    want = forward_dense(params, toks, cfg)
    cache = init_cache(cfg, 2, 10)
    lg, cache = prefill_dense(params, toks[:, :5], cache, cfg)
    np.testing.assert_allclose(
        np.asarray(lg), np.asarray(want[:, :5]), atol=1e-4, rtol=1e-4
    )
    for t in range(5, 10):
        lg, cache = decode_step_dense(
            params, toks[:, t], cache, jnp.int32(t), cfg
        )
        np.testing.assert_allclose(
            np.asarray(lg), np.asarray(want[:, t]), atol=1e-4, rtol=1e-4
        )


def test_moe_decode_mesh_validation():
    cfg = dataclasses.replace(CFG, n_experts=2)
    mesh = make_mesh((2, 4), ("dp", "tp"))  # no ep axis
    with pytest.raises(ValueError, match="missing axes \\['ep'\\]"):
        make_prefill(cfg, mesh)


@pytest.mark.parametrize("shape,axes", [
    ((2, 2, 2), ("dp", "ep", "tp")),
    ((1, 2, 4), ("dp", "ep", "tp")),
])
@pytest.mark.slow
def test_moe_sharded_decode_matches_dense(shape, axes):
    """Expert-parallel decode (round 4): routing runs sharded with the
    all_to_all over ep inside the incremental forward, exactly like the
    training path — teacher-forced logits must match the dense oracle
    (capacity generous enough that no drops occur, the same contract
    test_moe.py pins for training)."""
    cfg = dataclasses.replace(
        CFG, n_experts=2, d_model=64, n_heads=8, n_kv_heads=2, d_ff=128,
        capacity_factor=2.0,
    )
    mesh = make_mesh(shape, axes)
    params = init_params(cfg, seed=9)
    toks = _tokens(cfg, B=4, L=12, seed=9)
    want = forward_dense(params, toks, cfg)

    sp = shard_params(params, cfg, mesh)
    cache = shard_cache(init_cache(cfg, 4, 12, mesh), cfg, mesh)
    prefill = make_prefill(cfg, mesh)
    step = make_decode_step(cfg, mesh)
    from mpistragglers_jl_tpu.models.decode import decode_batch_axes

    bax = decode_batch_axes(cfg)
    Tp = 6
    lg, cache = prefill(
        sp, jax.device_put(toks[:, :Tp], NamedSharding(mesh, P(bax, None))),
        cache,
    )
    np.testing.assert_allclose(
        np.asarray(lg), np.asarray(want[:, Tp - 1]), atol=1e-4, rtol=1e-4
    )
    for t in range(Tp, 12):
        lg, cache = step(
            sp, jax.device_put(toks[:, t], NamedSharding(mesh, P(bax))),
            cache, jnp.int32(t),
        )
        np.testing.assert_allclose(
            np.asarray(lg), np.asarray(want[:, t]), atol=1e-4, rtol=1e-4,
            err_msg=f"position {t}",
        )


def test_moe_sharded_generate_matches_dense():
    cfg = dataclasses.replace(
        CFG, n_experts=2, d_model=64, n_heads=8, n_kv_heads=2, d_ff=128,
        capacity_factor=2.0,
    )
    mesh = make_mesh((2, 2, 2), ("dp", "ep", "tp"))
    params = init_params(cfg, seed=10)
    prompt = _tokens(cfg, B=4, L=8, seed=11)
    want = generate_dense(params, prompt, 5, cfg)
    gen = make_generate(cfg, mesh, n_new=5)
    from mpistragglers_jl_tpu.models.decode import decode_batch_axes

    got = gen(
        shard_params(params, cfg, mesh),
        jax.device_put(
            prompt, NamedSharding(mesh, P(decode_batch_axes(cfg), None))
        ),
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cache_overflow_guards():
    """dynamic_update_slice clamps silently; the API must error instead
    of corrupting the last cache slot (review finding)."""
    params = init_params(CFG, seed=0)
    prompt = _tokens(CFG, B=1, L=8)
    with pytest.raises(ValueError, match="clamp into the last cache slot"):
        generate_dense(params, prompt, 8, CFG, max_len=10)
    cache = init_cache(CFG, 1, 4)
    with pytest.raises(ValueError, match="does not fit the cache"):
        prefill_dense(params, prompt, cache, CFG)
    mesh = make_mesh((1, 4), ("dp", "tp"))
    with pytest.raises(ValueError, match="clamp into the last cache slot"):
        make_generate(CFG, mesh, n_new=8, max_len=10)(
            shard_params(params, CFG, mesh),
            jax.device_put(prompt, NamedSharding(mesh, P("dp", None))),
        )


def test_generate_dense_compile_cached():
    """Same (cfg, shapes) -> the jitted runner is reused, not retraced
    (review finding: a per-call @jax.jit forced a recompile every
    generation)."""
    from mpistragglers_jl_tpu.models.decode import _dense_runner

    params = init_params(CFG, seed=0)
    prompt = _tokens(CFG, B=1, L=5)
    generate_dense(params, prompt, 3, CFG)
    hits0 = _dense_runner.cache_info().hits
    generate_dense(params, prompt, 3, CFG)
    assert _dense_runner.cache_info().hits == hits0 + 1


def test_generate_rejects_n_new_zero():
    """n_new=0 used to return one token (the n_new-1 scan rewrite's
    unconditional concat); it must be rejected up front."""
    params = init_params(CFG, seed=0)
    prompt = _tokens(CFG, B=1, L=4)
    with pytest.raises(ValueError, match="n_new must be >= 1"):
        generate_dense(params, prompt, 0, CFG)
    mesh = make_mesh((1, 4), ("dp", "tp"))
    with pytest.raises(ValueError, match="n_new must be >= 1"):
        make_generate(CFG, mesh, n_new=0)


class TestSampledDecoding:
    """temperature/top-k sampling shares the cached-decode machinery:
    temperature 0 IS greedy; dense and sharded streams agree for the
    same key; top-k truncation only emits top-k tokens."""

    def test_temperature_zero_is_greedy(self):
        params = init_params(CFG, seed=0)
        prompt = _tokens(CFG, B=2, L=6)
        a = generate_dense(params, prompt, 5, CFG)
        b = generate_dense(params, prompt, 5, CFG, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sampled_dense_matches_sharded_for_same_key(self):
        cfg = dataclasses.replace(CFG, n_kv_heads=2)
        mesh = make_mesh((2, 4), ("dp", "tp"))
        params = init_params(cfg, seed=1)
        prompt = _tokens(cfg, B=2, L=6, seed=2)
        key = jax.random.key(7)
        want = generate_dense(
            params, prompt, 6, cfg, temperature=0.8, top_k=8, key=key
        )
        gen = make_generate(cfg, mesh, n_new=6, temperature=0.8, top_k=8)
        got = gen(
            shard_params(params, cfg, mesh),
            jax.device_put(prompt, NamedSharding(mesh, P("dp", None))),
            key,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # a different key gives a different stream (it is really sampling)
        other = generate_dense(
            params, prompt, 6, cfg, temperature=0.8, top_k=8,
            key=jax.random.key(8),
        )
        assert not np.array_equal(np.asarray(want), np.asarray(other))

    def test_top_k_one_is_greedy(self):
        params = init_params(CFG, seed=3)
        prompt = _tokens(CFG, B=1, L=5, seed=4)
        greedy = generate_dense(params, prompt, 4, CFG)
        k1 = generate_dense(
            params, prompt, 4, CFG, temperature=1.5, top_k=1,
            key=jax.random.key(0),
        )
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))

    def test_sampling_validation(self):
        params = init_params(CFG, seed=0)
        prompt = _tokens(CFG, B=1, L=4)
        with pytest.raises(ValueError, match="needs a jax.random key"):
            generate_dense(params, prompt, 2, CFG, temperature=1.0)
        with pytest.raises(ValueError, match="only meaningful"):
            generate_dense(
                params, prompt, 2, CFG, key=jax.random.key(0)
            )
        with pytest.raises(ValueError, match="top_k must be"):
            generate_dense(
                params, prompt, 2, CFG, temperature=1.0, top_k=0,
                key=jax.random.key(0),
            )


@pytest.mark.slow
def test_moe_sharded_sampled_generate_matches_dense():
    """The ep-aware global-row sampling offset: a fixed key must give
    the SAME sampled stream dense and on a (dp, ep, tp) mesh (pins the
    mixed-radix row0 derivation for the MoE batch layout)."""
    cfg = dataclasses.replace(
        CFG, n_experts=2, d_model=64, n_heads=8, n_kv_heads=2, d_ff=128,
        capacity_factor=2.0,
    )
    mesh = make_mesh((2, 2, 2), ("dp", "ep", "tp"))
    params = init_params(cfg, seed=12)
    prompt = _tokens(cfg, B=4, L=8, seed=13)
    key = jax.random.key(21)
    want = generate_dense(
        params, prompt, 5, cfg, temperature=0.7, top_k=8, key=key
    )
    from mpistragglers_jl_tpu.models.decode import decode_batch_axes

    gen = make_generate(cfg, mesh, n_new=5, temperature=0.7, top_k=8)
    got = gen(
        shard_params(params, cfg, mesh),
        jax.device_put(
            prompt, NamedSharding(mesh, P(decode_batch_axes(cfg), None))
        ),
        key,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("hkv,chunk", [(2, 5), (1, 3)])
def test_chunked_prefill_matches_one_shot(hkv, chunk):
    """Streaming prefill (make_extend): feeding the prompt in chunks at
    increasing offsets must reproduce the one-shot prefill's cache and
    logits — and therefore the dense oracle (round-4 serving surface)."""
    cfg = dataclasses.replace(CFG, n_kv_heads=hkv)
    mesh = make_mesh((2, 4), ("dp", "tp"))
    params = init_params(cfg, seed=14)
    toks = _tokens(cfg, B=4, L=12, seed=15)
    want = forward_dense(params, toks, cfg)

    sp = shard_params(params, cfg, mesh)
    extend = make_extend(cfg, mesh)
    cache = shard_cache(init_cache(cfg, 4, 12, mesh), cfg, mesh)
    tok_sh = NamedSharding(mesh, P("dp", None))
    for i in range(0, 12, chunk):
        end = min(i + chunk, 12)
        lg, cache = extend(
            sp, jax.device_put(toks[:, i:end], tok_sh), cache,
            jnp.int32(i),
        )
        np.testing.assert_allclose(
            np.asarray(lg), np.asarray(want[:, i:end]),
            atol=1e-4, rtol=1e-4, err_msg=f"chunk at {i}",
        )


@pytest.mark.slow
def test_eos_clamp_dense_and_sharded():
    """Rows that emit eos_id keep emitting it for the rest of the
    (static-shape) generation, dense and sharded alike; rows that never
    hit it are untouched (compared against the eos-free stream)."""
    params = init_params(CFG, seed=20)
    prompt = _tokens(CFG, B=2, L=6, seed=21)
    free = np.asarray(generate_dense(params, prompt, 8, CFG))
    # pick the token row 0 emits at step 2 as the "EOS" id: from step 3
    # on, row 0 must be clamped to it; a token row 1 never emits leaves
    # row 1 identical to the free stream
    eos = int(free[0, 2])
    out = np.asarray(generate_dense(params, prompt, 8, CFG, eos_id=eos))
    first = int(np.argmax(free[0] == eos))
    assert np.all(out[0, first:] == eos)
    np.testing.assert_array_equal(out[0, :first + 1], free[0, :first + 1])
    for r in range(free.shape[0]):
        if eos not in free[r]:
            np.testing.assert_array_equal(out[r], free[r])
    # sharded program, same clamp
    mesh = make_mesh((2, 4), ("dp", "tp"))
    gen = make_generate(CFG, mesh, n_new=8, eos_id=eos)
    got = np.asarray(gen(
        shard_params(params, CFG, mesh),
        jax.device_put(prompt, NamedSharding(mesh, P("dp", None))),
    ))
    np.testing.assert_array_equal(got, out)
