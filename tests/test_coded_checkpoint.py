"""Erasure-coded checkpoints: restore from any k of n shard files
(utils/coded_checkpoint.py) — the any-k-of-n idea applied to storage."""

import glob
import os

import numpy as np
import pytest

import jax.numpy as jnp

from mpistragglers_jl_tpu.utils.coded_checkpoint import (
    CheckpointCorrupt,
    CodedCheckpoint,
)


def _state():
    return {
        "w": jnp.arange(10.0).reshape(2, 5),
        "opt": {"mu": jnp.full(3, 0.5), "step": np.int64(42)},
    }


def _check(restored, expect):
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(expect["w"]))
    np.testing.assert_array_equal(
        np.asarray(restored["opt"]["mu"]), np.asarray(expect["opt"]["mu"])
    )
    assert int(restored["opt"]["step"]) == 42


def test_roundtrip_all_shards(tmp_path):
    cc = CodedCheckpoint(5, 3)
    paths = cc.save(tmp_path, _state())
    assert len(paths) == 5 and all(os.path.exists(p) for p in paths)
    _check(cc.restore(tmp_path, target=_state()), _state())


def _shard(tmp_path, i):
    (match,) = glob.glob(str(tmp_path / f"shard_{i}.*.rs"))
    return match


def test_restores_after_losing_n_minus_k_shards(tmp_path):
    cc = CodedCheckpoint(5, 3)
    cc.save(tmp_path, _state())
    os.remove(_shard(tmp_path, 0))
    os.remove(_shard(tmp_path, 3))  # any 2 of 5 gone
    _check(cc.restore(tmp_path, target=_state()), _state())


def test_corrupt_shards_detected_and_excluded(tmp_path):
    cc = CodedCheckpoint(5, 3)
    cc.save(tmp_path, _state())
    # flip bytes in two shards: CRC catches them, decode uses the rest
    import pathlib

    for i in (1, 4):
        p = pathlib.Path(_shard(tmp_path, i))
        raw = bytearray(p.read_bytes())
        raw[7] ^= 0xFF
        p.write_bytes(bytes(raw))
    _check(cc.restore(tmp_path, target=_state()), _state())


def test_too_few_intact_shards_raises(tmp_path):
    cc = CodedCheckpoint(4, 3)
    cc.save(tmp_path, _state())
    import pathlib

    os.remove(_shard(tmp_path, 0))
    pathlib.Path(_shard(tmp_path, 2)).write_bytes(b"\x00" * 5)  # bad length
    with pytest.raises(CheckpointCorrupt) as e:
        cc.restore(tmp_path, target=_state())
    assert e.value.have == 2 and e.value.need == 3
    assert "corrupt" in str(e.value)


def test_mismatched_code_params_refused(tmp_path):
    CodedCheckpoint(5, 3).save(tmp_path, _state())
    with pytest.raises(ValueError, match="coded"):
        CodedCheckpoint(6, 4).restore(tmp_path)


def test_restore_without_target_returns_leaves(tmp_path):
    cc = CodedCheckpoint(3, 2)
    cc.save(tmp_path, {"a": np.arange(4), "b": np.ones(2)})
    leaves = cc.restore(tmp_path)
    assert isinstance(leaves, list) and len(leaves) == 2


def test_resave_is_crash_safe_generation_swap(tmp_path):
    """A second save commits via the manifest: new-suffix shards appear,
    previous generation's shards are pruned, restore gets the new state."""
    cc = CodedCheckpoint(4, 2)
    cc.save(tmp_path, {"a": np.zeros(3)})
    first = set(glob.glob(str(tmp_path / "shard_*.rs")))
    cc.save(tmp_path, {"a": np.full(3, 9.0)})
    second = set(glob.glob(str(tmp_path / "shard_*.rs")))
    assert len(second) == 4 and not (first & second)  # old gen pruned
    out = cc.restore(tmp_path, target={"a": np.zeros(3)})
    np.testing.assert_array_equal(np.asarray(out["a"]), np.full(3, 9.0))
