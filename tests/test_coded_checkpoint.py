"""Erasure-coded checkpoints: restore from any k of n shard files
(utils/coded_checkpoint.py) — the any-k-of-n idea applied to storage."""

import glob
import os

import numpy as np
import pytest

import jax.numpy as jnp

from mpistragglers_jl_tpu.utils.coded_checkpoint import (
    CheckpointCorrupt,
    CodedCheckpoint,
)


def _state():
    return {
        "w": jnp.arange(10.0).reshape(2, 5),
        "opt": {"mu": jnp.full(3, 0.5), "step": np.int64(42)},
    }


def _check(restored, expect):
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(expect["w"]))
    np.testing.assert_array_equal(
        np.asarray(restored["opt"]["mu"]), np.asarray(expect["opt"]["mu"])
    )
    assert int(restored["opt"]["step"]) == 42


def test_roundtrip_all_shards(tmp_path):
    cc = CodedCheckpoint(5, 3)
    paths = cc.save(tmp_path, _state())
    assert len(paths) == 5 and all(os.path.exists(p) for p in paths)
    _check(cc.restore(tmp_path, target=_state()), _state())


def _shard(tmp_path, i):
    (match,) = glob.glob(str(tmp_path / f"shard_{i}.*.rs"))
    return match


def test_restores_after_losing_n_minus_k_shards(tmp_path):
    cc = CodedCheckpoint(5, 3)
    cc.save(tmp_path, _state())
    os.remove(_shard(tmp_path, 0))
    os.remove(_shard(tmp_path, 3))  # any 2 of 5 gone
    _check(cc.restore(tmp_path, target=_state()), _state())


def test_corrupt_shards_detected_and_excluded(tmp_path):
    cc = CodedCheckpoint(5, 3)
    cc.save(tmp_path, _state())
    # flip bytes in two shards: CRC catches them, decode uses the rest
    import pathlib

    for i in (1, 4):
        p = pathlib.Path(_shard(tmp_path, i))
        raw = bytearray(p.read_bytes())
        raw[7] ^= 0xFF
        p.write_bytes(bytes(raw))
    _check(cc.restore(tmp_path, target=_state()), _state())


def test_too_few_intact_shards_raises(tmp_path):
    cc = CodedCheckpoint(4, 3)
    cc.save(tmp_path, _state())
    import pathlib

    os.remove(_shard(tmp_path, 0))
    pathlib.Path(_shard(tmp_path, 2)).write_bytes(b"\x00" * 5)  # bad length
    with pytest.raises(CheckpointCorrupt) as e:
        cc.restore(tmp_path, target=_state())
    assert e.value.have == 2 and e.value.need == 3
    assert "corrupt" in str(e.value)


def test_mismatched_code_params_refused(tmp_path):
    CodedCheckpoint(5, 3).save(tmp_path, _state())
    with pytest.raises(ValueError, match="coded"):
        CodedCheckpoint(6, 4).restore(tmp_path)


def test_restore_without_target_returns_leaves(tmp_path):
    cc = CodedCheckpoint(3, 2)
    cc.save(tmp_path, {"a": np.arange(4), "b": np.ones(2)})
    leaves = cc.restore(tmp_path)
    assert isinstance(leaves, list) and len(leaves) == 2


# -- round 18: controller/coordinator state through the coded channel ------


def _controller_state():
    """A coordinator-shaped state dict: epoch counters, per-worker
    repochs/active, and the router book summary — the payload
    fleet.FleetCheckpointer codes across shards."""
    return {
        "epoch": np.int64(41),
        "repochs": np.array([41, 41, 40, 41], np.int64),
        "active": np.array([False, False, True, False]),
        "provisioned": np.array([True, True, True, False]),
        "chip_seconds": np.array([120.5, 120.5, 60.25, 0.0]),
        "book_awaiting": np.array([2, 0, 1, 0], np.int64),
        "book_streaming": np.array([3, 4, 0, 0], np.int64),
        "inflight_ids": np.arange(10, dtype=np.int64),
        "rate_count": np.float64(17.25),
        "policy_code": np.int64(1),
    }


def test_controller_state_roundtrip_through_fleet_checkpointer(tmp_path):
    """The round-18 failover payload round-trips exactly: epoch,
    repochs, active set, router books — through the pickle-blob
    FleetCheckpointer channel, surviving n-k lost shards."""
    from mpistragglers_jl_tpu.fleet import FleetCheckpointer

    ck = FleetCheckpointer(tmp_path, n=5, k=3)
    state = _controller_state()
    ck.save(state)
    os.remove(_shard(tmp_path, 1))
    os.remove(_shard(tmp_path, 4))  # any n-k=2 of 5 gone
    out = ck.restore()
    assert set(out) == set(state)
    for k, v in state.items():
        np.testing.assert_array_equal(np.asarray(out[k]), np.asarray(v))
    assert out["repochs"].dtype == np.int64  # bit-exact, not value-cast
    assert ck.n_saves == 1


def test_controller_state_torn_write_refused_by_name(tmp_path):
    """A torn write (truncated shard) plus too many losses is REFUSED
    by name: CheckpointCorrupt lists each missing/corrupt shard, and
    the standby must not adopt a partial state."""
    from mpistragglers_jl_tpu.fleet import FleetCheckpointer

    ck = FleetCheckpointer(tmp_path, n=4, k=3)
    ck.save(_controller_state())
    # a torn write: the shard file exists but holds half its bytes
    p = _shard(tmp_path, 0)
    raw = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(raw[: len(raw) // 2])
    os.remove(_shard(tmp_path, 2))
    with pytest.raises(CheckpointCorrupt) as e:
        ck.restore()
    msg = str(e.value)
    assert e.value.have == 2 and e.value.need == 3
    assert "shard 0" in msg and "corrupt" in msg  # the torn write, named
    assert "shard 2" in msg  # the missing shard, named


def test_resave_is_crash_safe_generation_swap(tmp_path):
    """A second save commits via the manifest: new-suffix shards appear,
    previous generation's shards are pruned, restore gets the new state."""
    cc = CodedCheckpoint(4, 2)
    cc.save(tmp_path, {"a": np.zeros(3)})
    first = set(glob.glob(str(tmp_path / "shard_*.rs")))
    cc.save(tmp_path, {"a": np.full(3, 9.0)})
    second = set(glob.glob(str(tmp_path / "shard_*.rs")))
    assert len(second) == 4 and not (first & second)  # old gen pruned
    out = cc.restore(tmp_path, target={"a": np.zeros(3)})
    np.testing.assert_array_equal(np.asarray(out["a"]), np.full(3, 9.0))
